"""Isolated test environments (public test support).

Parity reference: internal/testenv -- isolated XDG dirs wired through env
overrides so tests never touch the real user config (SURVEY.md 4).
"""

from __future__ import annotations

import contextlib
import os
import tempfile
from pathlib import Path

from . import consts


class TestEnv(contextlib.AbstractContextManager):
    """Creates throwaway XDG dirs and points CLAWKER_TPU_*_DIR at them."""

    __test__ = False  # pytest: helper, not a test class

    def __init__(self, base: Path | None = None):
        self._tmp = None
        if base is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="clawker-tpu-test-")
            base = Path(self._tmp.name)
        self.base = Path(base)
        self.config = self.base / "config"
        self.data = self.base / "data"
        self.state = self.base / "state"
        self.cache = self.base / "cache"
        self._saved: dict[str, str | None] = {}

    def __enter__(self) -> "TestEnv":
        for p in (self.config, self.data, self.state, self.cache):
            p.mkdir(parents=True, exist_ok=True)
        mapping = {
            consts.ENV_CONFIG_DIR: self.config,
            consts.ENV_DATA_DIR: self.data,
            consts.ENV_STATE_DIR: self.state,
            consts.ENV_CACHE_DIR: self.cache,
        }
        for k, v in mapping.items():
            self._saved[k] = os.environ.get(k)
            os.environ[k] = str(v)
        return self

    def __exit__(self, *exc) -> None:
        for k, old in self._saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        if self._tmp is not None:
            self._tmp.cleanup()

    # convenience writers -------------------------------------------------

    def write_settings(self, text: str) -> Path:
        p = self.config / consts.SETTINGS_FILE
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
        return p

    def make_project(self, root: Path, text: str, *, form: str = "flat", local: str | None = None) -> Path:
        root.mkdir(parents=True, exist_ok=True)
        if form == "dir":
            d = root / consts.PROJECT_DIR_FORM
            d.mkdir(exist_ok=True)
            main = d / "clawker.yaml"
            main.write_text(text)
            if local is not None:
                (d / "clawker.local.yaml").write_text(local)
        else:
            main = root / consts.PROJECT_FLAT_FORM
            main.write_text(text)
            if local is not None:
                (root / ".clawker.local.yaml").write_text(local)
        return main
