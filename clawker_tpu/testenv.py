"""Isolated test environments (public test support).

Parity reference: internal/testenv -- isolated XDG dirs wired through env
overrides so tests never touch the real user config (SURVEY.md 4).

Fake-WAN harness (docs/workerd.md#fake-wan): any bench or test can
simulate host<->worker WAN latency deterministically by injecting a
per-call RTT at the transport seams --

- ``FakeDriver.set_rtt(index, rtt_s)`` / ``set_rtt_all(rtt_s)``: every
  REMOTE engine call against that fake worker sleeps ``rtt_s`` before
  executing (the fault gate's ``rtt_s`` knob).  The worker-resident
  view (``FakeDriver.local_engine(i)``, what an in-process
  :class:`~clawker_tpu.workerd.server.WorkerdServer` serves) pays
  injected faults but never the rtt -- locality is the whole point.
- ``SSHTransport.rtt_s``: the same knob for real transports -- every
  mux command pays it, so a localhost ssh target behaves like a
  cross-continent worker.
- ``WorkerdExecutor.rtt_s``: one-way propagation per intent/event
  FRAME on the workerd channel (rtt/2 each direction), modelling the
  single persistent connection the data plane rides.

Use :func:`inject_wan_rtt` to set all of a driver's workers at once.
"""

from __future__ import annotations

import contextlib
import os
import socket
import tempfile
import threading
import time
from pathlib import Path

from . import consts


class TestEnv(contextlib.AbstractContextManager):
    """Creates throwaway XDG dirs and points CLAWKER_TPU_*_DIR at them."""

    __test__ = False  # pytest: helper, not a test class

    def __init__(self, base: Path | None = None):
        self._tmp = None
        if base is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="clawker-tpu-test-")
            base = Path(self._tmp.name)
        self.base = Path(base)
        self.config = self.base / "config"
        self.data = self.base / "data"
        self.state = self.base / "state"
        self.cache = self.base / "cache"
        self._saved: dict[str, str | None] = {}

    def __enter__(self) -> "TestEnv":
        for p in (self.config, self.data, self.state, self.cache):
            p.mkdir(parents=True, exist_ok=True)
        mapping = {
            consts.ENV_CONFIG_DIR: self.config,
            consts.ENV_DATA_DIR: self.data,
            consts.ENV_STATE_DIR: self.state,
            consts.ENV_CACHE_DIR: self.cache,
        }
        for k, v in mapping.items():
            self._saved[k] = os.environ.get(k)
            os.environ[k] = str(v)
        return self

    def __exit__(self, *exc) -> None:
        for k, old in self._saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        if self._tmp is not None:
            self._tmp.cleanup()

    # convenience writers -------------------------------------------------

    def write_settings(self, text: str) -> Path:
        p = self.config / consts.SETTINGS_FILE
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
        return p

    def make_project(self, root: Path, text: str, *, form: str = "flat", local: str | None = None) -> Path:
        root.mkdir(parents=True, exist_ok=True)
        if form == "dir":
            d = root / consts.PROJECT_DIR_FORM
            d.mkdir(exist_ok=True)
            main = d / "clawker.yaml"
            main.write_text(text)
            if local is not None:
                (d / "clawker.local.yaml").write_text(local)
        else:
            main = root / consts.PROJECT_FLAT_FORM
            main.write_text(text)
            if local is not None:
                (root / ".clawker.local.yaml").write_text(local)
        return main


def inject_wan_rtt(driver, rtt_s: float) -> None:
    """Inject a deterministic per-call host<->worker WAN round trip on
    every worker of ``driver`` (see the module docstring).  Works on
    any driver exposing ``set_rtt_all`` (FakeDriver) or per-worker
    engine transports (tpu_vm); silently no-ops elsewhere -- tests can
    call it unconditionally."""
    set_all = getattr(driver, "set_rtt_all", None)
    if callable(set_all):
        set_all(rtt_s)
        return
    for w in driver.workers():
        transport = getattr(getattr(w, "engine", None), "transport", None)
        if transport is not None:
            transport.rtt_s = max(0.0, float(rtt_s))


@contextlib.contextmanager
def lock_tracing():
    """Opt-in lock-order tracing for a test or bench block
    (docs/static-analysis.md#lock-order-tracer): every
    ``threading.Lock``/``RLock`` created inside the block feeds a
    :class:`~clawker_tpu.analysis.lockgraph.LockGraph`; yields the
    graph so the caller can assert ``graph.cycles() == []`` (the
    deadlock-freedom check the chaos soak gates on).

        with testenv.lock_tracing() as graph:
            ... run the workload ...
        assert not graph.cycles(), graph.render_cycles()

    The suite-wide hook is ``CLAWKER_TPU_LOCKGRAPH=1`` (tests/conftest
    installs at session start and fails the session on cycles)."""
    from .analysis.lockgraph import install_lock_tracing, uninstall_lock_tracing

    graph = install_lock_tracing()
    try:
        yield graph
    finally:
        uninstall_lock_tracing()


class StubDockerDaemon:
    """Minimal keep-alive HTTP daemon over a unix socket (test/bench
    support for the engine client's connection pool).

    Answers EVERY request with one canned JSON body, so
    ``HTTPDockerAPI`` exercises real sockets, wire framing and
    keep-alive reuse without a real daemon behind them.  Counters:
    ``connections`` (accepts) and ``requests`` (responses served).

    ``max_requests_per_conn > 0`` closes the socket after N responses
    WITHOUT advertising ``Connection: close`` -- models a daemon reaping
    an idle keep-alive socket, which drives the client's
    retry-once-on-stale path.

    ``truncate_after > 0`` serves that many full responses per
    connection, then answers with a status line + headers advertising
    the full body but sends only half of it before closing -- models a
    daemon dying mid-response AFTER executing the request (the case the
    client must never retry).

    ``delay_after > 0`` serves that many prompt responses per
    connection, then sleeps ``response_delay_s`` before answering --
    models a healthy-but-slow daemon (a client read timeout here must
    NOT trigger a re-send).
    """

    __test__ = False  # pytest: helper, not a test class

    def __init__(self, sock_path: str | Path, *, body: bytes | None = None,
                 max_requests_per_conn: int = 0, truncate_after: int = 0,
                 delay_after: int = 0, response_delay_s: float = 0.0):
        self.sock_path = Path(sock_path)
        self.body = (body if body is not None
                     else b'{"Id": "stub", "StatusCode": 0, "Warnings": []}')
        self.max_requests_per_conn = max_requests_per_conn
        self.truncate_after = truncate_after
        self.delay_after = delay_after
        self.response_delay_s = response_delay_s
        self.connections = 0
        self.requests = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._srv: socket.socket | None = None
        self._conns: set[socket.socket] = set()
        self._thread: threading.Thread | None = None

    def start(self) -> "StubDockerDaemon":
        self.sock_path.parent.mkdir(parents=True, exist_ok=True)
        if self.sock_path.exists():
            self.sock_path.unlink()
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(str(self.sock_path))
        srv.listen(64)
        srv.settimeout(0.2)
        self._srv = srv
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(2.0)

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            with self._lock:
                self.connections += 1
                self._conns.add(conn)
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        served = 0
        buf = b""
        try:
            while not self._stop.is_set():
                while b"\r\n\r\n" not in buf:
                    try:
                        chunk = conn.recv(65536)
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                head, _, buf = buf.partition(b"\r\n\r\n")
                length = 0
                for line in head.split(b"\r\n")[1:]:
                    k, _, v = line.partition(b":")
                    if k.strip().lower() == b"content-length":
                        length = int(v.strip() or b"0")
                while len(buf) < length:
                    try:
                        chunk = conn.recv(65536)
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                buf = buf[length:]
                # counted on receipt, before the response goes out: a
                # client that has READ response N must find requests >= N
                with self._lock:
                    self.requests += 1
                if self.delay_after and served >= self.delay_after:
                    time.sleep(self.response_delay_s)
                payload = self.body
                truncate = bool(self.truncate_after
                                and served >= self.truncate_after)
                if truncate:
                    payload = self.body[: len(self.body) // 2]
                try:
                    conn.sendall(
                        b"HTTP/1.1 200 OK\r\n"
                        b"Content-Type: application/json\r\n"
                        b"Content-Length: " + str(len(self.body)).encode()
                        + b"\r\n\r\n" + payload)
                except OSError:
                    return
                served += 1
                if truncate:
                    return
                if self.max_requests_per_conn and served >= self.max_requests_per_conn:
                    return
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass


class FakeBulkIndex:
    """In-memory OpenSearch ``_bulk`` endpoint (test/bench support for
    the monitor shipper, docs/fleet-console.md#ingestion).

    Implements the shipper's sink contract -- ``bulk(payload) -> bool``
    -- by parsing the ndjson action/doc pairs into per-index doc lists,
    so tests and the ``ingest_docs_lag`` bench gate assert on what the
    index would actually hold.  Fault knobs model the chaos the shipper
    must degrade under:

    - ``down = True``: every bulk POST refuses (connection-refused
      index);
    - ``stall()`` / ``unstall()``: bulk POSTs block until released or
      ``stall_timeout_s`` passes, then fail -- a wedged index that eats
      the sink's deadline without answering;
    - ``delay_s``: fixed per-POST latency (a slow-but-healthy index).
    """

    def __init__(self, *, delay_s: float = 0.0,
                 stall_timeout_s: float = 2.0):
        import json

        self._json = json
        self.delay_s = delay_s
        self.stall_timeout_s = stall_timeout_s
        self.down = False
        self.docs: dict[str, list[dict]] = {}
        self.bulk_calls = 0
        self.refused = 0
        self._lock = threading.Lock()
        self._stalled = threading.Event()
        self._release = threading.Event()
        self._release.set()

    # fault knobs ---------------------------------------------------------

    def stall(self) -> None:
        self._release.clear()
        self._stalled.set()

    def unstall(self) -> None:
        self._release.set()
        self._stalled.clear()

    # sink contract -------------------------------------------------------

    def bulk(self, payload: bytes) -> bool:
        with self._lock:
            self.bulk_calls += 1
        if self._stalled.is_set():
            if not self._release.wait(self.stall_timeout_s):
                with self._lock:
                    self.refused += 1
                return False
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.down:
            with self._lock:
                self.refused += 1
            return False
        lines = payload.decode().splitlines()
        with self._lock:
            for action_line, doc_line in zip(lines[0::2], lines[1::2]):
                try:
                    action = self._json.loads(action_line)
                    doc = self._json.loads(doc_line)
                except ValueError:
                    continue
                index = str(action.get("index", {}).get("_index", ""))
                self.docs.setdefault(index, []).append(doc)
        return True

    # assertions ----------------------------------------------------------

    def count(self, index: str) -> int:
        with self._lock:
            return len(self.docs.get(index, []))

    def search(self, index: str, **match) -> list[dict]:
        """Every doc in ``index`` whose fields equal ``match``."""
        with self._lock:
            rows = list(self.docs.get(index, []))
        return [d for d in rows
                if all(d.get(k) == v for k, v in match.items())]


class FaultFS:
    """Disk-fault injection shim for the WAL chain (docs/durability.md,
    docs/chaos.md#disk-faults): a proxy wrapped around a journal's live
    file handle that makes storage lie on command.  The chaos runner
    installs it on a scheduler's ``RunJournal`` via :meth:`install`;
    unit tests wrap any open file.

    Fault knobs (armed counts; each triggered op decrements its arm):

    - ``fail_writes(n, errno_)``: the next ``n`` writes raise (ENOSPC
      by default -- a full disk; pass ``errno.EIO`` for a dying one);
    - ``short_writes(n)``: the next ``n`` writes write only half the
      payload, then raise -- a torn record on disk;
    - ``fail_fsyncs(n)``: the next ``n`` fsyncs raise EIO *after* the
      kernel may already have dropped the dirty pages -- the classic
      false-success trap the journal's poisoned-handle recovery exists
      for;
    - ``power_cut()``: truncate the real file at the last
      *successfully fsynced* offset -- everything after the last sync
      vanishes, exactly like a host losing power;
    - ``flip_bit(offset)`` / :func:`flip_bit_in_file`: corrupt one byte
      in place (checksum-verify must flag it).

    Counters (``writes``, ``failed_writes``, ``failed_fsyncs``,
    ``synced_offset``) are the evidence the chaos *no-silent-drop*
    audit compares against journal receipts and metrics.
    """

    def __init__(self, fh, path=None):
        import errno as _errno

        self._errno = _errno
        self._fh = fh
        self.path = path
        self._lock = threading.Lock()
        self._fail_writes = 0
        self._fail_errno = _errno.ENOSPC
        self._short_writes = 0
        self._fail_fsyncs = 0
        self.writes = 0
        self.failed_writes = 0
        self.short_written = 0
        self.failed_fsyncs = 0
        self.fsyncs = 0
        self.synced_offset = 0      # file size at the last good fsync

    # fault knobs ---------------------------------------------------------

    def fail_writes(self, n: int = 1, errno_: int | None = None) -> None:
        with self._lock:
            self._fail_writes = int(n)
            if errno_ is not None:
                self._fail_errno = int(errno_)

    def short_writes(self, n: int = 1) -> None:
        with self._lock:
            self._short_writes = int(n)

    def fail_fsyncs(self, n: int = 1) -> None:
        with self._lock:
            self._fail_fsyncs = int(n)

    def power_cut(self) -> int:
        """Truncate the REAL file at the last fsynced offset: the
        unsynced tail vanishes the way a power loss takes it.  Returns
        the number of bytes cut."""
        with self._lock:
            offset = self.synced_offset
        path = self.path or getattr(self._fh, "name", None)
        if path is None:
            return 0
        try:
            self._fh.flush()
        except (OSError, ValueError):
            pass
        try:
            size = os.path.getsize(path)
            with open(path, "rb+") as f:
                f.truncate(offset)
            return max(0, size - offset)
        except OSError:
            return 0

    @staticmethod
    def flip_bit_in_file(path, offset: int, bit: int = 0) -> bool:
        """Flip one bit of ``path`` in place (record corruption)."""
        try:
            with open(path, "rb+") as f:
                f.seek(offset)
                b = f.read(1)
                if not b:
                    return False
                f.seek(offset)
                f.write(bytes([b[0] ^ (1 << (bit & 7))]))
            return True
        except OSError:
            return False

    # file-handle proxy ---------------------------------------------------

    def write(self, data: str) -> int:
        with self._lock:
            if self._fail_writes > 0:
                self._fail_writes -= 1
                self.failed_writes += 1
                raise OSError(self._fail_errno,
                              os.strerror(self._fail_errno))
            if self._short_writes > 0:
                self._short_writes -= 1
                self.short_written += 1
                half = data[:max(1, len(data) // 2)]
                self._fh.write(half)
                self.failed_writes += 1
                raise OSError(self._errno.EIO, "short write")
            self.writes += 1
        return self._fh.write(data)

    def flush(self) -> None:
        self._fh.flush()

    def fsync(self) -> None:
        """The journal's fsync seam (``RunJournal._fsync_fh`` prefers
        a handle-level fsync exactly so this shim can intercept)."""
        with self._lock:
            if self._fail_fsyncs > 0:
                self._fail_fsyncs -= 1
                self.failed_fsyncs += 1
                raise OSError(self._errno.EIO, "fsync failed")
        self._fh.flush()
        os.fsync(self._fh.fileno())
        with self._lock:
            self.fsyncs += 1
            try:
                self.synced_offset = os.path.getsize(
                    self.path or self._fh.name)
            except (OSError, AttributeError):
                pass

    def fileno(self) -> int:
        return self._fh.fileno()

    def close(self) -> None:
        self._fh.close()

    @classmethod
    def install(cls, journal) -> "FaultFS | None":
        """Wrap a live ``RunJournal``'s handle in a FaultFS and return
        it (None when the journal is disabled/unhealthy).  Subsequent
        reopen-recoveries deliberately bypass the shim -- recovery
        opens a FRESH fd, which is the behavior under test."""
        fh = getattr(journal, "_fh", None)
        if fh is None:
            return None
        shim = cls(fh, path=getattr(journal, "path", None))
        try:
            shim.synced_offset = os.path.getsize(shim.path)
        except (OSError, TypeError):
            pass
        journal._fh = shim
        return shim
