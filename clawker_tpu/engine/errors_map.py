"""Docker API status-code -> typed error mapping."""

from __future__ import annotations

from ..errors import ClawkerError, ConflictError, DriverError, NotFoundError


class APIError(ClawkerError):
    """Raw daemon error with HTTP status."""

    def __init__(self, status: int, message: str, path: str = ""):
        super().__init__(f"daemon: {message} (status {status}{', ' + path if path else ''})")
        self.status = status
        self.raw_message = message


def raise_for(status: int, message: str, path: str = "") -> None:
    if status < 400:
        return
    if status == 404:
        raise NotFoundError(message or f"not found: {path}")
    if status == 409:
        raise ConflictError(message or f"conflict: {path}")
    if status >= 500:
        raise DriverError(message or f"daemon error on {path}")
    raise APIError(status, message, path)
