"""Label-jailed engine: the safety boundary over any Docker-API daemon.

Parity reference: pkg/whail/engine.go -- ``injectManagedFilter`` (engine.go:135)
scopes every list to managed objects, and every mutate op verifies the target
carries the managed label before touching it.  The jail means this framework
can never destroy containers/images/volumes/networks it does not own, on a
laptop daemon or a TPU-VM worker daemon alike.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field
from typing import Any, Iterator

from .. import consts
from ..errors import JailViolation, NotFoundError


@dataclass
class ContainerSpec:
    """Builder for the daemon's container-create JSON."""

    image: str
    cmd: list[str] = field(default_factory=list)
    entrypoint: list[str] | None = None
    env: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    tty: bool = False
    open_stdin: bool = False
    working_dir: str = ""
    user: str = ""
    hostname: str = ""
    binds: list[str] = field(default_factory=list)          # "src:dst[:opts]"
    network: str = ""
    static_ip: str = ""
    privileged: bool = False
    pid_host: bool = False
    cap_add: list[str] = field(default_factory=list)
    memory: str = ""
    nano_cpus: int = 0
    restart_policy: str = ""                                 # e.g. "on-failure:3"
    dns: list[str] = field(default_factory=list)             # resolver override
    extra_hosts: list[str] = field(default_factory=list)     # "host:ip"
    mount_docker_socket: bool = False
    stop_signal: str = ""
    init: bool = False

    def to_json(self) -> dict:
        host_config: dict[str, Any] = {}
        if self.binds:
            host_config["Binds"] = list(self.binds)
        if self.mount_docker_socket:
            host_config.setdefault("Binds", []).append(
                "/var/run/docker.sock:/var/run/docker.sock"
            )
        if self.privileged:
            host_config["Privileged"] = True
        if self.pid_host:
            host_config["PidMode"] = "host"
        if self.cap_add:
            host_config["CapAdd"] = list(self.cap_add)
        if self.memory:
            host_config["Memory"] = _parse_bytes(self.memory)
        if self.nano_cpus:
            host_config["NanoCpus"] = self.nano_cpus
        if self.restart_policy:
            name, _, cnt = self.restart_policy.partition(":")
            rp: dict[str, Any] = {"Name": name}
            if cnt:
                rp["MaximumRetryCount"] = int(cnt)
            host_config["RestartPolicy"] = rp
        if self.extra_hosts:
            host_config["ExtraHosts"] = list(self.extra_hosts)
        if self.dns:
            host_config["Dns"] = list(self.dns)
        if self.init:
            host_config["Init"] = True
        cfg: dict[str, Any] = {
            "Image": self.image,
            "Labels": dict(self.labels),
            "Tty": self.tty,
            "OpenStdin": self.open_stdin,
            "AttachStdin": self.open_stdin,
            "AttachStdout": True,
            "AttachStderr": True,
            "StdinOnce": False,
            "HostConfig": host_config,
        }
        if self.cmd:
            cfg["Cmd"] = list(self.cmd)
        if self.entrypoint is not None:
            cfg["Entrypoint"] = list(self.entrypoint)
        if self.env:
            cfg["Env"] = [f"{k}={v}" for k, v in self.env.items()]
        if self.working_dir:
            cfg["WorkingDir"] = self.working_dir
        if self.user:
            cfg["User"] = self.user
        if self.hostname:
            cfg["Hostname"] = self.hostname
        if self.stop_signal:
            cfg["StopSignal"] = self.stop_signal
        if self.network:
            epc: dict[str, Any] = {}
            if self.static_ip:
                epc["IPAMConfig"] = {"IPv4Address": self.static_ip}
            cfg["NetworkingConfig"] = {"EndpointsConfig": {self.network: epc}}
        return cfg


def _demux_stdcopy(chunks: Iterator[bytes]) -> Iterator[bytes]:
    """Strip Docker's 8-byte stdcopy frame headers from a log stream."""
    import struct as _struct

    buf = b""
    for chunk in chunks:
        buf += chunk
        while len(buf) >= 8:
            length = _struct.unpack(">I", buf[4:8])[0]
            if len(buf) < 8 + length:
                break
            payload = buf[8 : 8 + length]
            buf = buf[8 + length :]
            if payload:
                yield payload
    if buf:
        # trailing partial frame: emit what we can see rather than drop it
        yield buf[8:] if len(buf) > 8 else b""


def _parse_bytes(s: str) -> int:
    s = s.strip().lower()
    mult = 1
    for suffix, m in (("k", 1024), ("m", 1024**2), ("g", 1024**3)):
        if s.endswith(suffix) or s.endswith(suffix + "b"):
            s = s.rstrip("b").rstrip(suffix)
            mult = m
            break
    return int(float(s) * mult)


class Engine:
    """Managed-label jail over a DockerAPI (HTTPDockerAPI or FakeDockerAPI)."""

    def __init__(self, api):
        self.api = api
        self._builder = None  # lazy: probes the daemon once (buildkit.py)

    # ------------------------------------------------------------ helpers

    @staticmethod
    def _managed_labels(extra: dict[str, str] | None = None) -> dict[str, str]:
        labels = {consts.LABEL_MANAGED: consts.MANAGED_VALUE}
        if extra:
            labels.update(extra)
        return labels

    @staticmethod
    def _managed_filter(filters: dict | None = None) -> dict:
        f = {k: list(v) for k, v in (filters or {}).items()}
        f.setdefault("label", [])
        tag = f"{consts.LABEL_MANAGED}={consts.MANAGED_VALUE}"
        if tag not in f["label"]:
            f["label"].append(tag)
        return f

    def _assert_managed_container(self, ref: str) -> dict:
        info = self.api.container_inspect(ref)
        labels = (info.get("Config") or {}).get("Labels") or {}
        if labels.get(consts.LABEL_MANAGED) != consts.MANAGED_VALUE:
            raise JailViolation(
                f"container {ref} is not managed by {consts.PRODUCT}; refusing to touch it"
            )
        return info

    # --------------------------------------------------------- containers

    def create_container(self, name: str, spec: ContainerSpec) -> str:
        spec.labels = self._managed_labels(spec.labels)
        res = self.api.container_create(name, spec.to_json())
        return res["Id"]

    def start_container(self, ref: str) -> None:
        self._assert_managed_container(ref)
        self.api.container_start(ref)

    def stop_container(self, ref: str, timeout: int = 10) -> None:
        self._assert_managed_container(ref)
        self.api.container_stop(ref, timeout)

    def kill_container(self, ref: str, signal: str = "KILL") -> None:
        self._assert_managed_container(ref)
        self.api.container_kill(ref, signal)

    def restart_container(self, ref: str, timeout: int = 10) -> None:
        self._assert_managed_container(ref)
        self.api.container_restart(ref, timeout)

    def pause_container(self, ref: str) -> None:
        self._assert_managed_container(ref)
        self.api.container_pause(ref)

    def unpause_container(self, ref: str) -> None:
        self._assert_managed_container(ref)
        self.api.container_unpause(ref)

    def remove_container(self, ref: str, *, force: bool = False, volumes: bool = False) -> None:
        """volumes=True also removes the agent's NAMED volumes by label.
        Docker's ?v=1 only removes anonymous volumes, and every agent
        volume is named -- without the label-scoped sweep `rm --volumes`
        would be a silent no-op for agent data on real daemons."""
        info = self._assert_managed_container(ref)
        labels = (info.get("Config") or {}).get("Labels") or {}
        self.api.container_remove(ref, force=force, volumes=volumes)
        if not volumes:
            return
        project = labels.get(consts.LABEL_PROJECT, "")
        agent = labels.get(consts.LABEL_AGENT, "")
        if not project or not agent:
            return
        # jailed sweep: the managed filter scopes the listing, and
        # remove_volume re-asserts ownership per volume -- `rm --volumes`
        # must never touch a volume this framework does not own
        for vol in self.list_volumes(filters={"label": [
                f"{consts.LABEL_PROJECT}={project}",
                f"{consts.LABEL_AGENT}={agent}"]}):
            try:
                self.remove_volume(vol["Name"], force=force)
            except NotFoundError:
                pass

    def rename_container(self, ref: str, new_name: str) -> None:
        self._assert_managed_container(ref)
        self.api.container_rename(ref, new_name)

    @property
    def supports_relabel(self) -> bool:
        """True when the backing api can mutate container labels in
        place (the fake/nsd engines; real Docker cannot -- labels are
        create-time immutable there)."""
        return hasattr(self.api, "container_relabel")

    def relabel_container(self, ref: str, labels: dict[str, str]) -> bool:
        """Merge ``labels`` into a managed container's label set.
        Returns False (no-op) on engines without relabel support --
        warm-pool adoption then relies on the run journal instead of
        the labels being authoritative (docs/loop-warmpool.md)."""
        self._assert_managed_container(ref)
        if not self.supports_relabel:
            return False
        self.api.container_relabel(ref, labels)
        return True

    def finalize_adoption(self, ref: str, *, labels: dict[str, str],
                          archive_path: str = "", archive: bytes = b"",
                          new_name: str = "") -> bool:
        """Warm-pool adoption fixups under ONE jail check: relabel
        (where the api supports it), optional archive injection (the
        env-fixup file), and rename, in that order.  Batched because
        every managed assert is a full inspect -- a remote daemon pays
        a round-trip per call, and the warm-pool hit budget is 1ms
        (docs/loop-warmpool.md).  Returns whether the relabel landed."""
        self._assert_managed_container(ref)
        relabeled = False
        if labels and self.supports_relabel:
            self.api.container_relabel(ref, labels)
            relabeled = True
        if archive:
            self.api.put_archive(ref, archive_path, archive)
        if new_name:
            self.api.container_rename(ref, new_name)
        return relabeled

    def inspect_container(self, ref: str) -> dict:
        return self._assert_managed_container(ref)

    def container_exists(self, ref: str) -> bool:
        try:
            self._assert_managed_container(ref)
            return True
        except NotFoundError:
            return False

    def list_containers(self, *, all: bool = False, filters: dict | None = None) -> list[dict]:
        return self.api.container_list(all=all, filters=self._managed_filter(filters))

    def wait_container(self, ref: str) -> int:
        self._assert_managed_container(ref)
        return int(self.api.container_wait(ref)["StatusCode"])

    def attach_container(self, ref: str, *, tty: bool, stdin: bool = True):
        self._assert_managed_container(ref)
        return self.api.container_attach(ref, tty=tty, stdin=stdin)

    def resize_container(self, ref: str, height: int, width: int) -> None:
        self.api.container_resize(ref, height, width)

    def logs(self, ref: str, *, follow: bool = False, tail: str = "all") -> Iterator[bytes]:
        """Log payload chunks; non-TTY daemon streams are stdcopy-demuxed."""
        info = self._assert_managed_container(ref)
        tty = bool((info.get("Config") or {}).get("Tty"))
        raw = self.api.container_logs(ref, follow=follow, tail=tail)
        if tty:
            return raw
        return _demux_stdcopy(raw)

    def put_archive(self, ref: str, path: str, tar_bytes: bytes) -> None:
        self._assert_managed_container(ref)
        self.api.put_archive(ref, path, tar_bytes)

    def get_archive(self, ref: str, path: str) -> bytes:
        self._assert_managed_container(ref)
        return self.api.get_archive(ref, path)

    def exec(
        self,
        ref: str,
        cmd: list[str],
        *,
        user: str = "",
        env: dict[str, str] | None = None,
        tty: bool = False,
        detach: bool = False,
        stdin: bool = False,
        workdir: str = "",
    ):
        """Create+start an exec; returns (exec_id, stream-or-None)."""
        self._assert_managed_container(ref)
        cfg: dict[str, Any] = {
            "Cmd": cmd,
            "AttachStdout": True,
            "AttachStderr": True,
            "AttachStdin": stdin,
            "Tty": tty,
        }
        if user:
            cfg["User"] = user
        if workdir:
            cfg["WorkingDir"] = workdir
        if env:
            cfg["Env"] = [f"{k}={v}" for k, v in env.items()]
        eid = self.api.exec_create(ref, cfg)["Id"]
        stream = self.api.exec_start(eid, tty=tty, detach=detach)
        return eid, stream

    def exec_exit_code(self, exec_id: str) -> int:
        """Exit code once the exec has finished.  Stream EOF can precede
        the daemon committing the code (docker CLI polls inspect for the
        same reason), so poll briefly while Running/None."""
        import time as _time

        deadline = _time.monotonic() + 5.0
        while True:
            info = self.api.exec_inspect(exec_id)
            code = info.get("ExitCode")
            if code is not None and not info.get("Running"):
                return int(code)
            if _time.monotonic() >= deadline:
                return int(code or 0)
            _time.sleep(0.05)

    def run_exec(self, ref: str, cmd: list[str], *, user: str = "") -> tuple[int, bytes]:
        """Exec to completion, collecting output."""
        eid, stream = self.exec(ref, cmd, user=user)
        out = b""
        if stream is not None:
            for _, payload in stream.frames():
                out += payload
            stream.close()
        return self.exec_exit_code(eid), out

    # ------------------------------------------------------------- images

    def list_images(self, *, filters: dict | None = None) -> list[dict]:
        return self.api.image_list(filters=self._managed_filter(filters))

    def image_exists(self, ref: str) -> bool:
        try:
            self.api.image_inspect(ref)
            return True
        except NotFoundError:
            return False

    def inspect_image(self, ref: str) -> dict:
        return self.api.image_inspect(ref)

    def build_image(
        self,
        context_tar: bytes,
        *,
        tags: list[str],
        labels: dict[str, str] | None = None,
        dockerfile: str = "Dockerfile",
        buildargs: dict[str, str] | None = None,
        target: str = "",
        pull: bool = False,
        no_cache: bool = False,
        secrets: dict[str, bytes] | None = None,
        ssh_auth_sock: str = "",
    ) -> Iterator[dict]:
        from .buildkit import Builder

        if self._builder is None:
            self._builder = Builder(self.api)
        return self._builder.build(
            context_tar,
            tags=tags,
            labels=self._managed_labels(labels),
            dockerfile=dockerfile,
            buildargs=buildargs,
            target=target,
            pull=pull,
            no_cache=no_cache,
            secrets=secrets,
            ssh_auth_sock=ssh_auth_sock,
        )

    def tag_image(self, ref: str, repo: str, tag: str) -> None:
        self.api.image_tag(ref, repo, tag)

    def remove_image(self, ref: str, *, force: bool = False) -> None:
        img = self.api.image_inspect(ref)
        # real daemons nest labels under Config.Labels; fakes/summaries use Labels
        labels = (img.get("Config") or {}).get("Labels") or img.get("Labels") or {}
        if labels.get(consts.LABEL_MANAGED) != consts.MANAGED_VALUE:
            raise JailViolation(f"image {ref} is not managed; refusing to remove")
        self.api.image_remove(ref, force=force)

    def pull_image(self, ref: str) -> Iterator[dict]:
        return self.api.image_pull(ref)

    # ------------------------------------------------------------ volumes

    def ensure_volume(self, name: str, labels: dict[str, str] | None = None) -> dict:
        return self.api.volume_create(name, labels=self._managed_labels(labels))

    def list_volumes(self, *, filters: dict | None = None) -> list[dict]:
        # dockerd marshals an empty result as {"Volumes": null}
        got = self.api.volume_list(filters=self._managed_filter(filters))
        return (got or {}).get("Volumes") or []

    def remove_volume(self, name: str, *, force: bool = False) -> None:
        try:
            vol = self.api.volume_inspect(name)
        except NotFoundError:
            if force:
                return
            raise
        if (vol.get("Labels") or {}).get(consts.LABEL_MANAGED) != consts.MANAGED_VALUE:
            raise JailViolation(f"volume {name} is not managed; refusing to remove")
        self.api.volume_remove(name, force=force)

    # ----------------------------------------------------------- networks

    def ensure_network(self, name: str, *, subnet: str = "") -> dict:
        """Idempotent create (reference: whail EnsureNetwork, SURVEY.md 2.3)."""
        for n in self.api.network_list(filters=self._managed_filter()):
            if n["Name"] == name:
                return n
        cfg: dict[str, Any] = {"Labels": self._managed_labels(), "Driver": "bridge"}
        if subnet:
            cfg["IPAM"] = {"Config": [{"Subnet": subnet}]}
        self.api.network_create(name, cfg)
        return self.api.network_inspect(name)

    def network_static_ip(self, name: str, host_offset: int) -> str:
        """Deterministic static IP: network base + offset (reference:
        ARCHITECTURE.md:490 -- gateway+.2 Envoy, +.3 CoreDNS, +.202 CP)."""
        n = self.api.network_inspect(name)
        subnet = n["IPAM"]["Config"][0]["Subnet"]
        net = ipaddress.ip_network(subnet)
        return str(net.network_address + host_offset)

    def remove_network(self, name: str) -> None:
        n = self.api.network_inspect(name)
        if (n.get("Labels") or {}).get(consts.LABEL_MANAGED) != consts.MANAGED_VALUE:
            raise JailViolation(f"network {name} is not managed; refusing to remove")
        self.api.network_remove(name)

    def connect_network(self, name: str, ref: str, *, ipv4: str = "") -> None:
        self._assert_managed_container(ref)
        self.api.network_connect(name, ref, ipv4=ipv4)

    # ------------------------------------------------------------- events

    def events(self, *, filters: dict | None = None) -> Iterator[dict]:
        return self.api.events(filters=self._managed_filter(filters))

    def ping(self) -> bool:
        return self.api.ping()

    def info(self) -> dict:
        return self.api.info()

    # ----------------------------------------------------------- lifecycle

    def pool_stats(self) -> dict:
        """Connection-pool telemetry from the underlying client (empty for
        clients without a pool)."""
        stats = getattr(self.api, "pool_stats", None)
        return stats() if stats is not None else {}

    def close(self) -> None:
        """Drain-on-shutdown: tear down event streams and the client's
        idle keep-alive connections.  Safe to call more than once."""
        closer = getattr(self.api, "close", None)
        if closer is not None:
            closer()
