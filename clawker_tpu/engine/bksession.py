"""BuildKit client session: secrets + ssh-agent forwarding over /session.

Docker's BuildKit lane can dial BACK into the client during a solve: the
client POSTs /session with an h2c upgrade, keeps the hijacked duplex
connection open, and serves gRPC on it; the daemon then calls the
client's services mid-build (secret mounts, ssh-agent forwarding, auth).
`RUN --mount=type=secret` and `--mount=type=ssh` only work on this lane.

Implementation: grpcio cannot serve on an already-connected socket, so
the session server listens on a private unix socket (inside a 0700
tmpdir -- never loopback TCP, which any local user could dial; ADVICE
r5) and a byte pump bridges the hijacked connection to it -- the
daemon's h2c traffic flows through the pump into a stock gRPC server.
Service payloads are hand-coded
protobufs (tiny messages; field numbers below are the wire contract):

  moby.buildkit.secrets.v1.Secrets/GetSecret
      req  field1 string id          resp field1 bytes data
  moby.sshforward.v1.SSH/CheckAgent
      req  field1 string id          resp (empty)
  moby.sshforward.v1.SSH/ForwardAgent   (bidi stream)
      both directions: field1 bytes data  <-> local ssh-agent socket

Parity reference: pkg/whail/buildkit/{client,solve}.go -- session-based
solve with secrets provider + ssh forwarding; re-designed on grpcio +
the loopback bridge instead of a vendored buildkit session library.

No dockerd exists in this build environment, so the wire behavior is
pinned by tests/test_bksession.py's daemon simulator: a real gRPC
CLIENT dialing through the same hijacked-socket bridge the daemon
would use.
"""

from __future__ import annotations

import os
import secrets as _secrets
import shutil
import socket
import tempfile
import threading
import uuid
from concurrent import futures

from .. import logsetup

log = logsetup.get("engine.bksession")

SECRETS_GET = "/moby.buildkit.secrets.v1.Secrets/GetSecret"
SSH_CHECK = "/moby.sshforward.v1.SSH/CheckAgent"
SSH_FORWARD = "/moby.sshforward.v1.SSH/ForwardAgent"
HEALTH_CHECK = "/grpc.health.v1.Health/Check"


# ------------------------------------------------------------ protobuf bits


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _field_varint(num: int, value: int) -> bytes:
    """Wire type 0 (varint): enums and ints -- NOT length-delimited."""
    return _varint(num << 3) + _varint(value)


def _parse_fields(data: bytes) -> dict[int, list[bytes]]:
    """Length-delimited fields only (all these messages use strings/bytes);
    varint/fixed fields are skipped."""
    out: dict[int, list[bytes]] = {}
    i = 0
    while i < len(data):
        tag, i = _read_varint(data, i)
        num, wt = tag >> 3, tag & 7
        if wt == 2:
            ln, i = _read_varint(data, i)
            out.setdefault(num, []).append(data[i:i + ln])
            i += ln
        elif wt == 0:
            _, i = _read_varint(data, i)
        elif wt == 5:
            i += 4
        elif wt == 1:
            i += 8
        else:
            break
    return out


def _read_varint(data: bytes, i: int) -> tuple[int, int]:
    shift = n = 0
    while i < len(data):
        b = data[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7
    return n, i


# ----------------------------------------------------------------- services


class SessionServices:
    """What this session exposes to the daemon."""

    def __init__(self, *, secrets: dict[str, bytes] | None = None,
                 ssh_auth_sock: str = ""):
        self.secrets = dict(secrets or {})
        self.ssh_auth_sock = ssh_auth_sock

    def exposed_methods(self) -> list[str]:
        out = [HEALTH_CHECK]
        if self.secrets:
            out.append(SECRETS_GET)
        if self.ssh_auth_sock:
            out += [SSH_CHECK, SSH_FORWARD]
        return out


def _grpc_handler(services: SessionServices):
    import grpc

    def get_secret(request: bytes, context):
        fields = _parse_fields(request)
        sid = (fields.get(1) or [b""])[0].decode("utf-8", "replace")
        if sid not in services.secrets:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"secret {sid} not found")
        return _field_bytes(1, services.secrets[sid])

    def check_agent(request: bytes, context):
        if not services.ssh_auth_sock:
            context.abort(grpc.StatusCode.NOT_FOUND, "no ssh agent")
        return b""

    def forward_agent(request_iterator, context):
        """Bidi byte stream <-> the local ssh-agent unix socket."""
        agent = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            agent.connect(services.ssh_auth_sock)
        except OSError as e:
            context.abort(grpc.StatusCode.UNAVAILABLE, f"agent: {e}")
        stop = threading.Event()

        def pump_in():
            try:
                for msg in request_iterator:
                    data = (_parse_fields(msg).get(1) or [b""])[0]
                    if data:
                        agent.sendall(data)
            except Exception:  # noqa: BLE001 - stream teardown
                pass
            finally:
                stop.set()
                try:
                    agent.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        threading.Thread(target=pump_in, daemon=True).start()
        agent.settimeout(0.2)
        try:
            while True:
                try:
                    chunk = agent.recv(65536)
                except socket.timeout:
                    if stop.is_set():
                        break
                    continue
                except OSError:
                    break
                if not chunk:
                    break
                yield _field_bytes(1, chunk)
        finally:
            agent.close()

    def health(request: bytes, context):
        # HealthCheckResponse.status = SERVING (field 1, enum -> varint):
        # buildkit polls this every second per session; a wire-type
        # mismatch here makes the daemon cancel the whole session
        return _field_varint(1, 1)

    ident = lambda x: x  # noqa: E731 - raw-bytes (de)serializers

    class Generic(grpc.GenericRpcHandler):
        def service(self, call_details):
            m = call_details.method
            if m == SECRETS_GET and services.secrets:
                return grpc.unary_unary_rpc_method_handler(
                    get_secret, request_deserializer=ident,
                    response_serializer=ident)
            if m == SSH_CHECK and services.ssh_auth_sock:
                return grpc.unary_unary_rpc_method_handler(
                    check_agent, request_deserializer=ident,
                    response_serializer=ident)
            if m == SSH_FORWARD and services.ssh_auth_sock:
                return grpc.stream_stream_rpc_method_handler(
                    forward_agent, request_deserializer=ident,
                    response_serializer=ident)
            if m == HEALTH_CHECK:
                return grpc.unary_unary_rpc_method_handler(
                    health, request_deserializer=ident,
                    response_serializer=ident)
            return None

    return Generic()


# ------------------------------------------------------------------ session


class Session:
    """One client session: private-socket gRPC server + hijack bridge.

    The bridge's gRPC server used to listen unauthenticated on loopback
    TCP (``127.0.0.1:0``) -- any local user could dial the ephemeral
    port and read build secrets or drive the ssh-agent forwarder while
    a build ran (ADVICE r5).  It now binds a unix socket inside a fresh
    ``0700`` tmpdir: filesystem permissions ARE the authentication, and
    nothing is reachable from the host's TCP namespace at all."""

    def __init__(self, services: SessionServices, *, name: str = "clawker"):
        import grpc

        self.services = services
        self.session_id = uuid.uuid4().hex
        self.name = name
        self.shared_key = _secrets.token_hex(16)
        # mkdtemp creates the dir 0700 already; chmod pins it against a
        # permissive umask-less override and documents the contract
        self._sock_dir = tempfile.mkdtemp(prefix="clawker-bk-")
        os.chmod(self._sock_dir, 0o700)
        self.socket_path = os.path.join(self._sock_dir, "session.sock")
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8),
            handlers=(_grpc_handler(services),))
        self._server.add_insecure_port(f"unix:{self.socket_path}")
        self._server.start()
        self._pumps: list[threading.Thread] = []
        self._hijack = None

    # -- docker /session request surface --------------------------------

    def headers(self) -> dict[str, str]:
        return {
            "X-Docker-Expose-Session-Uuid": self.session_id,
            "X-Docker-Expose-Session-Name": self.name,
            "X-Docker-Expose-Session-Sharedkey": self.shared_key,
        }

    def method_headers(self) -> list[tuple[str, str]]:
        return [("X-Docker-Expose-Session-Grpc-Method", m)
                for m in self.services.exposed_methods()]

    # -- bridging --------------------------------------------------------

    def attach(self, hijacked) -> None:
        """Bridge a hijacked /session connection to the gRPC server: the
        daemon's h2c bytes flow into the private unix socket and back."""
        self._hijack = hijacked
        local = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        local.connect(self.socket_path)

        def daemon_to_grpc():
            try:
                while True:
                    data = hijacked.read(65536)
                    if not data:
                        break
                    local.sendall(data)
            except OSError:
                pass
            finally:
                try:
                    local.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        def grpc_to_daemon():
            try:
                while True:
                    data = local.recv(65536)
                    if not data:
                        break
                    hijacked.write(data)
            except OSError:
                pass
            finally:
                try:
                    hijacked.close_write()
                except Exception:  # noqa: BLE001
                    pass

        for fn in (daemon_to_grpc, grpc_to_daemon):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"bksession-{fn.__name__}")
            t.start()
            self._pumps.append(t)

    def close(self) -> None:
        if self._hijack is not None:
            try:
                self._hijack.close()
            except Exception:  # noqa: BLE001
                pass
        self._server.stop(grace=0.5)
        for t in self._pumps:
            t.join(timeout=1.0)
        shutil.rmtree(self._sock_dir, ignore_errors=True)


def default_ssh_auth_sock() -> str:
    return os.environ.get("SSH_AUTH_SOCK", "")
