"""Docker Engine HTTP API client (no SDK dependency).

Speaks the daemon's REST API over a pluggable socket factory: local unix
socket, TCP, or an SSH-forwarded unix socket living on a TPU-VM worker
(drivers/tpu_vm).  Parity reference: pkg/whail wrapping the moby client
(engine.go:32); the surface below mirrors the ops inventory in SURVEY.md
2.3 (25 container ops, image ops incl. build, volume/network ops, events).

Implements the subset of API v1.43 this framework uses.  All methods return
parsed JSON trees (daemon-shaped); the typed/jailed layer lives above in
``api.Engine``.

Unary calls ride a keep-alive connection pool (pool.ConnectionPool):
checkout an idle persistent connection, send, check it back in on clean
completion.  A request that fails on a *reused* connection (the daemon
reaped the idle socket: BrokenPipeError / ECONNRESET / BadStatusLine) is
retried exactly once on a fresh dial -- but ONLY for idempotent verbs
(urllib3-style allowlist): a connection that dies before the status
line also matches a response lost AFTER the daemon executed the request
(forward drop, daemon restart), and re-sending a kill/exec_create there
would double-execute it.  Suppressed retries are counted
(``engine_retries_suppressed_total``).  A first-dial failure raises
``DriverError`` immediately.  Streams, ``/events`` and hijacked
attach/exec connections use dedicated sockets that are never pooled.
See docs/engine-connection-pool.md and docs/telemetry.md.
"""

from __future__ import annotations

import http.client
import io
import json
import socket
import struct
import threading
import time
import urllib.parse
from pathlib import Path
from typing import Any, Callable, Iterator

from .. import telemetry
from ..errors import ClawkerError, DriverError
from ..tracing.context import current as trace_current
from ..tracing.context import record_engine_request
from .errors_map import raise_for
from .pool import ConnectionPool, _SockConnection  # noqa: F401 (re-export)

API_PREFIX = "/v1.43"

# Verbs whose daemon-side handlers are safe to re-send after a reused
# socket died before the status line (urllib3 Retry.DEFAULT_ALLOWED_METHODS
# minus the ones this client never issues).  POST is deliberately absent:
# kill / exec_create / create re-sent after a lost response double-execute.
IDEMPOTENT_METHODS = frozenset({"GET", "HEAD", "PUT", "DELETE",
                                "OPTIONS", "TRACE"})

# Per-verb unary latency (dial + send + first-byte + body).  Verb, not
# path: bounded cardinality, and the slow verbs (POST create/start) are
# exactly the ones worth a distribution.
_REQUEST_SECONDS = telemetry.histogram(
    "engine_request_seconds", "Engine-API unary request latency",
    labels=("verb",))

# Unary calls against a hung daemon must fail, not block a scheduler
# lane forever; streams/hijacks clear this (pool.dedicated -> unbounded).
DEFAULT_UNARY_TIMEOUT_S = 30.0

SocketFactory = Callable[[], socket.socket]


def unix_socket_factory(path: str | Path, *,
                        timeout: float | None = DEFAULT_UNARY_TIMEOUT_S) -> SocketFactory:
    def connect() -> socket.socket:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(timeout)
        s.connect(str(path))
        return s

    return connect


def tcp_socket_factory(host: str, port: int) -> SocketFactory:
    def connect() -> socket.socket:
        return socket.create_connection((host, port), timeout=30)

    return connect


class HijackedStream:
    """Bidirectional raw stream from a hijacked attach/exec connection.

    ``tty=True`` streams are raw; ``tty=False`` multiplexes stdout/stderr in
    8-byte-header frames (demux with :meth:`frames`).
    """

    def __init__(self, sock: socket.socket, resp: http.client.HTTPResponse, tty: bool):
        self._sock = sock
        self._resp = resp
        self.tty = tty

    def write(self, data: bytes) -> None:
        self._sock.sendall(data)

    def close_write(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    def read(self, n: int = 65536) -> bytes:
        try:
            if self._resp.status == 101:
                # http.client pins 1xx body length to 0, so resp.read()
                # would return b"" forever; after a real daemon's 101
                # the raw stream follows the headers on the response's
                # buffered reader (which may already hold early bytes)
                return self._resp.fp.read1(n) or b""
            return self._resp.read(n) or b""
        except (http.client.IncompleteRead, ConnectionResetError,
                ValueError, OSError):
            return b""

    def frames(self) -> Iterator[tuple[int, bytes]]:
        """Yield (stream_fd, payload): 1=stdout, 2=stderr. TTY streams yield
        everything as fd 1."""
        if self.tty:
            while True:
                chunk = self.read()
                if not chunk:
                    return
                yield 1, chunk
            return
        buf = b""
        while True:
            while len(buf) < 8:
                chunk = self.read()
                if not chunk:
                    return
                buf += chunk
            fd, length = buf[0], struct.unpack(">I", buf[4:8])[0]
            buf = buf[8:]
            while len(buf) < length:
                chunk = self.read()
                if not chunk:
                    return
                buf += chunk
            yield fd, buf[:length]
            buf = buf[length:]

    def close(self) -> None:
        try:
            self._sock.close()
        finally:
            self._resp.close()


class HTTPDockerAPI:
    """The concrete daemon client.  One instance per daemon endpoint."""

    def __init__(self, factory: SocketFactory, *, api_prefix: str = API_PREFIX,
                 pool_max_idle: int | None = None,
                 pool_idle_ttl: float | None = None):
        self._factory = factory
        self._prefix = api_prefix
        pool_kw: dict[str, Any] = {}
        if pool_max_idle is not None:
            pool_kw["max_idle"] = pool_max_idle
        if pool_idle_ttl is not None:
            pool_kw["idle_ttl"] = pool_idle_ttl
        self._pool = ConnectionPool(factory, **pool_kw)
        self._event_conns: set = set()  # live /events connections (close_events)
        self._event_lock = threading.Lock()

    # ------------------------------------------------------------ plumbing

    def _url(self, path: str, query: dict[str, Any] | None = None, *,
             versioned: bool = True) -> str:
        url = (self._prefix if versioned else "") + path
        if query:
            q = {}
            for k, v in query.items():
                if v is None:
                    continue
                if isinstance(v, bool):
                    v = "true" if v else "false"
                elif isinstance(v, (dict, list)):
                    v = json.dumps(v)
                q[k] = v
            if q:
                url += "?" + urllib.parse.urlencode(q)
        return url

    def _request(
        self,
        method: str,
        path: str,
        *,
        query: dict[str, Any] | None = None,
        body: Any = None,
        raw_body: bytes | None = None,
        headers: dict[str, str] | None = None,
        versioned: bool = True,
        dedicated: bool = False,
    ) -> Any:
        """Unary call over a pooled keep-alive connection.

        ``dedicated=True`` dials a never-pooled, read-unbounded socket for
        unary ops whose response legitimately takes arbitrarily long
        (wait / stop / restart); everything else checks a connection out
        of the pool and returns it on clean completion.  A failure on a
        REUSED connection -- the daemon reaped the idle socket between
        requests -- is retried exactly once on a fresh dial IF the verb
        is idempotent; non-idempotent verbs surface the failure (the
        daemon may have executed the request and lost only the
        response), counting the suppressed retry.  First-dial failures
        raise ``DriverError`` unchanged.
        """
        t_req = time.perf_counter()
        hdrs = {"Host": "docker", "Connection": "keep-alive"}
        # Distributed tracing rides ambient context (docs/tracing.md):
        # when a scheduler/workerd wrapped this call in ``use(ctx)``, the
        # daemon sees a W3C traceparent header and the call is recorded
        # as an ``engine.request`` span -- zero cost when no context is
        # active (the common untraced path).
        t_trace = time.time() if trace_current() is not None else 0.0
        if t_trace:
            hdrs["traceparent"] = trace_current().to_header()
        data: bytes | None = None
        if raw_body is not None:
            data = raw_body
            hdrs["Content-Type"] = "application/x-tar"
        elif body is not None:
            data = json.dumps(body).encode()
            hdrs["Content-Type"] = "application/json"
        if headers:
            hdrs.update(headers)
        url = self._url(path, query, versioned=versioned)
        conn: _SockConnection | None = None
        reused = False
        retried = False
        while True:
            try:
                if dedicated:
                    conn, reused = self._pool.dedicated(), False
                elif retried:
                    conn, reused = self._pool.fresh(), False
                else:
                    conn, reused = self._pool.checkout()
                conn.request(method, url, body=data, headers=hdrs)
                resp = conn.getresponse()
            except (OSError, http.client.HTTPException) as e:
                if conn is not None:
                    conn.close()
                if reused and not retried and not isinstance(e, TimeoutError):
                    # the daemon reaped the idle socket under us
                    # (BrokenPipe / ECONNRESET / BadStatusLine): one
                    # retry on a guaranteed-fresh dial.  A TimeoutError
                    # is excluded: that is a SLOW daemon still executing
                    # the request, and re-sending would run it twice.
                    # Non-idempotent verbs are excluded too -- a socket
                    # dead before the status line ALSO matches a
                    # response lost after execution (forward drop,
                    # daemon restart), and re-sending a kill or an
                    # exec_create there would run it twice.
                    if method in IDEMPOTENT_METHODS:
                        self._pool.note_stale_retry()
                        retried = True
                        continue
                    self._pool.note_suppressed_retry()
                if t_trace:
                    record_engine_request(method, path, t_trace, ok=False)
                raise DriverError(f"daemon unreachable ({method} {path}): {e}") from e
            try:
                payload = resp.read()
            except (OSError, http.client.HTTPException) as e:
                # a status line arrived, so the daemon definitely executed
                # the request: NEVER retry here -- re-sending a delivered
                # non-idempotent request (create/kill/...) would run it
                # twice.  Stale-socket reaping manifests before the status
                # line, which the block above already handles.
                conn.close()
                if t_trace:
                    record_engine_request(method, path, t_trace, ok=False)
                raise DriverError(f"daemon unreachable ({method} {path}): {e}") from e
            break
        if not dedicated:
            # dedicated ops (wait/stop/put_archive) legitimately block for
            # container lifetimes -- recording them would drown the verb's
            # actual daemon latency distribution
            _REQUEST_SECONDS.labels(method).observe(time.perf_counter() - t_req)
        if dedicated or resp.will_close:
            conn.close()
        else:
            self._pool.checkin(conn)
        if t_trace:
            record_engine_request(method, path, t_trace,
                                  ok=resp.status < 400)
        self._check(resp.status, payload, path)
        if not payload:
            return None
        ct = resp.getheader("Content-Type", "")
        if ct.startswith("application/json"):
            return json.loads(payload)
        return payload

    def _open_stream(
        self,
        method: str,
        url: str,
        *,
        body: Any = None,
        headers: dict[str, str] | None = None,
        label: str = "",
        check_path: str = "",
    ) -> tuple[_SockConnection, http.client.HTTPResponse]:
        """Dial a dedicated (never-pooled, read-unbounded) connection and
        send one request on it, mapping dial/send failures to DriverError
        and HTTP errors through _check.  Shared by streams/logs/build."""
        conn: _SockConnection | None = None
        try:
            conn = self._pool.dedicated()
            conn.request(method, url, body=body, headers=headers or {})
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            if conn is not None:
                conn.close()
            raise DriverError(f"daemon unreachable ({label}): {e}") from e
        if resp.status >= 400:
            payload = resp.read()
            conn.close()
            self._check(resp.status, payload, check_path)
        return conn, resp

    def pool_stats(self) -> dict:
        """Connection-pool telemetry: dials / reuses / stale_retries / idle."""
        return self._pool.stats()

    def close(self) -> None:
        """Drain-on-shutdown: tear down event streams and idle pooled
        connections.  In-flight checkouts finish and are then dropped."""
        self.close_events()
        self._pool.close()

    def _stream(
        self,
        method: str,
        path: str,
        *,
        query: dict[str, Any] | None = None,
        body: Any = None,
        raw_body: bytes | io.BufferedIOBase | None = None,
        headers: dict[str, str] | None = None,
        track_events: bool = False,
    ) -> Iterator[dict]:
        """Request returning a stream of JSON objects (build/pull/events).

        Rides a dedicated, never-pooled connection with no read timeout:
        ``/events`` legitimately sits silent for hours.
        """
        hdrs = {"Host": "docker"}
        data: Any = None
        if raw_body is not None:
            data = raw_body
            hdrs["Content-Type"] = "application/x-tar"
        elif body is not None:
            data = json.dumps(body).encode()
            hdrs["Content-Type"] = "application/json"
        if headers:
            hdrs.update(headers)
        conn, resp = self._open_stream(
            method, self._url(path, query), body=data, headers=hdrs,
            label=f"{method} {path}", check_path=path)
        if track_events:
            with self._event_lock:
                self._event_conns.add(conn)

        def gen() -> Iterator[dict]:
            buf = b""
            try:
                while True:
                    try:
                        chunk = resp.read1(65536)
                    except OSError:
                        break  # close_events tore the socket down
                    if not chunk:
                        break
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        line = line.strip()
                        if line:
                            yield json.loads(line)
                if buf.strip():
                    yield json.loads(buf)
            finally:
                with self._event_lock:
                    self._event_conns.discard(conn)
                conn.close()

        return gen()

    def _hijack(
        self,
        path: str,
        *,
        query: dict[str, Any] | None = None,
        body: Any = None,
        tty: bool = False,
        upgrade: str = "tcp",
        extra_headers: list[tuple[str, str]] | None = None,
    ) -> HijackedStream:
        data = json.dumps(body).encode() if body is not None else b""
        conn: _SockConnection | None = None
        try:
            conn = self._pool.dedicated()
            conn.putrequest("POST", self._url(path, query), skip_host=True)
            conn.putheader("Host", "docker")
            conn.putheader("Content-Type", "application/json")
            conn.putheader("Content-Length", str(len(data)))
            conn.putheader("Connection", "Upgrade")
            conn.putheader("Upgrade", upgrade)
            for k, v in extra_headers or []:
                conn.putheader(k, v)
            conn.endheaders()
            if data:
                conn.send(data)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            if conn is not None:
                conn.close()
            raise DriverError(f"daemon unreachable (hijack {path}): {e}") from e
        if resp.status not in (101, 200):
            payload = resp.read()
            conn.close()
            self._check(resp.status, payload, path)
        sock = conn.sock
        assert sock is not None
        sock.settimeout(None)
        return HijackedStream(sock, resp, tty)

    @staticmethod
    def _check(status: int, payload: bytes, path: str) -> None:
        if status < 400:
            return
        msg = ""
        try:
            msg = json.loads(payload).get("message", "")
        except Exception:
            msg = payload.decode("utf-8", "replace")[:400]
        raise_for(status, msg, path)

    # -------------------------------------------------------------- system

    def ping(self) -> bool:
        try:
            self._request("GET", "/_ping", versioned=False)
            return True
        except ClawkerError:  # unreachable (DriverError) or non-200 status
            return False

    def info(self) -> dict:
        return self._request("GET", "/info")

    def version(self) -> dict:
        return self._request("GET", "/version")

    # ---------------------------------------------------------- containers

    def container_create(self, name: str, config: dict) -> dict:
        return self._request("POST", "/containers/create", query={"name": name}, body=config)

    def container_start(self, cid: str) -> None:
        self._request("POST", f"/containers/{cid}/start")

    def container_stop(self, cid: str, timeout: int = 10) -> None:
        # dedicated: the daemon answers only after up to `t` seconds of
        # graceful shutdown -- must not trip the pooled read timeout
        self._request("POST", f"/containers/{cid}/stop", query={"t": timeout},
                      dedicated=True)

    def container_kill(self, cid: str, signal: str = "KILL") -> None:
        self._request("POST", f"/containers/{cid}/kill", query={"signal": signal})

    def container_restart(self, cid: str, timeout: int = 10) -> None:
        self._request("POST", f"/containers/{cid}/restart", query={"t": timeout},
                      dedicated=True)

    def container_pause(self, cid: str) -> None:
        self._request("POST", f"/containers/{cid}/pause")

    def container_unpause(self, cid: str) -> None:
        self._request("POST", f"/containers/{cid}/unpause")

    def container_remove(self, cid: str, *, force: bool = False, volumes: bool = False) -> None:
        # dedicated: removing a container with large volumes can
        # legitimately outlast the pooled unary read timeout
        self._request("DELETE", f"/containers/{cid}",
                      query={"force": force, "v": volumes}, dedicated=True)

    def container_rename(self, cid: str, new_name: str) -> None:
        self._request("POST", f"/containers/{cid}/rename", query={"name": new_name})

    def container_inspect(self, cid: str) -> dict:
        return self._request("GET", f"/containers/{cid}/json")

    def container_list(self, *, all: bool = False, filters: dict | None = None) -> list[dict]:
        return self._request(
            "GET", "/containers/json", query={"all": all, "filters": filters or {}}
        )

    def container_wait(self, cid: str, condition: str = "not-running") -> dict:
        # dedicated: blocks until the container exits (the scheduler's
        # waker threads park here for whole iterations) -- never pooled,
        # never read-bounded
        return self._request(
            "POST", f"/containers/{cid}/wait", query={"condition": condition},
            dedicated=True,
        )

    def container_resize(self, cid: str, height: int, width: int) -> None:
        self._request(
            "POST", f"/containers/{cid}/resize", query={"h": height, "w": width}
        )

    def container_attach(
        self, cid: str, *, tty: bool, stdin: bool = True, logs: bool = False
    ) -> HijackedStream:
        return self._hijack(
            f"/containers/{cid}/attach",
            query={
                "stream": True,
                "stdin": stdin,
                "stdout": True,
                "stderr": True,
                "logs": logs,
            },
            tty=tty,
        )

    def container_logs(
        self, cid: str, *, follow: bool = False, tail: str = "all"
    ) -> Iterator[bytes]:
        q = {"stdout": True, "stderr": True, "follow": follow, "tail": tail}
        conn, resp = self._open_stream(
            "GET", self._url(f"/containers/{cid}/logs", q),
            headers={"Host": "docker"}, label="logs",
            check_path=f"/containers/{cid}/logs")

        def gen() -> Iterator[bytes]:
            try:
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        return
                    yield chunk
            finally:
                conn.close()

        return gen()

    def put_archive(self, cid: str, path: str, tar_bytes: bytes) -> None:
        # dedicated: the daemon extracts the whole tar before replying --
        # a large snapshot-workspace seed can outlast the pooled unary
        # read timeout on a perfectly healthy daemon
        self._request(
            "PUT",
            f"/containers/{cid}/archive",
            query={"path": path},
            raw_body=tar_bytes,
            dedicated=True,
        )

    def get_archive(self, cid: str, path: str) -> bytes:
        return self._request("GET", f"/containers/{cid}/archive", query={"path": path})

    # ---------------------------------------------------------------- exec

    def exec_create(self, cid: str, config: dict) -> dict:
        return self._request("POST", f"/containers/{cid}/exec", body=config)

    def exec_start(self, exec_id: str, *, tty: bool = False, detach: bool = False):
        if detach:
            return self._request(
                "POST", f"/exec/{exec_id}/start", body={"Detach": True, "Tty": tty}
            )
        return self._hijack(
            f"/exec/{exec_id}/start", body={"Detach": False, "Tty": tty}, tty=tty
        )

    def exec_inspect(self, exec_id: str) -> dict:
        return self._request("GET", f"/exec/{exec_id}/json")

    # ------------------------------------------------------------- session

    def session_attach(self, headers: dict[str, str],
                       method_headers: list[tuple[str, str]]) -> HijackedStream:
        """POST /session with the h2c upgrade: the returned duplex stream
        carries the daemon's gRPC calls back into the client
        (engine/bksession.Session.attach bridges it)."""
        return self._hijack(
            "/session", upgrade="h2c", tty=True,
            extra_headers=[*headers.items(), *method_headers])

    # -------------------------------------------------------------- images

    def image_list(self, *, filters: dict | None = None) -> list[dict]:
        return self._request("GET", "/images/json", query={"filters": filters or {}})

    def image_inspect(self, ref: str) -> dict:
        return self._request("GET", f"/images/{urllib.parse.quote(ref, safe='')}/json")

    def image_tag(self, ref: str, repo: str, tag: str) -> None:
        self._request(
            "POST",
            f"/images/{urllib.parse.quote(ref, safe='')}/tag",
            query={"repo": repo, "tag": tag},
        )

    def image_remove(self, ref: str, *, force: bool = False) -> None:
        # dedicated: deleting a multi-GB image's layers can outlast the
        # pooled unary read timeout
        self._request(
            "DELETE", f"/images/{urllib.parse.quote(ref, safe='')}",
            query={"force": force}, dedicated=True,
        )

    def image_build(
        self,
        context_tar: bytes,
        *,
        tags: list[str],
        labels: dict[str, str] | None = None,
        dockerfile: str = "Dockerfile",
        buildargs: dict[str, str] | None = None,
        target: str = "",
        pull: bool = False,
        no_cache: bool = False,
        version: str = "1",
        buildid: str = "",
        session: str = "",
    ) -> Iterator[dict]:
        q: dict[str, Any] = {
            "dockerfile": dockerfile,
            "labels": labels or {},
            "buildargs": buildargs or {},
            "pull": pull,
            "nocache": no_cache,
        }
        if target:
            q["target"] = target
        if version == "2":
            # BuildKit lane: progress arrives as aux trace records
            # (engine/buildkit.py decodes them)
            q["version"] = "2"
            if buildid:
                q["buildid"] = buildid
            if session:
                q["session"] = session
        url = self._url("/build", q)
        # t= repeats per tag; urlencode can't repeat via dict, append manually
        for t in tags:
            url += "&t=" + urllib.parse.quote(t, safe="")
        conn, resp = self._open_stream(
            "POST", url, body=context_tar,
            headers={"Host": "docker", "Content-Type": "application/x-tar"},
            label="build", check_path="/build")

        def gen() -> Iterator[dict]:
            buf = b""
            try:
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if line.strip():
                            yield json.loads(line)
                if buf.strip():
                    yield json.loads(buf)
            finally:
                conn.close()

        return gen()

    def image_build_buildkit(self, context_tar: bytes, **kw) -> Iterator[dict]:
        """BuildKit lane: same request with version=2 (the aux trace
        records are decoded by engine/buildkit.py)."""
        return self.image_build(context_tar, version="2", **kw)

    def build_cancel(self, buildid: str) -> None:
        """Cancel an in-flight BuildKit build by its buildid."""
        self._request("POST", "/build/cancel", query={"id": buildid})

    def image_pull(self, ref: str) -> Iterator[dict]:
        if ":" in ref.rsplit("/", 1)[-1]:
            name, tag = ref.rsplit(":", 1)
        else:
            name, tag = ref, "latest"
        return self._stream(
            "POST", "/images/create", query={"fromImage": name, "tag": tag}
        )

    # ------------------------------------------------------------- volumes

    def volume_create(self, name: str, labels: dict[str, str] | None = None) -> dict:
        return self._request(
            "POST", "/volumes/create", body={"Name": name, "Labels": labels or {}}
        )

    def volume_list(self, *, filters: dict | None = None) -> dict:
        return self._request("GET", "/volumes", query={"filters": filters or {}})

    def volume_inspect(self, name: str) -> dict:
        return self._request("GET", f"/volumes/{name}")

    def volume_remove(self, name: str, *, force: bool = False) -> None:
        # dedicated: same slow-deletion story as container/image remove
        self._request("DELETE", f"/volumes/{name}", query={"force": force},
                      dedicated=True)

    # ------------------------------------------------------------ networks

    def network_create(self, name: str, config: dict) -> dict:
        body = {"Name": name, **config}
        return self._request("POST", "/networks/create", body=body)

    def network_list(self, *, filters: dict | None = None) -> list[dict]:
        return self._request("GET", "/networks", query={"filters": filters or {}})

    def network_inspect(self, ref: str) -> dict:
        return self._request("GET", f"/networks/{ref}")

    def network_remove(self, ref: str) -> None:
        self._request("DELETE", f"/networks/{ref}")

    def network_connect(self, net: str, cid: str, *, ipv4: str = "") -> None:
        body: dict[str, Any] = {"Container": cid}
        if ipv4:
            body["EndpointConfig"] = {"IPAMConfig": {"IPv4Address": ipv4}}
        self._request("POST", f"/networks/{net}/connect", body=body)

    def network_disconnect(self, net: str, cid: str, *, force: bool = False) -> None:
        self._request(
            "POST", f"/networks/{net}/disconnect", body={"Container": cid, "Force": force}
        )

    # -------------------------------------------------------------- events

    def events(self, *, filters: dict | None = None) -> Iterator[dict]:
        return self._stream(
            "GET", "/events", query={"filters": filters or {}}, track_events=True
        )

    def close_events(self) -> None:
        """Tear down live event streams so blocked readers unblock
        (the Feeder's stop path; the fake exposes the same hook).
        Snapshot under the lock: stream generators concurrently discard
        from the set as they wind down."""
        with self._event_lock:
            conns = list(self._event_conns)
        for conn in conns:
            try:
                conn.close()
            except Exception:
                pass
