"""In-process fake Docker daemon: the universal unit-test seam.

Parity reference: pkg/whail/whailtest FakeAPIClient (SURVEY.md 4) -- the
fake sits at the same method surface as :class:`HTTPDockerAPI`, so all real
middleware (label jail, naming, bootstrap, control plane) runs unmodified
against it.  Adds: semantic container lifecycle with simulated processes,
attach duplex streams, events broadcast, exec handlers, a call recorder, and
failure injection.  Unlike the reference's panic-on-unstubbed discipline,
every method here has working default semantics; tests override behavior
where they care.
"""

from __future__ import annotations

import copy
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

from ..errors import ConflictError, NotFoundError
from ..util.ids import short_id


class FakeStreamEnd(Exception):
    pass


class _Pipe:
    """Byte pipe with EOF."""

    def __init__(self):
        self._q: "queue.Queue[bytes | None]" = queue.Queue()
        self._eof = False

    def write(self, data: bytes) -> None:
        if data:
            self._q.put(data)

    def close(self) -> None:
        self._q.put(None)

    def read(self, timeout: float | None = None) -> bytes:
        """One chunk; b"" on EOF."""
        if self._eof:
            return b""
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError("pipe read timeout")
        if item is None:
            self._eof = True
            return b""
        return item


class FakeProcessIO:
    """Handles given to a simulated container process."""

    def __init__(self, stdin: _Pipe, stdout: _Pipe, kill_event: threading.Event,
                 log_buf: bytearray | None = None):
        self._stdin = stdin
        self._stdout = stdout
        self._log_buf = log_buf
        self.kill_event = kill_event

    def read_stdin(self, timeout: float | None = 5.0) -> bytes:
        return self._stdin.read(timeout)

    def write_stdout(self, data: bytes) -> None:
        # daemons capture container stdout in the log ring whether or not
        # anyone is attached -- so does the fake (container_logs serves it)
        if self._log_buf is not None:
            self._log_buf.extend(data)
        self._stdout.write(data)

    def wait_for_kill(self, timeout: float | None = None) -> bool:
        return self.kill_event.wait(timeout)


Behavior = Callable[[FakeProcessIO], int]


def idle_behavior(io: FakeProcessIO) -> int:
    """Default simulated process: runs until stopped/killed, exits 137."""
    io.wait_for_kill()
    return 137


def exit_behavior(output: bytes = b"", code: int = 0, delay: float = 0.0) -> Behavior:
    def run(io: FakeProcessIO) -> int:
        if delay:
            time.sleep(delay)
        if output:
            io.write_stdout(output)
        return code

    return run


def echo_behavior(io: FakeProcessIO) -> int:
    """Echoes stdin back to stdout until stdin EOF or kill."""
    while not io.kill_event.is_set():
        try:
            data = io.read_stdin(timeout=0.1)
        except TimeoutError:
            continue
        if not data:
            return 0
        io.write_stdout(data)
    return 137


class FakeStream:
    """Duplex attach stream mirroring HijackedStream's interface."""

    def __init__(self, stdin: _Pipe, stdout: _Pipe, tty: bool):
        self._stdin = stdin
        self._stdout = stdout
        self.tty = tty

    def write(self, data: bytes) -> None:
        self._stdin.write(data)

    def close_write(self) -> None:
        self._stdin.close()

    def read(self, n: int = 65536) -> bytes:
        try:
            return self._stdout.read(timeout=10.0)
        except TimeoutError:
            return b""

    def frames(self) -> Iterator[tuple[int, bytes]]:
        while True:
            chunk = self.read()
            if not chunk:
                return
            yield 1, chunk

    def close(self) -> None:
        self._stdin.close()


@dataclass
class FakeContainer:
    id: str
    name: str
    config: dict
    state: str = "created"            # created | running | paused | exited
    exit_code: int = 0
    behavior: Behavior = idle_behavior
    archives: dict[str, bytes] = field(default_factory=dict)  # path -> tar bytes
    stdin: _Pipe = field(default_factory=_Pipe)
    stdout: _Pipe = field(default_factory=_Pipe)
    kill_event: threading.Event = field(default_factory=threading.Event)
    exited: threading.Event = field(default_factory=threading.Event)
    ip: str = ""
    networks: dict[str, str] = field(default_factory=dict)  # net -> ip
    log_buf: bytearray = field(default_factory=bytearray)  # captured stdout

    @property
    def labels(self) -> dict[str, str]:
        return self.config.get("Labels") or {}

    def inspect(self) -> dict:
        nets = {
            n: {"IPAddress": ip} for n, ip in self.networks.items()
        }
        return {
            "Id": self.id,
            "Name": "/" + self.name,
            "Created": "2026-01-01T00:00:00Z",
            "Config": copy.deepcopy(self.config),
            "State": {
                "Status": self.state,
                "Running": self.state == "running",
                "Paused": self.state == "paused",
                "ExitCode": self.exit_code,
                "Pid": 4242 if self.state == "running" else 0,
            },
            "HostConfig": copy.deepcopy(self.config.get("HostConfig", {})),
            "Mounts": [
                _mount_inspect(m) for m in self.config.get("HostConfig", {}).get("Binds", [])
            ],
            "NetworkSettings": {"Networks": nets, "IPAddress": self.ip},
        }

    def summary(self) -> dict:
        return {
            "Id": self.id,
            "Names": ["/" + self.name],
            "Image": self.config.get("Image", ""),
            "Labels": dict(self.labels),
            "State": self.state,
            "Status": self.state,
        }


def _mount_inspect(bind: str) -> dict:
    parts = bind.split(":")
    src, dst = parts[0], parts[1] if len(parts) > 1 else parts[0]
    ro = len(parts) > 2 and "ro" in parts[2]
    return {"Type": "bind", "Source": src, "Destination": dst, "RW": not ro}


class FakeDockerAPI:
    """Drop-in fake for HTTPDockerAPI with semantic state."""

    def __init__(self):
        self.containers: dict[str, FakeContainer] = {}
        self.images: dict[str, dict] = {}       # ref -> {"Id", "Labels", ...}
        self.volumes: dict[str, dict] = {}
        self.networks: dict[str, dict] = {}
        self.execs: dict[str, dict] = {}
        self.calls: list[tuple[str, tuple, dict]] = []
        self.fail_next: dict[str, Exception] = {}
        self.exec_handler: Callable[[FakeContainer, list[str]], tuple[int, bytes]] = (
            lambda c, cmd: (0, b"")
        )
        self.image_behaviors: dict[str, Behavior] = {}
        self.build_hook: Callable[[bytes, list[str]], None] | None = None
        # "1" = legacy-only daemon; "2" = BuildKit default (the engine's
        # Builder probes this via info()["BuilderVersion"])
        self.builder_version = "1"
        self.buildkit_refuse = False  # advertise v2 but reject the lane
        self._event_subs: list[queue.Queue] = []
        self._lock = threading.RLock()
        self._ip_counter = 9

    # ----------------------------------------------------------- test hooks

    def _record(self, name: str, *args, **kw) -> None:
        self.calls.append((name, args, kw))
        if name in self.fail_next:
            raise self.fail_next.pop(name)

    def calls_named(self, name: str) -> list[tuple[tuple, dict]]:
        return [(a, k) for n, a, k in self.calls if n == name]

    def add_image(self, ref: str, labels: dict[str, str] | None = None) -> None:
        self.images[ref] = {
            "Id": "sha256:" + short_id(32),
            "RepoTags": [ref],
            "Labels": labels or {},
        }

    def set_behavior(self, image: str, behavior: Behavior) -> None:
        self.image_behaviors[image] = behavior

    def add_container(self, name: str, *, image: str = "",
                      labels: dict[str, str] | None = None,
                      state: str = "created", exit_code: int = 0,
                      behavior: Behavior | None = None) -> str:
        """Seed a PRE-EXISTING container, bypassing the create/start API
        (and the call recorder): the state a daemon is in when a new CLI
        process arrives -- e.g. loop containers left running by a killed
        scheduler that ``--resume`` must adopt without re-creating.
        ``state`` is created | running | exited; a running container
        gets a live simulated process."""
        if state not in ("created", "running", "exited"):
            raise ValueError(f"add_container: unknown state {state!r}")
        with self._lock:
            for c in self.containers.values():
                if c.name == name:
                    raise ConflictError(f"container name {name} already in use")
            cid = short_id(64)
            config = {"Image": image, "Labels": dict(labels or {})}
            c = FakeContainer(
                id=cid, name=name, config=config,
                behavior=behavior or self.image_behaviors.get(image,
                                                              idle_behavior))
            self.containers[cid] = c
        if state == "running":
            c.state = "running"
            c.ip = c.networks.get("bridge", "") or self._next_ip()
            self._spawn(c)
        elif state == "exited":
            c.state = "exited"
            c.exit_code = exit_code
            c.stdout.close()
            c.exited.set()
        return cid

    def emit_event(self, ev: dict) -> None:
        with self._lock:
            for q in self._event_subs:
                q.put(ev)

    def _event(self, typ: str, action: str, actor_id: str, attrs: dict | None = None) -> None:
        # Real Docker attaches the object's labels to event Actor.Attributes;
        # the managed-label event filter depends on this.
        attributes = dict(attrs or {})
        if typ == "container":
            c = self.containers.get(actor_id)
            if c is not None:
                attributes.update(c.labels)
        self.emit_event(
            {
                "Type": typ,
                "Action": action,
                "Actor": {"ID": actor_id, "Attributes": attributes},
                "time": time.time(),
            }
        )

    def _find(self, ref: str) -> FakeContainer:
        with self._lock:
            if ref in self.containers:
                return self.containers[ref]
            for c in self.containers.values():
                if c.name == ref or c.id.startswith(ref):
                    return c
        raise NotFoundError(f"No such container: {ref}")

    # -------------------------------------------------------------- system

    def ping(self) -> bool:
        self._record("ping")
        return True

    def info(self) -> dict:
        self._record("info")
        return {"Name": "fake-daemon", "ServerVersion": "fake-1.0",
                "Containers": len(self.containers),
                "BuilderVersion": self.builder_version}

    def version(self) -> dict:
        return {"Version": "fake-1.0", "ApiVersion": "1.43"}

    # ---------------------------------------------------------- containers

    def container_create(self, name: str, config: dict) -> dict:
        self._record("container_create", name, config)
        with self._lock:
            for c in self.containers.values():
                if c.name == name:
                    raise ConflictError(f"container name {name} already in use")
            image = config.get("Image", "")
            if image and image not in self.images:
                raise NotFoundError(f"No such image: {image}")
            cid = short_id(64)
            behavior = self.image_behaviors.get(image, idle_behavior)
            c = FakeContainer(id=cid, name=name, config=copy.deepcopy(config), behavior=behavior)
            nc = config.get("NetworkingConfig", {}).get("EndpointsConfig", {})
            for net, epc in nc.items():
                ip = (epc or {}).get("IPAMConfig", {}).get("IPv4Address", "")
                c.networks[net] = ip or self._next_ip()
            self.containers[cid] = c
        self._event("container", "create", cid, {"name": name})
        return {"Id": cid, "Warnings": []}

    def _next_ip(self) -> str:
        self._ip_counter += 1
        return f"172.28.0.{self._ip_counter}"

    def container_start(self, cid: str) -> None:
        self._record("container_start", cid)
        c = self._find(cid)
        if c.state == "running":
            return
        if c.state == "exited":
            # restart: fresh pipes
            c.stdin, c.stdout = _Pipe(), _Pipe()
            c.kill_event = threading.Event()
            c.exited = threading.Event()
        c.state = "running"
        if not c.ip:
            c.ip = c.networks.get("bridge", "") or self._next_ip()

        # start event precedes any possible die (real daemons order it so)
        self._event("container", "start", c.id, {"name": c.name})
        self._spawn(c)

    def _spawn(self, c: FakeContainer) -> None:
        """Run the container's simulated process on a daemon thread."""

        def run() -> None:
            io = FakeProcessIO(c.stdin, c.stdout, c.kill_event, c.log_buf)
            try:
                code = c.behavior(io)
            except Exception:
                code = 1
            with self._lock:
                c.exit_code = code
                c.state = "exited"
            c.stdout.close()
            c.exited.set()
            self._event("container", "die", c.id, {"name": c.name, "exitCode": str(code)})

        threading.Thread(target=run, daemon=True, name=f"fake-{c.name}").start()

    def container_stop(self, cid: str, timeout: int = 10) -> None:
        self._record("container_stop", cid)
        c = self._find(cid)
        if c.state != "running":
            return
        c.kill_event.set()
        c.exited.wait(timeout=5)
        self._event("container", "stop", c.id, {"name": c.name})

    def container_kill(self, cid: str, signal: str = "KILL") -> None:
        self._record("container_kill", cid, signal)
        c = self._find(cid)
        if c.state != "running":
            raise ConflictError(f"container {c.name} is not running")
        c.kill_event.set()
        c.exited.wait(timeout=5)
        self._event("container", "kill", c.id, {"name": c.name, "signal": signal})

    def container_restart(self, cid: str, timeout: int = 10) -> None:
        self.container_stop(cid, timeout)
        self.container_start(cid)

    def container_pause(self, cid: str) -> None:
        self._record("container_pause", cid)
        c = self._find(cid)
        if c.state != "running":
            raise ConflictError("not running")
        c.state = "paused"

    def container_unpause(self, cid: str) -> None:
        self._record("container_unpause", cid)
        c = self._find(cid)
        if c.state != "paused":
            raise ConflictError("not paused")
        c.state = "running"

    def container_remove(self, cid: str, *, force: bool = False, volumes: bool = False) -> None:
        self._record("container_remove", cid, force=force, volumes=volumes)
        c = self._find(cid)
        if c.state == "running":
            if not force:
                raise ConflictError(f"container {c.name} is running; use force")
            c.kill_event.set()
            c.exited.wait(timeout=5)
        with self._lock:
            del self.containers[c.id]
            if volumes:
                for bind in c.config.get("HostConfig", {}).get("Binds", []):
                    src = bind.split(":")[0]
                    self.volumes.pop(src, None)
        # container already deleted from the table: carry labels explicitly
        self._event("container", "destroy", c.id, {"name": c.name, **c.labels})

    def container_rename(self, cid: str, new_name: str) -> None:
        self._record("container_rename", cid, new_name)
        c = self._find(cid)
        with self._lock:
            for other in self.containers.values():
                if other.name == new_name and other is not c:
                    # real daemons 409 here; adoption's replace path
                    # depends on seeing the conflict, not a dup name
                    raise ConflictError(
                        f"container name {new_name} already in use")
            c.name = new_name

    def container_relabel(self, cid: str, labels: dict) -> None:
        """Merge ``labels`` into the container's label set.  Real Docker
        has no relabel endpoint (labels are create-time immutable);
        engines that can do it (this fake; an nsd-style first-party
        daemon could) expose it so warm-pool adoption can finalize the
        agent/epoch labels in place -- Engine.relabel_container degrades
        gracefully where the api lacks the method."""
        self._record("container_relabel", cid, labels)
        c = self._find(cid)
        with self._lock:
            merged = dict(c.config.get("Labels") or {})
            merged.update({str(k): str(v) for k, v in labels.items()})
            c.config["Labels"] = merged

    def container_inspect(self, cid: str) -> dict:
        self._record("container_inspect", cid)
        return self._find(cid).inspect()

    def container_list(self, *, all: bool = False, filters: dict | None = None) -> list[dict]:
        self._record("container_list", all=all, filters=filters)
        out = []
        with self._lock:
            for c in self.containers.values():
                if not all and c.state != "running":
                    continue
                if not _match_filters(c.labels, c.name, filters):
                    continue
                out.append(c.summary())
        return out

    def container_wait(self, cid: str, condition: str = "not-running") -> dict:
        self._record("container_wait", cid)
        c = self._find(cid)
        if c.state == "running":
            c.exited.wait()
        return {"StatusCode": c.exit_code}

    def container_resize(self, cid: str, height: int, width: int) -> None:
        self._record("container_resize", cid, height, width)
        self._find(cid)

    def container_attach(self, cid: str, *, tty: bool, stdin: bool = True, logs: bool = False) -> FakeStream:
        self._record("container_attach", cid, tty=tty)
        c = self._find(cid)
        return FakeStream(c.stdin, c.stdout, tty)

    def container_logs(self, cid: str, *, follow: bool = False, tail: str = "all") -> Iterator[bytes]:
        self._record("container_logs", cid)
        c = self._find(cid)
        if follow:
            # stream-until-exit semantics collapse to: wait, then snapshot
            c.exited.wait(10.0)
        elif c.state == "running" and not c.log_buf:
            # a just-started behavior may not have written yet; give the
            # simulated process one beat, like a daemon's log ring would
            c.exited.wait(0.5)
        body = bytes(c.log_buf)
        if tail != "all":
            try:
                lines = body.splitlines(keepends=True)
                body = b"".join(lines[-int(tail):])
            except ValueError:
                pass
        if not body:
            return iter(())
        if not (c.config.get("Tty") or False):
            # non-TTY log bodies are stdcopy-framed by real daemons;
            # Engine.logs() demuxes, so unframed bytes would corrupt
            import struct as _struct

            body = b"\x01\x00\x00\x00" + _struct.pack(">I", len(body)) + body
        return iter([body])

    def put_archive(self, cid: str, path: str, tar_bytes: bytes) -> None:
        self._record("put_archive", cid, path)
        c = self._find(cid)
        c.archives[path] = tar_bytes

    def get_archive(self, cid: str, path: str) -> bytes:
        self._record("get_archive", cid, path)
        c = self._find(cid)
        if path not in c.archives:
            raise NotFoundError(f"no archive at {path}")
        return c.archives[path]

    # ---------------------------------------------------------------- exec

    def exec_create(self, cid: str, config: dict) -> dict:
        self._record("exec_create", cid, config)
        c = self._find(cid)
        eid = short_id(32)
        self.execs[eid] = {"container": c.id, "config": config, "exit": None}
        return {"Id": eid}

    def exec_start(self, exec_id: str, *, tty: bool = False, detach: bool = False):
        self._record("exec_start", exec_id, tty=tty, detach=detach)
        e = self.execs[exec_id]
        c = self.containers[e["container"]]
        cmd = e["config"].get("Cmd", [])
        code, output = self.exec_handler(c, cmd)
        e["exit"] = code
        if detach:
            return None
        stdin, stdout = _Pipe(), _Pipe()
        stdout.write(output)
        stdout.close()
        return FakeStream(stdin, stdout, tty)

    def exec_inspect(self, exec_id: str) -> dict:
        e = self.execs[exec_id]
        return {"ExitCode": e["exit"] if e["exit"] is not None else 0, "Running": False}

    # -------------------------------------------------------------- images

    def image_list(self, *, filters: dict | None = None) -> list[dict]:
        self._record("image_list", filters=filters)
        out = []
        for ref, img in self.images.items():
            if _match_filters(img.get("Labels") or {}, ref, filters):
                out.append({**img, "RepoTags": [ref]})
        return out

    def image_inspect(self, ref: str) -> dict:
        self._record("image_inspect", ref)
        if ref in self.images:
            return self.images[ref]
        for r, img in self.images.items():
            if img["Id"] == ref or img["Id"].startswith("sha256:" + ref):
                return img
        raise NotFoundError(f"No such image: {ref}")

    def image_tag(self, ref: str, repo: str, tag: str) -> None:
        self._record("image_tag", ref, repo, tag)
        img = self.image_inspect(ref)
        self.images[f"{repo}:{tag}"] = {**img}

    def image_remove(self, ref: str, *, force: bool = False) -> None:
        self._record("image_remove", ref, force=force)
        if ref not in self.images:
            raise NotFoundError(f"No such image: {ref}")
        del self.images[ref]

    def image_build(
        self,
        context_tar: bytes,
        *,
        tags: list[str],
        labels: dict[str, str] | None = None,
        dockerfile: str = "Dockerfile",
        buildargs: dict[str, str] | None = None,
        target: str = "",
        pull: bool = False,
        no_cache: bool = False,
    ) -> Iterator[dict]:
        self._record(
            "image_build", tags=tags, labels=labels, dockerfile=dockerfile, no_cache=no_cache
        )
        if self.build_hook:
            self.build_hook(context_tar, tags)
        for t in tags:
            self.add_image(t, labels=labels or {})

        def gen() -> Iterator[dict]:
            yield {"stream": "Step 1/1 : FROM scratch\n"}
            yield {"aux": {"ID": "sha256:" + short_id(32)}}
            yield {"stream": "Successfully built\n"}

        return gen()

    def image_build_buildkit(self, context_tar: bytes, **kw) -> Iterator[dict]:
        """BuildKit lane over the fake daemon: a recorded version=2
        transcript (aux trace records carrying real protobuf bytes) so
        the whole decode path runs in tests."""
        import base64

        from .bkproto import StatusResponse, Vertex, VertexLog, encode_status
        from ..errors import DriverError

        tags = kw.get("tags") or []
        self._record("image_build_buildkit", tags=tags)
        if self.buildkit_refuse:
            raise DriverError("buildkit session required (fake refusal)")
        if self.build_hook:
            self.build_hook(context_tar, tags)
        for t in tags:
            self.add_image(t, labels=kw.get("labels") or {})

        def aux(resp: StatusResponse) -> dict:
            return {"id": "moby.buildkit.trace",
                    "aux": base64.b64encode(encode_status(resp)).decode()}

        def gen() -> Iterator[dict]:
            d1, d2 = "sha256:aaa1", "sha256:bbb2"
            yield aux(StatusResponse(vertexes=[
                Vertex(digest=d1, name="[internal] load build definition",
                       started=1.0)]))
            yield aux(StatusResponse(
                vertexes=[Vertex(digest=d1, name="[internal] load build definition",
                                 started=1.0, completed=1.2),
                          Vertex(digest=d2, name="[1/1] FROM scratch",
                                 started=1.2)],
                logs=[VertexLog(vertex=d2, msg=b"hello from buildkit\n")]))
            yield aux(StatusResponse(vertexes=[
                Vertex(digest=d2, name="[1/1] FROM scratch",
                       started=1.2, completed=2.0)]))
            yield {"aux": {"ID": "sha256:" + short_id(32)}}

        return gen()

    def image_pull(self, ref: str) -> Iterator[dict]:
        self._record("image_pull", ref)
        self.add_image(ref if ":" in ref.rsplit("/", 1)[-1] else ref + ":latest")

        def gen() -> Iterator[dict]:
            yield {"status": f"Pulling from {ref}"}
            yield {"status": "Download complete"}

        return gen()

    # ------------------------------------------------------------- volumes

    def volume_create(self, name: str, labels: dict[str, str] | None = None) -> dict:
        self._record("volume_create", name, labels)
        if name not in self.volumes:
            self.volumes[name] = {"Name": name, "Labels": labels or {}, "Driver": "local"}
        return self.volumes[name]

    def volume_list(self, *, filters: dict | None = None) -> dict:
        self._record("volume_list", filters=filters)
        vols = [
            v for v in self.volumes.values()
            if _match_filters(v.get("Labels") or {}, v["Name"], filters)
        ]
        return {"Volumes": vols, "Warnings": []}

    def volume_inspect(self, name: str) -> dict:
        self._record("volume_inspect", name)
        if name not in self.volumes:
            raise NotFoundError(f"No such volume: {name}")
        return self.volumes[name]

    def volume_remove(self, name: str, *, force: bool = False) -> None:
        self._record("volume_remove", name, force=force)
        if name not in self.volumes:
            if force:
                return
            raise NotFoundError(f"No such volume: {name}")
        del self.volumes[name]

    # ------------------------------------------------------------ networks

    def network_create(self, name: str, config: dict) -> dict:
        self._record("network_create", name, config)
        for n in self.networks.values():
            if n["Name"] == name:
                raise ConflictError(f"network {name} exists")
        nid = short_id(64)
        subnet = "172.28.0.0/16"
        ipam = config.get("IPAM", {}).get("Config") or []
        if ipam and ipam[0].get("Subnet"):
            subnet = ipam[0]["Subnet"]
        self.networks[nid] = {
            "Id": nid,
            "Name": name,
            "Labels": config.get("Labels") or {},
            "IPAM": {"Config": [{"Subnet": subnet}]},
            "Containers": {},
        }
        return {"Id": nid}

    def network_list(self, *, filters: dict | None = None) -> list[dict]:
        self._record("network_list", filters=filters)
        return [
            n for n in self.networks.values()
            if _match_filters(n.get("Labels") or {}, n["Name"], filters)
        ]

    def network_inspect(self, ref: str) -> dict:
        self._record("network_inspect", ref)
        for n in self.networks.values():
            if n["Id"].startswith(ref) or n["Name"] == ref:
                return n
        raise NotFoundError(f"No such network: {ref}")

    def network_remove(self, ref: str) -> None:
        self._record("network_remove", ref)
        n = self.network_inspect(ref)
        del self.networks[n["Id"]]

    def network_connect(self, net: str, cid: str, *, ipv4: str = "") -> None:
        self._record("network_connect", net, cid, ipv4=ipv4)
        n = self.network_inspect(net)
        c = self._find(cid)
        ip = ipv4 or self._next_ip()
        c.networks[n["Name"]] = ip
        n["Containers"][c.id] = {"IPv4Address": ip}

    def network_disconnect(self, net: str, cid: str, *, force: bool = False) -> None:
        self._record("network_disconnect", net, cid)
        n = self.network_inspect(net)
        c = self._find(cid)
        c.networks.pop(n["Name"], None)
        n["Containers"].pop(c.id, None)

    # -------------------------------------------------------------- events

    def events(self, *, filters: dict | None = None) -> Iterator[dict]:
        self._record("events", filters=filters)
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._event_subs.append(q)

        def gen() -> Iterator[dict]:
            try:
                while True:
                    ev = q.get()
                    if ev is None:
                        return
                    if filters and not _event_matches(ev, filters):
                        continue
                    yield ev
            finally:
                with self._lock:
                    if q in self._event_subs:
                        self._event_subs.remove(q)

        return gen()

    def close_events(self) -> None:
        with self._lock:
            for q in self._event_subs:
                q.put(None)

    def pool_stats(self) -> dict:
        """Surface parity with HTTPDockerAPI: no sockets, all zeros."""
        return {"dials": 0, "reuses": 0, "stale_retries": 0,
                "suppressed_retries": 0, "idle": 0}

    def close(self) -> None:
        """Surface parity with HTTPDockerAPI.close (drain-on-shutdown)."""
        self._record("close")
        self.close_events()


def _match_filters(labels: dict[str, str], name: str, filters: dict | None) -> bool:
    if not filters:
        return True
    for want in filters.get("label", []):
        if "=" in want:
            k, v = want.split("=", 1)
            if labels.get(k) != v:
                return False
        elif want not in labels:
            return False
    for want in filters.get("name", []):
        if want not in name:
            return False
    return True


def _event_matches(ev: dict, filters: dict) -> bool:
    if types := filters.get("type"):
        if ev.get("Type") not in types:
            return False
    if wants := filters.get("label"):
        attrs = ev.get("Actor", {}).get("Attributes", {})
        for want in wants:
            if "=" in want:
                k, v = want.split("=", 1)
                if attrs.get(k) != v:
                    return False
            elif want not in attrs:
                return False
    return True
