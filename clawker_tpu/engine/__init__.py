"""Engine layer: the only code that talks to container daemons.

Parity reference: pkg/whail (label-jailed engine over the moby SDK,
pkg/whail/engine.go:32) + internal/docker middleware.  This build collapses
the SDK dependency: ``HTTPDockerAPI`` speaks the Docker Engine HTTP API
directly (unix socket, TCP, or an SSH-forwarded socket on a TPU-VM worker)
over a keep-alive ``ConnectionPool`` (docs/engine-connection-pool.md),
and ``Engine`` enforces the managed-label jail above it.  ``FakeDockerAPI``
is the in-process test seam (reference: pkg/whail/whailtest FakeAPIClient).

Rule carried over from the reference architecture: all daemon calls go
through this package (".claude/docs/ARCHITECTURE.md:833 — All Docker SDK
calls go through pkg/whail").
"""

from .api import Engine
from .httpapi import HTTPDockerAPI
from .pool import ConnectionPool
from .fake import FakeDockerAPI, FakeContainer
from .errors_map import APIError

__all__ = ["Engine", "HTTPDockerAPI", "ConnectionPool", "FakeDockerAPI",
           "FakeContainer", "APIError"]
