"""Keep-alive connection pool for the engine HTTP client.

Every unary Engine-API call used to dial a brand-new socket and close it
after one request.  Over a local ``/var/run/docker.sock`` that is merely
wasteful; over the SSH-forwarded socket of a TPU-VM worker each dial is
a fresh forwarded-stream setup (an extra round trip on the mux), so one
``clawker run`` orchestration paid dozens of avoidable RTTs -- and the
parallel per-worker loop lanes multiply that churn across 8+ threads
sharing an engine endpoint.

:class:`ConnectionPool` keeps a bounded LIFO of idle persistent
connections per endpoint (one pool per :class:`~.httpapi.HTTPDockerAPI`
instance).  Checkout is thread-safe: a connection is owned exclusively
by one request between :meth:`checkout` and :meth:`checkin`, so the
scheduler's per-worker lanes never interleave bytes on a socket.
Streams, ``/events`` and hijacked attach/exec connections use
:meth:`dedicated` sockets that are never pooled.

Telemetry: dials, reuses and stale retries are counted
(:meth:`stats`), and each dial rides the ``util/phases`` stopwatch
under ``engine.dial`` so bench.py's cold-start attribution can say how
many sockets a run opened and what the dialing cost.
"""

from __future__ import annotations

import http.client
import socket
import threading
import time
from typing import Callable

from ..util import phases
from .. import telemetry

SocketFactory = Callable[[], socket.socket]

# Registry metrics (telemetry subsystem): one process-wide family each,
# shared by every pool instance -- a process talks to one fleet.
_DIALS = telemetry.counter(
    "engine_dials_total", "Engine-API socket dials")
_REUSES = telemetry.counter(
    "engine_reuses_total", "Engine-API pooled-connection reuses")
_STALE_RETRIES = telemetry.counter(
    "engine_stale_retries_total",
    "Unary requests retried on a fresh dial after a reaped idle socket")
_SUPPRESSED_RETRIES = telemetry.counter(
    "engine_retries_suppressed_total",
    "Stale-socket retries suppressed because the verb is not idempotent")

# Sized for the loop scheduler's fan-out: 8 per-worker lanes plus the
# event feeder can share one endpoint without churning sockets.
DEFAULT_MAX_IDLE = 8
# The docker daemon reaps idle keep-alive connections after ~5 minutes;
# reap ours first so a checkout rarely hands back a socket the daemon
# already closed (the stale-retry path covers the race when it does).
DEFAULT_IDLE_TTL_S = 60.0


class _SockConnection(http.client.HTTPConnection):
    """HTTPConnection over an arbitrary pre-dialed socket."""

    def __init__(self, factory: SocketFactory,
                 on_dial: Callable[[], None] | None = None):
        super().__init__("localhost")
        self._factory = factory
        self._on_dial = on_dial
        self.idle_since = 0.0  # set by ConnectionPool.checkin

    def connect(self) -> None:  # type: ignore[override]
        with phases.phase("engine.dial"):
            self.sock = self._factory()
        if self._on_dial is not None:
            self._on_dial()


def _close_quietly(conn: http.client.HTTPConnection) -> None:
    try:
        conn.close()
    except Exception:
        pass


class ConnectionPool:
    """Bounded, thread-safe pool of idle keep-alive daemon connections.

    ``max_idle=0`` disables pooling entirely (every checkout dials
    fresh) -- the pre-pool behavior, kept reachable for the bench's
    dial-per-request baseline.
    """

    def __init__(self, factory: SocketFactory, *,
                 max_idle: int = DEFAULT_MAX_IDLE,
                 idle_ttl: float = DEFAULT_IDLE_TTL_S):
        self._factory = factory
        self.max_idle = max_idle
        self.idle_ttl = idle_ttl
        self._idle: list[_SockConnection] = []
        self._lock = threading.Lock()
        self._closed = False
        self._dials = 0
        self._reuses = 0
        self._stale_retries = 0
        self._suppressed_retries = 0

    # ---------------------------------------------------------- lifecycle

    def _count_dial(self) -> None:
        with self._lock:
            self._dials += 1
        _DIALS.inc()

    def _new(self) -> _SockConnection:
        return _SockConnection(self._factory, on_dial=self._count_dial)

    def checkout(self) -> tuple[_SockConnection, bool]:
        """-> (connection, reused).  Reaps idle connections past the TTL;
        the returned connection is exclusively owned until checkin."""
        now = time.monotonic()
        reaped: list[_SockConnection] = []
        conn: _SockConnection | None = None
        with self._lock:
            while self._idle:
                c = self._idle.pop()  # LIFO: warmest socket first
                if c.sock is None or now - c.idle_since > self.idle_ttl:
                    reaped.append(c)
                    continue
                self._reuses += 1
                conn = c
                break
        if conn is not None:
            _REUSES.inc()
        for c in reaped:
            _close_quietly(c)
        if conn is not None:
            return conn, True
        return self._new(), False

    def fresh(self) -> _SockConnection:
        """A guaranteed-fresh-dial connection (the stale-retry path must
        not be handed a second possibly-reaped idle socket)."""
        return self._new()

    def dedicated(self, *, unbounded: bool = True) -> _SockConnection:
        """Dial a connection that will never be pooled (streams, hijacks,
        ``/events``).  Dials eagerly so the factory's read timeout can be
        cleared: long-lived streams legitimately sit silent for hours."""
        conn = self._new()
        conn.connect()
        if unbounded and conn.sock is not None:
            conn.sock.settimeout(None)
        return conn

    def checkin(self, conn: _SockConnection) -> None:
        """Return a connection whose response was fully read.  Dropped
        (closed) when the pool is full, closed, or the socket died."""
        if conn.sock is None:
            return
        drop: _SockConnection | None = None
        with self._lock:
            if self._closed or len(self._idle) >= self.max_idle:
                drop = conn
            else:
                conn.idle_since = time.monotonic()
                self._idle.append(conn)
        if drop is not None:
            _close_quietly(drop)

    def note_stale_retry(self) -> None:
        with self._lock:
            self._stale_retries += 1
        _STALE_RETRIES.inc()

    def note_suppressed_retry(self) -> None:
        """A reused socket died before the status line under a
        NON-idempotent verb: the retry the idempotent path would take
        was suppressed (httpapi's allowlist) and the failure surfaced."""
        with self._lock:
            self._suppressed_retries += 1
        _SUPPRESSED_RETRIES.inc()

    # ---------------------------------------------------------- accessors

    def stats(self) -> dict:
        with self._lock:
            return {
                "dials": self._dials,
                "reuses": self._reuses,
                "stale_retries": self._stale_retries,
                "suppressed_retries": self._suppressed_retries,
                "idle": len(self._idle),
            }

    def close(self) -> None:
        """Drain-on-shutdown: close every idle connection.  Later
        checkouts still work (fresh dials); later checkins are dropped."""
        with self._lock:
            drain, self._idle = self._idle, []
            self._closed = True
        for c in drain:
            _close_quietly(c)
