"""Minimal protobuf wire codec for the BuildKit build trace.

With ``/build?version=2`` the daemon streams progress as JSON records
whose ``aux`` payload (under ``id: "moby.buildkit.trace"``) is a
base64-encoded protobuf ``StatusResponse``.  This module decodes exactly
that message family -- and encodes it, for recorded-transcript tests and
the fake daemon -- with a tiny generic wire-format codec instead of a
generated stub (no protoc dependency, and the message set is small and
frozen).

Message shapes (moby/buildkit api/services/control/control.proto):
  StatusResponse { Vertex vertexes=1; VertexStatus statuses=2;
                   VertexLog logs=3; }
  Vertex       { string digest=1; string inputs=2; string name=3;
                 bool cached=4; Timestamp started=5; Timestamp
                 completed=6; string error=7; }
  VertexStatus { string id=1; string vertex=2; string name=3;
                 int64 current=4; int64 total=5; }
  VertexLog    { string vertex=1; Timestamp timestamp=2;
                 int64 stream=3; bytes msg=4; }

Parity reference: pkg/whail/buildkit/progress.go (trace decoding into
vertex events) -- re-derived against the public BuildKit proto, not
translated.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class WireError(ValueError):
    pass


# ------------------------------------------------------------ wire codec

def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        if i >= len(buf):
            raise WireError("truncated varint")
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7
        if shift > 63:
            raise WireError("varint too long")


def parse_fields(buf: bytes) -> dict[int, list]:
    """Generic wire parse: field number -> list of raw values (int for
    varint, bytes for length-delimited).  Unknown wire types error."""
    out: dict[int, list] = {}
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:           # varint
            val, i = _read_varint(buf, i)
        elif wt == 2:         # length-delimited
            ln, i = _read_varint(buf, i)
            if i + ln > len(buf):
                raise WireError(f"field {fno}: truncated bytes")
            val = buf[i:i + ln]
            i += ln
        elif wt == 1:         # fixed64 (not used by this message set)
            if i + 8 > len(buf):
                raise WireError(f"field {fno}: truncated fixed64")
            val = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 5:         # fixed32
            if i + 4 > len(buf):
                raise WireError(f"field {fno}: truncated fixed32")
            val = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:
            raise WireError(f"unsupported wire type {wt} for field {fno}")
        out.setdefault(fno, []).append(val)
    return out


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def emit_field(fno: int, val) -> bytes:
    """Encode one field (int -> varint, bytes/str -> length-delimited)."""
    if isinstance(val, int):
        return _varint(fno << 3) + _varint(val)
    raw = val.encode() if isinstance(val, str) else bytes(val)
    return _varint((fno << 3) | 2) + _varint(len(raw)) + raw


# --------------------------------------------------------- typed decode

def _ts_seconds(raw: bytes) -> float:
    f = parse_fields(raw)
    return (f.get(1, [0])[0]) + (f.get(2, [0])[0]) / 1e9


@dataclass
class Vertex:
    digest: str = ""
    name: str = ""
    inputs: list[str] = field(default_factory=list)
    cached: bool = False
    started: float | None = None
    completed: float | None = None
    error: str = ""


@dataclass
class VertexStatus:
    id: str = ""
    vertex: str = ""
    current: int = 0
    total: int = 0


@dataclass
class VertexLog:
    vertex: str = ""
    stream: int = 1
    msg: bytes = b""


@dataclass
class StatusResponse:
    vertexes: list[Vertex] = field(default_factory=list)
    statuses: list[VertexStatus] = field(default_factory=list)
    logs: list[VertexLog] = field(default_factory=list)


def decode_status(buf: bytes) -> StatusResponse:
    top = parse_fields(buf)
    out = StatusResponse()
    for raw in top.get(1, []):
        f = parse_fields(raw)
        out.vertexes.append(Vertex(
            digest=f.get(1, [b""])[0].decode("utf-8", "replace"),
            inputs=[x.decode("utf-8", "replace") for x in f.get(2, [])],
            name=f.get(3, [b""])[0].decode("utf-8", "replace"),
            cached=bool(f.get(4, [0])[0]),
            started=_ts_seconds(f[5][0]) if 5 in f else None,
            completed=_ts_seconds(f[6][0]) if 6 in f else None,
            error=f.get(7, [b""])[0].decode("utf-8", "replace"),
        ))
    for raw in top.get(2, []):
        f = parse_fields(raw)
        out.statuses.append(VertexStatus(
            id=f.get(1, [b""])[0].decode("utf-8", "replace"),
            vertex=f.get(2, [b""])[0].decode("utf-8", "replace"),
            current=f.get(4, [0])[0],
            total=f.get(5, [0])[0],
        ))
    for raw in top.get(3, []):
        f = parse_fields(raw)
        out.logs.append(VertexLog(
            vertex=f.get(1, [b""])[0].decode("utf-8", "replace"),
            stream=f.get(3, [1])[0],
            msg=f.get(4, [b""])[0],
        ))
    return out


# --------------------------------------------------------- typed encode
# Used by tests and the fake daemon to produce recorded transcripts.

def _encode_ts(seconds: float) -> bytes:
    s = int(seconds)
    n = int((seconds - s) * 1e9)
    body = emit_field(1, s)
    if n:
        body += emit_field(2, n)
    return body


def encode_status(resp: StatusResponse) -> bytes:
    out = b""
    for v in resp.vertexes:
        body = emit_field(1, v.digest)
        for inp in v.inputs:
            body += emit_field(2, inp)
        body += emit_field(3, v.name)
        if v.cached:
            body += emit_field(4, 1)
        if v.started is not None:
            body += emit_field(5, _encode_ts(v.started))
        if v.completed is not None:
            body += emit_field(6, _encode_ts(v.completed))
        if v.error:
            body += emit_field(7, v.error)
        out += emit_field(1, body)
    for st in resp.statuses:
        body = emit_field(1, st.id) + emit_field(2, st.vertex)
        body += emit_field(4, st.current) + emit_field(5, st.total)
        out += emit_field(2, body)
    for lg in resp.logs:
        body = emit_field(1, lg.vertex) + emit_field(3, lg.stream)
        body += emit_field(4, lg.msg)
        out += emit_field(3, body)
    return out
