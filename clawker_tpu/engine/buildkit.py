"""BuildKit build lane: capability probe, trace decoding, legacy fallback.

The daemon advertises its default builder in ``/info`` (BuilderVersion
"2" = BuildKit).  On the BuildKit lane the progress stream carries
``aux`` records (``id: "moby.buildkit.trace"``) holding base64 protobuf
StatusResponses; this module decodes them (engine/bkproto.py) and
normalizes everything into the classic event dialect the bundler and
``ui/buildview.py`` already consume:

- ``{"stream": "#N <name>"}`` / ``"#N DONE <secs>s"`` / ``"#N CACHED"``
  / ``"#N ERROR <msg>"`` -- the plain-progress vertex lines buildview's
  ``_BK_VERTEX`` regex renders as tree nodes;
- ``{"stream": <log bytes>}`` for vertex logs;
- ``{"errorDetail": {"message": ...}}`` on failure.

If the daemon rejects the BuildKit request (older daemon, missing
session support), the builder transparently retries on the legacy
``/build`` lane -- capability probe + fallback, reference
pkg/whail/buildkit/{builder,solve,progress}.go semantics re-derived.
"""

from __future__ import annotations

import base64
from typing import Iterator

from .. import logsetup
from .bkproto import StatusResponse, WireError, decode_status
from ..errors import DriverError

log = logsetup.get("engine.buildkit")

TRACE_ID = "moby.buildkit.trace"


def builder_version(api) -> str:
    """Capability probe: "2" when the daemon defaults to BuildKit."""
    try:
        return str(api.info().get("BuilderVersion") or "1")
    except (DriverError, AttributeError):
        return "1"


class TraceRenderer:
    """Decode trace StatusResponses into plain-progress vertex lines.

    Vertices are numbered in first-seen order (#1, #2, ...) the way the
    docker CLI's plain progress does, so downstream consumers key on a
    stable small integer instead of a digest."""

    def __init__(self):
        self._num: dict[str, int] = {}
        self._done: set[str] = set()
        self._started: dict[str, float] = {}

    def _n(self, digest: str) -> int:
        if digest not in self._num:
            self._num[digest] = len(self._num) + 1
        return self._num[digest]

    def render(self, resp: StatusResponse) -> Iterator[dict]:
        for v in resp.vertexes:
            n = self._n(v.digest)
            if v.started is not None and v.digest not in self._started:
                self._started[v.digest] = v.started
                yield {"stream": f"#{n} {v.name}\n"}
            if v.error:
                if v.digest not in self._done:
                    self._done.add(v.digest)
                    yield {"stream": f"#{n} ERROR {v.error}\n"}
                continue
            if v.cached and v.digest not in self._done:
                self._done.add(v.digest)
                if v.digest not in self._started:
                    yield {"stream": f"#{n} {v.name}\n"}
                yield {"stream": f"#{n} CACHED\n"}
                continue
            if v.completed is not None and v.digest not in self._done:
                self._done.add(v.digest)
                took = v.completed - (v.started or v.completed)
                yield {"stream": f"#{n} DONE {took:.1f}s\n"}
        for st in resp.statuses:
            n = self._n(st.vertex)
            if st.total:
                yield {"stream": f"#{n} {st.id} {st.current}/{st.total}\n"}
        for lg in resp.logs:
            n = self._n(lg.vertex)
            text = lg.msg.decode("utf-8", "replace").rstrip("\n")
            for line in text.splitlines():
                yield {"stream": f"#{n} {line}\n"}


def decode_stream(raw_events: Iterator[dict]) -> Iterator[dict]:
    """Normalize a version=2 progress stream: trace aux records become
    vertex lines; classic records pass through untouched."""
    renderer = TraceRenderer()
    for ev in raw_events:
        if ev.get("id") == TRACE_ID and "aux" in ev:
            try:
                resp = decode_status(base64.b64decode(ev["aux"]))
            except (WireError, ValueError, TypeError, AttributeError) as e:
                # type-confused wire data (e.g. a message field arriving
                # as varint) must degrade to a skipped record, never
                # abort the whole build stream
                log.warning("buildkit trace decode failed: %s", e)
                continue
            for out in renderer.render(resp):
                if out.get("stream"):
                    yield out
        else:
            yield ev


class Builder:
    """The build front door: probe once, prefer BuildKit, fall back."""

    def __init__(self, api):
        self.api = api
        self._version: str | None = None
        self.last_buildid = ""  # cancel handle for the in-flight solve

    def version(self) -> str:
        if self._version is None:
            self._version = builder_version(self.api)
        return self._version

    def build(self, context_tar: bytes, *,
              secrets: dict[str, bytes] | None = None,
              ssh_auth_sock: str = "", **kw) -> Iterator[dict]:
        """Build, preferring the BuildKit lane.

        ``secrets`` / ``ssh_auth_sock`` require the SESSION lane
        (`RUN --mount=type=secret|ssh`): a client session is attached via
        /session (engine/bksession) and the daemon dials back into it for
        secret bytes and agent round-trips during the solve.  Without
        them the plain version=2 lane is used; the legacy /build lane
        remains the capability fallback either way (a build that NEEDS a
        session fails loudly on daemons that cannot provide one).
        """
        wants_session = bool(secrets) or bool(ssh_auth_sock)
        if wants_session and not hasattr(self.api, "session_attach"):
            raise DriverError(
                "build needs secrets/ssh mounts, but this daemon API has "
                "no /session lane")
        if self.version() == "2" and hasattr(self.api, "image_build_buildkit"):
            import uuid

            self.last_buildid = uuid.uuid4().hex
            session = None
            extra: dict = {}
            try:
                if wants_session:
                    from .bksession import Session, SessionServices

                    session = Session(SessionServices(
                        secrets=secrets, ssh_auth_sock=ssh_auth_sock))
                    session.attach(self.api.session_attach(
                        session.headers(), session.method_headers()))
                    extra["session"] = session.session_id
                raw = self.api.image_build_buildkit(
                    context_tar, buildid=self.last_buildid, **extra, **kw)
                return self._stream_with_session(raw, session)
            except DriverError as e:
                if session is not None:
                    session.close()
                if wants_session:
                    raise  # secret/ssh builds must not silently downgrade
                # daemon advertised BuildKit but refused the request
                # (e.g. session required): fall back AND remember -- the
                # context tar is uploaded eagerly, so retrying the doomed
                # lane would double-upload every subsequent build
                log.warning("buildkit lane refused (%s); legacy fallback", e)
                self._version = "1"
                self.last_buildid = ""
            except BaseException:
                # any other failure (transient socket error, attach
                # crash): the loopback gRPC server and pumps must not
                # outlive the attempt
                if session is not None:
                    session.close()
                raise
        if wants_session:
            raise DriverError(
                "build needs secrets/ssh mounts, which require the BuildKit "
                "session lane; this daemon only offers the legacy builder")
        return self.api.image_build(context_tar, **kw)

    @staticmethod
    def _stream_with_session(raw: Iterator[dict], session) -> Iterator[dict]:
        """Decode the progress stream; the session lives until it ends."""
        try:
            yield from decode_stream(raw)
        finally:
            if session is not None:
                session.close()

    def cancel(self) -> None:
        """Cancel the in-flight BuildKit solve (no-op on the legacy lane)."""
        if self.last_buildid and hasattr(self.api, "build_cancel"):
            self.api.build_cancel(self.last_buildid)
