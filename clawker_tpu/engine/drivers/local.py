"""Local Docker daemon driver (driver 0; the reference's only backend)."""

from __future__ import annotations

import os
from pathlib import Path

from ...errors import DriverError
from ..api import Engine
from ..httpapi import HTTPDockerAPI, tcp_socket_factory, unix_socket_factory
from .base import RuntimeDriver, Worker

DEFAULT_SOCKET = "/var/run/docker.sock"


class LocalDriver(RuntimeDriver):
    name = "local"

    def __init__(self, docker_host: str = ""):
        self._docker_host = docker_host or os.environ.get("DOCKER_HOST", "")
        self._workers: list[Worker] | None = None

    def _api(self) -> HTTPDockerAPI:
        host = self._docker_host
        if not host or host.startswith("unix://") or host.startswith("/"):
            path = host.removeprefix("unix://") if host else DEFAULT_SOCKET
            if not Path(path).exists():
                raise DriverError(
                    f"Docker socket {path} not found -- is the Docker daemon running?"
                )
            return HTTPDockerAPI(unix_socket_factory(path))
        if host.startswith("tcp://"):
            hostport = host.removeprefix("tcp://")
            h, _, p = hostport.partition(":")
            return HTTPDockerAPI(tcp_socket_factory(h, int(p or "2375")))
        raise DriverError(f"unsupported DOCKER_HOST {host!r}")

    def connect(self) -> list[Worker]:
        engine = Engine(self._api())
        if not engine.ping():
            raise DriverError("local Docker daemon did not answer ping")
        self._workers = [Worker(id="local-0", index=0, hostname="localhost", engine=engine)]
        return self._workers

    def workers(self) -> list[Worker]:
        if self._workers is None:
            return self.connect()
        return self._workers

    def close(self) -> None:
        """Drain each worker engine's keep-alive pool; a later workers()
        call reconnects from scratch."""
        for w in self._workers or []:
            if w.engine is not None:
                w.engine.close()
        self._workers = None
