"""RuntimeDriver seam: pluggable container backends."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from ...errors import ConfigError, DriverError
from ..api import Engine

if TYPE_CHECKING:
    from ...config.schema import Settings


@dataclass
class Worker:
    """One daemon endpoint (a host that can run agent containers).

    For the local driver there is exactly one.  For ``tpu_vm`` there is one
    per TPU-VM worker; ``index`` is the TPU worker index (used for
    topology-aware placement by the loop scheduler) and ``hostname`` the
    SSH target.
    """

    id: str
    index: int = 0
    hostname: str = "localhost"
    engine: Engine | None = None
    meta: dict = field(default_factory=dict)

    def require_engine(self) -> Engine:
        if self.engine is None:
            why = self.meta.get("dial_error", "")
            raise DriverError(f"worker {self.id}: engine not connected"
                              + (f" ({why})" if why else ""))
        return self.engine


class RuntimeDriver:
    """Abstract driver: a named set of workers with engines.

    Subclasses implement :meth:`connect` (build Worker list with live
    engines) plus any transport-specific provisioning.
    """

    name = "abstract"
    # do this driver's containers have real cgroup dirs on THIS host?
    # (gates kernel-enforcement lanes; the fake driver says no)
    real_cgroups = True

    def connect(self) -> list[Worker]:
        raise NotImplementedError

    def workers(self) -> list[Worker]:
        raise NotImplementedError

    def default_worker(self) -> Worker:
        ws = self.workers()
        if not ws:
            raise DriverError(f"driver {self.name}: no workers available")
        return ws[0]

    def engine(self) -> Engine:
        """Engine of the default worker (single-daemon callers)."""
        return self.default_worker().require_engine()

    def probe(self, worker: Worker) -> None:
        """One lightweight health round-trip against the worker's engine;
        raises on any failure.  ``ping`` proves the daemon answers at
        all, the label-jailed ``list_containers`` proves it can serve a
        real (filtered) query -- the pair is what the scheduler's control
        plane actually depends on.  Deadline enforcement is the caller's
        job: health.monitor runs probes under a hard per-attempt deadline
        so a wedged engine call reads as a failure, not a stall.
        """
        engine = worker.require_engine()
        if not engine.ping():
            raise DriverError(f"worker {worker.id}: engine ping failed")
        engine.list_containers(all=False)

    def diagnose(self, worker: Worker) -> str:
        """Best-effort one-liner on WHY a probe is failing, consulted by
        the health monitor when a probe overruns its deadline (the probe
        itself never got to say).  Must be cheap and bounded; empty
        string = nothing to add."""
        return ""

    def close(self) -> None:
        pass


def _seeded_fake_driver() -> "RuntimeDriver":
    """Fake driver seeded from the environment, so the real CLI can be driven
    end-to-end from a shell with no Docker daemon.

    ``CLAWKER_TPU_FAKE_IMAGES`` -- comma-separated image refs to pre-load.
    Seeded images run an exit(0) behavior that prints one line, so an
    attached ``run`` streams output and terminates instead of idling.
    """
    import os

    from .fakedriver import FakeDriver

    drv = FakeDriver()
    refs = [r.strip() for r in os.environ.get("CLAWKER_TPU_FAKE_IMAGES", "").split(",") if r.strip()]
    if refs:
        from ..fake import exit_behavior

        for api in drv.apis:
            for ref in refs:
                api.add_image(ref)
                api.set_behavior(ref, exit_behavior(b"fake agent ran\r\n", 0))
    return drv


def get_driver(settings: "Settings", *, override: str = "") -> RuntimeDriver:
    """Driver factory from settings.runtime.driver (or explicit override)."""
    from .fakedriver import FakeDriver
    from .local import LocalDriver

    name = override or settings.runtime.driver
    if name == "local":
        return LocalDriver(docker_host=settings.runtime.docker_host)
    if name == "fake":
        return _seeded_fake_driver()
    if name == "tpu_vm":
        from .tpu_vm import TPUVMDriver

        return TPUVMDriver(settings.runtime.tpu)
    if name == "nsd":
        from .nsdriver import NsdDriver

        return NsdDriver(docker_host=settings.runtime.docker_host)
    raise ConfigError(
        f"unknown runtime driver {name!r} (expected local|tpu_vm|nsd|fake)")
