"""Runtime drivers: where the compute backend becomes pluggable.

This seam is the core TPU-first design decision (SURVEY.md 7: "keep the
architecture, make the compute backend pluggable").  The reference hard-codes
one local Docker daemon; here every daemon lives behind a
:class:`RuntimeDriver` exposing one or more :class:`Worker` endpoints:

* ``local``  -- the laptop's Docker daemon (1 worker)
* ``tpu_vm`` -- every worker VM of a Cloud TPU pod, each running its own
  daemon reached over an SSH-forwarded socket (N workers)
* ``fake``   -- in-process fake daemons for tests (N workers)
"""

from .base import RuntimeDriver, Worker, get_driver
from .local import LocalDriver
from .fakedriver import FakeDriver

__all__ = ["RuntimeDriver", "Worker", "LocalDriver", "FakeDriver", "get_driver"]
