"""nsd runtime driver: the first-party namespace daemon as a backend.

`settings: runtime.driver: nsd` (or CLAWKER_TPU_DRIVER=nsd) points the
stock Docker-API client at a clawker_tpu.nsd daemon, auto-spawning one
on this host when none answers.  Everything above the socket -- engine
jail, orchestration, firewall enrollment -- is byte-identical to the
``local`` driver; only the daemon behind the socket changes.

Requires root (see nsd package docstring); intended for e2e tiers and
TPU-VM workers without Docker.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path

from ...errors import DriverError
from .local import LocalDriver

DEFAULT_SOCKET = "/run/clawker/nsd.sock"
ENV_SOCKET = "CLAWKER_TPU_NSD_SOCKET"
ENV_STATE = "CLAWKER_TPU_NSD_STATE"


def nsd_capable() -> bool:
    """Root + the kernel facilities nsd needs (cgroup-v2 checked by the
    daemon itself; unshare/overlay are the hard requirements)."""
    if os.name != "posix" or os.geteuid() != 0:
        return False
    from shutil import which

    return bool(which("unshare") and which("nsenter") and which("mount"))


class NsdDriver(LocalDriver):
    name = "nsd"

    def __init__(self, docker_host: str = ""):
        sock = (docker_host.removeprefix("unix://") if docker_host
                else os.environ.get(ENV_SOCKET, DEFAULT_SOCKET))
        self._sock_path = Path(sock)
        self._proc: subprocess.Popen | None = None
        super().__init__(docker_host=f"unix://{sock}")

    def connect(self):
        if not self._answers():
            self._spawn()
        return super().connect()

    def _answers(self) -> bool:
        if not self._sock_path.exists():
            return False
        try:
            return self._api_unchecked().ping()
        except DriverError:
            return False

    def _api_unchecked(self):
        from ..httpapi import HTTPDockerAPI, unix_socket_factory

        return HTTPDockerAPI(unix_socket_factory(self._sock_path))

    def _spawn(self) -> None:
        if not nsd_capable():
            raise DriverError(
                "nsd driver needs root + unshare/nsenter (namespace runtime)")
        state = os.environ.get(
            ENV_STATE, str(self._sock_path.parent / "nsd-state"))
        self._sock_path.parent.mkdir(parents=True, exist_ok=True)
        log = open(self._sock_path.parent / "nsd.log", "ab")
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "clawker_tpu.nsd",
             "--socket", str(self._sock_path), "--state-dir", state],
            stdout=log, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL, start_new_session=True,
            env={**os.environ,
                 "PYTHONPATH": str(Path(__file__).resolve().parents[3])},
        )
        log.close()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if self._answers():
                return
            time.sleep(0.05)
        raise DriverError(f"nsd daemon did not answer on {self._sock_path}")
