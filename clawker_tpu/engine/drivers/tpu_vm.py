"""Cloud TPU-VM runtime driver: every pod worker is a daemon endpoint.

Provisions and attaches to Docker daemons on the worker VMs of a TPU pod
over SSH (BASELINE.json north_star).  Worker order follows pod order
(inventory index = TPU worker index), which the loop scheduler uses for
topology-aware placement.  Engines ride SSH-forwarded docker sockets
(fleet/transport.py), so the whole jailed-engine stack works unchanged
against remote daemons.
"""

from __future__ import annotations

from ...config.schema import TPUSettings
from ...errors import DriverError
from .base import RuntimeDriver, Worker


class TPUVMDriver(RuntimeDriver):
    name = "tpu_vm"

    def __init__(self, tpu: TPUSettings, *, runner=None):
        self.tpu = tpu
        self.runner = runner          # fleet.transport.Runner seam (tests)
        self._workers: list[Worker] | None = None

    def hosts(self) -> list[str]:
        from ...fleet.inventory import discover_workers

        hosts = discover_workers(self.tpu)
        if not hosts:
            raise DriverError(
                f"tpu_vm: no workers found for pod {self.tpu.pod!r} "
                "(set runtime.tpu.workers or runtime.tpu.pod in settings.yaml)"
            )
        return hosts

    def connect(self) -> list[Worker]:
        from concurrent.futures import ThreadPoolExecutor

        from ...fleet.transport import connect_worker_engine

        hosts = self.hosts()

        def dial(args):
            i, host = args
            return Worker(
                id=f"tpu-{i}", index=i, hostname=host,
                engine=connect_worker_engine(self.tpu, host, i, runner=self.runner),
            )

        # dial workers concurrently: 8 serial SSH handshakes would eat the
        # whole <10s cold-start budget on a v5e-8
        with ThreadPoolExecutor(max_workers=min(16, len(hosts))) as pool:
            self._workers = list(pool.map(dial, enumerate(hosts)))
        return self._workers

    def workers(self) -> list[Worker]:
        if self._workers is None:
            return self.connect()
        return self._workers

    def close(self) -> None:
        for w in self._workers or []:
            if w.engine is not None:
                # drain pooled keep-alive sockets while the forward is
                # still up, then tear down the ssh -N forward itself
                w.engine.close()
            transport = getattr(w.engine, "transport", None)
            if transport is not None:
                transport.close()
        self._workers = None
