"""Cloud TPU-VM runtime driver: every pod worker is a daemon endpoint.

Provisions and attaches to Docker daemons on the worker VMs of a TPU pod
over SSH (BASELINE.json north_star).  Worker order follows pod order
(inventory index = TPU worker index), which the loop scheduler uses for
topology-aware placement.  Engines ride SSH-forwarded docker sockets
(fleet/transport.py), so the whole jailed-engine stack works unchanged
against remote daemons.
"""

from __future__ import annotations

from ... import logsetup
from ...config.schema import TPUSettings
from ...errors import DriverError
from .base import RuntimeDriver, Worker

log = logsetup.get("drivers.tpu_vm")


class TPUVMDriver(RuntimeDriver):
    name = "tpu_vm"

    def __init__(self, tpu: TPUSettings, *, runner=None):
        self.tpu = tpu
        self.runner = runner          # fleet.transport.Runner seam (tests)
        self._workers: list[Worker] | None = None

    def hosts(self) -> list[str]:
        from ...fleet.inventory import discover_workers

        hosts = discover_workers(self.tpu)
        if not hosts:
            raise DriverError(
                f"tpu_vm: no workers found for pod {self.tpu.pod!r} "
                "(set runtime.tpu.workers or runtime.tpu.pod in settings.yaml)"
            )
        return hosts

    def connect(self) -> list[Worker]:
        from concurrent.futures import ThreadPoolExecutor

        from ...fleet import transport as fleet_transport

        hosts = self.hosts()

        def dial(args):
            i, host = args
            try:
                engine = fleet_transport.connect_worker_engine(
                    self.tpu, host, i, runner=self.runner)
            except Exception as e:      # noqa: BLE001 -- any dial failure
                # machine failure is the common case, not the exception:
                # a worker that won't dial joins the fleet engine-less
                # (its health breaker opens on the first probe, failover
                # routes around it) instead of killing the whole connect
                log.warning("worker %d (%s): dial failed: %s", i, host, e)
                return Worker(id=f"tpu-{i}", index=i, hostname=host,
                              meta={"dial_error": str(e)})
            return Worker(id=f"tpu-{i}", index=i, hostname=host,
                          engine=engine)

        # dial workers concurrently: 8 serial SSH handshakes would eat the
        # whole <10s cold-start budget on a v5e-8
        with ThreadPoolExecutor(max_workers=min(16, len(hosts))) as pool:
            self._workers = list(pool.map(dial, enumerate(hosts)))
        if all(w.engine is None for w in self._workers):
            raise DriverError(
                "tpu_vm: no worker could be dialed ("
                + "; ".join(f"{w.id}: {w.meta.get('dial_error', '?')}"
                            for w in self._workers) + ")")
        return self._workers

    def workers(self) -> list[Worker]:
        if self._workers is None:
            return self.connect()
        return self._workers

    def diagnose(self, worker: Worker) -> str:
        """Deadline-exceeded probes never reach probe()'s ssh follow-up
        (the attempt thread is still stuck in the engine call), so the
        monitor asks separately: is the HOST at least alive?"""
        transport = getattr(worker.engine, "transport", None)
        if transport is None:
            return ""
        try:
            rtt = transport.probe(timeout=2.0)
        except DriverError:
            return "host unreachable over ssh"
        return f"host ssh alive ({rtt * 1000:.0f}ms rtt); daemon hung?"

    def probe(self, worker: Worker) -> None:
        """Engine probe, with an SSH-level follow-up on failure: a dead
        forwarded daemon behind a live host and a dead VM are different
        operator problems (restart dockerd vs recreate the worker), so
        the failure detail says which one this is."""
        try:
            super().probe(worker)
        except DriverError as engine_err:
            transport = getattr(worker.engine, "transport", None)
            if transport is None:
                raise
            try:
                rtt = transport.probe()
            except DriverError:
                raise DriverError(
                    f"worker {worker.id}: host unreachable over ssh "
                    f"(engine: {engine_err})") from engine_err
            raise DriverError(
                f"worker {worker.id}: docker daemon unreachable but host "
                f"ssh alive ({rtt * 1000:.0f}ms rtt; engine: {engine_err})"
            ) from engine_err

    def close(self) -> None:
        for w in self._workers or []:
            if w.engine is not None:
                # drain pooled keep-alive sockets while the forward is
                # still up, then tear down the ssh -N forward itself
                w.engine.close()
            transport = getattr(w.engine, "transport", None)
            if transport is not None:
                transport.close()
        self._workers = None
