"""Cloud TPU-VM runtime driver (skeleton; full transport in fleet/ + ssh).

Provisions and attaches to Docker daemons on every worker VM of a TPU pod
over SSH (BASELINE.json north_star).  The full implementation lands with the
fleet subsystem; this module keeps the driver factory importable.
"""

from __future__ import annotations

from ...config.schema import TPUSettings
from ...errors import DriverError
from .base import RuntimeDriver, Worker


class TPUVMDriver(RuntimeDriver):
    name = "tpu_vm"

    def __init__(self, tpu: TPUSettings):
        self.tpu = tpu
        self._workers: list[Worker] | None = None

    def connect(self) -> list[Worker]:
        from ...fleet.inventory import discover_workers
        from ...fleet.transport import connect_worker_engine

        hosts = discover_workers(self.tpu)
        if not hosts:
            raise DriverError(
                f"tpu_vm: no workers found for pod {self.tpu.pod!r} "
                "(set runtime.tpu.workers or runtime.tpu.pod in settings.yaml)"
            )
        self._workers = []
        for i, host in enumerate(hosts):
            engine = connect_worker_engine(self.tpu, host, i)
            self._workers.append(
                Worker(id=f"tpu-{i}", index=i, hostname=host, engine=engine)
            )
        return self._workers

    def workers(self) -> list[Worker]:
        if self._workers is None:
            return self.connect()
        return self._workers
