"""Fake driver: N in-process fake daemons (test seam for multi-worker paths).

Per-worker fault injection rides a :class:`_FaultGate` between each
worker's ``Engine`` and its ``FakeDockerAPI``: tests (and the failover
bench) kill, wedge, or flap one worker's daemon without touching the
fake's semantic state, then revive it -- the seam the health subsystem's
probes, breakers, and the scheduler's migration path are tested through.
"""

from __future__ import annotations

import threading
import time

from ...errors import DriverError
from ..api import Engine
from ..fake import FakeDockerAPI
from .base import RuntimeDriver, Worker

# a wedged call must eventually die even if the test forgets to revive
# the worker (daemon threads would otherwise pile up across a session)
WEDGE_ABANDON_S = 60.0

FAULT_KINDS = ("refuse", "wedge", "flap", "slow", "burst", "probe_drop")


class _FaultGate:
    """Injectable fault seam in front of one worker's FakeDockerAPI.

    - ``refuse``: every call raises DriverError immediately (dial
      refusal: daemon process gone, socket forward torn down).
    - ``wedge``: every call blocks until the fault clears (hung daemon:
      probes hit their deadline, lanes wedge).
    - ``flap``: every other call refuses (a worker bouncing between
      alive and dead -- the breaker must quarantine it, not bounce
      loops on and off it).
    - ``slow``: slow-loris -- every call pays ``delay_s`` before
      executing (a congested daemon: latency-weighted placement should
      shift load away without the breaker opening).
    - ``burst``: the next ``count`` calls fail like a daemon 5xx /
      mid-response ECONNRESET, then the gate self-heals (the transient
      burst the engine pool's stale-retry and the scheduler's strand
      ceiling must absorb without quarantining a healthy worker).
    - ``probe_drop``: ``ping`` fails while data-path calls succeed (a
      dropped SSH-mux probe channel: health must not condemn a worker
      whose engine still serves traffic without corroboration).

    Lifecycle/telemetry passthroughs (``close``/``close_events``/
    ``pool_stats``) are never gated: draining a dead worker's engine on
    shutdown must not raise.

    Fake-WAN RTT (``rtt_s``, docs/workerd.md#fake-wan): every call
    arriving through the REMOTE view (the ``Worker.engine`` the
    scheduler dials, i.e. the host side of the host<->worker link) pays
    an injected per-call round trip before executing -- the
    deterministic stand-in for an SSH-mux-forwarded daemon on a real
    pod.  The LOCAL view (:meth:`local_view`, what a worker-resident
    workerd dials) pays every injected FAULT (a dead daemon is dead
    from any side) but never the WAN rtt.
    """

    _UNGATED = {"close", "close_events", "pool_stats"}
    # calls that begin launch work against the daemon: what the
    # admission token bucket meters (docs/loop-placement.md); the gate
    # tracks their concurrency high-water mark so tests can assert a
    # worker's daemon never saw more than its cap at once
    _LAUNCH_CALLS = {"container_create", "container_start"}

    def __init__(self, inner: FakeDockerAPI):
        self.inner = inner
        self._mode: str | None = None
        self._cleared = threading.Event()
        self._cleared.set()
        self._lock = threading.Lock()
        self._calls = 0
        self._inflight = 0
        self._launch_inflight = 0
        self._burst_left = 0        # remaining 'burst' failures
        self._delay_s = 0.0         # per-call delay under 'slow'
        self.rtt_s = 0.0            # injected host<->worker WAN round trip
        #                             per REMOTE call (local_view skips it)
        self.injected = 0           # gated calls that were made to fail
        self.call_hwm = 0           # concurrent daemon calls, any kind
        self.launch_hwm = 0         # concurrent create/start calls

    def set_fault(self, mode: str | None, *, count: int = 3,
                  delay_s: float = 0.1) -> None:
        if mode is not None and mode not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {mode!r} "
                             f"(expected {'|'.join(FAULT_KINDS)})")
        with self._lock:
            # mode and event flip together: publishing 'wedge' before
            # clearing the event would let a concurrent call slip
            # through the wedge ungated
            self._mode = mode
            self._burst_left = int(count) if mode == "burst" else 0
            self._delay_s = float(delay_s) if mode == "slow" else 0.0
            if mode == "wedge":
                self._cleared.clear()
            else:
                self._cleared.set()

    def set_rtt(self, rtt_s: float) -> None:
        """Inject a per-call WAN round trip on the remote view."""
        self.rtt_s = max(0.0, float(rtt_s))

    def local_view(self) -> "_LocalGateView":
        """The worker-resident side of this daemon: same faults, no
        injected WAN rtt (what a WorkerdServer should be built on)."""
        return _LocalGateView(self)

    def _gate(self, name: str, *, local: bool = False) -> None:
        if not local and self.rtt_s > 0:
            # the remote caller's request/response round trip; paid
            # BEFORE mode handling so even refused dials cost the wire
            time.sleep(self.rtt_s)
        with self._lock:
            mode = self._mode
            delay = self._delay_s
            self._calls += 1
            n = self._calls
            if mode == "burst":
                if self._burst_left <= 0:
                    self._mode, mode = None, None   # burst spent: heal
                else:
                    self._burst_left -= 1
            if mode in ("refuse", "burst") or (mode == "flap" and n % 2) \
                    or (mode == "probe_drop" and name == "ping"):
                self.injected += 1
        if mode == "refuse":
            raise DriverError("injected fault: connection refused")
        if mode == "burst":
            raise DriverError(
                "injected fault: daemon 5xx / connection reset by peer")
        if mode == "wedge":
            if not self._cleared.wait(WEDGE_ABANDON_S):
                raise DriverError("injected fault: wedged (never revived)")
        if mode == "flap" and n % 2:
            raise DriverError("injected fault: flapping connection refused")
        if mode == "slow" and delay > 0:
            # interruptible: a revive (set_fault(None)) sets _cleared,
            # but slow keeps it set -- plain sleep, delays are small
            time.sleep(delay)
        if mode == "probe_drop" and name == "ping":
            raise DriverError("injected fault: probe channel dropped")

    def _wrap(self, name: str, *, local: bool):
        attr = getattr(self.inner, name)
        if not callable(attr) or name in self._UNGATED:
            return attr
        is_launch = name in self._LAUNCH_CALLS

        def call(*args, **kwargs):
            self._gate(name, local=local)
            with self._lock:
                self._inflight += 1
                self.call_hwm = max(self.call_hwm, self._inflight)
                if is_launch:
                    self._launch_inflight += 1
                    self.launch_hwm = max(self.launch_hwm,
                                          self._launch_inflight)
            try:
                return attr(*args, **kwargs)
            finally:
                with self._lock:
                    self._inflight -= 1
                    if is_launch:
                        self._launch_inflight -= 1

        return call

    def __getattr__(self, name: str):
        return self._wrap(name, local=False)


class _LocalGateView:
    """Worker-resident view of a gated fake daemon: shares the gate's
    faults, counters, and high-water marks (the daemon is ONE daemon),
    but never pays the injected WAN ``rtt_s`` -- calls from this side
    never cross the fake WAN.  Built by ``FakeDriver.local_engine``."""

    def __init__(self, gate: _FaultGate):
        self._gate_obj = gate

    def __getattr__(self, name: str):
        return self._gate_obj._wrap(name, local=True)


class FakeDriver(RuntimeDriver):
    name = "fake"
    real_cgroups = False

    def __init__(self, n_workers: int = 1, *, prefix: str = "fake"):
        # `prefix` namespaces worker ids/hostnames so several fake pods
        # (one FakeDriver each) coexist in one journal without id
        # collisions -- the federation migration path resumes a dead
        # pod's run on a survivor and its stand-in workers must never
        # alias the survivor's live ones (docs/federation.md)
        self.prefix = prefix
        self.apis = [FakeDockerAPI() for _ in range(n_workers)]
        self.gates = [_FaultGate(api) for api in self.apis]
        self._workers = [
            Worker(
                id=f"{prefix}-{i}",
                index=i,
                hostname=f"{prefix}-worker-{i}",
                engine=Engine(gate),
            )
            for i, gate in enumerate(self.gates)
        ]
        self._drained: set[str] = set()     # scaled-down worker ids

    def connect(self) -> list[Worker]:
        return self.workers()

    def workers(self) -> list[Worker]:
        if not self._drained:
            return self._workers
        return [w for w in self._workers if w.id not in self._drained]

    @property
    def api(self) -> FakeDockerAPI:
        """Default worker's fake API (single-worker tests)."""
        return self.apis[0]

    def inject_fault(self, index: int, kind: str = "refuse", **kw) -> None:
        """Fault worker ``index``'s daemon (see _FaultGate): refuse |
        wedge | flap | slow(delay_s=) | burst(count=) | probe_drop."""
        self.gates[index].set_fault(kind, **kw)

    def set_rtt(self, index: int, rtt_s: float) -> None:
        """Inject a deterministic host<->worker WAN round trip paid by
        every REMOTE engine call against worker ``index`` (the fake-WAN
        harness; docs/workerd.md#fake-wan).  ``local_engine`` calls --
        a worker-resident workerd's -- never pay it."""
        self.gates[index].set_rtt(rtt_s)

    def set_rtt_all(self, rtt_s: float) -> None:
        for gate in self.gates:
            gate.set_rtt(rtt_s)

    def local_engine(self, index: int) -> Engine:
        """An Engine over the worker-resident view of worker ``index``'s
        daemon: pays injected faults, never the injected WAN rtt.  What
        an in-process WorkerdServer for that worker should be built on."""
        return Engine(self.gates[index].local_view())

    def clear_fault(self, index: int) -> None:
        """Revive worker ``index`` (blocked 'wedge' calls proceed)."""
        self.gates[index].set_fault(None)

    # ----------------------------------------------------------- elasticity
    # The fake pod can grow and shrink in place: what the capacity
    # controller's FakeFleetScaler scales (docs/elastic-capacity.md).
    # Consumers that re-read workers() each tick (placement context,
    # pool tick) pick the change up naturally; ids are never reused and
    # apis/gates stay index-aligned with all_workers(), so audits over
    # a drained worker's call history keep working.

    def add_worker(self) -> Worker:
        """Provision one more fake worker; returns it."""
        index = len(self.apis)
        api = FakeDockerAPI()
        gate = _FaultGate(api)
        self.apis.append(api)
        self.gates.append(gate)
        worker = Worker(id=f"{self.prefix}-{index}", index=index,
                        hostname=f"{self.prefix}-worker-{index}",
                        engine=Engine(gate))
        self._workers.append(worker)
        self._drained.discard(worker.id)
        return worker

    def remove_worker(self, worker_id: str) -> bool:
        """Drain one worker out of the serving set, modeling VM
        deletion: it leaves ``workers()`` (no more placements, probes,
        or sweeps) and its containers vanish with the VM -- but its
        api/gate call recorders survive, index-aligned under
        ``all_workers()``, so post-scenario audits still see every call
        the daemon ever executed."""
        for w in self._workers:
            if w.id == worker_id and w.id not in self._drained:
                self._drained.add(w.id)
                i = next(j for j, x in enumerate(self._workers)
                         if x.id == worker_id)
                self.apis[i].containers.clear()
                return True
        return False

    def all_workers(self) -> list[Worker]:
        """Every worker ever provisioned (drained included), aligned
        index-for-index with ``apis``/``gates`` -- the audit view."""
        return list(self._workers)

    def close(self) -> None:
        for w in self._workers:
            if w.engine is not None:
                w.engine.close()
