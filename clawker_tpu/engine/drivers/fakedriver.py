"""Fake driver: N in-process fake daemons (test seam for multi-worker paths)."""

from __future__ import annotations

from ..api import Engine
from ..fake import FakeDockerAPI
from .base import RuntimeDriver, Worker


class FakeDriver(RuntimeDriver):
    name = "fake"
    real_cgroups = False

    def __init__(self, n_workers: int = 1):
        self.apis = [FakeDockerAPI() for _ in range(n_workers)]
        self._workers = [
            Worker(
                id=f"fake-{i}",
                index=i,
                hostname=f"fake-worker-{i}",
                engine=Engine(api),
            )
            for i, api in enumerate(self.apis)
        ]

    def connect(self) -> list[Worker]:
        return self._workers

    def workers(self) -> list[Worker]:
        return self._workers

    @property
    def api(self) -> FakeDockerAPI:
        """Default worker's fake API (single-worker tests)."""
        return self.apis[0]

    def close(self) -> None:
        for w in self._workers:
            if w.engine is not None:
                w.engine.close()
