"""Netlogger: kernel egress events -> enriched structured log records.

Drains the firewall events ring (FirewallMaps.drain_events: the fwctl
JSON lane on real hosts, the in-memory ring in tests), enriches each
record -- cgroup id back to the enrolled container, zone hash back to
the matched zone apex -- and emits JSON lines to the egress log file
plus, when the monitor stack is up, OTLP/HTTP log records to the
collector (landing in the ``clawker-otlp`` index with
``service.name=ebpf-egress`` as the discriminator).

Parity reference: controlplane/firewall/ebpf/netlogger (ringbuf drain ->
OTLP, enrichment by cgroup_id via enrollment + docker labels).
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path

from .. import logsetup
from ..firewall.maps import FirewallMaps
from ..firewall.model import Action, Reason

log = logsetup.get("monitor.netlogger")


class NetLogger:
    def __init__(
        self,
        maps: FirewallMaps,
        *,
        out_path: Path,
        resolve_cgroup=None,          # cgroup_id -> container name ("" unknown)
        resolve_zone=None,            # zone_hash -> apex ("" unknown)
        otlp_endpoint: str = "",      # http://host:4318 -- optional lane
        lane=None,                    # controlplane.otel.OtlpLane (carries
        #                               the mTLS material when the
        #                               collector requires client certs)
        poll_s: float = 1.0,
    ):
        self.maps = maps
        self.out_path = Path(out_path)
        self.resolve_cgroup = resolve_cgroup or (lambda cg: "")
        self.resolve_zone = resolve_zone or (lambda zh: "")
        self.otlp_endpoint = otlp_endpoint.rstrip("/")
        self.poll_s = poll_s
        self.emitted = 0
        self._lane = lane
        if self._lane is not None:
            self.otlp_endpoint = self._lane.endpoint
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------- records

    def enrich(self, ev) -> dict:
        return {
            "@timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "service": "ebpf-egress",
            "cgroup_id": ev.cgroup_id,
            "container": self.resolve_cgroup(ev.cgroup_id),
            "dst_ip": ev.dst_ip,
            "dst_port": ev.dst_port,
            "proto": ev.proto,
            "verdict": Action(ev.verdict).name,
            "reason": Reason(ev.reason).name,
            "zone": self.resolve_zone(ev.zone_hash),
            "zone_hash": str(ev.zone_hash),
        }

    def drain_once(self) -> int:
        events = self.maps.drain_events(max_events=512)
        if not events:
            return 0
        records = [self.enrich(ev) for ev in events]
        self.out_path.parent.mkdir(parents=True, exist_ok=True)
        with open(self.out_path, "a", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        if self.otlp_endpoint:
            self._ship_otlp(records)
        self.emitted += len(records)
        return len(records)

    def _ship_otlp(self, records: list[dict]) -> None:
        """Ship on the ebpf-egress subsystem lane (controlplane/otel)."""
        from ..controlplane.otel import OtlpLane

        if self._lane is None:
            self._lane = OtlpLane(self.otlp_endpoint, "ebpf-egress")
        self._lane.ship(records, severity_of=lambda rec: (
            "WARN" if rec.get("verdict") == "DENY" else "INFO"))

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="netlogger",
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.drain_once()
            except Exception as e:  # drain must never die silently mid-flight
                log.error("event=netlogger_drain_failed error=%s", e)
            self._stop.wait(self.poll_s)
        try:
            self.drain_once()  # final sweep so shutdown loses nothing
        except Exception:
            pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)


def handler_resolvers(handler, *, cache_ttl_s: float = 5.0):
    """Enrichment closures over a FirewallHandler's state.

    Both lookups are dict-cached with a short TTL: enrichment runs per
    event (up to 512/poll), and rebuilding the maps per event would mean
    a rules-file read + hash sweep for every record."""
    from ..firewall.hashes import zone_hash as _zh

    state = {"at": 0.0, "cgroups": {}, "zones": {}}

    def _refresh():
        now = time.monotonic()
        if now - state["at"] < cache_ttl_s:
            return
        state["cgroups"] = {
            e.cgroup_id: e.container_id for e in handler.enrollments.values()
        }
        zones = {}
        for rule in handler.effective_rules():
            apex = rule.dst[2:] if rule.dst.startswith("*.") else rule.dst
            zones[_zh(apex)] = apex
        state["zones"] = zones
        state["at"] = now

    def resolve_cgroup(cg: int) -> str:
        _refresh()
        return state["cgroups"].get(cg, "")

    def resolve_zone(zh: int) -> str:
        if not zh:
            return ""
        _refresh()
        return state["zones"].get(zh, "")

    return resolve_cgroup, resolve_zone
