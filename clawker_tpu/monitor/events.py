"""Ordered event fan-in for the concurrent fleet control plane.

With the loop scheduler fanned out across per-worker lanes, per-agent
``wait_container`` threads, and the anomaly watch's scoring thread,
``on_event`` callbacks fire from many threads at once.  Every consumer
(CLI stderr lines, the loop dashboard, the final status JSON) assumes
per-agent event order -- ``iteration_start 1`` must never be delivered
before ``iteration_done 0``.  :class:`EventBus` restores that guarantee:
emits are stamped with a global and a per-agent sequence number under
one lock, and a single drainer thread delivers them to the sink in
stamp order.

Delivery rides its own thread on purpose: holding the stamp lock across
the sink call would couple every lane, waiter, and the run loop to sink
latency -- one consumer blocked on a wedged stderr (terminal flow
control, a stalled pipe reader) would halt the whole pod's control
plane, exactly the coupling the per-worker lanes exist to prevent.  The
cost is that delivery is asynchronous: callers that need "everything
emitted so far has reached the sink" (the scheduler before returning
final states, tests) call :meth:`flush`.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable

from .. import logsetup

log = logsetup.get("monitor.events")

HISTORY_LIMIT = 4096    # long unbounded loops must not grow without bound

# Event name the health subsystem publishes breaker transitions under.
# The record's ``agent`` field carries the WORKER id (workers are the
# subjects of fleet health, agents of everything else on the bus).
WORKER_HEALTH = "worker.health"

# Event name completed trace spans ride the bus under (telemetry/spans):
# the record's agent is the loop agent, the detail the span's compact
# one-liner.  Consumers wanting structure read the flight recorder.
TRACE_SPAN = "trace.span"

# Event name placement decisions ride the bus under (placement/ +
# docs/loop-placement.md): where a loop landed (or why it could not),
# typed so the fleet placement view and tests can round-trip it.
PLACEMENT_DECISION = "placement.decision"

# Event name sentinel verdicts ride the bus under (clawker_tpu/sentinel
# + docs/analytics-online.md): a live per-agent anomaly flag.  Strictly
# observational -- nothing on the bus consumes it to change scheduling.
ANOMALY_FLAG = "anomaly.flag"

# Event name elastic-capacity decisions ride the bus under
# (clawker_tpu/capacity + docs/elastic-capacity.md): pool-target /
# token-cap / queue-mode / fleet-scale changes, typed so the console
# and tests can replay what the controller did and why.
CAPACITY_DECISION = "capacity.decision"


@dataclass(frozen=True)
class CapacityDecisionEvent:
    """Typed payload of a ``capacity.decision`` event.

    ``kind`` names the control loop that acted: ``pool`` (adaptive
    warm-pool target), ``tokens`` (SLO-scaled bucket cap), ``queue``
    (reject-with-retry-after flip), ``provision`` / ``drain`` /
    ``drain_blocked`` (fleet autoscale).  ``value`` is the compact
    outcome (``target=4``, ``cap=8``, ``reject retry_after_s=0.40``);
    ``reason`` carries the telemetry that drove it.  Rides as the
    detail string like the other typed events; structured consumers
    round-trip with :meth:`parse`.
    """

    kind: str
    worker: str
    value: str
    reason: str = ""

    def detail(self) -> str:
        base = f"{self.kind} {self.worker or '-'} {self.value}"
        return f"{base}: {self.reason}" if self.reason else base

    @classmethod
    def parse(cls, detail: str) -> "CapacityDecisionEvent":
        head, _, reason = detail.partition(": ")
        kind, _, rest = head.partition(" ")
        worker, _, value = rest.partition(" ")
        return cls(kind, "" if worker == "-" else worker, value, reason)


# Event name gitguard proxy verdicts ride the bus under
# (clawker_tpu/gitguard + docs/git-policy.md): every advertisement
# filter / push refusal / allow the git firewall made for this run,
# typed so status surfaces and tests can replay what was enforced.
GITGUARD_DECISION = "gitguard.decision"

# Event name storage faults ride the bus under (docs/durability.md):
# a durable journal append that failed or recovered through a poisoned
# handle, an unwritable journal at open, or a disk-pressure watermark
# transition.  The chaos no-silent-drop invariant audits this stream --
# a dropped or poisoned write with no storage.fault event is a bug.
STORAGE_FAULT = "storage.fault"


@dataclass(frozen=True)
class StorageFaultEvent:
    """Typed payload of a ``storage.fault`` event.

    ``op`` is the failed storage operation (``open`` / ``write`` /
    ``fsync`` / ``close`` -- or ``pressure`` for a watermark
    transition); ``action`` what the fault handler did (``recovered``,
    ``degraded``, ``fail_stop``, ``shed``, ``gc``); ``dropped`` how
    many records that fault lost (0 when recovery re-appended the
    unsynced ring).  Rides as the detail string like the other typed
    events; structured consumers round-trip with :meth:`parse`.
    """

    op: str
    action: str
    dropped: int = 0
    error: str = ""

    def detail(self) -> str:
        base = f"{self.op} {self.action} dropped={self.dropped}"
        return f"{base}: {self.error}" if self.error else base

    @classmethod
    def parse(cls, detail: str) -> "StorageFaultEvent":
        head, _, error = detail.partition(": ")
        parts = head.split(" ")
        op = parts[0] if parts else ""
        action = parts[1] if len(parts) > 1 else ""
        dropped = 0
        for p in parts[2:]:
            if p.startswith("dropped="):
                try:
                    dropped = int(p.split("=", 1)[1])
                except ValueError:
                    dropped = 0
        return cls(op, action, dropped, error)


@dataclass(frozen=True)
class GitguardDecisionEvent:
    """Typed payload of a ``gitguard.decision`` event.

    ``verdict`` is ``allow`` / ``deny`` / ``down_refused``; ``service``
    the smart-HTTP service judged (``git-receive-pack`` for pushes,
    ``git-upload-pack`` for fetch wants); ``ref`` the ref the verdict
    is about; ``reason`` the git-readable refusal text ("" on allow).
    Rides as the detail string like the other typed events; structured
    consumers round-trip with :meth:`parse`.
    """

    verdict: str
    service: str
    ref: str
    reason: str = ""

    def detail(self) -> str:
        base = f"{self.verdict} {self.service or '-'} {self.ref or '-'}"
        return f"{base}: {self.reason}" if self.reason else base

    @classmethod
    def parse(cls, detail: str) -> "GitguardDecisionEvent":
        head, _, reason = detail.partition(": ")
        verdict, _, rest = head.partition(" ")
        service, _, ref = rest.partition(" ")
        return cls(verdict, "" if service == "-" else service,
                   "" if ref == "-" else ref, reason)


@dataclass(frozen=True)
class AnomalyFlagEvent:
    """Typed payload of an ``anomaly.flag`` event.

    ``kind`` names the dominant feature family of the reconstruction
    error: ``egress`` (network behavior) or ``behavior`` (exit codes /
    orphans / migrations).  Rides as the detail string like the other
    typed events so every existing sink renders it unchanged;
    structured consumers round-trip with :meth:`parse`.
    """

    agent: str
    worker: str
    z: float
    kind: str = "egress"

    def detail(self) -> str:
        return f"{self.kind} z={self.z:.2f} worker={self.worker}"

    @classmethod
    def parse(cls, agent: str, detail: str) -> "AnomalyFlagEvent":
        kind, _, rest = detail.partition(" z=")
        zs, _, worker = rest.partition(" worker=")
        try:
            z = float(zs)
        except ValueError:
            z = 0.0
        return cls(agent, worker, z, kind)


@dataclass(frozen=True)
class PlacementEvent:
    """Typed payload of a ``placement.decision`` event.

    ``action`` is one of ``placed`` (initial slot), ``replaced``
    (failover/rescue re-placement), or ``rejected`` (admission queue
    full -- the loop went back to the rescue pass).  Same stance as
    :class:`WorkerHealthEvent`: rides as the detail string so every
    existing sink renders it unchanged; structured consumers parse.
    """

    agent: str
    worker: str
    policy: str
    tenant: str
    action: str
    reason: str = ""
    retry_after_s: float = 0.0      # rejected only: the backoff hint the
    #                                 admission controller handed back --
    #                                 how long until the queue is expected
    #                                 to have room (docs/elastic-capacity.md)

    def detail(self) -> str:
        base = f"{self.action} {self.worker} [{self.policy}/{self.tenant}]"
        if self.retry_after_s > 0:
            base += f" retry_after_s={self.retry_after_s:.3f}"
        return f"{base}: {self.reason}" if self.reason else base

    @classmethod
    def parse(cls, agent: str, detail: str) -> "PlacementEvent":
        head, _, reason = detail.partition(": ")
        action, _, rest = head.partition(" ")
        worker, _, tagged = rest.partition(" [")
        tagged, _, retry_raw = tagged.partition(" retry_after_s=")
        policy, _, tenant = tagged.rstrip("]").partition("/")
        try:
            retry = float(retry_raw) if retry_raw else 0.0
        except ValueError:
            retry = 0.0
        return cls(agent, worker, policy, tenant.rstrip("]"), action,
                   reason, retry)


@dataclass(frozen=True)
class WorkerHealthEvent:
    """Typed payload of a ``worker.health`` event.

    Rides the bus as the record's detail string so every existing sink
    (CLI stderr lines, the loop dashboard, status JSON) renders it with
    zero changes; structured consumers (``clawker fleet health``, tests)
    round-trip it with :meth:`parse`.
    """

    worker: str
    old_state: str
    new_state: str
    reason: str = ""

    def detail(self) -> str:
        base = f"{self.old_state}->{self.new_state}"
        return f"{base}: {self.reason}" if self.reason else base

    @classmethod
    def parse(cls, worker: str, detail: str) -> "WorkerHealthEvent":
        states, _, reason = detail.partition(": ")
        old, _, new = states.partition("->")
        return cls(worker, old, new, reason)


@dataclass(frozen=True)
class EventRecord:
    seq: int            # position in the global event stream
    agent_seq: int      # position within this agent's event stream
    agent: str
    event: str
    detail: str = ""


class EventBus:
    """Thread-safe, order-preserving emitter over an ``on_event`` sink."""

    def __init__(self, sink: Callable[..., None] | None = None,
                 *, history: int = HISTORY_LIMIT):
        self._sink = sink
        self._lock = threading.Lock()
        self._delivered_cond = threading.Condition(self._lock)
        self._seq = 0
        self._delivered = 0
        self._agent_seq: dict[str, int] = {}
        self._closed = False
        self.history: deque[EventRecord] = deque(maxlen=history)
        # per-agent index over the SAME records: for_agent() used to scan
        # the whole history deque under the stamp lock on every call --
        # a dashboard polling one agent contended with every hot-path
        # emit.  Kept in lockstep with history's bounded eviction.
        self._by_agent: dict[str, deque[EventRecord]] = {}
        # taps see every stamped record synchronously on the EMITTER
        # thread (no ordering loss, no drainer dependency): the seam the
        # fleet sentinel's behavioral featurizer rides.  A tap must be
        # O(dict update) cheap and never raise into the hot path.
        self._taps: list[Callable[[EventRecord], None]] = []
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        if sink is not None:
            threading.Thread(target=self._drain, daemon=True,
                             name="event-bus").start()

    def add_tap(self, tap: Callable[[EventRecord], None]) -> None:
        """Attach a synchronous observer of every stamped record.  Runs
        on the emitting thread AFTER the stamp lock is released -- a
        slow tap delays only its own emitter, never the stamp order."""
        self._taps.append(tap)

    def remove_tap(self, tap: Callable[[EventRecord], None]) -> None:
        try:
            self._taps.remove(tap)
        except ValueError:
            pass

    def emit(self, agent: str, event: str, detail: str = "") -> EventRecord:
        with self._lock:
            self._seq += 1
            aseq = self._agent_seq.get(agent, 0) + 1
            self._agent_seq[agent] = aseq
            rec = EventRecord(self._seq, aseq, agent, event, detail)
            maxlen = self.history.maxlen
            # `maxlen and len(...)`: a maxlen-0 history retains nothing,
            # so there is nothing to evict (and nothing to index below --
            # the index must mirror the history exactly)
            evicted = (self.history[0]
                       if maxlen and len(self.history) == maxlen else None)
            self.history.append(rec)
            if evicted is not None:
                # the global deque just dropped its oldest record; its
                # agent's index holds records in stamp order, so the
                # evicted one is necessarily that index's head
                idx = self._by_agent.get(evicted.agent)
                if idx:
                    idx.popleft()
                    if not idx:
                        del self._by_agent[evicted.agent]
            if maxlen != 0:
                self._by_agent.setdefault(agent, deque()).append(rec)
            if self._sink is not None and not self._closed:
                # stamped and enqueued under the same lock: queue order
                # is stamp order, and the single drainer preserves it
                self._q.put(rec)
            else:
                self._delivered = max(self._delivered, self._seq)
        for tap in self._taps:
            try:
                tap(rec)
            except Exception:       # noqa: BLE001 -- observers never wedge emits
                log.exception("event tap failed for %s/%s", agent, event)
        return rec

    def close(self) -> None:
        """Retire the drainer thread once everything queued so far has
        been delivered.  Later emits still stamp + record history; they
        just no longer reach the sink.  Without this, every scheduler
        would leak one blocked drainer (plus its sink closure) for the
        life of the process."""
        with self._lock:
            if self._sink is None or self._closed:
                return
            self._closed = True
            self._q.put(None)

    def _drain(self) -> None:
        while True:
            rec = self._q.get()
            if rec is None:
                return
            try:
                self._sink(rec.agent, rec.event, rec.detail)
            except Exception:
                # a broken consumer must never stall the event stream
                log.exception("event sink failed for %s/%s",
                              rec.agent, rec.event)
            with self._delivered_cond:
                self._delivered = max(self._delivered, rec.seq)
                self._delivered_cond.notify_all()

    def flush(self, timeout: float | None = 5.0) -> bool:
        """Block until every event stamped so far has been handed to the
        sink; False if the sink could not keep up within ``timeout``."""
        with self._delivered_cond:
            target = self._seq
            return self._delivered_cond.wait_for(
                lambda: self._delivered >= target, timeout)

    def for_agent(self, agent: str) -> list[EventRecord]:
        """This agent's records, oldest first.  O(k) copy of the
        per-agent index -- never a scan of the whole history under the
        stamp lock (loop-dashboard reads must not contend with hot-path
        emits beyond the copy itself)."""
        with self._lock:
            idx = self._by_agent.get(agent)
            return list(idx) if idx else []
