"""Monitor stack: deterministic compose rendering + lifecycle.

Renders the observability compose file (OTel Collector gateway,
OpenSearch single node, OpenSearch Dashboards, Prometheus, one-shot
bootstrap seeding index templates + saved objects) into the data dir and
drives ``docker compose`` over it.  Rendering is pure (settings -> bytes)
so tests pin the output; the compose invocation rides a runner seam.

Parity reference: internal/monitor/templates/compose.yaml.tmpl:11-198
(service set), otel-config.yaml.tmpl, prometheus.yaml.tmpl; `monitor up`
shells to docker compose (internal/cmd/monitor/up/up.go:81).  The six
log indices (SURVEY.md 2.11): claude-code, clawker-cli, clawkercp,
clawker-envoy, clawker-dnsgate, clawker-ebpf-egress.
"""

from __future__ import annotations

import json
import subprocess
from pathlib import Path

from .. import consts, logsetup
from ..config import Config
from ..errors import ClawkerError

log = logsetup.get("monitor.stack")

LOG_INDICES = (
    "clawker-otlp",       # everything arriving over OTLP (service.name
    #                       attribute discriminates: claude-code harness
    #                       telemetry, ebpf-egress, cp subsystems)
    "claude-code",        # harness telemetry (file-shipped lane)
    "clawker-cli",        # host CLI logs
    "clawkercp",          # control-plane logs
    "clawker-envoy",      # proxy access logs (container stdout)
    "clawker-dnsgate",    # DNS query decisions
    "clawker-ebpf-egress",  # per-decision kernel egress events (jsonl lane)
)

COMPOSE_PROJECT = "clawker-monitor"


class MonitorError(ClawkerError):
    pass


def render_otel_config(s, lanes: dict[str, list[str]] | None = None) -> str:
    """OTLP (grpc+http) -> OpenSearch log indices + Prometheus metrics.

    ``lanes`` maps index -> service.name values routed into it (base
    lanes + monitoring-unit lanes); everything unrouted lands in
    clawker-otlp.  Lane/service names pass the unit grammar (lowercase/
    digits/hyphens), so interpolating them into OTTL conditions cannot
    inject (unit.py index-name rule)."""
    lanes = lanes or {}
    exporters: dict = {
        "opensearch/default": {
            "http": {"endpoint": "http://opensearch:9200"},
            "logs_index": "clawker-otlp",
        },
        # harness OTLP traces land in the SS4O traces dataset (reference:
        # MONITORING-REFERENCE.md:5 -- Claude Code traces -> SS4O
        # traces/clawker), queryable from the Dashboards Observability UI
        "opensearch/traces": {
            "http": {"endpoint": "http://opensearch:9200"},
            "dataset": "clawker",
        },
        "prometheus": {"endpoint": "0.0.0.0:8889"},
        "debug": {"verbosity": "basic"},
    }
    pipelines: dict = {
        "metrics": {"receivers": ["otlp"],
                    "processors": ["transform/metrics", "batch"],
                    "exporters": ["prometheus"]},
        "traces": {"receivers": ["otlp"], "processors": ["batch"],
                   "exporters": ["opensearch/traces"]},
    }
    routing_table = []
    for index in sorted(lanes):
        exporters[f"opensearch/{index}"] = {
            "http": {"endpoint": "http://opensearch:9200"},
            "logs_index": index,
        }
        pipelines[f"logs/{index}"] = {
            "receivers": ["routing"], "processors": ["batch"],
            "exporters": [f"opensearch/{index}"]}
        cond = " or ".join(
            f'resource.attributes["service.name"] == "{svc}"'
            for svc in sorted(lanes[index]))
        # the condition rides INSIDE the OTTL statement -- a separate
        # `condition` key is rejected by the pinned collector's strict
        # config decoding (and a bare route() would match everything)
        routing_table.append(
            {"statement": f"route() where {cond}",
             "pipelines": [f"logs/{index}"]})
    pipelines["logs/default"] = {"receivers": ["routing"],
                                 "processors": ["batch"],
                                 "exporters": ["opensearch/default"]}
    pipelines["logs/in"] = {"receivers": ["otlp"], "processors": [],
                            "exporters": ["routing"]}
    cfg = {
        "receivers": {
            "otlp": {
                "protocols": {
                    "grpc": {"endpoint": f"0.0.0.0:{s.otlp_grpc_port}"},
                    "http": {"endpoint": "0.0.0.0:4318"},
                }
            }
        },
        "connectors": {
            "routing": {
                "default_pipelines": ["logs/default"],
                "table": routing_table,
            }
        },
        "processors": {
            "batch": {"timeout": "2s"},
            # label rename worked around an OpenSearch SQL-plugin bug in
            # the reference (MONITORING-REFERENCE.md:13-31); kept so
            # dashboards port over unchanged
            "transform/metrics": {
                "metric_statements": [{
                    "context": "datapoint",
                    "statements": [
                        'set(attributes["kind"], attributes["type"]) where attributes["type"] != nil',
                        'delete_key(attributes, "type")',
                    ],
                }]
            },
        },
        "exporters": exporters,
        "service": {"pipelines": pipelines},
    }
    import yaml

    return yaml.safe_dump(cfg, sort_keys=True)


def render_prometheus_config(s) -> str:
    import yaml

    return yaml.safe_dump({
        "global": {"scrape_interval": "15s"},
        "scrape_configs": [
            {"job_name": "otel-collector",
             "static_configs": [{"targets": ["otel-collector:8889"]}]},
            {"job_name": "prometheus",
             "static_configs": [{"targets": ["localhost:9090"]}]},
        ],
    }, sort_keys=True)


def render_bootstrap_script() -> str:
    """One-shot seeding: plain directory loops over the mounted
    opensearch-bootstrap tree (base corpus + unit overlays apply the
    same way -- that is the point of the shared layout).

    Reference: internal/monitor/templates/opensearch-bootstrap/
    bootstrap.sh.tmpl semantics."""
    return r"""#!/bin/sh
set -e
B=/bootstrap
OS=http://opensearch:9200
DASH=http://opensearch-dashboards:5601
H='Content-Type: application/json'

until curl -fsS "$OS" >/dev/null; do sleep 2; done

for f in "$B"/component-templates/*.json; do
  [ -e "$f" ] || continue
  n=$(basename "$f" .json)
  curl -fsS -X PUT -H "$H" "$OS/_component_template/$n" --data-binary @"$f" >/dev/null
  echo "component-template $n"
done

for f in "$B"/index-templates/*.json; do
  [ -e "$f" ] || continue
  n=$(basename "$f" .json)
  curl -fsS -X PUT -H "$H" "$OS/_index_template/$n" --data-binary @"$f" >/dev/null
  echo "index-template $n"
done

for f in "$B"/ingest-pipelines/*.json; do
  [ -e "$f" ] || continue
  n=$(basename "$f" .json)
  curl -fsS -X PUT -H "$H" "$OS/_ingest/pipeline/$n" --data-binary @"$f" >/dev/null
  echo "ingest-pipeline $n"
done

# ISM is a plugin: degrade (bare OSS images run without retention)
for f in "$B"/ism-policies/*.json; do
  [ -e "$f" ] || continue
  n=$(basename "$f" .json)
  curl -fsS -X PUT -H "$H" "$OS/_plugins/_ism/policies/$n" --data-binary @"$f" >/dev/null \
    && echo "ism-policy $n" || echo "ism-policy $n skipped (plugin unavailable)"
done

# saved objects import needs Dashboards, which boots after OpenSearch
until curl -fsS "$DASH/api/status" >/dev/null; do sleep 2; done
for f in "$B"/saved-objects/*.ndjson; do
  [ -e "$f" ] || continue
  curl -fsS -X POST "$DASH/api/saved_objects/_import?overwrite=true" \
    -H 'osd-xsrf: true' --form file=@"$f" >/dev/null
  echo "saved-objects $(basename "$f")"
done

echo 'clawker monitor bootstrap complete'
"""


def render_compose(s) -> str:
    import yaml

    services = {
        "otel-collector": {
            "image": "otel/opentelemetry-collector-contrib:0.103.0",
            "command": ["--config=/etc/otel/config.yaml"],
            "volumes": ["./otel-config.yaml:/etc/otel/config.yaml:ro"],
            "ports": [f"{s.otlp_grpc_port}:{s.otlp_grpc_port}", "4318:4318"],
            "depends_on": ["opensearch"],
            "restart": "unless-stopped",
        },
        "opensearch": {
            "image": "opensearchproject/opensearch:2.15.0",
            "environment": [
                "discovery.type=single-node",
                "DISABLE_SECURITY_PLUGIN=true",
                "OPENSEARCH_JAVA_OPTS=-Xms512m -Xmx512m",
            ],
            "ports": [f"{s.opensearch_port}:9200"],
            "volumes": ["opensearch-data:/usr/share/opensearch/data"],
            "restart": "unless-stopped",
        },
        "opensearch-bootstrap": {
            "image": "curlimages/curl:8.8.0",
            "entrypoint": ["/bin/sh", "/bootstrap.sh"],
            "volumes": ["./bootstrap.sh:/bootstrap.sh:ro",
                        "./opensearch-bootstrap:/bootstrap:ro"],
            "depends_on": ["opensearch", "opensearch-dashboards"],
            "restart": "no",
        },
        "opensearch-dashboards": {
            "image": "opensearchproject/opensearch-dashboards:2.15.0",
            "environment": [
                "OPENSEARCH_HOSTS=[\"http://opensearch:9200\"]",
                "DISABLE_SECURITY_DASHBOARDS_PLUGIN=true",
            ],
            "ports": [f"{s.dashboards_port}:5601"],
            "depends_on": ["opensearch"],
            "restart": "unless-stopped",
        },
        "prometheus": {
            "image": "prom/prometheus:v2.53.0",
            "volumes": ["./prometheus.yaml:/etc/prometheus/prometheus.yml:ro"],
            "ports": [f"{s.prometheus_port}:9090"],
            "restart": "unless-stopped",
        },
    }
    return yaml.safe_dump({
        "name": COMPOSE_PROJECT,
        "services": services,
        "volumes": {"opensearch-data": {}},
    }, sort_keys=True)


class MonitorStack:
    def __init__(self, cfg: Config, *, runner=None):
        self.cfg = cfg
        self.dir = cfg.data_dir / "monitor"
        self.runner = runner or self._run_compose

    # ------------------------------------------------------------ render

    def unit_roots(self) -> list:
        """Unit discovery roots: embedded floor, then the host's loose
        extension dir (later wins on name)."""
        from ..bundle.resolver import FLOOR_DIR

        return [FLOOR_DIR / "monitoring",
                self.cfg.data_dir / "monitoring-units"]

    def render(self) -> Path:
        from . import corpus
        from .ledger import Ledger
        from .unit import UnitError, discover_units, materialize

        s = self.cfg.settings.monitoring
        self.dir.mkdir(parents=True, exist_ok=True)

        # bootstrap tree: base corpus + monitoring-unit overlays, then
        # record every seeded unit in the ledger (collision = refusal)
        tree = self.dir / "opensearch-bootstrap"
        if tree.exists():
            import shutil

            shutil.rmtree(tree)
        corpus.write_bootstrap_tree(tree)
        floor, loose = self.unit_roots()
        units = discover_units([floor, loose])
        ledger = Ledger(self.dir)
        # units removed from the host are pruned: unit roots are
        # host-global, so an undiscovered name has no owner left and a
        # stale record would block its name forever
        for gone in set(ledger.units) - set(units):
            del ledger.units[gone]
        lanes: dict[str, list[str]] = {}
        lane_owner: dict[str, str] = {}      # index -> unit
        svc_owner: dict[str, str] = {}       # service.name -> unit
        retention_lanes: dict[str, list[str]] = {}  # token -> indices
        for name, unit in sorted(units.items()):
            source = "floor" if unit.root.is_relative_to(floor) else str(unit.root)
            ledger.seed(unit, source=source)
            materialize(unit, tree)
            for lane in unit.manifest.logs:
                if lane.index in lane_owner:
                    raise UnitError(
                        f"monitoring units {lane_owner[lane.index]!r} and "
                        f"{name!r} both claim index {lane.index!r}")
                lane_owner[lane.index] = name
                for svc in lane.service_names:
                    if svc in svc_owner:
                        raise UnitError(
                            f"monitoring units {svc_owner[svc]!r} and "
                            f"{name!r} both claim service {svc!r} -- logs "
                            "would be double-routed")
                    svc_owner[svc] = name
                lanes[lane.index] = list(lane.service_names)
                retention_lanes.setdefault(lane.retention, []).append(lane.index)
        ledger.save()
        # per-retention ISM policies for unit lanes (the declared tokens
        # must actually rotate the indices, not just pass validation)
        for token, indices in sorted(retention_lanes.items()):
            pol = corpus.ism_policy(
                sorted(f"{i}*" for i in indices),
                age=corpus.RETENTIONS[token])
            (tree / "ism-policies" / f"clawker-units-{token}.json").write_text(
                json.dumps(pol, indent=1, sort_keys=True))

        (self.dir / "compose.yaml").write_text(render_compose(s))
        (self.dir / "otel-config.yaml").write_text(render_otel_config(s, lanes))
        (self.dir / "prometheus.yaml").write_text(render_prometheus_config(s))
        (self.dir / "bootstrap.sh").write_text(render_bootstrap_script())
        return self.dir

    # --------------------------------------------------------- lifecycle

    def _run_compose(self, *args: str) -> subprocess.CompletedProcess:
        cmd = ["docker", "compose", "-p", COMPOSE_PROJECT,
               "-f", str(self.dir / "compose.yaml"), *args]
        try:
            return subprocess.run(cmd, capture_output=True, text=True, timeout=600)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise MonitorError(f"docker compose {' '.join(args)}: {e}") from None

    def up(self) -> None:
        self.render()
        res = self.runner("up", "-d", "--remove-orphans")
        if res.returncode != 0:
            raise MonitorError(f"monitor up failed: {res.stderr.strip()[:500]}")
        log.info("monitor stack up (dashboards :%d, prometheus :%d)",
                 self.cfg.settings.monitoring.dashboards_port,
                 self.cfg.settings.monitoring.prometheus_port)

    def down(self) -> None:
        res = self.runner("down", "--volumes")
        if res.returncode != 0:
            raise MonitorError(f"monitor down failed: {res.stderr.strip()[:500]}")
        # --volumes deletes every seeded object with the data volume, so
        # the ledger must reset too -- it is the documented way out of a
        # SeedCollision (ledger.py), and a stale record would otherwise
        # block the colliding name forever
        from .ledger import LEDGER_FILE

        (self.dir / LEDGER_FILE).unlink(missing_ok=True)

    def status(self) -> list[dict]:
        res = self.runner("ps", "--format", "json")
        if res.returncode != 0:
            return []
        out = []
        for line in res.stdout.splitlines():
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            # compose <2.21 emits one JSON array; newer emits NDJSON rows
            if isinstance(row, list):
                out.extend(row)
            else:
                out.append(row)
        return out
