"""Monitor stack: deterministic compose rendering + lifecycle.

Renders the observability compose file (OTel Collector gateway,
OpenSearch single node, OpenSearch Dashboards, Prometheus, one-shot
bootstrap seeding index templates + saved objects) into the data dir and
drives ``docker compose`` over it.  Rendering is pure (settings -> bytes)
so tests pin the output; the compose invocation rides a runner seam.

Parity reference: internal/monitor/templates/compose.yaml.tmpl:11-198
(service set), otel-config.yaml.tmpl, prometheus.yaml.tmpl; `monitor up`
shells to docker compose (internal/cmd/monitor/up/up.go:81).  The six
log indices (SURVEY.md 2.11): claude-code, clawker-cli, clawkercp,
clawker-envoy, clawker-dnsgate, clawker-ebpf-egress.
"""

from __future__ import annotations

import json
import subprocess
from pathlib import Path

from .. import consts, logsetup
from ..config import Config
from ..errors import ClawkerError

log = logsetup.get("monitor.stack")

LOG_INDICES = (
    "clawker-otlp",       # everything arriving over OTLP (service.name
    #                       attribute discriminates: claude-code harness
    #                       telemetry, ebpf-egress, cp subsystems)
    "claude-code",        # harness telemetry (file-shipped lane)
    "clawker-cli",        # host CLI logs
    "clawkercp",          # control-plane logs
    "clawker-envoy",      # proxy access logs (container stdout)
    "clawker-dnsgate",    # DNS query decisions
    "clawker-ebpf-egress",  # per-decision kernel egress events (jsonl lane)
)

COMPOSE_PROJECT = "clawker-monitor"


class MonitorError(ClawkerError):
    pass


def render_otel_config(s) -> str:
    """OTLP (grpc+http) -> OpenSearch log indices + Prometheus metrics."""
    cfg = {
        "receivers": {
            "otlp": {
                "protocols": {
                    "grpc": {"endpoint": f"0.0.0.0:{s.otlp_grpc_port}"},
                    "http": {"endpoint": "0.0.0.0:4318"},
                }
            }
        },
        "processors": {
            "batch": {"timeout": "2s"},
            # label rename worked around an OpenSearch SQL-plugin bug in
            # the reference (MONITORING-REFERENCE.md:13-31); kept so
            # dashboards port over unchanged
            "transform/metrics": {
                "metric_statements": [{
                    "context": "datapoint",
                    "statements": [
                        'set(attributes["kind"], attributes["type"]) where attributes["type"] != nil',
                        'delete_key(attributes, "type")',
                    ],
                }]
            },
        },
        "exporters": {
            "opensearch/logs": {
                "http": {"endpoint": "http://opensearch:9200"},
                "logs_index": "clawker-otlp",
            },
            "prometheus": {"endpoint": "0.0.0.0:8889"},
            "debug": {"verbosity": "basic"},
        },
        "service": {
            "pipelines": {
                "logs": {"receivers": ["otlp"], "processors": ["batch"],
                         "exporters": ["opensearch/logs"]},
                "metrics": {"receivers": ["otlp"],
                            "processors": ["transform/metrics", "batch"],
                            "exporters": ["prometheus"]},
                "traces": {"receivers": ["otlp"], "processors": ["batch"],
                           "exporters": ["debug"]},
            }
        },
    }
    import yaml

    return yaml.safe_dump(cfg, sort_keys=True)


def render_prometheus_config(s) -> str:
    import yaml

    return yaml.safe_dump({
        "global": {"scrape_interval": "15s"},
        "scrape_configs": [
            {"job_name": "otel-collector",
             "static_configs": [{"targets": ["otel-collector:8889"]}]},
            {"job_name": "prometheus",
             "static_configs": [{"targets": ["localhost:9090"]}]},
        ],
    }, sort_keys=True)


def render_bootstrap_script() -> str:
    """One-shot curl seeding: index templates for every log index."""
    lines = ["#!/bin/sh", "set -e",
             "until curl -fsS http://opensearch:9200 >/dev/null; do sleep 2; done"]
    for index in LOG_INDICES:
        template = json.dumps({
            "index_patterns": [f"{index}*"],
            "template": {
                "settings": {"number_of_replicas": 0},
                "mappings": {
                    "properties": {
                        "@timestamp": {"type": "date"},
                        "severity": {"type": "keyword"},
                        "service": {"type": "keyword"},
                        "message": {"type": "text"},
                    }
                },
            },
        })
        lines.append(
            "curl -fsS -X PUT -H 'Content-Type: application/json' "
            f"http://opensearch:9200/_index_template/{index} -d '{template}'"
        )
    lines.append("echo 'clawker monitor bootstrap complete'")
    return "\n".join(lines) + "\n"


def render_compose(s) -> str:
    import yaml

    services = {
        "otel-collector": {
            "image": "otel/opentelemetry-collector-contrib:0.103.0",
            "command": ["--config=/etc/otel/config.yaml"],
            "volumes": ["./otel-config.yaml:/etc/otel/config.yaml:ro"],
            "ports": [f"{s.otlp_grpc_port}:{s.otlp_grpc_port}", "4318:4318"],
            "depends_on": ["opensearch"],
            "restart": "unless-stopped",
        },
        "opensearch": {
            "image": "opensearchproject/opensearch:2.15.0",
            "environment": [
                "discovery.type=single-node",
                "DISABLE_SECURITY_PLUGIN=true",
                "OPENSEARCH_JAVA_OPTS=-Xms512m -Xmx512m",
            ],
            "ports": [f"{s.opensearch_port}:9200"],
            "volumes": ["opensearch-data:/usr/share/opensearch/data"],
            "restart": "unless-stopped",
        },
        "opensearch-bootstrap": {
            "image": "curlimages/curl:8.8.0",
            "entrypoint": ["/bin/sh", "/bootstrap.sh"],
            "volumes": ["./bootstrap.sh:/bootstrap.sh:ro"],
            "depends_on": ["opensearch"],
            "restart": "no",
        },
        "opensearch-dashboards": {
            "image": "opensearchproject/opensearch-dashboards:2.15.0",
            "environment": [
                "OPENSEARCH_HOSTS=[\"http://opensearch:9200\"]",
                "DISABLE_SECURITY_DASHBOARDS_PLUGIN=true",
            ],
            "ports": [f"{s.dashboards_port}:5601"],
            "depends_on": ["opensearch"],
            "restart": "unless-stopped",
        },
        "prometheus": {
            "image": "prom/prometheus:v2.53.0",
            "volumes": ["./prometheus.yaml:/etc/prometheus/prometheus.yml:ro"],
            "ports": [f"{s.prometheus_port}:9090"],
            "restart": "unless-stopped",
        },
    }
    return yaml.safe_dump({
        "name": COMPOSE_PROJECT,
        "services": services,
        "volumes": {"opensearch-data": {}},
    }, sort_keys=True)


class MonitorStack:
    def __init__(self, cfg: Config, *, runner=None):
        self.cfg = cfg
        self.dir = cfg.data_dir / "monitor"
        self.runner = runner or self._run_compose

    # ------------------------------------------------------------ render

    def render(self) -> Path:
        s = self.cfg.settings.monitoring
        self.dir.mkdir(parents=True, exist_ok=True)
        (self.dir / "compose.yaml").write_text(render_compose(s))
        (self.dir / "otel-config.yaml").write_text(render_otel_config(s))
        (self.dir / "prometheus.yaml").write_text(render_prometheus_config(s))
        (self.dir / "bootstrap.sh").write_text(render_bootstrap_script())
        return self.dir

    # --------------------------------------------------------- lifecycle

    def _run_compose(self, *args: str) -> subprocess.CompletedProcess:
        cmd = ["docker", "compose", "-p", COMPOSE_PROJECT,
               "-f", str(self.dir / "compose.yaml"), *args]
        try:
            return subprocess.run(cmd, capture_output=True, text=True, timeout=600)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise MonitorError(f"docker compose {' '.join(args)}: {e}") from None

    def up(self) -> None:
        self.render()
        res = self.runner("up", "-d", "--remove-orphans")
        if res.returncode != 0:
            raise MonitorError(f"monitor up failed: {res.stderr.strip()[:500]}")
        log.info("monitor stack up (dashboards :%d, prometheus :%d)",
                 self.cfg.settings.monitoring.dashboards_port,
                 self.cfg.settings.monitoring.prometheus_port)

    def down(self) -> None:
        res = self.runner("down", "--volumes")
        if res.returncode != 0:
            raise MonitorError(f"monitor down failed: {res.stderr.strip()[:500]}")

    def status(self) -> list[dict]:
        res = self.runner("ps", "--format", "json")
        if res.returncode != 0:
            return []
        out = []
        for line in res.stdout.splitlines():
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            # compose <2.21 emits one JSON array; newer emits NDJSON rows
            if isinstance(row, list):
                out.extend(row)
            else:
                out.append(row)
        return out
