"""The opensearch-bootstrap content corpus: templates, pipelines, ISM,
saved objects.

Everything the one-shot ``opensearch-bootstrap`` compose service seeds
into the cluster, generated as pure functions (settings -> JSON trees)
the way the rest of the monitor module renders configs -- pinnable by
golden tests, no template files to drift.

Parity reference: internal/monitor/templates/opensearch-bootstrap/
(component-templates/clawker-common.json, index-templates/*.json,
ingest-pipelines/{envelope,netlogger,envoy}-normalize.json,
ism-policies/clawker-retention.json.tmpl, saved-objects/clawker.ndjson)
-- shapes re-derived for this build's lanes, not copied.
"""

from __future__ import annotations

import json
from pathlib import Path

# Retention tokens a lane may declare (reference: unit.go retention
# validation); mapped to ISM min_index_age.
RETENTIONS = {"default": "7d", "short": "2d", "long": "30d"}


def component_template_common() -> dict:
    """Shared OTLP log-envelope mappings every lane composes."""
    return {
        "template": {
            "settings": {"number_of_shards": 1, "number_of_replicas": 0},
            "mappings": {
                "properties": {
                    "@timestamp": {"type": "date"},
                    "observedTimestamp": {"type": "date"},
                    "severityText": {"type": "keyword"},
                    "severityNumber": {"type": "integer"},
                    "traceId": {"type": "keyword"},
                    "spanId": {"type": "keyword"},
                    "body": {
                        "type": "text",
                        "fields": {"keyword": {"type": "keyword",
                                               "ignore_above": 2048}},
                    },
                    "resource": {
                        "properties": {
                            "service.name": {"type": "keyword"},
                            "service.version": {"type": "keyword"},
                        }
                    },
                }
            },
        }
    }


def _lane_template(index: str, default_pipeline: str | None,
                   attrs: dict) -> dict:
    settings: dict = {}
    if default_pipeline:
        settings["index"] = {"default_pipeline": default_pipeline,
                             "final_pipeline": "envelope-normalize"}
    else:
        settings["index"] = {"final_pipeline": "envelope-normalize"}
    return {
        "index_patterns": [index, f"{index}-*"],
        "priority": 100,
        "composed_of": ["clawker-common"],
        "template": {
            "settings": settings,
            "mappings": {"properties": {"attributes": {"properties": attrs}}},
        },
    }


def _fleet_template(index: str, props: dict) -> dict:
    """Bulk-ingested fleet-telemetry index: top-level document fields
    (no OTLP envelope -- the shipper stamps ``@timestamp`` itself),
    composed on clawker-common for the shared time/trace mappings and
    keeping the envelope-normalize backstop every clawker index
    carries (a no-op for docs that already arrive stamped)."""
    return {
        "index_patterns": [index, f"{index}-*"],
        "priority": 100,
        "composed_of": ["clawker-common"],
        "template": {
            "settings": {"index": {"final_pipeline": "envelope-normalize"}},
            "mappings": {"properties": props},
        },
    }


def index_templates() -> dict[str, dict]:
    """Per-lane index templates for the base log indices."""
    kw = {"type": "keyword"}
    return {
        "clawker-otlp": _lane_template("clawker-otlp", None, {
            "event": {"properties": {"name": kw}},
            "source": kw,
        }),
        "clawker-cli": _lane_template("clawker-cli", None, {
            "subsystem": kw, "event": {"properties": {"name": kw}},
            "project": kw, "agent": kw,
        }),
        "clawkercp": _lane_template("clawkercp", "cp-normalize", {
            "subsystem": kw, "event": {"properties": {"name": kw}},
            "container_id": kw, "agent": kw, "project": kw,
        }),
        "clawker-envoy": _lane_template("clawker-envoy", "envoy-normalize", {
            "authority": kw, "path": kw, "method": kw, "sni": kw,
            "action": kw, "response_code": {"type": "integer"},
            "bytes_sent": {"type": "long"}, "bytes_received": {"type": "long"},
            "duration_ms": {"type": "float"}, "upstream": kw,
        }),
        "clawker-dnsgate": _lane_template("clawker-dnsgate", None, {
            "qname": kw, "qtype": kw, "rcode": kw, "zone": kw,
            "verdict": kw, "container_id": kw,
        }),
        "clawker-ebpf-egress": _lane_template(
            "clawker-ebpf-egress", "netlogger-normalize", {
                "event": {"properties": {"name": kw}},
                "source": kw, "action": kw, "reason": kw,
                "container_id": kw, "agent": kw, "project": kw,
                "cgroup_id": kw, "bpf_ts_ns": kw,
                "dst_ip": {"type": "ip"}, "dst_port": kw,
                "l4_proto": kw, "l4_proto_code": {"type": "integer"},
                "zone_hash": kw, "dst_host": kw,
            }),
        # fleet-telemetry ingestion (monitor/shipper.py,
        # docs/fleet-console.md#ingestion): these docs arrive over the
        # bulk API with top-level fields, not OTLP attributes, so the
        # templates map the document root directly
        "clawker-fleet-metrics": _fleet_template("clawker-fleet-metrics", {
            "type": kw, "source": kw, "metric": kw, "kind": kw,
            "labels": {"type": "object", "dynamic": True},
            "value": {"type": "double"}, "sum": {"type": "double"},
        }),
        "clawker-fleet-events": _fleet_template("clawker-fleet-events", {
            "type": kw, "source": kw, "event": kw, "run": kw,
            "agent": kw, "worker": kw, "seq": {"type": "long"},
            "policy": kw, "tenant": kw, "action": kw,
            "old_state": kw, "new_state": kw, "reason": kw,
            "kind": kw, "z": {"type": "float"},
            "detail": {"type": "text"},
        }),
        "clawker-fleet-spans": _fleet_template("clawker-fleet-spans", {
            "type": kw, "source": kw, "run": kw, "trace_id": kw,
            "span_id": kw, "parent_id": kw, "name": kw, "agent": kw,
            "worker": kw, "status": kw,
            "t_start": {"type": "double"}, "t_end": {"type": "double"},
            "wall_ms": {"type": "float"},
            "attrs": {"type": "object", "dynamic": True},
        }),
    }


def _with_failure_markers(description: str, processors: list[dict]) -> dict:
    """Every pipeline marks (never drops) documents it could not process
    -- a normalization bug must not silently lose telemetry."""
    return {
        "description": description,
        "processors": processors,
        "on_failure": [
            {"set": {"field": "_normalize_failed", "value": True}},
            {"set": {"field": "_normalize_failed_pipeline",
                     "value": "{{ _ingest.on_failure_pipeline }}"}},
            {"set": {"field": "_normalize_failed_message",
                     "value": "{{ _ingest.on_failure_message }}"}},
        ],
    }


def ingest_pipelines() -> dict[str, dict]:
    return {
        "envelope-normalize": _with_failure_markers(
            "final pipeline for every clawker lane: backstop @timestamp "
            "from observedTimestamp so time-based views never lose docs",
            [{"set": {"field": "@timestamp",
                      "copy_from": "observedTimestamp",
                      "if": "ctx['@timestamp'] == null && ctx.observedTimestamp != null"}}],
        ),
        "netlogger-normalize": _with_failure_markers(
            "stringify bpf_ts_ns: an opaque BPF monotonic timestamp used "
            "for dedup/ordering, never numeric math -- keyword storage "
            "stops the UI rendering it with thousands separators",
            [{"convert": {"field": "attributes.bpf_ts_ns", "type": "string",
                          "ignore_missing": True}},
             {"convert": {"field": "attributes.cgroup_id", "type": "string",
                          "ignore_missing": True}}],
        ),
        "envoy-normalize": _with_failure_markers(
            "proxy access-log normalization: numeric response_code and "
            "duration for range filters",
            [{"convert": {"field": "attributes.response_code",
                          "type": "integer", "ignore_missing": True}},
             {"convert": {"field": "attributes.duration_ms", "type": "float",
                          "ignore_missing": True}}],
        ),
        "cp-normalize": _with_failure_markers(
            "control-plane log normalization: stringify container ids",
            [{"convert": {"field": "attributes.container_id",
                          "type": "string", "ignore_missing": True}}],
        ),
    }


def ism_policy(index_patterns: list[str], *, age: str = "7d") -> dict:
    """Retention: hot -> delete after ``age``.  A throwaway monitoring
    stack keeps short retention by design."""
    return {
        "policy": {
            "description": "Default retention for clawker observability "
                           "indices (throwaway stack, short by design).",
            "default_state": "hot",
            "states": [
                {"name": "hot", "actions": [], "transitions": [
                    {"state_name": "delete",
                     "conditions": {"min_index_age": age}}]},
                {"name": "delete", "actions": [{"delete": {}}],
                 "transitions": []},
            ],
            "ism_template": [
                {"index_patterns": index_patterns, "priority": 100}],
        }
    }


# ----------------------------------------------------------- saved objects

def _index_pattern(pid: str, title: str) -> dict:
    return {"id": pid, "type": "index-pattern",
            "attributes": {"title": title, "timeFieldName": "@timestamp"}}


def _metric_vis(vid: str, title: str, index_pattern: str, agg: dict) -> dict:
    vis_state = {"title": title, "type": "metric",
                 "aggs": [{"id": "1", "enabled": True, "schema": "metric",
                           **agg}],
                 "params": {"addTooltip": True, "metric": {
                     "metricColorMode": "None",
                     "style": {"fontSize": 36}}}}
    return {
        "id": vid, "type": "visualization",
        "attributes": {
            "title": title,
            "visState": json.dumps(vis_state),
            "uiStateJSON": "{}",
            "kibanaSavedObjectMeta": {"searchSourceJSON": json.dumps(
                {"query": {"query": "", "language": "kuery"}, "filter": [],
                 "indexRefName": "kibanaSavedObjectMeta.searchSourceJSON.index"})},
        },
        "references": [{"name": "kibanaSavedObjectMeta.searchSourceJSON.index",
                        "type": "index-pattern", "id": index_pattern}],
    }


def _histogram_vis(vid: str, title: str, index_pattern: str,
                   split_field: str) -> dict:
    vis_state = {
        "title": title, "type": "histogram",
        "aggs": [
            {"id": "1", "enabled": True, "schema": "metric",
             "type": "count", "params": {}},
            {"id": "2", "enabled": True, "schema": "segment",
             "type": "date_histogram",
             "params": {"field": "@timestamp", "interval": "auto"}},
            {"id": "3", "enabled": True, "schema": "group", "type": "terms",
             "params": {"field": split_field, "size": 8}},
        ],
        "params": {"addTooltip": True, "addLegend": True, "type": "histogram"},
    }
    out = _metric_vis(vid, title, index_pattern, {"type": "count", "params": {}})
    out["attributes"]["visState"] = json.dumps(vis_state)
    return out


def _dashboard(did: str, title: str, panel_ids: list[str]) -> dict:
    panels = []
    refs = []
    for i, pid in enumerate(panel_ids):
        name = f"panel_{i}"
        panels.append({
            "panelIndex": str(i), "panelRefName": name, "version": "2.15.0",
            "gridData": {"x": (i % 3) * 16, "y": (i // 3) * 12,
                         "w": 16, "h": 12, "i": str(i)},
            "embeddableConfig": {},
        })
        refs.append({"name": name, "type": "visualization", "id": pid})
    return {
        "id": did, "type": "dashboard",
        "attributes": {
            "title": title,
            "panelsJSON": json.dumps(panels),
            "optionsJSON": json.dumps({"useMargins": True}),
            "timeRestore": False,
            "kibanaSavedObjectMeta": {"searchSourceJSON": json.dumps(
                {"query": {"query": "", "language": "kuery"}, "filter": []})},
        },
        "references": refs,
    }


def saved_objects() -> list[dict]:
    """Base workspace: index patterns for every lane + the seeded egress
    dashboard (deny/allow over time, top denied zones, top talkers)."""
    objs = [
        _index_pattern("clawker-ebpf-egress", "clawker-ebpf-egress"),
        _index_pattern("clawker-envoy", "clawker-envoy"),
        _index_pattern("clawker-dnsgate", "clawker-dnsgate"),
        _index_pattern("clawkercp", "clawkercp"),
        _index_pattern("clawker-cli", "clawker-cli"),
        _metric_vis("clawker-egress-denies", "Egress denies",
                    "clawker-ebpf-egress",
                    {"type": "count", "params": {}}),
        _histogram_vis("clawker-egress-by-action", "Egress verdicts over time",
                       "clawker-ebpf-egress", "attributes.action"),
        _histogram_vis("clawker-egress-by-zone", "Denied zones over time",
                       "clawker-ebpf-egress", "attributes.dst_host"),
        _histogram_vis("clawker-envoy-by-code", "Proxy responses over time",
                       "clawker-envoy", "attributes.response_code"),
        _histogram_vis("clawker-dns-by-verdict", "DNS verdicts over time",
                       "clawker-dnsgate", "attributes.verdict"),
    ]
    objs.append(_dashboard(
        "clawker-egress", "Clawker Egress",
        ["clawker-egress-denies", "clawker-egress-by-action",
         "clawker-egress-by-zone", "clawker-envoy-by-code",
         "clawker-dns-by-verdict"]))
    return objs


def to_ndjson(objs: list[dict]) -> str:
    return "\n".join(json.dumps(o, sort_keys=True) for o in objs) + "\n"


# ------------------------------------------------------------ tree writer

def write_bootstrap_tree(root: Path) -> list[Path]:
    """Materialize the base corpus as the opensearch-bootstrap overlay
    tree (the same layout units overlay into; the bootstrap script's
    directory loops apply both unmodified)."""
    written: list[Path] = []

    def put(rel: str, body: str) -> None:
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(body)
        written.append(p)

    put("component-templates/clawker-common.json",
        json.dumps(component_template_common(), indent=1, sort_keys=True))
    for name, tmpl in index_templates().items():
        put(f"index-templates/{name}.json",
            json.dumps(tmpl, indent=1, sort_keys=True))
    for name, pipe in ingest_pipelines().items():
        put(f"ingest-pipelines/{name}.json",
            json.dumps(pipe, indent=1, sort_keys=True))
    patterns = sorted({p for t in index_templates().values()
                       for p in t["index_patterns"]})
    put("ism-policies/clawker-retention.json",
        json.dumps(ism_policy(patterns), indent=1, sort_keys=True))
    put("saved-objects/clawker.ndjson", to_ndjson(saved_objects()))
    return written
