"""Disk-pressure degradation ladder (docs/durability.md#ladder).

Production disks fill up.  When they do, the WAL chain must lose the
RIGHT data: post-mortem niceties first, crash evidence last.  The
:class:`DiskPressureMonitor` watches free space on the logs filesystem
(one statvfs per ``check_interval_s``, ticked from the scheduler run
loop and the loopd supervisor) and walks a two-watermark ladder:

- **soft watermark**: non-durable streams shed, in priority order --
  flight spans first (pure post-mortem), then shipper batches (the
  index re-ingests from files later), then sentinel state (rebuilt
  from live observation).  Streams stay functional, they just stop
  consuming disk; every shed record moves ``storage_shed_total``.
- **hard watermark**: emergency retention GC -- journals and flight
  files of DONE runs past the retention window are deleted (they
  otherwise live forever), reclaiming space BEFORE a durable journal
  append is allowed to fail.

The monitor never raises and never blocks the hot path: streams
consult :meth:`is_shedding` (a set lookup) and the statvfs happens at
tick cadence only.
"""

from __future__ import annotations

import os
import time
from pathlib import Path

from .. import telemetry
from .events import StorageFaultEvent

# the shed ladder, least-precious stream first; stream i sheds when
# free space falls below soft - i * (soft-hard)/len (evenly spaced
# rungs between the watermarks)
SHED_LADDER = ("flight", "shipper", "sentinel")

_SHED = telemetry.counter(
    "storage_shed_total",
    "records/batches shed under disk pressure, by stream",
    labels=("stream",))
_LEVEL = telemetry.gauge(
    "storage_pressure_level",
    "disk-pressure ladder level (0 ok, 1 soft: shedding, 2 hard: GC)")
_FREE = telemetry.gauge(
    "storage_disk_free_ratio",
    "free-space fraction of the logs filesystem at the last tick")
_GC_REMOVED = telemetry.counter(
    "storage_gc_removed_total",
    "done-run journal/flight file sets deleted by the emergency GC")
_GC_FREED = telemetry.counter(
    "storage_gc_freed_bytes_total",
    "bytes reclaimed by the emergency retention GC")

_GC_COOLDOWN_S = 30.0           # don't re-run the GC every tick at hard


def note_shed(stream: str, n: int = 1) -> None:
    """Count records a stream dropped under pressure (the stream calls
    this at its own append site -- only it knows a record was due)."""
    _SHED.labels(stream).inc(n)


class DiskPressureMonitor:
    """statvfs watermark monitor driving the shed ladder + emergency GC.

    ``gc`` is the hard-watermark reclaim callback (typically
    ``loop.journal.retention_gc`` partial-applied to the logs dir); it
    returns ``{"removed", "freed_bytes", ...}``.  ``on_event`` receives
    a :class:`StorageFaultEvent` per ladder transition and GC pass --
    the scheduler/loopd forward it onto their event bus.  Construction
    and ticking never raise: an unstatable filesystem reads as
    "no pressure verdict" and the ladder holds its last state.
    """

    def __init__(self, path: Path, *, soft_free_pct: float = 10.0,
                 hard_free_pct: float = 3.0, check_interval_s: float = 5.0,
                 gc=None, on_event=None, clock=time.monotonic,
                 statvfs=os.statvfs):
        self.path = Path(path)
        self.soft = max(0.0, float(soft_free_pct)) / 100.0
        self.hard = min(max(0.0, float(hard_free_pct)) / 100.0, self.soft)
        self.check_interval_s = max(0.05, float(check_interval_s))
        self.gc = gc
        self.on_event = on_event
        self._clock = clock
        self._statvfs = statvfs
        self.level = 0              # 0 ok | 1 soft | 2 hard
        self.shedding: frozenset[str] = frozenset()
        self.free_ratio: float | None = None
        self.gc_removed = 0
        self.gc_freed_bytes = 0
        self._next_check = 0.0
        self._gc_after = 0.0

    # ------------------------------------------------------------- queries

    def is_shedding(self, stream: str) -> bool:
        return stream in self.shedding

    def summary(self) -> dict:
        return {"level": self.level, "free_ratio": self.free_ratio,
                "shedding": sorted(self.shedding),
                "gc_removed": self.gc_removed,
                "gc_freed_bytes": self.gc_freed_bytes}

    # ---------------------------------------------------------------- tick

    def _emit(self, ev: StorageFaultEvent) -> None:
        if self.on_event is not None:
            try:
                self.on_event(ev)
            except Exception:   # noqa: BLE001 -- surfacing pressure must
                pass            # never become the pressure

    def _free_fraction(self) -> float | None:
        try:
            st = self._statvfs(str(self.path))
            total = st.f_blocks * st.f_frsize
            if total <= 0:
                return None
            return (st.f_bavail * st.f_frsize) / total
        except (OSError, ValueError, ZeroDivisionError):
            return None

    def tick(self, now: float | None = None) -> bool:
        """One ladder evaluation (rate-limited to the check interval).
        Returns True when the shed set or level changed."""
        now = self._clock() if now is None else now
        if now < self._next_check:
            return False
        self._next_check = now + self.check_interval_s
        free = self._free_fraction()
        if free is None:
            return False        # no verdict: hold the last state
        self.free_ratio = free
        _FREE.set(free)
        shed: set[str] = set()
        span = max(self.soft - self.hard, 1e-9)
        for i, stream in enumerate(SHED_LADDER):
            rung = self.soft - (i * span / len(SHED_LADDER))
            if free < rung:
                shed.add(stream)
        level = 0 if free >= self.soft else (1 if free >= self.hard else 2)
        changed = (level != self.level
                   or frozenset(shed) != self.shedding)
        if changed:
            self._emit(StorageFaultEvent(
                "pressure", "shed" if shed else "ok",
                error=(f"free={free:.1%} level={level} "
                       f"shedding={','.join(sorted(shed)) or '-'}")))
        self.level = level
        self.shedding = frozenset(shed)
        _LEVEL.set(level)
        if level >= 2 and self.gc is not None and now >= self._gc_after:
            self._gc_after = now + _GC_COOLDOWN_S
            try:
                out = self.gc() or {}
            except Exception:   # noqa: BLE001 -- a GC crash must never
                out = {}        # take the scheduler tick with it
            removed = int(out.get("removed", 0))
            freed = int(out.get("freed_bytes", 0))
            self.gc_removed += removed
            self.gc_freed_bytes += freed
            if removed:
                _GC_REMOVED.inc(removed)
            if freed:
                _GC_FREED.inc(freed)
            self._emit(StorageFaultEvent(
                "pressure", "gc", error=(f"removed={removed} "
                                         f"freed_bytes={freed}")))
        return changed
