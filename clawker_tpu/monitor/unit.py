"""Monitoring units: pluggable per-harness observability overlays.

A unit is a directory with a ``monitoring.yaml`` manifest plus artifact
subdirectories mirroring the opensearch-bootstrap tree, so materializing
a unit is a plain overlay copy and the bootstrap script's directory
loops apply unit artifacts unmodified.

Every validation failure is a named error at this front door -- never a
silent bootstrap-time skip.

Parity reference: internal/monitor/unit.go:48 (MonitoringUnit, lane/
metric/tree validation, index-name grammar) -- semantics re-derived.
"""

from __future__ import annotations

import hashlib
import json
import re
import shutil
from dataclasses import dataclass, field
from pathlib import Path

import yaml

from ..errors import ClawkerError
from .corpus import RETENTIONS

MANIFEST_FILE = "monitoring.yaml"

# Artifact subdirectories a unit may ship (the opensearch-bootstrap tree).
ARTIFACT_DIRS = (
    "index-templates",
    "ingest-pipelines",
    "component-templates",
    "ism-policies",
    "saved-objects",
)

# Index-name grammar a unit lane may declare: lowercase letters, digits,
# internal hyphens.  Deliberately a subset of what OpenSearch accepts --
# the quote/backslash-free charset makes injection into bootstrap curl
# commands unspellable by construction.  Service names share the rule.
_INDEX_RE = re.compile(r"^[a-z0-9][a-z0-9-]{0,62}$")
_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9-]{0,62}$")

# Base lanes are cluster infrastructure: a unit may not claim them.
# "default" and "traces" are reserved too: lane exporters are named
# opensearch/{index} in the collector config, and those two names are
# the fixed default-logs and spans exporters (monitor/stack.py) -- a
# lane by either name would silently clobber them.
RESERVED_INDICES = frozenset({
    "clawker-otlp", "clawker-cli", "clawkercp", "clawker-envoy",
    "clawker-dnsgate", "clawker-ebpf-egress", "default", "traces",
})


class UnitError(ClawkerError):
    pass


@dataclass
class LogLane:
    """One log lane: an index the unit owns + the OTLP service.name
    values routed into it."""

    index: str = ""
    service_names: list[str] = field(default_factory=list)
    retention: str = "default"


@dataclass
class UnitManifest:
    name: str = ""
    description: str = ""
    logs: list[LogLane] = field(default_factory=list)


@dataclass
class MonitoringUnit:
    name: str
    root: Path
    manifest: UnitManifest

    def artifact_files(self) -> list[Path]:
        out: list[Path] = []
        for sub in ARTIFACT_DIRS:
            d = self.root / sub
            if d.is_dir():
                out.extend(sorted(p for p in d.rglob("*") if p.is_file()))
        return out

    def content_hash(self) -> str:
        """Stable hash over manifest + every artifact byte (ledger
        identity: same hash == same content, regardless of source)."""
        h = hashlib.sha256()
        h.update((self.root / MANIFEST_FILE).read_bytes())
        for p in self.artifact_files():
            h.update(str(p.relative_to(self.root)).encode())
            h.update(p.read_bytes())
        return h.hexdigest()[:16]


def load_unit(name: str, root: Path) -> MonitoringUnit:
    """Load + validate a unit directory.  Fails loud on: bad names, bad
    index/service grammar, reserved indices, unknown artifact dirs,
    unparseable JSON artifacts."""
    root = Path(root)
    if not _NAME_RE.fullmatch(name):
        raise UnitError(f"monitoring unit name {name!r} is not a valid key")
    mpath = root / MANIFEST_FILE
    if not mpath.is_file():
        raise UnitError(f"monitoring unit {name!r}: no {MANIFEST_FILE} in {root}")
    try:
        raw = yaml.safe_load(mpath.read_text()) or {}
    except yaml.YAMLError as e:
        raise UnitError(f"monitoring unit {name!r}: parse {MANIFEST_FILE}: {e}")
    lanes_raw = raw.get("logs") or []
    for l in lanes_raw:
        if not isinstance(l, dict):
            raise UnitError(
                f"monitoring unit {name!r}: each logs entry must be a "
                f"mapping with index/service_names, got {l!r}")
    manifest = UnitManifest(
        name=str(raw.get("name") or name),
        description=str(raw.get("description") or ""),
        logs=[LogLane(index=str(l.get("index") or ""),
                      service_names=[str(s) for s in l.get("service_names") or []],
                      retention=str(l.get("retention") or "default"))
              for l in lanes_raw],
    )
    if manifest.name != name:
        raise UnitError(
            f"monitoring unit {name!r}: manifest names itself "
            f"{manifest.name!r} (registry key and manifest must agree)")
    _validate_lanes(name, manifest.logs)
    _validate_tree(name, root)
    return MonitoringUnit(name=name, root=root, manifest=manifest)


def _validate_lanes(name: str, lanes: list[LogLane]) -> None:
    if not lanes:
        raise UnitError(
            f"monitoring unit {name!r}: logs must declare at least one lane")
    seen_index: set[str] = set()
    seen_service: set[str] = set()
    for lane in lanes:
        if not _INDEX_RE.fullmatch(lane.index):
            raise UnitError(
                f"monitoring unit {name!r}: index {lane.index!r} is not a "
                "valid OpenSearch index name (lowercase/digits/hyphens)")
        if lane.index in RESERVED_INDICES:
            raise UnitError(
                f"monitoring unit {name!r}: index {lane.index!r} is a "
                "reserved clawker lane")
        if lane.index in seen_index:
            raise UnitError(
                f"monitoring unit {name!r}: duplicate index {lane.index!r}")
        seen_index.add(lane.index)
        if not lane.service_names:
            raise UnitError(
                f"monitoring unit {name!r}: lane {lane.index!r} needs at "
                "least one service name")
        for svc in lane.service_names:
            if not _INDEX_RE.fullmatch(svc):
                raise UnitError(
                    f"monitoring unit {name!r}: service name {svc!r} is not "
                    "valid (lowercase/digits/hyphens)")
            if svc in seen_service:
                raise UnitError(
                    f"monitoring unit {name!r}: duplicate service {svc!r}")
            seen_service.add(svc)
        if lane.retention not in RETENTIONS:
            raise UnitError(
                f"monitoring unit {name!r}: unknown retention "
                f"{lane.retention!r} (want one of {sorted(RETENTIONS)})")


def _validate_tree(name: str, root: Path) -> None:
    for entry in root.iterdir():
        if entry.name == MANIFEST_FILE or entry.name.startswith("."):
            continue
        if entry.is_dir():
            if entry.name not in ARTIFACT_DIRS:
                raise UnitError(
                    f"monitoring unit {name!r}: unknown artifact dir "
                    f"{entry.name!r} (want one of {ARTIFACT_DIRS})")
            for p in entry.rglob("*.json"):
                try:
                    json.loads(p.read_text())
                except (OSError, json.JSONDecodeError) as e:
                    raise UnitError(
                        f"monitoring unit {name!r}: bad artifact "
                        f"{p.relative_to(root)}: {e}")
        else:
            raise UnitError(
                f"monitoring unit {name!r}: stray file {entry.name!r} "
                "(artifacts live under the known subdirectories)")


def materialize(unit: MonitoringUnit, bootstrap_root: Path) -> list[Path]:
    """Overlay the unit's artifacts into the bootstrap tree.

    A destination that already exists with DIFFERENT content (base
    corpus, or another unit's artifact) is a named refusal, never a
    silent clobber: a unit shipping ingest-pipelines/envelope-normalize
    .json would otherwise replace the final pipeline shared by every
    lane, cluster-wide."""
    written: list[Path] = []
    for src in unit.artifact_files():
        rel = src.relative_to(unit.root)
        dst = bootstrap_root / rel
        if dst.exists() and dst.read_bytes() != src.read_bytes():
            raise UnitError(
                f"monitoring unit {unit.name!r}: artifact {rel} collides "
                "with an already-materialized file of different content "
                "(base corpus artifacts and other units' files cannot be "
                "overridden)")
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(src, dst)
        written.append(dst)
    return written


def discover_units(roots: list[Path]) -> dict[str, MonitoringUnit]:
    """Load every unit directory under the given roots (embedded floor
    first, then loose extension dirs -- later roots win on name)."""
    out: dict[str, MonitoringUnit] = {}
    for root in roots:
        root = Path(root)
        if not root.is_dir():
            continue
        for entry in sorted(root.iterdir()):
            if entry.is_dir() and (entry / MANIFEST_FILE).is_file():
                out[entry.name] = load_unit(entry.name, entry)
    return out
