"""Fleet-telemetry bulk ingestion: registry + bus + spans -> OpenSearch.

The missing half of BASELINE config #4: the compose stack (stack.py)
and its seeded index corpus (corpus.py) existed, but fleet telemetry
never reached the index -- metrics lived on the scrape port, typed
events on the in-process bus, spans in the per-run flight recorder.
:class:`TelemetryShipper` closes the loop: it batches three doc types
into the OpenSearch bulk API --

- ``clawker-fleet-metrics``: :class:`~clawker_tpu.telemetry.registry.
  MetricsRegistry` snapshots (one doc per series sample);
- ``clawker-fleet-events``: typed bus events (placement decisions,
  worker health transitions, anomaly flags), parsed back into their
  structured payloads so the index gets fields, not detail strings;
- ``clawker-fleet-spans``: completed flight-recorder span records.

**Backpressure contract** (docs/fleet-console.md#degrade-matrix): the
shipper may lose telemetry, it may never delay the system it observes.
``ingest``/``bus_tap``/``span_sink`` are O(append) under one lock and
never touch the network; all sink I/O rides the pump thread.  At most
``max_batches`` sealed batches wait in memory -- when the index is slow
or down the OLDEST batches drop first (counted in
``monitor_ingest_dropped_total``), so a recovered index sees the most
recent fleet state, and a wedged one bounds memory instead of the bus.
The journal and flight recorder stay the durable history; the index is
a live view, exactly like the loopd attach stream.

loopd hosts one shipper for its daemon lifetime (every hosted run
attaches at construction); in-process runs attach via
``clawker loop --ship-telemetry``.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque

from .. import logsetup, telemetry
from .events import ANOMALY_FLAG, PLACEMENT_DECISION, TRACE_SPAN, WORKER_HEALTH

log = logsetup.get("monitor.shipper")

FLEET_METRICS_INDEX = "clawker-fleet-metrics"
FLEET_EVENTS_INDEX = "clawker-fleet-events"
FLEET_SPANS_INDEX = "clawker-fleet-spans"
FLEET_INDICES = (FLEET_METRICS_INDEX, FLEET_EVENTS_INDEX, FLEET_SPANS_INDEX)

# bus event kinds worth indexing, and the doc "type" each maps to
_TYPED_EVENTS = {
    PLACEMENT_DECISION: "placement",
    WORKER_HEALTH: "health",
    ANOMALY_FLAG: "anomaly",
}

_DOCS = telemetry.counter(
    "monitor_ingest_docs_total",
    "Fleet-telemetry docs accepted into shipper batches",
    labels=("type",))
_DROPPED = telemetry.counter(
    "monitor_ingest_dropped_total",
    "Fleet-telemetry docs dropped with their batch under backpressure "
    "(slow/down index, bounded buffer)")
_BATCHES = telemetry.counter(
    "monitor_ingest_batches_total",
    "Bulk batches flushed to the monitor stack", labels=("result",))
_LAG = telemetry.histogram(
    "monitor_ingest_lag_seconds",
    "Batch seal -> bulk-ack latency (how stale the index view runs)")


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ts)) + (
        ".%03dZ" % int((ts % 1) * 1000))


def bulk_payload(items: list[tuple[str, dict]]) -> bytes:
    """(index, doc) pairs -> the ndjson body the _bulk API takes."""
    lines = []
    for index, doc in items:
        lines.append(json.dumps({"index": {"_index": index}},
                                separators=(",", ":")))
        lines.append(json.dumps(doc, separators=(",", ":"), default=str))
    return ("\n".join(lines) + "\n").encode()


class BulkSink:
    """POST ``/_bulk`` against a real OpenSearch endpoint.

    The shipper's sink contract: ``bulk(payload) -> bool``, never
    raises, bounded by ``timeout_s`` -- a hung index must cost the pump
    thread one deadline, not forever."""

    def __init__(self, url: str, *, timeout_s: float = 5.0):
        self.url = url.rstrip("/") + "/_bulk"
        self.timeout_s = timeout_s

    def bulk(self, payload: bytes) -> bool:
        req = urllib.request.Request(
            self.url, data=payload,
            headers={"Content-Type": "application/x-ndjson"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                if r.status >= 300:
                    return False
                body = json.loads(r.read() or b"{}")
                return not body.get("errors", False)
        except (OSError, ValueError, urllib.error.URLError) as e:
            log.debug("bulk POST failed: %s", e)
            return False


def resolve_sink(cfg) -> BulkSink:
    """The configured bulk sink: settings ``monitoring.shipper.url``
    override or the local stack's opensearch port."""
    ms = cfg.settings.monitoring
    url = ms.shipper.url or f"http://127.0.0.1:{ms.opensearch_port}"
    return BulkSink(url, timeout_s=ms.shipper.timeout_s)


# ------------------------------------------------------------ doc builders


def metric_docs(snapshot: list[dict], *, source: str = "",
                ts: float | None = None) -> list[dict]:
    """Registry snapshot rows -> one doc per series sample.  Histogram
    buckets stay nested (the index template maps them as an object);
    ``value`` is the headline scalar either way."""
    stamp = _iso(ts if ts is not None else time.time())
    out = []
    for row in snapshot:
        doc = {
            "@timestamp": stamp, "type": "metric", "source": source,
            "metric": row["metric"], "kind": row["kind"],
            "labels": dict(row.get("labels") or {}),
            "value": float(row.get("value", 0.0)),
        }
        if "sum" in row:
            doc["sum"] = float(row["sum"])
        out.append(doc)
    return out


def event_doc(rec, *, run: str = "", source: str = "",
              ts: float | None = None) -> dict | None:
    """Typed EventRecord -> structured doc, or None for kinds the index
    does not carry (lifecycle noise, trace.span -- spans arrive
    structured via :meth:`TelemetryShipper.span_sink`)."""
    kind = _TYPED_EVENTS.get(rec.event)
    if kind is None:
        return None
    doc = {
        "@timestamp": _iso(ts if ts is not None else time.time()),
        "type": kind, "event": rec.event, "run": run, "source": source,
        "agent": rec.agent, "seq": rec.seq, "detail": rec.detail,
    }
    # re-hydrate the typed payload: the bus carries compact detail
    # strings so every sink renders them; the index wants fields
    from .events import AnomalyFlagEvent, PlacementEvent, WorkerHealthEvent

    if rec.event == PLACEMENT_DECISION:
        ev = PlacementEvent.parse(rec.agent, rec.detail)
        doc.update({"worker": ev.worker, "policy": ev.policy,
                    "tenant": ev.tenant, "action": ev.action,
                    "reason": ev.reason})
    elif rec.event == WORKER_HEALTH:
        ev = WorkerHealthEvent.parse(rec.agent, rec.detail)
        doc.update({"worker": ev.worker, "old_state": ev.old_state,
                    "new_state": ev.new_state, "reason": ev.reason})
    elif rec.event == ANOMALY_FLAG:
        ev = AnomalyFlagEvent.parse(rec.agent, rec.detail)
        doc.update({"worker": ev.worker, "z": round(ev.z, 3),
                    "kind": ev.kind})
    return doc


def span_doc(rec, *, run: str = "", source: str = "") -> dict:
    doc = rec.to_json()
    doc.pop("kind", None)
    doc.update({
        "@timestamp": _iso(rec.t_end),
        "type": "span", "run": run or rec.trace_id, "source": source,
        "wall_ms": round(rec.wall_s * 1000, 3),
    })
    return doc


# ---------------------------------------------------------------- shipper


class TelemetryShipper:
    """Bounded-buffer bulk ingester (see module docstring).

    ``sink`` is anything with ``bulk(payload: bytes) -> bool``
    (:class:`BulkSink` in production, ``testenv.FakeBulkIndex`` in
    tests/bench).  One shipper serves many runs: loopd constructs one
    and every hosted scheduler attaches; taps and span sinks are
    per-run closures so docs carry their run id."""

    def __init__(self, sink, *, registry=None, interval_s: float = 2.0,
                 batch_docs: int = 256, max_batches: int = 64,
                 source: str = ""):
        self.sink = sink
        self.registry = registry if registry is not None else telemetry.REGISTRY
        self.interval_s = interval_s
        self.batch_docs = max(1, int(batch_docs))
        self.max_batches = max(1, int(max_batches))
        self.source = source
        self._lock = threading.Lock()
        self._open: list[tuple[str, dict]] = []
        # sealed batches awaiting flush: (seal_monotonic, items)
        self._pending: deque[tuple[float, list[tuple[str, dict]]]] = deque()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # plain tallies mirrored into the registry counters: stats()
        # must work against a reset/shared registry (tests, loopd
        # status RPC) without scraping exposition text
        self.ingested = 0
        self.dropped = 0
        self.flushed_batches = 0
        self.flushed_docs = 0
        self.failed_flushes = 0

    @classmethod
    def from_config(cls, cfg, *, sink=None, source: str = ""
                    ) -> "TelemetryShipper":
        ss = cfg.settings.monitoring.shipper
        return cls(sink if sink is not None else resolve_sink(cfg),
                   interval_s=ss.interval_s, batch_docs=ss.batch_docs,
                   max_batches=ss.max_batches, source=source)

    # ------------------------------------------------------------- intake

    def ingest(self, index: str, doc: dict, *, doc_type: str = "doc") -> None:
        """Accept one doc; never blocks, never raises.  Seals the open
        batch at ``batch_docs`` and applies drop-oldest past
        ``max_batches`` -- backpressure lands HERE, on the intake side,
        so a wedged sink bounds memory without touching callers."""
        dropped = 0
        with self._lock:
            self._open.append((index, doc))
            self.ingested += 1
            if len(self._open) >= self.batch_docs:
                dropped = self._seal_locked()
        _DOCS.labels(doc_type).inc()
        if dropped:
            _DROPPED.inc(dropped)

    def _seal_locked(self) -> int:
        """Move the open batch to pending; returns docs dropped off the
        oldest end to hold ``max_batches``.  Caller holds the lock."""
        if not self._open:
            return 0
        self._pending.append((time.monotonic(), self._open))
        self._open = []
        dropped = 0
        while len(self._pending) > self.max_batches:
            _, lost = self._pending.popleft()
            dropped += len(lost)
            self.dropped += len(lost)
        return dropped

    # per-run adapters ----------------------------------------------------

    def bus_tap_for(self, run_id: str):
        """An EventBus tap shipping this run's typed events.  Runs on
        the emitting thread: O(parse + append), no I/O."""

        def tap(rec) -> None:
            if rec.event == TRACE_SPAN:
                return      # spans arrive structured via span_sink_for
            doc = event_doc(rec, run=run_id, source=self.source)
            if doc is not None:
                self.ingest(FLEET_EVENTS_INDEX, doc, doc_type="event")

        return tap

    def span_sink_for(self, run_id: str):
        def sink(rec) -> None:
            self.ingest(FLEET_SPANS_INDEX,
                        span_doc(rec, run=run_id, source=self.source),
                        doc_type="span")

        return sink

    # -------------------------------------------------------------- pump

    def snapshot_once(self) -> int:
        """One registry snapshot into the metrics index; returns docs."""
        docs = metric_docs(self.registry.snapshot(), source=self.source)
        for doc in docs:
            self.ingest(FLEET_METRICS_INDEX, doc, doc_type="metric")
        return len(docs)

    def flush_once(self, *, budget_s: float | None = None) -> int:
        """Drain pending batches to the sink within ``budget_s``;
        returns batches flushed.  A failed POST requeues the batch at
        the FRONT (it is still the oldest) and stops -- the next tick
        retries, and intake's drop-oldest reclaims the space if the
        outage outlasts the buffer."""
        deadline = (time.monotonic() + budget_s) if budget_s else None
        n = 0
        with self._lock:
            dropped = self._seal_locked()
        if dropped:
            _DROPPED.inc(dropped)
        while True:
            with self._lock:
                if not self._pending:
                    return n
                sealed_at, items = self._pending.popleft()
            ok = False
            try:
                ok = bool(self.sink.bulk(bulk_payload(items)))
            except Exception as e:  # noqa: BLE001 -- sink contract: degrade
                log.debug("shipper sink raised: %s", e)
            if not ok:
                self.failed_flushes += 1
                _BATCHES.labels("error").inc()
                with self._lock:
                    if len(self._pending) >= self.max_batches:
                        # the buffer filled while we were stuck in the
                        # POST: this batch IS the oldest -- drop it
                        self.dropped += len(items)
                        _DROPPED.inc(len(items))
                    else:
                        self._pending.appendleft((sealed_at, items))
                return n
            n += 1
            self.flushed_batches += 1
            self.flushed_docs += len(items)
            _BATCHES.labels("ok").inc()
            _LAG.observe(max(0.0, time.monotonic() - sealed_at))
            if deadline is not None and time.monotonic() >= deadline:
                return n

    def _pump(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.snapshot_once()
            self.flush_once(budget_s=self.interval_s)

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "TelemetryShipper":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._pump, daemon=True,
                                            name="monitor-shipper")
            self._thread.start()
        return self

    def _retire_pump(self, timeout: float) -> bool:
        """Signal the pump and wait for it to exit; False when it is
        still wedged inside the sink past ``timeout``.  A wedged pump
        keeps ``_thread`` set: callers must not run their own
        snapshot/flush concurrently with it (unsynchronized counter
        updates), and a later start() must not spawn a second pump."""
        self._stop.set()
        t = self._thread
        if t is None:
            return True
        t.join(timeout=timeout)
        if t.is_alive():
            return False
        self._thread = None
        return True

    def stop(self) -> None:
        """Final snapshot + one bounded flush attempt: a short run's
        telemetry still lands when the index is up, and a down index
        costs one sink deadline, never a hang.  A pump still wedged in
        the sink past the join deadline skips the final flush -- racing
        it would corrupt the drop/flush accounting."""
        if not self._retire_pump(5.0):
            return
        self.snapshot_once()
        self.flush_once(budget_s=self.interval_s)

    def kill(self) -> bool:
        """Stop the pump with NO final snapshot/flush (the simulated-
        SIGKILL path chaos and loopd.kill() exercise): a killed process
        ships nothing on the way down.  Returns False when the pump is
        still wedged in the sink -- the caller must not touch the
        shipper's flush path until it drains."""
        return self._retire_pump(2.0)

    # ------------------------------------------------------------- status

    def stats(self) -> dict:
        with self._lock:
            pending = len(self._pending)
            pending_docs = sum(len(items) for _, items in self._pending)
            open_docs = len(self._open)
        return {
            "ingested_docs": self.ingested,
            "dropped_docs": self.dropped,
            "flushed_batches": self.flushed_batches,
            "flushed_docs": self.flushed_docs,
            "failed_flushes": self.failed_flushes,
            "pending_batches": pending,
            "pending_docs": pending_docs,
            "open_docs": open_docs,
            "max_batches": self.max_batches,
            "batch_docs": self.batch_docs,
        }
