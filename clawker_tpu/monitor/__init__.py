"""Observability stack: compose-rendered OTel/OpenSearch/Prometheus +
the kernel egress netlogger.

Parity reference: internal/monitor (compose stack templates, monitoring
units, ledger -- SURVEY.md 2.11) and controlplane/firewall/ebpf/netlogger
(events ringbuf -> log records).
"""

from .events import EventBus, EventRecord

__all__ = ["EventBus", "EventRecord"]
