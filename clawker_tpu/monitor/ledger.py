"""Seeded-units ledger: which monitoring units this host's stack carries.

A bare unit name is one cluster-wide namespace: the ledger refuses to
re-seed a name with DIFFERENT content from a DIFFERENT source (a silent
last-write-wins PUT would let one project's stack artifacts clobber
another's).  Same source updating in place is always fine.

Parity reference: internal/monitor/ledger.go:63 (SeededUnit,
SeedCollisionError, LoadLedger) -- semantics re-derived.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from pathlib import Path

import yaml

from ..errors import ClawkerError
from ..util.fs import atomic_write
from .unit import MonitoringUnit

LEDGER_FILE = "units-ledger.yaml"


class SeedCollision(ClawkerError):
    def __init__(self, name: str, prev_source: str, new_source: str):
        super().__init__(
            f"monitoring unit {name!r} from {new_source} has different "
            f"content than the same-named unit already seeded from "
            f"{prev_source} -- a bare unit name is one cluster-wide "
            "namespace.  Rename or remove one side, or reset the stack "
            "with `clawker monitor down` (this deletes indexed telemetry)")


@dataclass
class SeededUnit:
    name: str = ""
    source: str = ""          # provenance: "floor" | path of a loose dir
    content_hash: str = ""
    indices: list[str] = field(default_factory=list)
    seeded_at: float = 0.0


class Ledger:
    def __init__(self, monitor_dir: Path):
        self.path = Path(monitor_dir) / LEDGER_FILE
        self.units: dict[str, SeededUnit] = {}
        if self.path.exists():
            raw = yaml.safe_load(self.path.read_text()) or {}
            for name, rec in (raw.get("units") or {}).items():
                self.units[name] = SeededUnit(
                    name=name, source=str(rec.get("source") or ""),
                    content_hash=str(rec.get("content_hash") or ""),
                    indices=[str(i) for i in rec.get("indices") or []],
                    seeded_at=float(rec.get("seeded_at") or 0.0))

    def seed(self, unit: MonitoringUnit, *, source: str) -> SeededUnit:
        """Record a unit as seeded; refuse cross-source content clashes."""
        content = unit.content_hash()
        prev = self.units.get(unit.name)
        if prev and prev.content_hash != content and prev.source != source:
            raise SeedCollision(unit.name, prev.source, source)
        rec = SeededUnit(
            name=unit.name, source=source, content_hash=content,
            indices=[l.index for l in unit.manifest.logs],
            seeded_at=time.time())
        self.units[unit.name] = rec
        return rec

    def save(self) -> None:
        body = yaml.safe_dump({"units": {
            name: {"source": u.source, "content_hash": u.content_hash,
                   "indices": u.indices, "seeded_at": u.seeded_at}
            for name, u in sorted(self.units.items())
        }}, sort_keys=True)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        atomic_write(self.path, body.encode())
