"""Monitoring ledgers: seeded stack units, and the per-run flight recorder.

**Units ledger** -- which monitoring units this host's stack carries.
A bare unit name is one cluster-wide namespace: the ledger refuses to
re-seed a name with DIFFERENT content from a DIFFERENT source (a silent
last-write-wins PUT would let one project's stack artifacts clobber
another's).  Same source updating in place is always fine.

**Flight recorder** -- the post-mortem half of the telemetry subsystem:
an append-only JSONL ledger of one loop run's trace spans (and any
other typed record a subsystem wants preserved), written as events
happen so a crashed run leaves a readable record up to the crash.
``clawker loop trace <run>`` reconstructs iteration span trees from it
(telemetry/spans.py); records may land out of order (lane threads,
waiter threads, the run loop all append).

Parity reference: internal/monitor/ledger.go:63 (SeededUnit,
SeedCollisionError, LoadLedger) -- semantics re-derived.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import zlib
from dataclasses import dataclass, field
from pathlib import Path

import yaml

from ..errors import ClawkerError
from ..util.fs import atomic_write
from .unit import MonitoringUnit

LEDGER_FILE = "units-ledger.yaml"
FLIGHT_DIR = "flight"           # under Config.logs_dir

# --------------------------------------------------------------------------
# record integrity (docs/durability.md): every JSONL record written by
# this module's writers carries a CRC32 of its serialized body as a
# reserved trailing field `"c"`.  One writer, one verifier: the run
# journal, the flight recorder, and the capacity WAL all encode through
# encode_record(), so a flipped bit degrades identically everywhere --
# flagged, never silently folded into a wrong RunImage.  Checksum-less
# legacy records (pre-checksum journals) stay first-class readable.
# --------------------------------------------------------------------------

CRC_FIELD = "c"                 # reserved record field: 8 hex CRC32 chars
_CRC_RE = re.compile(r'(,?)"c":"([0-9a-f]{8})"\}$')


def encode_record(record: dict) -> str:
    """Serialize one record to its checksummed JSONL line (no newline).

    The CRC32 covers the serialized body *without* the checksum field,
    which is spliced on as the final member -- verifiers strip the
    fixed-shape suffix and recompute, no re-serialization ambiguity."""
    body = json.dumps(record, separators=(",", ":"), default=str)
    if not body.endswith("}"):          # non-object: nothing to protect
        return body
    crc = zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF
    sep = "" if body == "{}" else ","
    return f'{body[:-1]}{sep}"{CRC_FIELD}":"{crc:08x}"}}'


def classify_line(line: str) -> tuple[str, dict | None]:
    """Classify one JSONL line: ``("ok", doc)`` checksum verified,
    ``("legacy", doc)`` parseable pre-checksum record, ``("mismatch",
    None)`` parseable but the checksum disagrees (a flipped bit),
    ``("garbled", None)`` unparseable (a torn write -- or worse, which
    only its position can tell), ``("blank", None)``.  The checksum
    field is stripped from returned docs -- folds and span-loaders
    must never see the transport framing."""
    line = line.strip()
    if not line:
        return "blank", None
    m = _CRC_RE.search(line)
    if m is not None:
        body = line[:m.start()] + "}"
        try:
            doc = json.loads(line)
        except ValueError:
            return "garbled", None
        if not isinstance(doc, dict):
            return "garbled", None
        want = int(m.group(2), 16)
        if (zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF) != want:
            return "mismatch", None
        doc.pop(CRC_FIELD, None)
        return "ok", doc
    try:
        doc = json.loads(line)
    except ValueError:
        return "garbled", None
    if not isinstance(doc, dict):
        return "garbled", None
    return "legacy", doc


@dataclass
class IntegrityReport:
    """What a verifying read saw: counts per classify_line() verdict.

    ``torn_tail`` is the FINAL non-blank line failing to parse -- the
    signature of a writer killed mid-line, tolerated everywhere.
    ``corrupt`` is everything else: a mid-file unparseable line or any
    checksum mismatch -- evidence of real damage, never tolerated
    silently (``clawker journal verify`` exits 2 on it)."""

    path: str = ""
    total: int = 0              # non-blank lines seen
    verified: int = 0           # checksum present and matched
    legacy: int = 0             # parseable, no checksum field
    corrupt: int = 0            # mismatch / mid-file garbage
    torn_tail: bool = False     # final line truncated (crash tail)
    first_corrupt_line: int = 0  # 1-based line number of first damage

    @property
    def ok(self) -> bool:
        return self.corrupt == 0

    def to_doc(self) -> dict:
        return {"path": self.path, "total": self.total,
                "verified": self.verified, "legacy": self.legacy,
                "corrupt": self.corrupt, "torn_tail": self.torn_tail,
                "first_corrupt_line": self.first_corrupt_line,
                "ok": self.ok}


def parse_jsonl(lines, report: IntegrityReport | None = None) -> list[dict]:
    """Every parseable JSON object in ``lines``, skipping blanks,
    corrupt lines, and non-objects.  THE tolerant parse for the
    flight-record format -- ``telemetry.load_spans`` and
    :meth:`FlightRecorder.read` both ride it, so a crashed writer's
    truncated tail degrades identically everywhere.  Checksummed
    records are verified (a mismatch is SKIPPED like a torn line, and
    counted when a ``report`` is passed); the checksum field never
    reaches callers."""
    out: list[dict] = []
    last_garbled = False
    for line in lines:
        status, doc = classify_line(line)
        if status == "blank":
            continue
        last_garbled = status == "garbled"
        if report is not None:
            report.total += 1
            if status == "ok":
                report.verified += 1
            elif status == "legacy":
                report.legacy += 1
            else:
                report.corrupt += 1
                if not report.first_corrupt_line:
                    report.first_corrupt_line = report.total
        if doc is not None:
            out.append(doc)
    if report is not None and last_garbled and report.corrupt:
        # an unparseable FINAL line is the crash-tail signature, not
        # damage (a parseable final line with a bad checksum still is)
        report.corrupt -= 1
        report.torn_tail = True
        if report.first_corrupt_line == report.total:
            report.first_corrupt_line = 0
    return out


def read_jsonl(path: Path,
               report: IntegrityReport | None = None) -> list[dict]:
    """Crash-tolerant JSONL *file* read: every parseable record in
    ``path``, skipping blanks, corrupt lines, and the truncated tail a
    writer that died mid-line leaves behind.  THE shared tail-reader for
    every append-only crash-evidence format (the flight recorder and the
    loop run journal both ride it), so a torn write degrades identically
    everywhere instead of each reader inventing its own tolerance.
    Pass a ``report`` to count checksum verdicts."""
    if report is not None:
        report.path = str(path)
    try:
        text = Path(path).read_text(encoding="utf-8")
    except OSError:
        return []
    return parse_jsonl(text.splitlines(), report)


def read_verified_prefix(path: Path) -> tuple[list[dict], IntegrityReport]:
    """The longest verified prefix of a checksummed JSONL file, for
    folds whose CORRECTNESS rides the records (the run-journal durable
    replay): unlike :func:`read_jsonl`, a damaged mid-file record does
    not skip-and-continue -- the fold STOPS at the last verified record
    before it and the report flags the damage, so ``--resume``
    reconciles from truth rather than from records that survived a
    corruption by accident.  A torn final line is still tolerated."""
    report = IntegrityReport(path=str(path))
    try:
        text = Path(path).read_text(encoding="utf-8")
    except OSError:
        return [], report
    lines = text.splitlines()
    last_nonblank = -1
    for i, line in enumerate(lines):
        if line.strip():
            last_nonblank = i
    out: list[dict] = []
    for i, line in enumerate(lines):
        status, doc = classify_line(line)
        if status == "blank":
            continue
        report.total += 1
        if status == "ok":
            report.verified += 1
            out.append(doc)
        elif status == "legacy":
            report.legacy += 1
            out.append(doc)
        else:
            if status == "garbled" and i == last_nonblank:
                report.torn_tail = True
            else:
                report.corrupt += 1
                report.first_corrupt_line = report.total
            break
    return out, report


def verify_jsonl(path: Path) -> IntegrityReport:
    """Full-file integrity scan (``clawker journal verify``): every
    line classified, nothing skipped early.  A truncated final line
    reads as ``torn_tail`` (a crash artifact, exit 0); anything else
    unverifiable counts as ``corrupt`` (exit 2)."""
    report = IntegrityReport(path=str(path))
    try:
        text = Path(path).read_text(encoding="utf-8")
    except OSError:
        return report
    parse_jsonl(text.splitlines(), report)
    return report


@dataclass
class TailState:
    """Cursor for :func:`tail_jsonl`: byte offset of everything consumed,
    the carried possibly-partial last line, and how many times the file
    was observed truncated/rotated (callers that cache derived state --
    the anomaly watch's record window, the sentinel collector's feed --
    compare ``resets`` to know when to drop it)."""

    offset: int = 0
    carry: bytes = b""
    resets: int = 0
    ino: int = -1               # st_ino of the generation being tailed


def tail_jsonl(path: Path, state: TailState) -> list[dict]:
    """Incremental crash-tolerant JSONL tail: every parseable record
    appended past ``state.offset``, riding :func:`parse_jsonl` so a
    torn write (a netlogger or journal writer dying mid-line) is
    SKIPPED, never fatal, and degrades identically to the whole-file
    readers.  A partial trailing line is carried in ``state`` and
    completed by a later append; truncation/rotation resets the cursor
    (and bumps ``state.resets``) so the stream replays from the top.
    Cost is O(new bytes); a missing/unreadable file reads as no news.
    """
    path = Path(path)
    try:
        st = path.stat()
    except OSError:
        return []
    size = st.st_size
    # rotated/truncated: start over.  Size alone cannot tell -- a
    # rotation of fixed-width records can land the new generation at
    # EXACTLY the stale offset -- so the cursor also pins the inode.
    if size < state.offset or (state.ino >= 0 and st.st_ino != state.ino):
        state.offset = 0
        state.carry = b""
        state.resets += 1
    state.ino = st.st_ino
    if size == state.offset:
        return []
    try:
        with open(path, "rb") as f:
            f.seek(state.offset)
            chunk = f.read(size - state.offset)
    except OSError:
        return []
    state.offset += len(chunk)
    data = state.carry + chunk
    lines = data.split(b"\n")
    state.carry = lines.pop()       # possibly-partial last line
    return parse_jsonl(
        line.decode("utf-8", "replace") for line in lines)


def flight_path(logs_dir: Path, run_id: str) -> Path:
    """Canonical flight-recorder path for one loop run."""
    return Path(logs_dir) / FLIGHT_DIR / f"loop-{run_id}.jsonl"


def rotated_path(path: Path) -> Path:
    """The previous generation a size-capped recorder rotated out."""
    return Path(str(path) + ".1")


def read_rotated_lines(path: Path) -> list[str]:
    """Raw lines across the rotation boundary: the ``.1`` generation
    first (older records), then the current file.  Missing files read
    as empty, so the helper serves unrotated recorders unchanged."""
    lines: list[str] = []
    for p in (rotated_path(path), Path(path)):
        try:
            lines.extend(p.read_text(encoding="utf-8").splitlines())
        except OSError:
            continue
    return lines


def read_rotated(path: Path) -> list[dict]:
    """:func:`read_jsonl` across the rotation boundary."""
    return parse_jsonl(read_rotated_lines(path))


def tail_rotated(path: Path, state: TailState) -> list[dict]:
    """Rotation-aware incremental tail: like :func:`tail_jsonl`, but
    when the file shrank because the recorder ROTATED (current ->
    ``.1``), the old generation's remaining records are drained from
    the prior offset before the cursor restarts on the new file -- a
    console tailing a capped recorder loses nothing at the boundary.
    ``state.resets`` still bumps, but only genuinely (a truncation, or
    a second rotation between polls) loses records."""
    path = Path(path)
    try:
        st = path.stat()
        size, ino = st.st_size, st.st_ino
    except OSError:
        size, ino = -1, -1
    out: list[dict] = []
    if size >= 0 and (size < state.offset
                      or (state.ino >= 0 and ino != state.ino)):
        try:
            with open(rotated_path(path), "rb") as f:
                f.seek(state.offset - len(state.carry))
                data = f.read()
            lines = data.split(b"\n")
            out.extend(parse_jsonl(
                line.decode("utf-8", "replace") for line in lines))
        except OSError:
            pass        # double rotation / no .1: the remainder is gone
        state.offset = 0
        state.carry = b""
        state.ino = -1          # adopt the new generation without a
        state.resets += 1       # second reset inside tail_jsonl
    out.extend(tail_jsonl(path, state))
    return out


class FlightRecorder:
    """Append-only JSONL record sink for one run.

    Writes are line-atomic under one lock and flushed per record: the
    recorder exists exactly for the runs that die unexpectedly, so
    buffering records in memory would lose the most interesting tail.
    A recorder whose directory cannot be created degrades to a no-op --
    telemetry must never fail the run it is recording.

    ``max_bytes`` bounds the file for daemon-lifetime recorders (and
    long daemon-hosted runs): when an append would pass the cap, the
    current file rotates to ``<path>.1`` (replacing any prior ``.1``)
    and a fresh generation starts, so the newest records are always in
    a readable, bounded pair of files.  Readers cross the boundary via
    :func:`read_rotated` / :func:`tail_rotated`.  0 = unbounded.
    """

    def __init__(self, path: Path, *, max_bytes: int = 0):
        self.path = Path(path)
        self.max_bytes = max(0, int(max_bytes))
        self._lock = threading.Lock()
        self._fh = None
        self._size = 0
        self.dropped = 0
        self.rotations = 0
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
            self._size = self.path.stat().st_size
        except OSError:
            self._fh = None

    def _rotate_locked(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass
        try:
            os.replace(self.path, rotated_path(self.path))
        except OSError:
            pass        # rotation is best-effort; keep appending
        try:
            self._fh = open(self.path, "a", encoding="utf-8")
            self._size = self.path.stat().st_size
            self.rotations += 1
        except OSError:
            self._fh = None

    def append(self, record: dict) -> None:
        if self._fh is None:
            self.dropped += 1
            return
        line = encode_record(record)
        with self._lock:
            if self._fh is None:
                self.dropped += 1
                return
            if (self.max_bytes and self._size
                    and self._size + len(line) + 1 > self.max_bytes):
                self._rotate_locked()
                if self._fh is None:
                    self.dropped += 1
                    return
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
                self._size += len(line) + 1
            except OSError:
                self.dropped += 1

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    @staticmethod
    def read(path: Path) -> list[dict]:
        """Every parseable record in the file, skipping a truncated tail
        (the writer may have died mid-line)."""
        return read_jsonl(path)


class SeedCollision(ClawkerError):
    def __init__(self, name: str, prev_source: str, new_source: str):
        super().__init__(
            f"monitoring unit {name!r} from {new_source} has different "
            f"content than the same-named unit already seeded from "
            f"{prev_source} -- a bare unit name is one cluster-wide "
            "namespace.  Rename or remove one side, or reset the stack "
            "with `clawker monitor down` (this deletes indexed telemetry)")


@dataclass
class SeededUnit:
    name: str = ""
    source: str = ""          # provenance: "floor" | path of a loose dir
    content_hash: str = ""
    indices: list[str] = field(default_factory=list)
    seeded_at: float = 0.0


class Ledger:
    def __init__(self, monitor_dir: Path):
        self.path = Path(monitor_dir) / LEDGER_FILE
        self.units: dict[str, SeededUnit] = {}
        if self.path.exists():
            raw = yaml.safe_load(self.path.read_text()) or {}
            for name, rec in (raw.get("units") or {}).items():
                self.units[name] = SeededUnit(
                    name=name, source=str(rec.get("source") or ""),
                    content_hash=str(rec.get("content_hash") or ""),
                    indices=[str(i) for i in rec.get("indices") or []],
                    seeded_at=float(rec.get("seeded_at") or 0.0))

    def seed(self, unit: MonitoringUnit, *, source: str) -> SeededUnit:
        """Record a unit as seeded; refuse cross-source content clashes."""
        content = unit.content_hash()
        prev = self.units.get(unit.name)
        if prev and prev.content_hash != content and prev.source != source:
            raise SeedCollision(unit.name, prev.source, source)
        rec = SeededUnit(
            name=unit.name, source=source, content_hash=content,
            indices=[l.index for l in unit.manifest.logs],
            seeded_at=time.time())
        self.units[unit.name] = rec
        return rec

    def save(self) -> None:
        body = yaml.safe_dump({"units": {
            name: {"source": u.source, "content_hash": u.content_hash,
                   "indices": u.indices, "seeded_at": u.seeded_at}
            for name, u in sorted(self.units.items())
        }}, sort_keys=True)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        atomic_write(self.path, body.encode())
