"""Monitoring ledgers: seeded stack units, and the per-run flight recorder.

**Units ledger** -- which monitoring units this host's stack carries.
A bare unit name is one cluster-wide namespace: the ledger refuses to
re-seed a name with DIFFERENT content from a DIFFERENT source (a silent
last-write-wins PUT would let one project's stack artifacts clobber
another's).  Same source updating in place is always fine.

**Flight recorder** -- the post-mortem half of the telemetry subsystem:
an append-only JSONL ledger of one loop run's trace spans (and any
other typed record a subsystem wants preserved), written as events
happen so a crashed run leaves a readable record up to the crash.
``clawker loop trace <run>`` reconstructs iteration span trees from it
(telemetry/spans.py); records may land out of order (lane threads,
waiter threads, the run loop all append).

Parity reference: internal/monitor/ledger.go:63 (SeededUnit,
SeedCollisionError, LoadLedger) -- semantics re-derived.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

import yaml

from ..errors import ClawkerError
from ..util.fs import atomic_write
from .unit import MonitoringUnit

LEDGER_FILE = "units-ledger.yaml"
FLIGHT_DIR = "flight"           # under Config.logs_dir


def parse_jsonl(lines) -> list[dict]:
    """Every parseable JSON object in ``lines``, skipping blanks,
    corrupt lines, and non-objects.  THE tolerant parse for the
    flight-record format -- ``telemetry.load_spans`` and
    :meth:`FlightRecorder.read` both ride it, so a crashed writer's
    truncated tail degrades identically everywhere."""
    out: list[dict] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            out.append(doc)
    return out


def read_jsonl(path: Path) -> list[dict]:
    """Crash-tolerant JSONL *file* read: every parseable record in
    ``path``, skipping blanks, corrupt lines, and the truncated tail a
    writer that died mid-line leaves behind.  THE shared tail-reader for
    every append-only crash-evidence format (the flight recorder and the
    loop run journal both ride it), so a torn write degrades identically
    everywhere instead of each reader inventing its own tolerance."""
    try:
        text = Path(path).read_text(encoding="utf-8")
    except OSError:
        return []
    return parse_jsonl(text.splitlines())


@dataclass
class TailState:
    """Cursor for :func:`tail_jsonl`: byte offset of everything consumed,
    the carried possibly-partial last line, and how many times the file
    was observed truncated/rotated (callers that cache derived state --
    the anomaly watch's record window, the sentinel collector's feed --
    compare ``resets`` to know when to drop it)."""

    offset: int = 0
    carry: bytes = b""
    resets: int = 0


def tail_jsonl(path: Path, state: TailState) -> list[dict]:
    """Incremental crash-tolerant JSONL tail: every parseable record
    appended past ``state.offset``, riding :func:`parse_jsonl` so a
    torn write (a netlogger or journal writer dying mid-line) is
    SKIPPED, never fatal, and degrades identically to the whole-file
    readers.  A partial trailing line is carried in ``state`` and
    completed by a later append; truncation/rotation resets the cursor
    (and bumps ``state.resets``) so the stream replays from the top.
    Cost is O(new bytes); a missing/unreadable file reads as no news.
    """
    path = Path(path)
    try:
        size = path.stat().st_size
    except OSError:
        return []
    if size < state.offset:         # rotated/truncated: start over
        state.offset = 0
        state.carry = b""
        state.resets += 1
    if size == state.offset:
        return []
    try:
        with open(path, "rb") as f:
            f.seek(state.offset)
            chunk = f.read(size - state.offset)
    except OSError:
        return []
    state.offset += len(chunk)
    data = state.carry + chunk
    lines = data.split(b"\n")
    state.carry = lines.pop()       # possibly-partial last line
    return parse_jsonl(
        line.decode("utf-8", "replace") for line in lines)


def flight_path(logs_dir: Path, run_id: str) -> Path:
    """Canonical flight-recorder path for one loop run."""
    return Path(logs_dir) / FLIGHT_DIR / f"loop-{run_id}.jsonl"


def rotated_path(path: Path) -> Path:
    """The previous generation a size-capped recorder rotated out."""
    return Path(str(path) + ".1")


def read_rotated_lines(path: Path) -> list[str]:
    """Raw lines across the rotation boundary: the ``.1`` generation
    first (older records), then the current file.  Missing files read
    as empty, so the helper serves unrotated recorders unchanged."""
    lines: list[str] = []
    for p in (rotated_path(path), Path(path)):
        try:
            lines.extend(p.read_text(encoding="utf-8").splitlines())
        except OSError:
            continue
    return lines


def read_rotated(path: Path) -> list[dict]:
    """:func:`read_jsonl` across the rotation boundary."""
    return parse_jsonl(read_rotated_lines(path))


def tail_rotated(path: Path, state: TailState) -> list[dict]:
    """Rotation-aware incremental tail: like :func:`tail_jsonl`, but
    when the file shrank because the recorder ROTATED (current ->
    ``.1``), the old generation's remaining records are drained from
    the prior offset before the cursor restarts on the new file -- a
    console tailing a capped recorder loses nothing at the boundary.
    ``state.resets`` still bumps, but only genuinely (a truncation, or
    a second rotation between polls) loses records."""
    path = Path(path)
    try:
        size = path.stat().st_size
    except OSError:
        size = -1
    out: list[dict] = []
    if 0 <= size < state.offset:
        try:
            with open(rotated_path(path), "rb") as f:
                f.seek(state.offset - len(state.carry))
                data = f.read()
            lines = data.split(b"\n")
            out.extend(parse_jsonl(
                line.decode("utf-8", "replace") for line in lines))
        except OSError:
            pass        # double rotation / no .1: the remainder is gone
        state.offset = 0
        state.carry = b""
        state.resets += 1
    out.extend(tail_jsonl(path, state))
    return out


class FlightRecorder:
    """Append-only JSONL record sink for one run.

    Writes are line-atomic under one lock and flushed per record: the
    recorder exists exactly for the runs that die unexpectedly, so
    buffering records in memory would lose the most interesting tail.
    A recorder whose directory cannot be created degrades to a no-op --
    telemetry must never fail the run it is recording.

    ``max_bytes`` bounds the file for daemon-lifetime recorders (and
    long daemon-hosted runs): when an append would pass the cap, the
    current file rotates to ``<path>.1`` (replacing any prior ``.1``)
    and a fresh generation starts, so the newest records are always in
    a readable, bounded pair of files.  Readers cross the boundary via
    :func:`read_rotated` / :func:`tail_rotated`.  0 = unbounded.
    """

    def __init__(self, path: Path, *, max_bytes: int = 0):
        self.path = Path(path)
        self.max_bytes = max(0, int(max_bytes))
        self._lock = threading.Lock()
        self._fh = None
        self._size = 0
        self.dropped = 0
        self.rotations = 0
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
            self._size = self.path.stat().st_size
        except OSError:
            self._fh = None

    def _rotate_locked(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass
        try:
            os.replace(self.path, rotated_path(self.path))
        except OSError:
            pass        # rotation is best-effort; keep appending
        try:
            self._fh = open(self.path, "a", encoding="utf-8")
            self._size = self.path.stat().st_size
            self.rotations += 1
        except OSError:
            self._fh = None

    def append(self, record: dict) -> None:
        if self._fh is None:
            self.dropped += 1
            return
        line = json.dumps(record, separators=(",", ":"), default=str)
        with self._lock:
            if self._fh is None:
                self.dropped += 1
                return
            if (self.max_bytes and self._size
                    and self._size + len(line) + 1 > self.max_bytes):
                self._rotate_locked()
                if self._fh is None:
                    self.dropped += 1
                    return
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
                self._size += len(line) + 1
            except OSError:
                self.dropped += 1

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    @staticmethod
    def read(path: Path) -> list[dict]:
        """Every parseable record in the file, skipping a truncated tail
        (the writer may have died mid-line)."""
        return read_jsonl(path)


class SeedCollision(ClawkerError):
    def __init__(self, name: str, prev_source: str, new_source: str):
        super().__init__(
            f"monitoring unit {name!r} from {new_source} has different "
            f"content than the same-named unit already seeded from "
            f"{prev_source} -- a bare unit name is one cluster-wide "
            "namespace.  Rename or remove one side, or reset the stack "
            "with `clawker monitor down` (this deletes indexed telemetry)")


@dataclass
class SeededUnit:
    name: str = ""
    source: str = ""          # provenance: "floor" | path of a loose dir
    content_hash: str = ""
    indices: list[str] = field(default_factory=list)
    seeded_at: float = 0.0


class Ledger:
    def __init__(self, monitor_dir: Path):
        self.path = Path(monitor_dir) / LEDGER_FILE
        self.units: dict[str, SeededUnit] = {}
        if self.path.exists():
            raw = yaml.safe_load(self.path.read_text()) or {}
            for name, rec in (raw.get("units") or {}).items():
                self.units[name] = SeededUnit(
                    name=name, source=str(rec.get("source") or ""),
                    content_hash=str(rec.get("content_hash") or ""),
                    indices=[str(i) for i in rec.get("indices") or []],
                    seeded_at=float(rec.get("seeded_at") or 0.0))

    def seed(self, unit: MonitoringUnit, *, source: str) -> SeededUnit:
        """Record a unit as seeded; refuse cross-source content clashes."""
        content = unit.content_hash()
        prev = self.units.get(unit.name)
        if prev and prev.content_hash != content and prev.source != source:
            raise SeedCollision(unit.name, prev.source, source)
        rec = SeededUnit(
            name=unit.name, source=source, content_hash=content,
            indices=[l.index for l in unit.manifest.logs],
            seeded_at=time.time())
        self.units[unit.name] = rec
        return rec

    def save(self) -> None:
        body = yaml.safe_dump({"units": {
            name: {"source": u.source, "content_hash": u.content_hash,
                   "indices": u.indices, "seeded_at": u.seeded_at}
            for name, u in sorted(self.units.items())
        }}, sort_keys=True)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        atomic_write(self.path, body.encode())
