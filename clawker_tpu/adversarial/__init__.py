"""Adversarial egress suite: exfiltration payload corpus + capture harness.

Parity reference: /root/reference/test/adversarial (C2 "attacker server"
recording every contact to sqlite + 30 payload directories of
exfiltration techniques, test/adversarial/CLAUDE.md).  This build's
corpus expresses each technique as a driver over the enforcement
surface (kernel-policy oracle + DNS gate + route table), records every
attempt in a capture DB, and the report asserts ZERO escapes -- the
same all-must-be-captured bar, runnable both off-box (policy level, in
CI) and on a TPU-VM worker against the live kernel.
"""

from .harness import CaptureDB, EgressSurface, Outcome, run_corpus

__all__ = ["CaptureDB", "EgressSurface", "Outcome", "run_corpus"]
