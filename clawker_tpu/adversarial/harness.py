"""Adversarial harness: the egress surface payloads attack, and the
capture database the report is graded from.

``EgressSurface`` wires the real enforcement components -- FakeMaps with
kernel semantics, the policy oracle, the DNS gate's serve_packet path,
and the production route builder -- exactly as the firewall handler
does, so a payload that slips through here is a real semantic hole, not
a test-double artifact.

Outcome taxonomy:
- CAPTURED:  the attempt was denied / answered NXDOMAIN (the attacker
  endpoint saw nothing).
- CONTAINED: traffic reached a clawker-controlled chokepoint (Envoy,
  the DNS gate, loopback) that applies its own policy -- never the
  attacker directly.
- ESCAPED:   bytes would have reached an attacker-controlled endpoint.
  Any ESCAPED fails the suite.
"""

from __future__ import annotations

import json
import sqlite3
import time
from dataclasses import dataclass, field
from enum import Enum
from pathlib import Path

from ..config.schema import EgressRule
from ..firewall import policy as policy_mod
from ..firewall.dnsgate import (
    RCODE_NOERROR,
    RCODE_NXDOMAIN,
    DnsGate,
    ZonePolicy,
    parse_a_records,
)
from ..firewall.maps import FakeMaps
from ..firewall.model import (
    FLAG_ENFORCE,
    FLAG_HOSTPROXY,
    PROTO_TCP,
    PROTO_UDP,
    Action,
    ContainerPolicy,
    DnsEntry,
)

CG = 0xC0FFEE          # the sandboxed agent's cgroup
ENVOY_IP = "10.77.0.2"
DNS_IP = "10.77.0.1"   # gate on the gateway
HOSTPROXY_IP = "10.77.0.1"
HOSTPROXY_PORT = 18374


class Outcome(str, Enum):
    CAPTURED = "captured"
    CONTAINED = "contained"
    ESCAPED = "escaped"


@dataclass
class Attempt:
    payload: str
    technique: str
    detail: str
    outcome: Outcome


class CaptureDB:
    """Sqlite record of every attempt (reference: the attacker server's
    capture DB the operator grades from)."""

    def __init__(self, path: Path | str = ":memory:"):
        self.conn = sqlite3.connect(str(path))
        self.conn.execute(
            "CREATE TABLE IF NOT EXISTS attempts ("
            " ts REAL, payload TEXT, technique TEXT, detail TEXT, outcome TEXT)"
        )

    def record(self, attempt: Attempt) -> None:
        self.conn.execute(
            "INSERT INTO attempts VALUES (?, ?, ?, ?, ?)",
            (time.time(), attempt.payload, attempt.technique, attempt.detail,
             attempt.outcome.value),
        )
        self.conn.commit()

    def escapes(self) -> list[tuple]:
        return list(self.conn.execute(
            "SELECT payload, technique, detail FROM attempts WHERE outcome = ?",
            (Outcome.ESCAPED.value,),
        ))

    def counts(self) -> dict[str, int]:
        return dict(self.conn.execute(
            "SELECT outcome, COUNT(*) FROM attempts GROUP BY outcome"))

    def close(self) -> None:
        self.conn.close()


class EgressSurface:
    """The sandbox, as a payload sees it."""

    def __init__(self, rules: list[EgressRule], *,
                 resolutions: dict[str, str] | None = None):
        self.rules = rules
        self.maps = FakeMaps()
        self.maps.enroll(CG, ContainerPolicy(
            envoy_ip=ENVOY_IP, dns_ip=DNS_IP,
            hostproxy_ip=HOSTPROXY_IP, hostproxy_port=HOSTPROXY_PORT,
            flags=FLAG_ENFORCE | FLAG_HOSTPROXY,
        ))
        # production route construction, not a test re-derivation
        from ..firewall.envoy import generate_envoy_config

        bundle = generate_envoy_config(rules)
        self.maps.sync_routes(policy_mod.build_routes(
            rules, envoy_ip=ENVOY_IP, tls_port=10000,
            tcp_ports=bundle.tcp_ports,
        ))
        # DNS gate with a canned upstream: allowed domains resolve to the
        # address in ``resolutions`` (attacker-controlled hosts resolve
        # nowhere -- the gate never forwards them)
        self.resolutions = resolutions or {}
        self.gate = DnsGate(ZonePolicy.from_rules(rules), self.maps,
                            host="127.0.0.1", port=0)
        self._cookie = 0

    # -- resolution ----------------------------------------------------

    def dns_query(self, qname: str, qtype: int = 1) -> tuple[int, list[str]]:
        """Query through the REAL gate path; returns (rcode, ips)."""
        from ..firewall.dnsgate import _encode_name
        import struct as _struct

        hdr = _struct.pack(">HHHHHH", 0x0101, 0x0100, 1, 0, 0, 0)
        q = hdr + _encode_name(qname) + _struct.pack(">HH", qtype, 1)

        def forward(data, resolvers, *, tcp):
            ip = self.resolutions.get(qname.lower().rstrip("."))
            if ip is None:
                return None
            # upstream-shaped answer
            import socket as _s

            reply_hdr = _struct.pack(">HHHHHH", 0x0101, 0x8180, 1, 1, 0, 0)
            body = data[12:]
            body += _struct.pack(">HHHIH", 0xC00C, 1, 1, 120, 4) + _s.inet_aton(ip)
            return reply_hdr + body

        self.gate._forward = forward  # type: ignore[method-assign]
        reply = self.gate.serve_packet(q)
        if reply is None:
            return -1, []
        rcode = _struct.unpack(">H", reply[2:4])[0] & 0xF
        return rcode, [ip for ip, _ in parse_a_records(reply)]

    # -- kernel hooks --------------------------------------------------

    def cookie(self) -> int:
        self._cookie += 1
        return self._cookie

    def connect(self, ip: str, port: int, proto: int = PROTO_TCP):
        return policy_mod.connect4(self.maps, CG, ip, port, proto,
                                   sock_cookie=self.cookie())

    def sendmsg(self, ip: str, port: int):
        return policy_mod.sendmsg4(self.maps, CG, self.cookie(), ip, port)

    def connect6(self, ip6: str, port: int):
        return policy_mod.connect6(self.maps, CG, ip6, port)

    def raw_socket(self):
        return policy_mod.sock_create(self.maps, CG, 2, policy_mod.SOCK_RAW)

    def packet_socket(self):
        return policy_mod.sock_create(self.maps, CG, 2, policy_mod.SOCK_PACKET)

    # -- grading -------------------------------------------------------

    def grade_verdict(self, v, dst_ip: str) -> tuple[Outcome, str]:
        """Where would the bytes have gone?"""
        if v.action is Action.DENY:
            return Outcome.CAPTURED, f"denied ({v.reason.name})"
        if v.action in (Action.REDIRECT, Action.REDIRECT_DNS):
            target = v.redirect_ip
            if target in (ENVOY_IP, DNS_IP):
                return Outcome.CONTAINED, f"redirected to chokepoint {target}"
            return Outcome.ESCAPED, f"redirected to uncontrolled {target}"
        # ALLOW: direct path to dst
        if dst_ip.startswith("127.") or dst_ip in (ENVOY_IP, DNS_IP):
            return Outcome.CONTAINED, f"allowed to chokepoint/loopback {dst_ip}"
        if dst_ip == HOSTPROXY_IP:
            return Outcome.CONTAINED, "allowed to host proxy (audited channel)"
        return Outcome.ESCAPED, f"allowed direct to {dst_ip}"


@dataclass
class Report:
    total: int = 0
    captured: int = 0
    contained: int = 0
    escaped: int = 0
    escapes: list[tuple] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.escaped == 0

    def to_json(self) -> str:
        return json.dumps({
            "total": self.total, "captured": self.captured,
            "contained": self.contained, "escaped": self.escaped,
            "escapes": [list(e) for e in self.escapes], "pass": self.ok,
        }, indent=2)


def run_corpus(db: CaptureDB | None = None, *, rules=None) -> Report:
    """Run every payload class; any ESCAPED outcome fails."""
    from .payloads import CORPUS, default_rules, default_resolutions

    rules = rules if rules is not None else default_rules()
    surface = EgressSurface(rules, resolutions=default_resolutions())
    db = db or CaptureDB()
    report = Report()
    for payload in CORPUS:
        for attempt in payload(surface):
            db.record(attempt)
            report.total += 1
            if attempt.outcome is Outcome.CAPTURED:
                report.captured += 1
            elif attempt.outcome is Outcome.CONTAINED:
                report.contained += 1
            else:
                report.escaped += 1
    report.escapes = db.escapes()
    return report
