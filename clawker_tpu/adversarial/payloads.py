"""The exfiltration payload corpus: 30 technique classes.

Each payload is a function ``(surface) -> list[Attempt]`` modeling one
exfil technique an in-container attacker (or a prompt-injected agent)
would try.  Grading: bytes reaching an attacker endpoint = ESCAPED;
reaching a clawker chokepoint = CONTAINED; denied/NXDOMAIN = CAPTURED.

Parity reference: the 30 payload directories of
/root/reference/test/adversarial/payloads (01..30) -- technique classes
re-derived, not copied: direct-IP lanes, DNS tricks, raw sockets, v6,
infra abuse, zone-matching confusions, timing races.
"""

from __future__ import annotations

import time

from ..config.schema import EgressRule
from ..firewall.model import Action, DnsEntry, PROTO_TCP, PROTO_UDP
from ..firewall.hashes import zone_hash
from .harness import (
    CG,
    DNS_IP,
    ENVOY_IP,
    HOSTPROXY_IP,
    HOSTPROXY_PORT,
    Attempt,
    Outcome,
)

ATTACKER_IP = "198.51.100.66"
ATTACKER_DOMAIN = "exfil.attacker.net"
ALLOWED_API_IP = "93.184.216.34"


def default_rules() -> list[EgressRule]:
    return [
        EgressRule(dst="*.example.com", proto="https"),
        EgressRule(dst="exact.example.org", proto="https"),
        EgressRule(dst="github.com", proto="tcp", port=22),
        EgressRule(dst="plain.example.net", proto="http"),
    ]


def default_resolutions() -> dict[str, str]:
    """What the upstream resolver would answer for allowed zones."""
    return {
        "api.example.com": ALLOWED_API_IP,
        "example.com": ALLOWED_API_IP,
        "tun1.example.com": ALLOWED_API_IP,
        "exact.example.org": "93.184.216.40",
        "github.com": "140.82.112.3",
        "plain.example.net": "93.184.216.50",
    }


def _attempt(payload, technique, surface, verdict, dst_ip, detail="") -> Attempt:
    outcome, why = surface.grade_verdict(verdict, dst_ip)
    return Attempt(payload, technique, detail or why, outcome)


def _dns_attempt(payload, surface, qname) -> Attempt:
    rcode, ips = surface.dns_query(qname)
    if rcode == 3:  # NXDOMAIN
        return Attempt(payload, "dns", f"{qname}: NXDOMAIN", Outcome.CAPTURED)
    if not ips:
        return Attempt(payload, "dns", f"{qname}: empty answer", Outcome.CAPTURED)
    return Attempt(payload, "dns", f"{qname} -> {ips} (gate-resolved)",
                   Outcome.CONTAINED)


# ---------------------------------------------------------------- corpus

def p01_direct_ip_https(s):
    return [_attempt("01-direct-ip-https", "connect", s,
                     s.connect(ATTACKER_IP, 443), ATTACKER_IP)]


def p02_direct_ip_http(s):
    return [_attempt("02-direct-ip-http", "connect", s,
                     s.connect(ATTACKER_IP, 80), ATTACKER_IP)]


def p03_high_port_tcp(s):
    return [_attempt("03-high-port-tcp", "connect", s,
                     s.connect(ATTACKER_IP, 31337), ATTACKER_IP)]


def p04_udp_datagram(s):
    return [_attempt("04-udp-datagram", "sendmsg", s,
                     s.sendmsg(ATTACKER_IP, 9999), ATTACKER_IP)]


def p05_icmp_ping(s):
    v = s.raw_socket()
    out = (Outcome.CAPTURED if v.action is Action.DENY else Outcome.ESCAPED)
    return [Attempt("05-icmp-ping", "sock_create", f"raw socket: {v.reason.name}", out)]


def p06_packet_socket(s):
    v = s.packet_socket()
    out = (Outcome.CAPTURED if v.action is Action.DENY else Outcome.ESCAPED)
    return [Attempt("06-packet-socket", "sock_create", f"packet socket: {v.reason.name}", out)]


def p07_hardcoded_resolver(s):
    # 8.8.8.8:53 must be rewritten to the gate, never reach Google
    return [_attempt("07-hardcoded-resolver", "sendmsg", s,
                     s.sendmsg("8.8.8.8", 53), "8.8.8.8")]


def p08_resolve_attacker_domain(s):
    return [_dns_attempt("08-resolve-attacker-domain", s, ATTACKER_DOMAIN)]


def p09_dns_tunnel_subdomains(s):
    return [_dns_attempt("09-dns-tunnel", s, f"{chunk}.{ATTACKER_DOMAIN}")
            for chunk in ("aGVsbG8", "d29ybGQ", "ZXhmaWw")]


def p10_dns_tunnel_allowed_zone(s):
    # data-in-label under an ALLOWED zone: resolves via the gate (logged,
    # rate-limited upstream) -- contained, never attacker-direct
    return [_dns_attempt("10-dns-tunnel-allowed-zone", s, "tun1.example.com")]


def p11_ipv6_literal(s):
    return [_attempt("11-ipv6-literal", "connect6", s,
                     s.connect6("2001:db8::bad", 443), "0.0.0.0")]


def p12_v4mapped_attacker(s):
    return [_attempt("12-v4mapped", "connect6", s,
                     s.connect6(f"::ffff:{ATTACKER_IP}", 443), ATTACKER_IP)]


def p13_loopback_is_not_egress(s):
    return [_attempt("13-loopback", "connect", s,
                     s.connect("127.0.0.1", 8080), "127.0.0.1")]


def p14_stale_cache_unruled_zone(s):
    # attacker somehow seeded dns_cache with an IP under a zone that has
    # NO route: the route lookup must still deny
    s.maps.cache_dns(ATTACKER_IP, DnsEntry(
        zone_hash=zone_hash(ATTACKER_DOMAIN), expires_unix=int(time.time()) + 300))
    return [_attempt("14-stale-cache-unruled", "connect", s,
                     s.connect(ATTACKER_IP, 443), ATTACKER_IP)]


def p15_resolver_port_masquerade(s):
    # attacker C2 listening on :53/tcp -- kernel rewrites to the gate
    return [_attempt("15-port53-masquerade", "connect", s,
                     s.connect(ATTACKER_IP, 53), ATTACKER_IP)]


def p16_udp_port53_masquerade(s):
    return [_attempt("16-udp53-masquerade", "sendmsg", s,
                     s.sendmsg(ATTACKER_IP, 53), ATTACKER_IP)]


def p17_envoy_direct_wrong_sni(s):
    # dialing the proxy directly: kernel allows (chokepoint); Envoy's SNI
    # filter chains + default-deny handle the rest
    return [_attempt("17-envoy-direct", "connect", s,
                     s.connect(ENVOY_IP, 10000), ENVOY_IP)]


def p18_hostproxy_off_port(s):
    return [_attempt("18-hostproxy-off-port", "connect", s,
                     s.connect(HOSTPROXY_IP, 2222), HOSTPROXY_IP,
                     "host-proxy ip, non-allowed port")]


def p19_gateway_service_scan(s):
    return [_attempt("19-gateway-scan", "connect", s,
                     s.connect("10.77.0.1", p), "10.77.0.1", f"gateway :{p}")
            for p in (80, 443, 8080) ]


def p20_subnet_neighbor_scan(s):
    return [_attempt("20-subnet-scan", "connect", s,
                     s.connect("10.77.0.9", 445), "10.77.0.9")]


def p21_cloud_metadata(s):
    return [_attempt("21-cloud-metadata", "connect", s,
                     s.connect("169.254.169.254", 80), "169.254.169.254")]


def p22_ttl_expiry_race(s):
    s.maps.cache_dns("198.51.100.77", DnsEntry(
        zone_hash=zone_hash("example.com"), expires_unix=int(time.time()) - 10))
    s.maps.expire_dns()
    return [_attempt("22-ttl-expiry-race", "connect", s,
                     s.connect("198.51.100.77", 443), "198.51.100.77",
                     "cached entry expired + GC'd")]


def p23_allowed_zone_wrong_port(s):
    rcode, ips = s.dns_query("api.example.com")
    v = s.connect(ips[0], 2222) if ips else s.connect(ALLOWED_API_IP, 2222)
    return [_attempt("23-allowed-wrong-port", "connect", s, v, ALLOWED_API_IP,
                     "allowed zone, unruled port 2222")]


def p24_allowed_zone_wrong_proto(s):
    s.dns_query("api.example.com")
    return [_attempt("24-allowed-wrong-proto", "sendmsg", s,
                     s.sendmsg(ALLOWED_API_IP, 443), ALLOWED_API_IP,
                     "udp to an https-only zone")]


def p25_exact_rule_subdomain(s):
    return [_dns_attempt("25-exact-subdomain", s, "sub.exact.example.org")]


def p26_lookalike_domain(s):
    return [_dns_attempt("26-lookalike", s, "evilexample.com")]


def p27_zone_suffix_confusion(s):
    return [_dns_attempt("27-suffix-confusion", s, "example.com.attacker.net")]


def p28_expired_bypass(s):
    # a bypass the operator granted yesterday must not still be open
    s.maps.set_bypass(CG, int(time.time()) - 3600)
    out = [_attempt("28-expired-bypass", "connect", s,
                    s.connect(ATTACKER_IP, 443), ATTACKER_IP,
                    "bypass deadline passed")]
    s.maps.clear_bypass(CG)
    return out


def p29_udp_reply_spoof(s):
    # recvmsg reverse-NAT must only rewrite replies from the gate/proxy:
    # a spoofed reply from the attacker must come through unmasked
    from ..firewall import policy as policy_mod

    cookie = s.cookie()
    policy_mod.sendmsg4(s.maps, CG, cookie, "9.9.9.9", 53)
    src = policy_mod.recvmsg4(s.maps, CG, cookie, ATTACKER_IP, 53)
    ok = src == (ATTACKER_IP, 53)
    return [Attempt("29-udp-reply-spoof", "recvmsg",
                    f"spoofed reply surfaced as {src[0]}:{src[1]}",
                    Outcome.CAPTURED if ok else Outcome.ESCAPED)]


def p30_allowed_flow_is_proxied(s):
    # the happy path itself: allowed https must ride the proxy chokepoint,
    # never go direct (otherwise SNI/path policy is bypassed)
    rcode, ips = s.dns_query("api.example.com")
    v = s.connect(ips[0], 443) if ips else s.connect(ALLOWED_API_IP, 443)
    outcome, why = s.grade_verdict(v, ALLOWED_API_IP)
    if v.action is Action.ALLOW:  # direct-to-internet allow = policy hole
        outcome, why = Outcome.ESCAPED, "allowed https went direct, not proxied"
    return [Attempt("30-allowed-flow-proxied", "connect", why, outcome)]


CORPUS = [
    p01_direct_ip_https, p02_direct_ip_http, p03_high_port_tcp,
    p04_udp_datagram, p05_icmp_ping, p06_packet_socket,
    p07_hardcoded_resolver, p08_resolve_attacker_domain,
    p09_dns_tunnel_subdomains, p10_dns_tunnel_allowed_zone,
    p11_ipv6_literal, p12_v4mapped_attacker, p13_loopback_is_not_egress,
    p14_stale_cache_unruled_zone, p15_resolver_port_masquerade,
    p16_udp_port53_masquerade, p17_envoy_direct_wrong_sni,
    p18_hostproxy_off_port, p19_gateway_service_scan,
    p20_subnet_neighbor_scan, p21_cloud_metadata, p22_ttl_expiry_race,
    p23_allowed_zone_wrong_port, p24_allowed_zone_wrong_proto,
    p25_exact_rule_subdomain, p26_lookalike_domain,
    p27_zone_suffix_confusion, p28_expired_bypass, p29_udp_reply_spoof,
    p30_allowed_flow_is_proxied,
]
