"""Socket bridge: host SSH/GPG agent sockets forwarded into containers.

Parity reference: internal/socketbridge -- length-prefixed mux over a
``docker exec`` stdio channel; the container side materializes unix
sockets the agent's ssh/gpg point at, the host side relays each
connection to the real ``SSH_AUTH_SOCK`` / gpg-agent extra socket.
Keys never enter the container; only agent-protocol traffic does.

No eager imports here: this ``__init__`` also ships inside the agentd
zipapp, where only the stdlib-only ``protocol``/``container`` halves
exist -- ``host`` (which pulls framework modules) is host-side only.
"""
