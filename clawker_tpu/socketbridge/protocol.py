"""Bridge wire protocol: length-prefixed frames over one byte stream.

Frame: ``!IBBH`` header (channel u32, kind u8, which u8, len u16) +
payload.  ``channel`` identifies one proxied connection; ``which`` names
the logical socket (SSH agent / GPG agent).  Stdlib-only: this module
ships in the agentd zipapp and runs on a bare python3 in any image.

Re-designed from the reference's muxrpc (internal/socketbridge
bridge.go:59): connections are symmetric byte pipes, so three frame
kinds suffice -- OPEN (container accepted a client), DATA, CLOSE.
"""

from __future__ import annotations

import struct
from typing import BinaryIO

HEADER = struct.Struct("!IBBH")
MAX_PAYLOAD = 0xFFFF

K_OPEN = 1
K_DATA = 2
K_CLOSE = 3

W_SSH = 1
W_GPG = 2

WHICH_NAMES = {W_SSH: "ssh", W_GPG: "gpg"}


def pack(channel: int, kind: int, which: int, payload: bytes = b"") -> bytes:
    assert len(payload) <= MAX_PAYLOAD
    return HEADER.pack(channel, kind, which, len(payload)) + payload


def read_frame(stream: BinaryIO) -> tuple[int, int, int, bytes] | None:
    """(channel, kind, which, payload), or None on EOF."""
    hdr = b""
    while len(hdr) < HEADER.size:
        chunk = stream.read(HEADER.size - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    channel, kind, which, length = HEADER.unpack(hdr)
    payload = b""
    while len(payload) < length:
        chunk = stream.read(length - len(payload))
        if not chunk:
            return None
        payload += chunk
    return channel, kind, which, payload


def chunked(channel: int, which: int, data: bytes):
    """Yield DATA frames for an arbitrarily large read."""
    for off in range(0, len(data), MAX_PAYLOAD):
        yield pack(channel, K_DATA, which, data[off:off + MAX_PAYLOAD])
