"""Container-side bridge endpoint (runs under ``docker exec``).

Creates the in-container unix sockets (ssh-agent / gpg-agent), accepts
client connections, and muxes their bytes over stdio to the host side.
Stdlib-only; launched from the agentd zipapp:

    PYTHONPATH=/usr/local/lib/clawker-agentd.pyz \\
        python3 -m clawker_tpu.socketbridge.container

Parity reference: the reference's in-container ``clawker-socket-server``
binary (internal/hostproxy/internals/cmd), reached the same way (exec'd
by the host, stdio as the channel).
"""

from __future__ import annotations

import os
import socket
import sys
import threading

from .protocol import (
    K_CLOSE,
    K_DATA,
    K_OPEN,
    W_GPG,
    W_SSH,
    chunked,
    pack,
    read_frame,
)

SOCK_DIR = "/run/clawker"
SOCK_PATHS = {
    W_SSH: f"{SOCK_DIR}/ssh-agent.sock",
    W_GPG: f"{SOCK_DIR}/gpg-agent.sock",
}


class ContainerBridge:
    def __init__(self, stdin, stdout, sock_paths: dict[int, str] | None = None):
        self.stdin = stdin
        self.stdout = stdout
        self.sock_paths = sock_paths or SOCK_PATHS
        self._conns: dict[int, socket.socket] = {}
        self._next_channel = 1
        self._lock = threading.Lock()
        self._closed = threading.Event()

    def _send(self, frame: bytes) -> None:
        with self._lock:
            self.stdout.write(frame)
            self.stdout.flush()

    # ------------------------------------------------------- accept side

    def _serve_listener(self, which: int, path: str) -> None:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            if os.path.exists(path):
                os.unlink(path)
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            # analyze: allow(socket-hardening): in-container bridge
            # endpoint -- 0666 is the contract (the agent user is not the
            # exec user) and the container namespace is the boundary
            srv.bind(path)
            os.chmod(path, 0o666)  # the agent user is not the exec user
            srv.listen(8)
        except OSError as e:
            print(f"socketbridge: listener {path}: {e}", file=sys.stderr)
            return
        while not self._closed.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                break
            with self._lock:
                channel = self._next_channel
                self._next_channel += 1
                self._conns[channel] = conn
            self._send(pack(channel, K_OPEN, which))
            threading.Thread(
                target=self._pump_conn, args=(channel, which, conn),
                daemon=True,
            ).start()
        srv.close()

    def _pump_conn(self, channel: int, which: int, conn: socket.socket) -> None:
        try:
            while True:
                data = conn.recv(65536)
                if not data:
                    break
                for frame in chunked(channel, which, data):
                    self._send(frame)
        except OSError:
            pass
        self._drop(channel, which, notify=True)

    def _drop(self, channel: int, which: int, *, notify: bool) -> None:
        with self._lock:
            conn = self._conns.pop(channel, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            if notify:
                self._send(pack(channel, K_CLOSE, which))

    # ------------------------------------------------------ host -> here

    def run(self) -> None:
        for which, path in self.sock_paths.items():
            threading.Thread(
                target=self._serve_listener, args=(which, path), daemon=True
            ).start()
        while True:
            frame = read_frame(self.stdin)
            if frame is None:
                break
            channel, kind, which, payload = frame
            if kind == K_DATA:
                conn = self._conns.get(channel)
                if conn is not None:
                    try:
                        conn.sendall(payload)
                    except OSError:
                        self._drop(channel, which, notify=True)
            elif kind == K_CLOSE:
                self._drop(channel, which, notify=False)
        self._closed.set()
        for ch in list(self._conns):
            self._drop(ch, 0, notify=False)


def main() -> int:
    sock_dir = os.environ.get("CLAWKER_SOCK_DIR", SOCK_DIR)
    paths = {w: p.replace(SOCK_DIR, sock_dir, 1) for w, p in SOCK_PATHS.items()}
    ContainerBridge(sys.stdin.buffer, sys.stdout.buffer, paths).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
