"""Host-side bridge: relay container connections to the real agent sockets.

One ``Bridge`` per container: it launches the container-side endpoint
over ``docker exec`` (stdio hijack) and, for every OPEN frame, dials the
corresponding host socket (``SSH_AUTH_SOCK`` / gpg-agent extra socket)
and pumps bytes both ways.  ``SocketBridgeManager`` keys bridges by
container and tears them down on container stop.

Parity reference: internal/socketbridge Manager (manager.go:43) +
Bridge (bridge.go:59).
"""

from __future__ import annotations

import os
import socket
import subprocess
import threading

from .. import consts, logsetup
from ..errors import ClawkerError
from .protocol import K_CLOSE, K_DATA, K_OPEN, W_GPG, W_SSH, chunked, pack, read_frame

log = logsetup.get("socketbridge")

CONTAINER_CMD = [
    "python3", "-c",
    # zipapp on sys.path -> the package imports resolve from inside it
    "import sys; sys.path.insert(0, '" + consts.AGENTD_PYZ_PATH + "'); "
    "from clawker_tpu.socketbridge.container import main; sys.exit(main())",
]


_gpgconf_cache: str | None = None


def _gpgconf_extra_socket() -> str:
    """One SUCCESSFUL gpgconf subprocess per process: the answer depends
    only on the gpg home, and the probe was a fixed per-create cost.
    Failures stay retryable -- a host that grows a gpg setup mid-process
    must not be locked out of agent forwarding until restart."""
    global _gpgconf_cache
    if _gpgconf_cache is not None:
        return _gpgconf_cache
    try:
        res = subprocess.run(
            ["gpgconf", "--list-dirs", "agent-extra-socket"],
            capture_output=True, text=True, timeout=5,
        )
        if res.returncode == 0:
            _gpgconf_cache = res.stdout.strip()
            return _gpgconf_cache
    except (OSError, subprocess.SubprocessError):
        pass
    return ""


def default_host_sockets() -> dict[int, str]:
    out: dict[int, str] = {}
    ssh = os.environ.get("SSH_AUTH_SOCK", "")
    if ssh:
        out[W_SSH] = ssh
    gpg = os.environ.get("GPG_AGENT_EXTRA_SOCK", "") or _gpgconf_extra_socket()
    if gpg and os.path.exists(gpg):
        out[W_GPG] = gpg
    return out


class Bridge:
    """Pump frames between one exec stream and the host agent sockets.

    ``stream`` needs ``read(n)``, ``write(bytes)`` and ``close()`` --
    satisfied by the engine's HijackedStream and by test pipes alike.
    """

    def __init__(self, stream, host_sockets: dict[int, str]):
        self.stream = stream
        self.host_sockets = host_sockets
        self._conns: dict[int, socket.socket] = {}
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self.closed = threading.Event()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._pump, name="sockbridge",
                                        daemon=True)
        self._thread.start()

    def _send(self, frame: bytes) -> None:
        with self._lock:
            self.stream.write(frame)

    def _pump(self) -> None:
        try:
            while True:
                frame = read_frame(self.stream)
                if frame is None:
                    break
                channel, kind, which, payload = frame
                if kind == K_OPEN:
                    self._open(channel, which)
                elif kind == K_DATA:
                    conn = self._conns.get(channel)
                    if conn is not None:
                        try:
                            conn.sendall(payload)
                        except OSError:
                            self._drop(channel, which, notify=True)
                elif kind == K_CLOSE:
                    self._drop(channel, which, notify=False)
        except OSError:
            pass
        finally:
            self.close()

    def _open(self, channel: int, which: int) -> None:
        path = self.host_sockets.get(which)
        if not path:
            self._send(pack(channel, K_CLOSE, which))
            return
        try:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.connect(path)
        except OSError as e:
            log.warning("bridge open %d: %s: %s", which, path, e)
            self._send(pack(channel, K_CLOSE, which))
            return
        with self._lock:
            self._conns[channel] = conn
        threading.Thread(target=self._pump_host, args=(channel, which, conn),
                         daemon=True).start()

    def _pump_host(self, channel: int, which: int, conn: socket.socket) -> None:
        try:
            while True:
                data = conn.recv(65536)
                if not data:
                    break
                for frame in chunked(channel, which, data):
                    self._send(frame)
        except OSError:
            pass
        self._drop(channel, which, notify=True)

    def _drop(self, channel: int, which: int, *, notify: bool) -> None:
        with self._lock:
            conn = self._conns.pop(channel, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            if notify:
                try:
                    self._send(pack(channel, K_CLOSE, which))
                except OSError:
                    pass

    def close(self) -> None:
        if self.closed.is_set():
            return
        self.closed.set()
        with self._lock:
            for conn in self._conns.values():
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()

        # off-thread: closing a buffered stream another thread is blocked
        # reading deadlocks on CPython's buffered-IO lock; sockets (the
        # real exec channel) close instantly, pipes unblock on peer EOF
        def _close_stream():
            try:
                self.stream.close()
            except OSError:
                pass

        threading.Thread(target=_close_stream, daemon=True).start()


class SocketBridgeManager:
    """Per-container bridges over docker exec (EnsureBridge semantics)."""

    def __init__(self, engine, host_sockets: dict[int, str] | None = None):
        self.engine = engine
        self.host_sockets = (host_sockets if host_sockets is not None
                             else default_host_sockets())
        self._bridges: dict[str, Bridge] = {}
        self._lock = threading.Lock()
        self._closed = False

    def ensure_bridge(self, container_ref: str) -> Bridge | None:
        if not self.host_sockets:
            log.debug("no host agent sockets to forward; bridge skipped")
            return None
        with self._lock:
            existing = self._bridges.get(container_ref)
            if existing is not None and not existing.closed.is_set():
                return existing
        # the exec is an engine round-trip (and on tpu_vm a WAN hop):
        # doing it under the lock coupled every other caller -- and
        # close() -- to the daemon's latency.  Dial outside, then
        # settle the install race under the lock; the loser's bridge
        # (and its exec stream) is closed, the winner is shared.
        _eid, stream = self.engine.exec(
            container_ref, CONTAINER_CMD, stdin=True, tty=False,
        )
        if stream is None:
            raise ClawkerError(
                f"socketbridge: exec into {container_ref} gave no stream")
        bridge = Bridge(_RawStream(stream), self.host_sockets)
        bridge.start()
        with self._lock:
            if self._closed:
                # manager torn down while our exec was in flight: a
                # bridge installed now would outlive every close()
                winner, loser = None, bridge
            else:
                existing = self._bridges.get(container_ref)
                if existing is not None and not existing.closed.is_set():
                    winner, loser = existing, bridge
                else:
                    self._bridges[container_ref] = bridge
                    winner, loser = bridge, None
        if loser is not None:
            loser.close()
            return winner
        log.info("socket bridge up for %s (%s)", container_ref,
                 ",".join(str(w) for w in self.host_sockets))
        return winner

    def drop_bridge(self, container_ref: str) -> None:
        with self._lock:
            bridge = self._bridges.pop(container_ref, None)
        if bridge is not None:
            bridge.close()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            bridges, self._bridges = list(self._bridges.values()), {}
        for b in bridges:
            b.close()


class _RawStream:
    """Adapt a HijackedStream (frames() for non-tty) to read/write bytes."""

    def __init__(self, stream):
        self._stream = stream
        self._frames = stream.frames() if hasattr(stream, "frames") else None
        self._buf = b""

    def read(self, n: int) -> bytes:
        if self._frames is None:
            return self._stream.read(n)
        while len(self._buf) < n:
            try:
                fd, payload = next(self._frames)
            except StopIteration:
                break
            if fd == 2:  # container-side stderr: surface, don't mux
                log.warning("bridge stderr: %s",
                            payload.decode(errors="replace").strip())
                continue
            self._buf += payload
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def write(self, data: bytes) -> None:
        self._stream.write(data)

    def close(self) -> None:
        self._stream.close()
