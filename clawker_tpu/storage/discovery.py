"""Project config discovery: bounded walk-up, dir-form vs flat-form.

Parity reference: internal/storage discovery (SURVEY.md 2.5) -- static XDG
plus bounded walk-up finding either the dir form ``.clawker/clawker.yaml``
(with ``clawker.local.yaml`` overlay) or the flat form ``.clawker.yaml``
(with ``.clawker.local.yaml`` overlay).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from .. import consts
from .store import Layer


@dataclass
class ProjectDiscovery:
    """Result of walking up from a directory looking for project config."""

    root: Path                       # directory containing the config
    form: str                        # "dir" | "flat"
    layers: list[Layer] = field(default_factory=list)  # lowest priority first

    @property
    def config_path(self) -> Path:
        return self.layers[0].path


def _dir_form(root: Path) -> ProjectDiscovery | None:
    d = root / consts.PROJECT_DIR_FORM
    main = d / "clawker.yaml"
    if d.is_dir() and main.exists():
        local = d / "clawker.local.yaml"
        layers = [Layer("project", main)]
        layers.append(Layer("project-local", local))
        return ProjectDiscovery(root=root, form="dir", layers=layers)
    return None


def _flat_form(root: Path) -> ProjectDiscovery | None:
    main = root / consts.PROJECT_FLAT_FORM
    if main.exists():
        local = root / ".clawker.local.yaml"
        layers = [Layer("project", main), Layer("project-local", local)]
        return ProjectDiscovery(root=root, form="flat", layers=layers)
    return None


def discover_project_layers(start: Path | str, limit: int = consts.WALKUP_LIMIT) -> ProjectDiscovery | None:
    """Walk up from ``start`` (at most ``limit`` levels) to find project config.

    Dir form wins over flat form within one directory.  Returns None when no
    config is found before the filesystem root or the limit.
    """
    cur = Path(start).resolve()
    for _ in range(limit):
        found = _dir_form(cur) or _flat_form(cur)
        if found:
            return found
        if cur.parent == cur:
            return None
        cur = cur.parent
    return None
