"""Comment-preserving YAML edits: surgical line patches, verified.

The reference stores YAML as yaml.Node trees, so provenance-routed
writes keep comments and ordering byte-for-byte.  PyYAML has no node
round-trip, so this module patches the original TEXT instead: locate the
mapping line for a dotted path by an indentation scan, replace/insert/
delete just those lines, and VERIFY the result re-parses to exactly the
intended tree.  Block-sequence edits are item-surgical too: replacing,
inserting or deleting individual items (the hand-commented egress-rule
lists are the hot case) touches only that item's lines, so comments on
the key line and on OTHER items survive.  Anything not surgically
expressible (flow mappings/lists, anchors, multi-line scalars, list
reshuffles...) returns None and the caller falls back to a full
re-dump -- correctness never depends on this module, only comment
survival does.

Round-3 verdict weak #6: storage destroyed YAML comments on every
provenance-routed write; round-4 weak #5: list interiors still fell
back to the re-dump.
"""

from __future__ import annotations

import re

import yaml

_KEY_LINE = re.compile(r"^(\s*)([A-Za-z0-9_.\-\"']+)\s*:(.*)$")


def _render_scalar(value) -> str:
    """One-line YAML rendering of a scalar/short value."""
    text = yaml.safe_dump(value, default_flow_style=True, width=10**6).strip()
    if text.endswith("\n..."):
        text = text[:-4].strip()
    return text


def _render_block(key: str, value, indent: int) -> list[str]:
    """Render ``key: value`` as indented block lines."""
    pad = " " * indent
    if isinstance(value, (dict, list)) and value:
        body = yaml.safe_dump({key: value}, default_flow_style=False,
                              sort_keys=False)
        return [pad + line if line.strip() else line
                for line in body.rstrip("\n").split("\n")]
    return [f"{pad}{key}: {_render_scalar(value)}"]


class _Doc:
    """Indentation-indexed view of a YAML mapping document."""

    def __init__(self, text: str):
        self.lines = text.split("\n")
        # path -> (line_no, indent, inline_rest)
        self.index: dict[tuple[str, ...], tuple[int, int, str]] = {}
        self.ok = self._scan()

    def _scan(self) -> bool:
        stack: list[tuple[int, str]] = []   # (indent, key)
        item_guard: int | None = None       # indent of the innermost "- "
        for i, line in enumerate(self.lines):
            stripped = line.strip()
            if not stripped or stripped.startswith("#"):
                continue
            indent = len(line) - len(line.lstrip())
            if stripped == "-" or stripped.startswith("- "):
                # sequence items (and the keys inside them) are indexed
                # by _seq_items per edit, not here; guard their interiors
                if item_guard is None or indent < item_guard:
                    item_guard = indent
                continue
            if item_guard is not None:
                if indent > item_guard:
                    continue        # key inside an item's block
                item_guard = None   # left the sequence
            m = _KEY_LINE.match(line)
            if m is None:
                # multi-line scalar bodies etc.: tolerated as long as no
                # edit lands inside them (verification catches otherwise)
                continue
            key = m.group(2).strip("\"'")
            while stack and stack[-1][0] >= indent:
                stack.pop()
            stack.append((indent, key))
            path = tuple(k for _, k in stack)
            if path in self.index:
                return False  # duplicate key path: ambiguous target
            self.index[path] = (i, indent, m.group(3))
        return True

    def subtree_end(self, line_no: int, indent: int) -> int:
        """Last line (exclusive) of the block owned by the key line."""
        j = line_no + 1
        last_content = line_no + 1
        while j < len(self.lines):
            s = self.lines[j].strip()
            if s and not s.startswith("#"):
                cur = len(self.lines[j]) - len(self.lines[j].lstrip())
                if cur <= indent:
                    break
                last_content = j + 1
            j += 1
        return last_content


def _diff(before, after, prefix=()) -> list[tuple[str, tuple, object]]:
    """(op, path, payload) edits turning ``before`` into ``after``.

    Ops: set/del on mapping keys; setitem/delitem/insitem on sequence
    positions (payload = (index, value)), so single-item list changes --
    the egress-rule hot case -- patch one item's lines instead of
    re-dumping the whole block.  Unexpressible list reshapes degrade to
    a whole-value set."""
    out: list[tuple[str, tuple, object]] = []
    if isinstance(before, list) and isinstance(after, list):
        return _diff_list(before, after, prefix)
    if not isinstance(before, dict) or not isinstance(after, dict):
        if before != after:
            out.append(("set", prefix, after))
        return out
    for key in before:
        if key not in after:
            out.append(("del", prefix + (key,), None))
    for key, val in after.items():
        if key not in before:
            out.append(("set", prefix + (key,), val))
        elif before[key] != val:
            out.extend(_diff(before[key], val, prefix + (key,)))
    return out


def _diff_list(b: list, a: list, prefix: tuple) -> list[tuple[str, tuple, object]]:
    if b == a:
        return []
    if not a or not b:
        return [("set", prefix, a)]
    if len(a) == len(b):
        return [("setitem", prefix, (i, a[i]))
                for i in range(len(b)) if b[i] != a[i]]
    if len(a) < len(b):
        # removals with order preserved: two-pointer match; emitted
        # DESCENDING so earlier indices stay valid while applying
        dels, ai = [], 0
        for bi, item in enumerate(b):
            if ai < len(a) and item == a[ai]:
                ai += 1
            else:
                dels.append(bi)
        if ai == len(a):
            return [("delitem", prefix, (i, None)) for i in reversed(dels)]
        return [("set", prefix, a)]
    # insertions with order preserved: indices are final-array positions,
    # emitted ASCENDING so each insert lands before the right neighbor
    ins, bi = [], 0
    for ai, item in enumerate(a):
        if bi < len(b) and item == b[bi]:
            bi += 1
        else:
            ins.append((ai, item))
    if bi == len(b):
        return [("insitem", prefix, (i, v)) for i, v in ins]
    return [("set", prefix, a)]


def apply_edits(text: str, after: dict) -> str | None:
    """Patch ``text`` so it parses to ``after``, keeping comments and
    ordering.  None when the change is not surgically expressible (the
    caller re-dumps)."""
    try:
        before = yaml.safe_load(text) or {}
    except yaml.YAMLError:
        return None
    if not isinstance(before, dict):
        return None
    edits = _diff(before, after)
    if not edits:
        return text
    lines_text = text
    for op, path, value in sorted(edits, key=lambda e: len(e[1]), reverse=True):
        doc = _Doc(lines_text)
        if not doc.ok:
            return None
        patched = _apply_one(doc, op, path, value)
        if patched is None:
            return None
        lines_text = patched
    try:
        if yaml.safe_load(lines_text) != after:
            return None
    except yaml.YAMLError:
        return None
    return lines_text


def _seq_items(
    doc: _Doc, spath: tuple,
) -> tuple[list[tuple[int, int]], list[int], int] | None:
    """(comment-widened item spans, raw ``-`` line numbers, item indent)
    for the block sequence at ``spath``.  None when the list is not a
    plain block sequence (inline/flow, nested weirdness) -- callers
    fall back."""
    hit = doc.index.get(spath)
    if hit is None:
        return None
    line_no, indent, rest = hit
    if rest.strip() and not rest.strip().startswith("#"):
        return None  # flow list on the key line
    # items may legally sit at the SAME indent as their key (PyYAML's
    # default dump style), so the extent cannot come from subtree_end;
    # walk until a content line that is neither an item at item_indent
    # nor an item-interior line
    starts: list[int] = []
    item_indent = -1
    last_content = line_no
    for j in range(line_no + 1, len(doc.lines)):
        s = doc.lines[j]
        st = s.strip()
        if not st or st.startswith("#"):
            continue
        cur = len(s) - len(s.lstrip())
        is_item = st == "-" or st.startswith("- ")
        if item_indent < 0:
            if not (is_item and cur >= indent):
                return None  # first content under the key is not an item
            item_indent = cur
            starts.append(j)
            last_content = j
            continue
        if is_item and cur == item_indent:
            starts.append(j)
            last_content = j
        elif cur > item_indent:
            last_content = j   # item interior (incl. nested sequences)
        else:
            break              # left the sequence
    if not starts:
        return None
    # the sequence ends at its last CONTENT line: a standalone comment
    # block between the last item and the next key belongs to whatever
    # follows, so deleting/appending items never touches it
    end = last_content + 1
    # a comment block immediately above an item describes THAT item:
    # widen each span backwards over contiguous comment/blank lines so
    # deleting an item removes its own commentary and deleting its
    # predecessor keeps it
    widened: list[int] = []
    for k, s in enumerate(starts):
        floor = starts[k - 1] if k else line_no
        j = s
        while j - 1 > floor:
            st = doc.lines[j - 1].strip()
            if st and not st.startswith("#"):
                break  # previous item's (or the key's) content line
            j -= 1
        widened.append(j)
    spans = [(w, widened[k + 1] if k + 1 < len(widened) else end)
             for k, w in enumerate(widened)]
    return spans, starts, item_indent


def _render_item(value, indent: int) -> list[str]:
    body = yaml.safe_dump([value], default_flow_style=False, sort_keys=False)
    pad = " " * indent
    return [pad + line if line.strip() else line
            for line in body.rstrip("\n").split("\n")]


def _apply_item(doc: _Doc, op: str, spath: tuple, payload) -> str | None:
    got = _seq_items(doc, spath)
    if got is None:
        return None
    spans, starts, item_indent = got
    idx, value = payload
    if op == "delitem":
        # an item dies with its own leading comment block
        if not 0 <= idx < len(spans):
            return None
        s, e = spans[idx]
        return "\n".join(doc.lines[:s] + doc.lines[e:])
    if op == "setitem":
        # only the item's content is replaced; its leading comment block
        # keeps describing the (updated) item
        if not 0 <= idx < len(spans):
            return None
        s, e = starts[idx], spans[idx][1]
        return "\n".join(doc.lines[:s] + _render_item(value, item_indent)
                         + doc.lines[e:])
    # insitem: before the comment block of the item currently at idx (so
    # that comment stays with the item it describes); past-the-end appends
    if idx > len(spans):
        return None
    at = spans[idx][0] if idx < len(spans) else spans[-1][1]
    return "\n".join(doc.lines[:at] + _render_item(value, item_indent)
                     + doc.lines[at:])


def _block_end(doc: _Doc, spath: tuple, line_no: int, indent: int) -> int:
    """End (exclusive) of the value block owned by a key line, covering
    sequences whose items sit at the key's own indent (subtree_end's
    indentation rule cannot see those)."""
    end = doc.subtree_end(line_no, indent)
    got = _seq_items(doc, spath)
    if got is not None:
        end = max(end, got[0][-1][1])   # last widened span's end
    return end


def _apply_one(doc: _Doc, op: str, path: tuple, value) -> str | None:
    spath = tuple(str(p) for p in path)
    if op in ("setitem", "delitem", "insitem"):
        return _apply_item(doc, op, spath, value)
    hit = doc.index.get(spath)
    if op == "del":
        if hit is None:
            return None
        line_no, indent, _ = hit
        end = _block_end(doc, spath, line_no, indent)
        out = doc.lines[:line_no] + doc.lines[end:]
        # deleting the last child leaves `parent:` parsing as null, not
        # the empty mapping the tree holds: pin it to `parent: {}`
        parent = spath[:-1]
        if parent and not any(
                p[:len(parent)] == parent and p != spath and len(p) > len(parent)
                for p in doc.index):
            pline, pindent, prest = doc.index[parent]
            if not prest.strip() or prest.strip().startswith("#"):
                comment = f"  {prest.strip()}" if prest.strip() else ""
                out[pline] = " " * pindent + f"{parent[-1]}: {{}}" + comment
        return "\n".join(out)
    # set
    if hit is not None:
        line_no, indent, rest = hit
        if isinstance(value, (dict, list)) and value:
            # replacing a whole block: delete + re-insert rendered block
            end = _block_end(doc, spath, line_no, indent)
            block = _render_block(spath[-1], value, indent)
            return "\n".join(doc.lines[:line_no] + block + doc.lines[end:])
        # scalar in place: keep any trailing comment on the line
        comment = ""
        m = re.search(r"\s#(?![^\"']*[\"'][^#]*$).*$", rest)
        if m and not rest.strip().startswith("#"):
            comment = m.group(0)
        elif rest.strip().startswith("#"):
            comment = "  " + rest.strip()
        new_line = (" " * indent + f"{spath[-1]}: {_render_scalar(value)}"
                    + comment)
        end = _block_end(doc, spath, line_no, indent)
        if end > line_no + 1:
            # key owned a nested block: replace the whole block
            return "\n".join(doc.lines[:line_no] + [new_line] + doc.lines[end:])
        return "\n".join(doc.lines[:line_no] + [new_line] + doc.lines[line_no + 1:])
    # new key: insert under the deepest existing ancestor.  The suffix
    # below the ancestor nests into one rendered block.
    for depth in range(len(spath) - 1, -1, -1):
        anc = spath[:depth]
        suffix = spath[depth:]
        nested = _nest(suffix[1:], value)
        if not anc:
            body = _render_block(suffix[0], nested, 0)
            out = doc.lines[:]
            while out and not out[-1].strip():
                out.pop()
            return "\n".join(out + body)
        hit = doc.index.get(anc)
        if hit is None:
            continue
        line_no, indent, rest = hit
        if rest.strip() and not rest.strip().startswith("#"):
            return None  # ancestor holds an inline value: not expressible
        child_indent = _child_indent(doc, line_no, indent)
        end = doc.subtree_end(line_no, indent)
        body = _render_block(suffix[0], nested, child_indent)
        return "\n".join(doc.lines[:end] + body + doc.lines[end:])
    return None


def _nest(keys: tuple, value):
    for key in reversed(keys):
        value = {key: value}
    return value


def _child_indent(doc: _Doc, line_no: int, indent: int) -> int:
    """Indent of the key's existing children, or indent+2."""
    for j in range(line_no + 1, len(doc.lines)):
        s = doc.lines[j].strip()
        if not s or s.startswith("#"):
            continue
        cur = len(doc.lines[j]) - len(doc.lines[j].lstrip())
        if cur <= indent:
            break
        return cur
    return indent + 2
