"""Comment-preserving YAML edits: surgical line patches, verified.

The reference stores YAML as yaml.Node trees, so provenance-routed
writes keep comments and ordering byte-for-byte.  PyYAML has no node
round-trip, so this module patches the original TEXT instead: locate the
mapping line for a dotted path by an indentation scan, replace/insert/
delete just those lines, and VERIFY the result re-parses to exactly the
intended tree.  Anything not surgically expressible (list interiors,
flow mappings, anchors, multi-line scalars...) returns None and the
caller falls back to a full re-dump -- correctness never depends on this
module, only comment survival does.

Round-3 verdict weak #6: storage destroyed YAML comments on every
provenance-routed write (store.py safe_load round-trip).
"""

from __future__ import annotations

import re

import yaml

_KEY_LINE = re.compile(r"^(\s*)([A-Za-z0-9_.\-\"']+)\s*:(.*)$")


def _render_scalar(value) -> str:
    """One-line YAML rendering of a scalar/short value."""
    text = yaml.safe_dump(value, default_flow_style=True, width=10**6).strip()
    if text.endswith("\n..."):
        text = text[:-4].strip()
    return text


def _render_block(key: str, value, indent: int) -> list[str]:
    """Render ``key: value`` as indented block lines."""
    pad = " " * indent
    if isinstance(value, (dict, list)) and value:
        body = yaml.safe_dump({key: value}, default_flow_style=False,
                              sort_keys=False)
        return [pad + line if line.strip() else line
                for line in body.rstrip("\n").split("\n")]
    return [f"{pad}{key}: {_render_scalar(value)}"]


class _Doc:
    """Indentation-indexed view of a YAML mapping document."""

    def __init__(self, text: str):
        self.lines = text.split("\n")
        # path -> (line_no, indent, inline_rest)
        self.index: dict[tuple[str, ...], tuple[int, int, str]] = {}
        self.ok = self._scan()

    def _scan(self) -> bool:
        stack: list[tuple[int, str]] = []   # (indent, key)
        for i, line in enumerate(self.lines):
            stripped = line.strip()
            if not stripped or stripped.startswith("#"):
                continue
            if stripped.startswith("- "):
                continue  # list items are never edit targets; keys under
                #           them would need sequence tracking -> bail there
            m = _KEY_LINE.match(line)
            if m is None:
                # multi-line scalar bodies etc.: tolerated as long as no
                # edit lands inside them (verification catches otherwise)
                continue
            indent = len(m.group(1))
            key = m.group(2).strip("\"'")
            while stack and stack[-1][0] >= indent:
                stack.pop()
            stack.append((indent, key))
            path = tuple(k for _, k in stack)
            if path in self.index:
                return False  # duplicate key path: ambiguous target
            self.index[path] = (i, indent, m.group(3))
        return True

    def subtree_end(self, line_no: int, indent: int) -> int:
        """Last line (exclusive) of the block owned by the key line."""
        j = line_no + 1
        last_content = line_no + 1
        while j < len(self.lines):
            s = self.lines[j].strip()
            if s and not s.startswith("#"):
                cur = len(self.lines[j]) - len(self.lines[j].lstrip())
                if cur <= indent:
                    break
                last_content = j + 1
            j += 1
        return last_content


def _diff(before, after, prefix=()) -> list[tuple[str, tuple, object]]:
    """(op, path, value) edits turning ``before`` into ``after`` where op
    is set/del.  Non-dict containers diff as whole-value sets."""
    out: list[tuple[str, tuple, object]] = []
    if not isinstance(before, dict) or not isinstance(after, dict):
        if before != after:
            out.append(("set", prefix, after))
        return out
    for key in before:
        if key not in after:
            out.append(("del", prefix + (key,), None))
    for key, val in after.items():
        if key not in before:
            out.append(("set", prefix + (key,), val))
        elif before[key] != val:
            out.extend(_diff(before[key], val, prefix + (key,)))
    return out


def apply_edits(text: str, after: dict) -> str | None:
    """Patch ``text`` so it parses to ``after``, keeping comments and
    ordering.  None when the change is not surgically expressible (the
    caller re-dumps)."""
    try:
        before = yaml.safe_load(text) or {}
    except yaml.YAMLError:
        return None
    if not isinstance(before, dict):
        return None
    edits = _diff(before, after)
    if not edits:
        return text
    lines_text = text
    for op, path, value in sorted(edits, key=lambda e: len(e[1]), reverse=True):
        doc = _Doc(lines_text)
        if not doc.ok:
            return None
        patched = _apply_one(doc, op, path, value)
        if patched is None:
            return None
        lines_text = patched
    try:
        if yaml.safe_load(lines_text) != after:
            return None
    except yaml.YAMLError:
        return None
    return lines_text


def _apply_one(doc: _Doc, op: str, path: tuple, value) -> str | None:
    spath = tuple(str(p) for p in path)
    hit = doc.index.get(spath)
    if op == "del":
        if hit is None:
            return None
        line_no, indent, _ = hit
        end = doc.subtree_end(line_no, indent)
        out = doc.lines[:line_no] + doc.lines[end:]
        # deleting the last child leaves `parent:` parsing as null, not
        # the empty mapping the tree holds: pin it to `parent: {}`
        parent = spath[:-1]
        if parent and not any(
                p[:len(parent)] == parent and p != spath and len(p) > len(parent)
                for p in doc.index):
            pline, pindent, prest = doc.index[parent]
            if not prest.strip() or prest.strip().startswith("#"):
                comment = f"  {prest.strip()}" if prest.strip() else ""
                out[pline] = " " * pindent + f"{parent[-1]}: {{}}" + comment
        return "\n".join(out)
    # set
    if hit is not None:
        line_no, indent, rest = hit
        if isinstance(value, (dict, list)) and value:
            # replacing a whole block: delete + re-insert rendered block
            end = doc.subtree_end(line_no, indent)
            block = _render_block(spath[-1], value, indent)
            return "\n".join(doc.lines[:line_no] + block + doc.lines[end:])
        # scalar in place: keep any trailing comment on the line
        comment = ""
        m = re.search(r"\s#(?![^\"']*[\"'][^#]*$).*$", rest)
        if m and not rest.strip().startswith("#"):
            comment = m.group(0)
        elif rest.strip().startswith("#"):
            comment = "  " + rest.strip()
        new_line = (" " * indent + f"{spath[-1]}: {_render_scalar(value)}"
                    + comment)
        end = doc.subtree_end(line_no, indent)
        if end > line_no + 1:
            # key owned a nested block: replace the whole block
            return "\n".join(doc.lines[:line_no] + [new_line] + doc.lines[end:])
        return "\n".join(doc.lines[:line_no] + [new_line] + doc.lines[line_no + 1:])
    # new key: insert under the deepest existing ancestor.  The suffix
    # below the ancestor nests into one rendered block.
    for depth in range(len(spath) - 1, -1, -1):
        anc = spath[:depth]
        suffix = spath[depth:]
        nested = _nest(suffix[1:], value)
        if not anc:
            body = _render_block(suffix[0], nested, 0)
            out = doc.lines[:]
            while out and not out[-1].strip():
                out.pop()
            return "\n".join(out + body)
        hit = doc.index.get(anc)
        if hit is None:
            continue
        line_no, indent, rest = hit
        if rest.strip() and not rest.strip().startswith("#"):
            return None  # ancestor holds an inline value: not expressible
        child_indent = _child_indent(doc, line_no, indent)
        end = doc.subtree_end(line_no, indent)
        body = _render_block(suffix[0], nested, child_indent)
        return "\n".join(doc.lines[:end] + body + doc.lines[end:])
    return None


def _nest(keys: tuple, value):
    for key in reversed(keys):
        value = {key: value}
    return value


def _child_indent(doc: _Doc, line_no: int, indent: int) -> int:
    """Indent of the key's existing children, or indent+2."""
    for j in range(line_no + 1, len(doc.lines)):
        s = doc.lines[j].strip()
        if not s or s.startswith("#"):
            continue
        cur = len(doc.lines[j]) - len(doc.lines[j].lstrip())
        if cur <= indent:
            break
        return cur
    return indent + 2
