"""Layered YAML storage engine.

Parity reference: internal/storage (SURVEY.md 2.5) -- generic Store[T] with
static + walk-up discovery, N-way merge with per-field strategies
(union/overwrite), provenance-routed writes, atomic temp+rename, flock, and
per-layer migrations.
"""

from .store import Layer, Store, MergeStrategy
from .merge import merge_trees, Provenance
from .discovery import discover_project_layers, ProjectDiscovery

__all__ = [
    "Layer",
    "Store",
    "MergeStrategy",
    "merge_trees",
    "Provenance",
    "discover_project_layers",
    "ProjectDiscovery",
]
