"""Generic layered Store over YAML files.

Parity reference: internal/storage Store[T] (SURVEY.md 2.5): per-layer
migrations, N-way merge, provenance-routed writes, atomic temp+rename under
flock, lock-free snapshot reads.
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Generic, Mapping, Sequence, TypeVar

import yaml

from ..util.fs import atomic_write, file_lock
from .merge import (
    OVERWRITE,
    UNION,
    PathKey,
    Provenance,
    delete_path,
    get_path,
    merge_trees,
    set_path,
)

T = TypeVar("T")

MergeStrategy = str  # OVERWRITE | UNION

# A migration rewrites one layer's raw tree from schema version N to N+1.
Migration = Callable[[dict], dict]


@dataclass
class Layer:
    """One YAML file participating in the merge, lowest priority first."""

    name: str
    path: Path
    writable: bool = True

    def read(self) -> dict | None:
        if not self.path.exists():
            return None
        text = self.path.read_text(encoding="utf-8")
        data = yaml.safe_load(text)
        if data is None:
            return {}
        if not isinstance(data, dict):
            raise ValueError(f"layer {self.name} ({self.path}): top level must be a mapping")
        return data

@dataclass
class _Snapshot:
    merged: Any
    provenance: Provenance
    raw_layers: list[dict | None]


class Store(Generic[T]):
    """Layered YAML store with typed view, provenance, and routed writes.

    ``schema_factory`` converts the merged raw tree into the typed view T
    (usually a dataclass ``from_dict``).  ``strategies`` maps dotted paths to
    merge strategies; everything else defaults to overwrite.
    """

    def __init__(
        self,
        layers: Sequence[Layer],
        *,
        schema_factory: Callable[[dict], T] | None = None,
        strategies: Mapping[str, MergeStrategy] | None = None,
        migrations: Sequence[tuple[int, Migration]] = (),
        version: int = 1,
    ):
        self.layers = list(layers)
        self._schema_factory = schema_factory
        self._strategies: dict[PathKey, str] = {
            tuple(k.split(".")): v for k, v in (strategies or {}).items()
        }
        self._migrations = sorted(migrations)
        self._version = version
        self._lock = threading.Lock()
        self._snap: _Snapshot | None = None

    # ---------------------------------------------------------------- load

    def reload(self) -> None:
        raws: list[dict | None] = []
        for layer in self.layers:
            tree = layer.read()
            if tree is not None:
                tree = self._migrate(tree)
            raws.append(tree)
        merged, prov = merge_trees(
            [t if t is not None else None for t in raws], self._strategies
        )
        if merged is None:
            merged = {}
        if isinstance(merged, dict):
            merged.pop("_v", None)
        self._snap = _Snapshot(merged=merged, provenance=prov, raw_layers=raws)

    def _migrate(self, tree: dict) -> dict:
        v = int(tree.get("_v", 1))
        for target, fn in self._migrations:
            if v < target <= self._version:
                tree = fn(copy.deepcopy(tree))
                tree["_v"] = target
                v = target
        return tree

    def _snapshot(self) -> _Snapshot:
        snap = self._snap
        if snap is None:
            with self._lock:
                if self._snap is None:
                    self.reload()
                snap = self._snap
        assert snap is not None
        return snap

    # ---------------------------------------------------------------- read

    def raw(self) -> dict:
        """Merged raw tree (deep copy; callers may mutate freely)."""
        return copy.deepcopy(self._snapshot().merged)

    def typed(self) -> T:
        if self._schema_factory is None:
            raise TypeError("store has no schema_factory")
        return self._schema_factory(self.raw())

    def get(self, dotted: str, default: Any = None) -> Any:
        try:
            return copy.deepcopy(get_path(self._snapshot().merged, tuple(dotted.split("."))))
        except KeyError:
            return default

    def provenance_of(self, dotted: str) -> list[str]:
        """Names of the layers that supplied the effective value at ``dotted``."""
        snap = self._snapshot()
        key = tuple(dotted.split("."))
        idxs = snap.provenance.get(key, ())
        return [self.layers[i].name for i in idxs]

    # --------------------------------------------------------------- write

    def set(self, dotted: str, value: Any, *, layer: str | None = None) -> None:
        """Provenance-routed write.

        If ``layer`` is not given, the write goes to the layer that currently
        supplies the value (reference: provenance-routed writes,
        SURVEY.md 2.5); if the key is new, it goes to the highest-priority
        writable layer.
        """
        key = tuple(dotted.split("."))
        idx = self._route(key, layer)
        self._mutate_layer(idx, lambda tree: set_path(tree, key, value))

    def unset(self, dotted: str, *, layer: str | None = None) -> bool:
        key = tuple(dotted.split("."))
        try:
            idx = self._route(key, layer)
        except KeyError:
            return False
        changed = {"v": False}

        def fn(tree: dict) -> None:
            changed["v"] = delete_path(tree, key)

        self._mutate_layer(idx, fn)
        return changed["v"]

    def write_layer(self, layer_name: str, tree: dict) -> None:
        """Replace a whole layer's raw tree."""
        idx = self._layer_index(layer_name)
        self._mutate_layer(idx, None, replace=tree)

    def _route(self, key: PathKey, layer: str | None) -> int:
        if layer is not None:
            return self._layer_index(layer)
        snap = self._snapshot()
        idxs = snap.provenance.get(key, ())
        for i in reversed(idxs):
            if self.layers[i].writable:
                return i
        for i in reversed(range(len(self.layers))):
            if self.layers[i].writable:
                return i
        raise PermissionError("no writable layer")

    def _layer_index(self, name: str) -> int:
        for i, l in enumerate(self.layers):
            if l.name == name:
                return i
        raise KeyError(f"no layer named {name!r}")

    def _mutate_layer(
        self,
        idx: int,
        fn: Callable[[dict], Any] | None,
        *,
        replace: dict | None = None,
    ) -> None:
        layer = self.layers[idx]
        with self._lock:
            with file_lock(layer.path):
                original = (layer.path.read_text(encoding="utf-8")
                            if layer.path.exists() else "")
                tree = (yaml.safe_load(original) if original else None) or {}
                if not isinstance(tree, dict):
                    raise ValueError(
                        f"layer {layer.name} ({layer.path}): top level must "
                        "be a mapping")
                tree = self._migrate(tree)
                if replace is not None:
                    tree = copy.deepcopy(replace)
                elif fn is not None:
                    fn(tree)
                if self._version > 1:
                    tree["_v"] = self._version
                # comment-preserving surgical patch first; a change the
                # editor cannot express (or that fails its re-parse
                # verification) falls back to a full re-dump
                from .yamledit import apply_edits

                text = apply_edits(original, tree) if original else None
                if text is None:
                    text = yaml.safe_dump(tree, sort_keys=False,
                                          default_flow_style=False)
                elif text and not text.endswith("\n"):
                    text += "\n"
                atomic_write(layer.path, text)
            self._snap = None  # invalidate snapshot; next read re-merges


__all__ = ["Layer", "Store", "MergeStrategy", "OVERWRITE", "UNION"]
