"""N-way tree merge with per-path strategies and provenance tracking.

Semantics (parity reference: internal/storage merge engine with
``merge:"union"|"overwrite"`` struct tags, SURVEY.md 2.5):

* Layers are ordered lowest priority first; later layers override earlier.
* Mappings merge recursively, key by key.
* Scalars: highest-priority layer that defines the key wins.
* Lists: strategy ``overwrite`` (default) -- highest layer's list replaces;
  strategy ``union`` -- concatenation lowest-to-highest with stable
  de-duplication (first occurrence kept).
* ``None`` in a higher layer is an explicit override to null (it wins), but a
  layer simply not defining a key does not mask lower layers.
* Provenance records, for every leaf path, which layer index supplied the
  effective value (for union lists: every contributing layer).
"""

from __future__ import annotations

from typing import Any, Mapping

PathKey = tuple[str, ...]
Provenance = dict[PathKey, tuple[int, ...]]

OVERWRITE = "overwrite"
UNION = "union"


def _strategy_for(path: PathKey, strategies: Mapping[PathKey, str]) -> str:
    if path in strategies:
        return strategies[path]
    # Allow glob-ish addressing one level deep: ("security", "egress", "*")
    for cand, strat in strategies.items():
        if len(cand) == len(path) and all(a == "*" or a == b for a, b in zip(cand, path)):
            return strat
    return OVERWRITE


def _canon(item: Any) -> str:
    """Order-insensitive canonical key for union dedupe (two YAML mappings
    with the same keys in different order are the same rule)."""
    import json

    try:
        return json.dumps(item, sort_keys=True, default=repr)
    except (TypeError, ValueError):
        return repr(item)


def _dedupe(items: list[Any]) -> list[Any]:
    seen: set[str] = set()
    out: list[Any] = []
    for it in items:
        key = _canon(it)
        if key not in seen:
            seen.add(key)
            out.append(it)
    return out


def merge_trees(
    trees: list[Any],
    strategies: Mapping[PathKey, str] | None = None,
) -> tuple[Any, Provenance]:
    """Merge raw YAML trees (dict/list/scalar) lowest-priority-first.

    Returns ``(merged, provenance)``.  Layer indexes in provenance refer to
    positions in ``trees``.
    """
    strategies = strategies or {}
    prov: Provenance = {}
    merged = _merge_at((), [(i, t) for i, t in enumerate(trees) if t is not None], strategies, prov)
    return merged, prov


def _merge_at(
    path: PathKey,
    entries: list[tuple[int, Any]],
    strategies: Mapping[PathKey, str],
    prov: Provenance,
) -> Any:
    if not entries:
        return None
    # If every present value is a mapping, merge recursively.
    if all(isinstance(v, Mapping) for _, v in entries):
        keys: list[str] = []
        for _, tree in entries:
            for k in tree:
                if k not in keys:
                    keys.append(k)
        out: dict[str, Any] = {}
        for k in keys:
            sub = [(i, v[k]) for i, v in entries if k in v]
            out[k] = _merge_at(path + (str(k),), sub, strategies, prov)
        return out
    # Lists under a union strategy combine across layers.
    if all(isinstance(v, list) for _, v in entries) and _strategy_for(path, strategies) == UNION:
        combined: list[Any] = []
        contributors: list[int] = []
        for i, v in entries:
            if v:
                contributors.append(i)
            combined.extend(v)
        prov[path] = tuple(contributors) or (entries[-1][0],)
        return _dedupe(combined)
    # Otherwise the highest-priority entry wins outright (scalar, list
    # overwrite, or mixed types where the override changes shape).
    winner_idx, winner = entries[-1]
    prov[path] = (winner_idx,)
    return winner


def get_path(tree: Any, path: PathKey) -> Any:
    cur = tree
    for p in path:
        if not isinstance(cur, Mapping) or p not in cur:
            raise KeyError(".".join(path))
        cur = cur[p]
    return cur


def set_path(tree: dict, path: PathKey, value: Any) -> None:
    cur = tree
    for p in path[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[p] = nxt
        cur = nxt
    cur[path[-1]] = value


def delete_path(tree: dict, path: PathKey) -> bool:
    cur = tree
    for p in path[:-1]:
        if not isinstance(cur, Mapping) or p not in cur:
            return False
        cur = cur[p]
    if isinstance(cur, dict) and path[-1] in cur:
        del cur[path[-1]]
        return True
    return False
