"""Iteration trace spans: typed records + tree reconstruction.

Every loop iteration becomes a span tree::

    iteration                       (root; agent/worker/epoch attributes)
      +- create                     (fresh container only)
      +- start                      (engine start + bootstrap)
      +- wait                       (container executing the harness)
      +- exit | orphan | migrate    (how the iteration ended / moved)
      +- resume                     (zero-width: --resume adopted it)

Spans are recorded COMPLETE (start + end timestamps known at record
time) because the scheduler knows both ends of every phase it drives;
there is no context-propagation machinery to pay for on the hot path.
Each record is emitted as a typed EventBus record (so dashboards see
spans interleaved with agent events, in order) and appended to the
per-run JSONL flight recorder (:class:`~clawker_tpu.monitor.ledger.
FlightRecorder`); ``clawker loop trace`` rebuilds the tree offline.

Reconstruction (:func:`build_trees`) is defensive by design: the flight
recorder is append-only from many threads, so records land OUT OF
ORDER, and a crashed run may leave root spans unclosed or children
whose parent never flushed.  Orphan children are promoted to roots
rather than dropped -- a post-mortem tool must show what it has, not
only what is well-formed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Iterable

from ..monitor.ledger import parse_jsonl
from ..util import ids

# span names
SPAN_ITERATION = "iteration"
SPAN_CREATE = "create"
SPAN_START = "start"
SPAN_WAIT = "wait"
SPAN_EXIT = "exit"
SPAN_ORPHAN = "orphan"
SPAN_MIGRATE = "migrate"
SPAN_RESUME = "resume"      # zero-width hop: --resume adopted/continued
#                             this iteration across a scheduler death
SPAN_SENTINEL_TICK = "sentinel.tick"    # one fleet-wide scoring tick
#                             (clawker_tpu/sentinel); a run-level span

# Root spans that are NOT iteration roots by design (run-level
# subsystems recording into the same flight file).  `loop trace` and
# the chaos span-tree invariant treat any OTHER non-iteration root as
# evidence of a writer that died mid-flush.
STANDALONE_SPANS = frozenset({SPAN_SENTINEL_TICK})


@dataclass(frozen=True)
class SpanRecord:
    """One completed span.  ``trace_id`` is the loop run id; the
    (agent, iteration, attempt) triple plus parent links rebuild the
    tree without any in-order delivery guarantee."""

    trace_id: str
    span_id: str
    parent_id: str          # "" = root (an iteration span)
    name: str
    agent: str
    worker: str
    t_start: float          # unix seconds
    t_end: float
    status: str = "ok"      # ok | failed | orphaned | stopped
    attrs: dict = field(default_factory=dict)

    @property
    def wall_s(self) -> float:
        return max(0.0, self.t_end - self.t_start)

    def to_json(self) -> dict:
        return {
            "kind": "span", "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "name": self.name, "agent": self.agent, "worker": self.worker,
            "t_start": self.t_start, "t_end": self.t_end,
            "status": self.status, "attrs": dict(self.attrs),
        }

    @classmethod
    def from_json(cls, doc: dict) -> "SpanRecord":
        return cls(
            trace_id=str(doc.get("trace_id", "")),
            span_id=str(doc.get("span_id", "")),
            parent_id=str(doc.get("parent_id", "")),
            name=str(doc.get("name", "")),
            agent=str(doc.get("agent", "")),
            worker=str(doc.get("worker", "")),
            t_start=float(doc.get("t_start", 0.0)),
            t_end=float(doc.get("t_end", 0.0)),
            status=str(doc.get("status", "ok")),
            attrs=dict(doc.get("attrs") or {}),
        )

    # compact EventBus detail: "<name> <worker> <ms>ms [k=v ...]"
    def detail(self) -> str:
        base = f"{self.name} {self.worker} {self.wall_s * 1000:.1f}ms"
        extras = " ".join(f"{k}={v}" for k, v in sorted(self.attrs.items()))
        return f"{base} {extras}" if extras else base


@dataclass
class SpanNode:
    """Reconstructed tree node."""

    record: SpanRecord
    children: list["SpanNode"] = field(default_factory=list)


def build_trees(records: Iterable[SpanRecord]) -> list[SpanNode]:
    """Span records (any order) -> roots sorted by (t_start, agent).

    Children sort by start time under their parent.  A child whose
    parent is missing (lost write, crashed run) becomes a root so the
    data still renders.
    """
    nodes: dict[str, SpanNode] = {}
    order: list[SpanNode] = []
    for rec in records:
        node = SpanNode(rec)
        # a duplicated span_id (double flush) keeps the LAST record:
        # re-emits happen on retry paths where the later one is complete
        if rec.span_id in nodes:
            nodes[rec.span_id].record = rec
            continue
        nodes[rec.span_id] = node
        order.append(node)
    roots: list[SpanNode] = []
    for node in order:
        parent = nodes.get(node.record.parent_id) if node.record.parent_id else None
        if parent is None or parent is node:
            roots.append(node)
        else:
            parent.children.append(node)
    for node in order:
        node.children.sort(key=lambda n: (n.record.t_start, n.record.name))
    roots.sort(key=lambda n: (n.record.t_start, n.record.agent))
    return roots


def tree_to_dict(node: SpanNode) -> dict:
    doc = node.record.to_json()
    doc.pop("kind", None)
    doc["wall_ms"] = round(node.record.wall_s * 1000, 3)
    doc["children"] = [tree_to_dict(c) for c in node.children]
    return doc


class Tracer:
    """The scheduler's span factory: opens iteration roots, records
    phase children, and flushes every completed span to the sinks.

    Thread-safety: lane threads open/extend iteration spans while the
    run thread ends them; the open-span table rides one lock.  Sinks
    (EventBus emit + FlightRecorder append) are called OUTSIDE it --
    both are internally synchronized and must not serialize tracing.
    """

    def __init__(self, trace_id: str, *, on_span=None, clock=time.time):
        self.trace_id = trace_id
        self.on_span = on_span          # callable(SpanRecord)
        self._clock = clock
        import threading

        self._lock = threading.Lock()
        # (agent, iteration) -> open root: [span_id, t_start, worker, attrs]
        self._open: dict[tuple[str, int], list] = {}

    # ------------------------------------------------------------ plumbing

    def now(self) -> float:
        return self._clock()

    def _flush(self, rec: SpanRecord) -> None:
        if self.on_span is not None:
            try:
                self.on_span(rec)
            except Exception:   # noqa: BLE001 -- telemetry never raises into
                pass            # the scheduler hot path

    # ------------------------------------------------------------- surface

    def begin_iteration(self, agent: str, iteration: int, worker: str,
                        **attrs) -> str:
        """Open (idempotently) the root span for this (agent, iteration)
        attempt.  A re-placed iteration opens a FRESH root: the orphaned
        attempt's root was already closed when the worker died.

        A repeat begin on an open root merges attrs the root does not
        hold yet (first value wins): the rescue pass opens a migrated
        attempt's root before the lane task measures its queue wait, and
        the later begin must attach ``queue_ms`` rather than drop it.
        """
        with self._lock:
            entry = self._open.get((agent, iteration))
            if entry is not None:
                for k, v in attrs.items():
                    entry[3].setdefault(k, v)
                return entry[0]
            span_id = ids.short_id(16)
            self._open[(agent, iteration)] = [span_id, self.now(), worker,
                                              dict(attrs)]
            return span_id

    def open_root(self, agent: str, iteration: int) -> str:
        """The open root's span id, or "" -- a PEEK (never opens): the
        workerd dispatch path asks for a traceparent to stamp on adopt
        intents, and must not conjure roots for iterations that have
        not begun."""
        with self._lock:
            entry = self._open.get((agent, iteration))
            return entry[0] if entry is not None else ""

    def child(self, agent: str, iteration: int, name: str,
              t_start: float, t_end: float, *, worker: str = "",
              status: str = "ok", **attrs) -> SpanRecord | None:
        with self._lock:
            entry = self._open.get((agent, iteration))
            if entry is None:
                return None     # span already closed (stale lane task)
            parent_id, _, root_worker, _ = entry
        rec = SpanRecord(
            trace_id=self.trace_id, span_id=ids.short_id(16),
            parent_id=parent_id, name=name, agent=agent,
            worker=worker or root_worker, t_start=t_start, t_end=t_end,
            status=status, attrs={"iteration": iteration, **attrs})
        self._flush(rec)
        return rec

    def end_iteration(self, agent: str, iteration: int, status: str = "ok",
                      **attrs) -> SpanRecord | None:
        with self._lock:
            entry = self._open.pop((agent, iteration), None)
        if entry is None:
            return None
        span_id, t_start, worker, open_attrs = entry
        rec = SpanRecord(
            trace_id=self.trace_id, span_id=span_id, parent_id="",
            name=SPAN_ITERATION, agent=agent, worker=worker,
            t_start=t_start, t_end=self.now(), status=status,
            attrs={"iteration": iteration, **open_attrs, **attrs})
        self._flush(rec)
        return rec

    def close_open(self, status: str = "stopped") -> int:
        """Flush every still-open root (run stopped / crashed) so the
        flight record never loses an iteration that was in flight."""
        with self._lock:
            entries = list(self._open.items())
            self._open.clear()
        for (agent, iteration), (span_id, t_start, worker, attrs) in entries:
            self._flush(SpanRecord(
                trace_id=self.trace_id, span_id=span_id, parent_id="",
                name=SPAN_ITERATION, agent=agent, worker=worker,
                t_start=t_start, t_end=self.now(), status=status,
                attrs={"iteration": iteration, **attrs}))
        return len(entries)


def load_spans(lines: Iterable[str]) -> list[SpanRecord]:
    """Parse flight-recorder JSONL into span records, skipping non-span
    records and corrupt lines (one shared tolerant parse --
    monitor.ledger.parse_jsonl -- so this reader can never diverge from
    FlightRecorder.read)."""
    return [SpanRecord.from_json(doc) for doc in parse_jsonl(lines)
            if doc.get("kind") == "span"]
