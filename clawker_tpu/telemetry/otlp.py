"""Ship registry snapshots over the control plane's OTLP lanes.

The reference clawker's monitoring stack ingests everything through an
OTel Collector; our CP subsystems already hold per-subsystem OTLP/HTTP
lanes (controlplane/otel.py, mTLS-capable).  Fleet metrics ride the
same transport: a shipper thread snapshots the registry every
``interval_s`` and POSTs the samples as one batch on a
``clawker-telemetry`` lane, so the collector-side routing that indexes
CP logs needs zero new endpoints to pick up fleet metrics.

Shipping is best-effort by the lane's contract -- a downed collector
degrades telemetry, never the loop run.
"""

from __future__ import annotations

import threading

from .. import logsetup
from .registry import REGISTRY, MetricsRegistry

log = logsetup.get("telemetry.otlp")

TELEMETRY_SUBSYSTEM = "clawker-telemetry"
DEFAULT_INTERVAL_S = 10.0


def telemetry_lane(cfg):
    """The fleet-telemetry OTLP lane for this deployment, or None when
    no collector endpoint is configured (CLAWKER_TPU_OTLP env / local
    monitoring stack) -- same resolution as the CP's own lanes."""
    from ..controlplane.otel import build_lanes

    return build_lanes(cfg, (TELEMETRY_SUBSYSTEM,)).get(TELEMETRY_SUBSYSTEM)


class MetricsOtlpShipper:
    """Periodic registry -> OTLP batches on a daemon thread.

    ``lane`` is any object with ``ship(records) -> bool``
    (controlplane.otel.OtlpLane in production, a list-appender in
    tests).  ``stop()`` ships one final snapshot so a short run's
    metrics are never lost to the interval."""

    def __init__(self, lane, *, registry: MetricsRegistry | None = None,
                 interval_s: float = DEFAULT_INTERVAL_S):
        self.lane = lane
        self.registry = registry if registry is not None else REGISTRY
        self.interval_s = interval_s
        self.shipped_batches = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def ship_once(self) -> bool:
        records = self.registry.snapshot()
        if not records:
            return False
        try:
            ok = bool(self.lane.ship(records))
        except Exception as e:   # noqa: BLE001 -- lane contract: never raise
            log.debug("telemetry otlp ship failed: %s", e)
            return False
        if ok:
            self.shipped_batches += 1
        return ok

    def start(self) -> "MetricsOtlpShipper":
        if self._thread is not None:
            return self
        self._stop.clear()

        def pump() -> None:
            while not self._stop.wait(self.interval_s):
                self.ship_once()

        self._thread = threading.Thread(target=pump, name="telemetry-otlp",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.ship_once()    # final flush: short runs still land a batch
