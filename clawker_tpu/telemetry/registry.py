"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The fleet's telemetry used to live in three disconnected fragments: the
``util/phases`` stopwatch (bench-only, enable/disable around a run),
ad-hoc counters inside ``engine/pool.py``'s stats dict, and per-worker
count dicts in ``health/monitor.py``.  None of them could answer "what
is the engine's request latency per verb right now" without a re-run.
This registry subsumes them: every subsystem registers named metrics
once at import time and records into them on the hot path; consumers
(the Prometheus endpoint, the OTLP shipper, ``clawker fleet health``)
read consistent snapshots.

Design constraints, in order:

- **Hot-path cost.**  A record is one enabled-flag read, one dict hit
  on the child cache (only on first use per label set), and one
  striped-lock increment.  ``set_enabled(False)`` turns every record
  into a single attribute check -- bench.py's ``telemetry_overhead_ns``
  gates both paths so instrumentation can never silently regress the
  cold-start budget.
- **Lock striping.**  One global lock would couple every lane, waiter,
  prober, and the scrape handler; per-child locks would allocate one
  lock per label set.  Children hash onto a fixed stripe array instead:
  concurrent writers to DIFFERENT metrics almost never contend, and a
  scrape takes the stripes one at a time, never stopping the world.
- **Fixed buckets.**  Histograms pre-declare their bucket bounds, so
  ``observe`` is a linear scan over a small tuple (latency histograms
  here have <= 14 bounds) and exposition needs no merging.

Not a tracing system -- spans live in :mod:`clawker_tpu.telemetry.spans`.
``util/phases`` stays for bench cold-start attribution (its
enable/around-a-run contract is different); new instrumentation should
land here.
"""

from __future__ import annotations

import threading
from bisect import bisect_left

N_STRIPES = 16

# Default latency buckets (seconds): spans dial-on-unix (~100us) through
# a wedged-SSH probe deadline (multi-second).
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_KIND_COUNTER = "counter"
_KIND_GAUGE = "gauge"
_KIND_HISTOGRAM = "histogram"


def _format_value(v: float) -> str:
    """Prometheus sample formatting: integers without the trailing .0."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _label_str(labelnames: tuple[str, ...], labelvalues: tuple[str, ...],
               extra: str = "") -> str:
    pairs = [f'{k}="{_escape(v)}"' for k, v in zip(labelnames, labelvalues)]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Child:
    """One (metric, label-values) time series.  All mutation rides the
    stripe lock the registry assigned at creation."""

    __slots__ = ("_metric", "labelvalues", "_lock", "value",
                 "bucket_counts", "sum")

    def __init__(self, metric: "Metric", labelvalues: tuple[str, ...],
                 lock: threading.Lock):
        self._metric = metric
        self.labelvalues = labelvalues
        self._lock = lock
        self.value = 0.0
        if metric.kind == _KIND_HISTOGRAM:
            self.bucket_counts = [0] * (len(metric.buckets) + 1)  # +Inf last
            self.sum = 0.0

    # ------------------------------------------------------------ counter

    def inc(self, n: float = 1.0) -> None:
        if not self._metric.registry.enabled:
            return
        with self._lock:
            self.value += n

    # -------------------------------------------------------------- gauge

    def set(self, v: float) -> None:
        if not self._metric.registry.enabled:
            return
        with self._lock:
            self.value = v

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    # ---------------------------------------------------------- histogram

    def observe(self, v: float) -> None:
        if not self._metric.registry.enabled:
            return
        idx = bisect_left(self._metric.buckets, v)
        with self._lock:
            self.bucket_counts[idx] += 1
            self.value += 1          # observation count
            self.sum += v

    # ----------------------------------------------------------- snapshot

    def peek(self) -> float:
        with self._lock:
            return self.value


class Metric:
    """A named metric family; label sets materialize children on demand."""

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 kind: str, labelnames: tuple[str, ...],
                 buckets: tuple[float, ...] = ()):
        self.registry = registry
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = labelnames
        self.buckets = tuple(sorted(buckets)) if kind == _KIND_HISTOGRAM else ()
        self._children: dict[tuple[str, ...], _Child] = {}
        self._children_lock = threading.Lock()
        if not labelnames:
            self._default = self._child(())

    def _child(self, labelvalues: tuple[str, ...]) -> _Child:
        child = self._children.get(labelvalues)
        if child is not None:
            return child
        with self._children_lock:
            child = self._children.get(labelvalues)
            if child is None:
                child = _Child(self, labelvalues,
                               self.registry._stripe(self.name, labelvalues))
                self._children[labelvalues] = child
            return child

    def labels(self, *labelvalues: str, **labelkw: str) -> _Child:
        if labelkw:
            labelvalues = tuple(str(labelkw[k]) for k in self.labelnames)
        else:
            labelvalues = tuple(str(v) for v in labelvalues)
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name}: got {len(labelvalues)} label values "
                f"for labels {self.labelnames}")
        return self._child(labelvalues)

    # unlabeled convenience: metric.inc() / .set() / .observe()
    def inc(self, n: float = 1.0) -> None:
        self._default.inc(n)

    def set(self, v: float) -> None:
        self._default.set(v)

    def observe(self, v: float) -> None:
        self._default.observe(v)

    def children(self) -> list[_Child]:
        with self._children_lock:
            return list(self._children.values())


class MetricsRegistry:
    """Named-metric store with striped locks and consistent-enough reads.

    Registration is idempotent: a second ``counter(name, ...)`` returns
    the existing family (so modules can declare their metrics at import
    time without ordering constraints), but re-registering a name as a
    different kind is a programming error and raises.
    """

    def __init__(self, *, enabled: bool = True):
        self.enabled = enabled
        self._metrics: dict[str, Metric] = {}
        self._lock = threading.Lock()
        self._stripes = [threading.Lock() for _ in range(N_STRIPES)]

    # --------------------------------------------------------- registration

    def _register(self, name: str, help: str, kind: str,
                  labelnames: tuple[str, ...],
                  buckets: tuple[float, ...] = ()) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind or m.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind} "
                        f"with labels {m.labelnames}")
                return m
            m = Metric(self, name, help, kind, labelnames, buckets)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: tuple[str, ...] = ()) -> Metric:
        return self._register(name, help, _KIND_COUNTER, tuple(labels))

    def gauge(self, name: str, help: str = "",
              labels: tuple[str, ...] = ()) -> Metric:
        return self._register(name, help, _KIND_GAUGE, tuple(labels))

    def histogram(self, name: str, help: str = "",
                  labels: tuple[str, ...] = (),
                  buckets: tuple[float, ...] = LATENCY_BUCKETS) -> Metric:
        return self._register(name, help, _KIND_HISTOGRAM, tuple(labels),
                              buckets)

    def _stripe(self, name: str, labelvalues: tuple[str, ...]) -> threading.Lock:
        return self._stripes[hash((name, labelvalues)) % N_STRIPES]

    # -------------------------------------------------------------- control

    def set_enabled(self, enabled: bool) -> None:
        """Global record gate.  Metric handles stay valid either way;
        disabled records cost one attribute read."""
        self.enabled = enabled

    def reset(self) -> None:
        """Zero every series in place (tests, bench).  Handles cached at
        module import keep working -- values reset, identity doesn't."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            for c in m.children():
                with c._lock:
                    c.value = 0.0
                    if m.kind == _KIND_HISTOGRAM:
                        c.bucket_counts = [0] * (len(m.buckets) + 1)
                        c.sum = 0.0

    # ------------------------------------------------------------ consumers

    def snapshot(self) -> list[dict]:
        """Point-in-time sample list (OTLP shipper, fleet health).
        Consistent per series; the set of series is whatever existed when
        the snapshot started."""
        out: list[dict] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            for c in sorted(m.children(), key=lambda c: c.labelvalues):
                labels = dict(zip(m.labelnames, c.labelvalues))
                with c._lock:
                    row = {"metric": m.name, "kind": m.kind, "labels": labels,
                           "value": c.value}
                    if m.kind == _KIND_HISTOGRAM:
                        row["sum"] = c.sum
                        row["buckets"] = dict(zip(
                            [*map(str, m.buckets), "+Inf"],
                            list(c.bucket_counts)))
                out.append(row)
        return out

    def exposition(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every series."""
        lines: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            children = sorted(m.children(), key=lambda c: c.labelvalues)
            if not children:
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for c in children:
                if m.kind == _KIND_HISTOGRAM:
                    with c._lock:
                        counts = list(c.bucket_counts)
                        total, s = c.value, c.sum
                    acc = 0
                    for bound, n in zip(m.buckets, counts):
                        acc += n
                        le = 'le="' + _format_value(bound) + '"'
                        labels = _label_str(m.labelnames, c.labelvalues, le)
                        lines.append(f"{m.name}_bucket{labels} {acc}")
                    labels = _label_str(m.labelnames, c.labelvalues,
                                        'le="+Inf"')
                    lines.append(f"{m.name}_bucket{labels} {int(total)}")
                    lines.append(
                        f"{m.name}_sum{_label_str(m.labelnames, c.labelvalues)}"
                        f" {repr(s)}")
                    lines.append(
                        f"{m.name}_count{_label_str(m.labelnames, c.labelvalues)}"
                        f" {int(total)}")
                else:
                    lines.append(
                        f"{m.name}{_label_str(m.labelnames, c.labelvalues)}"
                        f" {_format_value(c.peek())}")
        return "\n".join(lines) + ("\n" if lines else "")


# The process-wide default registry.  Subsystems register against this
# at import time; `telemetry.REGISTRY` is the single scrape/ship source.
REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "", labels: tuple[str, ...] = ()) -> Metric:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: tuple[str, ...] = ()) -> Metric:
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: tuple[str, ...] = (),
              buckets: tuple[float, ...] = LATENCY_BUCKETS) -> Metric:
    return REGISTRY.histogram(name, help, labels, buckets)
