"""Unified fleet telemetry: metrics registry, trace spans, exporters.

Three consumers, one source of truth:

- :mod:`.registry` -- process-wide counters/gauges/histograms behind a
  lock-striped :class:`MetricsRegistry`; subsystems (engine pool/client,
  loop lanes, health probes/breakers) register at import time and record
  on the hot path.  ``REGISTRY`` is the process default.
- :mod:`.httpserv` -- opt-in local Prometheus scrape endpoint
  (``clawker loop --metrics-port``).
- :mod:`.otlp` -- registry snapshots batched over the control plane's
  existing OTLP lanes (controlplane/otel.py).
- :mod:`.spans` -- per-iteration span records + tree reconstruction for
  the flight recorder and ``clawker loop trace``.

See docs/telemetry.md for metric names, the span schema, and setup.
"""

from .httpserv import MetricsServer
from .otlp import MetricsOtlpShipper, telemetry_lane
from .registry import (
    LATENCY_BUCKETS,
    REGISTRY,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
)
from .spans import (
    SPAN_CREATE,
    SPAN_EXIT,
    SPAN_ITERATION,
    SPAN_MIGRATE,
    SPAN_ORPHAN,
    SPAN_START,
    SPAN_WAIT,
    SpanNode,
    SpanRecord,
    Tracer,
    build_trees,
    load_spans,
    tree_to_dict,
)

__all__ = [
    "LATENCY_BUCKETS", "REGISTRY", "MetricsRegistry", "MetricsServer",
    "MetricsOtlpShipper", "telemetry_lane", "counter", "gauge", "histogram",
    "SPAN_CREATE", "SPAN_EXIT", "SPAN_ITERATION", "SPAN_MIGRATE",
    "SPAN_ORPHAN", "SPAN_START", "SPAN_WAIT", "SpanNode", "SpanRecord",
    "Tracer", "build_trees", "load_spans", "tree_to_dict",
]
