"""Opt-in local Prometheus scrape endpoint.

``clawker loop --metrics-port N`` (or settings ``telemetry.metrics_port``)
serves the process registry's text exposition on ``127.0.0.1:N/metrics``
for the duration of the run.  Loopback-only on purpose: the scrape
surface carries worker ids and agent names; anything fleet-wide rides
the OTLP lanes to the collector instead (telemetry/otlp.py), exactly
like the reference stack's OTel Collector -> Prometheus path.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import logsetup
from .registry import REGISTRY, MetricsRegistry

log = logsetup.get("telemetry.http")


class MetricsServer:
    """Daemon-threaded scrape server over one registry.

    ``port=0`` binds an ephemeral port (tests); read :attr:`port` after
    :meth:`start`.  Serving never blocks a recording thread: the handler
    takes registry stripes one at a time, same as any snapshot.
    """

    def __init__(self, port: int, *, registry: MetricsRegistry | None = None,
                 host: str = "127.0.0.1"):
        self.registry = registry if registry is not None else REGISTRY
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsServer":
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 -- http.server contract
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = registry.exposition().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:    # scrapes are not news
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="telemetry-metrics",
                                        daemon=True)
        self._thread.start()
        log.info("metrics endpoint on http://%s:%d/metrics",
                 self.host, self.port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
