"""Git operations via the git CLI (reference: internal/git go-git GitManager)."""

from .git import GitError, GitManager, WorktreeInfo

__all__ = ["GitError", "GitManager", "WorktreeInfo"]
