"""GitManager: worktree lifecycle over the git CLI.

Parity reference: internal/git/git.go -- SetupWorktree (:191),
RemoveWorktree (:356), ListWorktrees (:392).  The reference uses go-git;
this build shells out to the ubiquitous git binary (no vendored VCS), which
also works unchanged over SSH on TPU-VM workers.
"""

from __future__ import annotations

import subprocess
from dataclasses import dataclass
from pathlib import Path

from ..errors import ClawkerError


class GitError(ClawkerError):
    pass


@dataclass
class WorktreeInfo:
    path: Path
    branch: str
    head: str


class GitManager:
    def __init__(self, repo_root: Path):
        self.root = Path(repo_root)

    def _git(self, *args: str, cwd: Path | None = None, check: bool = True) -> str:
        res = subprocess.run(
            ["git", *args],
            cwd=str(cwd or self.root),
            capture_output=True,
            text=True,
        )
        if check and res.returncode != 0:
            raise GitError(
                f"git {' '.join(args)} failed ({res.returncode}): {res.stderr.strip()}"
            )
        return res.stdout

    # ----------------------------------------------------------- queries

    def is_repo(self) -> bool:
        try:
            return self._git("rev-parse", "--is-inside-work-tree").strip() == "true"
        except GitError:
            return False

    def git_dir(self) -> Path:
        """Absolute path of the main repository's .git directory (mounted
        read-only into worktree agent containers, reference setup.go:288)."""
        out = self._git("rev-parse", "--path-format=absolute", "--git-common-dir").strip()
        return Path(out)

    def current_branch(self) -> str:
        return self._git("rev-parse", "--abbrev-ref", "HEAD").strip()

    def is_dirty(self, path: Path | None = None) -> bool:
        out = self._git("status", "--porcelain", cwd=path or self.root)
        return bool(out.strip())

    def branch_exists(self, branch: str) -> bool:
        try:
            self._git("rev-parse", "--verify", "--quiet", f"refs/heads/{branch}")
            return True
        except GitError:
            return False

    # --------------------------------------------------------- worktrees

    def setup_worktree(self, dest: Path, branch: str, *, base: str = "HEAD") -> WorktreeInfo:
        """Create a linked worktree at ``dest`` on ``branch`` (created from
        ``base`` if it does not exist)."""
        dest = Path(dest)
        dest.parent.mkdir(parents=True, exist_ok=True)
        if self.branch_exists(branch):
            self._git("worktree", "add", str(dest), branch)
        else:
            self._git("worktree", "add", "-b", branch, str(dest), base)
        head = self._git("rev-parse", "HEAD", cwd=dest).strip()
        return WorktreeInfo(path=dest, branch=branch, head=head)

    def list_worktrees(self) -> list[WorktreeInfo]:
        out = self._git("worktree", "list", "--porcelain")
        infos: list[WorktreeInfo] = []
        cur: dict = {}
        for line in out.splitlines() + [""]:
            if not line.strip():
                if cur.get("worktree"):
                    infos.append(
                        WorktreeInfo(
                            path=Path(cur["worktree"]),
                            branch=cur.get("branch", "").removeprefix("refs/heads/"),
                            head=cur.get("HEAD", ""),
                        )
                    )
                cur = {}
                continue
            key, _, val = line.partition(" ")
            cur[key] = val
        return infos

    def remove_worktree(self, path: Path, *, force: bool = False) -> None:
        args = ["worktree", "remove", str(path)]
        if force:
            args.insert(2, "--force")
        self._git(*args)

    def prune_worktrees(self) -> None:
        self._git("worktree", "prune")
