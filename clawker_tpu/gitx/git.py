"""GitManager: worktree lifecycle over the git CLI.

Parity reference: internal/git/git.go -- SetupWorktree (:191),
RemoveWorktree (:356), ListWorktrees (:392).  The reference uses go-git;
this build shells out to the ubiquitous git binary (no vendored VCS), which
also works unchanged over SSH on TPU-VM workers.
"""

from __future__ import annotations

import shutil
import subprocess
import tempfile
from dataclasses import dataclass
from pathlib import Path

from ..errors import ClawkerError


class GitError(ClawkerError):
    pass


class MergeConflict(GitError):
    """A merge-queue landing hit conflicting hunks.

    Carries enough context for the scheduler to resubmit the losing
    branch through admission (docs/loop-worktrees.md#merge-queue)."""

    def __init__(self, target: str, src: str, detail: str = ""):
        super().__init__(
            f"merge of {src} into {target} conflicts"
            + (f": {detail}" if detail else ""))
        self.target = target
        self.src = src


@dataclass
class WorktreeInfo:
    path: Path
    branch: str
    head: str


class GitManager:
    def __init__(self, repo_root: Path):
        self.root = Path(repo_root)

    def _git(self, *args: str, cwd: Path | None = None, check: bool = True) -> str:
        res = subprocess.run(
            ["git", *args],
            cwd=str(cwd or self.root),
            capture_output=True,
            text=True,
        )
        if check and res.returncode != 0:
            raise GitError(
                f"git {' '.join(args)} failed ({res.returncode}): {res.stderr.strip()}"
            )
        return res.stdout

    # ----------------------------------------------------------- queries

    def is_repo(self) -> bool:
        try:
            return self._git("rev-parse", "--is-inside-work-tree").strip() == "true"
        except GitError:
            return False

    def git_dir(self) -> Path:
        """Absolute path of the main repository's .git directory (mounted
        read-only into worktree agent containers, reference setup.go:288)."""
        out = self._git("rev-parse", "--path-format=absolute", "--git-common-dir").strip()
        return Path(out)

    def current_branch(self) -> str:
        return self._git("rev-parse", "--abbrev-ref", "HEAD").strip()

    def is_dirty(self, path: Path | None = None) -> bool:
        out = self._git("status", "--porcelain", cwd=path or self.root)
        return bool(out.strip())

    def branch_exists(self, branch: str) -> bool:
        try:
            self._git("rev-parse", "--verify", "--quiet", f"refs/heads/{branch}")
            return True
        except GitError:
            return False

    # --------------------------------------------------------- worktrees

    def setup_worktree(self, dest: Path, branch: str, *, base: str = "HEAD") -> WorktreeInfo:
        """Create -- or RE-ATTACH -- a linked worktree at ``dest`` on
        ``branch`` (created from ``base`` if it does not exist).

        Idempotent against every stale state a crashed prior run leaves
        behind (docs/loop-worktrees.md#degrade-matrix): ``branch``
        already checked out at ``dest`` reuses it as-is; a worktree
        registration whose directory vanished is pruned before re-adding;
        a branch that exists with no worktree (prior run died between
        branch create and ``worktree add``) is attached rather than
        erroring.  This is what lets ``--resume`` replay REC_SEED_WORKTREE
        records straight back through this call with zero duplicates."""
        dest = Path(dest)
        dest.parent.mkdir(parents=True, exist_ok=True)
        existing = None
        for wt in self.list_worktrees():
            if wt.branch == branch or wt.path == dest:
                existing = wt
                break
        if existing is not None:
            if existing.path == dest and existing.branch == branch:
                if dest.exists():
                    # crash-survivor: the worktree is intact, reuse it
                    head = self._git("rev-parse", "HEAD", cwd=dest).strip()
                    return WorktreeInfo(path=dest, branch=branch, head=head)
                # registered but the directory is gone: drop the stale
                # registration, then fall through to a fresh add
                self.prune_worktrees()
            else:
                raise GitError(
                    f"branch {branch!r} / dest {dest} already attached to "
                    f"worktree {existing.path} (branch {existing.branch!r})")
        if self.branch_exists(branch):
            self._git("worktree", "add", str(dest), branch)
        else:
            self._git("worktree", "add", "-b", branch, str(dest), base)
        head = self._git("rev-parse", "HEAD", cwd=dest).strip()
        return WorktreeInfo(path=dest, branch=branch, head=head)

    def list_worktrees(self) -> list[WorktreeInfo]:
        out = self._git("worktree", "list", "--porcelain")
        infos: list[WorktreeInfo] = []
        cur: dict = {}
        for line in out.splitlines() + [""]:
            if not line.strip():
                if cur.get("worktree"):
                    infos.append(
                        WorktreeInfo(
                            path=Path(cur["worktree"]),
                            branch=cur.get("branch", "").removeprefix("refs/heads/"),
                            head=cur.get("HEAD", ""),
                        )
                    )
                cur = {}
                continue
            key, _, val = line.partition(" ")
            cur[key] = val
        return infos

    def remove_worktree(self, path: Path, *, force: bool = False) -> None:
        args = ["worktree", "remove", str(path)]
        if force:
            args.insert(2, "--force")
        self._git(*args)

    def prune_worktrees(self) -> None:
        self._git("worktree", "prune")

    # ------------------------------------------------------- merge queue

    def ensure_branch(self, branch: str, *, base: str = "HEAD") -> str:
        """Create ``branch`` at ``base`` if missing; return its tip sha."""
        if not self.branch_exists(branch):
            self._git("branch", branch, base)
        return self._git("rev-parse", f"refs/heads/{branch}").strip()

    def merge_into(self, target: str, src: str, *, message: str = "") -> str:
        """Land branch ``src`` onto branch ``target`` without touching any
        checked-out tree.  Returns ``"clean"`` (src already contained),
        ``"ff"`` (fast-forwarded), or ``"merged"`` (true merge commit);
        raises :class:`MergeConflict` on conflicting hunks.

        The container's git predates ``merge-tree --write-tree``
        (needs >= 2.38), so a true merge runs in a throwaway *detached*
        temp worktree and publishes via a guarded ``update-ref`` -- the
        old-value argument makes the ref move atomic against a
        concurrent mover, and no user checkout is ever mutated (the
        merge queue lands onto a run-scoped integration branch for the
        same reason; docs/loop-worktrees.md#merge-queue)."""
        target_tip = self._git("rev-parse", f"refs/heads/{target}").strip()
        src_tip = self._git("rev-parse", f"refs/heads/{src}").strip()
        if self._is_ancestor(src_tip, target_tip):
            return "clean"
        if self._is_ancestor(target_tip, src_tip):
            self._git("update-ref", f"refs/heads/{target}", src_tip,
                      target_tip)
            return "ff"
        tmp = Path(tempfile.mkdtemp(prefix="clawker-mergeq-")) / "wt"
        try:
            self._git("worktree", "add", "--detach", str(tmp), target_tip)
            res = subprocess.run(
                ["git", *self._identity_args(), "merge", "--no-ff", "-m",
                 message or f"merge {src} into {target}", src_tip],
                cwd=str(tmp), capture_output=True, text=True)
            if res.returncode != 0:
                subprocess.run(["git", "merge", "--abort"], cwd=str(tmp),
                               capture_output=True, text=True)
                raise MergeConflict(target, src,
                                    detail=res.stdout.strip()[:200])
            new_tip = self._git("rev-parse", "HEAD", cwd=tmp).strip()
            self._git("update-ref", f"refs/heads/{target}", new_tip,
                      target_tip)
            return "merged"
        finally:
            self._git("worktree", "remove", "--force", str(tmp),
                      check=False)
            shutil.rmtree(tmp.parent, ignore_errors=True)
            self.prune_worktrees()

    def _identity_args(self) -> list[str]:
        """``-c`` identity fallback for commits the merge queue itself
        authors.  A configured user identity always wins; the synthetic
        one only keeps the landing from dying with "committer identity
        unknown" on bare CI hosts / fresh TPU-VM workers."""
        res = subprocess.run(
            ["git", "config", "user.email"],
            cwd=str(self.root), capture_output=True, text=True)
        if res.returncode == 0 and res.stdout.strip():
            return []
        return ["-c", "user.name=clawker", "-c",
                "user.email=clawker@localhost"]

    def _is_ancestor(self, maybe_ancestor: str, descendant: str) -> bool:
        res = subprocess.run(
            ["git", "merge-base", "--is-ancestor", maybe_ancestor,
             descendant],
            cwd=str(self.root), capture_output=True, text=True)
        return res.returncode == 0
