"""Agent-container orchestration: create -> bootstrap -> start -> attach.

Parity reference: internal/cmd/container/shared/container_create.go:1473
CreateContainer (workspace prep, config volumes, env assembly, create,
bootstrap material) and container_start.go (BootstrapServicesPreStart /
PostStart).  The control-plane/firewall bootstrap hooks are injected as
callables so this module stays below the CP layer in the import DAG.
"""

from __future__ import annotations

import io
import sys
import tarfile
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import BinaryIO, Callable

from .. import consts
from ..config import Config
from ..engine.api import ContainerSpec, Engine
from ..errors import ConflictError
from ..util import phases
from . import attach as attach_mod
from .labels import agent_labels
from .names import container_name
from .resolve import resolve_image

# --- harness-seed staging cache (docs/loop-warmpool.md) -------------------
# Building the harness staging tar (walk host harness state, copy into a
# staging dir, tar it) was 3.3ms of an 8.95ms framework cold start
# (BENCH_r05 harness_seed) and its content depends only on
# (harness, project root, credential staging policy) -- NOT on the agent
# or container.  Cache the finished tar bytes per key so a loop fan-out
# (or a warm-pool fill) stages once and every create after it pays one
# put_archive.  TTL-bounded: host harness state may change under a
# long-lived process, and a warm pool must not serve hour-old seeds.
_HARNESS_TAR_TTL_S = 30.0
_harness_tar_cache: dict[tuple, tuple[float, bytes]] = {}
_harness_tar_lock = threading.Lock()


def clear_harness_seed_cache() -> None:
    """Drop cached harness staging tars (tests; explicit invalidation)."""
    with _harness_tar_lock:
        _harness_tar_cache.clear()


# --- workspace-seed digest cache (docs/loop-worktrees.md#seed-cache) ------
# The same TTL-cache pattern, extended to the workspace snapshot itself:
# SnapshotSeed used to re-walk and re-tar the ENTIRE project tree per
# agent per create, so a 32-agent fan-out on one repo paid 32 identical
# tree walks.  The tar is deterministic (workspace.strategy._tar_tree
# normalizes every non-content field), so it digests to a stable sha256;
# the cache maps project root -> (built_at, digest, tar) and a second
# digest-keyed view serves the bytes back to whoever fans them out (the
# scheduler shipping one copy per worker into workerd seed stores).
_WORKSPACE_TAR_TTL_S = 30.0
_workspace_tar_cache: dict[str, tuple[float, str, bytes]] = {}
_workspace_tar_lock = threading.Lock()


def clear_workspace_seed_cache() -> None:
    """Drop cached workspace seed tars (tests; explicit invalidation)."""
    with _workspace_tar_lock:
        _workspace_tar_cache.clear()


def workspace_seed_tar(root: Path) -> tuple[str, bytes]:
    """``(digest, tar)`` for the project tree at ``root``: built once,
    then served from the TTL-bounded cache -- the tree walk is paid per
    *fan-out*, not per agent.  N git worktrees forked from one base have
    identical content and therefore collapse to one digest, but each
    worktree path keys its own entry (the walk is what discovers the
    content, so a path-keyed probe is the only free lookup)."""
    from ..workspace.strategy import (
        _SEED_CACHE_HITS,
        _SEED_CACHE_MISSES,
        _tar_tree,
        seed_digest,
    )

    key = str(root)
    now = time.monotonic()
    with _workspace_tar_lock:
        hit = _workspace_tar_cache.get(key)
        if hit is not None and now - hit[0] < _WORKSPACE_TAR_TTL_S:
            phases.incr("workspace_seed.tar_cache_hit")
            _SEED_CACHE_HITS.inc()
            return hit[1], hit[2]
    phases.incr("workspace_seed.tar_cache_miss")
    _SEED_CACHE_MISSES.inc()
    tar = _tar_tree(Path(root))
    digest = seed_digest(tar)
    with _workspace_tar_lock:
        if len(_workspace_tar_cache) > 64:
            _workspace_tar_cache.clear()
        _workspace_tar_cache[key] = (now, digest, tar)
    return digest, tar


def workspace_seed_by_digest(digest: str) -> bytes | None:
    """The cached tar for ``digest`` (any root), or None when the cache
    no longer holds it -- the content-addressed view the seed fan-out
    re-serves worker copies from."""
    with _workspace_tar_lock:
        for (_ts, d, tar) in _workspace_tar_cache.values():
            if d == digest:
                return tar
    return None


@dataclass
class CreateOptions:
    agent: str = "dev"
    image: str = "@"                  # '@' = project default harness image
    cmd: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    tty: bool = True
    workspace_mode: str = ""          # '' = project config value
    harness: str = ""
    worker: str = ""                  # tpu_vm worker id (label only here)
    loop_id: str = ""
    extra_labels: dict[str, str] = field(default_factory=dict)  # caller-scoped
    #                                 labels (loop epoch, ...) on top of the
    #                                 standard agent label set
    replace: bool = False             # remove an existing same-name container
    mount_docker_socket: bool | None = None
    worktree_git_dir: Path | None = None
    workspace_root: Path | None = None  # override project root (worktrees)
    workdir: str = ""                   # override container working dir
    seed_digest: str = ""               # expected workspace-seed digest
    #                                 (content-addressed; the workerd path
    #                                 resolves it in the worker-local store)
    seed_tar: bytes | None = None       # pre-resolved seed bytes: skip the
    #                                 tree walk and seed with exactly these
    #                                 (a worker-local seed-store hit)


class AgentRuntime:
    """Create/start/attach/stop agent containers on one worker engine."""

    def __init__(
        self,
        engine: Engine,
        cfg: Config,
        *,
        pre_start: Callable[[str], None] | None = None,
        post_start: Callable[[str], None] | None = None,
        bootstrap: Callable[[str, str, str], None] | None = None,
        channels=None,                 # fleet.channels.SideChannels | None
    ):
        self.engine = engine
        self.cfg = cfg
        # bootstrap hooks wired by the CLI factory once CP/firewall exist.
        # ``bootstrap(container_id, project, agent)`` runs between create and
        # start (reference: InstallAgentBootstrapMaterial in
        # createAndBootstrapContainer, container_create.go:2074).
        self.pre_start = pre_start
        self.post_start = post_start
        self.bootstrap = bootstrap
        # side-channel URLs for THIS worker (remote workers: SSH -R tunnel
        # addresses; local: host-gateway) -- fleet/channels.SideChannels,
        # or a zero-arg callable resolved lazily on the create path only
        self.channels = channels
        # lazy resolution replaces self.channels in place; guard it so a
        # runtime handed to threaded callers (the Factory exposes one to
        # arbitrary commands) resolves exactly once.  The loop scheduler
        # builds per-worker runtimes with channels already resolved, but
        # the contract must not depend on that.
        self._channels_lock = threading.Lock()

    def _resolve_channels(self):
        with self._channels_lock:
            if callable(self.channels):
                try:
                    self.channels = self.channels()
                except Exception as e:
                    # best-effort: a failed tunnel degrades the agent (no
                    # browser-open/OAuth/telemetry), never blocks the create
                    import logging

                    logging.getLogger("runtime").warning(
                        "event=side_channels_unavailable error=%s", e)
                    self.channels = None
            return self.channels

    # -------------------------------------------------------------- create

    def create(self, opts: CreateOptions) -> str:
        from ..workspace import setup_mounts  # local import: workspace is a peer

        project = self.cfg.project_name()
        name = container_name(project, opts.agent)

        if opts.replace and self.engine.container_exists(name):
            self.engine.remove_container(name, force=True, volumes=False)

        image = resolve_image(self.engine, project, opts.image)

        pconf = self.cfg.project
        mode = opts.workspace_mode or (pconf.workspace.mode if pconf else "bind")
        root = opts.workspace_root or self.cfg.project_root or Path.cwd()
        mount_sock = (
            opts.mount_docker_socket
            if opts.mount_docker_socket is not None
            else bool(pconf and pconf.workspace.mount_docker_socket)
        )
        with phases.phase("workspace_mounts"):
            mounts = setup_mounts(
                self.engine,
                project,
                opts.agent,
                root,
                mode=mode,
                extra_mounts=(pconf.workspace.extra_mounts if pconf else None),
                worktree_git_dir=opts.worktree_git_dir,
            )

        env = self._build_env(project, opts)
        harness = opts.harness or (pconf.build.harness if pconf else "")
        labels = agent_labels(
            project,
            opts.agent,
            harness=harness,
            worker=opts.worker,
            loop_id=opts.loop_id,
        )
        labels.update(opts.extra_labels)
        cmd = opts.cmd or (pconf.agent.cmd if pconf else [])
        spec = ContainerSpec(
            image=image,
            cmd=list(cmd),
            env=env,
            labels=labels,
            tty=opts.tty,
            open_stdin=True,
            working_dir=opts.workdir or consts.WORKSPACE_DIR,
            hostname=f"{project}-{opts.agent}",
            binds=mounts.binds,
            memory=(pconf.agent.memory if pconf else ""),
            nano_cpus=int((pconf.agent.cpus if pconf else 0.0) * 1e9),
            init=False,  # the harness image's clawkerd is PID 1, not tini
            mount_docker_socket=mount_sock,
            # host.docker.internal only resolves on Linux daemons with an
            # explicit host-gateway mapping; needed whenever any injected
            # URL (hostproxy OR OTLP telemetry) points there
            extra_hosts=(
                ["host.docker.internal:host-gateway"]
                if any("host.docker.internal" in v for v in env.values())
                or self.cfg.settings.host_proxy.enable
                else []
            ),
        )
        try:
            with phases.phase("engine_create"):
                cid = self.engine.create_container(name, spec)
        except ConflictError:
            raise ConflictError(
                f"agent {opts.agent!r} already exists for project {project!r} "
                f"(container {name}); use --replace or `clawker start`"
            )
        with phases.phase("workspace_seed"):
            mounts.seed(self.engine, cid, tar=opts.seed_tar,
                        worker=opts.worker)
        with phases.phase("harness_seed"):
            self._seed_harness_config(cid, harness, root)
        if self.bootstrap:
            with phases.phase("identity_bootstrap"):
                self.bootstrap(cid, project, opts.agent)
        return cid

    def prefetch_seeds(self, harness: str, root: Path) -> str:
        """Warm both create-time seed caches off the hot path (warm-pool
        fills call this before their create, so a later adoption -- the
        hit path -- never pays a tree walk or harness staging).  Returns
        the workspace seed digest ("" when the root has nothing to
        seed)."""
        self.harness_seed_tar(harness, root)
        if not Path(root).exists():
            return ""
        digest, _tar = workspace_seed_tar(Path(root))
        return digest

    # ------------------------------------------------------- pool adoption

    def adopt_pooled(self, cid: str, opts: CreateOptions) -> None:
        """Finalize a warm-pool container for a real agent placement
        (docs/loop-warmpool.md).

        The pool fill already paid the expensive create-time stages
        (engine create, workspace seed, harness seed, identity prewarm)
        under a placeholder agent name; adoption finalizes the
        agent-specific surface -- labels, env, name -- in place:

        - **relabel**: the full agent label set (plus ``extra_labels``,
          e.g. the loop epoch) replaces the placeholder's, where the
          engine supports in-place relabel; the pool-origin marker
          (``LABEL_WARMPOOL``) survives so volume sweeps can trace the
          placeholder's volumes.
        - **env fixup**: create-time env is immutable, so the
          agent-specific env lands as ``/run/clawker/agent-env``
          (KEY=VAL lines) -- the same advisory-file channel the loop
          scheduler already uses for per-iteration context.
        - **identity**: the bootstrap hook re-runs under the REAL agent
          name; with the CA session cache prewarmed this is the warm
          path (leaf reused, only the per-container assertion JWT and
          session key are fresh).
        - **rename** (LAST): the deterministic agent name lands only
          after every other fixup, so a crash mid-adoption leaves
          either a pool-named container (swept) or a fully-finalized
          one (continued) -- never a half-adopted name.

        Raises ClawkerError subclasses on failure; the caller owns the
        fallback to a cold create.
        """
        project = self.cfg.project_name()
        name = container_name(project, opts.agent)
        pconf = self.cfg.project
        harness = opts.harness or (pconf.build.harness if pconf else "")
        labels = agent_labels(
            project, opts.agent, harness=harness,
            worker=opts.worker, loop_id=opts.loop_id)
        labels.update(opts.extra_labels)
        with phases.phase("pool_adopt_env"):
            env = self._build_env(project, opts)
            body = "".join(f"{k}={v}\n" for k, v in sorted(env.items())).encode()
            buf = io.BytesIO()
            with tarfile.open(fileobj=buf, mode="w") as tf:
                ti = tarfile.TarInfo("agent-env")
                ti.size = len(body)
                ti.mode = 0o600
                tf.addfile(ti, io.BytesIO(body))
            env_tar = buf.getvalue()
        # without a bootstrap hook the whole fixup batches under ONE
        # jail check (rename included); with one, the rename waits for
        # the identity install so a crash mid-adoption can never leave
        # an agent-named container without identity material
        with phases.phase("pool_adopt_finalize"):
            self._finalize_replacing(
                cid, name, opts.replace, labels=labels,
                archive_path=consts.RUN_STATE_DIR, archive=env_tar,
                new_name="" if self.bootstrap else name)
        if self.bootstrap:
            with phases.phase("identity_bootstrap"):
                self.bootstrap(cid, project, opts.agent)
            with phases.phase("pool_adopt_rename"):
                self._rename_replacing(cid, name, opts.replace)

    def _finalize_replacing(self, cid: str, name: str, replace: bool,
                            **kw) -> None:
        """finalize_adoption with replace-on-conflict semantics: the
        conflict path (a leftover same-name container) pays the extra
        remove, the common path pays nothing."""
        try:
            self.engine.finalize_adoption(cid, **kw)
        except ConflictError:
            if not replace:
                raise
            self.engine.remove_container(name, force=True, volumes=False)
            self.engine.finalize_adoption(cid, **kw)

    def _rename_replacing(self, cid: str, name: str, replace: bool) -> None:
        try:
            self.engine.rename_container(cid, name)
        except ConflictError:
            if not replace:
                raise
            self.engine.remove_container(name, force=True, volumes=False)
            self.engine.rename_container(cid, name)

    def _seed_harness_config(self, cid: str, harness: str, root: Path) -> None:
        """Stage host harness state into the config volume per the harness
        bundle's staging manifest (containerfs; reference
        container_create.go:1907 initConfigVolume).  A host with zero
        harness state, or no staging manifest, degrades to a no-op.
        The staging tar is built once per (harness, root, credentials)
        and reused (see the module cache above)."""
        tar = self.harness_seed_tar(harness, root)
        if tar:
            self.engine.put_archive(cid, consts.CONTAINER_HOME, tar)

    def harness_seed_tar(self, harness: str, root: Path) -> bytes:
        """The staging tar for (harness, root, credential policy), built
        once and served from the TTL-bounded module cache afterwards --
        a warm-pool fill's own seed pays this cost off the hot path for
        every later create on the worker.  Returns b"" when the harness
        has nothing to stage."""
        stage_creds = self.cfg.settings.credentials.stage
        key = (harness or "claude", str(root), bool(stage_creds),
               consts.CONTAINER_HOME, consts.WORKSPACE_DIR)
        now = time.monotonic()
        with _harness_tar_lock:
            hit = _harness_tar_cache.get(key)
            if hit is not None and now - hit[0] < _HARNESS_TAR_TTL_S:
                phases.incr("harness_seed.tar_cache_hit")
                return hit[1]
        phases.incr("harness_seed.tar_cache_miss")
        tar = self._build_harness_seed_tar(harness, root, stage_creds)
        with _harness_tar_lock:
            if len(_harness_tar_cache) > 64:
                _harness_tar_cache.clear()
            _harness_tar_cache[key] = (now, tar)
        return tar

    def _build_harness_seed_tar(self, harness: str, root: Path,
                                stage_creds: bool) -> bytes:
        from .. import containerfs
        from ..bundle.resolver import Resolver
        from ..errors import NotFoundError

        try:
            h = Resolver(self.cfg).harness(harness or "claude")
        except NotFoundError:
            return b""
        staging = containerfs.Staging.from_raw(h.staging)
        if not staging.copy and not (stage_creds and staging.credentials):
            return b""
        sdir, cleanup = containerfs.prepare_config(
            staging,
            container_home=consts.CONTAINER_HOME,
            container_work=consts.WORKSPACE_DIR,
            host_project_root=str(root),
            include_credentials=stage_creds,
        )
        try:
            return containerfs.staging_tar(sdir)
        finally:
            cleanup()

    def _build_env(self, project: str, opts: CreateOptions) -> dict[str, str]:
        """Create-time env (reference: buildCreateTimeEnv
        container_create.go:2117): identity, workspace, host-proxy wiring."""
        env = {
            "CLAWKER_PROJECT": project,
            "CLAWKER_AGENT": opts.agent,
            "CLAWKER_WORKSPACE": consts.WORKSPACE_DIR,
            # socket-bridge landing point: ssh picks the agent up the
            # moment the bridge materializes the socket; harmless (key-file
            # fallback) when no bridge is running
            "SSH_AUTH_SOCK": "/run/clawker/ssh-agent.sock",
        }
        channels = self._resolve_channels()
        if channels is not None and channels.hostproxy_url:
            # worker-specific side channel (remote: the SSH -R tunnel bind)
            env["CLAWKER_HOSTPROXY"] = channels.hostproxy_url
        elif self.cfg.settings.host_proxy.enable:
            env["CLAWKER_HOSTPROXY"] = (
                f"http://host.docker.internal:{self.cfg.settings.host_proxy.port}"
            )
        if channels is not None and channels.otlp_endpoint:
            env["OTEL_EXPORTER_OTLP_ENDPOINT"] = channels.otlp_endpoint
        pconf = self.cfg.project
        if pconf:
            env.update(pconf.agent.env)
        env.update(opts.env)
        return env

    # --------------------------------------------------------- start/attach

    def start(self, name_or_id: str) -> None:
        if self.pre_start:
            with phases.phase("pre_start"):
                self.pre_start(name_or_id)
        with phases.phase("engine_start"):
            self.engine.start_container(name_or_id)
        if self.post_start:
            with phases.phase("post_start"):
                self.post_start(name_or_id)

    def attach_and_run(
        self,
        name_or_id: str,
        *,
        tty: bool = True,
        stdin: BinaryIO | None = None,
        stdout: BinaryIO | None = None,
    ) -> int:
        """Attach first, then start, then pump until exit (mirrors
        attachThenStart run.go:331 -- attaching before start loses no
        output).  Returns the container exit code."""
        out = stdout or sys.stdout.buffer
        stream = self.engine.attach_container(name_or_id, tty=tty)
        self.start(name_or_id)
        attach_mod.wire_resize(self.engine, name_or_id)
        use_raw = (
            stdin is None
            and stdout is None
            and tty
            and sys.stdin.isatty()
            and sys.stdout.isatty()
        )
        inp = stdin if stdin is not None else sys.stdin.buffer
        if use_raw:
            with attach_mod.raw_terminal(sys.stdin.fileno()):
                attach_mod.pump_streams(stream, inp, out)
        else:
            attach_mod.pump_streams(stream, inp, out)
        return self.engine.wait_container(name_or_id)

    # --------------------------------------------------------------- query

    def list_agents(self, *, all: bool = True, project: str | None = None) -> list[dict]:
        filters: dict = {"label": [f"{consts.LABEL_ROLE}=agent"]}
        if project:
            filters["label"].append(f"{consts.LABEL_PROJECT}={project}")
        return self.engine.list_containers(all=all, filters=filters)
