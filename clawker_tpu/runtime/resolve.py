"""Image reference resolution, including the ``@`` placeholder shortcut.

Parity reference: internal/cmd/container/shared ResolvePlaceholderImage
(run.go:207) + internal/docker/image_resolve.go.  ``@`` resolves to the
project's default harness image ``clawker-<project>:default``; ``@base`` /
``@<tag>`` select another project image tag; anything else is a literal
reference (pulled on demand when absent).
"""

from __future__ import annotations

from .. import consts
from ..engine.api import Engine
from ..errors import NotFoundError
from .names import image_ref


def resolve_image(engine: Engine, project: str, image_arg: str, *, pull_missing: bool = True) -> str:
    if image_arg.startswith("@"):
        tag = image_arg[1:] or consts.IMAGE_TAG_DEFAULT
        ref = image_ref(project, tag)
        if not engine.image_exists(ref):
            raise NotFoundError(
                f"project image {ref} not built yet -- run `clawker build` first"
            )
        return ref
    if not engine.image_exists(image_arg) and pull_missing:
        for _ in engine.pull_image(image_arg):
            pass
        if not engine.image_exists(image_arg):
            raise NotFoundError(f"image {image_arg} not found and pull failed")
    return image_arg
