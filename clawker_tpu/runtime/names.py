"""Deterministic object naming.

Parity reference: internal/docker/names.go -- containers are
``clawker.<project>.<agent>``; volumes carry a purpose suffix; images are
``clawker-<project>:<tag>``.
"""

from __future__ import annotations

from .. import consts
from ..util.text import validate_name

VOLUME_PURPOSES = ("workspace", "config", "history")


def container_name(project: str, agent: str) -> str:
    validate_name("project", project)
    validate_name("agent", agent)
    return consts.CONTAINER_NAME_SEP.join((consts.CONTAINER_NAME_PREFIX, project, agent))


def parse_container_name(name: str) -> tuple[str, str] | None:
    """-> (project, agent) or None if not one of ours."""
    parts = name.lstrip("/").split(consts.CONTAINER_NAME_SEP)
    if len(parts) != 3 or parts[0] != consts.CONTAINER_NAME_PREFIX:
        return None
    return parts[1], parts[2]


def agent_volume_name(project: str, agent: str, purpose: str) -> str:
    if purpose not in VOLUME_PURPOSES:
        raise ValueError(f"unknown volume purpose {purpose!r}")
    return f"{container_name(project, agent)}.{purpose}"


def image_ref(project: str, tag: str = consts.IMAGE_TAG_DEFAULT) -> str:
    validate_name("project", project)
    return f"{consts.IMAGE_NAME_PREFIX}{project}:{tag}"
