"""Attach/stream plumbing: the hot TTY copy loop.

Parity reference: internal/docker/pty.go (raw-mode attach) and the stream
select in internal/cmd/container/run/run.go:331-527 (attachThenStart,
waitForContainerExit).
"""

from __future__ import annotations

import contextlib
import os
import signal
import sys
import threading
from typing import BinaryIO, Iterator


@contextlib.contextmanager
def raw_terminal(fd: int) -> Iterator[None]:
    """Put a real TTY into raw mode for the duration of an attach."""
    import termios
    import tty as tty_mod

    saved = termios.tcgetattr(fd)
    try:
        tty_mod.setraw(fd)
        yield
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, saved)


def pump_streams(
    stream,
    stdin: BinaryIO | None,
    stdout: BinaryIO,
    *,
    stderr: BinaryIO | None = None,
) -> None:
    """Copy stdin -> stream and stream -> stdout until the container side
    closes.  The writer runs on a daemon thread (it may block on a read of a
    terminal forever); the reader runs inline so returning means output is
    fully drained.
    """

    def feed() -> None:
        assert stdin is not None
        try:
            while True:
                chunk = stdin.read(4096)
                if not chunk:
                    break
                if isinstance(chunk, str):
                    chunk = chunk.encode()
                stream.write(chunk)
        except (OSError, ValueError):
            pass
        finally:
            with contextlib.suppress(Exception):
                stream.close_write()

    t = None
    if stdin is not None:
        t = threading.Thread(target=feed, daemon=True, name="attach-stdin")
        t.start()
    err = stderr or stdout
    for fd, payload in stream.frames():
        out = stdout if fd != 2 else err
        out.write(payload)
        with contextlib.suppress(Exception):
            out.flush()


def wire_resize(engine, container_ref: str) -> None:
    """Forward terminal size now and on SIGWINCH (real TTY sessions only)."""
    if not sys.stdout.isatty():
        return

    def push(*_args) -> None:
        with contextlib.suppress(Exception):
            cols, rows = os.get_terminal_size()
            engine.resize_container(container_ref, rows, cols)

    push()
    with contextlib.suppress(ValueError):  # not main thread
        signal.signal(signal.SIGWINCH, push)
