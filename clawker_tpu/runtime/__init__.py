"""Runtime middleware: naming, labels, image resolution, container
orchestration -- the glue between CLI verbs and the engine.

Parity reference: internal/docker middleware (names.go, labels.go, pty.go,
image_resolve.go) + the orchestration in internal/cmd/container/shared
(container_create.go:1473 CreateContainer, container_start.go).
"""

from .names import (
    agent_volume_name,
    container_name,
    image_ref,
    parse_container_name,
)
from .labels import agent_labels, infra_labels
from .resolve import resolve_image
from .orchestrate import AgentRuntime, CreateOptions

__all__ = [
    "AgentRuntime",
    "CreateOptions",
    "agent_labels",
    "agent_volume_name",
    "container_name",
    "image_ref",
    "infra_labels",
    "parse_container_name",
    "resolve_image",
]
