"""Label builders (reference: internal/docker/labels.go dev.clawker.*)."""

from __future__ import annotations

from .. import consts


def agent_labels(
    project: str,
    agent: str,
    *,
    harness: str = "",
    worker: str = "",
    loop_id: str = "",
) -> dict[str, str]:
    labels = {
        consts.LABEL_PROJECT: project,
        consts.LABEL_AGENT: agent,
        consts.LABEL_ROLE: "agent",
    }
    if harness:
        labels[consts.LABEL_HARNESS] = harness
    if worker:
        labels[consts.LABEL_WORKER] = worker
    if loop_id:
        labels[consts.LABEL_LOOP] = loop_id
    return labels


def infra_labels(role: str, *, content_sha: str = "") -> dict[str, str]:
    labels = {consts.LABEL_ROLE: role}
    if content_sha:
        labels[consts.LABEL_CONTENT_SHA] = content_sha
    return labels


def volume_labels(project: str, agent: str, purpose: str) -> dict[str, str]:
    return {
        consts.LABEL_PROJECT: project,
        consts.LABEL_AGENT: agent,
        consts.LABEL_VOLUME_PURPOSE: purpose,
    }
