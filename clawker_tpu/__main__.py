"""``python -m clawker_tpu`` entry point."""

import sys

from .cli import main

sys.exit(main())
