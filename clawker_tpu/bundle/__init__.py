"""Bundle system: pluggable harness / stack / monitoring components.

Parity reference: internal/bundle (SURVEY.md 2.6) -- three-tier component
resolution (embedded floor assets, loose directories, installed bundles
under the data dir) with a Manager facade for install / list / validate /
remove.  Assets are plain directories holding ``harness.yaml`` /
``stack.yaml`` plus optional files referenced by Dockerfile generation.
"""

from .model import Harness, MonitoringUnit, Stack, load_component_dir
from .resolver import Resolver
from .manager import BundleManager

__all__ = [
    "Harness",
    "Stack",
    "MonitoringUnit",
    "Resolver",
    "BundleManager",
    "load_component_dir",
]
