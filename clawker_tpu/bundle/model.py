"""Bundle component models and directory loading.

A component is a directory with a manifest (``harness.yaml`` /
``stack.yaml`` / ``monitoring.yaml``) plus optional support files.  The
manifest schema is deliberately small; Dockerfile rendering lives in
``clawker_tpu.bundler`` (the component only *declares* what it needs).
Parity reference: internal/bundle/assets harness.yaml + stack bundles
(SURVEY.md 2.6).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from pathlib import Path

import yaml

from ..config.schema import EgressRule, from_dict
from ..errors import ConfigError


@dataclass
class Harness:
    """An agent harness: what to install and how to run the agent."""

    name: str = ""
    description: str = ""
    version: str = ""
    packages: list[str] = field(default_factory=list)   # OS packages it needs
    install: list[str] = field(default_factory=list)    # RUN lines (shell)
    cmd: list[str] = field(default_factory=list)        # container CMD
    env: dict[str, str] = field(default_factory=dict)
    egress: list[EgressRule] = field(default_factory=list)  # required domains
    files: list[str] = field(default_factory=list)      # extra files copied into image
    # create-time host->container config staging directives, interpreted
    # by clawker_tpu.containerfs (raw tree; schema lives there)
    staging: dict = field(default_factory=dict)
    source_dir: Path | None = None                      # where files resolve from
    tier: str = ""                                      # floor | installed | loose

    def validate(self) -> list[str]:
        errs = []
        if not self.name:
            errs.append("harness: missing name")
        if not self.cmd:
            errs.append(f"harness {self.name}: missing cmd")
        for f in self.files:
            if self.source_dir and not (self.source_dir / f).exists():
                errs.append(f"harness {self.name}: missing file {f}")
        return errs


@dataclass
class Stack:
    """A language stack: the base image layer of a project image."""

    name: str = ""
    description: str = ""
    base_image: str = ""
    packages: list[str] = field(default_factory=list)
    install: list[str] = field(default_factory=list)    # RUN lines after packages
    env: dict[str, str] = field(default_factory=dict)
    source_dir: Path | None = None
    tier: str = ""

    def validate(self) -> list[str]:
        errs = []
        if not self.name:
            errs.append("stack: missing name")
        if not self.base_image:
            errs.append(f"stack {self.name}: missing base_image")
        return errs


@dataclass
class MonitoringUnit:
    """Per-harness observability overlay: index templates, pipelines,
    saved objects seeded into the monitor stack (reference:
    internal/monitor/unit.go:48)."""

    name: str = ""
    description: str = ""
    indices: list[str] = field(default_factory=list)
    files: list[str] = field(default_factory=list)
    source_dir: Path | None = None
    tier: str = ""

    def validate(self) -> list[str]:
        return [] if self.name else ["monitoring unit: missing name"]


MANIFESTS = {
    "harness": ("harness.yaml", Harness),
    "stack": ("stack.yaml", Stack),
    "monitoring": ("monitoring.yaml", MonitoringUnit),
}


# mtime-keyed parse cache: component resolution runs on every container
# create (harness staging), and re-parsing an unchanged manifest costs
# more than the rest of the create path combined
_manifest_cache: dict[tuple[str, int, int], dict] = {}


def _load_manifest(mf: Path) -> dict:
    try:
        st = mf.stat()
        key = (str(mf), st.st_mtime_ns, st.st_size)
    except OSError as e:
        raise ConfigError(f"{mf}: unreadable: {e}") from e
    cached = _manifest_cache.get(key)
    if cached is None:
        try:
            cached = yaml.safe_load(mf.read_text()) or {}
        except OSError as e:
            raise ConfigError(f"{mf}: unreadable: {e}") from e
        except yaml.YAMLError as e:
            raise ConfigError(f"{mf}: invalid yaml: {e}") from e
        if len(_manifest_cache) > 256:
            _manifest_cache.clear()
        _manifest_cache[key] = cached
    # deep copy: from_dict/__post_init__ may normalize nested values in
    # place, and the cache must stay pristine
    return copy.deepcopy(cached)


def load_component_dir(kind: str, path: Path, *, tier: str = "loose"):
    """Load one component of ``kind`` from a directory."""
    manifest_name, cls = MANIFESTS[kind]
    mf = path / manifest_name
    if not mf.is_file():
        raise ConfigError(f"{path}: no {manifest_name}")
    raw = _load_manifest(mf)
    comp = from_dict(cls, raw)
    comp.source_dir = path
    comp.tier = tier
    if not comp.name:
        comp.name = path.name
    return comp
