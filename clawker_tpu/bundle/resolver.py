"""Three-tier component resolution.

Precedence (reference: internal/bundle/resolver.go): **installed** bundles
(under ``<data>/bundles/<ns>/<name>``) shadow **loose** directories
(project-local ``.clawker/bundles``) shadow the embedded **floor**
(``clawker_tpu/bundle/assets`` package data) -- the floor guarantees a
working claude harness + language stacks with zero installation.
"""

from __future__ import annotations

from pathlib import Path

from ..config import Config
from ..errors import NotFoundError
from .model import MANIFESTS, load_component_dir

FLOOR_DIR = Path(__file__).parent / "assets"

KIND_DIRS = {"harness": "harnesses", "stack": "stacks", "monitoring": "monitoring"}


class Resolver:
    def __init__(self, cfg: Config):
        self.cfg = cfg

    # ------------------------------------------------------------- tiers

    def _tier_roots(self) -> list[tuple[str, Path]]:
        """(tier, root) pairs in decreasing precedence."""
        roots: list[tuple[str, Path]] = []
        bundles = self.cfg.bundles_dir
        if bundles.is_dir():
            # installed bundles: <bundles>/<ns>/<name>/ each a bundle root
            for ns in sorted(bundles.iterdir()):
                if ns.is_dir() and not ns.name.startswith("."):
                    for b in sorted(ns.iterdir()):
                        # dot-dirs are install staging (manager.py swap)
                        if b.is_dir() and not b.name.startswith("."):
                            roots.append(("installed", b))
        if self.cfg.project_root is not None:
            loose = self.cfg.project_root / ".clawker" / "bundles"
            if loose.is_dir():
                for b in sorted(loose.iterdir()):
                    if b.is_dir():
                        roots.append(("loose", b))
        roots.append(("floor", FLOOR_DIR))
        return roots

    # ----------------------------------------------------------- resolve

    def resolve(self, kind: str, name: str):
        sub = KIND_DIRS[kind]
        for tier, root in self._tier_roots():
            cdir = root / sub / name
            if cdir.is_dir() and (cdir / MANIFESTS[kind][0]).is_file():
                return load_component_dir(kind, cdir, tier=tier)
        raise NotFoundError(f"no {kind} component named {name!r}")

    def harness(self, name: str):
        return self.resolve("harness", name)

    def stack(self, name: str):
        return self.resolve("stack", name)

    def monitoring(self, name: str):
        return self.resolve("monitoring", name)

    def list(self, kind: str) -> list:
        """All visible components of ``kind`` (higher tiers shadow lower)."""
        sub = KIND_DIRS[kind]
        seen: dict[str, object] = {}
        for tier, root in self._tier_roots():
            d = root / sub
            if not d.is_dir():
                continue
            for cdir in sorted(d.iterdir()):
                if (
                    cdir.is_dir()
                    and (cdir / MANIFESTS[kind][0]).is_file()
                    and cdir.name not in seen
                ):
                    seen[cdir.name] = load_component_dir(kind, cdir, tier=tier)
        return list(seen.values())
