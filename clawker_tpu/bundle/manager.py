"""Bundle install / list / validate / remove (reference: internal/bundle
manager.go Install/Update/Remove/Validate + receipt.go fetch receipts).

A bundle is a directory tree holding any of ``harnesses/<name>/``,
``stacks/<name>/``, ``monitoring/<name>/``.  Sources are local paths or git
URLs (cloned via the system git).  Installs are atomic (staging dir +
rename), recorded with a receipt, and symlink-hostile: symlinks in sources
are rejected rather than followed (reference: install.go symlink-safe
pipeline).
"""

from __future__ import annotations

import json
import shutil
import subprocess
import time
from dataclasses import dataclass
from pathlib import Path

from .. import logsetup
from ..config import Config
from ..errors import ClawkerError, NotFoundError
from .model import MANIFESTS, load_component_dir
from .resolver import KIND_DIRS

log = logsetup.get("bundle.manager")

RECEIPT = ".clawker-bundle-receipt.json"


class BundleError(ClawkerError):
    pass


@dataclass
class InstalledBundle:
    namespace: str
    name: str
    path: Path
    source: str
    installed_at: float
    components: dict[str, list[str]]
    commit: str = ""       # git sources: the installed revision


class BundleManager:
    def __init__(self, cfg: Config):
        self.cfg = cfg

    # ------------------------------------------------------------ install

    def install(self, source: str, *, namespace: str = "local", name: str = "") -> InstalledBundle:
        src = Path(source)
        if src.is_dir():
            # the receipt must survive a cwd change: auto-update re-reads
            # it from arbitrary working directories later
            source = str(src.resolve())
            bundle_name = name or src.name
            staged = self._stage_copy(src)
        elif "://" in source or source.endswith(".git") or source.startswith("git@"):
            bundle_name = name or source.rstrip("/").rsplit("/", 1)[-1].removesuffix(".git")
            staged = self._stage_clone(source)
        else:
            raise BundleError(f"bundle source {source!r}: not a directory or git URL")
        try:
            comps = self._scan(staged)
            if not any(comps.values()):
                raise BundleError(f"{source}: no harness/stack/monitoring components found")
            errs = self.validate_tree(staged)
            if errs:
                raise BundleError(f"{source}: invalid bundle: " + "; ".join(errs))
            dest = self.cfg.bundles_dir / namespace / bundle_name
            dest.parent.mkdir(parents=True, exist_ok=True)
            receipt = {
                "source": source,
                "installed_at": time.time(),
                "components": comps,
            }
            if getattr(self, "_last_clone_commit", ""):
                receipt["commit"] = self._last_clone_commit
                self._last_clone_commit = ""
            (staged / RECEIPT).write_text(json.dumps(receipt, indent=2))
            # land next to dest first (staging may be on another filesystem,
            # making move non-atomic); only then swap out any old install
            landing = dest.parent / f".{bundle_name}.installing"
            if landing.exists():
                shutil.rmtree(landing)
            shutil.move(str(staged), str(landing))
            old = dest.parent / f".{bundle_name}.old"
            if old.exists():
                shutil.rmtree(old)
            if dest.exists():
                dest.rename(old)
            landing.rename(dest)
            if old.exists():
                shutil.rmtree(old)
            return InstalledBundle(
                namespace=namespace,
                name=bundle_name,
                path=dest,
                source=source,
                installed_at=receipt["installed_at"],
                components=comps,
            )
        finally:
            if staged.exists():
                shutil.rmtree(staged, ignore_errors=True)

    def _staging_dir(self) -> Path:
        d = self.cfg.cache_dir / "bundle-staging"
        d.mkdir(parents=True, exist_ok=True)
        return d

    def _stage_copy(self, src: Path) -> Path:
        staged = self._staging_dir() / f"stage-{int(time.time() * 1e6)}"
        for p in src.rglob("*"):
            if p.is_symlink():
                raise BundleError(f"{src}: symlink {p.relative_to(src)} not allowed in bundles")
        shutil.copytree(src, staged, symlinks=False)
        return staged

    def _stage_clone(self, url: str, *, timeout: float = 120.0) -> Path:
        staged = self._staging_dir() / f"stage-{int(time.time() * 1e6)}"
        try:
            res = subprocess.run(
                ["git", "clone", "--depth", "1", url, str(staged)],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            shutil.rmtree(staged, ignore_errors=True)
            raise BundleError(f"git clone {url}: timed out after {timeout:.0f}s")
        if res.returncode != 0:
            raise BundleError(f"git clone {url} failed: {res.stderr.strip()}")
        rev = subprocess.run(["git", "-C", str(staged), "rev-parse", "HEAD"],
                             capture_output=True, text=True, timeout=30)
        self._last_clone_commit = rev.stdout.strip() if rev.returncode == 0 else ""
        shutil.rmtree(staged / ".git", ignore_errors=True)
        for p in staged.rglob("*"):
            if p.is_symlink():
                shutil.rmtree(staged, ignore_errors=True)
                raise BundleError(f"{url}: symlinks not allowed in bundles")
        return staged

    # -------------------------------------------------------------- query

    def _scan(self, root: Path) -> dict[str, list[str]]:
        comps: dict[str, list[str]] = {}
        for kind, sub in KIND_DIRS.items():
            d = root / sub
            comps[kind] = sorted(
                c.name
                for c in (d.iterdir() if d.is_dir() else [])
                if c.is_dir() and (c / MANIFESTS[kind][0]).is_file()
            )
        return comps

    def list_installed(self) -> list[InstalledBundle]:
        out = []
        root = self.cfg.bundles_dir
        if not root.is_dir():
            return out
        for ns in sorted(root.iterdir()):
            if not ns.is_dir() or ns.name.startswith("."):
                continue
            for b in sorted(ns.iterdir()):
                # dot-dirs are install staging (see install() swap)
                if not b.is_dir() or b.name.startswith("."):
                    continue
                receipt = {}
                rp = b / RECEIPT
                if rp.is_file():
                    try:
                        receipt = json.loads(rp.read_text())
                    except json.JSONDecodeError:
                        receipt = {}
                out.append(
                    InstalledBundle(
                        namespace=ns.name,
                        name=b.name,
                        path=b,
                        source=receipt.get("source", ""),
                        installed_at=receipt.get("installed_at", 0.0),
                        components=receipt.get("components") or self._scan(b),
                        commit=receipt.get("commit", ""),
                    )
                )
        return out

    def remove(self, namespace: str, name: str) -> None:
        dest = self.cfg.bundles_dir / namespace / name
        if not dest.is_dir():
            raise NotFoundError(f"bundle {namespace}/{name} not installed")
        shutil.rmtree(dest)

    # ---------------------------------------------------------- auto-update

    @staticmethod
    def _tree_hash(root: Path) -> str:
        import hashlib

        h = hashlib.sha256()
        for p in sorted(root.rglob("*")):
            if p.name == RECEIPT or not p.is_file():
                continue
            h.update(str(p.relative_to(root)).encode())
            h.update(p.read_bytes())
        return h.hexdigest()[:16]

    def auto_update_check(self, *, state=None, ttl_s: float = 86400.0,
                          errors: list[tuple[str, str]] | None = None) -> list[str]:
        """TTL-gated refresh of installed bundles (reference
        cmdutil.RunBundleAutoUpdate on the run path + bundle
        AutoUpdateCheck): local-dir sources re-install when their content
        drifted from the installed copy; git sources re-fetch.  Every
        failure is a soft skip -- an offline host must still run agents.
        Returns the ``ns/name`` list that was updated."""
        from ..state import StateStore

        state = state or StateStore()
        now = time.time()
        last = float(state.get("bundle_auto_update") or 0.0)
        if now - last < ttl_s:
            return []
        state.set("bundle_auto_update", now)
        updated: list[str] = []
        for inst in self.list_installed():
            src = inst.source
            if not src:
                continue
            try:
                if Path(src).is_dir():
                    if self._tree_hash(Path(src)) == self._tree_hash(inst.path):
                        continue
                else:
                    # git source: cheap drift probe before any clone; an
                    # unreachable remote (or unchanged HEAD) skips the
                    # re-install entirely.  A commit-less receipt (bundle
                    # installed before commits were recorded) still
                    # probes: one re-install backfills the commit instead
                    # of re-cloning on every TTL expiry forever.
                    head = self._ls_remote_head(src)
                    if not head or (inst.commit and head == inst.commit):
                        continue
                self.install(src, namespace=inst.namespace, name=inst.name)
                updated.append(f"{inst.namespace}/{inst.name}")
            except (BundleError, OSError, subprocess.TimeoutExpired) as e:
                # background runs soft-skip (an offline host must still
                # run agents); an explicit `bundle update` passes
                # ``errors`` so failures surface instead of reading as
                # "all current"
                if errors is not None:
                    errors.append((f"{inst.namespace}/{inst.name}", str(e)))
                log.debug("bundle auto-update %s/%s skipped: %s",
                          inst.namespace, inst.name, e)
        return updated

    @staticmethod
    def _ls_remote_head(url: str, *, timeout: float = 10.0) -> str:
        try:
            res = subprocess.run(["git", "ls-remote", url, "HEAD"],
                                 capture_output=True, text=True,
                                 timeout=timeout)
        except (OSError, subprocess.TimeoutExpired):
            return ""
        if res.returncode != 0 or not res.stdout.strip():
            return ""
        return res.stdout.split()[0]

    # ----------------------------------------------------------------- gc

    def _referenced_components(self) -> set[str]:
        """Component names any registered project declares (build.harness
        / build.stack); floor defaults are implicitly live everywhere."""
        from ..config import load_config
        from ..errors import ClawkerError
        from ..project.manager import ProjectManager

        refs: set[str] = set()
        try:
            projects = ProjectManager(self.cfg).list_projects()
        except ClawkerError:
            return refs
        for rec in projects:
            try:
                pcfg = load_config(Path(rec.root))
            except (ClawkerError, OSError):
                continue
            if pcfg.project is None:
                continue
            # unset fields resolve to the build defaults (bundler/build.py)
            # -- an installed bundle shadowing "python"/"claude" is live
            refs.add(pcfg.project.build.harness or "claude")
            refs.add(pcfg.project.build.stack or "python")
        return refs

    def gc(self, *, apply: bool = False,
           grace_s: float = 7 * 86400) -> dict:
        """Prune installed bundles (reference internal/bundle/gc.go):

        - crashed-swap leftovers (``.X.installing`` / ``.X.old``) always
          qualify;
        - an install older than ``grace_s`` whose components no
          registered project declares qualifies as unreferenced.

        Dry-run by default: ``apply=True`` deletes.  Returns the report
        {"leftovers", "unreferenced", "removed"}.
        """
        refs = self._referenced_components()
        leftovers: list[Path] = []
        unreferenced: list[InstalledBundle] = []
        root = self.cfg.bundles_dir
        if root.is_dir():
            for ns in sorted(root.iterdir()):
                if not ns.is_dir():
                    continue
                for b in sorted(ns.iterdir()):
                    if b.is_dir() and b.name.startswith("."):
                        leftovers.append(b)
        now = time.time()
        for inst in self.list_installed():
            # a lost/corrupt receipt must not bypass the grace period:
            # fall back to the install dir's mtime
            installed_at = inst.installed_at
            if not installed_at:
                try:
                    installed_at = inst.path.stat().st_mtime
                except OSError:
                    installed_at = now
            if now - installed_at < grace_s:
                continue
            if inst.components.get("monitoring"):
                # monitoring units are host-global (discovered by monitor
                # render, not declared per-project): never unreferenced
                continue
            provided = {n for names in inst.components.values() for n in names}
            if provided and provided & refs:
                continue
            unreferenced.append(inst)
        removed: list[str] = []
        if apply:
            for path in leftovers:
                shutil.rmtree(path, ignore_errors=True)
                removed.append(str(path))
            for inst in unreferenced:
                shutil.rmtree(inst.path, ignore_errors=True)
                removed.append(f"{inst.namespace}/{inst.name}")
        return {
            "leftovers": [str(p) for p in leftovers],
            "unreferenced": [f"{i.namespace}/{i.name}" for i in unreferenced],
            "removed": removed,
        }

    # ----------------------------------------------------------- validate

    def validate_tree(self, root: Path) -> list[str]:
        """Validate every component in a bundle tree; [] when clean."""
        errs: list[str] = []
        for kind, sub in KIND_DIRS.items():
            d = root / sub
            if not d.is_dir():
                continue
            for cdir in sorted(d.iterdir()):
                if not cdir.is_dir():
                    continue
                try:
                    comp = load_component_dir(kind, cdir)
                except ClawkerError as e:
                    errs.append(str(e))
                    continue
                errs.extend(comp.validate())
        return errs
