"""``python -m clawker_tpu.controlplane`` -- the CP daemon entrypoint.

Parity reference: cmd/clawkercp (thin main over internal/controlplane
cmd.go:193 Main).  Config comes from the same layered settings the CLI
reads; the runtime driver (and thus which daemon the CP watches) follows
settings.runtime.driver / CLAWKER_TPU_DRIVER exactly like the CLI.
"""

from __future__ import annotations

import os
import sys

from .. import consts, logsetup
from ..config import load_config
from ..engine.drivers import get_driver
from .daemon import ControlPlaneDaemon, CPConfig


def main() -> int:
    logsetup.setup(os.environ.get("CLAWKER_TPU_CP_LOG", "info"))
    cfg = load_config()
    # per-subsystem OTLP lanes (controlplane/otel): the CP's own logs
    # ship on the clawkercp lane, the netlogger rides the ebpf-egress
    # lane (SAME lane set, so an mTLS collector's infra certs cover
    # both); https collectors get per-subsystem client certs.
    # Best-effort: no collector, no lanes, no failed connects.
    lanes = {}
    try:
        from .otel import build_lanes

        lanes = build_lanes(cfg)
        if "clawkercp" in lanes:
            import logging

            logging.getLogger().addHandler(lanes["clawkercp"].handler())
    except Exception as e:  # noqa: BLE001 - telemetry never blocks boot
        logsetup.get("cp").warning("otel lanes unavailable: %s", e)
    driver = get_driver(cfg.settings, override=os.environ.get("CLAWKER_TPU_DRIVER", ""))
    cp = cfg.settings.control_plane
    firewall = None
    netlogger = None
    if cfg.settings.firewall.enable:
        # resilience contract: a failed enforcement build degrades the CP
        # (verbs answer 501 -> agent starts fail loudly), never kills it
        from ..firewall.runtime import build_handler

        try:
            firewall = build_handler(
                cfg, driver.engine(),
                monitor_fallback=not cfg.settings.firewall.default_deny,
                inprocess_ok=getattr(driver, "real_cgroups", True),
            )
        except Exception as e:
            import logging

            logging.getLogger("cp").error("event=firewall_unavailable error=%s", e)
        if firewall is not None:
            from ..monitor.netlogger import NetLogger, handler_resolvers

            rc, rz = handler_resolvers(firewall)
            # the egress stream rides its OWN subsystem lane from the
            # shared lane set (carries the infra client cert when the
            # collector terminates mTLS) -- one endpoint policy, one PKI
            netlogger = NetLogger(
                firewall.maps,
                out_path=cfg.logs_dir / "ebpf-egress.jsonl",
                resolve_cgroup=rc,
                resolve_zone=rz,
                lane=lanes.get("ebpf-egress"),
            )
    daemon = ControlPlaneDaemon(
        CPConfig(
            pki_dir=cfg.pki_dir,
            registry_path=cfg.data_dir / "agents.db",
            admin_port=cp.admin_port,
            agent_port=cp.agent_port,
            health_port=cp.health_port,
            cp_host=os.environ.get("CLAWKER_TPU_CP_HOST", "")
            or cp.advertise_host
            or consts.DOCKER_BRIDGE_GATEWAY,
            drain_to_zero=cp.drain_to_zero,
        ),
        driver.engine(),
        firewall=firewall,
        netlogger=netlogger,
    )
    return daemon.run_forever()


if __name__ == "__main__":
    sys.exit(main())
