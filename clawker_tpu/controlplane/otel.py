"""Per-subsystem OTLP log lanes, optionally over mTLS.

Each control-plane subsystem (cp, netlogger, firewall, dnsgate, ...)
gets its OWN OTLP/HTTP lane with ``service.name`` identifying it --
that is what routes its records into the right OpenSearch index
(monitor/stack.py routing connector).  When the collector terminates
TLS, the lane authenticates with a per-subsystem infra client cert
minted from the deployment's identity CA: a compromised agent container
cannot impersonate a CP subsystem's telemetry without the CA.

Parity reference: controlplane/otel (NewOtelLoggerProvider per
subsystem) + controlplane/otelcerts + controlplane/infracerts (client
certs for OTLP-over-mTLS lanes, SURVEY.md 2.7) -- re-derived over
urllib + ssl.
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
import time
import urllib.request as urlrequest
from pathlib import Path

from .. import logsetup
from ..firewall import pki

log = logsetup.get("cp.otel")


def otlp_logs_payload(service: str, records: list[dict], *,
                      severity_of=None) -> bytes:
    """The OTLP/HTTP JSON logs envelope for one subsystem's batch."""
    severity_of = severity_of or (lambda rec: "INFO")
    return json.dumps({
        "resourceLogs": [{
            "resource": {"attributes": [{
                "key": "service.name",
                "value": {"stringValue": service},
            }]},
            "scopeLogs": [{
                "logRecords": [{
                    "timeUnixNano": str(time.time_ns()),
                    "severityText": severity_of(rec),
                    "body": {"stringValue": json.dumps(rec)},
                } for rec in records]
            }],
        }]
    }).encode()


def mint_infra_cert(pki_dir: Path, subsystem: str) -> tuple[Path, Path, Path]:
    """Per-subsystem client cert from the deployment CA.  Returns
    (cert, key, ca) file paths, minting on first use (reference
    infracerts.EnsureClientCert)."""
    ca = pki.ensure_ca(Path(pki_dir))
    certs = Path(pki_dir) / "infra"
    certs.mkdir(parents=True, exist_ok=True)
    cert_p = certs / f"{subsystem}.crt"
    key_p = certs / f"{subsystem}.key"
    ca_p = Path(pki_dir) / "ca.crt"
    if not (cert_p.exists() and key_p.exists()):
        pair = pki.generate_client_cert(ca, f"clawker-otel-{subsystem}")
        cert_p.write_bytes(pair.cert_pem)
        key_p.write_bytes(pair.key_pem)
    if not ca_p.exists():
        ca_p.write_bytes(ca.cert_pem)
    return cert_p, key_p, ca_p


class OtlpLane:
    """One subsystem's lane to the collector.

    Plain HTTP for loopback/tunneled collectors; https endpoints verify
    the server against ``ca`` and authenticate with the client pair.
    Shipping is best-effort and never raises into the caller -- a downed
    collector degrades telemetry, not the subsystem."""

    def __init__(self, endpoint: str, service: str, *,
                 client_cert: Path | None = None,
                 client_key: Path | None = None,
                 ca: Path | None = None,
                 timeout: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        self.service = service
        self.timeout = timeout
        self._ctx: ssl.SSLContext | None = None
        if self.endpoint.startswith("https://"):
            self._ctx = ssl.create_default_context(
                cafile=str(ca) if ca else None)
            if client_cert and client_key:
                self._ctx.load_cert_chain(str(client_cert), str(client_key))

    def ship(self, records: list[dict], *, severity_of=None) -> bool:
        if not records or not self.endpoint:
            return False
        body = otlp_logs_payload(self.service, records,
                                 severity_of=severity_of)
        req = urlrequest.Request(
            f"{self.endpoint}/v1/logs", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            urlrequest.urlopen(req, timeout=self.timeout,
                               context=self._ctx).close()
            return True
        except Exception as e:  # noqa: BLE001 - contract: telemetry never
            # raises into the caller (urlopen surfaces ValueError/
            # InvalidURL/HTTPException beyond OSError)
            log.debug("otlp lane %s: ship failed: %s", self.service, e)
            return False

    # ------------------------------------------------------ logging lane

    def handler(self, *, level: int = logging.INFO,
                batch: int = 32, flush_s: float = 2.0) -> logging.Handler:
        """A logging.Handler that batches records onto this lane."""
        return _LaneHandler(self, level=level, batch=batch, flush_s=flush_s)


class _LaneHandler(logging.Handler):
    """Batching handler with a background shipper.

    ``emit`` only appends under the lock -- network I/O never happens on
    the logging caller's thread (Handler.handle holds the handler lock
    around emit; synchronous shipping there would stall every thread
    logging to the same logger for up to the lane timeout).  A daemon
    thread ships when the batch fills or flush_s elapses, so a quiet
    daemon's sub-batch records still reach the collector."""

    def __init__(self, lane: OtlpLane, *, level: int, batch: int,
                 flush_s: float):
        super().__init__(level=level)
        self.lane = lane
        self.batch = batch
        self.flush_s = flush_s
        self._buf: list[dict] = []
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._pump,
                                        name=f"otel-{lane.service}",
                                        daemon=True)
        self._thread.start()

    def emit(self, record: logging.LogRecord) -> None:
        rec = {"logger": record.name, "level": record.levelname,
               "message": record.getMessage()}
        with self._cond:
            self._buf.append(rec)
            if len(self._buf) >= self.batch:
                self._cond.notify()

    def _drain(self) -> list[dict]:
        out, self._buf = self._buf, []
        return out

    def _pump(self) -> None:
        while True:
            with self._cond:
                self._cond.wait(self.flush_s)
                if self._closed and not self._buf:
                    return
                out = self._drain()
            if out:
                self.lane.ship(out,
                               severity_of=lambda r: r.get("level", "INFO"))

    def flush(self) -> None:
        with self._cond:
            out = self._drain()
        if out:
            self.lane.ship(out, severity_of=lambda r: r.get("level", "INFO"))

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self.flush()
        super().close()


def build_lanes(cfg, subsystems: tuple[str, ...] = (
        "clawkercp", "ebpf-egress", "clawker-dnsgate")) -> dict[str, OtlpLane]:
    """The CP's lane set.  Endpoint from CLAWKER_TPU_OTLP (worker tunnel)
    or local collector when monitoring is enabled; https endpoints get
    per-subsystem infra certs from the deployment PKI."""
    import os

    from .. import consts

    endpoint = os.environ.get("CLAWKER_TPU_OTLP", "") or (
        f"http://127.0.0.1:{consts.OTLP_HTTP_PORT}"
        if cfg.settings.monitoring.enable else "")
    if not endpoint:
        return {}
    lanes: dict[str, OtlpLane] = {}
    pki_dir = cfg.data_dir / "pki"
    for sub in subsystems:
        cert = key = ca = None
        if endpoint.startswith("https://"):
            cert, key, ca = mint_infra_cert(pki_dir, sub)
        lanes[sub] = OtlpLane(endpoint, sub, client_cert=cert,
                              client_key=key, ca=ca)
    return lanes
