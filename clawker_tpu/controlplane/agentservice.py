"""AgentService: the CP's inbound Register listener for agentd.

Parity reference: api/agent/v1/agent.proto:32 Register (:43, scope
``self.register``) + controlplane/agent/register_handler.go -- agentd's one
outbound call binds its connection identity to the registry row.  The
reference grounds identity in peer IP (IdentityInterceptor); this build
grounds it in the *client certificate thumbprint*: the row is only marked
registered when the presented leaf's SHA-256 matches the thumbprint bound
at mint time, which survives IP churn across workers (stronger than the
peer-IP check and required once agents live on remote TPU-VM daemons).
"""

from __future__ import annotations

import socket
import ssl
import threading
from pathlib import Path

from cryptography import x509
from cryptography.hazmat.primitives import hashes

from .. import logsetup
from ..agentd.protocol import ConnectionClosed, ProtocolError, read_msg, write_msg
from . import identity
from .registry import Registry

log = logsetup.get("cp.agentservice")


class AgentService:
    """mTLS listener accepting one framed register exchange per connection."""

    def __init__(
        self,
        registry: Registry,
        *,
        cert_file: Path,
        key_file: Path,
        ca_file: Path,
        host: str = "0.0.0.0",
        port: int = 0,
    ):
        self.registry = registry
        self.host = host
        self.port = port
        self.bound_port = 0
        self._ca_pub = x509.load_pem_x509_certificate(
            Path(ca_file).read_bytes()
        ).public_key()
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_3
        ctx.load_cert_chain(cert_file, key_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(ca_file)
        self._ssl = ctx
        self._listener: socket.socket | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.host, self.port))
        ls.listen(16)
        self.bound_port = ls.getsockname()[1]
        self._listener = ls
        self._thread = threading.Thread(target=self._serve, name="agentservice", daemon=True)
        self._thread.start()
        log.info("agent service listening on :%d", self.bound_port)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout)

    def _serve(self) -> None:
        assert self._listener is not None
        while not self._stop.is_set():
            try:
                raw, addr = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._handle_recovered, args=(raw, addr), daemon=True
            )
            t.start()

    def _handle_recovered(self, raw: socket.socket, addr) -> None:
        try:
            self._handle(raw, addr)
        except Exception as e:
            log.warning("register conn %s failed: %s", addr, e)
        finally:
            try:
                raw.close()
            except OSError:
                pass

    # ------------------------------------------------------------- handling

    def _handle(self, raw: socket.socket, addr) -> None:
        raw.settimeout(10.0)
        try:
            tls = self._ssl.wrap_socket(raw, server_side=True)
        except ssl.SSLError as e:
            log.info("register tls rejected from %s: %s", addr, e)
            return
        with tls:
            try:
                msg = read_msg(tls)
            except (ProtocolError, ConnectionClosed, OSError):
                return
            if msg.get("type") != "register":
                write_msg(tls, {"type": "register_ack", "ok": False, "error": "expected register"})
                return
            reply = self._register(tls, msg)
            try:
                write_msg(tls, reply)
            except (OSError, ssl.SSLError):
                pass

    def _register(self, tls: ssl.SSLSocket, msg: dict) -> dict:
        def reject(err: str) -> dict:
            log.warning("register rejected: %s", err)
            return {"type": "register_ack", "ok": False, "error": err}

        try:
            claims = identity.verify_jwt_es256(self._ca_pub, str(msg.get("assertion", "")))
        except identity.IdentityError as e:
            return reject(str(e))
        if claims.get("scope") != "self.register":
            return reject(f"wrong scope {claims.get('scope')!r}")
        full = str(claims.get("sub") or "")
        record = self.registry.get(full)
        if record is None:
            return reject(f"unknown agent {full!r}")
        der = tls.getpeercert(binary_form=True)
        if not der:
            return reject("no client certificate")
        digest = hashes.Hash(hashes.SHA256())
        digest.update(der)
        thumb = digest.finalize().hex()
        if not self.registry.mark_registered(full, thumb):
            return reject(f"thumbprint mismatch for {full}")
        log.info("agent %s registered (cert %s)", full, thumb[:16])
        return {"type": "register_ack", "ok": True, "agent": full}
