"""The control-plane daemon: subsystem orchestration, healthz, drain.

Parity reference: internal/controlplane/cmd.go:921 run -- boot logging,
topics, enforcement build, gRPC stack (AdminService + AgentService),
docker-events feeder, workers, agent dialer, healthz aggregate (:441), and
the ordered drain sequence (:306, ordering INV-B2-007): action queue close
-> server stop -> firewall stack stop -> feeder cancel -> clean exit 0.
Resilience contract: nothing on the serve path may crash the daemon
("CP crashing is a SECURITY incident", reference root CLAUDE.md) -- every
worker thread is exception-recovered and subsystem failure degrades with a
structured ``<subsystem>_unavailable`` log, never an exit.
"""

from __future__ import annotations

import json
import signal
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from .. import consts, logsetup
from ..firewall import pki
from .adminapi import AdminServer
from .agentservice import AgentService
from .dialer import Dialer, DialerConfig, engine_endpoint_resolver, engine_profile_builder
from .dockerevents import ContainerStateRepo, DockerEvent, Feeder
from .pubsub import Topic
from .registry import Registry
from .watcher import AgentWatcher

log = logsetup.get("cp.daemon")

CP_COMMON_NAME = "clawker-controlplane"


def ensure_cp_material(pki_dir: Path) -> tuple[Path, Path, Path]:
    """CP identity on disk: (cert, key, ca) paths, minted once from the CA.

    The CP cert carries both server and client EKU (it serves Admin/Agent
    listeners *and* dials agentd), CN pinned to ``clawker-controlplane``
    (agentd verifies the CN -- reference: clawkerd listener CP CN pin).
    """
    ca = pki.ensure_ca(pki_dir)
    cert_p, key_p, ca_p = pki_dir / "cp.crt", pki_dir / "cp.key", pki_dir / "ca.crt"
    if not (cert_p.exists() and key_p.exists()):
        pair = pki.generate_cp_cert(ca)
        cert_p.write_bytes(pair.cert_pem)
        key_p.touch(mode=0o600)
        key_p.write_bytes(pair.key_pem)
    if not ca_p.exists():
        ca_p.write_bytes(ca.cert_pem)
    return cert_p, key_p, ca_p


@dataclass
class CPConfig:
    pki_dir: Path
    registry_path: Path
    host: str = "0.0.0.0"
    admin_port: int = consts.CP_ADMIN_PORT
    agent_port: int = consts.CP_AGENT_PORT
    health_port: int = consts.CP_HEALTH_PORT
    cp_host: str = ""                    # address agentd uses to Register back
    watch_interval_s: float = 30.0
    drain_to_zero: bool = False
    drain_grace_polls: int = 2
    dns_gc_interval_s: float = 30.0      # dns_cache/bypass map GC ticker


@dataclass
class Subsystems:
    """What the daemon wired; exposed for healthz/status and tests."""

    topic: Topic[DockerEvent] | None = None
    repo: ContainerStateRepo | None = None
    feeder: Feeder | None = None
    dialer: Dialer | None = None
    agent_service: AgentService | None = None
    admin: AdminServer | None = None
    watcher: AgentWatcher | None = None
    registry: Registry | None = None
    unavailable: list[str] = field(default_factory=list)


class ControlPlaneDaemon:
    def __init__(self, cfg: CPConfig, engine, firewall=None, netlogger=None):
        self.cfg = cfg
        self.engine = engine
        self.firewall = firewall          # FirewallHandler | None
        self.netlogger = netlogger        # monitor.netlogger.NetLogger | None
        self.subs = Subsystems()
        self._stop = threading.Event()
        self._gc_thread: threading.Thread | None = None
        self._drained_to_zero = False
        self._healthz: ThreadingHTTPServer | None = None
        self._healthz_thread: threading.Thread | None = None
        self.health_bound_port = 0
        self.started_at = 0.0

    # ---------------------------------------------------------------- boot

    def start(self) -> None:
        self.started_at = time.time()
        cert, key, ca = ensure_cp_material(self.cfg.pki_dir)
        registry = Registry(self.cfg.registry_path)
        self.subs.registry = registry

        # topics + docker-events feeder (cmd.go:768 buildTopics, :489 startFeeder)
        topic: Topic[DockerEvent] = Topic("docker-events")
        repo = ContainerStateRepo()
        feeder = Feeder(self.engine, topic, repo)
        self.subs.topic, self.subs.repo, self.subs.feeder = topic, repo, feeder

        # grpc-equivalent stack (cmd.go:609 buildGRPCStack)
        agent_service = AgentService(
            registry, cert_file=cert, key_file=key, ca_file=ca,
            host=self.cfg.host, port=self.cfg.agent_port,
        )
        admin = AdminServer(
            cert_file=cert, key_file=key, ca_file=ca,
            host=self.cfg.host, port=self.cfg.admin_port,
        )
        admin.register("ListAgents", self._handle_list_agents)
        admin.register("Status", self._handle_status)
        if self.firewall is not None:
            # enforcement build (cmd.go:517 buildEnforcement): verbs only
            # exist when the handler does -- absent = 501, fail-closed
            self.firewall.register_on(admin)
            try:
                cleared = self.firewall.clear_expired_bypass()
                if cleared:
                    log.info("cleared %d stale bypass entries", cleared)
            except Exception as e:
                log.error("event=firewall_bypass_gc_failed error=%s", e)
        self.subs.agent_service, self.subs.admin = agent_service, admin

        # agent dialer (cmd.go:847 startAgentDialer)
        dialer = Dialer(
            DialerConfig(
                cert_file=cert, key_file=key, ca_file=ca,
                cp_host=self.cfg.cp_host,
                cp_agent_port=0,      # patched after bind below
            ),
            registry,
            engine_endpoint_resolver(self.engine),
            engine_profile_builder(self.engine),
        )
        self.subs.dialer = dialer

        # watcher (watcher.go; drain-to-zero cmd.go:306)
        watcher = AgentWatcher(
            self.engine,
            interval_s=self.cfg.watch_interval_s,
            drain_grace_polls=self.cfg.drain_grace_polls,
            on_drained=self._on_drained_to_zero if self.cfg.drain_to_zero else None,
        )
        self.subs.watcher = watcher

        # bring-up order: listeners first (agents may register the moment
        # the feeder reconciles), then feeder, dialer, watcher
        for name, fn in (
            ("agent_service", agent_service.start),
            ("admin", admin.start),
        ):
            try:
                fn()
            except Exception as e:
                # fail-closed subsystems degrade loudly, the daemon survives
                log.error("event=%s_unavailable error=%s", name, e)
                self.subs.unavailable.append(name)
        dialer.cfg.cp_agent_port = agent_service.bound_port or self.cfg.agent_port
        feeder.start()
        dialer.start(topic, repo)
        watcher.start()
        if self.netlogger is not None:   # workers (cmd.go:812 startWorkers)
            try:
                self.netlogger.start()
            except Exception as e:
                log.error("event=netlogger_unavailable error=%s", e)
                self.subs.unavailable.append("netlogger")
        if self.firewall is not None and self.cfg.dns_gc_interval_s > 0:
            # periodic dns_cache + bypass GC (reference: ebpf/dns_gc.go
            # ticker) -- TTL expiry is enforced ONLY here, the kernel skips
            # expires_unix at lookup by design
            self._gc_thread = threading.Thread(
                target=self._gc_loop, name="dns-gc", daemon=True
            )
            self._gc_thread.start()
        self._start_healthz()
        log.info(
            "control plane up: admin=:%s agent=:%s health=:%s",
            admin.bound_port, agent_service.bound_port, self.health_bound_port,
        )

    def _gc_loop(self) -> None:
        """Recovered worker: tick map GC until drain (serve-path contract:
        errors degrade with a structured log, never crash)."""
        while not self._stop.wait(self.cfg.dns_gc_interval_s):
            try:
                res = self.firewall.gc_tick()
                if res.get("dns_expired") or res.get("bypass_cleared"):
                    log.info(
                        "event=map_gc dns_expired=%d bypass_cleared=%d",
                        res.get("dns_expired", 0), res.get("bypass_cleared", 0),
                    )
            except Exception as e:
                log.error("event=map_gc_failed error=%s", e)

    # ------------------------------------------------------------- healthz

    def _start_healthz(self) -> None:
        outer = self

        class _Health(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_GET(self):  # noqa: N802
                body = json.dumps(outer.health()).encode()
                ok = outer.healthy()
                self.send_response(200 if ok else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        try:
            self._healthz = ThreadingHTTPServer((self.cfg.host, self.cfg.health_port), _Health)
        except OSError as e:
            log.error("event=healthz_unavailable error=%s", e)
            self.subs.unavailable.append("healthz")
            return
        self.health_bound_port = self._healthz.server_address[1]
        self._healthz_thread = threading.Thread(
            target=self._healthz.serve_forever, name="healthz", daemon=True
        )
        self._healthz_thread.start()

    def health(self) -> dict:
        """Aggregate probe (reference: cmd.go:441 startHealthz, 7 probes)."""
        s = self.subs
        return {
            "admin": bool(s.admin and s.admin.bound_port),
            "agent_service": bool(s.agent_service and s.agent_service.bound_port),
            "feeder": bool(s.feeder and s.feeder._thread and s.feeder._thread.is_alive()),
            "watcher": bool(s.watcher and s.watcher._thread and s.watcher._thread.is_alive()),
            "watcher_blind": bool(s.watcher and s.watcher.consecutive_errors > 0),
            "registry": s.registry is not None,
            "unavailable": list(s.unavailable),
            "uptime_s": round(time.time() - self.started_at, 1),
        }

    def healthy(self) -> bool:
        h = self.health()
        return h["admin"] and h["agent_service"] and h["feeder"] and not h["unavailable"]

    # ------------------------------------------------------------- handlers

    def _handle_list_agents(self, req: dict) -> dict:
        assert self.subs.registry is not None
        records = self.subs.registry.list(req.get("project") or None)
        return {
            "agents": [
                {
                    "full_name": r.full_name, "project": r.project, "agent": r.agent,
                    "container_id": r.container_id, "state": r.state,
                    "initialized": r.initialized,
                    "registered": bool(r.registered_at), "worker": r.worker,
                    "last_seen": r.last_seen,
                }
                for r in records
            ]
        }

    def _handle_status(self, req: dict) -> dict:
        return {"health": self.health(), "healthy": self.healthy()}

    # ---------------------------------------------------------------- drain

    def request_stop(self) -> None:
        self._stop.set()

    def _on_drained_to_zero(self) -> None:
        self._drained_to_zero = True
        self.request_stop()

    def wait(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(1.0)

    def drain(self) -> None:
        """Ordered shutdown (reference: runDrainSequence cmd.go:306)."""
        s = self.subs
        log.info("drain: begin")
        self._stop.set()                 # stops the GC ticker
        if self._gc_thread is not None:
            self._gc_thread.join(2.0)
        for name, fn in (
            # firewall action queue closes FIRST (ordering INV-B2-007):
            # no mutation may land while listeners wind down
            ("firewall_queue", lambda: self.firewall and self.firewall.close()),
            ("admin", lambda: s.admin and s.admin.stop()),
            ("agent_service", lambda: s.agent_service and s.agent_service.stop()),
            ("watcher", lambda: s.watcher and s.watcher.stop()),
            ("dialer", lambda: s.dialer and s.dialer.stop()),
            # drain-to-zero (no agents left): tear the data plane down and
            # flush maps; on any other exit the pinned maps keep enforcing
            # the last rule set (fail-closed)
            # netlogger stops BEFORE teardown: teardown flushes the maps
            # (events ring included), so the final drain must land first
            ("netlogger", lambda: self.netlogger and self.netlogger.stop()),
            ("firewall_teardown",
             lambda: self.firewall and self._drained_to_zero
             and self.firewall.teardown()),
            ("feeder", lambda: s.feeder and s.feeder.stop()),
            ("registry", lambda: s.registry and s.registry.close()),
        ):
            try:
                fn()
            except Exception as e:
                log.warning("drain: %s stop failed: %s", name, e)
        if self._healthz is not None:
            self._healthz.shutdown()
            self._healthz.server_close()
        if self._healthz_thread is not None:
            self._healthz_thread.join(2.0)
        log.info("drain: complete")

    def run_forever(self) -> int:
        """Start, serve until SIGTERM/SIGINT (or drain-to-zero), drain."""
        signal.signal(signal.SIGTERM, lambda *_: self.request_stop())
        signal.signal(signal.SIGINT, lambda *_: self.request_stop())
        self.start()
        self.wait()
        self.drain()
        return 0
