"""AdminService: mTLS + bearer-token JSON API of the control plane.

Parity reference: api/admin/v1/admin.proto:27 -- 15 RPCs (13 firewall
verbs :33-:91, ListAgents :96, GetSystemTime :116) with a method->scope
map (``AdminMethodScopes``) enforced by an auth interceptor
(controlplane/server AuthInterceptor: fail-closed).  The reference fronts
gRPC with Ory Hydra introspection; this build keeps the same wire contract
shape as ``POST /v1/<Method>`` JSON over mTLS with a self-issued ES256
bearer (SURVEY.md section 7 step 5: the Ory triple is the designated
replaceable part).  Transport auth (client cert signed by the CA) and
request auth (bearer scope) are both required -- fail-closed on either.
"""

from __future__ import annotations

import json
import socket
import ssl
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Callable
from urllib import error as urlerror
from urllib import request as urlrequest

from cryptography import x509

from .. import consts, logsetup
from ..errors import ClawkerError
from . import identity

log = logsetup.get("cp.admin")

Handler = Callable[[dict], dict]

# Parity: AdminMethodScopes (admin.proto) -- uniform `admin` scope for every
# management verb; `self.register` never reaches this surface (AgentService).
ADMIN_METHODS = (
    "FirewallInit", "FirewallEnable", "FirewallDisable", "FirewallBypass",
    "FirewallAddRules", "FirewallRemoveRule", "FirewallListRules",
    "FirewallReload", "FirewallStatus", "FirewallRotateCA",
    "FirewallSyncRoutes", "FirewallResolveHostname", "FirewallRemove",
    "ListAgents", "GetSystemTime", "Status",
)
ADMIN_METHOD_SCOPES = {m: "admin" for m in ADMIN_METHODS}
TOKEN_TTL_S = 3600


class AdminError(ClawkerError):
    pass


def mint_admin_token(ca, *, ttl_s: int = TOKEN_TTL_S) -> str:
    """Client-credentials stand-in: an ES256 bearer signed by the CA key."""
    now = int(time.time())
    return identity.sign_jwt_es256(
        ca.key,
        {"iss": consts.PRODUCT, "sub": "admin-cli", "scope": "admin",
         "iat": now, "exp": now + ttl_s},
    )


class AdminServer:
    """Threaded HTTPS server dispatching POST /v1/<Method> to handlers."""

    def __init__(
        self,
        *,
        cert_file: Path,
        key_file: Path,
        ca_file: Path,
        host: str = "0.0.0.0",
        port: int = 0,
    ):
        self._handlers: dict[str, Handler] = {}
        self._ca_pub = x509.load_pem_x509_certificate(
            Path(ca_file).read_bytes()
        ).public_key()
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_3
        ctx.load_cert_chain(cert_file, key_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(ca_file)
        self._ssl = ctx
        self.host = host
        self.port = port
        self.bound_port = 0
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.register("GetSystemTime", lambda req: {"unix": time.time()})

    def register(self, method: str, handler: Handler) -> None:
        if method not in ADMIN_METHOD_SCOPES:
            raise AdminError(f"unknown admin method {method!r}")
        self._handlers[method] = handler

    def registered(self) -> list[str]:
        return sorted(self._handlers)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        outer = self

        class _Requests(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route through our logger
                log.debug("admin http: " + fmt, *args)

            def do_POST(self):  # noqa: N802 (http.server convention)
                outer._dispatch(self)

        self._httpd = ThreadingHTTPServer((self.host, self.port), _Requests)
        self._httpd.socket = self._ssl.wrap_socket(self._httpd.socket, server_side=True)
        self.bound_port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="adminapi", daemon=True
        )
        self._thread.start()
        log.info("admin api listening on :%d", self.bound_port)

    def stop(self, timeout: float = 5.0) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------------------------------------------------------- dispatch

    def _dispatch(self, req: BaseHTTPRequestHandler) -> None:
        try:
            self._dispatch_inner(req)
        except Exception as e:
            # serve-path resilience: a handler bug answers 500, never kills
            # the CP (reference: no panic on serve path, root CLAUDE.md)
            log.error("admin dispatch failure: %s", e)
            try:
                self._reply(req, 500, {"error": "internal error"})
            except Exception:
                pass

    def _dispatch_inner(self, req: BaseHTTPRequestHandler) -> None:
        path = req.path
        if not path.startswith("/v1/"):
            self._reply(req, 404, {"error": "not found"})
            return
        method = path[len("/v1/"):]
        scope = ADMIN_METHOD_SCOPES.get(method)
        if scope is None:
            self._reply(req, 404, {"error": f"unknown method {method!r}"})
            return
        auth = req.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            self._reply(req, 401, {"error": "missing bearer token"})
            return
        try:
            claims = identity.verify_jwt_es256(self._ca_pub, auth[len("Bearer "):])
        except identity.IdentityError as e:
            self._reply(req, 401, {"error": str(e)})
            return
        granted = set(str(claims.get("scope", "")).split())
        if scope not in granted:
            self._reply(req, 403, {"error": f"scope {scope!r} required"})
            return
        handler = self._handlers.get(method)
        if handler is None:
            self._reply(req, 501, {"error": f"{method} not available"})
            return
        length = int(req.headers.get("Content-Length") or 0)
        body = req.rfile.read(length) if length else b"{}"
        try:
            payload = json.loads(body or b"{}")
        except json.JSONDecodeError:
            self._reply(req, 400, {"error": "invalid JSON body"})
            return
        try:
            result = handler(payload if isinstance(payload, dict) else {})
        except ClawkerError as e:
            self._reply(req, 422, {"error": str(e)})
            return
        self._reply(req, 200, result if isinstance(result, dict) else {"result": result})

    @staticmethod
    def _reply(req: BaseHTTPRequestHandler, code: int, payload: dict) -> None:
        data = json.dumps(payload).encode()
        req.send_response(code)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)


class AdminClient:
    """CLI-side client: mTLS client cert + bearer, JSON in/out.

    Parity reference: controlplane/adminclient Dial -- mTLS with an
    auto-refreshing bearer; here the token is minted locally from the CA
    key the CLI already owns (same trust root the CP verifies against).
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        cert_file: Path,
        key_file: Path,
        ca_file: Path,
        token: str,
        timeout: float = 15.0,
    ):
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_3
        ctx.load_cert_chain(cert_file, key_file)
        ctx.load_verify_locations(ca_file)
        ctx.check_hostname = False      # dialed by IP; CA grounds trust
        ctx.verify_mode = ssl.CERT_REQUIRED
        self._ctx = ctx
        self.base = f"https://{host}:{port}"
        self.token = token
        self.timeout = timeout

    def call(self, method: str, payload: dict | None = None) -> dict:
        req = urlrequest.Request(
            f"{self.base}/v1/{method}",
            data=json.dumps(payload or {}).encode(),
            headers={
                "Content-Type": "application/json",
                "Authorization": f"Bearer {self.token}",
            },
            method="POST",
        )
        try:
            with urlrequest.urlopen(req, timeout=self.timeout, context=self._ctx) as resp:
                return json.loads(resp.read() or b"{}")
        except urlerror.HTTPError as e:
            try:
                detail = json.loads(e.read() or b"{}").get("error", "")
            except json.JSONDecodeError:
                detail = ""
            raise AdminError(f"{method}: HTTP {e.code} {detail}".strip()) from None
        except (urlerror.URLError, socket.timeout, OSError) as e:
            raise AdminError(f"{method}: control plane unreachable ({e})") from None
