"""Host-side control-plane lifecycle: ensure-running / stop / status.

Parity reference: controlplane/manager (bootstrap.go EnsureRunning / Stop /
CPRunning).  The reference runs clawkercp as PID1 of a privileged container
with a content-derived image tag; this build runs the CP as a supervised
host daemon (``python -m clawker_tpu.controlplane``) -- on a TPU-VM worker
the same entrypoint runs per-worker under the tpu_vm driver's SSH
provisioner, which is the graft shape BASELINE.json asks for (CP per
worker, streams tunneled).  Liveness is grounded in the healthz aggregate
probe, not the pidfile: a stale pidfile never blocks bring-up and a wedged
CP (pid alive, healthz dead) is restarted.
"""

from __future__ import annotations

from .. import logsetup
from ..config import Config
from ..errors import ClawkerError
from ..util.daemon import DaemonError, DaemonSpec

log = logsetup.get("cp.manager")

START_DEADLINE_S = 15.0
STOP_DEADLINE_S = 10.0


class ControlPlaneError(ClawkerError):
    pass


def _spec(cfg: Config) -> DaemonSpec:
    return DaemonSpec(
        name="control plane",
        module="clawker_tpu.controlplane",
        pidfile=cfg.state_dir / "cp.pid",
        logfile=cfg.logs_dir / "cp.log",
        health_url=(
            f"http://127.0.0.1:{cfg.settings.control_plane.health_port}/healthz"
        ),
        start_deadline_s=START_DEADLINE_S,
    )


def health(cfg: Config, timeout: float = 2.0) -> dict | None:
    """The healthz aggregate, or None when no CP answers.  A 503 is a
    live-but-degraded CP (body still returned) -- see DaemonSpec.health."""
    return _spec(cfg).health(timeout)


def running(cfg: Config) -> bool:
    return _spec(cfg).running()


def ensure_running(cfg: Config, *, wait_s: float = START_DEADLINE_S) -> None:
    """Idempotent bring-up: healthy CP -> no-op; wedged CP -> replace."""
    spec = _spec(cfg)
    spec.start_deadline_s = wait_s
    try:
        spec.ensure_running(log=log)
    except DaemonError as e:
        raise ControlPlaneError(str(e)) from None


def stop(cfg: Config) -> bool:
    """Stop the CP if running; returns whether anything was stopped."""
    return _spec(cfg).stop()


def admin_client(cfg: Config, *, ensure_material: bool = False):
    """The one place the CLI-side mTLS + bearer admin client is assembled
    (cmd_controlplane, cmd_firewall and the run-path firewall hooks all
    route through here so connection/token logic can't drift)."""
    from ..firewall import pki
    from .adminapi import AdminClient, mint_admin_token

    cert = cfg.pki_dir / "cp.crt"
    key = cfg.pki_dir / "cp.key"
    ca_path = cfg.pki_dir / "ca.crt"
    if not (cert.exists() and key.exists() and ca_path.exists()):
        if not ensure_material:
            # read paths must not mint fresh PKI a running CP would reject
            raise ControlPlaneError(
                "control-plane PKI not initialized (run `clawker controlplane up` first)"
            )
        from .daemon import ensure_cp_material

        cert, key, ca_path = ensure_cp_material(cfg.pki_dir)
    ca = pki.ensure_ca(cfg.pki_dir)  # loads the existing CA, never re-mints
    return AdminClient(
        "127.0.0.1",
        cfg.settings.control_plane.admin_port,
        cert_file=cert,
        key_file=key,
        ca_file=ca_path,
        token=mint_admin_token(ca),
    )
