"""Host-side control-plane lifecycle: ensure-running / stop / status.

Parity reference: controlplane/manager (bootstrap.go EnsureRunning / Stop /
CPRunning).  The reference runs clawkercp as PID1 of a privileged container
with a content-derived image tag; this build runs the CP as a supervised
host daemon (``python -m clawker_tpu.controlplane``) -- on a TPU-VM worker
the same entrypoint runs per-worker under the tpu_vm driver's SSH
provisioner, which is the graft shape BASELINE.json asks for (CP per
worker, streams tunneled).  Liveness is grounded in the healthz aggregate
probe, not the pidfile: a stale pidfile never blocks bring-up and a wedged
CP (pid alive, healthz dead) is restarted.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path
from urllib import error as urlerror
from urllib import request as urlrequest

from .. import logsetup
from ..config import Config
from ..errors import ClawkerError

log = logsetup.get("cp.manager")

START_DEADLINE_S = 15.0
STOP_DEADLINE_S = 10.0


class ControlPlaneError(ClawkerError):
    pass


def _pidfile(cfg: Config) -> Path:
    return cfg.state_dir / "cp.pid"


def _logfile(cfg: Config) -> Path:
    return cfg.logs_dir / "cp.log"


def health(cfg: Config, timeout: float = 2.0) -> dict | None:
    """The healthz aggregate, or None when no CP answers.

    A 503 is a *live but degraded* CP: the aggregate body still comes back
    (so status can show which subsystem is down) instead of being treated
    as not-running -- which would send ensure_running into a kill/respawn
    loop against a CP that answers every probe."""
    port = cfg.settings.control_plane.health_port
    try:
        with urlrequest.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=timeout) as r:
            return json.loads(r.read() or b"{}")
    except urlerror.HTTPError as e:
        try:
            return json.loads(e.read() or b"{}")
        except (OSError, json.JSONDecodeError):
            return {"degraded": True}
    except (urlerror.URLError, OSError, json.JSONDecodeError):
        return None


def running(cfg: Config) -> bool:
    h = health(cfg)
    return bool(h)


def _read_pid(cfg: Config) -> int:
    try:
        return int(_pidfile(cfg).read_text().strip())
    except (OSError, ValueError):
        return 0


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def ensure_running(cfg: Config, *, wait_s: float = START_DEADLINE_S) -> None:
    """Idempotent bring-up: healthy CP -> no-op; wedged CP -> replace."""
    if running(cfg):
        return
    pid = _read_pid(cfg)
    if _pid_alive(pid):
        log.warning("cp pid %d alive but healthz dead; replacing", pid)
        _terminate(pid)
    cfg.logs_dir.mkdir(parents=True, exist_ok=True)
    cfg.state_dir.mkdir(parents=True, exist_ok=True)
    logf = open(_logfile(cfg), "ab")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "clawker_tpu.controlplane"],
            stdout=logf,
            stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL,
            start_new_session=True,      # survive the CLI process
            env=os.environ.copy(),
        )
    finally:
        logf.close()
    _pidfile(cfg).write_text(str(proc.pid))
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        if running(cfg):
            log.info("control plane up (pid %d)", proc.pid)
            return
        if proc.poll() is not None:
            _pidfile(cfg).unlink(missing_ok=True)
            raise ControlPlaneError(
                f"control plane exited {proc.returncode} during startup; see {_logfile(cfg)}"
            )
        time.sleep(0.2)
    # never got healthy: don't leave a half-alive CP owning the pidfile --
    # the next ensure_running would kill/respawn it on every container start
    _terminate(proc.pid)
    _pidfile(cfg).unlink(missing_ok=True)
    raise ControlPlaneError(
        f"control plane not healthy within {wait_s:.0f}s; see {_logfile(cfg)}"
    )


def _terminate(pid: int, deadline_s: float = STOP_DEADLINE_S) -> None:
    try:
        os.kill(pid, signal.SIGTERM)
    except OSError:
        return
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if not _pid_alive(pid):
            return
        time.sleep(0.1)
    try:
        os.kill(pid, signal.SIGKILL)       # drain hung; hard stop
    except OSError:
        pass


def stop(cfg: Config) -> bool:
    """Stop the CP if running; returns whether anything was stopped."""
    pid = _read_pid(cfg)
    was = _pid_alive(pid)
    if was:
        _terminate(pid)
    _pidfile(cfg).unlink(missing_ok=True)
    return was


def admin_client(cfg: Config, *, ensure_material: bool = False):
    """The one place the CLI-side mTLS + bearer admin client is assembled
    (cmd_controlplane, cmd_firewall and the run-path firewall hooks all
    route through here so connection/token logic can't drift)."""
    from ..firewall import pki
    from .adminapi import AdminClient, mint_admin_token

    cert = cfg.pki_dir / "cp.crt"
    key = cfg.pki_dir / "cp.key"
    ca_path = cfg.pki_dir / "ca.crt"
    if not (cert.exists() and key.exists() and ca_path.exists()):
        if not ensure_material:
            # read paths must not mint fresh PKI a running CP would reject
            raise ControlPlaneError(
                "control-plane PKI not initialized (run `clawker controlplane up` first)"
            )
        from .daemon import ensure_cp_material

        cert, key, ca_path = ensure_cp_material(cfg.pki_dir)
    ca = pki.ensure_ca(cfg.pki_dir)  # loads the existing CA, never re-mints
    return AdminClient(
        "127.0.0.1",
        cfg.settings.control_plane.admin_port,
        cert_file=cert,
        key_file=key,
        ca_file=ca_path,
        token=mint_admin_token(ca),
    )
