"""Control-plane layer (reference: internal/controlplane + controlplane/*)."""
