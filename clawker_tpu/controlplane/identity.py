"""Per-agent identity: bootstrap material minting, assertion JWTs, delivery.

Parity reference: internal/cmd/container/shared/agent_bootstrap.go:153
InstallAgentBootstrapMaterial -- between create and start the CLI mints a
per-agent mTLS leaf plus an assertion JWT and tars them into the container
at /run/clawker/bootstrap; agentd's boot reads exactly these files.  The
reference gets its assertion from Ory Hydra; this build self-issues an
ES256 JWT signed by the firewall CA key (the CP verifies with the CA public
key), which keeps the AdminService/Register contract without the Ory triple
(SURVEY.md section 7 step 5 explicitly defers it).
"""

from __future__ import annotations

import base64
import io
import json
import secrets
import tarfile
import threading
import time
from dataclasses import dataclass

from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
    encode_dss_signature,
)

from .. import consts
from ..errors import ClawkerError
from ..firewall import pki
from ..util import phases

ASSERTION_TTL_S = 24 * 3600

# --- CA session cache: per-agent leaf certs keyed by (CA cert, agent
# full name).  Leaf minting (EC keygen + cert sign) dominated the
# identity_bootstrap cold-start stage (BENCH_r05: 7.0ms of an 8.95ms
# framework cold start); the leaf's CN/SAN is project.agent -- no
# container id -- so a warm placement (loop restart, migration,
# re-create, resume) can reuse it while the assertion JWT and session
# key stay per-container.  Keying by the CA cert PEM makes rotation
# self-invalidating: rotate_ca yields a new PEM, so every cached leaf
# of the retired root simply stops being found.
_LEAF_CACHE: dict[tuple[bytes, str], "pki.CertPair"] = {}
_LEAF_CACHE_MAX = 1024          # ~1KB/entry; a 64-agent pod uses 64
_leaf_lock = threading.Lock()


def _leaf_for(ca: pki.CA, fname: str, *, reuse: bool = True) -> pki.CertPair:
    if not reuse:
        return pki.generate_agent_cert(ca, fname)
    key = (ca.cert_pem, fname)
    with _leaf_lock:
        leaf = _LEAF_CACHE.get(key)
    phases.incr("identity.leaf_cache_hit" if leaf is not None
                else "identity.leaf_cache_miss")
    if leaf is None:
        leaf = pki.generate_agent_cert(ca, fname)
        with _leaf_lock:
            if len(_LEAF_CACHE) >= _LEAF_CACHE_MAX:
                _LEAF_CACHE.clear()
            _LEAF_CACHE[key] = leaf
    return leaf


def prewarm_identities(ca: pki.CA, project: str, agents) -> int:
    """Pre-mint leaf certs into the session cache for the given agent
    names (fleet fan-outs call this once up front so every placement's
    identity_bootstrap is a cache hit).  Returns how many were minted
    (already-warm agents cost nothing)."""
    minted = 0
    for agent in agents:
        fname = full_name(project, agent)
        key = (ca.cert_pem, fname)
        with _leaf_lock:
            warm = key in _LEAF_CACHE
        if not warm:
            _leaf_for(ca, fname)
            minted += 1
    return minted


def clear_identity_cache() -> None:
    """Drop every cached leaf (tests; explicit revocation sweeps)."""
    with _leaf_lock:
        _LEAF_CACHE.clear()


class IdentityError(ClawkerError):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_dec(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def sign_jwt_es256(key: ec.EllipticCurvePrivateKey, claims: dict) -> str:
    """Compact ES256 JWT (raw r||s signature per RFC 7518 3.4)."""
    header = _b64url(json.dumps({"alg": "ES256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = f"{header}.{payload}".encode()
    der = key.sign(signing_input, ec.ECDSA(hashes.SHA256()))
    r, s = decode_dss_signature(der)
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    return f"{header}.{payload}.{_b64url(sig)}"


def verify_jwt_es256(pub: ec.EllipticCurvePublicKey, token: str, *, now: float | None = None) -> dict:
    """Verify signature + exp/iat; returns claims or raises IdentityError."""
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        header = json.loads(_b64url_dec(header_b64))
        if header.get("alg") != "ES256":
            raise IdentityError(f"unexpected JWT alg {header.get('alg')!r}")
        raw = _b64url_dec(sig_b64)
        if len(raw) != 64:
            raise IdentityError("malformed ES256 signature")
        der = encode_dss_signature(int.from_bytes(raw[:32], "big"), int.from_bytes(raw[32:], "big"))
        pub.verify(der, f"{header_b64}.{payload_b64}".encode(), ec.ECDSA(hashes.SHA256()))
        claims = json.loads(_b64url_dec(payload_b64))
    except IdentityError:
        raise
    except Exception as e:
        raise IdentityError(f"invalid assertion JWT: {e}") from None
    t = time.time() if now is None else now
    if claims.get("exp") is not None and t > float(claims["exp"]):
        raise IdentityError("assertion JWT expired")
    if claims.get("iat") is not None and t < float(claims["iat"]) - 300:
        raise IdentityError("assertion JWT issued in the future")
    return claims


@dataclass
class BootstrapMaterial:
    """The five files agentd boot reads from /run/clawker/bootstrap."""

    agent_cert: bytes       # agent.crt -- mTLS leaf (server+client EKU)
    agent_key: bytes        # agent.key
    ca_cert: bytes          # ca.crt -- trust anchor for the CP dialer
    assertion_jwt: str      # assertion.jwt -- identity proof for Register
    session_key: str        # session.key -- per-agent shared secret (audit HMAC)

    def files(self) -> dict[str, bytes]:
        return {
            "agent.crt": self.agent_cert,
            "agent.key": self.agent_key,
            "ca.crt": self.ca_cert,
            "assertion.jwt": self.assertion_jwt.encode(),
            "session.key": self.session_key.encode(),
        }

    def tar_bytes(self, prefix: str = "") -> bytes:
        """Tar of the bundle.  With ``prefix`` (e.g. ``bootstrap``) the tar
        carries a leading directory entry and prefixed members, so it can be
        extracted at an *existing* parent dir -- real daemons 404 when the
        extraction path itself is missing (reference solves this the same
        way: WriteAgentBootstrapToContainer tars ``bootstrap/`` into
        /run/clawker, agent_bootstrap.go:209)."""
        buf = io.BytesIO()
        now = int(time.time())
        with tarfile.open(fileobj=buf, mode="w") as tf:
            if prefix:
                d = tarfile.TarInfo(prefix)
                d.type = tarfile.DIRTYPE
                d.mode = 0o700
                d.mtime = now
                tf.addfile(d)
            for name, data in self.files().items():
                info = tarfile.TarInfo(f"{prefix}/{name}" if prefix else name)
                info.size = len(data)
                info.mode = 0o600 if name.endswith((".key", ".jwt")) else 0o644
                info.mtime = now
                tf.addfile(info, io.BytesIO(data))
        return buf.getvalue()


def full_name(project: str, agent: str) -> str:
    return f"{project}.{agent}"


def mint_bootstrap_material(
    ca: pki.CA, project: str, agent: str, *, container_id: str = "",
    reuse_leaf: bool = True
) -> BootstrapMaterial:
    """Mint the per-agent identity bundle (leaf + assertion + session key).

    The mTLS leaf rides the CA session cache (warm placements reuse it;
    ``reuse_leaf=False`` forces a fresh keypair); the assertion JWT and
    session key are ALWAYS fresh -- they bind the container id and the
    per-container audit secret."""
    fname = full_name(project, agent)
    with phases.phase("identity_mint_leaf"):
        leaf = _leaf_for(ca, fname, reuse=reuse_leaf)
    now = int(time.time())
    claims = {
        "iss": consts.PRODUCT,
        "sub": fname,
        "project": project,
        "agent": agent,
        "container_id": container_id,
        "iat": now,
        "exp": now + ASSERTION_TTL_S,
        "jti": secrets.token_hex(8),
        "scope": "self.register",
    }
    return BootstrapMaterial(
        agent_cert=leaf.cert_pem,
        agent_key=leaf.key_pem,
        ca_cert=ca.cert_pem,
        assertion_jwt=sign_jwt_es256(ca.key, claims),
        session_key=secrets.token_hex(32),
    )


def install_bootstrap_material(engine, container_ref: str, material: BootstrapMaterial) -> None:
    """Tar the bundle into the created (not yet started) container
    (reference: WriteAgentBootstrapToContainer agent_bootstrap.go:209).
    Extracts at the parent dir with a ``bootstrap/`` directory entry so the
    target need not pre-exist in the image."""
    parent, _, leaf = consts.BOOTSTRAP_DIR.rpartition("/")
    engine.put_archive(container_ref, parent or "/", material.tar_bytes(prefix=leaf))


def make_bootstrapper(cfg, engine, registry=None):
    """The create-path hook: mint + install material, bind the registry row.

    Wired by the CLI factory as ``AgentRuntime.bootstrap`` so every created
    agent container carries identity material before it first starts.
    """

    def hook(container_id: str, project: str, agent: str) -> None:
        ca = pki.ensure_ca(cfg.pki_dir)
        material = mint_bootstrap_material(ca, project, agent, container_id=container_id)
        with phases.phase("identity_install"):
            install_bootstrap_material(engine, container_id, material)
        if registry is not None:
            registry.bind(
                full_name(project, agent),
                project,
                agent,
                container_id=container_id,
                cert_sha256=cert_fingerprint(material.agent_cert),
            )

    return hook


def cert_fingerprint(cert_pem: bytes) -> str:
    """SHA-256 thumbprint of the DER cert, hex -- the registry binding key."""
    from cryptography import x509

    cert = x509.load_pem_x509_certificate(cert_pem)
    return cert.fingerprint(hashes.SHA256()).hex()
