"""Step-plan executor: the CP drives agentd through typed plans.

Parity reference: controlplane/agent/exec.go:212-340 (Executor + Step
plans) with **InitPlan** (init_steps.go:67 -- config, git, git-credentials,
ssh, post-init, AgentInitialized) and **BootPlan** (boot_steps.go:52 --
docker-socket, pre-run, AgentReady), each step dispatched as ShellCommand
pipelines over the Session stream with per-stage uid/gid drop.

This build keeps the same shape: a plan is an ordered list of ``Step``
values (pure data, independently testable); ``Executor.run_plan`` walks
them over one ``SessionClient``, stops on the first hard failure, and
reports per-step results.  Steps degrade loudly -- a missing optional tool
(e.g. git absent from a minimal image) is a *soft* skip only when the step
is marked ``best_effort``.
"""

from __future__ import annotations

import shlex
from dataclasses import dataclass, field

from .. import consts, logsetup
from .session_client import SessionClient, SessionError

log = logsetup.get("cp.executor")


@dataclass
class Step:
    """One shell-command step of a plan."""

    name: str
    stages: list[dict]                     # [{"argv": [...], "uid": N, "gid": N}]
    env: dict[str, str] = field(default_factory=dict)
    cwd: str = ""
    stdin: bytes | None = None
    timeout: float = 120.0
    best_effort: bool = False              # non-zero exit degrades, not aborts


@dataclass
class StepResult:
    name: str
    code: int
    stdout: bytes = b""
    stderr: bytes = b""
    skipped: bool = False

    @property
    def ok(self) -> bool:
        return self.skipped or self.code == 0


@dataclass
class PlanResult:
    plan: str
    steps: list[StepResult] = field(default_factory=list)
    aborted_at: str = ""

    @property
    def ok(self) -> bool:
        return not self.aborted_at


@dataclass
class AgentProfile:
    """Everything the plans need to know about one agent container.

    Built by the dialer from container labels + inspect output; plans are
    pure functions of this profile so they are testable without a daemon.
    """

    project: str
    agent: str
    uid: int = 0
    gid: int = 0
    workdir: str = "/workspace"
    cmd: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    git_user_name: str = ""
    git_user_email: str = ""
    post_init: str = ""                    # path of harness post-init script in image
    pre_run: str = ""                      # path of pre-run hook script
    docker_socket: bool = False            # docker.sock mounted -> fix group access
    host_proxy_url: str = ""               # http://<gw>:18374 when host proxy is on

    @property
    def full_name(self) -> str:
        return f"{self.project}.{self.agent}"


def _sh(script: str, *, uid: int = 0, gid: int = 0) -> list[dict]:
    return [{"argv": ["/bin/sh", "-c", script], "uid": uid, "gid": gid}]


def init_plan(p: AgentProfile) -> list[Step]:
    """The once-per-agent-container initialization plan.

    Parity: init_steps.go:67 ordering -- config, git, git-credentials, ssh,
    post-init.  AgentInitialized is sent by the executor's caller after the
    plan succeeds (it is a session verb, not a shell step).
    """
    steps: list[Step] = []
    steps.append(
        Step(
            name="config",
            stages=_sh(
                "mkdir -p /var/lib/clawker && "
                f"printf '%s\\n' {shlex.quote(p.full_name)} > /var/lib/clawker/agent-name"
            ),
        )
    )
    git_script = (
        f"command -v git >/dev/null 2>&1 || exit 0; "
        f"git config --global --add safe.directory {shlex.quote(p.workdir)}; "
        f"git config --global --add safe.directory '*'"
    )
    if p.git_user_name:
        git_script += f"; git config --global user.name {shlex.quote(p.git_user_name)}"
    if p.git_user_email:
        git_script += f"; git config --global user.email {shlex.quote(p.git_user_email)}"
    steps.append(
        Step(name="git", stages=_sh(git_script, uid=p.uid, gid=p.gid), best_effort=True)
    )
    if p.host_proxy_url:
        cred = (
            "command -v git >/dev/null 2>&1 || exit 0; "
            "git config --global credential.helper "
            f"{shlex.quote('!' + consts.GIT_CREDENTIAL_HELPER_PATH)}"
        )
        steps.append(
            Step(
                name="git-credentials",
                stages=_sh(cred, uid=p.uid, gid=p.gid),
                env={"CLAWKER_HOST_PROXY": p.host_proxy_url},
                best_effort=True,
            )
        )
    steps.append(
        Step(
            name="ssh",
            stages=_sh(
                "d=$(eval echo ~$(id -un)); mkdir -p \"$d/.ssh\" && chmod 700 \"$d/.ssh\"",
                uid=p.uid,
                gid=p.gid,
            ),
            best_effort=True,
        )
    )
    if p.post_init:
        steps.append(
            Step(
                name="post-init",
                stages=_sh(
                    f"[ -x {shlex.quote(p.post_init)} ] && {shlex.quote(p.post_init)} || exit 0",
                    uid=p.uid,
                    gid=p.gid,
                ),
                env=dict(p.env),
                cwd=p.workdir,
                timeout=600.0,
            )
        )
    return steps


def boot_plan(p: AgentProfile) -> list[Step]:
    """The every-container-start plan.  Parity: boot_steps.go:52 --
    docker-socket, pre-run; AgentReady is the session verb that follows."""
    steps: list[Step] = []
    if p.docker_socket:
        steps.append(
            Step(
                name="docker-socket",
                stages=_sh(
                    "[ -S /var/run/docker.sock ] || exit 0; "
                    f"chgrp {p.gid or 0} /var/run/docker.sock && "
                    "chmod g+rw /var/run/docker.sock",
                ),
                best_effort=True,
            )
        )
    if p.pre_run:
        steps.append(
            Step(
                name="pre-run",
                stages=_sh(
                    f"[ -x {shlex.quote(p.pre_run)} ] && {shlex.quote(p.pre_run)} || exit 0",
                    uid=p.uid,
                    gid=p.gid,
                ),
                env=dict(p.env),
                cwd=p.workdir,
                timeout=300.0,
            )
        )
    return steps


class Executor:
    """Runs plans over a live session, collecting per-step results."""

    def __init__(self, session: SessionClient, *, full_name: str = ""):
        self.session = session
        self.full_name = full_name

    def run_plan(self, plan_name: str, steps: list[Step]) -> PlanResult:
        result = PlanResult(plan=plan_name)
        for step in steps:
            try:
                shell = self.session.run_shell(
                    step.stages,
                    env=step.env,
                    cwd=step.cwd,
                    stdin=step.stdin,
                    timeout=step.timeout,
                )
            except SessionError as e:
                log.error(
                    "plan %s step %s transport failure for %s: %s",
                    plan_name, step.name, self.full_name, e,
                )
                result.steps.append(StepResult(name=step.name, code=-1, stderr=str(e).encode()))
                result.aborted_at = step.name
                return result
            sr = StepResult(
                name=step.name, code=shell.code, stdout=shell.stdout, stderr=shell.stderr
            )
            result.steps.append(sr)
            if shell.code != 0:
                if step.best_effort:
                    log.warning(
                        "plan %s step %s degraded (exit %d) for %s: %s",
                        plan_name, step.name, shell.code, self.full_name,
                        shell.stderr[-300:].decode(errors="replace"),
                    )
                    continue
                log.error(
                    "plan %s aborted at step %s (exit %d) for %s",
                    plan_name, step.name, shell.code, self.full_name,
                )
                result.aborted_at = step.name
                return result
        return result
