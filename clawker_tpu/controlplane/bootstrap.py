"""Pre/post-start service bootstrap hooks.

Parity reference: internal/cmd/container/shared/container_start.go --
BootstrapServicesPreStart (:103 -- CP EnsureRunning, firewall init+rules,
host proxy) and BootstrapServicesPostStart (:297 -- firewall enable on the
container's cgroup, socket bridge).  Round 1: gated no-ops that light up as
the subsystems land; the seam exists so the run path never changes shape.
"""

from __future__ import annotations

from ..config import Config
from ..engine.drivers import RuntimeDriver
from .. import logsetup

log = logsetup.get("cp.bootstrap")


def pre_start_services(cfg: Config, driver: RuntimeDriver, container_ref: str) -> None:
    if cfg.settings.control_plane.enable:
        from . import manager

        manager.ensure_running(cfg)
    if cfg.settings.firewall.enable:
        from ..firewall.lifecycle import firewall_pre_start

        firewall_pre_start(cfg, driver, container_ref)
    if cfg.settings.host_proxy.enable:
        from ..hostproxy.manager import ensure_running as hostproxy_ensure

        hostproxy_ensure(cfg)


def post_start_services(cfg: Config, driver: RuntimeDriver, container_ref: str) -> None:
    if cfg.settings.firewall.enable:
        from ..firewall.lifecycle import firewall_post_start

        firewall_post_start(cfg, driver, container_ref)
    _ensure_socket_bridge(cfg, driver, container_ref)


def _ensure_socket_bridge(cfg: Config, driver: RuntimeDriver, container_ref: str) -> None:
    """SSH/GPG agent forwarding (reference: container_start.go:349-371
    socketbridge EnsureBridge).  Best-effort: a missing host agent or a
    non-exec-capable engine degrades loudly, never fails the start.

    The manager lives ON the engine (not a module global) so it dies with
    the engine/factory; individual bridges self-close when their exec
    stream EOFs -- i.e. when the container stops."""
    try:
        from ..socketbridge.host import SocketBridgeManager

        engine = driver.engine()
        mgr = getattr(engine, "_socketbridge_manager", None)
        if mgr is None:
            mgr = SocketBridgeManager(engine)
            engine._socketbridge_manager = mgr
        mgr.ensure_bridge(container_ref)
    except Exception as e:
        log.warning("event=socketbridge_unavailable container=%s error=%s",
                    container_ref, e)
