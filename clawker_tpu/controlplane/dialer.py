"""CP agent dialer: container-start events -> mTLS session -> plans.

Parity reference: controlplane/agent/dialer.go -- the CP dials each agent
container's agentd outbound (DialAgent :211, retry/backoff :703-829),
reconciles on boot with DialAllRunning, and drives the session:
Hello -> [unregistered] RegisterRequired -> InitPlan (skipped when the
Hello carries Initialized) -> AgentInitialized -> BootPlan -> AgentReady
(skipped when CmdRunning).  Plans and registry state are updated as the
flow progresses so reconnects are idempotent.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Callable

from .. import consts, logsetup
from ..errors import ClawkerError
from .dockerevents import ContainerStateRepo, DockerEvent
from .executor import AgentProfile, Executor, boot_plan, init_plan
from .pubsub import Topic
from .registry import Registry
from .session_client import SessionError, dial_with_retry

log = logsetup.get("cp.dialer")

# (host, port) agentd endpoint for a container id
EndpointResolver = Callable[[str], tuple[str, int]]
ProfileBuilder = Callable[[str], AgentProfile]


def engine_endpoint_resolver(engine) -> EndpointResolver:
    """Default resolver: the container's bridge IP from daemon inspect."""

    def resolve(container_id: str) -> tuple[str, int]:
        info = engine.inspect_container(container_id)
        net = info.get("NetworkSettings") or {}
        ip = net.get("IPAddress") or ""
        if not ip:
            for settings in (net.get("Networks") or {}).values():
                ip = settings.get("IPAddress") or ""
                if ip:
                    break
        if not ip:
            raise ClawkerError(f"container {container_id[:12]}: no IP address")
        return ip, consts.AGENTD_PORT

    return resolve


def engine_profile_builder(engine) -> ProfileBuilder:
    """Default profile builder from container inspect: labels, user, cmd."""

    def build(container_id: str) -> AgentProfile:
        info = engine.inspect_container(container_id)
        cfg = info.get("Config") or {}
        labels = cfg.get("Labels") or {}
        user = str(cfg.get("User") or "")
        uid = gid = 0
        if user:
            parts = user.split(":")
            try:
                uid = int(parts[0])
                gid = int(parts[1]) if len(parts) > 1 else uid
            except ValueError:
                pass  # named user: agentd resolves at spawn; plans run as root
        mounts = info.get("Mounts") or []
        docker_socket = any(m.get("Destination") == "/var/run/docker.sock" for m in mounts)
        return AgentProfile(
            project=labels.get(consts.LABEL_PROJECT, ""),
            agent=labels.get(consts.LABEL_AGENT, ""),
            uid=uid,
            gid=gid,
            workdir=cfg.get("WorkingDir") or consts.WORKSPACE_DIR,
            cmd=list(cfg.get("Cmd") or []),
            env={},
            docker_socket=docker_socket,
        )

    return build


@dataclass
class DialerConfig:
    cert_file: Path                 # CP client identity for the agentd session
    key_file: Path
    ca_file: Path
    cp_host: str = ""               # where agentd should Register back to
    cp_agent_port: int = consts.CP_AGENT_PORT
    dial_deadline_s: float = 30.0


class Dialer:
    """Watches container starts and drives each agent's session to ready."""

    def __init__(
        self,
        cfg: DialerConfig,
        registry: Registry,
        resolve: EndpointResolver,
        build_profile: ProfileBuilder,
    ):
        self.cfg = cfg
        self.registry = registry
        self.resolve = resolve
        self.build_profile = build_profile
        self._stop = threading.Event()
        self._consumer: threading.Thread | None = None
        self._workers: dict[str, threading.Thread] = {}   # live dials only
        self._inflight: set[str] = set()
        self._lock = threading.Lock()
        # observable results for tests/status: full_name -> outcome string
        self.outcomes: dict[str, str] = {}

    # ------------------------------------------------------------ lifecycle

    def start(self, topic: Topic[DockerEvent], repo: ContainerStateRepo) -> None:
        """Subscribe to start events and reconcile already-running agents."""
        sub = topic.subscribe("dialer")
        self._consumer = threading.Thread(
            target=self._consume, args=(sub,), name="dialer-events", daemon=True
        )
        self._consumer.start()
        for state in repo.running():
            if state.role == "agent":
                self._spawn_drive(state.container_id)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._lock:
            workers = list(self._workers.values())
        for t in workers:
            t.join(timeout)
        if self._consumer is not None:
            self._consumer.join(timeout)

    def _consume(self, sub) -> None:
        for ev in sub:
            if self._stop.is_set():
                return
            payload: DockerEvent = ev.payload
            if payload.action == "start" and payload.role == "agent":
                self._spawn_drive(payload.container_id)

    def _spawn_drive(self, container_id: str) -> None:
        t = threading.Thread(
            target=self._drive_recovered, args=(container_id,),
            name=f"dial-{container_id[:12]}", daemon=True,
        )
        with self._lock:
            if container_id in self._inflight:
                return
            self._inflight.add(container_id)
            self._workers[container_id] = t
        t.start()

    def _drive_recovered(self, container_id: str) -> None:
        # every dial worker is exception-recovered: a bad agent container
        # must never take the CP down (reference: "CP crashing is a SECURITY
        # incident", root CLAUDE.md; recoverGoroutine discipline)
        try:
            self.drive(container_id)
        except Exception as e:
            log.error("dial %s failed: %s", container_id[:12], e)
        finally:
            with self._lock:
                self._inflight.discard(container_id)
                self._workers.pop(container_id, None)

    # ------------------------------------------------------------ the flow

    def drive(self, container_id: str) -> str:
        """Run one container's session flow to ready; returns outcome."""
        profile = self.build_profile(container_id)
        full = profile.full_name
        if not profile.project:
            self.outcomes[container_id] = "unmanaged"
            return "unmanaged"
        host, port = self.resolve(container_id)
        record = self.registry.get(full)
        session = dial_with_retry(
            host,
            port,
            cert_file=self.cfg.cert_file,
            key_file=self.cfg.key_file,
            ca_file=self.cfg.ca_file,
            deadline_s=self.cfg.dial_deadline_s,
        )
        try:
            outcome = self._run_session(session, profile, record)
        except SessionError as e:
            self.registry.set_state(full, "error")
            self.outcomes[full] = f"error: {e}"
            raise
        finally:
            session.close()
        self.outcomes[full] = outcome
        return outcome

    def _run_session(self, session, profile: AgentProfile, record) -> str:
        full = profile.full_name
        hello = session.hello()
        registered = bool(record and record.registered_at)
        if not registered and self.cfg.cp_host:
            session.register_required(self.cfg.cp_host, self.cfg.cp_agent_port)
            log.info("agent %s registered", full)
        initialized = hello.initialized or bool(record and record.initialized)
        executor = Executor(session, full_name=full)
        if not initialized:
            res = executor.run_plan("init", init_plan(profile))
            if not res.ok:
                self.registry.set_state(full, "init-failed")
                return f"init-failed:{res.aborted_at}"
            session.agent_initialized()
            self.registry.mark_initialized(full)
            log.info("agent %s initialized", full)
        if not hello.cmd_running:
            res = executor.run_plan("boot", boot_plan(profile))
            if not res.ok:
                self.registry.set_state(full, "boot-failed")
                return f"boot-failed:{res.aborted_at}"
            pid = session.agent_ready(
                profile.cmd, uid=profile.uid, gid=profile.gid,
                env=profile.env, cwd=profile.workdir,
            )
            log.info("agent %s ready (pid %d)", full, pid)
        self.registry.set_state(full, "ready")
        return "ready"
