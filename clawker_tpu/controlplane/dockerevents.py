"""Reconnecting daemon-event feeder -> typed topic + container state repo.

Parity reference: controlplane/dockerevents (SURVEY.md 2.7) -- a
reconnecting ``Feeder`` turns the Docker events stream into a typed
``DockerEvent`` topic, and a container state repo reconciles against
``container_list`` on every (re)connect so subscribers observing through a
disconnect converge to daemon truth instead of missing transitions.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .. import consts, logsetup
from .pubsub import Topic

log = logsetup.get("cp.dockerevents")

# Daemon actions worth broadcasting; everything else is noise for the CP.
_CONTAINER_ACTIONS = {
    "create", "start", "die", "stop", "kill", "destroy", "pause", "unpause",
    "rename", "restart", "oom", "health_status",
}


@dataclass
class DockerEvent:
    """One normalized daemon event for a managed container."""

    action: str
    container_id: str
    name: str = ""
    project: str = ""
    agent: str = ""
    role: str = ""
    exit_code: int | None = None
    ts: float = field(default_factory=time.time)
    attributes: dict = field(default_factory=dict)

    @property
    def full_name(self) -> str:
        return f"{self.project}.{self.agent}" if self.project else self.name


def _normalize(raw: dict) -> DockerEvent | None:
    if raw.get("Type") != "container":
        return None
    action = str(raw.get("Action", ""))
    # health_status events arrive as "health_status: healthy"
    base_action = action.split(":", 1)[0].strip()
    if base_action not in _CONTAINER_ACTIONS:
        return None
    actor = raw.get("Actor") or {}
    attrs = dict(actor.get("Attributes") or {})
    ev = DockerEvent(
        action=base_action,
        container_id=str(actor.get("ID") or raw.get("id") or ""),
        name=attrs.get("name", ""),
        project=attrs.get(consts.LABEL_PROJECT, ""),
        agent=attrs.get(consts.LABEL_AGENT, ""),
        role=attrs.get(consts.LABEL_ROLE, ""),
        attributes=attrs,
    )
    if "exitCode" in attrs:
        try:
            ev.exit_code = int(attrs["exitCode"])
        except ValueError:
            pass
    if raw.get("time"):
        ev.ts = float(raw["time"])
    return ev


@dataclass
class ContainerState:
    """Last known state of one managed container."""

    container_id: str
    name: str
    project: str
    agent: str
    role: str
    running: bool
    labels: dict = field(default_factory=dict)


class ContainerStateRepo:
    """Event-driven mirror of managed-container state, reconciled on connect."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_id: dict[str, ContainerState] = {}

    def reconcile(self, summaries: list[dict]) -> None:
        with self._lock:
            self._by_id.clear()
            for s in summaries:
                labels = s.get("Labels") or {}
                names = s.get("Names") or [""]
                name = names[0].lstrip("/")
                self._by_id[s["Id"]] = ContainerState(
                    container_id=s["Id"],
                    name=name,
                    project=labels.get(consts.LABEL_PROJECT, ""),
                    agent=labels.get(consts.LABEL_AGENT, ""),
                    role=labels.get(consts.LABEL_ROLE, ""),
                    running=s.get("State") == "running",
                    labels=labels,
                )

    def apply(self, ev: DockerEvent) -> None:
        with self._lock:
            if ev.action == "destroy":
                self._by_id.pop(ev.container_id, None)
                return
            st = self._by_id.get(ev.container_id)
            if st is None:
                st = ContainerState(
                    container_id=ev.container_id,
                    name=ev.name,
                    project=ev.project,
                    agent=ev.agent,
                    role=ev.role,
                    running=False,
                    labels=dict(ev.attributes),
                )
                self._by_id[ev.container_id] = st
            if ev.action in ("start", "restart", "unpause"):
                st.running = True
            elif ev.action in ("die", "stop", "kill", "pause", "oom"):
                st.running = False
            if ev.action == "rename" and ev.name:
                st.name = ev.name

    def running(self) -> list[ContainerState]:
        with self._lock:
            return [s for s in self._by_id.values() if s.running]

    def get(self, container_id: str) -> ContainerState | None:
        with self._lock:
            return self._by_id.get(container_id)

    def all(self) -> list[ContainerState]:
        with self._lock:
            return list(self._by_id.values())


class Feeder:
    """Streams daemon events into a topic, reconnecting with backoff.

    On every (re)connect the state repo is reconciled from a full
    ``container_list`` before events flow, closing the blind window
    (reference: dockerevents reconcile-on-reconnect).
    """

    def __init__(
        self,
        engine,
        topic: Topic[DockerEvent],
        repo: ContainerStateRepo | None = None,
        *,
        backoff_s: float = 1.0,
        max_backoff_s: float = 30.0,
    ):
        self.engine = engine
        self.topic = topic
        self.repo = repo or ContainerStateRepo()
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.reconnects = 0

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="dockerevents", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        # The events iterator may be blocked on the daemon; fakes unblock on
        # close, HTTP streams unblock on socket close via engine.close hooks.
        closer = getattr(self.engine.api, "close_events", None)
        if closer:
            closer()
        if self._thread is not None:
            self._thread.join(timeout)

    def _run(self) -> None:
        delay = self.backoff_s
        while not self._stop.is_set():
            try:
                # Subscribe first so events raised during the reconcile list
                # are buffered, not lost: no blind window between snapshot
                # and stream.
                stream = self.engine.events()
                self.repo.reconcile(self.engine.list_containers(all=True))
                delay = self.backoff_s  # healthy connect resets backoff
                for raw in stream:
                    if self._stop.is_set():
                        return
                    ev = _normalize(raw)
                    if ev is None:
                        continue
                    self.repo.apply(ev)
                    self.topic.publish(ev)
            except Exception as e:
                if self._stop.is_set():
                    return
                log.warning("event stream lost (%s); reconnecting in %.1fs", e, delay)
            if self._stop.is_set():
                return
            self.reconnects += 1
            self._stop.wait(delay)
            delay = min(delay * 2, self.max_backoff_s)
