"""In-process typed pub/sub bus for control-plane subsystems.

Parity reference: controlplane/pubsub (SURVEY.md 2.7) -- generic
``Topic[T]``/``Event[T]`` with non-blocking publish, per-subscriber bounded
buffer with drop-oldest overflow, and panic-recovered delivery; zero domain
knowledge.  The Python build keeps the same contract with a lock +
per-subscription deque: ``publish`` never blocks and never raises, slow
subscribers lose their *oldest* events first (and the loss is counted), and
a subscriber that dies mid-iteration never poisons the topic.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Generic, Iterator, TypeVar

T = TypeVar("T")

DEFAULT_BUFFER = 256


@dataclass
class Event(Generic[T]):
    """One published event: payload + publish-time metadata."""

    payload: T
    seq: int = 0
    ts: float = field(default_factory=time.time)


class Subscription(Generic[T]):
    """A bounded mailbox attached to a topic.

    Iterate to consume (blocks until an event or :meth:`close`); ``dropped``
    counts events lost to overflow.  Closing is idempotent and detaches from
    the topic.
    """

    def __init__(self, topic: "Topic[T]", name: str, buffer: int):
        self._topic = topic
        self.name = name
        self._buf: deque[Event[T]] = deque(maxlen=max(1, buffer))
        self._cond = threading.Condition()
        self._closed = False
        self.dropped = 0

    # Called by the topic with its own lock held only briefly; never blocks.
    def _offer(self, ev: Event[T]) -> None:
        with self._cond:
            if self._closed:
                return
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(ev)
            self._cond.notify()

    def get(self, timeout: float | None = None) -> Event[T] | None:
        """Next event, or None on close/timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._buf:
                if self._closed:
                    return None
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            return self._buf.popleft()

    def __iter__(self) -> Iterator[Event[T]]:
        while True:
            ev = self.get()
            if ev is None:
                return
            yield ev

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._topic._detach(self)

    @property
    def closed(self) -> bool:
        return self._closed


class Topic(Generic[T]):
    """Typed broadcast topic.

    ``publish`` fans out to every live subscription without blocking or
    raising; a full mailbox drops its oldest event (slow consumers degrade
    themselves, never the publisher -- the CP resilience contract).
    """

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._subs: list[Subscription[T]] = []
        self._seq = 0
        self._closed = False

    def publish(self, payload: T) -> None:
        # Fan-out happens under the topic lock so concurrent publishers
        # cannot interleave out of seq order in a mailbox; _offer never
        # blocks (bounded deque, drop-oldest), so the lock hold is O(subs).
        with self._lock:
            if self._closed:
                return
            self._seq += 1
            ev = Event(payload=payload, seq=self._seq)
            for sub in self._subs:
                try:
                    sub._offer(ev)
                except Exception:  # delivery must never take down the publisher
                    pass

    def subscribe(self, name: str = "", *, buffer: int = DEFAULT_BUFFER) -> Subscription[T]:
        sub = Subscription(self, name or f"{self.name}-sub", buffer)
        with self._lock:
            if self._closed:
                sub._closed = True
                return sub
            self._subs.append(sub)
        return sub

    def _detach(self, sub: Subscription[T]) -> None:
        with self._lock:
            try:
                self._subs.remove(sub)
            except ValueError:
                pass

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def close(self) -> None:
        """Close the topic and every subscription (drain shutdown step)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            subs = list(self._subs)
            self._subs.clear()
        for sub in subs:
            with sub._cond:
                sub._closed = True
                sub._cond.notify_all()


def run_subscriber(
    sub: Subscription[T], handler, *, name: str = "", daemon: bool = True
) -> threading.Thread:
    """Spawn a recovered delivery thread: handler exceptions are logged and
    swallowed per-event (reference: pubsub panic-recovered delivery)."""
    from .. import logsetup

    log = logsetup.get("cp.pubsub")

    def loop() -> None:
        for ev in sub:
            try:
                handler(ev)
            except Exception:
                log.exception("subscriber %s: handler error (event dropped)", sub.name)

    t = threading.Thread(target=loop, name=name or f"sub-{sub.name}", daemon=daemon)
    t.start()
    return t
