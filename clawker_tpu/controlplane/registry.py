"""SQLite agent registry: the CP's durable identity <-> container binding.

Parity reference: controlplane/agent/registry_sqlite.go (SURVEY.md 2.7) --
the CP is the *sole writer* (WAL coherence on bind mounts is why the
reference centralizes writes); rows bind agent full-name to container id and
cert thumbprint, and persist the initialized marker so reconnects skip the
InitPlan.
"""

from __future__ import annotations

import sqlite3
import threading
import time
from dataclasses import dataclass
from pathlib import Path

_SCHEMA = """
CREATE TABLE IF NOT EXISTS agents (
    full_name     TEXT PRIMARY KEY,
    project       TEXT NOT NULL,
    agent         TEXT NOT NULL,
    container_id  TEXT NOT NULL DEFAULT '',
    cert_sha256   TEXT NOT NULL DEFAULT '',
    worker        TEXT NOT NULL DEFAULT '',
    state         TEXT NOT NULL DEFAULT 'created',
    initialized   INTEGER NOT NULL DEFAULT 0,
    registered_at REAL NOT NULL DEFAULT 0,
    last_seen     REAL NOT NULL DEFAULT 0
);
CREATE INDEX IF NOT EXISTS agents_project ON agents(project);
"""


@dataclass
class AgentRecord:
    full_name: str
    project: str
    agent: str
    container_id: str = ""
    cert_sha256: str = ""
    worker: str = ""
    state: str = "created"
    initialized: bool = False
    registered_at: float = 0.0
    last_seen: float = 0.0


def _row_to_record(row: sqlite3.Row) -> AgentRecord:
    return AgentRecord(
        full_name=row["full_name"],
        project=row["project"],
        agent=row["agent"],
        container_id=row["container_id"],
        cert_sha256=row["cert_sha256"],
        worker=row["worker"],
        state=row["state"],
        initialized=bool(row["initialized"]),
        registered_at=row["registered_at"],
        last_seen=row["last_seen"],
    )


class Registry:
    """Thread-safe single-writer registry over one sqlite file."""

    def __init__(self, path: Path | str):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._db = sqlite3.connect(str(self.path), check_same_thread=False)
        self._db.row_factory = sqlite3.Row
        with self._lock:
            self._db.execute("PRAGMA journal_mode=WAL")
            self._db.executescript(_SCHEMA)
            self._db.commit()

    def close(self) -> None:
        with self._lock:
            self._db.close()

    # ------------------------------------------------------------- writes

    def bind(
        self,
        full_name: str,
        project: str,
        agent: str,
        *,
        container_id: str,
        cert_sha256: str,
        worker: str = "",
    ) -> None:
        """Create-or-rebind a row at container-create time.  Rebinding (new
        container for a known agent) resets registration but keeps the
        initialized marker only if the container is unchanged."""
        with self._lock:
            prev = self._db.execute(
                "SELECT container_id, initialized FROM agents WHERE full_name=?",
                (full_name,),
            ).fetchone()
            keep_init = bool(prev and prev["container_id"] == container_id and prev["initialized"])
            self._db.execute(
                """INSERT INTO agents
                   (full_name, project, agent, container_id, cert_sha256, worker,
                    state, initialized, registered_at, last_seen)
                   VALUES (?,?,?,?,?,?, 'created', ?, 0, ?)
                   ON CONFLICT(full_name) DO UPDATE SET
                     container_id=excluded.container_id,
                     cert_sha256=excluded.cert_sha256,
                     worker=excluded.worker,
                     state='created',
                     initialized=excluded.initialized,
                     registered_at=0,
                     last_seen=excluded.last_seen""",
                (full_name, project, agent, container_id, cert_sha256, worker,
                 int(keep_init), time.time()),
            )
            self._db.commit()

    def mark_registered(self, full_name: str, cert_sha256: str) -> bool:
        """Record a successful Register call iff the thumbprint matches the
        bound material (identity binding; reference: Register handler)."""
        with self._lock:
            cur = self._db.execute(
                "UPDATE agents SET registered_at=?, last_seen=?, state='registered' "
                "WHERE full_name=? AND cert_sha256=?",
                (time.time(), time.time(), full_name, cert_sha256),
            )
            self._db.commit()
            return cur.rowcount == 1

    def mark_initialized(self, full_name: str) -> None:
        with self._lock:
            self._db.execute(
                "UPDATE agents SET initialized=1, last_seen=? WHERE full_name=?",
                (time.time(), full_name),
            )
            self._db.commit()

    def set_state(self, full_name: str, state: str) -> None:
        with self._lock:
            self._db.execute(
                "UPDATE agents SET state=?, last_seen=? WHERE full_name=?",
                (state, time.time(), full_name),
            )
            self._db.commit()

    def touch(self, full_name: str) -> None:
        with self._lock:
            self._db.execute(
                "UPDATE agents SET last_seen=? WHERE full_name=?", (time.time(), full_name)
            )
            self._db.commit()

    def remove(self, full_name: str) -> None:
        with self._lock:
            self._db.execute("DELETE FROM agents WHERE full_name=?", (full_name,))
            self._db.commit()

    # -------------------------------------------------------------- reads

    def get(self, full_name: str) -> AgentRecord | None:
        with self._lock:
            row = self._db.execute(
                "SELECT * FROM agents WHERE full_name=?", (full_name,)
            ).fetchone()
        return _row_to_record(row) if row else None

    def list(self, project: str | None = None) -> list[AgentRecord]:
        with self._lock:
            if project:
                rows = self._db.execute(
                    "SELECT * FROM agents WHERE project=? ORDER BY full_name", (project,)
                ).fetchall()
            else:
                rows = self._db.execute("SELECT * FROM agents ORDER BY full_name").fetchall()
        return [_row_to_record(r) for r in rows]

    def by_container(self, container_id: str) -> AgentRecord | None:
        with self._lock:
            row = self._db.execute(
                "SELECT * FROM agents WHERE container_id=?", (container_id,)
            ).fetchone()
        return _row_to_record(row) if row else None
