"""AgentWatcher: periodic daemon polls + drain-to-zero self-shutdown.

Parity reference: controlplane/agent/watcher.go -- 30s polls of managed
agent containers, a ``ListErrCeiling`` bound on how long the CP tolerates a
wedged daemon blinding it, and drain-to-zero: when no agent containers
remain for a full grace window the CP triggers its own drain sequence
(the CP container has no reason to outlive its last agent).
"""

from __future__ import annotations

import threading
from typing import Callable

from .. import consts, logsetup

log = logsetup.get("cp.watcher")

LIST_ERR_CEILING = 5


class AgentWatcher:
    def __init__(
        self,
        engine,
        *,
        interval_s: float = 30.0,
        drain_grace_polls: int = 2,
        on_drained: Callable[[], None] | None = None,
        on_blind: Callable[[], None] | None = None,
    ):
        self.engine = engine
        self.interval_s = interval_s
        self.drain_grace_polls = drain_grace_polls
        self.on_drained = on_drained
        self.on_blind = on_blind
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.polls = 0
        self.consecutive_errors = 0
        self.last_count = -1
        self._zero_streak = 0
        # drain-to-zero only arms after at least one agent has been seen:
        # a CP brought up ahead of a slow image pull must not self-terminate
        # before its first agent ever starts
        self._armed = False

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="agentwatcher", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def poll_once(self) -> int:
        """One poll; returns live agent count (or -1 on list failure)."""
        self.polls += 1
        try:
            containers = self.engine.list_containers(
                filters={"label": [f"{consts.LABEL_ROLE}=agent"]}
            )
        except Exception as e:
            self.consecutive_errors += 1
            log.warning(
                "agent list failed (%d/%d): %s",
                self.consecutive_errors, LIST_ERR_CEILING, e,
            )
            if self.consecutive_errors >= LIST_ERR_CEILING and self.on_blind:
                self.on_blind()
            return -1
        self.consecutive_errors = 0
        count = len(containers)
        self.last_count = count
        if count == 0:
            self._zero_streak += 1
            if self._armed and self._zero_streak >= self.drain_grace_polls and self.on_drained:
                log.info("drain-to-zero: no agents for %d polls", self._zero_streak)
                self.on_drained()
        else:
            self._armed = True
            self._zero_streak = 0
        return count

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:
                log.error("watcher poll crashed: %s", e)
            self._stop.wait(self.interval_s)
