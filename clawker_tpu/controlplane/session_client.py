"""CP-side session client: dial agentd, run plans, stream shell output.

Parity reference: controlplane/agent/dialer.go (DialAgent :211) and
exec.go Step plans -- the CP is the dialing side of the CP->agentd mTLS
session; the client cert is the CP identity, server verification is
CA-grounded but hostname-free (containers are dialed by IP; the reference
uses permissive trust with thumbprint classification, dialer.go:123).
"""

from __future__ import annotations

import socket
import ssl
import time
from dataclasses import dataclass, field
from pathlib import Path

from .. import consts, logsetup
from ..agentd.protocol import ConnectionClosed, read_msg, unb64, write_msg
from ..errors import ClawkerError

log = logsetup.get("cp.session")


class SessionError(ClawkerError):
    pass


@dataclass
class ShellResult:
    code: int
    stdout: bytes = b""
    stderr: bytes = b""
    stage_codes: list[int] = field(default_factory=list)


@dataclass
class Hello:
    initialized: bool
    cmd_running: bool
    pid: int = 0


class SessionClient:
    """One mTLS session to one agentd.  Not thread-safe; the executor owns it."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        cert_file: Path,
        key_file: Path,
        ca_file: Path,
        timeout: float = 10.0,
    ):
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_3
        ctx.load_cert_chain(cert_file, key_file)
        ctx.load_verify_locations(ca_file)
        ctx.check_hostname = False          # dialed by IP; CA signature grounds trust
        ctx.verify_mode = ssl.CERT_REQUIRED
        raw = socket.create_connection((host, port), timeout=timeout)
        self._sock = ctx.wrap_socket(raw, server_hostname=host)
        self._seq = 0

    def close(self) -> None:
        try:
            write_msg(self._sock, {"type": "bye"})
        except (OSError, ClawkerError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- verbs

    def hello(self) -> Hello:
        write_msg(self._sock, {"type": "hello"})
        ack = read_msg(self._sock)
        if ack.get("type") != "hello_ack":
            raise SessionError(f"expected hello_ack, got {ack.get('type')}")
        return Hello(
            initialized=bool(ack.get("initialized")),
            cmd_running=bool(ack.get("cmd_running")),
            pid=int(ack.get("pid") or 0),
        )

    def run_shell(
        self,
        stages: list[dict],
        *,
        env: dict[str, str] | None = None,
        cwd: str = "",
        stdin: bytes | None = None,
        timeout: float = 120.0,
    ) -> ShellResult:
        """Run a pipeline to completion, collecting output.

        ``stages`` = [{"argv": [...], "uid": 0, "gid": 0}, ...].
        """
        self._seq += 1
        job_id = f"s{self._seq}"
        write_msg(
            self._sock,
            {"type": "shell", "id": job_id, "stages": stages, "env": env or {}, "dir": cwd},
        )
        prev_timeout = self._sock.gettimeout()
        res = ShellResult(code=-1)
        started = False
        deadline = time.monotonic() + timeout
        try:
            return self._collect_shell(job_id, res, started, deadline, stdin)
        finally:
            self._sock.settimeout(prev_timeout)

    def _collect_shell(self, job_id, res, started, deadline, stdin) -> ShellResult:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise SessionError(f"shell {job_id}: timeout")
            self._sock.settimeout(remaining)
            msg = read_msg(self._sock)
            t = msg.get("type")
            if t == "started" and msg.get("id") == job_id:
                started = True
                if stdin is not None:
                    from ..agentd.protocol import b64

                    write_msg(self._sock, {"type": "stdin", "id": job_id, "data": b64(stdin)})
                    write_msg(self._sock, {"type": "close_stdin", "id": job_id})
            elif t == "output" and msg.get("id") == job_id:
                data = unb64(msg.get("data", ""))
                if msg.get("fd") == 2:
                    res.stderr += data
                else:
                    res.stdout += data
            elif t == "stage_exit" and msg.get("id") == job_id:
                res.stage_codes.append(int(msg.get("code") or 0))
            elif t == "done" and msg.get("id") == job_id:
                res.code = int(msg.get("code") or 0)
                return res
            elif t == "error":
                raise SessionError(f"shell {job_id}: {msg.get('error')} (started={started})")
            # unrelated frames (other jobs' output) are skipped

    def agent_ready(
        self,
        argv: list[str],
        *,
        uid: int = 0,
        gid: int = 0,
        env: dict[str, str] | None = None,
        cwd: str = "",
    ) -> int:
        write_msg(
            self._sock,
            {
                "type": "agent_ready",
                "argv": argv,
                "uid": uid,
                "gid": gid,
                "env": env or {},
                "cwd": cwd,
            },
        )
        ack = read_msg(self._sock)
        if ack.get("type") != "ready_ack":
            raise SessionError(f"agent_ready failed: {ack.get('error', ack)}")
        return int(ack.get("pid") or 0)

    def agent_initialized(self) -> None:
        write_msg(self._sock, {"type": "agent_initialized"})
        ack = read_msg(self._sock)
        if ack.get("type") != "init_ack":
            raise SessionError(f"agent_initialized failed: {ack.get('error', ack)}")

    def register_required(self, cp_host: str, cp_port: int) -> None:
        write_msg(
            self._sock,
            {"type": "register_required", "cp_host": cp_host, "cp_port": cp_port},
        )
        ack = read_msg(self._sock)
        if ack.get("type") != "register_done" or not ack.get("ok"):
            raise SessionError(f"register failed: {ack.get('error', ack)}")


def dial_with_retry(
    host: str,
    port: int,
    *,
    cert_file: Path,
    key_file: Path,
    ca_file: Path,
    deadline_s: float = 30.0,
    base_delay_s: float = 0.2,
) -> SessionClient:
    """Dial with capped exponential backoff (reference: dialer.go:703-829
    retry/backoff with deadline)."""
    deadline = time.monotonic() + deadline_s
    delay = base_delay_s
    last: Exception | None = None
    while time.monotonic() < deadline:
        try:
            return SessionClient(
                host, port, cert_file=cert_file, key_file=key_file, ca_file=ca_file
            )
        except (OSError, ssl.SSLError, ConnectionClosed) as e:
            last = e
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 2, 5.0)
    raise SessionError(f"dial {host}:{port} failed within {deadline_s}s: {last}")
