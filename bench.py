"""Benchmark: p50 agent-container cold-start orchestration overhead.

BASELINE.md's headline target is p50 container cold-start < 10 s on a TPU-VM
worker.  Total cold start = framework orchestration (this bench: config
load, image resolve, volume ensure, mount assembly, create, bootstrap,
start) + daemon-side work (image present: ~1-2 s).  Without a Docker daemon
in the bench environment the daemon side is served by the in-process fake,
so this measures the framework's contribution -- the part this codebase
controls -- end to end through the real `clawker run` CLI path.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = (10 s budget) / (measured p50): >1 means within budget,
bigger is better.
"""

from __future__ import annotations

import json
import statistics
import time


def bench_cold_start(iters: int = 40) -> float:
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.testenv import TestEnv

    samples: list[float] = []
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        tenv.make_project(proj, "project: bench\n")
        runner = CliRunner()
        for i in range(iters):
            driver = FakeDriver()
            driver.api.add_image("clawker-bench:default")
            factory = Factory(cwd=proj, driver=driver)
            t0 = time.perf_counter()
            res = runner.invoke(
                cli,
                ["run", "--detach", "--agent", f"a{i}", "--workspace", "snapshot"],
                obj=factory,
                catch_exceptions=False,
            )
            dt = time.perf_counter() - t0
            assert res.exit_code == 0, res.output
            samples.append(dt)
    return statistics.median(samples)


def main() -> None:
    p50_s = bench_cold_start()
    budget_s = 10.0
    print(
        json.dumps(
            {
                "metric": "agent_cold_start_framework_p50",
                "value": round(p50_s * 1000, 2),
                "unit": "ms",
                "vs_baseline": round(budget_s / p50_s, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
