"""Benchmark suite: one JSON line, five metrics against BASELINE configs.

Headline (unchanged): p50 agent-container cold-start orchestration
overhead through the real `clawker run` CLI path over the in-process
fake daemon (BASELINE config #1: <10 s budget on a TPU-VM worker; this
measures the framework's contribution).

Added (round-4 verdict task #4), in ``extra``:
- firewall_parity_pass_rate -- the 22-scenario e2e scorecard + the
  30-technique capture-graded adversarial corpus (BASELINE config #3:
  reference bar = all-pass); vs_baseline 1.0 == full parity.
- parity_suite_wall -- wall seconds for the full 52-surface run over
  real sockets (budget 120 s).
- policy_oracle_decisions_per_s -- kernel-twin connect4 verdict
  throughput, the CP-side cost ceiling for route/dns churn (budget
  10k/s).
- dnsgate_qps -- real UDP round-trips against the live gate socket
  (budget 1k qps).
- loop_fanout_p50 -- `loop --parallel 8` scheduling latency: start()
  until all 8 loops are created+started across an 8-worker fake pod
  (BASELINE config #4; budget 10 s).

Added (parallel control plane PR):
- loop_poll_cost_n8 -- control-plane round-trips per agent iteration
  while a fanned-out loop runs (batched list + wait threads vs the old
  one-inspect-per-agent-per-tick; budget 12 calls/iteration).
- fleet_provision_wall_n8 -- wall seconds to provision an 8-worker pod
  over FakeRunner transports with an injected per-call delay standing
  in for SSH RTT; vs_baseline is the speedup over the serial,
  tar-per-worker path (bar: >= 2x).

Added (connection-pool PR):
- engine_dials_per_run -- socket dials behind one `clawker run`
  orchestration's unary daemon calls, replayed over a real unix socket
  with an injected per-dial delay (forwarded-stream setup on the SSH
  mux); vs_baseline is the dial reduction over the dial-per-request
  client (bar: >= 2x).

Added (health & failover PR):
- failover_detect_to_restart_s -- kill one fake worker mid-loop under
  `--failover migrate`; wall seconds from the death to the first
  migrated agent's next iteration start, with every loop still
  reaching its budget (bar: 5 s -- recovery must undercut the 10 s
  cold-start budget or failover is pointless).

Added (telemetry PR):
- telemetry_overhead_ns -- per-record cost of the metrics registry
  (counter inc + histogram observe, hot label-set), enabled vs
  disabled.  Telemetry is on by default in the loop scheduler and the
  engine client, so this is the per-call tax every instrumented hot
  path pays; the smoke gate keeps it bounded so instrumentation can
  never silently regress the cold-start headline.

Added (distributed tracing PR):
- tracing_overhead_ns -- per-span propagate+record cost: parse the
  inbound traceparent, mint a child, serialize the outbound header,
  and record one SpanRecord through the sink into a real flight
  recorder (append + flush).  Gated alongside telemetry_overhead_ns.
- trace_merge_wall_n256 -- wall to merge 256 agents x 4 recorder
  processes (router/loopd/scheduler/workerd) into the causal forest
  `clawker trace` renders, skew adjustment and gap audit included.

Added (run journal / resume PR):
- resume_reattach_wall_n8 -- kill the scheduler of a running
  8-loop/4-worker fake pod mid-wait, then measure the `--resume`
  invocation (journal replay + reconcile) until all 8 loops are live
  again via container ADOPTION; vs_baseline is the speedup over the
  cold fan-out the resume avoided (adoption makes zero engine
  mutations, so it must beat re-creating 8 containers).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"extra": [...]}.  vs_baseline > 1 (or == 1.0 for pass rates) means
within budget; bigger is better.
"""

from __future__ import annotations

import json
import statistics
import tempfile
import time
from pathlib import Path


def bench_cold_start(iters: int = 40) -> tuple[float, dict[str, float], dict]:
    """-> (p50 seconds, mean per-stage milliseconds, identity split).

    Stages come from the in-tree phase stopwatch (util/phases) wired
    through factory config load and the orchestrator's create/start
    path, so the breakdown attributes the SAME run the headline times.

    The identity split reports the CA session cache's effect on the
    ``identity_bootstrap`` stage (BENCH_r05: 7.0ms, 78% of framework
    cold start): each agent name runs once COLD (leaf minted) and once
    WARM (leaf reused from the session cache -- the loop-restart /
    migration / resume shape), with the per-create stage cost for both.
    """
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.testenv import TestEnv
    from clawker_tpu.util import phases

    samples: list[float] = []
    identity: dict = {}
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        tenv.make_project(proj, "project: bench\n")
        runner = CliRunner()

        def one_run(i: int, agent: str) -> float:
            driver = FakeDriver()
            driver.api.add_image("clawker-bench:default")
            factory = Factory(cwd=proj, driver=driver)
            t0 = time.perf_counter()
            res = runner.invoke(
                cli,
                ["run", "--detach", "--agent", agent, "--workspace", "snapshot"],
                obj=factory,
                catch_exceptions=False,
            )
            dt = time.perf_counter() - t0
            assert res.exit_code == 0, res.output
            return dt

        phases.enable()
        for i in range(iters):
            samples.append(one_run(i, f"a{i}"))
        stage_totals = phases.disable()
        stage_counts = phases.counts()

        # warm-placement leg: the SAME agent names re-created -- their
        # leaves are session-cached, so identity_bootstrap pays only the
        # assertion JWT + install (docs/loop-placement.md satellite)
        phases.enable()
        for i in range(iters):
            one_run(i, f"a{i}")
        warm_totals = phases.disable()
        warm_counts = phases.counts()
        identity = {
            "cold_identity_bootstrap_ms": round(
                stage_totals.get("identity_bootstrap", 0.0) * 1000 / iters, 3),
            "warm_identity_bootstrap_ms": round(
                warm_totals.get("identity_bootstrap", 0.0) * 1000 / iters, 3),
            "cold_mint_leaf_ms": round(
                stage_totals.get("identity_mint_leaf", 0.0) * 1000 / iters, 3),
            "warm_mint_leaf_ms": round(
                warm_totals.get("identity_mint_leaf", 0.0) * 1000 / iters, 3),
            "cold_leaf_cache_hits": stage_counts.get(
                "identity.leaf_cache_hit", 0),
            "warm_leaf_cache_hits": warm_counts.get(
                "identity.leaf_cache_hit", 0),
            "warm_leaf_cache_misses": warm_counts.get(
                "identity.leaf_cache_miss", 0),
        }
    stages = {name: round(total * 1000.0 / iters, 3)
              for name, total in sorted(stage_totals.items())}
    stages["other"] = round(
        statistics.mean(samples) * 1000 - sum(stages.values()), 3)
    return statistics.median(samples), stages, identity


def bench_parity(jobs: int | None = None) -> tuple[float, int, int]:
    """(wall_s, passed, total) over e2e scenarios + adversarial corpus.

    The 52-surface suite used to run strictly serially (20.5s
    ``parity_suite_wall``, BENCH_r05).  Independent cases now fan
    across a bounded process pool (per-case tmpdir subtrees + per-world
    capture stores keep isolation identical to the serial run), and the
    scenario corpus overlaps the redteam corpus: BOTH halves' cases go
    into ONE shared fork pool submitted from this (main) thread.  Two
    thread-driven pools would fork each half's workers from a thread
    while the sibling pool's management threads run -- the classic
    fork-under-threads child deadlock; one pool keeps every fork on the
    main thread and interleaves the halves for free."""
    import multiprocessing
    from concurrent.futures import ProcessPoolExecutor

    from clawker_tpu.parity.__main__ import default_parity_jobs
    from clawker_tpu.parity.redteam import (
        _corpus_shard,
        corpus_shards,
        merge_shards,
    )
    from clawker_tpu.parity.scenarios import _scenario_case, scenario_cases

    if jobs is None:
        jobs = default_parity_jobs()
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="clawker-bench-parity-") as td:
        cases = scenario_cases(Path(td))
        shards = corpus_shards(Path(td) / "redteam", jobs)
        with ProcessPoolExecutor(
                max_workers=min(2 * jobs, len(cases) + len(shards)),
                mp_context=multiprocessing.get_context("fork")) as ex:
            # corpus shards first: they are the long poles, and the
            # scenario cases backfill the remaining workers
            shard_futs = [ex.submit(_corpus_shard, s) for s in shards]
            case_futs = [ex.submit(_scenario_case, c) for c in cases]
            rows = [f.result() for f in case_futs]
            red = merge_shards([f.result() for f in shard_futs])
    wall = time.perf_counter() - t0
    passed = sum(1 for r in rows if r["pass"])
    if red["captures"] == 0:  # any capture voids the whole corpus
        passed += red["passed"]
    return wall, passed, len(rows) + red["total"]


def bench_policy_oracle(budget_s: float = 0.5) -> float:
    """Kernel-twin decisions/s over a realistic verdict mix."""
    from clawker_tpu.firewall import policy
    from clawker_tpu.firewall.hashes import zone_hash
    from clawker_tpu.firewall.maps import DnsEntry, FakeMaps
    from clawker_tpu.firewall.model import (
        FLAG_ENFORCE,
        PROTO_TCP,
        Action,
        ContainerPolicy,
        RouteKey,
        RouteVal,
    )

    maps = FakeMaps()
    maps.enroll(7, ContainerPolicy(envoy_ip="10.0.0.2", dns_ip="10.0.0.1",
                                   hostproxy_ip="10.0.0.1", hostproxy_port=18374,
                                   flags=FLAG_ENFORCE))
    zh = zone_hash("example.com")
    maps.cache_dns("93.184.216.34", DnsEntry(zone_hash=zh, expires_unix=2**40))
    maps.sync_routes({RouteKey(zh, 443, PROTO_TCP): RouteVal(
        Action.REDIRECT, redirect_ip="10.0.0.2", redirect_port=10000)})
    mix = [("93.184.216.34", 443), ("8.8.8.8", 53), ("1.2.3.4", 443),
           ("127.0.0.1", 80), ("10.0.0.2", 10000)]
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < budget_s:
        for ip, port in mix:
            policy.connect4(maps, 7, ip, port, sock_cookie=n)
            n += 1
    return n / (time.perf_counter() - t0)


def bench_dnsgate_qps(budget_s: float = 1.0) -> float:
    """Real UDP round-trips against the live gate socket."""
    import socket
    import struct

    from clawker_tpu.config.schema import EgressRule
    from clawker_tpu.firewall.dnsgate import DnsGate, ZonePolicy, _encode_name
    from clawker_tpu.firewall.maps import FakeMaps

    gate = DnsGate(ZonePolicy.from_rules([EgressRule(dst="*.example.com")]),
                   FakeMaps(), host="127.0.0.1", port=0)
    gate._forward = lambda data, resolvers, tcp=False: None  # NXDOMAIN path
    gate.start()
    try:
        q = (struct.pack(">HHHHHH", 1, 0x0100, 1, 0, 0, 0)
             + _encode_name("x.notruled.net") + struct.pack(">HH", 1, 1))
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.settimeout(2.0)
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < budget_s:
            sock.sendto(q, ("127.0.0.1", gate.bound_port))
            sock.recv(512)
            n += 1
        sock.close()
        return n / (time.perf_counter() - t0)
    finally:
        gate.stop()


def bench_loop_fanout(n: int = 8, iters: int = 3) -> float:
    """p50 seconds from scheduler.start() until all N loop containers are
    created across an N-worker fake pod.  start() only SUBMITS the
    fan-out (creates ride per-worker lanes), so the sample spans
    submit -> the Nth ``created`` event -- the same create-all span the
    serial scheduler's start() used to cover inline."""
    import threading

    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.fake import exit_behavior
    from clawker_tpu.loop import LoopScheduler, LoopSpec
    from clawker_tpu.testenv import TestEnv

    samples = []
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchloop\n")
        cfg = load_config(proj)
        # one warmup run eats lazy-import costs (bootstrap, channels,
        # workspace) so the samples measure scheduling, not importing
        for trial in range(iters + 1):
            drv = FakeDriver(n_workers=n)
            for api in drv.apis:
                api.add_image("clawker-benchloop:default")
                api.set_behavior("clawker-benchloop:default",
                                 exit_behavior(b"done\n", 0))
            all_started = threading.Event()
            t_started = [0.0]
            remaining = [n]

            def on_event(agent, event, detail=""):
                if event == "created":
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        t_started[0] = time.perf_counter()
                        all_started.set()

            sched = LoopScheduler(cfg, drv, LoopSpec(parallel=n, iterations=1),
                                  on_event=on_event)
            t0 = time.perf_counter()
            sched.start()
            all_started.wait(30.0)
            if trial > 0:
                samples.append((t_started[0] or time.perf_counter()) - t0)
            sched.run(poll_s=0.02)
            sched.cleanup(remove_containers=True)
    return statistics.median(samples)


def bench_loop_fanout_n64(n_loops: int = 64, n_workers: int = 4,
                          iters: int = 2, cap: int = 4) -> dict:
    """loop_fanout_p50_n64: p50 seconds from scheduler.start() until the
    64th loop container is created on the 4-worker fake pod, ADMISSION
    ENABLED (ISSUE 6 acceptance).  The burst drains through per-worker
    token buckets instead of flooding the lanes; the sample also
    verifies no bucket ever exceeded its cap and every loop reached its
    budget."""
    import threading

    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.fake import exit_behavior
    from clawker_tpu.loop import LoopScheduler, LoopSpec
    from clawker_tpu.testenv import TestEnv

    samples = []
    hwm_ok = True
    all_done = True
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchloop\n")
        cfg = load_config(proj)
        for trial in range(iters + 1):      # one warmup eats lazy imports
            drv = FakeDriver(n_workers=n_workers)
            for api in drv.apis:
                api.add_image("clawker-benchloop:default")
                api.set_behavior("clawker-benchloop:default",
                                 exit_behavior(b"done\n", 0))
            all_created = threading.Event()
            t_created = [0.0]
            remaining = [n_loops]

            def on_event(agent, event, detail=""):
                if event == "created":
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        t_created[0] = time.perf_counter()
                        all_created.set()

            sched = LoopScheduler(
                cfg, drv,
                LoopSpec(parallel=n_loops, iterations=1,
                         max_inflight_per_worker=cap),
                on_event=on_event)
            t0 = time.perf_counter()
            sched.start()
            runner = threading.Thread(target=sched.run,
                                      kwargs={"poll_s": 0.05}, daemon=True)
            runner.start()
            all_created.wait(60.0)
            if trial > 0:
                samples.append((t_created[0] or time.perf_counter()) - t0)
            runner.join(60.0)
            stats = sched.admission.stats()
            if trial > 0:
                hwm_ok = hwm_ok and all(
                    w["inflight_hwm"] <= cap
                    for w in stats["workers"].values())
                all_done = all_done and all(
                    l.status == "done" for l in sched.loops)
            sched.cleanup(remove_containers=True)
    return {
        "fanout_p50_s": round(statistics.median(samples), 3),
        "loops": n_loops,
        "workers": n_workers,
        "cap": cap,
        "cap_respected": hwm_ok,
        "all_loops_done": all_done,
    }


def bench_placement_admission_stampede(n_loops: int = 64,
                                       create_delay: float = 0.03) -> dict:
    """placement_admission_stampede: a 64-loop burst PACKED onto one
    slow worker (every create pays ``create_delay``) must drain at the
    daemon's sustainable rate -- admission bucket never exceeded, the
    worker's breaker never opens, every loop completes (ISSUE 6
    acceptance: a burst cannot stampede a daemon into quarantine)."""
    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.api import Engine
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.drivers.fakedriver import _FaultGate
    from clawker_tpu.engine.fake import FakeDockerAPI, exit_behavior
    from clawker_tpu.health import BREAKER_OPEN, BreakerConfig, HealthConfig
    from clawker_tpu.loop import LoopScheduler, LoopSpec
    from clawker_tpu.testenv import TestEnv

    class SlowCreate(FakeDockerAPI):
        def container_create(self, name, config):
            time.sleep(create_delay)
            return super().container_create(name, config)

    cap = 4
    breaker_opened = [False]
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchloop\n")
        cfg = load_config(proj)
        drv = FakeDriver(n_workers=1)
        api = SlowCreate()
        drv.apis[0] = api
        drv.gates[0] = _FaultGate(api)
        drv._workers[0].engine = Engine(drv.gates[0])
        api.add_image("clawker-benchloop:default")
        api.set_behavior("clawker-benchloop:default",
                         exit_behavior(b"done\n", 0))

        def on_event(agent, event, detail=""):
            if event == "worker.health" and "open" in detail.split(":")[0]:
                breaker_opened[0] = True

        sched = LoopScheduler(
            cfg, drv,
            LoopSpec(parallel=n_loops, iterations=1, placement="pack",
                     max_inflight_per_worker=cap),
            on_event=on_event,
            health_config=HealthConfig(
                probe_interval_s=0.05, probe_deadline_s=1.0,
                breaker=BreakerConfig(failure_threshold=3,
                                      backoff_base_s=0.05)))
        t0 = time.perf_counter()
        sched.start()
        loops = sched.run(poll_s=0.05)
        wall = time.perf_counter() - t0
        stats = sched.admission.stats()
        state = sched.health.state(drv.workers()[0].id)
        breaker_opened[0] = breaker_opened[0] or state == BREAKER_OPEN
        sched.cleanup(remove_containers=True)
    wstats = stats["workers"].get("fake-0", {})
    return {
        "wall_s": round(wall, 3),
        "loops": n_loops,
        "cap": cap,
        "all_loops_done": all(l.status == "done" for l in loops),
        "cap_respected": wstats.get("inflight_hwm", 0) <= cap,
        "dispatched": wstats.get("dispatched", 0),
        "breaker_opened": breaker_opened[0],
    }


def bench_loop_poll_cost(n: int = 8, iterations: int = 2) -> dict:
    """Control-plane round-trips per agent iteration while a fanned-out
    loop runs.  The serial scheduler paid one inspect per agent per
    tick; the batched one pays one list per worker per tick, one
    blocking wait per running iteration, and one inspect per finished
    iteration.  Counts list + inspect + wait calls, measured over N
    agents on 2 fake workers with a 0.1s iteration body."""
    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.fake import exit_behavior
    from clawker_tpu.loop import LoopScheduler, LoopSpec
    from clawker_tpu.testenv import TestEnv

    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchloop\n")
        cfg = load_config(proj)
        drv = FakeDriver(n_workers=2)
        for api in drv.apis:
            api.add_image("clawker-benchloop:default")
            api.set_behavior("clawker-benchloop:default",
                             exit_behavior(b"", 0, delay=0.1))
        sched = LoopScheduler(cfg, drv,
                              LoopSpec(parallel=n, iterations=iterations))
        sched.start()
        sched.run(poll_s=0.05)
        # health probes also list (all=False); the poll cost is the
        # scheduler's all=True batched lists
        lists = sum(1 for api in drv.apis
                    for _, kw in api.calls_named("container_list")
                    if kw.get("all"))
        inspects = sum(len(api.calls_named("container_inspect"))
                       for api in drv.apis)
        waits = sum(len(api.calls_named("container_wait")) for api in drv.apis)
        total_iters = sum(l.iteration for l in sched.loops) or 1
        sched.cleanup(remove_containers=True)
    return {
        "list_calls": lists,
        "inspect_calls": inspects,
        "wait_calls": waits,
        "iterations": total_iters,
        "calls_per_iteration": round(
            (lists + inspects + waits) / total_iters, 2),
    }


def bench_fleet_provision(n: int = 8, per_call_delay: float = 0.02) -> dict:
    """Wall seconds to provision an N-worker pod over FakeRunner
    transports with an injected per-call delay (standing in for SSH
    RTT), vs the same plan run serially with a per-worker tar build --
    the pre-tentpole behavior.  The repo payload is a tiny synthetic
    tree so the delay (not tar IO) dominates both sides equally."""
    from clawker_tpu.config.schema import TPUSettings
    from clawker_tpu.fleet.provision import provision_fleet, provision_worker
    from clawker_tpu.fleet.transport import FakeRunner, SSHTransport

    class SlowRunner(FakeRunner):
        def run(self, argv, *, input_bytes=None, timeout=60.0):
            time.sleep(per_call_delay)
            return super().run(argv, input_bytes=input_bytes, timeout=timeout)

    tpu = TPUSettings(ssh_user="bench")
    with tempfile.TemporaryDirectory(prefix="clawker-bench-fleet-") as td:
        root = Path(td) / "repo"
        (root / "clawker_tpu").mkdir(parents=True)
        (root / "clawker_tpu" / "__init__.py").write_text("x = 1\n")
        (root / "native").mkdir()
        (root / "native" / "Makefile").write_text("all:\n")

        def transports():
            return [SSHTransport(tpu, f"10.0.0.{i}", i,
                                 mux_dir=Path(td) / "mux", runner=SlowRunner())
                    for i in range(n)]

        t0 = time.perf_counter()
        for t in transports():   # serial baseline: per-worker plan AND tar
            provision_worker(t, root)
        serial = time.perf_counter() - t0

        t0 = time.perf_counter()
        reports = provision_fleet(transports(), root)
        wall = time.perf_counter() - t0
    ok = all(r.ok for r in reports)
    return {
        "wall_s": round(wall, 3),
        "serial_wall_s": round(serial, 3),
        "speedup": round(serial / wall, 2) if wall > 0 else 0.0,
        "workers": n,
        "ok": ok,
    }


def bench_failover(n_loops: int = 8, n_workers: int = 4,
                   iterations: int = 4) -> dict:
    """failover_detect_to_restart_s: kill one fake worker mid-loop under
    ``--failover migrate`` and measure death -> the first migrated
    agent's next iteration START (detection + breaker trip + orphan +
    re-place + create + bootstrap on the new worker).  Budget: the
    worker-death recovery must stay well under the 10 s cold-start
    budget -- a dead worker costing more than a cold start would make
    failover pointless.
    """
    import threading

    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.fake import exit_behavior
    from clawker_tpu.health import BreakerConfig, HealthConfig
    from clawker_tpu.loop import LoopScheduler, LoopSpec
    from clawker_tpu.testenv import TestEnv

    victim = 1
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchloop\n")
        cfg = load_config(proj)
        drv = FakeDriver(n_workers=n_workers)
        for api in drv.apis:
            api.add_image("clawker-benchloop:default")
            api.set_behavior("clawker-benchloop:default",
                             exit_behavior(b"", 0, delay=0.1))
        migrated: set = set()
        restart_evt = threading.Event()
        t_restart = [0.0]

        def on_event(agent, event, detail=""):
            if event == "migrated":
                migrated.add(agent)
            elif (event == "iteration_start" and agent in migrated
                  and not restart_evt.is_set()):
                t_restart[0] = time.perf_counter()
                restart_evt.set()

        sched = LoopScheduler(
            cfg, drv,
            LoopSpec(parallel=n_loops, iterations=iterations,
                     failover="migrate"),
            on_event=on_event,
            health_config=HealthConfig(
                probe_interval_s=0.05, probe_deadline_s=0.5,
                breaker=BreakerConfig(failure_threshold=3,
                                      backoff_base_s=0.05,
                                      backoff_max_s=0.2)))
        sched.start()
        runner = threading.Thread(target=sched.run,
                                  kwargs={"poll_s": 0.05}, daemon=True)
        runner.start()
        deadline = time.monotonic() + 20.0
        vid = drv.workers()[victim].id
        while time.monotonic() < deadline:     # victim must be mid-loop
            if any(l.status == "running" and l.worker.id == vid
                   for l in sched.loops):
                break
            time.sleep(0.01)
        t_kill = time.perf_counter()
        drv.inject_fault(victim, "refuse")
        restart_evt.wait(20.0)
        runner.join(30.0)
        all_done = bool(sched.loops) and all(
            l.status == "done" and l.iteration == iterations
            for l in sched.loops)
        migrations = sum(l.migrations for l in sched.loops)
        sched.cleanup(remove_containers=True)
    detect = (t_restart[0] - t_kill) if restart_evt.is_set() else -1.0
    return {
        "detect_to_restart_s": round(detect, 3),
        "all_loops_done": all_done,
        "migrations": migrations,
        "loops": n_loops,
        "workers": n_workers,
    }


def bench_resume_reattach(n_loops: int = 8, n_workers: int = 4) -> dict:
    """resume_reattach_wall_n8: kill a mid-run scheduler, then measure
    the wall time from the ``--resume`` invocation (journal read +
    replay + reconcile) until all N loops are live again.  Adoption
    reattaches to still-running containers with ZERO engine mutations,
    so the resume must beat the cold fan-out it replaces (``speedup`` =
    cold create+start wall / reattach wall); the smoke gate also pins
    zero duplicate creates and a full adoption count.
    """
    import threading

    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.loop import LoopScheduler, LoopSpec
    from clawker_tpu.loop.journal import RunJournal, journal_path, replay
    from clawker_tpu.testenv import TestEnv

    hold = threading.Event()

    def behavior(io) -> int:
        if not hold.is_set():
            hold.wait(30.0)
        return 0

    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchloop\n")
        cfg = load_config(proj)
        drv = FakeDriver(n_workers=n_workers)
        for api in drv.apis:
            api.add_image("clawker-benchloop:default")
            api.set_behavior("clawker-benchloop:default", behavior)
        sched1 = LoopScheduler(cfg, drv,
                               LoopSpec(parallel=n_loops, iterations=1))
        t_cold = time.perf_counter()
        sched1.start()
        runner = threading.Thread(target=sched1.run,
                                  kwargs={"poll_s": 0.05}, daemon=True)
        runner.start()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if sched1.loops and all(l.status == "running"
                                    for l in sched1.loops):
                break
            time.sleep(0.005)
        cold_wall = time.perf_counter() - t_cold
        creates_before = sum(len(api.calls_named("container_create"))
                             for api in drv.apis)
        sched1.kill()
        runner.join(10.0)

        t_resume = time.perf_counter()
        image = replay(RunJournal.read(
            journal_path(cfg.logs_dir, sched1.loop_id)))
        sched2 = LoopScheduler.resume(cfg, drv, image)
        summary = sched2.reconcile()
        reattach_wall = time.perf_counter() - t_resume
        live = sum(1 for l in sched2.loops if l.status == "running")
        creates_after = sum(len(api.calls_named("container_create"))
                            for api in drv.apis)
        runner2 = threading.Thread(target=sched2.run,
                                   kwargs={"poll_s": 0.05}, daemon=True)
        runner2.start()
        hold.set()
        runner2.join(30.0)
        all_done = bool(sched2.loops) and all(
            l.status == "done" and l.iteration == 1 for l in sched2.loops)
        sched2.cleanup(remove_containers=True)
    return {
        "reattach_wall_s": round(reattach_wall, 4),
        "cold_fanout_wall_s": round(cold_wall, 4),
        "speedup": round(cold_wall / reattach_wall, 2) if reattach_wall > 0
        else 0.0,
        "adopted": summary["adopted"],
        "live_after_reconcile": live,
        "duplicate_creates": creates_after - creates_before,
        "all_loops_done": all_done,
        "loops": n_loops,
        "workers": n_workers,
    }


def bench_warm_pool_hit(iters: int = 30) -> dict:
    """warm_pool_hit_p50: framework cost of a warm-pool HIT vs the cold
    create it replaces (ISSUE 7 acceptance: <= 1ms on a hit).

    Cold leg: the full create path -- engine_create + workspace_seed +
    harness_seed + identity_bootstrap (where the cryptography stack is
    available) + engine_start -- under fresh agent names, so every leaf
    is a cache miss (the 8.95ms-shaped cold start of BENCH_r05).

    Warm leg: the pool shape -- members pre-created through the SAME
    create path under placeholder names (untimed; that is the pool
    fill's whole point), identities prewarmed for the upcoming agent
    names, then the timed hit = WarmPool.checkout + adopt_pooled
    (relabel + env fixup + warm identity + rename) + engine_start.
    ``harness_seed`` and the expensive half of ``identity_bootstrap``
    are OFF this path by construction; the reported split proves it.
    """
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.loop.warmpool import WarmPool
    from clawker_tpu.runtime.orchestrate import (
        AgentRuntime,
        CreateOptions,
        clear_harness_seed_cache,
    )
    from clawker_tpu.testenv import TestEnv
    from clawker_tpu.util import phases

    try:        # identity needs the cryptography stack; degrade visibly
        from clawker_tpu.controlplane.identity import (
            clear_identity_cache,
            make_bootstrapper,
            prewarm_identities,
        )
        from clawker_tpu.firewall import pki
        identity_wired = True
    except ImportError:
        identity_wired = False

    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        tenv.make_project(proj, "project: benchpool\n")
        cfg = load_config(proj)
        driver = FakeDriver()
        driver.api.add_image("clawker-benchpool:default")
        engine = driver.engine()
        bootstrap = (make_bootstrapper(cfg, engine)
                     if identity_wired else None)
        rt = AgentRuntime(engine, cfg, bootstrap=bootstrap)
        worker = driver.workers()[0]
        if identity_wired:
            clear_identity_cache()
        clear_harness_seed_cache()

        def opts(agent: str) -> CreateOptions:
            return CreateOptions(agent=agent, workspace_mode="snapshot",
                                 tty=False, replace=True)

        # --- cold leg: full create+start per fresh agent.  The staging
        # tar cache keys on (harness, root, creds), NOT the agent --
        # clear it each iteration (outside the timer) so every cold
        # create pays the real staging walk the warm pool is up against.
        cold: list[float] = []
        phases.enable()
        for i in range(iters):
            clear_harness_seed_cache()
            t0 = time.perf_counter()
            cid = rt.create(opts(f"cold{i}"))
            rt.start(cid)
            cold.append(time.perf_counter() - t0)
        cold_stages = phases.disable()

        # --- warm leg: pool fill (untimed) -> checkout+adopt+start (timed)
        import gc
        gc.collect()    # the 30 true-cold staging walks leave garbage;
        # a gen-2 pause inside the ~1ms timed hits would be cold-leg debt
        pool = WarmPool("benchrun", depth=iters)
        for _ in range(iters):
            agent = pool.begin_refill(worker)
            cid = rt.create(CreateOptions(agent=agent,
                                          workspace_mode="snapshot",
                                          tty=False, replace=True))
            pool.fill_done(worker, agent, cid)
        if identity_wired:
            prewarm_identities(pki.ensure_ca(cfg.pki_dir),
                               cfg.project_name(),
                               [f"warm{i}" for i in range(iters)])
        warm: list[float] = []
        phases.enable()
        for i in range(iters):
            t0 = time.perf_counter()
            entry = pool.checkout(worker.id, by=f"warm{i}", epoch=0)
            rt.adopt_pooled(entry.cid, opts(f"warm{i}"))
            rt.start(entry.cid)
            warm.append(time.perf_counter() - t0)
        warm_stages = phases.disable()
        stats = pool.stats()

    def per_iter_ms(stages: dict, name: str) -> float:
        return round(stages.get(name, 0.0) * 1000 / iters, 3)

    hit_p50 = statistics.median(warm)
    cold_p50 = statistics.median(cold)
    return {
        "hit_p50_ms": round(hit_p50 * 1000, 3),
        "cold_p50_ms": round(cold_p50 * 1000, 3),
        "speedup": round(cold_p50 / hit_p50, 1) if hit_p50 > 0 else 0.0,
        "hits": stats["hits"],
        "misses": stats["misses"],
        "iters": iters,
        "identity_wired": identity_wired,
        # the cold/warm split, bench_cold_start identity_split style:
        # what the hit path still pays vs what moved to the fill
        "split": {
            "cold_harness_seed_ms": per_iter_ms(cold_stages, "harness_seed"),
            "hit_harness_seed_ms": per_iter_ms(warm_stages, "harness_seed"),
            "cold_identity_bootstrap_ms": per_iter_ms(
                cold_stages, "identity_bootstrap"),
            "hit_identity_bootstrap_ms": per_iter_ms(
                warm_stages, "identity_bootstrap"),
            "hit_env_fixup_ms": per_iter_ms(warm_stages, "pool_adopt_env"),
            "hit_finalize_ms": per_iter_ms(warm_stages,
                                           "pool_adopt_finalize"),
            "hit_rename_ms": per_iter_ms(warm_stages, "pool_adopt_rename"),
            "hit_engine_start_ms": per_iter_ms(warm_stages, "engine_start"),
        },
    }


def bench_warm_pool_refill_burst(n_loops: int = 32, n_workers: int = 4,
                                 depth: int = 2, cap: int = 4) -> dict:
    """warm_pool_refill_burst: a full fan-out burst over a pool-enabled
    scheduler must (a) complete every loop within the fan-out budget --
    refills ride a low-weight admission tenant, so they may never
    starve live placements -- (b) leave every worker's pool refilled to
    target depth, and (c) leak zero pool containers after drain."""
    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.fake import exit_behavior
    from clawker_tpu.loop import LoopScheduler, LoopSpec
    from clawker_tpu.testenv import TestEnv

    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchloop\n")
        cfg = load_config(proj)
        drv = FakeDriver(n_workers=n_workers)
        for api in drv.apis:
            api.add_image("clawker-benchloop:default")
            api.set_behavior("clawker-benchloop:default",
                             exit_behavior(b"done\n", 0))
        sched = LoopScheduler(
            cfg, drv,
            LoopSpec(parallel=n_loops, iterations=1, warm_pool_depth=depth,
                     max_inflight_per_worker=cap))
        t0 = time.perf_counter()
        sched.start()
        loops = sched.run(poll_s=0.05)
        wall = time.perf_counter() - t0
        stats = sched.warmpool.stats()
        refilled = all(
            sched.warmpool.depth_of(w.id) == depth for w in drv.workers())
        sched.cleanup(remove_containers=True)
        leaked = sum(
            len(api.container_list(all=True, filters={
                "label": [f"{consts.LABEL_LOOP}={sched.loop_id}"]}))
            for api in drv.apis)
    return {
        "wall_s": round(wall, 3),
        "loops": n_loops,
        "workers": n_workers,
        "depth": depth,
        "all_loops_done": all(l.status == "done" for l in loops),
        "pool_refilled": refilled,
        "hits": stats["hits"],
        "refills": stats["refills"],
        "leaked_containers": leaked,
    }


CHAOS_SOAK_SEED = 20260803    # fixed: a CI failure replays anywhere with
#                               `clawker chaos replay --seed ... --scenario N`
CHAOS_SOAK_SCENARIOS = 25     # ISSUE 8 acceptance floor
CHAOS_SOAK_BUDGET_S = 240.0   # wall ceiling for the whole soak


def bench_workerd_rtt_independence(n_loops: int = 8, n_workers: int = 4,
                                   iterations: int = 4,
                                   rtt_s: float = 0.05) -> dict:
    """workerd_rtt_independence: the ISSUE 11 acceptance bar.

    Four legs of the same 8-loop/4-worker fan-out + iteration run on
    the fake pod with the fake-WAN harness (testenv docstring):
    workerd executors at zero RTT and at 50ms injected per-call RTT,
    then the direct in-process path at both.  The direct path pays the
    RTT on EVERY engine call (create's whole call chain, each restart,
    each poll), so its wall scales with RTT; the workerd path pays one
    propagation delay per batched intent/event frame, so its wall must
    stay within 1.5x of its own zero-RTT run -- fan-out and iteration
    latency independent of host<->worker RTT.

    The container runtime (0.15s/iteration) is deliberately non-tiny:
    a dependent submit->execute->exit->account cycle costs ONE
    propagation RTT as a physical floor even over a perfect data
    plane, so the baseline must represent real agent iterations
    (seconds+), not an RTT-microbenchmark -- the gate judges that the
    per-ENGINE-CALL multiplier is gone, which is the workerd claim.
    """
    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.fake import exit_behavior
    from clawker_tpu.loop import LoopScheduler, LoopSpec
    from clawker_tpu.testenv import TestEnv, inject_wan_rtt
    from clawker_tpu.workerd.executor import ExecutorSet, WorkerdExecutor
    from clawker_tpu.workerd.server import WorkerdServer

    def leg(leg_rtt_s: float, workerd: bool) -> tuple[float, bool]:
        with TestEnv() as tenv:
            proj = tenv.base / "proj"
            proj.mkdir()
            (proj / consts.PROJECT_FLAT_FORM).write_text(
                "project: benchloop\n")
            cfg = load_config(proj)
            drv = FakeDriver(n_workers=n_workers)
            for api in drv.apis:
                api.add_image("clawker-benchloop:default")
                api.set_behavior("clawker-benchloop:default",
                                 exit_behavior(b"", 0, delay=0.15))
            inject_wan_rtt(drv, leg_rtt_s)
            servers, exs = [], {}
            if workerd:
                for i, w in enumerate(drv.workers()):
                    sock = tenv.base / f"wd-{i}.sock"
                    servers.append(WorkerdServer(
                        cfg, drv.local_engine(i), worker_id=w.id,
                        sock_path=sock).start())
                    exs[w.id] = WorkerdExecutor(w.id, sock,
                                                rtt_s=leg_rtt_s,
                                                intent_deadline_s=30.0)
            execset = ExecutorSet(exs) if workerd else None
            sched = LoopScheduler(
                cfg, drv, LoopSpec(parallel=n_loops, iterations=iterations,
                                   image="clawker-benchloop:default"),
                executors=execset)
            t0 = time.perf_counter()
            sched.start()
            loops = sched.run(poll_s=0.2)
            wall = time.perf_counter() - t0
            done = bool(loops) and all(
                l.status == "done" and l.iteration == iterations
                for l in loops)
            inject_wan_rtt(drv, 0.0)    # cleanup off the fake WAN
            sched.cleanup(remove_containers=True)
            if execset is not None:
                execset.close_all()
            for s in servers:
                s.stop()
            drv.close()
            return wall, done

    wd_zero, wd_zero_ok = leg(0.0, True)
    wd_rtt, wd_rtt_ok = leg(rtt_s, True)
    direct_zero, direct_zero_ok = leg(0.0, False)
    direct_rtt, direct_rtt_ok = leg(rtt_s, False)
    return {
        "rtt_ms": round(rtt_s * 1000),
        "workerd_zero_rtt_wall_s": round(wd_zero, 3),
        "workerd_rtt_wall_s": round(wd_rtt, 3),
        "direct_zero_rtt_wall_s": round(direct_zero, 3),
        "direct_rtt_wall_s": round(direct_rtt, 3),
        "workerd_ratio": round(wd_rtt / max(wd_zero, 1e-9), 2),
        "direct_ratio": round(direct_rtt / max(direct_zero, 1e-9), 2),
        "all_done": bool(wd_zero_ok and wd_rtt_ok and direct_zero_ok
                         and direct_rtt_ok),
        "loops": n_loops, "workers": n_workers, "iterations": iterations,
    }


def bench_workerd_event_batch_overhead(iters: int = 40) -> dict:
    """workerd_event_batch_overhead: framework cost of the batched
    channel itself.  One executor + one workerd on a fake worker run
    ``iters`` sequential launch intents against a stub accounting sink;
    per launch we measure submit -> started-event-handled wall minus
    the worker-side engine time the events report -- the pure
    intent/event machinery overhead -- plus the event/batch coalescing
    ratio (events per frame; > 1 means batching actually batches).
    """
    import threading

    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.fake import exit_behavior
    from clawker_tpu.testenv import TestEnv
    from clawker_tpu.workerd.executor import WorkerdExecutor
    from clawker_tpu.workerd.server import WorkerdServer

    class _Sink:
        """Stub scheduler surface: records handler receipt times."""

        def __init__(self):
            self.started = threading.Event()
            self.engine_ms = 0.0

        def _workerd_created(self, loop, epoch, worker, cid, pool_hit,
                             pool_error, pool_entry, ms, **kw):
            self.engine_ms += ms

        def _workerd_started(self, loop, epoch, worker, ms, **kw):
            self.engine_ms += ms
            self.started.set()

        def _workerd_failed(self, *a, **kw):
            self.started.set()

        def _workerd_exited(self, *a, **kw):
            pass

        def _workerd_running_view(self, worker_id):
            return []

        class seams:            # noqa: N801 -- stub attribute surface
            @staticmethod
            def fire(name):
                pass

    class _Loop:
        def __init__(self, agent):
            self.agent = agent
            self.iteration = 0

    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchloop\n")
        cfg = load_config(proj)
        drv = FakeDriver(n_workers=1)
        drv.apis[0].add_image("clawker-benchloop:default")
        drv.apis[0].set_behavior("clawker-benchloop:default",
                                 exit_behavior(b"", 0, delay=0.001))
        sock = tenv.base / "wd.sock"
        srv = WorkerdServer(cfg, drv.local_engine(0), worker_id="fake-0",
                            sock_path=sock).start()
        ex = WorkerdExecutor("fake-0", sock, intent_deadline_s=20.0)
        overheads: list[float] = []
        worker = drv.workers()[0]
        try:
            for i in range(iters):
                sink = _Sink()
                ex.bind(sink)
                loop = _Loop(f"ovh-{i}")
                t0 = time.perf_counter()
                ex.submit_launch(loop, 0, worker, opts_doc={
                    "agent": loop.agent,
                    "image": "clawker-benchloop:default",
                    "loop_id": "benchwd", "worker": "fake-0",
                    "extra_labels": {consts.LABEL_LOOP_EPOCH: "0"}})
                if not sink.started.wait(10.0):
                    break
                wall_ms = (time.perf_counter() - t0) * 1000
                overheads.append(max(0.0, wall_ms - sink.engine_ms))
            events = srv.stats["events"]
            batches = max(1, srv.stats["batches"])
        finally:
            ex.close()
            srv.stop()
            drv.close()
    overheads.sort()
    return {
        "event_overhead_p50_ms": (round(overheads[len(overheads) // 2], 3)
                                  if overheads else -1.0),
        "event_overhead_max_ms": (round(overheads[-1], 3)
                                  if overheads else -1.0),
        "completed": len(overheads), "iters": iters,
        "events": events, "batches": batches,
        "coalesce_ratio": round(events / batches, 2),
    }


def bench_workspace_seed_amortization(n_agents: int = 32,
                                      n_workers: int = 4,
                                      rtt_s: float = 0.05) -> dict:
    """workspace_seed_amortization: the ISSUE 16 acceptance bar.

    One seeded repo fanned out to 32 agents on the 4-worker fake pod
    with 50ms injected WAN RTT.  Baseline leg: the per-agent path every
    snapshot create used to pay -- a fresh tree walk + tar build + one
    WAN put_archive per agent.  Amortized leg: the content-addressed
    path -- the walk paid ONCE into the digest cache (>= 31 of the 32
    agent lookups must hit), exactly one seed transfer per worker into
    the workerd-resident store, then every create resolves the digest
    over the worker's local socket with zero further WAN bytes.  The
    gate: amortized wall >= 10x faster, executor seed transfers == 1
    per channel, a store hit for every create, all creates landed.
    """
    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.runtime.orchestrate import (
        clear_workspace_seed_cache,
        workspace_seed_tar,
    )
    from clawker_tpu.testenv import TestEnv, inject_wan_rtt
    from clawker_tpu.workerd.executor import WorkerdExecutor
    from clawker_tpu.workerd.server import WorkerdServer
    from clawker_tpu.workspace.strategy import (
        _SEED_CACHE_HITS,
        _SEED_CACHE_MISSES,
        _tar_tree,
    )

    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchseed\n")
        # a repo big enough that the per-agent tree walk is real work
        for d in range(8):
            sub = proj / "src" / f"pkg{d}"
            sub.mkdir(parents=True)
            for f in range(12):
                (sub / f"mod{f}.py").write_text(
                    f"# pkg{d}.mod{f}\n" + "x = 1\n" * 200)
        cfg = load_config(proj)
        drv = FakeDriver(n_workers=n_workers)
        for api in drv.apis:
            api.add_image("clawker-benchseed:default")
        inject_wan_rtt(drv, rtt_s)
        workers = drv.workers()

        # --- baseline leg: per-agent walk + per-agent WAN transfer.
        # Target containers are created off the clock straight on the
        # fake daemons (the legs compare SEEDING cost, not create cost).
        base_cids = []
        for i in range(n_agents):
            r = drv.apis[i % n_workers].container_create(
                f"seedbase-{i}", {
                    "Image": "clawker-benchseed:default",
                    "Labels": {consts.LABEL_MANAGED: consts.MANAGED_VALUE}})
            base_cids.append(r["Id"])
        t0 = time.perf_counter()
        for i in range(n_agents):
            tar = _tar_tree(proj)               # the per-agent walk
            workers[i % n_workers].engine.put_archive(
                base_cids[i], consts.WORKSPACE_DIR, tar)
        baseline_wall = time.perf_counter() - t0

        # --- amortized leg: digest cache + workerd seed stores + real
        # worker-local creates referencing the digest.
        servers, exs = [], []
        try:
            for i, w in enumerate(workers):
                sock = tenv.base / f"wd-{i}.sock"
                servers.append(WorkerdServer(
                    cfg, drv.local_engine(i), worker_id=w.id,
                    sock_path=sock).start())
                exs.append(WorkerdExecutor(w.id, sock, rtt_s=rtt_s,
                                           intent_deadline_s=30.0))
            clear_workspace_seed_cache()
            hits0 = _SEED_CACHE_HITS._default.peek()
            misses0 = _SEED_CACHE_MISSES._default.peek()
            t0 = time.perf_counter()
            digest, seed_tar = "", b""
            for i in range(n_agents):       # one lookup per agent
                digest, seed_tar = workspace_seed_tar(proj)
            for ex in exs:
                ex.submit_seed(digest, seed_tar)
            futs = []
            for i in range(n_agents):
                futs.append(exs[i % n_workers].submit_pool_fill(
                    f"seedwd-{i}", {
                        "agent": f"seedwd-{i}",
                        "image": "clawker-benchseed:default",
                        "loop_id": "benchseed",
                        "worker": workers[i % n_workers].id,
                        "workspace_mode": "snapshot",
                        "seed_digest": digest}))
            created = 0
            for f in futs:
                try:
                    if f.result(timeout=30.0):
                        created += 1
                except Exception:       # noqa: BLE001 -- counted below
                    pass
            amortized_wall = time.perf_counter() - t0
            cache_hits = int(_SEED_CACHE_HITS._default.peek() - hits0)
            cache_misses = int(_SEED_CACHE_MISSES._default.peek() - misses0)
            transfers = [ex.stats["seeds"] for ex in exs]
            store_hits = sum(s.stats["seed_hits"] for s in servers)
            store_misses = sum(s.stats["seed_misses"] for s in servers)
            stored = [s.stats["seeds_stored"] for s in servers]
        finally:
            inject_wan_rtt(drv, 0.0)
            for ex in exs:
                ex.close()
            for s in servers:
                s.stop()
            drv.close()
            clear_workspace_seed_cache()
    return {
        "agents": n_agents, "workers": n_workers,
        "rtt_ms": round(rtt_s * 1000),
        "baseline_wall_s": round(baseline_wall, 3),
        "amortized_wall_s": round(amortized_wall, 3),
        "amortization": round(baseline_wall / max(amortized_wall, 1e-9), 1),
        "created": created,
        "cache_hits": cache_hits, "cache_misses": cache_misses,
        "seed_transfers": transfers, "seeds_stored": stored,
        "store_hits": store_hits, "store_misses": store_misses,
        "one_transfer_per_worker": transfers == [1] * n_workers,
    }


def bench_chaos_soak(scenarios: int = CHAOS_SOAK_SCENARIOS,
                     seed: int = CHAOS_SOAK_SEED) -> dict:
    """chaos_soak: N seeded compound-fault scenarios on the 4-worker fake
    pod (worker kill/wedge/flap/slow-loris, engine 5xx bursts, probe
    drops, CLI SIGKILLs at crash seams with kill/resume cycles), each
    audited by the fleet invariant checker (docs/chaos.md).  The gate is
    ZERO invariant violations: this is the composition test for
    breakers/failover + journal/--resume + admission + warm pools +
    the sentinel riding along (stream silence/floods, collector kills)
    -- any failure is a one-command deterministic repro.  The soak ends
    with the sentinel observe-only twin check (docs/analytics-online.md)."""
    from clawker_tpu.chaos.runner import run_soak
    from clawker_tpu.testenv import lock_tracing

    # the lock-order tracer rides the soak (docs/static-analysis.md#
    # lock-order-tracer): 25 compound-fault scenarios exercise every
    # scheduler/journal/admission/pool lock from many threads, so a
    # cycle-free acquisition graph here is the deadlock-freedom gate
    with lock_tracing() as graph:
        report = run_soak(scenarios, seed, shrink=True, keep_going=False)
    cycles = graph.cycles()
    if cycles:
        print(graph.render_cycles())
    return {
        "scenarios": report["scenarios"],
        "passed": report["passed"],
        "seed": report["seed"],
        "kills": report["kills"],
        "injected": report["injected"],
        "wall_s": report["wall_s"],
        "observe_only": report.get("observe_only"),
        "lockgraph": {"acquires": graph.acquires,
                      "edges": graph.report()["edges"],
                      "cycles": len(cycles)},
        "ok": report["ok"] and not cycles,
        "failures": [
            {"scenario": f["scenario"], "violations": f["violations"],
             "repro": f["repro"],
             "minimal_events": (f.get("minimal_plan") or {}).get("events")}
            for f in report["failures"]
        ],
    }


JOURNAL_CHECKSUM_BUDGET_NS = 20_000   # per-record CRC32 trailer cost the
#                                       checksummed WAL may add over a
#                                       plain json.dumps encode -- the
#                                       integrity tax on every journal
#                                       and flight append must stay
#                                       microscopic next to the fsync it
#                                       rides with (docs/durability.md)


def bench_journal_checksum_overhead(n: int = 20_000) -> dict:
    """journal_checksum_overhead: per-record cost of the CRC32 trailer.

    Measures ``encode_record`` (serialize + crc + splice) against the
    bare ``json.dumps`` it wraps, on a realistic placement-record
    shape, and the end-to-end non-durable ``RunJournal.append`` p50 on
    tmpfs for scale.  The gate is the DELTA -- the checksum must cost
    nanoseconds-per-record, because it rides every journal and flight
    append (docs/durability.md#verify)."""
    from clawker_tpu.loop.journal import RunJournal
    from clawker_tpu.monitor.ledger import encode_record

    rec = {"kind": "placement", "seq": 12345, "ts": 1723.456789,
           "agent": "bench-agent-07", "worker": "w3", "epoch": 2}

    def _encode_ns() -> tuple[float, float]:
        t0 = time.perf_counter()
        for _ in range(n):
            json.dumps(rec, separators=(",", ":"), default=str)
        plain = (time.perf_counter() - t0) / n * 1e9
        t0 = time.perf_counter()
        for _ in range(n):
            encode_record(rec)
        full = (time.perf_counter() - t0) / n * 1e9
        return plain, full

    _encode_ns()                        # warmup
    plain_ns, full_ns = _encode_ns()
    with tempfile.TemporaryDirectory() as td:
        j = RunJournal(Path(td) / "bench.journal")
        samples = []
        for i in range(2_000):
            t0 = time.perf_counter()
            j.append("placement", agent="bench", worker="w0", epoch=i)
            samples.append(time.perf_counter() - t0)
        j.close()
    samples.sort()
    return {
        "plain_encode_ns": round(plain_ns, 1),
        "checksum_encode_ns": round(full_ns, 1),
        "overhead_ns": round(full_ns - plain_ns, 1),
        "append_p50_us": round(samples[len(samples) // 2] * 1e6, 1),
        "records": n,
    }


DISK_FULL_CHAOS_BUDGET_S = 30.0   # wall ceiling for the one-scenario
#                                   disk-fault gate: a full disk must
#                                   degrade the run, never wedge it


def bench_disk_full_chaos() -> dict:
    """disk_full_chaos: one seeded disk-fault scenario, end to end.

    A ``disk_full`` rider (docs/chaos.md#disk-faults) arms ENOSPC on
    the live journal's writes mid-run; the fleet must drain clean and
    the no-silent-drop / replay-integrity invariants must hold -- the
    degraded-durability path exercised as a perf-suite gate, not just
    in the soak's draw luck."""
    from clawker_tpu.chaos.plan import FaultEvent, FaultPlan
    from clawker_tpu.chaos.runner import ChaosRunner, _fresh_cfg

    plan = FaultPlan(seed=CHAOS_SOAK_SEED, scenario=0, n_workers=2,
                     n_loops=4, iterations=2, events=[
                         FaultEvent(at_s=0.05, kind="disk_full",
                                    worker=-1, arg=3),
                     ])
    env, cfg = _fresh_cfg()
    t0 = time.perf_counter()
    try:
        result = ChaosRunner(cfg, plan).run_scenario()
    finally:
        env.__exit__(None, None, None)
    return {
        "ok": result.ok,
        "violations": result.violations,
        "injected": result.injected,
        "wall_s": round(time.perf_counter() - t0, 2),
    }


LOOPD_SUBMIT_BUDGET_MS = 5.0  # submit frame -> submitted ack over the
#                               loopd unix socket: the per-run cost the
#                               daemon split adds on top of scheduling
#                               (ISSUE 9 acceptance; the point of a
#                               resident daemon is that hundreds of
#                               loops stop paying a CLI start-up)


def bench_loopd_submit_roundtrip(iters: int = 14) -> dict:
    """loopd_submit_roundtrip_p50: p50 milliseconds from a client's
    ``submit_run`` frame hitting the daemon socket to the ``submitted``
    ack (run registered, id assigned) -- ISSUE 9 gate <= 5ms.  Each
    submitted run is also driven to completion and its first
    ``created`` event timed, so the reported doc carries the full
    submit -> first-container picture alongside the gated hop."""
    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.fake import exit_behavior
    from clawker_tpu.loopd.client import LoopdClient
    from clawker_tpu.loopd.server import LoopdServer
    from clawker_tpu.testenv import TestEnv

    acks: list[float] = []
    createds: list[float] = []
    ok_runs = 0
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchloopd\n")
        cfg = load_config(proj)
        drv = FakeDriver(n_workers=1)
        drv.api.add_image("clawker-benchloopd:default")
        drv.api.set_behavior("clawker-benchloopd:default",
                             exit_behavior(b"done\n", 0))
        server = LoopdServer(cfg, drv).start()
        try:
            for i in range(iters + 2):      # two warmups eat lazy imports
                client = LoopdClient(server.sock_path)
                client.hello()
                t0 = time.perf_counter()
                client.submit_run({"parallel": 1, "iterations": 1})
                ack_ms = (time.perf_counter() - t0) * 1000
                first_created = None
                done_ok = False
                for frame in client.events():
                    if (frame.get("type") == "event"
                            and frame.get("event") == "created"
                            and first_created is None):
                        first_created = (time.perf_counter() - t0) * 1000
                    if frame.get("type") == "run_done":
                        done_ok = frame["ok"]
                client.close()
                if i >= 2:
                    acks.append(ack_ms)
                    if first_created is not None:
                        createds.append(first_created)
                    ok_runs += int(done_ok)
        finally:
            server.stop()
    return {
        "submit_p50_ms": round(statistics.median(acks), 3),
        "submit_max_ms": round(max(acks), 3),
        "first_created_p50_ms": round(statistics.median(createds), 3)
        if createds else 0.0,
        "iters": iters,
        "runs_ok": ok_runs,
    }


GITGUARD_PUSH_OVERHEAD_BUDGET_MS = 5.0  # p50 ms the git firewall proxy
#                               may add to a push round-trip on top of
#                               the upstream apply (ISSUE 18
#                               acceptance: protocol-aware enforcement
#                               must be invisible next to a real
#                               network push)


def bench_gitguard_push_overhead(iters: int = 60) -> dict:
    """gitguard_push_overhead: p50 milliseconds the git firewall proxy
    (docs/git-policy.md) adds to a push round-trip -- one receive-pack
    POST through the proxy's HTTP path (identity check, pkt-line
    parse, policy verdict, forward, report-status relay) versus the
    same command list applied to the upstream directly.  Gate:
    overhead p50 <= 5ms, with EVERY guarded push acknowledged (an
    overhead measured on refused pushes would be flattering and
    wrong)."""
    import http.client

    from clawker_tpu.gitguard import (
        FakeGitUpstream,
        GitguardServer,
        RefPolicy,
    )
    from clawker_tpu.gitguard.pktline import FLUSH_PKT, encode_pkt
    from clawker_tpu.gitguard.refpolicy import IDENTITY_HEADER

    def push_body(i: int) -> bytes:
        sha = format(i + 1, "040x")
        ref = "refs/heads/loop/bench/agent-0/work"
        return encode_pkt(
            f"{'0' * 40} {sha} {ref}".encode() + b"\x00report-status\n"
        ) + FLUSH_PKT

    guarded: list[float] = []
    direct: list[float] = []
    upstream = FakeGitUpstream(refs={"refs/heads/main": "a" * 40})
    srv = GitguardServer(upstream, RefPolicy(run="bench"),
                         tcp_addr=("127.0.0.1", 0))
    srv.start()
    try:
        for i in range(iters + 3):      # warmups eat lazy imports
            body = push_body(i)
            t0 = time.perf_counter()
            conn = http.client.HTTPConnection(
                "127.0.0.1", srv.port, timeout=5.0)
            conn.request(
                "POST", "/bench/git-receive-pack", body=body,
                headers={IDENTITY_HEADER: "bench/agent-0",
                         "Content-Type":
                         "application/x-git-receive-pack-request"})
            resp = conn.getresponse()
            resp.read()
            conn.close()
            g_ms = (time.perf_counter() - t0) * 1000
            # the baseline: the SAME command list applied straight to
            # the upstream (what the push costs with no guard in path)
            t1 = time.perf_counter()
            upstream.caller = "bench/agent-0"
            upstream.call("git-receive-pack", body)
            d_ms = (time.perf_counter() - t1) * 1000
            if i >= 3 and resp.status == 200:
                guarded.append(g_ms)
                direct.append(d_ms)
    finally:
        srv.close()
    acked = sum(1 for _, ident, _r in upstream.acknowledged
                if ident == "bench/agent-0")
    g50 = statistics.median(guarded) if guarded else 0.0
    d50 = statistics.median(direct) if direct else 0.0
    return {
        "guarded_p50_ms": round(g50, 3),
        "direct_p50_ms": round(d50, 3),
        "overhead_p50_ms": round(g50 - d50, 3),
        "iters": iters,
        "pushes_measured": len(guarded),
        # each loop pushes twice (guarded + baseline), so all-acked
        # means every guarded push actually landed
        "all_acked": acked >= 2 * len(guarded),
    }


def bench_cross_process_fairness(loops_per_client: int = 6,
                                 cap: int = 2) -> dict:
    """cross_process_fairness: TWO real client processes submit
    concurrent runs to ONE loopd (pack onto one slow worker).  The
    daemon-side launch high-water mark must hold the shared admission
    cap -- the exact failure PR-6's per-process controllers allowed --
    and the WFQ must interleave the tenants (both bursts overlap in
    wall time) instead of first-burst-wins (ISSUE 9 acceptance)."""
    import os
    import subprocess
    import sys

    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.fake import exit_behavior
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.loopd.server import LoopdServer
    from clawker_tpu.testenv import TestEnv

    child_src = (
        "import json, sys, time\n"
        "from clawker_tpu.loopd.client import LoopdClient\n"
        "sock, tenant, n = sys.argv[1], sys.argv[2], int(sys.argv[3])\n"
        "c = LoopdClient(sock)\n"
        "c.hello()\n"
        "c.submit_run({'parallel': n, 'iterations': 1,\n"
        "              'placement': 'pack', 'tenant': tenant})\n"
        "created, ok = [], False\n"
        "for frame in c.events():\n"
        "    if (frame.get('type') == 'event'\n"
        "            and frame.get('event') == 'created'):\n"
        "        created.append(time.time())\n"
        "    if frame.get('type') == 'run_done':\n"
        "        ok = frame['ok']\n"
        "c.close()\n"
        "print(json.dumps({'tenant': tenant, 'ok': ok,\n"
        "                  'created': created}))\n"
    )
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchloopd\n")
        cfg = load_config(proj)
        # the shared bucket's capacity is DAEMON state (settings), the
        # whole point: no client can widen it from its own process
        cfg.settings.loop.placement.max_inflight_per_worker = cap
        drv = FakeDriver(n_workers=1)
        api = drv.api
        api.add_image("clawker-benchloopd:default")
        api.set_behavior("clawker-benchloopd:default",
                         exit_behavior(b"done\n", 0))
        orig_create = api.container_create

        def slow_create(name, config):
            time.sleep(0.02)    # bursts must genuinely overlap
            return orig_create(name, config)

        api.container_create = slow_create
        server = LoopdServer(cfg, drv).start()
        env = dict(os.environ)
        env["PYTHONPATH"] = str(Path(__file__).resolve().parent)
        t0 = time.perf_counter()
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", child_src, str(server.sock_path),
                 tenant, str(loops_per_client)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
            for tenant in ("tenant-a", "tenant-b")
        ]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=120)
            outs.append((p.returncode, out, err))
        wall = time.perf_counter() - t0
        stats = server.admission.stats()
        launch_hwm = drv.gates[0].launch_hwm
        server.stop()
    results = []
    for rc, out, err in outs:
        if rc != 0:
            return {"both_ok": False, "cap": cap, "cap_respected": False,
                    "interleaved": False, "wall_s": round(wall, 3),
                    "error": err.decode(errors="replace")[-400:]}
        results.append(json.loads(out.decode()))
    by_tenant = {r["tenant"]: r for r in results}
    a, b = by_tenant["tenant-a"], by_tenant["tenant-b"]
    overlap = (a["created"] and b["created"]
               and max(a["created"][0], b["created"][0])
               < min(a["created"][-1], b["created"][-1]))
    admission_hwm = stats["workers"].get("fake-0", {}).get("inflight_hwm", 0)
    return {
        "both_ok": bool(a["ok"] and b["ok"]),
        "cap": cap,
        "daemon_launch_hwm": launch_hwm,
        "admission_inflight_hwm": admission_hwm,
        "cap_respected": launch_hwm <= cap and admission_hwm <= cap,
        "interleaved": bool(overlap),
        "loops_per_client": loops_per_client,
        "wall_s": round(wall, 3),
    }


def bench_federation_fanout_n512(n_loops: int = 512, n_pods: int = 8,
                                 per_run: int = 4, cap: int = 4,
                                 rtt_s: float = 0.005) -> dict:
    """federation_fanout_p50_n512: 512 loops routed across 8 fake pods
    by the federation router, with a deterministic DCN round-trip
    injected on every router->pod admission RPC (ISSUE 17 acceptance).

    The evidence set: every loop reaches its budget; every pod's
    daemon-side launch high-water mark holds its admission cap (the
    router's leases are flow control, never a cap bypass); and the
    capacity leases cost >= LEASE_AMORTIZATION_MIN x fewer admission
    RPCs than the per-launch baseline protocol driven over the same
    pods at the same RTT -- the zero-WAN-hop launch hot path."""
    from clawker_tpu import consts
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.fake import exit_behavior
    from clawker_tpu.federation import FederationRouter
    from clawker_tpu.federation.lease import LeaseManager
    from clawker_tpu.loopd.client import discover_all
    from clawker_tpu.loopd.server import LoopdServer
    from clawker_tpu.testenv import TestEnv

    n_runs = n_loops // per_run
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchfed\n")
        cfg = load_config(proj)
        cfg.settings.loop.placement.max_inflight_per_worker = cap
        drivers: list[FakeDriver] = []
        servers: list[LoopdServer] = []
        for i in range(n_pods):
            drv = FakeDriver(n_workers=4, prefix=f"pod{i}")
            for api in drv.apis:
                api.add_image("clawker-benchfed:default")
                api.set_behavior("clawker-benchfed:default",
                                 exit_behavior(b"done\n", 0))
            drivers.append(drv)
            servers.append(LoopdServer(
                cfg, drv,
                sock_path=tenv.base / f"pod{i}" / "loopd.sock").start())
        cfg.settings.federation.enable = True
        cfg.settings.federation.pods = [str(s.sock_path) for s in servers]
        router = FederationRouter(cfg, discover_all(cfg),
                                  control_rtt_s=rtt_s)
        reqs = [(f"tenant-{i % 4}",
                 {"parallel": per_run, "iterations": 1,
                  "tenant": f"tenant-{i % 4}"}) for i in range(n_runs)]
        t0 = time.perf_counter()
        results = router.submit_many(reqs)
        submit_wall = time.perf_counter() - t0
        lease_rpcs = router.lease.rpcs
        # drain: stamp each run as it completes (per-run latency p50)
        pending = {ack["run"] for _, ack in results}
        done_at: dict[str, float] = {}
        deadline = time.monotonic() + 120.0
        while pending and time.monotonic() < deadline:
            for srv in servers:
                with srv._runs_lock:
                    runs = list(srv.runs.items())
                for rid, run in runs:
                    if rid in pending and run.done.is_set():
                        done_at[rid] = time.perf_counter() - t0
                        pending.discard(rid)
            if pending:
                time.sleep(0.01)
        wall = time.perf_counter() - t0
        loops_done = 0
        for srv in servers:
            for run in srv.runs.values():
                if run.done.is_set() and run.result and run.result["ok"]:
                    loops_done += len(run.result["agents"])
        launch_hwm = max(g.launch_hwm for drv in drivers
                         for g in drv.gates)
        # the per-launch baseline: the SAME admission traffic (one RPC
        # per routed run, to the pod that actually hosted it) over the
        # naive protocol at the same injected RTT
        per_pod: dict[str, int] = {}
        for pod, _ack in results:
            per_pod[pod] = per_pod.get(pod, 0) + 1
        baseline = LeaseManager(tokens=1, ttl_s=1.0, amortize=False,
                                rtt_s=rtt_s)
        tb = time.perf_counter()
        for pod, count in per_pod.items():
            client = router.registry.pods[pod].client
            for _ in range(count):
                baseline.spend(pod, client)
        baseline_wall = time.perf_counter() - tb
        router.close()
        for srv in servers:
            srv.stop()
    lat = sorted(done_at.values())
    p50 = lat[len(lat) // 2] if lat else 0.0
    amortization = round(baseline.rpcs / max(lease_rpcs, 1), 1)
    return {
        "loops": n_loops, "pods": n_pods, "runs": n_runs,
        "parallel_per_run": per_run, "rtt_ms": rtt_s * 1000.0,
        "all_loops_done": loops_done == n_loops and not pending,
        "loops_done": loops_done,
        "cap": cap, "launch_hwm": launch_hwm,
        "cap_respected": launch_hwm <= cap,
        "submit_wall_s": round(submit_wall, 3),
        "fanout_p50_s": round(p50, 3),
        "fanout_wall_s": round(wall, 3),
        "lease_rpcs": lease_rpcs,
        "per_launch_rpcs": baseline.rpcs,
        "per_launch_wall_s": round(baseline_wall, 3),
        "lease_amortization": amortization,
    }


def bench_pod_failover_migrate() -> dict:
    """pod_failover_migrate_s: kill the pod hosting a live run
    mid-iteration; the router drains it onto the survivor via journal
    adoption (`migrate_pod`).  Every loop must reach its budget on the
    survivor within POD_FAILOVER_MIGRATE_BUDGET_S of the kill, with
    the federation-wide exactly-once audit green -- a duplicate create
    anywhere reads FAILED, never fast (ISSUE 17 acceptance)."""
    import threading

    from clawker_tpu import consts
    from clawker_tpu.chaos.invariants import cross_pod_exactly_once
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.federation import FederationRouter
    from clawker_tpu.loopd.client import discover_all
    from clawker_tpu.loopd.server import LoopdServer
    from clawker_tpu.testenv import TestEnv

    hold = threading.Event()

    def hold_behavior(io) -> int:
        if not hold.is_set():
            hold.wait(20.0)
        return 0

    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: benchfed\n")
        cfg = load_config(proj)
        drivers: dict[str, FakeDriver] = {}
        servers: list[LoopdServer] = []
        for name in ("pod0", "pod1"):
            drv = FakeDriver(n_workers=2, prefix=name)
            for api in drv.apis:
                api.add_image("clawker-benchfed:default")
                api.set_behavior("clawker-benchfed:default", hold_behavior)
            drivers[name] = drv
            servers.append(LoopdServer(
                cfg, drv,
                sock_path=tenv.base / name / "loopd.sock").start())
        cfg.settings.federation.enable = True
        cfg.settings.federation.pods = [str(s.sock_path) for s in servers]
        router = FederationRouter(cfg, discover_all(cfg))
        pod, ack = router.submit(
            {"parallel": 2, "iterations": 1, "tenant": "mig"})
        run_id = ack["run"]
        victim = next(s for s in servers
                      if s.sock_path.parent.name == pod)
        survivor = next(s for s in servers if s is not victim)
        creates = lambda d: sum(  # noqa: E731
            len(api.calls_named("container_create")) for api in d.apis)
        deadline = time.monotonic() + 30.0
        while creates(drivers[pod]) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        creates_before = creates(drivers[pod])
        t0 = time.perf_counter()
        victim.kill()
        moved = router.migrate_pod(pod, orphan_grace_s=0.5)
        hold.set()
        run = survivor.runs.get(run_id)
        run_ok = (run is not None and run.done.wait(30.0)
                  and bool(run.result and run.result["ok"]))
        wall = time.perf_counter() - t0
        loops_done = (len(run.result["agents"])
                      if run is not None and run.result else 0)
        violations = cross_pod_exactly_once(drivers, cfg, run_id)
        dead_created_after = creates(drivers[pod]) != creates_before
        router.close()
        survivor.stop()
    return {
        "migrate_wall_s": round(wall, 3),
        "migrated_runs": len(moved),
        "run_ok": run_ok,
        "loops_done": loops_done, "parallel": 2,
        "orphan_grace_s": 0.5,
        "dead_pod_created_after_kill": dead_created_after,
        "violations": violations,
    }


def bench_engine_dials(per_dial_delay: float = 0.01) -> dict:
    """Engine-API socket dials behind one `clawker run` orchestration.

    Records the create+start orchestration `clawker run --detach` drives
    (AgentRuntime over the fake driver; the identity-bootstrap hook,
    which would only ADD unary exec calls, needs the cryptography module
    and is left unwired), then replays its unary daemon-call sequence
    through HTTPDockerAPI over a real unix socket served by the
    keep-alive stub daemon -- once with the connection pool (default)
    and once dial-per-request (max_idle=0, the pre-pool behavior).
    Each dial pays an injected delay standing in for forwarded-stream
    setup on the SSH mux, so the wall-clock numbers show what the dial
    churn costs a TPU-VM worker endpoint.  ``dial_reduction`` is
    dials_per_request / dials_pooled (bar: >= 2x).
    """
    from clawker_tpu.config import load_config
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.engine.httpapi import HTTPDockerAPI, unix_socket_factory
    from clawker_tpu.runtime.orchestrate import AgentRuntime, CreateOptions
    from clawker_tpu.testenv import StubDockerDaemon, TestEnv

    # hijack/stream ops check out dedicated sockets by design; the replay
    # covers the unary surface the pool serves
    non_unary = {"container_attach", "container_logs", "events", "exec_start",
                 "image_build", "image_build_buildkit", "image_pull",
                 "session_attach", "close", "close_events"}
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        tenv.make_project(proj, "project: benchdials\n")
        cfg = load_config(proj)
        driver = FakeDriver()
        driver.api.add_image("clawker-benchdials:default")
        rt = AgentRuntime(driver.engine(), cfg)
        cid = rt.create(CreateOptions(agent="a0", workspace_mode="snapshot"))
        rt.start(cid)
        unary = [(n, a, k) for n, a, k in driver.api.calls
                 if n not in non_unary and hasattr(HTTPDockerAPI, n)]

    with tempfile.TemporaryDirectory(prefix="clawker-bench-dials-") as td:
        sock = Path(td) / "stub.sock"
        daemon = StubDockerDaemon(sock).start()
        try:
            def replay(pooled: bool) -> tuple[int, float, dict]:
                base = unix_socket_factory(sock)
                dials = [0]

                def counting_factory():
                    dials[0] += 1
                    time.sleep(per_dial_delay)
                    return base()

                api = HTTPDockerAPI(counting_factory,
                                    pool_max_idle=None if pooled else 0)
                t0 = time.perf_counter()
                for name, args, kw in unary:
                    if name == "put_archive":  # fake records (cid, path) only
                        api.put_archive(args[0], args[1], b"")
                    else:
                        getattr(api, name)(*args, **kw)
                wall = time.perf_counter() - t0
                stats = api.pool_stats()
                api.close()
                return dials[0], wall, stats

            dials_pooled, wall_pooled, stats = replay(True)
            dials_per_req, wall_per_req, _ = replay(False)
        finally:
            daemon.stop()
    return {
        "unary_calls": len(unary),
        "dials_pooled": dials_pooled,
        "dials_per_request": dials_per_req,
        "dial_reduction": round(dials_per_req / max(dials_pooled, 1), 1),
        "reuses": stats["reuses"],
        "stale_retries": stats["stale_retries"],
        "per_dial_delay_s": per_dial_delay,
        "wall_pooled_s": round(wall_pooled, 3),
        "wall_per_request_s": round(wall_per_req, 3),
    }


def bench_telemetry_overhead(n: int = 50_000) -> dict:
    """Per-record registry cost in nanoseconds, enabled vs disabled.

    Measures the EXACT call shape the hot paths use -- a labeled counter
    child resolved per record (engine pool dials) and a labeled
    histogram observe (lane queue/execute, request latency) -- on a
    private registry so a concurrently-imported subsystem can't skew
    the sample.  ``disabled_ns`` is the same loop after
    ``set_enabled(False)``: the cost instrumentation adds to a process
    that opted out.
    """
    from clawker_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    counter = reg.counter("bench_records_total", "bench", labels=("worker",))
    hist = reg.histogram("bench_latency_seconds", "bench", labels=("worker",))

    def run_once() -> float:
        t0 = time.perf_counter()
        for i in range(n):
            counter.labels("w0").inc()
            hist.labels("w0").observe(0.003)
        return (time.perf_counter() - t0) / (2 * n) * 1e9

    run_once()                      # warm the child cache + JIT-less warmup
    enabled_ns = run_once()
    reg.set_enabled(False)
    disabled_ns = run_once()
    reg.set_enabled(True)
    return {
        "enabled_ns": round(enabled_ns, 1),
        "disabled_ns": round(disabled_ns, 1),
        "records": 2 * n,
    }


def bench_tracing_overhead(n: int = 5_000) -> dict:
    """Per-span distributed-tracing cost in nanoseconds, split into the
    two quantities the tracing design budgets separately
    (docs/tracing.md#overhead):

    - ``propagate_ns``: the pure context plumbing every traced RPC hop
      pays -- parse the inbound traceparent, mint a child context,
      serialize the outbound header.  Rides frames already being sent,
      so this IS the whole propagation cost.
    - ``record_ns``: propagate plus recording one SpanRecord through
      the context sink into a real flight recorder (json + append +
      flush per record -- the durability the recorder exists for).
    """
    import tempfile

    from clawker_tpu.monitor.ledger import FlightRecorder
    from clawker_tpu.tracing.context import TraceContext

    header = TraceContext("benchrun0123", "a1b2c3d4e5f60718").to_header()

    def propagate_once() -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            ctx = TraceContext.from_header(header)
            ctx.child().to_header()
        return (time.perf_counter() - t0) / n * 1e9

    with tempfile.TemporaryDirectory() as td:
        flight = FlightRecorder(Path(td) / "bench-trace.jsonl")
        # child() inherits the parent's sink, matching the real hop
        # shape: the daemon holds one sink-bearing context per run and
        # mints a child per recorded span
        parent = TraceContext(
            "benchrun0123", "a1b2c3d4e5f60718", agent="bench",
            worker="w0", sink=lambda rec: flight.append(rec.to_json()))

        def record_loop() -> float:
            t0 = time.perf_counter()
            for _ in range(n):
                ctx = TraceContext.from_header(header)
                ctx.child().to_header()
                parent.child().record(
                    "engine.request", t0, t0 + 0.001, verb="GET",
                    path="/ping")
            return (time.perf_counter() - t0) / n * 1e9

        propagate_once()            # warmup
        propagate_ns = propagate_once()
        record_loop()               # warmup (file + page cache)
        record_ns = record_loop()
        flight.close()
    return {
        "propagate_ns": round(propagate_ns, 1),
        "record_ns": round(record_ns, 1),
        "spans": n,
    }


def _trace_merge_fixture(agents: int = 256, iterations: int = 2) -> dict:
    """Synthetic 4-process recorder set for one run: router + loopd
    submit hops, the scheduler's iteration trees (ctx_parent-linked),
    and workerd's remote segments (skewed, parentless -- the launch
    path), shaped exactly like the real recorder files."""
    from clawker_tpu.telemetry.spans import SpanRecord

    run = "benchmergerun"
    t = 1_722_700_000.0
    router = [SpanRecord(
        trace_id=run, span_id="rtr0", parent_id="", name="router.submit",
        agent="", worker="front", t_start=t, t_end=t + 0.05,
        attrs={"pod": "pod-a", "wan_ms": 50.0})]
    loopd = [SpanRecord(
        trace_id=run, span_id="lpd0", parent_id="", name="loopd.submit",
        agent="", worker="pod-a", t_start=t + 0.02, t_end=t + 0.04,
        attrs={"ctx_parent": "rtr0", "skew_s": 0.002})]
    sched: list = []
    workerd: list = []
    for a in range(agents):
        agent = f"loop-bench-{a:03d}"
        for it in range(iterations):
            base = t + 0.1 + it * 0.5 + (a % 7) * 0.01
            root_id = f"it{a:03d}x{it}"
            sched.append(SpanRecord(
                trace_id=run, span_id=root_id, parent_id="",
                name="iteration", agent=agent, worker=f"w{a % 4}",
                t_start=base, t_end=base + 0.4,
                attrs={"iteration": it, "ctx_parent": "lpd0"}))
            for j, phase in enumerate(("create", "start", "wait")):
                sched.append(SpanRecord(
                    trace_id=run, span_id=f"{root_id}p{j}",
                    parent_id=root_id, name=phase, agent=agent,
                    worker=f"w{a % 4}", t_start=base + j * 0.1,
                    t_end=base + (j + 1) * 0.1,
                    attrs={"iteration": it, "workerd": True}))
            for j, phase in enumerate(("workerd.create", "workerd.start")):
                workerd.append(SpanRecord(
                    trace_id=run, span_id=f"{root_id}w{j}", parent_id="",
                    name=phase, agent=agent, worker=f"w{a % 4}",
                    t_start=base + 0.003 + j * 0.1,
                    t_end=base + 0.003 + (j + 1) * 0.1,
                    attrs={"iteration": it, "skew_s": 0.003}))
    return {"run": run, "sources": {
        "router:router-front": router, "loopd:loopd-pod-a": loopd,
        "scheduler": sched, "workerd:workerd-w0": workerd}}


def bench_trace_merge(agents: int = 256) -> dict:
    """Wall time to merge one run's 4-process recorder set at fleet
    scale (256 agents x 2 iterations: ~2.5k spans) into the causal
    forest `clawker trace` renders -- skew adjustment, cross-recorder
    linking, gap synthesis, monotonicity audit included."""
    from clawker_tpu.tracing.merge import merge_records

    fx = _trace_merge_fixture(agents=agents)
    merge_records(fx["sources"], fx["run"])     # warmup
    t0 = time.perf_counter()
    res = merge_records(fx["sources"], fx["run"])
    wall = time.perf_counter() - t0
    rooted = len(res.roots)
    return {
        "agents": agents,
        "spans": res.spans,
        "roots": rooted,
        "gaps": res.gaps,
        "skew_suspects": res.skew_suspects,
        "one_rooted_tree": rooted == 1,     # everything under the router
        "merge_wall_s": round(wall, 4),
    }


CONSOLE_REPAINT_BUDGET_MS = 50.0    # p95 frame build+paint at 256 agents
#                                     across 4 hosted runs (fleet console,
#                                     docs/fleet-console.md#repaint-budget)
CONSOLE_FRAME_LINE_BOUND = 140      # row virtualization must bound the
#                                     frame no matter the agent count
INGEST_LAG_BUDGET_S = 1.0           # typed bus event -> searchable doc on
#                                     the fake bulk index (shipper tick
#                                     cadence + batch seal + flush)


def _console_status_doc(runs: int, per_run: int, tick: int,
                        statuses: dict) -> dict:
    """Synthetic loopd status RPC doc shaped like LoopdServer._status_doc
    (the console feed's input contract) for `runs` hosted runs of
    `per_run` agents each."""
    workers = [f"w{i}" for i in range(4)]
    run_docs = []
    for r in range(runs):
        agents = []
        for i in range(per_run):
            status, iteration = statuses.get((r, i), ("running", 1))
            agents.append({
                "agent": f"loop-r{r}-{i:03d}", "worker": workers[i % 4],
                "status": status, "iteration": iteration,
                "exit_codes": [0],
                **({"anomaly_z": 4.2} if i == 7 else {}),
            })
        run_docs.append({
            "run": f"run{r:02d}", "state": "running", "tenant": f"t{r}",
            "client": "bench", "parallel": per_run, "iterations": 4,
            "placement": "spread", "agents": agents, "subscribers": 1,
            "events_dropped": tick % 3,
        })
    return {
        "pid": 4242, "project": "bench", "uptime_s": float(tick),
        "runs": run_docs,
        "admission": {
            "workers": {w: {"inflight": 2, "capacity": 4, "pending": 0,
                            "inflight_hwm": 3, "dispatched": 40 + tick,
                            "rejected": 0} for w in workers},
            "tenants": {f"t{r}": {"weight": 1.0, "inflight": 2,
                                  "queued": 1, "dispatched": 10 + tick}
                        for r in range(runs)},
        },
        "health": [{"worker": w, "state": "closed",
                    "breaker_state_gauge": 0, "probe_p50_ms": 1.2,
                    "probe_p95_ms": 2.0, "probes": 100 + tick,
                    "probe_failures": 0, "orphaned": 0,
                    "migrations_out": 0, "migrations_in": 0,
                    "last_error": ""} for w in workers],
        "workerd": {w: "ok" for w in workers},
        "warm_pools": {"run00": {"target_depth": 2, "hits": 9, "misses": 1,
                                 "refills": 3, "recycled": 0,
                                 "workers": {w: {"ready": 2, "inflight": 0}
                                             for w in workers}}},
        "sentinel": {"enabled": True, "ticks": tick, "rows": []},
        "shipper": {"enabled": True, "ingested_docs": 100 * tick,
                    "pending_batches": 0, "dropped_docs": 0},
        "events_dropped_total": tick % 3,
    }


def bench_console_repaint(agents: int = 256, runs: int = 4,
                          frames: int = 80) -> dict:
    """Fleet-console repaint cost at the acceptance shape: 256 agents
    across 4 hosted runs, a handful of rows changing per tick, span
    waterfalls tailed from a real flight file.

    Measures per-frame wall (feed normalize + frame build + damage
    paint into a buffer) and the damage ratio (rows rewritten / rows
    total) -- virtualization must bound the frame and damage tracking
    must keep idle rows free."""
    from clawker_tpu.loopd.feed import console_feed
    from clawker_tpu.telemetry.spans import SpanRecord
    from clawker_tpu.ui.fleetconsole import FleetConsole
    from clawker_tpu.ui.iostreams import IOStreams

    per_run = agents // runs
    statuses: dict = {}
    tick = [0]

    with tempfile.TemporaryDirectory(prefix="clawker-console-bench-") as td:
        logs = Path(td)
        # a real flight file for run00: the waterfall path must be on
        # the measured frame, not just the table
        from clawker_tpu.monitor.ledger import flight_path

        fpath = flight_path(logs, "run00")
        fpath.parent.mkdir(parents=True, exist_ok=True)
        with open(fpath, "w", encoding="utf-8") as fh:
            for i in range(64):
                root = SpanRecord(
                    trace_id="run00", span_id=f"s{i}", parent_id="",
                    name="iteration", agent=f"loop-r0-{i % 8:03d}",
                    worker=f"w{i % 4}", t_start=float(i),
                    t_end=float(i) + 0.5, attrs={"iteration": i})
                child = SpanRecord(
                    trace_id="run00", span_id=f"c{i}", parent_id=f"s{i}",
                    name="wait", agent=root.agent, worker=root.worker,
                    t_start=float(i) + 0.1, t_end=float(i) + 0.4,
                    attrs={"iteration": i})
                fh.write(json.dumps(root.to_json()) + "\n")
                fh.write(json.dumps(child.to_json()) + "\n")

        def feed_fn() -> dict:
            return console_feed(_console_status_doc(
                runs, per_run, tick[0], statuses))

        streams, _, out, _ = IOStreams.test()
        console = FleetConsole(streams, feed_fn, logs_dir=logs)
        samples = []
        for f in range(frames):
            tick[0] = f
            # 8 rows change per frame -- the steady-state churn shape
            for j in range(8):
                statuses[(j % runs, (f + j) % per_run)] = (
                    "running" if (f + j) % 5 else "done", f)
            t0 = time.perf_counter()
            console.render_once()
            samples.append((time.perf_counter() - t0) * 1000)
            out.truncate(0)
            out.seek(0)
        frame_lines = len(console.frame_lines(feed_fn()))
        stats = console.painter.stats()
    samples.sort()
    return {
        "agents": agents, "runs": runs, "frames": frames,
        "frame_p50_ms": round(samples[len(samples) // 2], 2),
        "frame_p95_ms": round(samples[int(len(samples) * 0.95) - 1], 2),
        "frame_lines": frame_lines,
        "bounded": frame_lines <= CONSOLE_FRAME_LINE_BOUND,
        "rows_total": stats["rows_total"],
        "rows_painted": stats["rows_painted"],
        "damage_ratio": round(
            stats["rows_painted"] / max(1, stats["rows_total"]), 3),
    }


def bench_ingest_lag(bursts: int = 20, per_burst: int = 10) -> dict:
    """Docs/search lag on the fake monitor stack: typed bus events
    emitted -> searchable in the fake bulk index through the shipper's
    seal/flush cadence.  Completeness is part of the gate -- a healthy
    index must receive every doc."""
    from clawker_tpu.monitor.events import PLACEMENT_DECISION, EventBus
    from clawker_tpu.monitor.shipper import (
        FLEET_EVENTS_INDEX,
        TelemetryShipper,
    )
    from clawker_tpu.telemetry import MetricsRegistry
    from clawker_tpu.testenv import FakeBulkIndex

    idx = FakeBulkIndex()
    shipper = TelemetryShipper(idx, registry=MetricsRegistry(),
                               interval_s=0.05, batch_docs=64,
                               max_batches=32, source="bench").start()
    bus = EventBus()
    bus.add_tap(shipper.bus_tap_for("bench-run"))
    lags = []
    emitted = 0
    try:
        for _ in range(bursts):
            t0 = time.perf_counter()
            last_seq = 0
            for i in range(per_burst):
                rec = bus.emit(f"agent-{i}", PLACEMENT_DECISION,
                               "placed w0 [spread/bench]")
                last_seq = rec.seq
            emitted += per_burst
            deadline = time.perf_counter() + 5.0
            while time.perf_counter() < deadline:
                if idx.search(FLEET_EVENTS_INDEX, seq=last_seq):
                    break
                time.sleep(0.002)
            lags.append(time.perf_counter() - t0)
    finally:
        shipper.stop()
    lags.sort()
    indexed = idx.count(FLEET_EVENTS_INDEX)
    return {
        "bursts": bursts, "docs_emitted": emitted,
        "docs_indexed": indexed,
        "complete": indexed == emitted,
        "lag_p50_s": round(lags[len(lags) // 2], 3),
        "lag_p95_s": round(lags[int(len(lags) * 0.95) - 1], 3),
        "dropped": shipper.stats()["dropped_docs"],
    }


def synth_egress_records(agents: int = 8, windows: int = 64,
                         per_window: int = 40) -> list[dict]:
    """Deterministic synthetic netlogger stream: `agents` containers with
    plausible verdict/port mixes across `windows` minutes."""
    verdicts = ["ALLOW", "ALLOW", "ALLOW", "REDIRECT", "DENY"]
    reasons = {"ALLOW": "ROUTE", "REDIRECT": "ROUTE", "DENY": "NO_DNS_ENTRY"}
    base = 1_700_000_000
    out = []
    for a in range(agents):
        for w in range(windows):
            for i in range(per_window):
                ts = base + w * 60 + (i * 7) % 60
                v = verdicts[(a + w + i) % len(verdicts)]
                out.append({
                    "@timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime(ts)),
                    "service": "ebpf-egress",
                    "container": f"clawker.loop-{a}",
                    "dst_ip": f"198.51.100.{(a * 13 + i) % 250}",
                    "dst_port": [443, 443, 80, 53, 8443][(w + i) % 5],
                    "proto": 6 if i % 5 else 17,
                    "verdict": v,
                    "reason": reasons[v],
                    "zone": f"z{(a + i) % 6}.example.com",
                })
    return out


_ANOMALY_CHILD = """
import json, sys
if "--cpu" in sys.argv:
    import jax
    jax.config.update("jax_platforms", "cpu")
from bench import synth_egress_records
from clawker_tpu.analytics import runtime as art
if "--small" in sys.argv:
    records = synth_egress_records(agents=4, windows=24, per_window=20)
    out = art.bench_lane(records, train_steps=40, reps=10)
else:
    out = art.bench_lane(synth_egress_records())
print("BENCHJSON " + json.dumps(out))
"""


def bench_anomaly(device_budget_s: float = 240.0) -> dict:
    """TPU analytics lane: featurize a fleet stream, fit the autoencoder,
    and measure the steady-state score step on the accelerator
    (BASELINE: net-new lane; budget 5 ms/step on a [512, 32] fleet
    batch -- the whole-pod scoring cadence).  Runs the PRODUCT pipeline
    (analytics.runtime: denoising fit + jit-cached score), so the number
    cannot drift from what `monitor anomalies` / AnomalyWatch execute.

    Every attempt runs in a bounded subprocess -- a tunneled remote
    backend (axon) can take unbounded time just COMPILING, and a wedged
    bench is worse than a CPU-measured one.  Degradation ladder
    (MULTICHIP r05 fix -- the device leg once ate the WHOLE suite
    budget and the run died rc=124 with nothing reported):

    1. full problem on the accelerator, 1/2 of ``device_budget_s``;
    2. reduced problem on the accelerator, 1/4 of the budget -- a slow
       device still gets measured ON DEVICE, flagged ``degraded``;
    3. CPU fallback on the SAME reduced problem (a CPU that earns this
       rung is slower than the device that just failed rung 2 -- the
       full-size workload would need the old 600 s allowance), bounded
       by the remaining 1/4 (floor 60 s), flagged ``degraded`` with the
       fallback reason in ``device``.

    Worst case the ladder spends half + a quarter + the CPU rung's
    ``max(60s, quarter)`` of ``device_budget_s`` -- exactly
    ``device_budget_s`` at the 240 s default, and bounded by it plus
    the 60 s floor for smaller budgets; whichever rung lands is
    labeled, so the record always says which device and problem size
    produced the number."""
    import subprocess
    import sys

    here = str(Path(__file__).resolve().parent)
    failures: list[str] = []
    ladder = (
        (["--dev"], device_budget_s * 0.5, "device/full"),
        (["--dev", "--small"], device_budget_s * 0.25, "device/small"),
        (["--cpu", "--small"], max(60.0, device_budget_s * 0.25), "cpu"),
    )
    for args, budget, leg in ladder:
        try:
            res = subprocess.run(
                [sys.executable, "-c", _ANOMALY_CHILD, *args],
                capture_output=True, text=True, timeout=budget, cwd=here)
        except subprocess.TimeoutExpired:
            failures.append(f"{leg}: exceeded {budget:.0f}s budget")
            continue
        doc = None
        for line in res.stdout.splitlines():
            if line.startswith("BENCHJSON "):
                try:
                    doc = json.loads(line[len("BENCHJSON "):])
                except ValueError:
                    pass
        if res.returncode == 0 and doc is not None:
            doc["leg"] = leg
            doc["degraded"] = leg != "device/full"
            if doc["degraded"]:
                doc["device"] += f" (degraded: {'; '.join(failures)})"
            return doc
        failures.append(
            f"{leg}: rc={res.returncode} "
            f"{(res.stderr or res.stdout).strip()[-200:]}")
    return {"windows": 0, "featurize_ms": 0.0, "train_ms": 0.0,
            "train_steps": 0, "score_step_us": 0.0, "leg": "none",
            "degraded": True, "device": "unavailable",
            "error": "; ".join(failures)}


_SENTINEL_FLAG_CHILD = """
import json, sys, time, tempfile
from pathlib import Path
import jax
jax.config.update("jax_platforms", "cpu")
from bench import synth_egress_records
from clawker_tpu.monitor.events import ANOMALY_FLAG, EventBus
from clawker_tpu.sentinel import FleetSentinel, StreamCollector

BASE = 1_700_000_000
REPS = 5
lat = []
total_flags = 0
for rep in range(REPS):
    # one seeded incident per fresh sentinel: append -> flag latency at
    # steady state (the jit cache is warm after rep 0's prewarm tick,
    # like tick N>1 of a long-running sentinel)
    tmp = Path(tempfile.mkdtemp())
    recs = synth_egress_records(agents=8, windows=6, per_window=16)
    with open(tmp / "w0.jsonl", "w") as f0, open(tmp / "w1.jsonl", "w") as f1:
        for i, r in enumerate(recs):
            r["worker"] = f"fake-{i % 2}"
            (f0 if i % 2 == 0 else f1).write(json.dumps(r) + chr(10))
    col = StreamCollector()
    col.add_local("fake-0", tmp / "w0.jsonl")
    col.add_local("fake-1", tmp / "w1.jsonl")

    class Cfg:
        logs_dir = tmp

    flags = {}
    bus = EventBus(lambda agent, ev, detail:
                   flags.setdefault(agent, time.perf_counter())
                   if ev == ANOMALY_FLAG else None)
    s = FleetSentinel(Cfg(), interval_s=0.05, train_steps=40, window_s=60,
                      collector=col)
    s.bind_run(events=bus)
    s.refresh_once(); s.refresh_once()      # compile (rep 0) + baselines
    s.start()
    agent = "clawker.hot"
    t0 = time.perf_counter()
    with open(tmp / "w1.jsonl", "a") as f:
        for i in range(60):
            ts = BASE + 2 * 60 + i % 59
            f.write(json.dumps({
                "@timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime(ts)),
                "container": agent, "worker": "fake-1",
                "dst_ip": f"203.0.113.{i}", "dst_port": 4444 + i,
                "proto": 6, "verdict": "DENY", "reason": "NO_DNS_ENTRY",
                "zone": "",
            }) + chr(10))
    deadline = t0 + 10.0
    while agent not in flags and time.perf_counter() < deadline:
        time.sleep(0.005)
    s.stop()
    bus.close()
    lat.append(flags.get(agent, deadline) - t0)
    total_flags += len(flags)
lat.sort()
print("BENCHJSON " + json.dumps({
    "flag_latency_p50_s": round(lat[len(lat) // 2], 3),
    "flag_latency_max_s": round(lat[-1], 3),
    "flags": total_flags, "reps": REPS,
    "workers_fused": 2,
}))
"""


_SENTINEL_TICK_CHILD = """
import json, time
import jax
jax.config.update("jax_platforms", "cpu")
from bench import synth_egress_records
from clawker_tpu.sentinel import ScoringEngine, featurize_fused

recs = synth_egress_records(agents=64, windows=4, per_window=16)
for i, r in enumerate(recs):
    r["worker"] = f"fake-{i % 4}"
keys, X, worker_of = featurize_fused(recs, None)
eng = ScoringEngine(train_steps=40)
rep = eng.score_tick(keys, X, worker_of)    # warm: compile
ticks = []
for _ in range(3):
    t0 = time.perf_counter()
    rep = eng.score_tick(keys, X, worker_of)
    ticks.append(time.perf_counter() - t0)
ticks.sort()
agents = len({k.agent for k in rep.keys})
print("BENCHJSON " + json.dumps({
    "windows": rep.windows, "agents": agents,
    "tick_p50_s": round(ticks[len(ticks) // 2], 3),
    "train_ms": round(rep.train_ms, 1),
    "score_ms": round(rep.score_ms, 1),
    "device": rep.device,
}))
"""


def _run_bench_child(code: str, budget_s: float) -> dict:
    """Run a jax-using bench body in a bounded CPU-pinned subprocess
    (the bench_anomaly pattern): a wedged accelerator runtime must cost
    the budget, never the whole suite."""
    import os
    import subprocess
    import sys

    here = str(Path(__file__).resolve().parent)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # never touch the TPU tunnel
    try:
        res = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=budget_s, cwd=here, env=env)
    except subprocess.TimeoutExpired:
        return {"error": f"exceeded {budget_s:.0f}s budget"}
    for line in res.stdout.splitlines():
        if line.startswith("BENCHJSON "):
            try:
                return json.loads(line[len("BENCHJSON "):])
            except ValueError:
                pass
    return {"error": f"rc={res.returncode} "
                     f"{(res.stderr or res.stdout).strip()[-300:]}"}


def bench_anomaly_flag_latency() -> dict:
    """anomaly_flag_latency_p50: egress record appended to a worker
    stream -> typed ``anomaly.flag`` observable on the event bus, with
    the sentinel ticking live over TWO fused worker streams on the fake
    pod (docs/analytics-online.md).  A seeded deny-storm/exotic-port
    agent per rep; gate p50 <= ANOMALY_FLAG_LATENCY_BUDGET_S -- the
    security signal must land while the behavior is still happening."""
    return _run_bench_child(_SENTINEL_FLAG_CHILD, 180.0)


def bench_anomaly_fleet_score_tick() -> dict:
    """anomaly_fleet_score_tick: 64 agents' open windows (the fused
    40-dim extended ABI) scored in ONE sharded fit/score program --
    the sentinel's steady-state tick, compile excluded (the persistent
    cache + stable padded shapes make tick 1 the only compile)."""
    return _run_bench_child(_SENTINEL_TICK_CHILD, 180.0)


def previous_round_p50() -> float:
    """The newest committed BENCH_r*.json's headline value (ms), or 0."""
    import re

    best = (0, 0.0)
    for p in Path(__file__).resolve().parent.glob("BENCH_r*.json"):
        m = re.match(r"BENCH_r(\d+)\.json$", p.name)
        if not m:
            continue
        try:
            doc = json.loads(p.read_text())
            if doc.get("rc", 0) != 0:
                continue  # a failed round never becomes the baseline
            # driver wrapper format: the bench line lives in "tail"
            if "value" not in doc and "tail" in doc:
                doc = json.loads(doc["tail"])
            if "regression" in doc:
                continue  # nor does a round that tripped the gate
            val = float(doc.get("value", 0.0))
        except (OSError, ValueError):
            continue
        rnd = int(m.group(1))
        if rnd > best[0] and val > 0:
            best = (rnd, val)
    return best[1]


ELASTIC_CS_SLACK = 1.10       # a static config counts as "within the
#                               adaptive run's container-second budget"
#                               up to this slack -- the comparison set
#                               the adaptive p99 must beat outright
ELASTIC_CREATE_S = 0.03       # simulated cold create / refill cost
ELASTIC_ADOPT_S = 0.002       # simulated warm-pool adoption cost


def bench_elastic_vs_static_p99(cycles: int = 3) -> dict:
    """elastic_vs_static_p99: the elastic-capacity acceptance bench
    (ISSUE 14 / docs/elastic-capacity.md).

    One bursty OPEN-LOOP arrival trace (arrivals land on schedule no
    matter how backed up the queue is -- production traffic does not
    wait) is replayed against the real AdmissionController + WarmPool
    under five capacity configs: static pool depths {0, 2, 8, 16} with the
    static token bucket, and the adaptive config -- a live
    :class:`~clawker_tpu.capacity.CapacityController` sizing each
    worker's pool from the EWMA arrival rate and scaling token caps
    against a latency SLO.  Per config the bench measures the p99
    admission wait (submit -> dispatch) over the measured window (the
    first burst cycle is controller warmup, identical for every
    config) and the container-seconds spent: create work (cold +
    refill + adopt) plus pool-member idle seconds.

    The gate: the adaptive run must beat EVERY static config whose
    container-seconds fit inside the adaptive budget (x ELASTIC_CS_
    SLACK) on p99 admission wait, while itself spending no more than
    the most expensive static config -- i.e. adaptive sizing
    dominates the static frontier at equal container-seconds.
    """
    import threading

    from clawker_tpu import telemetry
    from clawker_tpu.capacity import CapacityController, CapacityHooks
    from clawker_tpu.config.schema import CapacitySettings, CapacitySloSettings
    from clawker_tpu.engine.drivers import Worker
    from clawker_tpu.loop.warmpool import POOL_TENANT, WarmPool
    from clawker_tpu.placement import AdmissionController

    n_workers = 2
    static_cap = 2
    # the trace: warmup cycle + `cycles` measured burst/quiet cycles +
    # a long quiet tail (where adaptive depth decays and static-deep
    # keeps paying idle members).  The burst rate deliberately exceeds
    # the static fleet's create throughput (workers x cap / CREATE_S
    # ~ 133/s), so a backlog genuinely builds -- only pre-stocked pool
    # depth and SLO-scaled tokens can hold the p99 down
    burst = (0.4, 300.0)            # (seconds, arrivals/second)
    quiet = (0.6, 5.0)
    tail = (1.6, 2.0)

    def run_config(name: str, depth: int, adaptive: bool) -> dict:
        telemetry.REGISTRY.reset()
        workers = [Worker(id=f"bw{i}", index=i, hostname=f"bw{i}",
                          engine=None) for i in range(n_workers)]
        adm = AdmissionController(max_inflight_per_worker=static_cap,
                                  max_pending_per_worker=100_000)
        # clock=perf_counter: member idle time is measured against
        # perf_counter below, and the pool's default monotonic clock
        # shares no epoch with it on every platform
        pool = WarmPool(f"bench-{name}", depth=depth, max_age_s=600.0,
                        clock=time.perf_counter)
        adm.register_tenant(POOL_TENANT, weight=0.25)
        adm.register_tenant("bench", weight=1.0)
        lock = threading.Lock()
        stats = {"idle_s": 0.0, "hits": 0, "misses": 0, "refills": 0,
                 "outstanding": 0, "rejected": 0}
        waits: list[tuple[float, bool]] = []    # (wait_s, measured)
        measuring = [False]
        stop = threading.Event()

        def arrival(worker_id: str) -> None:
            t_submit = time.perf_counter()
            flag = measuring[0]

            def dispatch(release) -> None:
                waits.append((time.perf_counter() - t_submit, flag))
                entry = pool.checkout(worker_id, by="arrival", epoch=0)

                def work() -> None:
                    if entry is not None:
                        time.sleep(ELASTIC_ADOPT_S)
                        with lock:
                            stats["hits"] += 1
                            stats["idle_s"] += max(
                                0.0, time.perf_counter() - entry.created_at)
                    else:
                        time.sleep(ELASTIC_CREATE_S)
                        with lock:
                            stats["misses"] += 1
                    release()
                    with lock:
                        stats["outstanding"] -= 1

                threading.Thread(target=work, daemon=True).start()

            with lock:
                stats["outstanding"] += 1
            st = adm.submit(worker_id, "bench", dispatch)
            if st == "rejected":
                # a shed rejection answers immediately with a backoff;
                # for the p99 comparison it is billed as a wait of its
                # own retry_after (the honest client-experienced delay)
                # so shedding can never game the gate
                waits.append((getattr(st, "retry_after_s", 0.25), flag))
                with lock:
                    stats["outstanding"] -= 1
                    stats["rejected"] += 1

        def refill_pump() -> None:
            seq = [0]
            while not stop.is_set():
                for w in workers:
                    while pool.want(w.id) > 0:
                        agent = pool.begin_refill(w)
                        if agent is None:
                            break
                        seq[0] += 1
                        cid = f"cid{seq[0]}"

                        def dispatch(release, w=w, agent=agent, cid=cid):
                            def fill() -> None:
                                time.sleep(ELASTIC_CREATE_S)
                                with lock:
                                    stats["refills"] += 1
                                pool.fill_done(w, agent, cid)
                                release()

                            threading.Thread(target=fill,
                                             daemon=True).start()

                        adm.submit(w.id, POOL_TENANT, dispatch)
                time.sleep(0.002)

        controller = None
        tick_stop = threading.Event()
        if adaptive:
            controller = CapacityController(
                CapacitySettings(
                    enable=True, interval_s=0.02, pool_min_depth=0,
                    pool_max_depth=8, alpha_up=0.6, alpha_down=0.15,
                    token_max=16,
                    slo=CapacitySloSettings(default_s=0.1)),
                hooks=CapacityHooks(
                    workers=lambda: [w.id for w in workers],
                    admission_stats=adm.stats,
                    set_token_cap=adm.set_worker_capacity,
                    set_shed=adm.set_shed,
                    pool_stats=pool.stats,
                    set_pool_target=pool.set_target,
                ))

            def ticker() -> None:
                while not tick_stop.wait(0.02):
                    controller.tick()

            threading.Thread(target=ticker, daemon=True).start()

        pump = threading.Thread(target=refill_pump, daemon=True)
        pump.start()

        def play(phase: tuple[float, float]) -> None:
            duration, rate = phase
            period = 1.0 / rate
            t_end = time.perf_counter() + duration
            i = 0
            while time.perf_counter() < t_end:
                arrival(workers[i % n_workers].id)
                i += 1
                # open loop: the NEXT arrival lands on schedule no
                # matter how deep the queue got
                time.sleep(period)

        play(burst)                     # controller warmup (unmeasured)
        play(quiet)
        measuring[0] = True
        for _ in range(cycles):
            play(burst)
            play(quiet)
        play(tail)
        # drain: every admitted launch completes (the waits list is
        # only appended at dispatch, so a straggler still counts)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with lock:
                if stats["outstanding"] == 0:
                    break
            time.sleep(0.01)
        stop.set()
        tick_stop.set()
        pump.join(1.0)
        # leftover ready members keep costing idle until teardown
        t_end = time.perf_counter()
        leftovers = 0
        pool.begin_drain()
        for w in workers:
            for entry in pool.drain_worker(w.id):
                leftovers += 1
                with lock:
                    stats["idle_s"] += max(0.0, t_end - entry.created_at)
        measured = sorted(w for w, flag in waits if flag)
        p99 = (measured[min(len(measured) - 1,
                            int(0.99 * len(measured)))]
               if measured else 0.0)
        cs = (stats["idle_s"]
              + ELASTIC_CREATE_S * (stats["misses"] + stats["refills"])
              + ELASTIC_ADOPT_S * stats["hits"])
        return {
            "config": name,
            "p99_wait_ms": round(p99 * 1000, 2),
            "container_seconds": round(cs, 3),
            "arrivals": len(measured),
            "hits": stats["hits"], "misses": stats["misses"],
            "refills": stats["refills"], "rejected": stats["rejected"],
            "leftover_members": leftovers,
        }

    statics = [run_config(f"static-{d}", d, adaptive=False)
               for d in (0, 2, 8, 16)]
    adaptive = run_config("adaptive", 0, adaptive=True)
    budget = adaptive["container_seconds"] * ELASTIC_CS_SLACK
    comparable = [s for s in statics if s["container_seconds"] <= budget]
    beats = (bool(comparable)
             and all(adaptive["p99_wait_ms"] < s["p99_wait_ms"]
                     for s in comparable)
             and adaptive["container_seconds"]
             <= max(s["container_seconds"] for s in statics))
    best_static = min(
        (s for s in comparable), key=lambda s: s["p99_wait_ms"],
        default=None)
    return {
        "beats_static": beats,
        "adaptive": adaptive,
        "statics": statics,
        "best_comparable_static": best_static,
        "cs_budget": round(budget, 3),
    }


POLL_COST_BUDGET = 12.0       # control-plane calls per agent iteration
FANOUT64_BUDGET_S = 10.0      # submit -> 64th created on the 4-worker fake
#                               pod with admission enabled (ISSUE 6)
STAMPEDE_BUDGET_S = 20.0      # 64-loop burst against one slow worker must
#                               drain to budget without tripping its breaker
FAILOVER_BUDGET_S = 5.0       # worker death -> first migrated iteration
RESUME_BUDGET_S = 5.0         # --resume invocation -> all loops live again
#                               (adoption path; must undercut the 10 s
#                               cold-start budget or resuming would be
#                               no better than starting over)
WARM_POOL_HIT_BUDGET_MS = 1.0  # framework time of a warm-pool hit
#                               (checkout + relabel/env-fixup/rename +
#                               warm identity + engine_start) -- vs the
#                               8.95ms cold p50 at r05, with harness
#                               seed + leaf minting off the hit path
WARM_POOL_BURST_BUDGET_S = 10.0  # pool-enabled full fan-out burst must
#                               drain within the cold-start fan-out
#                               budget AND leave every pool refilled:
#                               refills never starve live placements
PARITY_WALL_BUDGET_S = 10.0   # parallel parity suite wall (serial was
#                               20.5s at BENCH_r05: the bounded worker
#                               pool must hold >= 2x)
TELEMETRY_BUDGET_NS = 20_000  # per-record registry cost, enabled (a
#                               run() orchestration makes O(100) records:
#                               20us/record keeps the total well under
#                               1% of the 8.95ms cold-start headline)
TELEMETRY_DISABLED_BUDGET_NS = 4_000   # disabled = one attr check; it
#                               must stay near-free or opting out is a lie
TRACING_BUDGET_NS = 50_000    # per-span propagate+record, flight append
#                               and flush included: a traced hop fires a
#                               handful of spans per iteration, so 50us
#                               keeps tracing under 1% of even a warm
#                               ~40ms create/start pair
TRACE_MERGE_BUDGET_S = 2.0    # merge 256 agents x 4 recorder processes
#                               (~2.5k spans) into one causal forest --
#                               `clawker trace` is interactive, so the
#                               offline merge must stay prompt-speed
ANOMALY_FLAG_LATENCY_BUDGET_S = 2.0   # egress append -> anomaly.flag on
#                               the bus, sentinel live on the fake pod
#                               (ISSUE 10 acceptance)
ANOMALY_TICK_BUDGET_S = 10.0  # 64 agents x open windows, one sharded
#                               fit/score tick, compile excluded
WORKERD_RTT_RATIO_BUDGET = 1.5   # workerd wall at 50ms injected RTT vs
#                               its own zero-RTT wall: the data plane
#                               must be (near-)independent of the
#                               host<->worker RTT (ISSUE 11 acceptance)
WORKERD_DIRECT_RTT_MIN_RATIO = 1.8   # the direct path must be
#                               DEMONSTRABLY RTT-bound on the same
#                               fleet, or the comparison proves nothing
WORKERD_EVENT_OVERHEAD_BUDGET_MS = 25.0  # per-launch intent/event
#                               machinery cost (submit -> started
#                               handled, engine time excluded)
SEED_AMORTIZATION_MIN = 10.0  # content-addressed seed fan-out (one walk,
#                               one transfer per worker, local puts) vs
#                               the per-agent walk+WAN-put baseline at
#                               50ms RTT (ISSUE 16 acceptance)
SEED_CACHE_HIT_MIN = 31       # of 32 agent digest lookups in one
#                               fan-out, at least 31 must hit the cache
FEDERATION_FANOUT_BUDGET_S = 30.0  # 512 loops routed across 8 pods by
#                               the federation router at 5ms injected
#                               DCN RTT: submit -> p50 run completion
#                               (ISSUE 17 acceptance)
LEASE_AMORTIZATION_MIN = 5.0  # capacity leases vs per-launch admission
#                               round-trips over the same routed traffic
#                               at the same RTT: the zero-WAN-hop launch
#                               hot path evidence
POD_FAILOVER_MIGRATE_BUDGET_S = 10.0  # pod kill -> its run finished on
#                               the survivor via journal adoption, with
#                               the cross-pod exactly-once audit green


def main() -> None:
    p50_s, stages, identity_split = bench_cold_start()
    parity_wall, parity_passed, parity_total = bench_parity()
    decisions = bench_policy_oracle()
    qps = bench_dnsgate_qps()
    fanout_s = bench_loop_fanout()
    fanout64 = bench_loop_fanout_n64()
    stampede = bench_placement_admission_stampede()
    poll_cost = bench_loop_poll_cost()
    provision = bench_fleet_provision()
    failover = bench_failover()
    resume = bench_resume_reattach()
    pool_hit = bench_warm_pool_hit()
    pool_burst = bench_warm_pool_refill_burst()
    loopd_rt = bench_loopd_submit_roundtrip()
    gitguard_rt = bench_gitguard_push_overhead()
    fairness = bench_cross_process_fairness()
    fed = bench_federation_fanout_n512()
    fed_mig = bench_pod_failover_migrate()
    wd_rtt = bench_workerd_rtt_independence()
    wd_batch = bench_workerd_event_batch_overhead()
    seed_amort = bench_workspace_seed_amortization()
    dials = bench_engine_dials()
    tele = bench_telemetry_overhead()
    tracing = bench_tracing_overhead()
    tmerge = bench_trace_merge()
    console = bench_console_repaint()
    ingest = bench_ingest_lag()
    elastic = bench_elastic_vs_static_p99()
    anom = bench_anomaly()
    flag_lat = bench_anomaly_flag_latency()
    score_tick = bench_anomaly_fleet_score_tick()

    budget_s = 10.0
    extra = [
        {"metric": "firewall_parity_pass_rate",
         "value": round(100.0 * parity_passed / parity_total, 1),
         "unit": "%", "vs_baseline": round(parity_passed / parity_total, 3)},
        {"metric": "parity_suite_wall", "value": round(parity_wall, 1),
         "unit": "s", "vs_baseline": round(120.0 / parity_wall, 1)},
        {"metric": "policy_oracle_decisions_per_s",
         "value": round(decisions), "unit": "1/s",
         "vs_baseline": round(decisions / 10_000, 1)},
        {"metric": "dnsgate_qps", "value": round(qps), "unit": "1/s",
         "vs_baseline": round(qps / 1_000, 1)},
        {"metric": "loop_fanout_p50_n8", "value": round(fanout_s * 1000, 1),
         "unit": "ms", "vs_baseline": round(10.0 / max(fanout_s, 1e-9), 1)},
        {"metric": "loop_fanout_p50_n64",
         "value": round(fanout64["fanout_p50_s"] * 1000, 1), "unit": "ms",
         # a run that blew an admission cap or missed its budget must
         # read FAILED, never as merely fast
         "vs_baseline": (round(
             FANOUT64_BUDGET_S / max(fanout64["fanout_p50_s"], 1e-9), 1)
             if fanout64["cap_respected"] and fanout64["all_loops_done"]
             else 0.0),
         "detail": fanout64},
        {"metric": "placement_admission_stampede",
         "value": stampede["wall_s"], "unit": "s",
         # the gate IS the invariant set: burst drained, cap held, and
         # the slow-but-healthy worker was never quarantined
         "vs_baseline": (round(
             STAMPEDE_BUDGET_S / max(stampede["wall_s"], 1e-9), 1)
             if stampede["all_loops_done"] and stampede["cap_respected"]
             and not stampede["breaker_opened"] else 0.0),
         "detail": stampede},
        {"metric": "loop_poll_cost_n8",
         "value": poll_cost["calls_per_iteration"], "unit": "calls/iter",
         "vs_baseline": round(
             POLL_COST_BUDGET / max(poll_cost["calls_per_iteration"], 1e-9), 1),
         "detail": poll_cost},
        {"metric": "fleet_provision_wall_n8", "value": provision["wall_s"],
         "unit": "s",
         # vs_baseline IS the speedup over serial provisioning: >= 2
         # means the concurrency pass holds its acceptance bar
         "vs_baseline": provision["speedup"] if provision["ok"] else 0.0,
         "detail": provision},
        {"metric": "failover_detect_to_restart_s",
         "value": failover["detect_to_restart_s"], "unit": "s",
         # a failed scenario (no migration, loops short of budget, or a
         # negative detect) must read as FAILED, never as within budget
         "vs_baseline": (round(
             FAILOVER_BUDGET_S / max(failover["detect_to_restart_s"], 1e-9), 1)
             if failover["all_loops_done"]
             and failover["detect_to_restart_s"] > 0 else 0.0),
         "detail": failover},
        {"metric": "resume_reattach_wall_n8",
         "value": resume["reattach_wall_s"], "unit": "s",
         # vs_baseline IS the adoption speedup over the cold fan-out the
         # resume avoided; a failed scenario (missed adoptions, duplicate
         # creates, loops short of budget) must read as FAILED
         "vs_baseline": (resume["speedup"]
                         if resume["all_loops_done"]
                         and resume["adopted"] == resume["loops"]
                         and not resume["duplicate_creates"] else 0.0),
         "detail": resume},
        {"metric": "warm_pool_hit_p50", "value": pool_hit["hit_p50_ms"],
         "unit": "ms",
         # vs_baseline is headroom under the 1ms hit budget; a leg that
         # missed the pool (hits < iters) must read FAILED, never fast
         "vs_baseline": (round(
             WARM_POOL_HIT_BUDGET_MS / max(pool_hit["hit_p50_ms"], 1e-9), 1)
             if pool_hit["hits"] == pool_hit["iters"] else 0.0),
         "detail": pool_hit},
        {"metric": "warm_pool_refill_burst", "value": pool_burst["wall_s"],
         "unit": "s",
         # the gate IS the invariant set: burst drained, pools refilled
         # behind it, zero members leaked after drain
         "vs_baseline": (round(
             WARM_POOL_BURST_BUDGET_S / max(pool_burst["wall_s"], 1e-9), 1)
             if pool_burst["all_loops_done"] and pool_burst["pool_refilled"]
             and not pool_burst["leaked_containers"] else 0.0),
         "detail": pool_burst},
        {"metric": "loopd_submit_roundtrip_p50",
         "value": loopd_rt["submit_p50_ms"], "unit": "ms",
         # headroom under the 5ms submit-hop budget; a leg whose runs
         # failed must read FAILED, never merely fast
         "vs_baseline": (round(
             LOOPD_SUBMIT_BUDGET_MS / max(loopd_rt["submit_p50_ms"], 1e-9),
             1) if loopd_rt["runs_ok"] == loopd_rt["iters"] else 0.0),
         "detail": loopd_rt},
        {"metric": "gitguard_push_overhead",
         "value": gitguard_rt["overhead_p50_ms"], "unit": "ms",
         # headroom under the 5ms per-push budget; a leg whose pushes
         # were refused (or never landed) must read FAILED, never fast
         "vs_baseline": (round(
             GITGUARD_PUSH_OVERHEAD_BUDGET_MS
             / max(gitguard_rt["overhead_p50_ms"], 1e-9), 1)
             if gitguard_rt["all_acked"]
             and gitguard_rt["pushes_measured"] == gitguard_rt["iters"]
             else 0.0),
         "detail": gitguard_rt},
        {"metric": "cross_process_fairness", "value": fairness["wall_s"],
         "unit": "s",
         # the gate IS the invariant set: two client processes, one
         # daemon -- cap held at the daemon, tenants interleaved
         "vs_baseline": (1.0 if fairness["both_ok"]
                         and fairness["cap_respected"]
                         and fairness["interleaved"] else 0.0),
         "detail": fairness},
        {"metric": "federation_fanout_p50_n512",
         "value": round(fed["fanout_p50_s"], 3), "unit": "s",
         # the gate IS the acceptance set: all 512 loops done across 8
         # pods, no pod's admission cap breached, and leases amortizing
         # admission RPCs >= 5x over per-launch round-trips at the same
         # injected DCN RTT -- a cap breach or lost loop reads FAILED
         "vs_baseline": (round(
             FEDERATION_FANOUT_BUDGET_S / max(fed["fanout_p50_s"], 1e-9),
             1) if fed["all_loops_done"] and fed["cap_respected"]
             and fed["lease_amortization"] >= LEASE_AMORTIZATION_MIN
             else 0.0),
         "detail": fed},
        {"metric": "pod_failover_migrate_s",
         "value": fed_mig["migrate_wall_s"], "unit": "s",
         # a migration that duplicated a create, left the run short, or
         # launched on the dead pod must read FAILED, never fast
         "vs_baseline": (round(
             POD_FAILOVER_MIGRATE_BUDGET_S
             / max(fed_mig["migrate_wall_s"], 1e-9), 1)
             if fed_mig["run_ok"] and fed_mig["migrated_runs"] == 1
             and not fed_mig["violations"]
             and not fed_mig["dead_pod_created_after_kill"] else 0.0),
         "detail": fed_mig},
        {"metric": "workerd_rtt_independence",
         "value": wd_rtt["workerd_ratio"], "unit": "x",
         # the gate IS the acceptance bar: all four legs drained, the
         # workerd wall within 1.5x of its zero-RTT run, the direct
         # path visibly RTT-bound on the same fleet
         "vs_baseline": (round(
             WORKERD_RTT_RATIO_BUDGET / max(wd_rtt["workerd_ratio"], 1e-9),
             2) if wd_rtt["all_done"]
             and wd_rtt["direct_ratio"] >= WORKERD_DIRECT_RTT_MIN_RATIO
             else 0.0),
         "detail": wd_rtt},
        {"metric": "workerd_event_batch_overhead",
         "value": wd_batch["event_overhead_p50_ms"], "unit": "ms",
         "vs_baseline": (round(
             WORKERD_EVENT_OVERHEAD_BUDGET_MS
             / max(wd_batch["event_overhead_p50_ms"], 1e-9), 1)
             if wd_batch["completed"] == wd_batch["iters"]
             and wd_batch["event_overhead_p50_ms"] >= 0 else 0.0),
         "detail": wd_batch},
        {"metric": "workspace_seed_amortization",
         "value": seed_amort["amortization"], "unit": "x",
         # vs_baseline IS the amortization headroom over the 10x bar; a
         # run that missed a create, shipped a duplicate seed, or fell
         # back to per-create walks must read FAILED, never merely fast
         "vs_baseline": (round(
             seed_amort["amortization"] / SEED_AMORTIZATION_MIN, 2)
             if seed_amort["created"] == seed_amort["agents"]
             and seed_amort["one_transfer_per_worker"]
             and seed_amort["cache_hits"] >= SEED_CACHE_HIT_MIN
             and seed_amort["store_misses"] == 0 else 0.0),
         "detail": seed_amort},
        {"metric": "engine_dials_per_run", "value": dials["dials_pooled"],
         "unit": "dials",
         # vs_baseline IS the dial reduction over the dial-per-request
         # client under the injected forwarded-socket delay: >= 2 means
         # the pool holds its acceptance bar
         "vs_baseline": dials["dial_reduction"],
         "detail": dials},
        {"metric": "console_repaint_p95", "value": console["frame_p95_ms"],
         "unit": "ms",
         # the gate IS the acceptance bar: 256 agents / 4 hosted runs
         # repaint within budget, the frame bounded by virtualization,
         # and damage tracking actually saving rows -- an unbounded or
         # full-repaint frame must read FAILED, never merely fast
         "vs_baseline": (round(
             CONSOLE_REPAINT_BUDGET_MS / max(console["frame_p95_ms"], 1e-9),
             1) if console["bounded"] and console["damage_ratio"] <= 0.5
             else 0.0),
         "detail": console},
        {"metric": "ingest_docs_lag", "value": ingest["lag_p95_s"],
         "unit": "s",
         # a lossy healthy-index run must read FAILED, never fast
         "vs_baseline": (round(
             INGEST_LAG_BUDGET_S / max(ingest["lag_p95_s"], 1e-9), 1)
             if ingest["complete"] else 0.0),
         "detail": ingest},
        {"metric": "elastic_vs_static_p99",
         "value": elastic["adaptive"]["p99_wait_ms"], "unit": "ms",
         # vs_baseline IS the p99 advantage over the best static
         # warm-pool/token config within the adaptive run's
         # container-second budget; a run that lost the frontier (or
         # had no comparable static) must read FAILED
         "vs_baseline": (round(
             elastic["best_comparable_static"]["p99_wait_ms"]
             / max(elastic["adaptive"]["p99_wait_ms"], 1e-9), 1)
             if elastic["beats_static"]
             and elastic["best_comparable_static"] else 0.0),
         "detail": elastic},
        {"metric": "telemetry_overhead_ns", "value": tele["enabled_ns"],
         "unit": "ns",
         # vs_baseline is headroom under the per-record budget: >= 1
         # means instrumentation stays invisible next to the cold start
         "vs_baseline": round(
             TELEMETRY_BUDGET_NS / max(tele["enabled_ns"], 1e-9), 1),
         "detail": tele},
        {"metric": "tracing_overhead_ns", "value": tracing["record_ns"],
         "unit": "ns",
         # headroom under the per-span budget (propagate + record +
         # flight append/flush): >= 1 means a traced hop stays invisible
         "vs_baseline": round(
             TRACING_BUDGET_NS / max(tracing["record_ns"], 1e-9), 1),
         "detail": tracing},
        {"metric": "trace_merge_wall_n256", "value": tmerge["merge_wall_s"],
         "unit": "s",
         "vs_baseline": round(
             TRACE_MERGE_BUDGET_S / max(tmerge["merge_wall_s"], 1e-9), 1),
         "detail": tmerge},
        {"metric": "anomaly_score_step", "value": anom["score_step_us"],
         "unit": "us",
         # a dead lane (score_step 0 / device unavailable) must read as
         # FAILED, never as infinitely within budget
         "vs_baseline": (round(5000.0 / anom["score_step_us"], 1)
                         if anom["score_step_us"] > 0 else 0.0),
         "detail": anom},
        {"metric": "anomaly_flag_latency_p50",
         "value": flag_lat.get("flag_latency_p50_s", 0.0), "unit": "s",
         # the gate is the full sentinel acceptance: every seeded rep
         # flagged, within budget -- a rep that never flagged reads 0
         "vs_baseline": (round(
             ANOMALY_FLAG_LATENCY_BUDGET_S
             / max(flag_lat.get("flag_latency_p50_s", 0.0), 1e-9), 1)
             if not flag_lat.get("error")
             and flag_lat.get("flags") == flag_lat.get("reps")
             and flag_lat.get("flag_latency_p50_s", 99.0)
             <= ANOMALY_FLAG_LATENCY_BUDGET_S else 0.0),
         "detail": flag_lat},
        {"metric": "anomaly_fleet_score_tick",
         "value": score_tick.get("tick_p50_s", 0.0), "unit": "s",
         "vs_baseline": (round(
             ANOMALY_TICK_BUDGET_S
             / max(score_tick.get("tick_p50_s", 0.0), 1e-9), 1)
             if not score_tick.get("error")
             and score_tick.get("agents") == 64
             and score_tick.get("tick_p50_s", 99.0)
             <= ANOMALY_TICK_BUDGET_S else 0.0),
         "detail": score_tick},
    ]
    prev_ms = previous_round_p50()
    cur_ms = round(p50_s * 1000, 2)
    regressed = bool(prev_ms) and cur_ms > prev_ms * 1.15
    doc = {
        "metric": "agent_cold_start_framework_p50",
        "value": cur_ms,
        "unit": "ms",
        "vs_baseline": round(budget_s / p50_s, 1),
        "stages_ms": stages,
        # CA session cache effect on the identity_bootstrap stage: the
        # warm leg re-creates the same agents, so leaves come from the
        # cache (the loop-restart/migration/resume placement shape)
        "identity_split": identity_split,
        "prev_round_ms": prev_ms,
        "extra": extra,
    }
    if regressed:
        # the round-4 verdict's regression gate: >15% p50 creep vs the
        # committed previous round fails the bench run loudly
        doc["regression"] = f"p50 {cur_ms}ms > 1.15 x prev {prev_ms}ms"
    print(json.dumps(doc))
    if regressed:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
