# clawker-tpu build + test targets (reference: the Makefile test tier,
# SURVEY.md 4 -- test / test-ci / native builds / docs drift check).

PY ?= python

.PHONY: all test test-fast test-e2e parity bench bench-smoke chaos-smoke \
        analyze native ebpf-check docs docs-check adversarial graft clean

all: native test

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -x -m "not slow"

# Real-daemon e2e (reference test/e2e): dockerd when present, else the
# first-party nsd namespace daemon (root Linux).
test-e2e:
	CLAWKER_TPU_E2E=1 $(PY) -m pytest tests/e2e -q

# The 22-scenario + 35-technique firewall parity scorecard (twin rows
# re-graded on the real kernel where bpf(2) works).
parity:
	$(PY) -m clawker_tpu.parity

bench:
	$(PY) bench.py

# Scheduler/provisioning perf gates (fan-out latency, poll cost,
# provision wall vs serial) under a hard timeout -- regressions in the
# concurrent control plane fail in-repo, not in the next bench round.
bench-smoke:
	timeout -k 10 600 $(PY) scripts/bench_smoke.py

# Just the fixed-seed chaos soak gate (25 compound-fault scenarios,
# zero invariant violations; docs/chaos.md) -- the fast robustness
# regression check for scheduler/journal/admission/warm-pool changes.
chaos-smoke:
	timeout -k 10 420 $(PY) scripts/bench_smoke.py --only chaos

# Static architectural-invariant checks (docs/static-analysis.md):
# pure-stdlib, <5s, exit 2 on any finding not in the committed
# grandfather baseline.  Also rides bench-smoke and a tier-1 test.
analyze:
	$(PY) -m clawker_tpu.analysis

native:
	$(MAKE) -C native

ebpf-check:
	./scripts/check_bpf.sh

adversarial:
	$(PY) -c "from clawker_tpu.adversarial import run_corpus; \
	r = run_corpus(); print(r.to_json()); \
	import sys; sys.exit(0 if r.ok else 1)"

graft:
	$(PY) __graft_entry__.py

docs:
	$(PY) -c "from clawker_tpu.cli.root import main; \
	main(['gen-docs', '--out', 'docs/cli-reference'])"

# regenerating must be a no-op against the committed reference
docs-check: docs
	git diff --exit-code docs/cli-reference \
	|| (echo 'docs drift: run `make docs` and commit' && exit 1)

clean:
	$(MAKE) -C native clean
	$(MAKE) -C native/ebpf clean
