"""Concurrency stress: the Python analogue of the reference's
`go test -race` tier (SURVEY.md 5 race detection).

CPython has no race detector, so the shared-state surfaces are hammered
from many threads while invariants are asserted: no exceptions escape,
counts reconcile, snapshots stay internally consistent, and the
data-plane swap (rules reload during traffic) never produces a torn
read.  These tests fail on real lock bugs (dropped locks turn into
KeyErrors/duplicate applies/ torn dicts under this load).
"""

from __future__ import annotations

import socket
import struct
import threading
import time

import pytest

from clawker_tpu.config.schema import EgressRule

THREADS = 8
ROUNDS = 200


def hammer(fn, *, threads=THREADS, rounds=ROUNDS):
    """Run fn(thread_index, round_index) from N threads; surface every
    exception."""
    errors: list[BaseException] = []
    barrier = threading.Barrier(threads)

    def work(ti):
        try:
            barrier.wait(5)
            for ri in range(rounds):
                fn(ti, ri)
        except BaseException as e:  # noqa: BLE001 - collected for assert
            errors.append(e)

    ts = [threading.Thread(target=work, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert not errors, errors[:3]


def test_action_queue_serializes_mutations(tmp_path):
    """Concurrent rule mutations through the queue end in a consistent
    store: every add applied exactly once, no lost updates."""
    from clawker_tpu.firewall.queue import ActionQueue
    from clawker_tpu.firewall.rules import RulesStore

    store = RulesStore(tmp_path / "rules.yaml")
    queue = ActionQueue()
    applied = []

    def one(ti, ri):
        if ri % 10 == 0:
            dst = f"d{ti}-{ri}.example.com"
            queue.run(lambda d=dst: applied.append(
                store.add([EgressRule(dst=d)])))
        else:
            queue.run(store.load)

    try:
        hammer(one, rounds=100)
    finally:
        queue.close()
    added = {r.dst for batch in applied for r in batch}
    assert added == {r.dst for r in store.load()}
    assert len(added) == THREADS * 10


def test_store_snapshot_never_torn(tmp_path):
    """Readers racing provenance-routed writers always see a parseable,
    internally consistent snapshot (atomic temp+rename + lock-free
    snapshot reads)."""
    from clawker_tpu.storage.store import Layer, Store

    p = tmp_path / "settings.yaml"
    p.write_text("monitoring:\n  opensearch_port: 9200\n")
    store = Store([Layer("user", p)])

    def one(ti, ri):
        if ti % 2 == 0:
            store.set(f"slot{ti}.value", ri)
        else:
            raw = store.raw()
            # a torn write would surface as a half-merged tree here
            assert isinstance(raw, dict)
            assert raw["monitoring"]["opensearch_port"] == 9200

    hammer(one, rounds=60)
    for ti in range(0, THREADS, 2):
        assert store.get(f"slot{ti}.value") == 59


def test_pubsub_concurrent_publish_subscribe():
    """Publishers racing subscribe/unsubscribe: no deadlock, every
    subscriber sees an ordered (possibly drop-oldest-bounded) stream."""
    from clawker_tpu.controlplane.pubsub import Topic

    topic = Topic("stress")
    seen: dict[int, list] = {i: [] for i in range(THREADS)}

    def one(ti, ri):
        if ti < THREADS // 2:
            topic.publish((ti, ri))
        else:
            sub = topic.subscribe(f"s{ti}-{ri}")
            ev = sub.get(timeout=0.005)
            if ev is not None:
                seen[ti].append(ev)
            sub.close()

    hammer(one, rounds=80)
    # monotone sequence numbers within every consumer's view
    for evs in seen.values():
        seqs = [e.seq for e in evs]
        assert seqs == sorted(seqs)
    assert topic.subscriber_count() == 0


def test_maps_churn_vs_policy_decisions():
    """Verdict reads racing enroll/bypass/dns churn: decide() must never
    raise or return an inconsistent verdict object."""
    from clawker_tpu.firewall import policy
    from clawker_tpu.firewall.hashes import zone_hash
    from clawker_tpu.firewall.maps import DnsEntry, FakeMaps
    from clawker_tpu.firewall.model import (
        FLAG_ENFORCE,
        Action,
        ContainerPolicy,
    )

    maps = FakeMaps()
    pol = ContainerPolicy(envoy_ip="10.0.0.2", dns_ip="10.0.0.1",
                          hostproxy_ip="10.0.0.1", hostproxy_port=18374,
                          flags=FLAG_ENFORCE)
    maps.enroll(7, pol)
    zh = zone_hash("example.com")

    def one(ti, ri):
        if ti == 0:
            maps.enroll(7, pol) if ri % 2 else maps.unenroll(7)
        elif ti == 1:
            maps.set_bypass(7, int(time.time()) + 5) if ri % 2 \
                else maps.clear_bypass(7)
        elif ti == 2:
            maps.cache_dns("93.184.216.34",
                           DnsEntry(zone_hash=zh, expires_unix=2**40))
            maps.expire_dns()
        else:
            v = policy.connect4(maps, 7, "93.184.216.34", 443,
                                sock_cookie=ti * 1000 + ri)
            assert isinstance(v.action, Action)

    hammer(one)


def test_dnsgate_queries_during_policy_swaps(tmp_path):
    """Live traffic racing set_policy reloads: every reply is a valid
    DNS message with a verdict from ONE coherent policy (never a tear)."""
    from clawker_tpu.firewall.dnsgate import DnsGate, ZonePolicy, _encode_name
    from clawker_tpu.firewall.maps import FakeMaps

    allow = ZonePolicy.from_rules([EgressRule(dst="*.example.com")])
    deny = ZonePolicy.from_rules([])
    gate = DnsGate(allow, FakeMaps(), host="127.0.0.1", port=0)
    gate._forward = lambda data, resolvers, tcp=False: None
    gate.start()
    query = (struct.pack(">HHHHHH", 7, 0x0100, 1, 0, 0, 0)
             + _encode_name("a.example.com") + struct.pack(">HH", 1, 1))
    try:
        def one(ti, ri):
            if ti == 0:
                gate.set_policy(allow if ri % 2 else deny)
                return
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.settimeout(2.0)
                s.sendto(query, ("127.0.0.1", gate.bound_port))
                reply = s.recv(512)
            rcode = struct.unpack(">H", reply[2:4])[0] & 0xF
            assert rcode in (0, 2, 3)   # NOERROR/SERVFAIL/NXDOMAIN only

        hammer(one, rounds=60)
        assert gate.stats.queries >= (THREADS - 1) * 60
    finally:
        gate.stop()
