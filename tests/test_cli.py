"""CLI tests over the fake driver (reference Tier-2 pattern: full command
pipeline with fake engine, TESTING-REFERENCE.md:253-299)."""

import subprocess
from pathlib import Path

import pytest
from click.testing import CliRunner

from clawker_tpu import consts
from clawker_tpu.cli.factory import Factory
from clawker_tpu.cli.root import cli
from clawker_tpu.engine.drivers import FakeDriver
from clawker_tpu.engine.fake import exit_behavior


@pytest.fixture()
def env(tenv, tmp_path):
    tenv.make_project(tmp_path, "project: demo\n")
    drv = FakeDriver()
    drv.api.add_image("clawker-demo:default")
    factory = Factory(cwd=tmp_path, driver=drv)
    return CliRunner(), factory, drv.api, tmp_path


def invoke(runner, factory, *args, **kw):
    return runner.invoke(cli, list(args), obj=factory, catch_exceptions=False, **kw)


def test_run_attaches_and_propagates_exit(env):
    runner, factory, api, _ = env
    api.set_behavior("clawker-demo:default", exit_behavior(b"agent says hi\n", code=0))
    res = invoke(runner, factory, "run", "--agent", "dev")
    assert res.exit_code == 0, res.output
    assert "agent says hi" in res.output


def test_run_nonzero_exit_code(env):
    runner, factory, api, _ = env
    api.set_behavior("clawker-demo:default", exit_behavior(code=3))
    res = runner.invoke(cli, ["run"], obj=factory)
    assert res.exit_code == 3


def test_run_detach_then_ps_stop_rm(env):
    runner, factory, api, _ = env
    res = invoke(runner, factory, "run", "--detach")
    assert res.exit_code == 0
    assert "clawker.demo.dev" in res.output
    res = invoke(runner, factory, "ps")
    assert "clawker.demo.dev" in res.output and "running" in res.output
    res = invoke(runner, factory, "stop", "dev")
    assert res.exit_code == 0
    res = invoke(runner, factory, "rm", "dev")
    assert res.exit_code == 0
    res = invoke(runner, factory, "ps")
    assert "no agent containers" in res.output


def test_run_missing_project_image(env):
    runner, factory, api, _ = env
    del api.images["clawker-demo:default"]
    res = runner.invoke(cli, ["run"], obj=factory)
    assert res.exit_code == 1
    assert "clawker build" in res.output


def test_container_create_and_inspect(env):
    runner, factory, api, _ = env
    res = invoke(runner, factory, "container", "create", "--agent", "aux")
    assert res.exit_code == 0
    res = invoke(runner, factory, "container", "inspect", "aux")
    assert '"clawker.demo.aux"' in res.output.replace("/clawker", "clawker")


def test_run_env_flag(env):
    runner, factory, api, _ = env
    invoke(runner, factory, "run", "--detach", "-e", "FOO=bar")
    info = list(api.containers.values())[0].config
    assert "FOO=bar" in info["Env"]


def test_init_scaffold(tenv, tmp_path):
    runner = CliRunner()
    factory = Factory(cwd=tmp_path, driver=FakeDriver())
    res = invoke(runner, factory, "init", "--name", "myproj")
    assert res.exit_code == 0
    assert (tmp_path / consts.PROJECT_FLAT_FORM).exists()
    res = invoke(runner, factory, "init")
    assert res.exit_code != 0  # already exists


def test_init_wizard_drives_choices(tenv, tmp_path):
    """Interactive init runs the wizard: name, stack, harness, mode
    (reference tui wizard).  Scripted TTY session picks snapshot mode."""
    from clawker_tpu.cli.cmd_init import _wizard

    from clawker_tpu.ui.iostreams import IOStreams

    factory = Factory(cwd=tmp_path, driver=FakeDriver())
    streams, *_ = IOStreams.test(stdin_data="wiz proj\n\n\n2\n")
    for s in (streams.stdin, streams.stdout, streams.stderr):
        s.isatty = lambda: True  # isolated buffers, never real stdio
    factory.__dict__["streams"] = streams  # pre-seed the cached property
    name, stack, harness, mode = _wizard(factory, "", "python")
    assert name == "wiz-proj"
    assert stack == "python" and harness == "claude"
    assert mode == "snapshot"


def test_volume_ls_after_run(env):
    runner, factory, api, _ = env
    invoke(runner, factory, "run", "--detach")
    res = invoke(runner, factory, "volume", "ls")
    assert "clawker.demo.dev.config" in res.output


# ------------------------------------------------------------- worktrees

@pytest.fixture()
def git_env(tenv, tmp_path):
    subprocess.run(["git", "init", "-q", str(tmp_path)], check=True)
    subprocess.run(
        ["git", "-C", str(tmp_path), "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "--allow-empty", "-q", "-m", "init"],
        check=True,
    )
    tenv.make_project(tmp_path, "project: demo\n")
    drv = FakeDriver()
    drv.api.add_image("clawker-demo:default")
    return CliRunner(), Factory(cwd=tmp_path, driver=drv), tmp_path


def test_worktree_add_list_remove(git_env):
    runner, factory, root = git_env
    res = invoke(runner, factory, "worktree", "add", "feat1")
    assert res.exit_code == 0, res.output
    assert "clawker/feat1" in res.output
    res = invoke(runner, factory, "worktree", "list")
    assert "feat1" in res.output
    res = invoke(runner, factory, "worktree", "remove", "feat1")
    assert res.exit_code == 0
    res = invoke(runner, factory, "worktree", "list")
    assert "feat1" not in res.output


def test_worktree_remove_dirty_requires_force(git_env):
    runner, factory, root = git_env
    res = invoke(runner, factory, "worktree", "add", "feat2")
    wt_path = Path(res.output.split("\t")[1].strip())
    (wt_path / "junk.txt").write_text("dirty")
    res = runner.invoke(cli, ["worktree", "remove", "feat2"], obj=factory)
    assert res.exit_code == 1
    assert "local changes" in res.output
    res = invoke(runner, factory, "worktree", "remove", "feat2", "--force")
    assert res.exit_code == 0


def test_run_in_worktree_mounts(git_env):
    runner, factory, root = git_env
    invoke(runner, factory, "worktree", "add", "feat3")
    res = invoke(runner, factory, "run", "--detach", "--worktree", "feat3")
    assert res.exit_code == 0, res.output
    api = factory.driver.api
    c = list(api.containers.values())[0]
    binds = c.config["HostConfig"]["Binds"]
    assert any("worktrees/demo/feat3:/workspace" in b for b in binds)
    # main repo git dir mounted read-only so the worktree .git file resolves
    assert any(b.endswith(":ro") and "/.git" in b for b in binds)


def test_project_register_and_list(git_env):
    runner, factory, root = git_env
    res = invoke(runner, factory, "project", "register")
    assert res.exit_code == 0
    res = invoke(runner, factory, "project", "list")
    assert "demo" in res.output


def test_stop_long_agent_name_resolves_to_project(env):
    # agent names up to 63 chars are valid; only hex container ids skip the
    # project-prefix resolution
    runner, factory, api, _ = env
    long_agent = "experiment-long-context-window-ablation-a"
    res = invoke(runner, factory, "run", "--detach", "--agent", long_agent)
    assert res.exit_code == 0, res.output
    res = invoke(runner, factory, "stop", long_agent)
    assert res.exit_code == 0, res.output
    res = invoke(runner, factory, "ps", "--running")
    assert "no agent containers" in res.output
    res = invoke(runner, factory, "ps")
    assert long_agent in res.output


def test_create_wires_socket_and_hostproxy_mapping(env):
    runner, factory, api, _ = env
    res = invoke(runner, factory, "run", "--detach")
    assert res.exit_code == 0, res.output
    c = list(api.containers.values())[0]
    hc = c.config["HostConfig"]
    # host proxy on by default -> host-gateway mapping for Linux daemons
    assert hc.get("ExtraHosts") == ["host.docker.internal:host-gateway"]
    # docker socket NOT mounted unless opted in
    assert not any("docker.sock" in b for b in hc["Binds"])
