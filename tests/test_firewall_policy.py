"""Policy-oracle suite: the reference's e2e firewall scenarios at map level.

Parity bar: /root/reference/test/e2e/firewall_test.go:77-709 (22 scenarios
-- blocked/allowed domains, ICMP, bypass, wildcard/exact subdomain
semantics, SSH TCP mapping, docker-internal DNS, host-proxy reachability,
HTTP domain detection) driven through clawker_tpu.firewall.policy over
FakeMaps.  The same semantics compile into native/ebpf/fw.c; ABI pins at
the bottom keep the two in lock-step.
"""

from __future__ import annotations

import time

import pytest

from clawker_tpu.config.schema import EgressRule
from clawker_tpu.firewall import policy
from clawker_tpu.firewall.hashes import zone_hash
from clawker_tpu.firewall.maps import FakeMaps, UDP_FLOWS_MAX, iter_expired_bypass
from clawker_tpu.firewall.model import (
    FLAG_ENFORCE,
    FLAG_HOSTPROXY,
    PROTO_TCP,
    PROTO_UDP,
    Action,
    ContainerPolicy,
    DnsEntry,
    EgressEvent,
    Reason,
    RouteKey,
    RouteVal,
    UdpFlow,
)

CG = 4242  # enrolled cgroup id
ENVOY = "10.99.0.2"
DNSGATE = "10.99.0.3"
HOSTPROXY = "10.99.0.1"


@pytest.fixture
def maps():
    m = FakeMaps()
    m.enroll(CG, ContainerPolicy(
        envoy_ip=ENVOY, dns_ip=DNSGATE, hostproxy_ip=HOSTPROXY,
        hostproxy_port=18374, flags=FLAG_ENFORCE | FLAG_HOSTPROXY,
    ))
    return m


def cache(maps, ip, zone, ttl=300):
    maps.cache_dns(ip, DnsEntry(zone_hash=zone_hash(zone), expires_unix=int(time.time()) + ttl))


def route(maps, zone, port, proto, val):
    t = maps.routes()
    t[RouteKey(zone_hash(zone), port, proto)] = val
    maps.sync_routes(t)


# -- scenario: unmanaged cgroups are never touched --------------------------

def test_unmanaged_cgroup_allowed(maps):
    v = policy.connect4(maps, 999, "93.184.216.34", 443)
    assert v.action is Action.ALLOW and v.reason is Reason.UNMANAGED


# -- scenario: allowed domain -> Envoy redirect (firewall_test.go:206) ------

def test_allowed_domain_redirects_to_envoy(maps):
    cache(maps, "93.184.216.34", "example.com")
    route(maps, "example.com", 443, PROTO_TCP,
          RouteVal(Action.REDIRECT, redirect_ip=ENVOY, redirect_port=10000))
    v = policy.connect4(maps, CG, "93.184.216.34", 443)
    assert v.action is Action.REDIRECT
    assert (v.redirect_ip, v.redirect_port) == (ENVOY, 10000)
    assert v.zone_hash == zone_hash("example.com")


# -- scenario: blocked domain -> deny (firewall_test.go:77) -----------------

def test_blocked_domain_denied(maps):
    # DNS gate never resolved it, so no dns_cache entry: ip-literal deny
    v = policy.connect4(maps, CG, "203.0.113.9", 443)
    assert v.action is Action.DENY and v.reason is Reason.NO_DNS_ENTRY


def test_resolved_but_unrouted_zone_denied(maps):
    cache(maps, "198.51.100.7", "evil.example.net")
    v = policy.connect4(maps, CG, "198.51.100.7", 443)
    assert v.action is Action.DENY and v.reason is Reason.NO_ROUTE


# -- scenario: port-specific route + any-port fallback ----------------------

def test_port_specific_route_beats_any_port(maps):
    cache(maps, "10.1.2.3", "example.com")
    route(maps, "example.com", 0, PROTO_TCP, RouteVal(Action.ALLOW))
    route(maps, "example.com", 8443, PROTO_TCP,
          RouteVal(Action.REDIRECT, redirect_ip=ENVOY, redirect_port=10000))
    assert policy.connect4(maps, CG, "10.1.2.3", 8443).action is Action.REDIRECT
    assert policy.connect4(maps, CG, "10.1.2.3", 9999).action is Action.ALLOW


# -- scenario: ICMP blocked via raw-socket deny (firewall_test.go:103) ------

def test_raw_socket_denied_blocks_icmp(maps):
    v = policy.sock_create(maps, CG, 2, policy.SOCK_RAW)
    assert v.action is Action.DENY and v.reason is Reason.RAW_SOCKET
    assert policy.sock_create(maps, CG, 2, policy.SOCK_STREAM).action is Action.ALLOW
    assert policy.sock_create(maps, 999, 2, policy.SOCK_RAW).action is Action.ALLOW


# -- scenario: bypass allows everything, dead-man timed (test.go:147) -------

def test_bypass_allows_and_emits_event(maps):
    maps.set_bypass(CG, int(time.time()) + 60)
    v = policy.connect4(maps, CG, "203.0.113.9", 443)
    assert v.action is Action.ALLOW and v.reason is Reason.BYPASS
    assert policy.sock_create(maps, CG, 2, policy.SOCK_RAW).action is Action.ALLOW
    evs = maps.drain_events()
    assert any(e.reason is Reason.BYPASS for e in evs)


def test_bypass_deadman_expiry(maps):
    maps.set_bypass(CG, int(time.time()) - 1)
    expired = list(iter_expired_bypass(maps))
    assert expired == [CG]
    for cg in expired:
        maps.clear_bypass(cg)
    assert policy.connect4(maps, CG, "203.0.113.9", 443).action is Action.DENY


# -- scenario: DNS is forced through the gate -------------------------------

def test_hardcoded_resolver_rewritten_to_gate(maps):
    v = policy.connect4(maps, CG, "8.8.8.8", 53, PROTO_UDP)
    assert v.action is Action.REDIRECT_DNS
    assert (v.redirect_ip, v.redirect_port) == (DNSGATE, 53)


def test_gate_dns_allowed_directly(maps):
    assert policy.connect4(maps, CG, DNSGATE, 53, PROTO_UDP).action is Action.ALLOW


# -- scenario: infra endpoints ----------------------------------------------

def test_envoy_and_loopback_and_hostproxy_allowed(maps):
    assert policy.connect4(maps, CG, ENVOY, 10000).reason is Reason.ENVOY
    assert policy.connect4(maps, CG, "127.0.0.1", 8080).reason is Reason.LOOPBACK
    # host-proxy reachability (firewall_test.go:452)
    assert policy.connect4(maps, CG, HOSTPROXY, 18374).reason is Reason.HOSTPROXY
    # ...but only on the flagged port
    assert policy.connect4(maps, CG, HOSTPROXY, 22).action is Action.DENY


def test_hostproxy_flag_off_denies(maps):
    maps.enroll(CG, ContainerPolicy(envoy_ip=ENVOY, dns_ip=DNSGATE,
                                    hostproxy_ip=HOSTPROXY, hostproxy_port=18374,
                                    flags=FLAG_ENFORCE))
    assert policy.connect4(maps, CG, HOSTPROXY, 18374).action is Action.DENY


# -- scenario: UDP reverse NAT via socket cookie ----------------------------

def test_udp_redirect_reverse_nat(maps):
    cookie = 777
    v = policy.sendmsg4(maps, CG, cookie, "9.9.9.9", 53)
    assert v.action is Action.REDIRECT_DNS
    # reply arrives from the gate; the app sees the resolver it aimed at
    src = policy.recvmsg4(maps, CG, cookie, DNSGATE, 53)
    assert src == ("9.9.9.9", 53)
    # unrelated source passes through untouched
    assert policy.recvmsg4(maps, CG, cookie, "1.2.3.4", 9) == ("1.2.3.4", 9)
    # getpeername mirrors the same reverse mapping
    assert policy.getpeername4(maps, CG, cookie, DNSGATE, 53) == ("9.9.9.9", 53)


def test_tcp_connect_redirect_getpeername_reverse(maps):
    """Connected-TCP redirects report the original dst via getpeername,
    and TCP churn lives in its own LRU so it can't evict UDP entries."""
    cache(maps, "93.184.216.34", "example.com")
    route(maps, "example.com", 443, PROTO_TCP,
          RouteVal(Action.REDIRECT, redirect_ip=ENVOY, redirect_port=10000))
    v = policy.connect4(maps, CG, "93.184.216.34", 443, PROTO_TCP, sock_cookie=555)
    assert v.action is Action.REDIRECT
    assert policy.getpeername4(maps, CG, 555, ENVOY, 10000) == ("93.184.216.34", 443)
    # recvmsg (UDP-only path) must NOT consult the tcp flow table
    assert policy.recvmsg4(maps, CG, 555, ENVOY, 10000) == (ENVOY, 10000)
    # the TCP entry went to tcp_flows, not udp_flows
    assert maps.lookup_udp_flow(555) is None
    assert maps.lookup_tcp_flow(555) is not None


def test_bypass_opens_ipv6_too(maps):
    maps.set_bypass(CG, int(time.time()) + 60)
    v = policy.connect6(maps, CG, "2606:4700::1111", 443)
    assert v.action is Action.ALLOW and v.reason is Reason.BYPASS


def test_udp_flow_lru_bound():
    m = FakeMaps()
    for c in range(UDP_FLOWS_MAX + 10):
        m.record_udp_flow(c, UdpFlow("1.1.1.1", 53))
    assert m.lookup_udp_flow(0) is None          # evicted
    assert m.lookup_udp_flow(UDP_FLOWS_MAX + 9) is not None


# -- scenario: IPv6 ----------------------------------------------------------

def test_connect6_v4mapped_routes_native_denied(maps):
    cache(maps, "93.184.216.34", "example.com")
    route(maps, "example.com", 443, PROTO_TCP,
          RouteVal(Action.REDIRECT, redirect_ip=ENVOY, redirect_port=10000))
    v = policy.connect6(maps, CG, "::ffff:93.184.216.34", 443)
    assert v.action is Action.REDIRECT
    v6 = policy.connect6(maps, CG, "2606:4700::1111", 443)
    assert v6.action is Action.DENY and v6.reason is Reason.IPV6
    assert policy.connect6(maps, CG, "::1", 443).action is Action.ALLOW
    assert policy.connect6(maps, 999, "2606:4700::1111", 443).action is Action.ALLOW


# -- scenario: monitor (non-enforcing) mode ---------------------------------

def test_monitor_mode_allows_but_logs(maps):
    maps.enroll(CG, ContainerPolicy(envoy_ip=ENVOY, dns_ip=DNSGATE, flags=0))
    v = policy.connect4(maps, CG, "203.0.113.9", 443)
    assert v.action is Action.ALLOW and v.reason is Reason.MONITOR
    assert any(e.reason is Reason.MONITOR for e in maps.drain_events())


# -- scenario: dns cache TTL GC ---------------------------------------------

def test_dns_cache_expiry_gc(maps):
    now = int(time.time())
    maps.cache_dns("1.2.3.4", DnsEntry(zone_hash=1, expires_unix=now - 5))
    maps.cache_dns("5.6.7.8", DnsEntry(zone_hash=2, expires_unix=now + 500))
    assert maps.expire_dns() == 1
    assert maps.lookup_dns("1.2.3.4") is None
    assert maps.lookup_dns("5.6.7.8") is not None


# -- route-table construction from egress rules -----------------------------

def test_build_routes_wildcard_and_tcp_mapping():
    rules = [
        EgressRule(dst="*.example.com", proto="https"),
        EgressRule(dst="plain.example.org", proto="http"),
        EgressRule(dst="github.com", proto="tcp", port=22),
        EgressRule(dst="ntp.example.net", proto="udp", port=123),
    ]
    table = policy.build_routes(
        rules, envoy_ip=ENVOY, tls_port=10000,
        tcp_ports={"github.com:tcp:22": 10001, "plain.example.org:http:80": 10002},
    )
    # wildcard rule routes on the apex hash
    https = table[RouteKey(zone_hash("example.com"), 443, PROTO_TCP)]
    assert https.action is Action.REDIRECT and https.redirect_port == 10000
    # http rides its allocated plain-HTTP lane, never the TLS listener
    http = table[RouteKey(zone_hash("plain.example.org"), 80, PROTO_TCP)]
    assert http.action is Action.REDIRECT and http.redirect_port == 10002
    # without an allocated lane, http falls back to direct allow
    bare = policy.build_routes(rules, envoy_ip=ENVOY, tls_port=10000)
    assert bare[RouteKey(zone_hash("plain.example.org"), 80, PROTO_TCP)].action is Action.ALLOW
    # SSH TCP mapping (firewall_test.go:503): per-rule Envoy TCP listener
    ssh = table[RouteKey(zone_hash("github.com"), 22, PROTO_TCP)]
    assert ssh.action is Action.REDIRECT and ssh.redirect_port == 10001
    udp = table[RouteKey(zone_hash("ntp.example.net"), 123, PROTO_UDP)]
    assert udp.action is Action.ALLOW


def test_events_ring_bounded():
    m = FakeMaps()
    m.enroll(CG, ContainerPolicy(envoy_ip=ENVOY, dns_ip=DNSGATE))
    from clawker_tpu.firewall.maps import EVENTS_RING_MAX

    for _ in range(EVENTS_RING_MAX + 7):
        policy.connect4(m, CG, "203.0.113.9", 443)
    assert m.events_dropped == 7


# -- ABI pins: C struct twins must match these exactly ----------------------

def test_abi_struct_sizes():
    assert ContainerPolicy.SIZE == 28
    assert DnsEntry.SIZE == 16
    assert RouteKey.SIZE == 12
    assert RouteVal.SIZE == 8
    assert UdpFlow.SIZE == 8
    assert EgressEvent.SIZE == 40


def test_abi_pack_roundtrip():
    p = ContainerPolicy(envoy_ip="10.0.0.2", dns_ip="10.0.0.3",
                        hostproxy_ip="172.17.0.1", hostproxy_port=18374,
                        flags=FLAG_ENFORCE | FLAG_HOSTPROXY)
    assert ContainerPolicy.unpack(p.pack()) == p
    k = RouteKey(zone_hash("example.com"), 443, PROTO_TCP)
    assert RouteKey.unpack(k.pack()) == k
    v = RouteVal(Action.REDIRECT, redirect_ip="10.0.0.2", redirect_port=10000)
    assert RouteVal.unpack(v.pack()) == v
    f = UdpFlow("9.9.9.9", 53)
    assert UdpFlow.unpack(f.pack()) == f
    e = EgressEvent(ts_ns=1, cgroup_id=CG, dst_ip="1.2.3.4", dst_port=443,
                    zone_hash=zone_hash("example.com"), verdict=Action.DENY,
                    proto=PROTO_TCP, reason=Reason.NO_ROUTE)
    assert EgressEvent.unpack(e.pack()) == e


def test_zone_hash_pinned_vectors():
    """Known vectors: the C fw_zone_hash must reproduce these exactly
    (native/ebpf test target checks the same table)."""
    assert zone_hash("") == 0xCBF29CE484222325
    assert zone_hash("a") == 0xAF63DC4C8601EC8C
    assert zone_hash("example.com") == zone_hash("EXAMPLE.COM.")
    assert zone_hash("example.com") != zone_hash("example.org")
