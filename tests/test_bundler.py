"""Bundle resolution + Dockerfile generation + `clawker build` pipeline."""

import tarfile
import io
from pathlib import Path

import pytest
from click.testing import CliRunner

from clawker_tpu import consts
from clawker_tpu.bundle import BundleManager, Resolver
from clawker_tpu.bundler import (
    ProjectBuilder,
    build_context,
    compose_egress_rules,
    generate_base,
    generate_harness,
)
from clawker_tpu.cli.factory import Factory
from clawker_tpu.cli.root import cli
from clawker_tpu.config import load_config
from clawker_tpu.config.schema import BuildConfig, EgressRule
from clawker_tpu.engine.drivers import FakeDriver
from clawker_tpu.errors import NotFoundError


@pytest.fixture()
def cfg(tenv, tmp_path):
    tenv.make_project(tmp_path, "project: demo\nbuild:\n  stack: go\n")
    return load_config(tmp_path)


# ---------------------------------------------------------------- resolver

def test_floor_assets_resolve(cfg):
    r = Resolver(cfg)
    claude = r.harness("claude")
    assert claude.tier == "floor" and claude.cmd == ["claude"]
    assert {s.name for s in r.list("stack")} >= {
        "python", "go", "node", "rust", "cpp", "java", "ruby", "dotnet"
    }
    with pytest.raises(NotFoundError):
        r.harness("nope")


def test_installed_bundle_shadows_floor(cfg, tmp_path):
    src = tmp_path / "mybundle"
    (src / "harnesses" / "claude").mkdir(parents=True)
    (src / "harnesses" / "claude" / "harness.yaml").write_text(
        "name: claude\ncmd: [my-claude]\n"
    )
    mgr = BundleManager(cfg)
    b = mgr.install(str(src))
    assert b.components["harness"] == ["claude"]
    assert Resolver(cfg).harness("claude").cmd == ["my-claude"]
    mgr.remove("local", "mybundle")
    assert Resolver(cfg).harness("claude").cmd == ["claude"]


def test_bundle_install_rejects_symlinks_and_empty(cfg, tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    mgr = BundleManager(cfg)
    with pytest.raises(Exception, match="no harness"):
        mgr.install(str(empty))
    bad = tmp_path / "bad"
    (bad / "harnesses" / "x").mkdir(parents=True)
    (bad / "harnesses" / "x" / "harness.yaml").write_text("name: x\ncmd: [x]\n")
    (bad / "evil").symlink_to("/etc/passwd")
    with pytest.raises(Exception, match="symlink"):
        mgr.install(str(bad))


# --------------------------------------------------------------- dockerfile

def test_generate_base_deterministic(cfg):
    stack = Resolver(cfg).stack("go")
    df1 = generate_base("demo", stack, BuildConfig(packages=["jq"]))
    df2 = generate_base("demo", stack, BuildConfig(packages=["jq"]))
    assert df1 == df2
    assert "FROM golang:" in df1
    assert "jq" in df1 and "useradd" in df1 and consts.WORKSPACE_DIR in df1


def test_generate_harness_cache_tail(cfg):
    harness = Resolver(cfg).harness("claude")
    df = generate_harness(
        "demo", harness, BuildConfig(), with_ca_cert=True, with_agentd=True
    )
    # supervisor/agentd COPYs must come after every install RUN and after
    # the CA COPY (cache-tail invariant)
    agentd_at = df.index("COPY clawker-supervisord")
    assert df.index("npm install") < agentd_at
    assert df.index("COPY clawker-ca.crt") < agentd_at
    assert df.index("COPY clawker-agentd.pyz") > agentd_at
    assert df.rstrip().endswith('CMD ["claude"]')
    # PID 1 = native supervisor; agentd zipapp is its --child; image CMD
    # flows into agentd's --default-cmd via Docker's ENTRYPOINT+CMD concat
    assert f'ENTRYPOINT ["{consts.SUPERVISOR_PATH}"' in df
    assert df.index("--default-cmd") < df.index('CMD ["claude"]')


def test_build_context_deterministic_tar():
    files = {"Dockerfile": b"FROM x\n", "clawkerd": b"\x7fELF"}
    t1, t2 = build_context(files), build_context(files)
    assert t1 == t2
    names = tarfile.open(fileobj=io.BytesIO(t1)).getnames()
    assert names == sorted(names)


# ------------------------------------------------------------------ egress

def test_compose_egress_rules_dedupes(cfg):
    harness = Resolver(cfg).harness("claude")
    pconf = cfg.project
    pconf.security.egress.append(EgressRule(dst="api.anthropic.com", proto="https"))
    pconf.security.egress.append(EgressRule(dst="internal.corp", proto="tcp", port=22))
    rules = compose_egress_rules(pconf, harness)
    keys = [r.key() for r in rules]
    assert len(keys) == len(set(keys))
    assert "api.anthropic.com:https:443" in keys
    assert "internal.corp:tcp:22" in keys


# ------------------------------------------------------------- build + CLI

def test_project_builder_two_stages(cfg):
    drv = FakeDriver()
    eng = drv.api and drv.workers()[0].require_engine()
    pb = ProjectBuilder(eng, cfg)
    res = pb.build()
    assert res.base_ref == "clawker-demo:base"
    assert res.harness_ref == "clawker-demo:claude"
    assert res.default_ref == "clawker-demo:default"
    assert "clawker-demo:default" in drv.api.images
    builds = drv.api.calls_named("image_build")
    assert [b[1]["tags"] for b in builds] == [["clawker-demo:base"], ["clawker-demo:claude"]]
    assert builds[0][1]["labels"][consts.LABEL_IMAGE_KIND] == "base"
    assert builds[1][1]["labels"][consts.LABEL_HARNESS] == "claude"


def test_build_cli_then_run(tenv, tmp_path):
    tenv.make_project(tmp_path, "project: demo\n")
    drv = FakeDriver()
    factory = Factory(cwd=tmp_path, driver=drv)
    runner = CliRunner()
    res = runner.invoke(cli, ["build", "-q"], obj=factory, catch_exceptions=False)
    assert res.exit_code == 0, res.output
    assert "clawker-demo:default" in res.output
    # the freshly built image satisfies `run` image resolution
    from clawker_tpu.engine.fake import exit_behavior

    drv.api.set_behavior("clawker-demo:default", exit_behavior(b"hi\n"))
    res = runner.invoke(cli, ["run"], obj=factory, catch_exceptions=False)
    assert res.exit_code == 0, res.output


def test_bundle_cli_list_validate(tenv, tmp_path):
    tenv.make_project(tmp_path, "project: demo\n")
    factory = Factory(cwd=tmp_path, driver=FakeDriver())
    runner = CliRunner()
    res = runner.invoke(cli, ["bundle", "list"], obj=factory, catch_exceptions=False)
    assert res.exit_code == 0
    assert "claude" in res.output and "floor" in res.output
    src = tmp_path / "b"
    (src / "stacks" / "zig").mkdir(parents=True)
    (src / "stacks" / "zig" / "stack.yaml").write_text("name: zig\nbase_image: alpine\n")
    res = runner.invoke(cli, ["bundle", "validate", str(src)], obj=factory)
    assert res.exit_code == 0, res.output
    res = runner.invoke(cli, ["bundle", "install", str(src)], obj=factory)
    assert res.exit_code == 0, res.output
    res = runner.invoke(cli, ["bundle", "list"], obj=factory)
    assert "zig" in res.output
    res = runner.invoke(cli, ["bundle", "remove", "b"], obj=factory)
    assert res.exit_code == 0, res.output


def test_harness_file_escape_rejected(cfg, tmp_path):
    src = tmp_path / "esc"
    hdir = src / "harnesses" / "h"
    hdir.mkdir(parents=True)
    hdir.joinpath("harness.yaml").write_text(
        "name: h\ncmd: [h]\nfiles: ['../../../secret.txt']\n"
    )
    tmp_path.joinpath("secret.txt").write_text("s3cret")
    cfg.project.build.harness = "h"
    # loose tier: place under project .clawker/bundles
    import shutil

    loose = cfg.project_root / ".clawker" / "bundles" / "esc"
    shutil.copytree(src, loose)
    drv = FakeDriver()
    with pytest.raises(Exception, match="escapes"):
        ProjectBuilder(drv.workers()[0].require_engine(), cfg).build()


def test_stack_install_gets_run_prefix_and_cmd_json(cfg):
    from clawker_tpu.bundle.model import Harness, Stack

    stack = Stack(name="s", base_image="debian", install=["pip install uv"])
    df = generate_base("demo", stack, BuildConfig())
    assert "RUN pip install uv" in df
    h = Harness(name="h", cmd=["sh", "-c", 'echo "hi"'])
    df = generate_harness("demo", h, BuildConfig(), with_agentd=False)
    assert 'CMD ["sh", "-c", "echo \\"hi\\""]' in df


def test_reinstall_preserves_other_bundles_and_updates(cfg, tmp_path):
    src = tmp_path / "rb"
    (src / "stacks" / "s1").mkdir(parents=True)
    (src / "stacks" / "s1" / "stack.yaml").write_text("name: s1\nbase_image: a:1\n")
    mgr = BundleManager(cfg)
    mgr.install(str(src))
    (src / "stacks" / "s1" / "stack.yaml").write_text("name: s1\nbase_image: a:2\n")
    mgr.install(str(src))
    assert Resolver(cfg).stack("s1").base_image == "a:2"
    assert [b.name for b in mgr.list_installed()] == ["rb"]


def test_no_cache_plumbed_to_daemon(cfg):
    drv = FakeDriver()
    eng = drv.workers()[0].require_engine()
    ProjectBuilder(eng, cfg).build(no_cache=True)
    builds = drv.api.calls_named("image_build")
    assert all(b[1]["no_cache"] for b in builds)
