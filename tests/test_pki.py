"""PKI: CA lifecycle, domain MITM certs, agent/CP leafs."""

import ssl

from cryptography import x509
from cryptography.x509.oid import ExtendedKeyUsageOID

from clawker_tpu.firewall import pki


def test_ensure_ca_idempotent(tmp_path):
    ca1 = pki.ensure_ca(tmp_path)
    ca2 = pki.ensure_ca(tmp_path)
    assert ca1.cert_pem == ca2.cert_pem
    cert = ca1.cert
    bc = cert.extensions.get_extension_for_class(x509.BasicConstraints).value
    assert bc.ca is True
    assert (tmp_path / "ca.key").stat().st_mode & 0o777 == 0o600


def test_rotate_ca_changes_identity(tmp_path):
    ca1 = pki.ensure_ca(tmp_path)
    ca2 = pki.rotate_ca(tmp_path)
    assert ca1.cert_pem != ca2.cert_pem


def test_domain_cert_sans_and_wildcard(tmp_path):
    ca = pki.ensure_ca(tmp_path)
    pair = pki.generate_domain_cert(ca, "*.example.com")
    cert = x509.load_pem_x509_certificate(pair.cert_pem)
    sans = cert.extensions.get_extension_for_class(x509.SubjectAlternativeName).value
    assert set(sans.get_values_for_type(x509.DNSName)) == {"*.example.com", "example.com"}
    eku = cert.extensions.get_extension_for_class(x509.ExtendedKeyUsage).value
    assert ExtendedKeyUsageOID.SERVER_AUTH in eku


def test_agent_cert_client_and_server_auth(tmp_path):
    ca = pki.ensure_ca(tmp_path)
    pair = pki.generate_agent_cert(ca, "demo.dev")
    cert = x509.load_pem_x509_certificate(pair.cert_pem)
    eku = cert.extensions.get_extension_for_class(x509.ExtendedKeyUsage).value
    assert ExtendedKeyUsageOID.CLIENT_AUTH in eku and ExtendedKeyUsageOID.SERVER_AUTH in eku
    assert cert.subject.rfc4514_string() == "CN=demo.dev"


def test_leaf_verifies_against_ca_via_ssl(tmp_path):
    """The chain is usable by real TLS stacks (ssl context load)."""
    ca = pki.ensure_ca(tmp_path)
    pair = pki.generate_cp_cert(ca)
    (tmp_path / "leaf.crt").write_bytes(pair.cert_pem)
    (tmp_path / "leaf.key").write_bytes(pair.key_pem)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(tmp_path / "leaf.crt", tmp_path / "leaf.key")
    store = x509.verification.Store([ca.cert])
    builder = x509.verification.PolicyBuilder().store(store)
    builder.build_client_verifier().verify(
        x509.load_pem_x509_certificate(pair.cert_pem), []
    )
