"""Unified fleet telemetry: registry semantics under concurrency, the
Prometheus exposition contract, span-tree reconstruction from an
out-of-order flight record, the exporters, the EventBus per-agent
index, and the flagship end-to-end: an 8-loop FakeDriver pod run (with
an injected wedge -> migrate) whose every iteration must yield a
complete span tree.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from clawker_tpu import consts, telemetry
from clawker_tpu.config import load_config
from clawker_tpu.engine.drivers import FakeDriver
from clawker_tpu.engine.fake import exit_behavior
from clawker_tpu.health import BreakerConfig, HealthConfig
from clawker_tpu.loop import LoopScheduler, LoopSpec
from clawker_tpu.monitor.events import EventBus
from clawker_tpu.monitor.ledger import FlightRecorder, flight_path
from clawker_tpu.telemetry import (
    MetricsOtlpShipper,
    MetricsRegistry,
    MetricsServer,
    SpanRecord,
    Tracer,
    build_trees,
    load_spans,
)
from clawker_tpu.testenv import TestEnv

IMAGE = "clawker-teleproj:default"


@pytest.fixture
def env():
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: teleproj\n")
        cfg = load_config(proj)
        yield tenv, proj, cfg


def driver_with(n_workers: int, behavior=None):
    drv = FakeDriver(n_workers=n_workers)
    for api in drv.apis:
        api.add_image(IMAGE)
        api.set_behavior(IMAGE, behavior or exit_behavior(b"done\n", 0))
    return drv


# ----------------------------------------------------------------- registry


def test_registry_concurrent_mutation_from_eight_threads():
    """8+ writer threads on shared and per-thread series: every record
    lands exactly once (the lock-striping must never lose increments)."""
    reg = MetricsRegistry()
    shared = reg.counter("t_shared_total", "shared")
    per = reg.counter("t_per_total", "per-thread", labels=("t",))
    hist = reg.histogram("t_lat_seconds", "lat", labels=("t",))
    gauge = reg.gauge("t_gauge", "gauge")
    n_threads, per_thread = 10, 2000
    start = threading.Barrier(n_threads)

    def writer(idx: int) -> None:
        start.wait()
        mine = per.labels(str(idx))
        h = hist.labels(str(idx))
        for i in range(per_thread):
            shared.inc()
            mine.inc()
            h.observe(0.001 * (i % 7))
            gauge.set(idx)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    snap = {(r["metric"], tuple(sorted(r["labels"].items()))): r
            for r in reg.snapshot()}
    assert snap[("t_shared_total", ())]["value"] == n_threads * per_thread
    for i in range(n_threads):
        key = ("t_per_total", (("t", str(i)),))
        assert snap[key]["value"] == per_thread
        hkey = ("t_lat_seconds", (("t", str(i)),))
        assert snap[hkey]["value"] == per_thread
        assert sum(snap[hkey]["buckets"].values()) == per_thread
    assert snap[("t_gauge", ())]["value"] in set(range(n_threads))


def test_registry_disabled_records_are_dropped_and_reset_zeroes():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "")
    c.inc(5)
    reg.set_enabled(False)
    c.inc(100)
    reg.set_enabled(True)
    assert reg.snapshot()[0]["value"] == 5
    reg.reset()
    assert reg.snapshot()[0]["value"] == 0
    c.inc()     # the handle survives reset
    assert reg.snapshot()[0]["value"] == 1


def test_registry_rejects_kind_conflict_and_is_idempotent():
    reg = MetricsRegistry()
    a = reg.counter("t_total", "", labels=("x",))
    assert reg.counter("t_total", "", labels=("x",)) is a
    with pytest.raises(ValueError):
        reg.gauge("t_total", "")
    with pytest.raises(ValueError):
        a.labels("1", "2")      # wrong label arity


def test_prometheus_exposition_golden():
    """The exact text-format contract a scraper parses: HELP/TYPE lines,
    label escaping, cumulative histogram buckets with le and +Inf,
    _sum/_count."""
    reg = MetricsRegistry()
    c = reg.counter("engine_dials_total", "Engine-API socket dials")
    c.inc(3)
    g = reg.gauge("health_breaker_state", "Breaker state", labels=("worker",))
    g.labels("fake-0").set(0)
    g.labels("fake-1").set(2)
    h = reg.histogram("probe_seconds", "Probe latency", labels=("worker",),
                      buckets=(0.1, 1.0))
    h.labels("fake-0").observe(0.05)
    h.labels("fake-0").observe(0.5)
    h.labels("fake-0").observe(5.0)
    assert reg.exposition() == (
        "# HELP engine_dials_total Engine-API socket dials\n"
        "# TYPE engine_dials_total counter\n"
        "engine_dials_total 3\n"
        "# HELP health_breaker_state Breaker state\n"
        "# TYPE health_breaker_state gauge\n"
        'health_breaker_state{worker="fake-0"} 0\n'
        'health_breaker_state{worker="fake-1"} 2\n'
        "# HELP probe_seconds Probe latency\n"
        "# TYPE probe_seconds histogram\n"
        'probe_seconds_bucket{worker="fake-0",le="0.1"} 1\n'
        'probe_seconds_bucket{worker="fake-0",le="1"} 2\n'
        'probe_seconds_bucket{worker="fake-0",le="+Inf"} 3\n'
        'probe_seconds_sum{worker="fake-0"} 5.55\n'
        'probe_seconds_count{worker="fake-0"} 3\n'
    )


def test_exposition_escapes_label_values():
    reg = MetricsRegistry()
    reg.counter("t_total", "", labels=("w",)).labels('a"b\\c\nd').inc()
    text = reg.exposition()
    assert 't_total{w="a\\"b\\\\c\\nd"} 1' in text


# ----------------------------------------------------------- scrape server


def test_metrics_server_serves_exposition():
    reg = MetricsRegistry()
    reg.counter("t_scraped_total", "scrape me").inc(7)
    srv = MetricsServer(0, registry=reg).start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5).read().decode()
        assert "t_scraped_total 7" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=5)
    finally:
        srv.stop()


# ------------------------------------------------------------ otlp shipper


def test_otlp_shipper_ships_snapshots_and_final_flush():
    reg = MetricsRegistry()
    reg.counter("t_shipped_total", "").inc(2)
    batches: list[list[dict]] = []

    class Lane:
        def ship(self, records):
            batches.append(records)
            return True

    shipper = MetricsOtlpShipper(Lane(), registry=reg, interval_s=3600.0)
    shipper.start()
    shipper.stop()          # final flush must land without the interval
    assert shipper.shipped_batches >= 1
    rec = next(r for r in batches[-1] if r["metric"] == "t_shipped_total")
    assert rec["value"] == 2 and rec["kind"] == "counter"


# -------------------------------------------------------- flight recorder


def test_flight_recorder_append_read_and_truncated_tail(tmp_path):
    path = tmp_path / "flight" / "loop-abc.jsonl"
    rec = FlightRecorder(path)
    rec.append({"kind": "span", "span_id": "s1"})
    rec.append({"kind": "note", "x": 1})
    rec.close()
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"kind": "span", "span_id": "trunc')   # crashed writer
    docs = FlightRecorder.read(path)
    assert [d.get("kind") for d in docs] == ["span", "note"]
    assert flight_path(tmp_path, "abc").name == "loop-abc.jsonl"


# ------------------------------------------------- span tree reconstruction


def _span(span_id, parent, name, agent="a0", t0=0.0, t1=1.0, status="ok",
          **attrs):
    return SpanRecord(trace_id="run1", span_id=span_id, parent_id=parent,
                      name=name, agent=agent, worker="fake-0",
                      t_start=t0, t_end=t1, status=status, attrs=attrs)


def test_build_trees_from_out_of_order_ledger():
    """Children recorded before their root (lane threads flush phase
    spans long before the run thread closes the iteration), interleaved
    across agents, plus an orphan child whose parent never flushed."""
    records = [
        _span("w0", "i0", "wait", t0=2.0, t1=4.0, iteration=0),
        _span("e1", "i1", "exit", agent="a1", t0=4.0, t1=4.0, iteration=0),
        _span("c0", "i0", "create", t0=0.5, t1=1.0, iteration=0),
        _span("i1", "", "iteration", agent="a1", t0=0.0, t1=4.0, iteration=0),
        _span("s0", "i0", "start", t0=1.0, t1=2.0, iteration=0),
        _span("lost", "never-flushed", "wait", agent="a2", t0=9.0, t1=9.5),
        _span("i0", "", "iteration", t0=0.0, t1=4.0, iteration=0),
        _span("x0", "i0", "exit", t0=4.0, t1=4.0, iteration=0),
    ]
    roots = build_trees(records)
    by_id = {r.record.span_id: r for r in roots}
    assert set(by_id) == {"i0", "i1", "lost"}   # orphan child promoted
    i0 = by_id["i0"]
    assert [c.record.name for c in i0.children] == [
        "create", "start", "wait", "exit"]      # start-time order
    assert i0.record.wall_s == 4.0
    # round-trips through JSONL identically
    lines = [json.dumps(r.to_json()) for r in records]
    assert build_trees(load_spans(lines))[0].record == roots[0].record


def test_load_spans_skips_corrupt_and_foreign_lines():
    lines = ['{"kind": "span", "span_id": "s", "trace_id": "t", '
             '"parent_id": "", "name": "iteration", "agent": "a", '
             '"worker": "w", "t_start": 1, "t_end": 2}',
             "not json at all", '{"kind": "other"}', ""]
    spans = load_spans(lines)
    assert len(spans) == 1 and spans[0].wall_s == 1.0


def test_tracer_idempotent_begin_and_close_open():
    flushed: list[SpanRecord] = []
    tr = Tracer("run1", on_span=flushed.append)
    a = tr.begin_iteration("a0", 0, "fake-0", epoch=0)
    # repeat begin: same root, attrs merge with first-value-wins (the
    # rescue pass opens a root before the lane measures its queue wait)
    assert tr.begin_iteration("a0", 0, "fake-9",
                              epoch=9, queue_ms=1.5) == a
    tr.child("a0", 0, "create", 0.0, 1.0)
    root = tr.end_iteration("a0", 0, status="ok")
    assert root.span_id == a
    assert root.attrs["epoch"] == 0 and root.attrs["queue_ms"] == 1.5
    assert tr.child("a0", 0, "late", 0.0, 1.0) is None  # closed: no orphans
    tr.begin_iteration("a0", 1, "fake-0")
    assert tr.close_open("stopped") == 1
    assert [r.name for r in flushed] == ["create", "iteration", "iteration"]
    assert flushed[-1].status == "stopped"


# ------------------------------------------------------ event bus index


def test_event_bus_zero_history_neither_indexes_nor_raises():
    bus = EventBus(None, history=0)
    bus.emit("a", "e", "0")     # must not IndexError on the empty deque
    bus.emit("a", "e", "1")
    assert len(bus.history) == 0
    assert bus.for_agent("a") == []   # the index mirrors the history


def test_event_bus_for_agent_index_tracks_bounded_eviction():
    bus = EventBus(None, history=8)
    for i in range(6):
        bus.emit("a", "e", str(i))
        bus.emit("b", "e", str(i))
    # 12 emits through a maxlen-8 history: the oldest 4 were evicted
    assert len(bus.history) == 8
    a_recs = bus.for_agent("a")
    assert [r.detail for r in a_recs] == ["2", "3", "4", "5"]
    assert [r.detail for r in bus.for_agent("b")] == ["2", "3", "4", "5"]
    # the index returns the SAME records the history holds, in order
    assert [r for r in bus.history if r.agent == "a"] == a_recs
    assert bus.for_agent("nobody") == []


# ----------------------------------------------------- end-to-end span run


def test_eight_loop_run_with_migration_yields_complete_span_trees(env):
    """BASELINE-shaped pod run: 8 loops on 4 fake workers, 2 iterations
    each, one worker WEDGED mid-run (hung daemon: probes hit their
    deadline, lanes freeze) under --failover migrate.  EVERY accounted
    iteration must reconstruct to a complete span tree (start + wait +
    exit under its root), the migrated loops' hops must appear as
    migrate spans, and the orphaned attempts must close as orphaned --
    the acceptance bar for `clawker loop trace`."""
    tenv, proj, cfg = env
    drv = driver_with(4, behavior=exit_behavior(b"", 0, delay=0.1))
    iterations = 2
    victim = drv.workers()[1].id
    sched = LoopScheduler(
        cfg, drv, LoopSpec(parallel=8, iterations=iterations,
                           failover="migrate"),
        health_config=HealthConfig(
            probe_interval_s=0.05, probe_deadline_s=0.5,
            breaker=BreakerConfig(failure_threshold=3, backoff_base_s=0.05,
                                  backoff_max_s=0.2)))
    sched.start()
    runner = threading.Thread(target=sched.run, kwargs={"poll_s": 0.05},
                              daemon=True)
    runner.start()
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:       # victim must be mid-loop
        if any(l.status == "running" and l.worker.id == victim
               for l in sched.loops):
            break
        time.sleep(0.01)
    drv.inject_fault(1, "wedge")
    runner.join(30.0)
    assert not runner.is_alive()
    assert all(l.status == "done" and l.iteration == iterations
               for l in sched.loops)
    migrated = [l for l in sched.loops if l.migrations]
    assert migrated, "the wedged worker's loops must have migrated"
    flight = sched.flight.path
    drv.clear_fault(1)      # revive so cleanup's removals don't block
    sched.cleanup(remove_containers=True)

    spans = load_spans(flight.read_text().splitlines())
    trees = build_trees(spans)
    roots = [t for t in trees if t.record.name == "iteration"]
    assert all(t.record.name == "iteration" for t in trees), \
        "no span may lose its parent in a clean run"
    # every accounted iteration of every agent has exactly one OK tree
    ok_roots: dict[tuple[str, int], list] = {}
    for t in roots:
        key = (t.record.agent, t.record.attrs.get("iteration"))
        if t.record.status == "ok":
            ok_roots.setdefault(key, []).append(t)
    for loop in sched.loops:
        for i in range(iterations):
            (tree,) = ok_roots[(loop.agent, i)]
            names = [c.record.name for c in tree.children]
            assert names.count("start") == 1, (loop.agent, i, names)
            assert names.count("wait") == 1, (loop.agent, i, names)
            assert names.count("exit") == 1, (loop.agent, i, names)
            exit_span = next(c.record for c in tree.children
                             if c.record.name == "exit")
            assert exit_span.attrs.get("code") == 0
            assert tree.record.worker      # placement attribute present
            assert tree.record.attrs.get("epoch") is not None
        # iteration 0 of a fresh placement includes the create span
        first = ok_roots[(loop.agent, 0)][0]
        first_names = [c.record.name for c in first.children]
        if not loop.migrations:
            assert "create" in first_names
    # the injected death shows up as orphaned attempts + migrate hops
    orphaned = [t for t in roots if t.record.status == "orphaned"]
    assert orphaned
    assert all(any(c.record.name == "orphan" for c in t.children)
               for t in orphaned)
    hops = [s for s in spans if s.name == "migrate"]
    assert hops and all(s.attrs["src"] != s.attrs["dst"] for s in hops)
    assert {s.agent for s in hops} == {l.agent for l in migrated}
    # a migrated attempt re-creates on the new worker: its OK tree holds
    # both the migrate hop and a fresh create
    for l in migrated:
        resumed = [t for ts in ok_roots.items() if ts[0][0] == l.agent
                   for t in ts[1]
                   if any(c.record.name == "migrate" for c in t.children)]
        assert resumed
        assert all(any(c.record.name == "create" for c in t.children)
                   for t in resumed)
        # the re-placed launch's lane queue wait must reach the fresh
        # root even though the rescue pass opened it first
        assert all(t.record.attrs.get("queue_ms") is not None
                   for t in resumed)


def test_loop_run_exports_documented_metric_names(env):
    """After a real (fake-driver) loop run, the process registry serves
    every metric family docs/telemetry.md documents."""
    tenv, proj, cfg = env
    drv = driver_with(2)
    sched = LoopScheduler(cfg, drv, LoopSpec(parallel=2, iterations=1))
    sched.start()
    sched.run(poll_s=0.05)
    sched.cleanup(remove_containers=True)
    text = telemetry.REGISTRY.exposition()
    for family in ("engine_dials_total", "engine_reuses_total",
                   "engine_stale_retries_total",
                   "engine_retries_suppressed_total",
                   "loop_lane_queue_seconds", "loop_lane_execute_seconds",
                   "loop_iterations_total", "health_breaker_state",
                   "placement_decisions_total", "placement_queue_depth",
                   "placement_inflight_launches",
                   "placement_admission_wait_seconds"):
        assert f"# TYPE {family} " in text, family


# ------------------------------------------------------------- trace CLI


def test_cli_loop_trace_renders_tree_and_json(env):
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    drv = driver_with(2)
    res = CliRunner().invoke(
        cli, ["loop", "--parallel", "2", "--iterations", "2", "--json"],
        obj=Factory(cwd=proj, driver=drv), catch_exceptions=False)
    assert res.exit_code == 0, res.output
    loop_id = json.loads(res.stdout)["loop_id"]

    res = CliRunner().invoke(
        cli, ["loop", "trace", loop_id],
        obj=Factory(cwd=proj, driver=driver_with(2)), catch_exceptions=False)
    assert res.exit_code == 0, res.output
    assert f"run {loop_id}: 4 iteration span(s) across 2 agent(s)" \
        in res.output
    assert "  start " in res.output and "  wait " in res.output
    assert "  exit " in res.output and "code=0" in res.output

    res = CliRunner().invoke(
        cli, ["loop", "trace", loop_id, "--json"],
        obj=Factory(cwd=proj, driver=driver_with(2)), catch_exceptions=False)
    assert res.exit_code == 0, res.output
    doc = json.loads(res.stdout)
    assert doc["run"] == loop_id and len(doc["iterations"]) == 4
    assert all(i["name"] == "iteration" and i["children"]
               for i in doc["iterations"])

    # unknown and ambiguous runs fail with a clean CLI error
    res = CliRunner().invoke(
        cli, ["loop", "trace", "nosuchrun"],
        obj=Factory(cwd=proj, driver=driver_with(2)))
    assert res.exit_code != 0
    assert "no flight record" in res.output


def test_cli_loop_trace_flags_crashed_run_without_iteration_root(env, tmp_path):
    """A run killed before end_iteration flushed leaves phase spans with
    no root: trace must show them flagged, not hide them or count them
    as iterations."""
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    crashed = tmp_path / "loop-dead.jsonl"
    rec = FlightRecorder(crashed)
    rec.append(_span("c1", "never-flushed", "create", t0=1.0, t1=2.0,
                     iteration=0).to_json())
    rec.close()
    res = CliRunner().invoke(
        cli, ["loop", "trace", str(crashed)],
        obj=Factory(cwd=proj, driver=driver_with(1)), catch_exceptions=False)
    assert res.exit_code == 0, res.output
    assert "0 iteration span(s)" in res.output
    assert "create (no iteration root)" in res.output
    assert "1 span(s) without a recorded iteration root" in res.output


def test_cli_loop_metrics_port_serves_scrape_during_run(env):
    """--metrics-port: the run serves /metrics while loops iterate."""
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    import socket

    tenv, proj, cfg = env
    drv = driver_with(1, behavior=exit_behavior(b"", 0, delay=0.2))
    scraped: list[str] = []
    port_holder: list[int] = []
    orig_start = telemetry.MetricsServer.start
    # 0 means "off" on the flag; grab a free real port for the test
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        free_port = s.getsockname()[1]

    def spy_start(self):
        orig_start(self)
        port_holder.append(self.port)
        return self

    def scrape_later():
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not port_holder:
            time.sleep(0.02)
        if not port_holder:
            return
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                scraped.append(urllib.request.urlopen(
                    f"http://127.0.0.1:{port_holder[0]}/metrics",
                    timeout=2).read().decode())
                return
            except OSError:
                time.sleep(0.05)

    t = threading.Thread(target=scrape_later, daemon=True)
    t.start()
    try:
        telemetry.MetricsServer.start = spy_start
        res = CliRunner().invoke(
            cli, ["loop", "--parallel", "1", "--iterations", "2",
                  "--metrics-port", str(free_port), "--json"],
            obj=Factory(cwd=proj, driver=drv), catch_exceptions=False)
    finally:
        telemetry.MetricsServer.start = orig_start
    t.join(15.0)
    assert res.exit_code == 0, res.output
    assert scraped and "loop_lane_execute_seconds" in scraped[0]
