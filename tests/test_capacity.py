"""Elastic-capacity suite (ISSUE 14 tentpole): the EWMA arrival
estimator, the SLO token-scaling law, reject-with-retry-after under
saturation, journal-gated scale-down, resume restoring controller
state, the chaos stranded-by-drain detector, and the `clawker fleet`
capacity views (docs/elastic-capacity.md)."""

from __future__ import annotations

import json
import threading
import time

import pytest

from clawker_tpu import consts, telemetry
from clawker_tpu.capacity import (
    REC_CAPACITY_POOL,
    REC_CAPACITY_SCALE,
    REC_CAPACITY_TOKENS,
    CapacityController,
    CapacityHooks,
    EwmaRate,
    FakeFleetScaler,
    NullScaler,
    tokens_for,
)
from clawker_tpu.config import load_config
from clawker_tpu.config.schema import (
    CapacityAutoscaleSettings,
    CapacitySettings,
    CapacitySloSettings,
)
from clawker_tpu.engine.drivers import FakeDriver, Worker
from clawker_tpu.engine.fake import exit_behavior
from clawker_tpu.loop import LoopScheduler, LoopSpec
from clawker_tpu.loop.journal import RunJournal, journal_path, replay
from clawker_tpu.loop.warmpool import WarmPool
from clawker_tpu.placement import (
    ADMISSION_DISPATCHED,
    ADMISSION_QUEUED,
    ADMISSION_REJECTED,
    AdmissionController,
)
from clawker_tpu.testenv import TestEnv

IMAGE = "clawker-capproj:default"


@pytest.fixture
def env():
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: capproj\n")
        cfg = load_config(proj)
        yield tenv, proj, cfg


def driver_with(n_workers: int, behavior=None):
    drv = FakeDriver(n_workers=n_workers)
    for api in drv.apis:
        api.add_image(IMAGE)
        api.set_behavior(IMAGE, behavior or exit_behavior(b"done\n", 0,
                                                          delay=0.02))
    return drv


def wait_for(pred, timeout=10.0, interval=0.01) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ------------------------------------------------------- EWMA estimator


def test_ewma_converges_to_constant_rate():
    r = EwmaRate(alpha_up=0.5, alpha_down=0.1)
    for _ in range(60):
        r.observe(10, 1.0)          # 10 events/s, forever
    assert r.value == pytest.approx(10.0, abs=0.01)
    # from above too (decay side)
    r2 = EwmaRate(alpha_up=0.5, alpha_down=0.1)
    r2.observe(100, 1.0)            # seeded high
    for _ in range(120):
        r2.observe(10, 1.0)
    assert r2.value == pytest.approx(10.0, abs=0.1)


def test_ewma_asymmetry_bursts_fast_decays_slow():
    r = EwmaRate(alpha_up=0.5, alpha_down=0.05)
    r.observe(1, 1.0)               # quiet baseline
    r.observe(100, 1.0)             # burst: must jump within one tick
    after_burst = r.value
    assert after_burst > 40.0
    r.observe(1, 1.0)               # back to quiet: must NOT collapse
    assert r.value > after_burst * 0.9


def test_ewma_first_sample_seeds():
    r = EwmaRate()
    r.observe(50, 1.0)
    assert r.value == 50.0          # no blend against the 0.0 prior


def test_pool_target_clamped_to_limits(env):
    """The controller's pool loop clamps targets to
    [pool_min_depth, pool_max_depth] no matter what the rate says."""
    tenv, proj, cfg = env
    telemetry.REGISTRY.reset()
    pool = WarmPool("caprun", depth=0)
    w = Worker(id="cw0", index=0, hostname="cw0", engine=None)
    adm = AdmissionController()
    ctrl = CapacityController(
        CapacitySettings(enable=True, interval_s=0.01, pool_min_depth=1,
                         pool_max_depth=3),
        hooks=CapacityHooks(
            workers=lambda: ["cw0"],
            admission_stats=adm.stats,
            set_token_cap=adm.set_worker_capacity,
            set_shed=adm.set_shed,
            pool_stats=pool.stats,
            set_pool_target=pool.set_target))
    ctrl.tick()
    # a storm of misses (cold checkouts) -> rate explodes; target must
    # stop at max_depth
    for _ in range(500):
        pool.checkout("cw0", by="t", epoch=0)
    time.sleep(0.02)
    ctrl.tick()
    assert ctrl.pool_targets["cw0"] == 3
    assert pool.target_of("cw0") == 3
    # silence decays the rate; the floor holds at min_depth
    for _ in range(300):
        time.sleep(0.001)
        ctrl.tick()
    assert ctrl.pool_targets["cw0"] == 1


# ----------------------------------------------------- SLO token scaling


def test_tokens_for_monotone_grid():
    """The scaling law is monotone: non-decreasing in queue depth and
    launch latency, non-increasing in SLO; always inside [lo, hi]."""
    queues = [0, 1, 4, 16, 64]
    latencies = [0.005, 0.02, 0.1, 0.5]
    slos = [0.05, 0.25, 1.0, 4.0]
    for lat in latencies:
        for slo in slos:
            caps = [tokens_for(q, 0, lat, slo, 2, 16)[0] for q in queues]
            assert caps == sorted(caps), (lat, slo, caps)
            assert all(2 <= c <= 16 for c in caps)
    for q in queues:
        for slo in slos:
            caps = [tokens_for(q, 0, lat, slo, 2, 16)[0]
                    for lat in latencies]
            assert caps == sorted(caps), (q, slo, caps)
    for q in queues:
        for lat in latencies:
            caps = [tokens_for(q, 0, lat, slo, 2, 16)[0] for slo in slos]
            assert caps == sorted(caps, reverse=True), (q, lat, caps)


def test_tokens_for_disabled_slo_returns_floor():
    assert tokens_for(100, 4, 0.1, 0.0, 3, 16) == (3, 0.0)
    assert tokens_for(100, 4, 0.0, 1.0, 3, 16) == (3, 0.0)


def test_slo_scaling_raises_cap_and_dispatches_queue():
    """A queued backlog under a tight SLO scales the worker's bucket up
    through the admission seam, and the raise pumps queued tickets."""
    adm = AdmissionController(max_inflight_per_worker=1)
    running: list = []

    def launch(release):
        running.append(release)     # holds its token until released

    for _ in range(6):
        adm.submit("w0", "t", launch)
    assert len(running) == 1        # one token, five queued
    adm.set_worker_capacity("w0", 4)
    assert len(running) == 4        # the raise pumped three more out
    stats = adm.stats()["workers"]["w0"]
    assert stats["capacity"] == 4
    for r in list(running):
        r()


# ------------------------------------- reject-with-retry-after (shed)


def test_full_queue_rejection_carries_retry_after():
    adm = AdmissionController(max_inflight_per_worker=1,
                              max_pending_per_worker=1)
    adm.submit("w0", "t", lambda release: None)     # takes the token
    adm.submit("w0", "t", lambda release: None)     # fills the queue
    st = adm.submit("w0", "t", lambda release: None)
    assert st == ADMISSION_REJECTED
    assert st.retry_after_s > 0
    assert "queue full" in st.reason


def test_shed_mode_rejects_would_queue_with_retry_after():
    adm = AdmissionController(max_inflight_per_worker=1)
    adm.submit("w0", "t", lambda release: None)     # token held
    adm.set_shed("w0", 0.7)
    st = adm.submit("w0", "t", lambda release: None)
    assert st == ADMISSION_REJECTED
    assert st.retry_after_s == pytest.approx(0.7)
    assert "shed" in st.reason
    # a submission a free token can take immediately still dispatches
    adm.set_shed("w0", 0.0)
    adm.reset_worker("w0")
    ran: list = []
    st = adm.submit("w0", "t", lambda release: ran.append(1))
    assert st == ADMISSION_DISPATCHED and ran


def test_controller_sheds_when_slo_unattainable_and_restores():
    """Saturation past what token_max can drain inside the SLO flips
    the queue to reject-with-retry-after; draining flips it back."""
    clock = [0.0]
    adm = AdmissionController(max_inflight_per_worker=1,
                              clock=lambda: clock[0])
    held: list = []
    for _ in range(40):
        adm.submit("w0", "t", lambda release: held.append(release))
    # teach the gate a launch latency: release one token at +1s
    clock[0] = 1.0
    held.pop(0)()
    journaled: list = []
    ctrl = CapacityController(
        CapacitySettings(enable=True, interval_s=0.01, token_max=2,
                         slo=CapacitySloSettings(default_s=0.2)),
        hooks=CapacityHooks(
            workers=lambda: ["w0"],
            admission_stats=adm.stats,
            set_token_cap=adm.set_worker_capacity,
            set_shed=adm.set_shed,
            journal=lambda kind, **f: journaled.append((kind, f))))
    ctrl.tick()
    assert ctrl.shedding.get("w0", 0.0) > 0
    st = adm.submit("w0", "t", lambda release: None)
    assert st == ADMISSION_REJECTED and st.retry_after_s > 0
    assert any(k == "capacity_queue" and f["mode"] == "reject"
               for k, f in journaled)
    # drain the backlog (each release dispatches the next queued
    # ticket, which appends its own release); the next tick restores
    # queueing
    while held:
        held.pop(0)()
    time.sleep(0.02)
    ctrl.tick()
    assert ctrl.shedding.get("w0", 0.0) == 0.0
    assert any(k == "capacity_queue" and f["mode"] == "queue"
               for k, f in journaled)


def test_scheduler_rescue_honors_retry_after(env):
    """A rejected launch re-places only after the rejection's
    retry_after_s elapsed -- never an immediate bounce -- and the typed
    placement.decision event carries the hint."""
    tenv, proj, cfg = env
    drv = driver_with(1)
    adm = AdmissionController(max_inflight_per_worker=1,
                              max_pending_per_worker=1)
    events: list = []
    sched = LoopScheduler(
        cfg, drv, LoopSpec(parallel=4, iterations=1, placement="pack"),
        admission=adm,
        on_event=lambda a, e, d="": events.append((a, e, d)))
    sched.start()
    loops = sched.run(poll_s=0.05)
    sched.cleanup(remove_containers=True)
    assert all(l.status == "done" for l in loops)
    rejected = [d for _a, e, d in events
                if e == "placement.decision" and "rejected" in d]
    assert rejected and all("retry_after_s=" in d for d in rejected)


# --------------------------------------------- drain gating / autoscale


def _controller_for(sched, drv, **kw):
    settings = CapacitySettings(
        enable=True, interval_s=0.01, pool_max_depth=4,
        autoscale=CapacityAutoscaleSettings(
            enable=True, min_workers=1, max_workers=len(drv.workers()),
            queue_high=10_000, idle_low=0.0, sustain_s=3600.0), **kw)
    ctrl = CapacityController(settings,
                              scaler=FakeFleetScaler(drv))
    sched.attach_capacity(ctrl)
    return ctrl


def test_drain_blocked_by_live_placement_then_fires(env):
    """A requested drain defers while the victim's journal shows live
    placements, and fires once the run has drained off it."""
    tenv, proj, cfg = env
    drv = driver_with(2, exit_behavior(b"", 0, delay=0.05))
    sched = LoopScheduler(cfg, drv,
                          LoopSpec(parallel=2, iterations=1,
                                   placement="spread"))
    ctrl = _controller_for(sched, drv)
    ctrl.request_drain("fake-1")
    sched.start()
    # while the run is live on fake-1 the drain must be BLOCKED
    ctrl.tick()
    assert "fake-1" in ctrl._pending_drain
    assert ctrl.drained == []
    loops = sched.run(poll_s=0.05)
    assert all(l.status == "done" for l in loops)
    # terminal run: the journal now proves zero live placements
    ctrl.tick()
    assert ctrl.drained == ["fake-1"]
    assert [w.id for w in drv.workers()] == ["fake-0"]
    sched.cleanup(remove_containers=True)
    records = RunJournal.read(journal_path(cfg.logs_dir, sched.loop_id))
    kinds = [(r.get("kind"), r.get("phase")) for r in records
             if r.get("kind") == REC_CAPACITY_SCALE]
    assert (REC_CAPACITY_SCALE, "blocked") in kinds
    assert (REC_CAPACITY_SCALE, "intent") in kinds
    assert (REC_CAPACITY_SCALE, "done") in kinds
    # WAL order: the durable intent precedes the done
    assert kinds.index((REC_CAPACITY_SCALE, "intent")) \
        < kinds.index((REC_CAPACITY_SCALE, "done"))


def test_stranded_by_drain_detector_fires_on_bad_journal():
    """The invariant detector flags a drain journaled while placements
    were live -- the violation the gate exists to prevent."""
    from clawker_tpu.chaos.invariants import check_invariants

    class _NoJournal:
        @staticmethod
        def read(path):
            return [
                {"kind": "run", "run": "r1", "spec": {}},
                {"kind": "placement", "agent": "a0", "worker": "w1"},
                {"kind": REC_CAPACITY_SCALE, "action": "drain",
                 "worker": "w1", "phase": "done"},
            ]

    import clawker_tpu.chaos.invariants as inv
    import clawker_tpu.loop.journal as journal_mod

    real = journal_mod.RunJournal.read
    journal_mod.RunJournal.read = _NoJournal.read
    try:
        drv = FakeDriver(n_workers=1)
        with TestEnv() as tenv:
            proj = tenv.base / "proj"
            proj.mkdir()
            (proj / consts.PROJECT_FLAT_FORM).write_text(
                "project: capproj\n")
            cfg = load_config(proj)
            violations = check_invariants(drv, cfg, "r1", loops=[])
        drv.close()
    finally:
        journal_mod.RunJournal.read = real
    assert any(v.startswith("stranded-by-drain") and "a0" in v
               for v in violations)


def test_stranded_by_drain_detector_accepts_gated_drain():
    from clawker_tpu.chaos.invariants import check_invariants

    import clawker_tpu.loop.journal as journal_mod

    recs = [
        {"kind": "run", "run": "r1", "spec": {}},
        {"kind": "placement", "agent": "a0", "worker": "w1"},
        {"kind": "loop_end", "agent": "a0", "status": "done"},
        {"kind": REC_CAPACITY_SCALE, "action": "drain",
         "worker": "w1", "phase": "done"},
    ]
    real = journal_mod.RunJournal.read
    journal_mod.RunJournal.read = staticmethod(lambda path: recs)
    try:
        drv = FakeDriver(n_workers=1)
        with TestEnv() as tenv:
            proj = tenv.base / "proj"
            proj.mkdir()
            (proj / consts.PROJECT_FLAT_FORM).write_text(
                "project: capproj\n")
            cfg = load_config(proj)
            violations = check_invariants(drv, cfg, "r1", loops=[])
        drv.close()
    finally:
        journal_mod.RunJournal.read = real
    assert not any(v.startswith("stranded-by-drain") for v in violations)


def test_chaos_capacity_scenario_green(env):
    """A hand-written capacity plan (traffic burst + scale-down under
    load) runs green end to end: the drain never strands the run and
    every standard invariant holds."""
    from clawker_tpu.chaos.plan import FaultEvent, FaultPlan
    from clawker_tpu.chaos.runner import run_plan

    plan = FaultPlan(
        seed=7, scenario=0, n_workers=3, n_loops=4, iterations=1,
        warm_pool_depth=1, capacity=True,
        events=[
            FaultEvent(at_s=0.05, kind="traffic_burst", worker=0, arg=8),
            FaultEvent(at_s=0.1, kind="scale_down", worker=2),
        ])
    result = run_plan(plan)
    assert result.ok, result.violations
    assert result.injected >= 2


# ------------------------------------------------------ resume restores


def test_resume_restores_controller_state(env):
    """Journaled REC_CAPACITY_* records rebuild the controller's pool
    targets, token caps, and pending drains on --resume."""
    tenv, proj, cfg = env
    drv = driver_with(2)
    sched = LoopScheduler(cfg, drv,
                          LoopSpec(parallel=2, iterations=2,
                                   warm_pool_depth=1))
    ctrl = _controller_for(sched, drv)
    sched.start()
    runner = threading.Thread(target=sched.run, kwargs={"poll_s": 0.05},
                              daemon=True)
    runner.start()
    assert wait_for(lambda: ctrl.ticks >= 1)
    # force a recognizable journaled state, then die mid-run
    sched._journal(REC_CAPACITY_POOL, worker="fake-0", target=3, rate=9.0)
    sched._journal(REC_CAPACITY_TOKENS, worker="fake-1", cap=7,
                   launch_ms=20.0)
    sched._journal(REC_CAPACITY_SCALE, action="drain", worker="fake-1",
                   phase="blocked", live=1)
    sched.journal.sync()
    sched.kill()
    runner.join(5.0)

    image = replay(RunJournal.read(journal_path(cfg.logs_dir,
                                                sched.loop_id)))
    assert image.capacity["pool_targets"]["fake-0"] == 3
    assert image.capacity["token_caps"]["fake-1"] == 7
    assert image.capacity["pending_drain"] == ["fake-1"]

    resumed = LoopScheduler.resume(cfg, drv, image)
    ctrl2 = CapacityController(
        CapacitySettings(enable=True, interval_s=0.01, pool_max_depth=4),
        scaler=NullScaler())
    resumed.attach_capacity(ctrl2)
    assert ctrl2.pool_targets["fake-0"] == 3
    assert resumed.warmpool.target_of("fake-0") == 3
    assert ctrl2.token_caps["fake-1"] == 7
    assert resumed.admission.stats()["workers"]["fake-1"]["capacity"] == 7
    assert "fake-1" in ctrl2._pending_drain
    resumed.reconcile()
    loops = resumed.run(poll_s=0.05)
    resumed.cleanup(remove_containers=True)
    assert all(l.status in ("done", "stopped") for l in loops)


# ------------------------------------------------- warm pool seam bits


def test_warmpool_per_worker_targets():
    pool = WarmPool("caprun", depth=2)
    w = Worker(id="w0", index=0, hostname="w0", engine=None)
    assert pool.target_of("w0") == 2        # static default
    pool.set_target("w0", 4)
    assert pool.target_of("w0") == 4
    assert pool.want("w0") == 4
    assert pool.target_of("other") == 2     # untouched workers keep static
    pool.set_target("w0", 0)
    assert pool.want("w0") == 0
    assert pool.begin_refill(w) is None
    stats = pool.stats()
    assert stats["adaptive"] is True
    assert stats["workers"]["w0"]["target"] == 0


# ----------------------------------------------------------------- CLI


def _daemon_doc() -> dict:
    return {
        "type": "status", "pid": 4242, "runs": [],
        "health": [{"worker": "fake-0", "state": "closed",
                    "breaker_state_gauge": 0, "probe_p50_ms": 1.0}],
        "admission": {
            "max_inflight_per_worker": 4, "max_pending_per_worker": 256,
            "workers": {"fake-0": {
                "inflight": 1, "inflight_hwm": 2, "capacity": 8,
                "pending": 3, "dispatched": 11, "rejected": 2,
                "launch_ewma_ms": 20.0, "shed_retry_after_s": 0.0}},
            "tenants": {"default": {
                "weight": 1.0, "queued": 3, "inflight": 1,
                "dispatched": 11, "max_inflight": 0, "inflight_hwm": 2,
                "rejected": 2, "cancelled": 0}},
        },
        "warm_pools": {"run1": {
            "target_depth": 0, "adaptive": True, "hits": 5, "misses": 1,
            "refills": 6, "recycled": 0,
            "workers": {"fake-0": {"ready": 2, "inflight": 1,
                                   "target": 3}}}},
        "capacity": {
            "enabled": True, "ticks": 12, "slo_s": 0.5,
            "workers": {"fake-0": {
                "pool_target": 3, "pool_ready": 2, "token_cap": 8,
                "arrival_rate": 4.5, "shed_retry_after_s": 0.0}},
            "tenants": {"default": {"slo_s": 0.5, "headroom_s": 0.41}},
            "autoscale": {"enabled": True, "pending_drain": [],
                          "drained": [], "provisioned": []},
        },
        "workerd": {},
        "sentinel": {"enabled": False},
        "shipper": {"enabled": False},
        "events_dropped_total": 0,
        "settings": {"max_inflight_per_worker": 4,
                     "max_pending_per_worker": 256, "metrics_port": 0},
    }


def test_fleet_warmpool_cli_renders_adaptive_targets(env, monkeypatch):
    from click.testing import CliRunner

    from clawker_tpu.cli import cmd_fleet
    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    monkeypatch.setattr(cmd_fleet, "_loopd_status",
                        lambda f, no_daemon: _daemon_doc())
    res = CliRunner().invoke(
        cli, ["fleet", "warmpool"],
        obj=Factory(cwd=proj, driver=FakeDriver()), catch_exceptions=False)
    assert res.exit_code == 0, res.output
    assert "target=3" in res.output            # per-run live target
    assert "TARGET=3" in res.output and "ACTUAL=2" in res.output
    assert "(adaptive)" in res.output
    # --json parity: the same capacity doc rides the JSON form
    res = CliRunner().invoke(
        cli, ["fleet", "warmpool", "--format", "json"],
        obj=Factory(cwd=proj, driver=FakeDriver()), catch_exceptions=False)
    assert res.exit_code == 0, res.output
    doc = json.loads(res.output)
    assert doc["capacity"]["workers"]["fake-0"]["pool_target"] == 3
    assert doc["daemon_pools"]["run1"]["workers"]["fake-0"]["target"] == 3


def test_fleet_placement_cli_renders_scaled_caps(env, monkeypatch):
    from click.testing import CliRunner

    from clawker_tpu.cli import cmd_fleet
    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    monkeypatch.setattr(cmd_fleet, "_loopd_status",
                        lambda f, no_daemon: _daemon_doc())
    res = CliRunner().invoke(
        cli, ["fleet", "placement"],
        obj=Factory(cwd=proj, driver=FakeDriver()), catch_exceptions=False)
    assert res.exit_code == 0, res.output
    assert "1/8" in res.output                 # the SLO-scaled cap
    assert "slo default: 0.5s headroom=0.41s" in res.output
    res = CliRunner().invoke(
        cli, ["fleet", "placement", "--format", "json"],
        obj=Factory(cwd=proj, driver=FakeDriver()), catch_exceptions=False)
    assert res.exit_code == 0, res.output
    doc = json.loads(res.output)
    row = doc["workers"][0]
    assert row["scaled_cap"] == 8
    assert doc["capacity"]["tenants"]["default"]["headroom_s"] == 0.41


def test_loopd_hosts_capacity_controller(env):
    """With settings capacity.enable, loopd ticks one daemon-lifetime
    controller: its state rides the status RPC and hosted runs' pools
    pick up the adaptive targets."""
    from clawker_tpu.loopd.client import LoopdClient
    from clawker_tpu.loopd.server import LoopdServer

    tenv, proj, cfg = env
    cfg.settings.capacity.enable = True
    cfg.settings.capacity.interval_s = 0.02
    cfg.settings.capacity.pool_max_depth = 3
    drv = driver_with(2)
    srv = LoopdServer(cfg, drv).start()
    try:
        assert srv.capacity is not None
        client = LoopdClient(srv.sock_path)
        ack = client.submit_run({"parallel": 2, "iterations": 1,
                                 "image": IMAGE, "warm_pool_depth": 1},
                                stream=False)
        assert ack.get("run")
        client.close()
        assert wait_for(lambda: srv.capacity.ticks >= 3)
        run = srv.runs[ack["run"]]
        assert wait_for(lambda: run.done.is_set())
        status = LoopdClient(srv.sock_path)
        doc = status.status()
        status.close()
        assert doc["capacity"]["enabled"] is True
        assert doc["capacity"]["ticks"] >= 3
    finally:
        srv.stop()
        drv.close()


# --------------------------------------------------- plan determinism


def test_capacity_rider_preserves_existing_draws():
    """The capacity rider draws strictly AFTER every pre-existing draw:
    a (seed, scenario) pair's worker-fault/sigkill/sentinel/workerd/
    shipper schedule is byte-identical to the pre-capacity generator's
    (simulated here by stripping the rider's own additions)."""
    from clawker_tpu.chaos.plan import generate_plan

    for i in range(12):
        plan = generate_plan(99, i)
        base = [e.to_doc() for e in plan.events
                if e.kind not in ("traffic_burst", "scale_down")]
        again = generate_plan(99, i)
        base2 = [e.to_doc() for e in again.events
                 if e.kind not in ("traffic_burst", "scale_down")]
        assert base == base2
        assert plan.capacity == again.capacity
