"""Socket-bridge suite: frame codec + a REAL end-to-end relay.

The e2e test runs the container-side endpoint as an actual subprocess
(stdio pipes standing in for the docker-exec channel), a throwaway unix
"ssh agent" on the host side, and a client dialing the container-side
socket -- proving agent-protocol bytes round-trip across the mux in both
directions with multiple concurrent connections.
"""

from __future__ import annotations

import io
import os
import socket
import socketserver
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from clawker_tpu.socketbridge import protocol
from clawker_tpu.socketbridge.host import Bridge
from clawker_tpu.socketbridge.protocol import (
    K_CLOSE,
    K_DATA,
    K_OPEN,
    W_SSH,
    chunked,
    pack,
    read_frame,
)

REPO = Path(__file__).resolve().parent.parent


# ------------------------------------------------------------------ codec

def test_frame_roundtrip():
    frame = pack(7, K_DATA, W_SSH, b"agent bytes")
    got = read_frame(io.BytesIO(frame))
    assert got == (7, K_DATA, W_SSH, b"agent bytes")


def test_frame_eof_and_truncation():
    assert read_frame(io.BytesIO(b"")) is None
    assert read_frame(io.BytesIO(pack(1, K_OPEN, W_SSH)[:-1] or b"\x00")) is None
    truncated = pack(1, K_DATA, W_SSH, b"xyz")[:-1]
    assert read_frame(io.BytesIO(truncated)) is None


def test_chunked_splits_large_payloads():
    data = b"x" * (protocol.MAX_PAYLOAD * 2 + 5)
    frames = list(chunked(3, W_SSH, data))
    assert len(frames) == 3
    total = b""
    buf = io.BytesIO(b"".join(frames))
    while (f := read_frame(buf)) is not None:
        total += f[3]
    assert total == data


# ------------------------------------------------------------------- e2e

class FakeAgent(socketserver.ThreadingUnixStreamServer):
    """Unix 'ssh-agent': answers PING-style requests deterministically."""

    daemon_threads = True      # handlers block in recv; never join them
    block_on_close = False

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            # raw echo: stream-safe under arbitrary recv segmentation
            while True:
                data = self.request.recv(65536)
                if not data:
                    return
                self.request.sendall(data)


class _PipeStream:
    """read/write/close adapter over a subprocess's stdio pipes."""

    def __init__(self, proc):
        self.proc = proc

    def read(self, n):
        return self.proc.stdout.read(n)

    def write(self, data):
        self.proc.stdin.write(data)
        self.proc.stdin.flush()

    def close(self):
        try:
            self.proc.stdin.close()
        except OSError:
            pass


@pytest.fixture
def bridge_env(tmp_path):
    agent_sock = tmp_path / "host-agent.sock"
    agent = FakeAgent(str(agent_sock), FakeAgent.Handler)
    threading.Thread(target=agent.serve_forever, daemon=True).start()

    sock_dir = tmp_path / "container"
    env = dict(os.environ, CLAWKER_SOCK_DIR=str(sock_dir),
               PYTHONPATH=str(REPO))
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "clawker_tpu.socketbridge.container"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
    )
    bridge = Bridge(_PipeStream(proc), {W_SSH: str(agent_sock)})
    bridge.start()
    container_sock = sock_dir / "ssh-agent.sock"
    deadline = time.time() + 10
    while not container_sock.exists() and time.time() < deadline:
        time.sleep(0.05)
    assert container_sock.exists(), "container-side socket never appeared"
    yield container_sock
    bridge.close()
    proc.terminate()
    proc.wait(5)
    agent.shutdown()
    agent.server_close()


def _roundtrip(container_sock: Path, payload: bytes) -> bytes:
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as c:
        c.settimeout(10)
        c.connect(str(container_sock))
        c.sendall(payload)
        want = payload
        got = b""
        while len(got) < len(want):
            chunk = c.recv(65536)
            if not chunk:
                break
            got += chunk
        return got


def test_e2e_agent_roundtrip(bridge_env):
    got = _roundtrip(bridge_env, b"\x00\x00\x00\x01\x0b")  # SSH2_AGENTC_REQUEST_IDENTITIES-ish
    assert got == b"\x00\x00\x00\x01\x0b"


def test_e2e_concurrent_connections(bridge_env):
    results = {}

    def worker(i):
        results[i] = _roundtrip(bridge_env, f"req-{i}".encode() * 100)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(5)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(15)
    assert len(results) == 5
    for i, got in results.items():
        assert got == f"req-{i}".encode() * 100


def test_e2e_large_payload_chunking(bridge_env):
    payload = bytes(range(256)) * 600  # ~150 KiB: crosses MAX_PAYLOAD many times
    got = _roundtrip(bridge_env, payload)
    assert got == payload


def test_open_without_host_socket_closes_channel(tmp_path):
    """A which with no host-side socket gets an immediate CLOSE back."""
    r_h, w_c = os.pipe()   # container -> host
    r_c, w_h = os.pipe()   # host -> container
    host_in = os.fdopen(r_h, "rb")
    host_out = os.fdopen(w_h, "wb")
    cont_in = os.fdopen(r_c, "rb")
    cont_out = os.fdopen(w_c, "wb")

    class _S:
        def read(self, n):
            return host_in.read(n)

        def write(self, d):
            host_out.write(d)
            host_out.flush()

        def close(self):
            for f in (host_in, host_out):
                try:
                    f.close()
                except OSError:
                    pass

    bridge = Bridge(_S(), host_sockets={})  # nothing forwardable
    bridge.start()
    cont_out.write(pack(9, K_OPEN, W_SSH))
    cont_out.flush()
    frame = read_frame(cont_in)
    assert frame == (9, K_CLOSE, W_SSH, b"")
    cont_out.close()   # EOF the pump thread before closing the bridge
    bridge.close()
    try:
        cont_in.close()
    except OSError:
        pass


def test_pyz_contains_container_side():
    from clawker_tpu.bundler.payload import build_agentd_pyz

    import zipfile

    with zipfile.ZipFile(io.BytesIO(build_agentd_pyz())) as zf:
        names = set(zf.namelist())
    assert "clawker_tpu/socketbridge/container.py" in names
    assert "clawker_tpu/socketbridge/protocol.py" in names
    assert "clawker_tpu/socketbridge/host.py" not in names  # host-side only
