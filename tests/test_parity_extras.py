"""Parity odds-and-ends: dotenv, JSON schemas, bundle GC, changelog.

Reference bars: internal/dotenv (godotenv semantics), internal/docs
(JSON schema gen), internal/bundle/gc.go, internal/changelog.
"""

from __future__ import annotations

import json
import time

import pytest

from clawker_tpu.util.dotenv import DotenvError, parse, parse_file


# ------------------------------------------------------------------ dotenv

def test_dotenv_basic_and_comments():
    env = parse(
        "# comment\n"
        "FOO=bar\n"
        "export BAZ=qux\n"
        "\n"
        "TRAILING=value # note\n",
        lookup=lambda k: None)
    assert env == {"FOO": "bar", "BAZ": "qux", "TRAILING": "value"}


def test_dotenv_quoting():
    env = parse(
        'DQ="line1\\nline2 # not a comment"\n'
        "SQ='literal $FOO \\n'\n"
        'ESCQ="say \\"hi\\""\n'
        'PASS="pa\\$\\$wd"\n',
        lookup=lambda k: None)
    assert env["DQ"] == "line1\nline2 # not a comment"
    assert env["SQ"] == "literal $FOO \\n"
    assert env["ESCQ"] == 'say "hi"'
    assert env["PASS"] == "pa$$wd"  # \\$ stays literal, never expands


def test_dotenv_expansion_prefers_file_then_lookup():
    env = parse(
        "A=1\n"
        "B=${A}2\n"
        "C=$OUTSIDE/x\n"
        "D=${MISSING}end\n",
        lookup={"OUTSIDE": "/ext"}.get)
    assert env == {"A": "1", "B": "12", "C": "/ext/x", "D": "end"}


def test_dotenv_errors():
    with pytest.raises(DotenvError):
        parse("not a pair\n")
    with pytest.raises(DotenvError):
        parse('X="unterminated\n')
    with pytest.raises(DotenvError):
        parse_file("/nonexistent/.env")


def test_dotenv_file_and_cli_merge(tmp_path):
    envf = tmp_path / ".env"
    envf.write_text("FROM_FILE=1\nSHARED=file\n")
    from clawker_tpu.cli.cmd_container import _assemble_env

    merged = _assemble_env(("SHARED=cli", "ONLY=x"), (str(envf),))
    assert merged == {"FROM_FILE": "1", "SHARED": "cli", "ONLY": "x"}


# ----------------------------------------------------------------- schemas

def test_json_schemas_cover_config_surface(tmp_path):
    from clawker_tpu.docs import generate_json_schemas

    written = generate_json_schemas(tmp_path)
    names = {p.name for p in written}
    assert names == {"clawker.schema.json", "settings.schema.json"}
    proj = json.loads((tmp_path / "clawker.schema.json").read_text())
    assert set(proj["properties"]) >= {"project", "build", "security",
                                       "workspace", "agent"}
    egress = (proj["properties"]["security"]["properties"]["egress"])
    assert egress["type"] == "array"
    rule = egress["items"]["properties"]
    assert {"dst", "proto", "port", "action", "path_rules"} <= set(rule)
    settings = json.loads((tmp_path / "settings.schema.json").read_text())
    assert "firewall" in settings["properties"]
    # deterministic regeneration
    again = generate_json_schemas(tmp_path)
    assert json.loads(again[0].read_text()) == json.loads(written[0].read_text())


# ---------------------------------------------------------------- bundle gc

def make_bundle(root, name="b1"):
    d = root / "harnesses" / name
    d.mkdir(parents=True)
    (d / "harness.yaml").write_text(
        f"name: {name}\ncmd: [run]\n")
    return root


def test_bundle_gc_dry_run_and_apply(tmp_path):
    from clawker_tpu.bundle.manager import BundleManager
    from clawker_tpu.config import load_config
    from clawker_tpu.testenv import TestEnv

    with TestEnv() as tenv:
        proj = tenv.base / "p"
        proj.mkdir()
        (proj / ".clawker.yaml").write_text("project: gcproj\n")
        cfg = load_config(proj)
        mgr = BundleManager(cfg)
        src = make_bundle(tmp_path / "src", "orphanharness")
        inst = mgr.install(str(src), name="orphan")
        # crashed-swap leftover
        leftover = cfg.bundles_dir / "local" / ".old.installing"
        leftover.mkdir(parents=True)
        # young install: protected by grace
        rep = mgr.gc()
        assert rep["unreferenced"] == [] and len(rep["leftovers"]) == 1
        # age it past grace: now unreferenced (no project declares it)
        rep = mgr.gc(grace_s=0)
        assert rep["unreferenced"] == ["local/orphan"]
        assert rep["removed"] == []           # dry-run
        assert inst.path.is_dir()
        rep = mgr.gc(apply=True, grace_s=0)
        assert "local/orphan" in rep["removed"]
        assert not inst.path.exists()
        assert not leftover.exists()


def test_bundle_gc_keeps_referenced(tmp_path):
    from clawker_tpu.bundle.manager import BundleManager
    from clawker_tpu.config import load_config
    from clawker_tpu.project.manager import ProjectManager
    from clawker_tpu.testenv import TestEnv

    with TestEnv() as tenv:
        proj = tenv.base / "p"
        proj.mkdir()
        (proj / ".clawker.yaml").write_text(
            "project: gcproj\nbuild:\n  harness: specialharness\n")
        cfg = load_config(proj)
        ProjectManager(cfg).register_current()
        mgr = BundleManager(cfg)
        src = make_bundle(tmp_path / "src", "specialharness")
        mgr.install(str(src), name="keepme")
        rep = mgr.gc(grace_s=0)
        assert rep["unreferenced"] == []


def test_bundle_auto_update_refreshes_drifted_source(tmp_path):
    from clawker_tpu.bundle.manager import BundleManager
    from clawker_tpu.config import load_config
    from clawker_tpu.state import StateStore
    from clawker_tpu.testenv import TestEnv

    with TestEnv() as tenv:
        proj = tenv.base / "p"
        proj.mkdir()
        (proj / ".clawker.yaml").write_text("project: auproj\n")
        cfg = load_config(proj)
        mgr = BundleManager(cfg)
        src = make_bundle(tmp_path / "src", "harn")
        mgr.install(str(src), name="au")
        state = StateStore(tmp_path / "state.json")
        # fresh install, unchanged source: TTL consumed, nothing updated
        assert mgr.auto_update_check(state=state, ttl_s=0) == []
        # source drifts: next check re-installs
        (src / "harnesses" / "harn" / "harness.yaml").write_text(
            "name: harn\ncmd: [run, --new]\n")
        assert mgr.auto_update_check(state=state, ttl_s=0) == ["local/au"]
        installed = cfg.bundles_dir / "local" / "au"
        assert "--new" in (installed / "harnesses" / "harn"
                           / "harness.yaml").read_text()
        # TTL gates: an immediate re-check is a no-op
        (src / "harnesses" / "harn" / "harness.yaml").write_text(
            "name: harn\ncmd: [run, --newer]\n")
        assert mgr.auto_update_check(state=state, ttl_s=9999) == []
        # a vanished source soft-skips (offline host still runs)
        import shutil as _sh

        _sh.rmtree(src)
        assert mgr.auto_update_check(state=state, ttl_s=0) == []


# --------------------------------------------------------------- changelog

def test_changelog_teaser_shows_once(tmp_path):
    from clawker_tpu.changelog import parse_changelog, teaser
    from clawker_tpu.state import StateStore

    log = tmp_path / "CHANGELOG.md"
    log.write_text(
        "# Changelog\n\n"
        "## [0.2.0]\n\n- Future entry\n\n"
        "## [0.1.0]\n\n- First release: parity scorecard\n- more\n")
    entries = parse_changelog(log.read_text())
    assert [v for v, _ in entries] == ["0.2.0", "0.1.0"]

    state = StateStore(tmp_path / "state.json")
    line = teaser(state=state, path=log, version="0.1.0")
    assert "what's new in 0.1.0" in line and "First release" in line
    # second invocation: quiet
    assert teaser(state=state, path=log, version="0.1.0") == ""
    # unknown version: quiet, but marks seen
    assert teaser(state=state, path=log, version="9.9.9") == ""


def test_dotenv_expanded_values_keep_literal_escapes():
    """godotenv order: escapes process the SOURCE text only; a referenced
    variable whose value contains a literal backslash-n must come through
    verbatim (ADVICE r4: unescape-then-expand)."""
    out = parse('A="x\\ny"\nB="ref: $A"\n',
                lookup={"RAW": "path\\nwith\\tliterals"}.get)
    assert out["A"] == "x\ny"
    assert out["B"] == "ref: x\ny"
    out = parse('C="$RAW"\n', lookup={"RAW": "path\\nwith\\tliterals"}.get)
    # the lookup value's backslashes are DATA, not escapes
    assert out["C"] == "path\\nwith\\tliterals"
    # \$ still blocks expansion
    out = parse('D="pa\\$\\$wd"\n', lookup=lambda n: "BOOM")
    assert out["D"] == "pa$$wd"


def test_bundle_auto_update_backfills_commitless_git_receipt(tmp_path):
    """A git receipt with no commit must probe ls-remote and re-install
    ONCE to backfill -- not fall through to an unconditional daily
    re-clone (ADVICE r4)."""
    import json as _json

    from clawker_tpu.bundle.manager import RECEIPT, BundleManager
    from clawker_tpu.config import load_config
    from clawker_tpu.state import StateStore
    from clawker_tpu.testenv import TestEnv

    with TestEnv() as tenv:
        proj = tenv.base / "p"
        proj.mkdir()
        (proj / ".clawker.yaml").write_text("project: aucommit\n")
        cfg = load_config(proj)
        mgr = BundleManager(cfg)
        src = make_bundle(tmp_path / "src", "harn")
        inst = mgr.install(str(src), name="au")
        # rewrite the receipt: pretend a git source, commit-less
        rp = inst.path / RECEIPT
        receipt = _json.loads(rp.read_text())
        receipt["source"] = "https://example.invalid/repo.git"
        receipt.pop("commit", None)
        rp.write_text(_json.dumps(receipt))

        calls = []
        mgr._ls_remote_head = lambda url: "abc123"
        mgr.install = lambda *a, **k: calls.append((a, k))
        state = StateStore(tmp_path / "state.json")
        assert mgr.auto_update_check(state=state, ttl_s=0) == ["local/au"]
        assert len(calls) == 1          # one backfill re-install
        # unreachable remote: soft-skip, no re-install
        mgr._ls_remote_head = lambda url: ""
        mgr.auto_update_check(state=state, ttl_s=0)
        assert len(calls) == 1
        # commit recorded and matching: skip
        receipt["commit"] = "abc123"
        rp.write_text(_json.dumps(receipt))
        mgr._ls_remote_head = lambda url: "abc123"
        assert mgr.auto_update_check(state=state, ttl_s=0) == []
        assert len(calls) == 1
