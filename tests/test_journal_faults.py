"""Journal storage-fault paths under the FaultFS shim.

The fail-loud durability contract (docs/durability.md): a durable
append either IS durable or says loudly that it is not, a failed fsync
poisons the fd (reopen + re-append, never a retry on the same handle),
and nothing is ever dropped without a counter and a fault callback.
The chaos soak proves these paths on drawn schedules; these tests pin
them one at a time.
"""

from __future__ import annotations

import errno

import pytest

from clawker_tpu.loop.journal import (
    JournalUnhealthy,
    RunJournal,
    dedupe_by_seq,
    receipt_synced,
    replay,
)
from clawker_tpu.testenv import FaultFS


@pytest.fixture()
def journal(tmp_path):
    j = RunJournal(tmp_path / "x.journal")
    yield j
    j.close()


def test_fsync_fail_poisons_handle_and_recovers(journal):
    faults = []
    journal.on_fault = faults.append
    journal.append("run", run="r1")
    shim = FaultFS.install(journal)
    shim.fail_fsyncs(1)
    rcpt = journal.append("placement", durable=True, agent="a",
                          worker="w0", epoch=0)
    # the promise was kept -- but only via recovery on a FRESH fd:
    # the poisoned handle is abandoned, never fsync-retried
    assert rcpt.ok and rcpt.synced and rcpt.error
    assert journal._fh is not shim
    assert journal.healthy
    assert journal.poisoned == 1 and journal.recoveries == 1
    assert journal.faults == 1 and journal.dropped == 0
    assert [f.op for f in faults] == ["fsync"]
    assert faults[0].recovered and faults[0].dropped == 0
    # the re-appended ring may duplicate on disk; the fold is exactly-once
    recs = RunJournal.read(journal.path)
    assert [r["kind"] for r in recs].count("placement") == 1
    assert replay(recs).loops["a"].worker == "w0"


def test_write_fail_rides_ring_through_recovery(journal):
    faults = []
    journal.on_fault = faults.append
    journal.append("run", run="r1")
    shim = FaultFS.install(journal)
    shim.fail_writes(1, errno.ENOSPC)
    rcpt = journal.append("placement", durable=True, agent="a",
                          worker="w0", epoch=0)
    # ENOSPC on the write: the record rides the ring onto the fresh fd
    assert rcpt.ok and rcpt.synced
    assert journal.dropped == 0 and journal.recoveries == 1
    assert shim.failed_writes == 1
    assert [f.op for f in faults] == ["write"]
    recs = RunJournal.read(journal.path)
    assert sum(1 for r in recs if r["kind"] == "placement") == 1


def test_unrecoverable_fault_drops_loudly(journal, monkeypatch):
    faults = []
    journal.on_fault = faults.append
    journal.append("run", run="r1")
    shim = FaultFS.install(journal)
    shim.fail_fsyncs(1)
    # recovery's reopen fails too: the disk is really gone
    monkeypatch.setattr("builtins.open", _make_raising_open())
    rcpt = journal.append("placement", durable=True, agent="a",
                          worker="w0", epoch=0)
    assert not rcpt.synced
    assert not receipt_synced(rcpt)
    with pytest.raises(JournalUnhealthy):
        rcpt.require_durable()
    assert faults and not faults[-1].recovered
    assert not journal.healthy


def _make_raising_open():
    def _raising_open(*a, **k):
        raise OSError(errno.EIO, "disk gone")
    return _raising_open


def test_reopen_backoff_then_lazy_recovery(journal, monkeypatch):
    journal.append("run", run="r1")
    shim = FaultFS.install(journal)
    shim.fail_fsyncs(1)
    real_open = open
    monkeypatch.setattr("builtins.open", _make_raising_open())
    bad = journal.append("placement", durable=True, agent="a",
                         worker="w0", epoch=0)
    assert not bad.synced and not journal.healthy
    # disk comes back: the next append past the backoff reopens lazily
    monkeypatch.setattr("builtins.open", real_open)
    journal._reopen_at = 0.0
    good = journal.append("placement", durable=True, agent="b",
                          worker="w1", epoch=0)
    assert good.ok and good.synced and journal.healthy


def test_open_fault_is_reported_not_silent(tmp_path):
    faults = []
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where the runs dir should be")
    j = RunJournal(blocker / "sub" / "x.journal",
                   on_fault=faults.append)
    assert [f.op for f in faults] == ["open"]
    rcpt = j.append("run", durable=True, run="x")
    assert not rcpt.ok and not rcpt.synced
    assert j.dropped == 1
    assert [f.op for f in faults] == ["open", "write"]
    j.close()


def test_close_reports_failed_final_sync_with_drop_count(tmp_path,
                                                         monkeypatch):
    faults = []
    j = RunJournal(tmp_path / "x.journal", on_fault=faults.append,
                   fsync_batch_n=100, fsync_interval_s=3600.0)
    j.append("run", run="r1")
    j.sync()                    # arm the interval clock
    j.append("note", text="batched, never fsynced")
    j.append("note", text="batched, never fsynced either")
    shim = FaultFS.install(j)
    shim.fail_fsyncs(1)
    # the last-ditch fresh-fd recovery must fail too to count a drop
    monkeypatch.setattr("builtins.open", _make_raising_open())
    j.close()
    assert [f.op for f in faults] == ["close"]
    assert not faults[0].recovered
    assert faults[0].dropped == 2 and j.dropped == 2


def test_close_recovers_unsynced_tail_on_fresh_fd(tmp_path):
    faults = []
    j = RunJournal(tmp_path / "x.journal", on_fault=faults.append,
                   fsync_batch_n=100, fsync_interval_s=3600.0)
    j.append("run", run="r1")
    j.sync()                    # arm the interval clock
    j.append("placement", agent="a", worker="w0", epoch=0)
    shim = FaultFS.install(j)
    shim.fail_fsyncs(1)
    j.close()
    assert [f.op for f in faults] == ["close"]
    assert faults[0].recovered and j.dropped == 0
    recs = RunJournal.read(j.path)
    assert [r["kind"] for r in recs] == ["run", "placement"]


def test_append_after_close_counts_dropped(journal):
    journal.append("run", run="r1")
    journal.close()
    rcpt = journal.append("late", durable=True)
    assert not rcpt.ok and journal.dropped == 1


def test_receipt_synced_tolerates_legacy_hooks():
    # warmpool/capacity accept `lambda kind, **f: None` journal hooks
    assert receipt_synced(None)
    assert receipt_synced(object())


def test_dedupe_by_seq_first_wins_and_passes_legacy():
    recs = [{"kind": "run", "seq": 1}, {"kind": "placement", "seq": 2},
            {"kind": "placement", "seq": 2}, {"kind": "legacy"},
            {"kind": "legacy"}, {"kind": "exited", "seq": 3}]
    out = dedupe_by_seq(recs)
    assert [r.get("seq") for r in out] == [1, 2, None, None, 3]


def test_short_write_torn_line_contained(journal):
    journal.append("run", run="r1")
    shim = FaultFS.install(journal)
    shim.short_writes(1)
    rcpt = journal.append("placement", durable=True, agent="a",
                          worker="w0", epoch=0)
    # half a line hit the disk, then the write raised: recovery's
    # blank-line terminator contains the garble and the ring re-append
    # lands the record intact
    assert rcpt.ok and rcpt.synced
    recs = RunJournal.read(journal.path)
    assert sum(1 for r in recs if r["kind"] == "placement") == 1
    from clawker_tpu.monitor.ledger import verify_jsonl
    report = verify_jsonl(journal.path)
    # the torn fragment reads as damage mid-file at worst -- the fold
    # (read) above still saw every record exactly once
    assert report.verified >= 2
