"""DNS gate suite: codec, zone policy, serving, and dns_cache feeding.

Parity bar: the reference's CoreDNS config semantics
(controlplane/firewall/coredns_config.go -- per-zone forwards, docker-
internal zones, catch-all NXDOMAIN) and the dnsbpf cache-writing plugin
(internal/dnsbpf/dnsbpf.go:49), exercised through a local fake upstream
resolver instead of Cloudflare.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading

import pytest

from clawker_tpu.config.schema import EgressRule
from clawker_tpu.firewall import dnsgate
from clawker_tpu.firewall.dnsgate import (
    QTYPE_A,
    QTYPE_AAAA,
    RCODE_NOERROR,
    RCODE_NXDOMAIN,
    RCODE_SERVFAIL,
    DnsGate,
    ZonePolicy,
    _encode_name,
    parse_a_records,
    parse_query,
    synthesize,
)
from clawker_tpu.firewall.hashes import zone_hash
from clawker_tpu.firewall.maps import FakeMaps


def make_query(name: str, qtype: int = QTYPE_A, qid: int = 0x1234) -> bytes:
    hdr = struct.pack(">HHHHHH", qid, 0x0100, 1, 0, 0, 0)
    return hdr + _encode_name(name) + struct.pack(">HH", qtype, 1)


def make_answer(query: bytes, ips: list[str], ttl: int = 120) -> bytes:
    """Upstream-style response: echoed question + A records (compressed)."""
    qid, _flags, _qd, _an, _ns, _ar = struct.unpack(">HHHHHH", query[:12])
    hdr = struct.pack(">HHHHHH", qid, 0x8180, 1, len(ips), 0, 0)
    body = query[12:]
    for ip in ips:
        body += struct.pack(">HHHIH", 0xC00C, QTYPE_A, 1, ttl, 4) + socket.inet_aton(ip)
    return hdr + body


class FakeUpstream:
    """Local UDP resolver answering every A query from a fixed table."""

    def __init__(self, table: dict[str, list[str]], ttl: int = 120):
        outer = self

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                data, sock = self.request
                q = parse_query(data)
                ips = outer.table.get(q.qname)
                if ips is None:
                    sock.sendto(synthesize(q, RCODE_NXDOMAIN), self.client_address)
                else:
                    sock.sendto(make_answer(data, ips, outer.ttl), self.client_address)

        self.table = table
        self.ttl = ttl
        self.srv = socketserver.ThreadingUDPServer(("127.0.0.1", 0), _H)
        self.port = self.srv.server_address[1]
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def stop(self):
        self.srv.shutdown()
        self.srv.server_close()


# --------------------------------------------------------------------------
# codec
# --------------------------------------------------------------------------

def test_codec_roundtrip_and_compression():
    q = parse_query(make_query("Sub.Example.COM"))
    assert q.qname == "sub.example.com" and q.qtype == QTYPE_A
    ans = make_answer(make_query("a.example.com"), ["1.2.3.4", "5.6.7.8"], ttl=77)
    assert parse_a_records(ans) == [("1.2.3.4", 77), ("5.6.7.8", 77)]


def test_synthesize_rcodes():
    q = parse_query(make_query("x.example.com"))
    nx = synthesize(q, RCODE_NXDOMAIN)
    assert struct.unpack(">H", nx[2:4])[0] & 0xF == RCODE_NXDOMAIN
    assert struct.unpack(">H", nx[:2])[0] == q.qid
    assert parse_query(nx).qname == "x.example.com"  # question echoed


def test_parse_query_rejects_garbage():
    with pytest.raises(dnsgate.DnsWireError):
        parse_query(b"\x00\x01")
    with pytest.raises(dnsgate.DnsWireError):
        parse_query(struct.pack(">HHHHHH", 1, 0, 0, 0, 0, 0))


# --------------------------------------------------------------------------
# zone policy (wildcard vs exact: firewall_test.go:609/:653 semantics)
# --------------------------------------------------------------------------

def test_zone_policy_wildcard_vs_exact():
    zp = ZonePolicy.from_rules([
        EgressRule(dst="*.wild.example"), EgressRule(dst="only.example"),
    ])
    assert zp.match("sub.wild.example").apex == "wild.example"
    assert zp.match("deep.sub.wild.example").apex == "wild.example"
    assert zp.match("wild.example").apex == "wild.example"  # apex included
    assert zp.match("only.example").apex == "only.example"
    assert zp.match("sub.only.example") is None              # exact is exact
    assert zp.match("unrelated.example") is None


def test_zone_policy_longest_apex_wins_and_internal():
    zp = ZonePolicy.from_rules([EgressRule(dst="*.example.com"),
                                EgressRule(dst="*.api.example.com")])
    assert zp.match("v1.api.example.com").apex == "api.example.com"
    assert zp.match("www.example.com").apex == "example.com"
    assert zp.match("host.docker.internal").internal


# --------------------------------------------------------------------------
# gate serving
# --------------------------------------------------------------------------

def _patched_gate(rules, maps, upstream_port, internal_port=None):
    gate = DnsGate(ZonePolicy.from_rules(rules), maps,
                   upstreams=(f"up:{upstream_port}",),
                   internal_resolver=f"int:{internal_port}",
                   host="127.0.0.1", port=0)

    def forward(data, resolvers, *, tcp):
        target = resolvers[0]
        port = int(target.split(":")[1]) if ":" in target else 53
        if "None" in target:
            return None
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.settimeout(2)
                s.sendto(data, ("127.0.0.1", port))
                reply, _ = s.recvfrom(4096)
                return reply
        except OSError:
            return None

    gate._forward = forward  # type: ignore[method-assign]
    return gate


def test_allowed_query_relays_and_caches():
    upstream = FakeUpstream({"api.example.com": ["93.184.216.34", "93.184.216.35"]})
    maps = FakeMaps()
    gate = _patched_gate([EgressRule(dst="*.example.com")], maps, upstream.port)
    reply = gate.serve_packet(make_query("api.example.com"))
    assert reply is not None
    assert [ip for ip, _ in parse_a_records(reply)] == ["93.184.216.34", "93.184.216.35"]
    entry = maps.lookup_dns("93.184.216.34")
    assert entry is not None and entry.zone_hash == zone_hash("example.com")
    assert maps.lookup_dns("93.184.216.35") is not None
    assert gate.stats.allowed == 1 and gate.stats.cached_ips == 2
    upstream.stop()


def test_denied_query_nxdomain_never_forwarded():
    maps = FakeMaps()
    gate = _patched_gate([EgressRule(dst="*.example.com")], maps, 1)  # port 1: would fail
    reply = gate.serve_packet(make_query("evil.exfil.net"))
    assert reply is not None
    assert struct.unpack(">H", reply[2:4])[0] & 0xF == RCODE_NXDOMAIN
    assert maps.dns_entries() == {}
    assert gate.stats.refused == 1


def test_ttl_clamped_to_floor():
    upstream = FakeUpstream({"api.example.com": ["9.9.9.9"]}, ttl=1)
    maps = FakeMaps()
    gate = _patched_gate([EgressRule(dst="*.example.com")], maps, upstream.port)
    gate.serve_packet(make_query("api.example.com"))
    import time as _t

    entry = maps.lookup_dns("9.9.9.9")
    assert entry is not None
    assert entry.expires_unix >= int(_t.time()) + dnsgate.TTL_MIN_S - 1
    upstream.stop()


def test_aaaa_in_allowed_zone_returns_empty_noerror():
    maps = FakeMaps()
    gate = _patched_gate([EgressRule(dst="*.example.com")], maps, 1)
    reply = gate.serve_packet(make_query("api.example.com", qtype=QTYPE_AAAA))
    assert reply is not None
    flags = struct.unpack(">H", reply[2:4])[0]
    assert flags & 0xF == RCODE_NOERROR
    assert struct.unpack(">H", reply[6:8])[0] == 0  # zero answers


def test_internal_zone_forwards_to_docker_resolver():
    internal = FakeUpstream({"db.docker.internal": ["172.17.0.5"]})
    maps = FakeMaps()
    gate = _patched_gate([], maps, 1, internal.port)
    gate._forward_orig = gate._forward

    def forward(data, resolvers, *, tcp):
        # internal zone must choose the internal resolver, not upstream
        assert resolvers == (f"int:{internal.port}",)
        return gate._forward_orig(data, (f"up:{internal.port}",), tcp=tcp)

    gate._forward = forward  # type: ignore[method-assign]
    reply = gate.serve_packet(make_query("db.docker.internal"))
    assert reply is not None
    assert [ip for ip, _ in parse_a_records(reply)] == ["172.17.0.5"]
    # internal answers are cached so the kernel can route them if ruled
    assert maps.lookup_dns("172.17.0.5") is not None
    internal.stop()


def test_internal_zone_answered_from_engine_inventory():
    """Host-resident gates answer docker.internal from the engine's
    container inventory (127.0.0.11 only exists inside a container netns,
    so forwarding there from the CP daemon can never work)."""
    maps = FakeMaps()
    gate = DnsGate(
        ZonePolicy.from_rules([]), maps,
        upstreams=("up:1",),
        internal_lookup=lambda name: {"db.docker.internal": "172.28.0.9"}.get(name),
        host="127.0.0.1", port=0,
    )
    reply = gate.serve_packet(make_query("db.docker.internal"))
    assert reply is not None
    assert [ip for ip, _ in parse_a_records(reply)] == ["172.28.0.9"]
    assert maps.lookup_dns("172.28.0.9") is not None
    # unknown container: NXDOMAIN, nothing cached
    reply = gate.serve_packet(make_query("ghost.docker.internal"))
    assert struct.unpack(">H", reply[2:4])[0] & 0xF == RCODE_NXDOMAIN
    assert maps.lookup_dns("1.1.1.1") is None


def test_stack_internal_lookup_resolves_via_inspect():
    """FirewallStack.internal_lookup: <name>.docker.internal -> the
    container's clawker-net address via the engine API."""
    from clawker_tpu import consts
    from clawker_tpu.engine.api import ContainerSpec
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.firewall.stack import FirewallStack

    driver = FakeDriver()
    driver.api.add_image("img:1")
    eng = driver.engine()
    eng.ensure_network(consts.NETWORK_NAME)
    ip = eng.network_static_ip(consts.NETWORK_NAME, 9)
    cid = eng.create_container(
        "clawker.proj.db",
        ContainerSpec(image="img:1", network=consts.NETWORK_NAME, static_ip=ip),
    )
    eng.start_container(cid)
    import pathlib
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        stack = FirewallStack(
            eng, FakeMaps(),
            conf_dir=pathlib.Path(td) / "conf", pki_dir=pathlib.Path(td) / "pki",
        )
        assert stack.internal_lookup("clawker.proj.db.docker.internal") == ip
        assert stack.internal_lookup("nope.docker.internal") is None


def test_upstream_down_servfail():
    maps = FakeMaps()
    gate = _patched_gate([EgressRule(dst="*.example.com")], maps, 1)
    reply = gate.serve_packet(make_query("api.example.com"))
    assert reply is not None
    assert struct.unpack(">H", reply[2:4])[0] & 0xF == RCODE_SERVFAIL
    assert gate.stats.upstream_errors == 1


def test_live_udp_and_tcp_serving():
    upstream = FakeUpstream({"api.example.com": ["93.184.216.34"]})
    maps = FakeMaps()
    gate = _patched_gate([EgressRule(dst="*.example.com")], maps, upstream.port)
    gate.start()
    try:
        q = make_query("api.example.com")
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.settimeout(3)
            s.sendto(q, ("127.0.0.1", gate.bound_port))
            reply, _ = s.recvfrom(4096)
        assert [ip for ip, _ in parse_a_records(reply)] == ["93.184.216.34"]
        with socket.create_connection(("127.0.0.1", gate.bound_port), 3) as s:
            s.sendall(struct.pack(">H", len(q)) + q)
            hdr = s.recv(2)
            (length,) = struct.unpack(">H", hdr)
            buf = b""
            while len(buf) < length:
                buf += s.recv(length - len(buf))
        assert [ip for ip, _ in parse_a_records(buf)] == ["93.184.216.34"]
    finally:
        gate.stop()
        upstream.stop()


def test_policy_hot_swap():
    maps = FakeMaps()
    gate = _patched_gate([EgressRule(dst="*.example.com")], maps, 1)
    assert gate.policy.match("api.example.com") is not None
    gate.set_policy(ZonePolicy.from_rules([EgressRule(dst="*.other.net")]))
    reply = gate.serve_packet(make_query("api.example.com"))
    assert struct.unpack(">H", reply[2:4])[0] & 0xF == RCODE_NXDOMAIN


def test_rebind_guard_refuses_private_answers(tmp_path):
    """DNS-rebinding guard: an external allowed zone answering with
    loopback/link-local/RFC1918 addresses is refused outright and never
    cached (dnsmasq --stop-dns-rebind semantics); internal zones keep
    their private answers."""
    import struct as _struct

    from clawker_tpu.config.schema import EgressRule
    from clawker_tpu.firewall.dnsgate import (
        DnsGate,
        ZonePolicy,
        _encode_name,
        is_rebind_ip,
        synthesize_a,
        parse_query,
    )
    from clawker_tpu.firewall.maps import FakeMaps

    for ip in ("127.0.0.1", "10.1.2.3", "169.254.169.254", "192.168.1.1",
               "172.16.0.9", "100.64.0.1", "0.0.0.0", "224.0.0.1"):
        assert is_rebind_ip(ip), ip
    for ip in ("93.184.216.34", "198.51.100.10", "8.8.8.8"):
        assert not is_rebind_ip(ip), ip

    maps = FakeMaps()
    gate = DnsGate(ZonePolicy.from_rules([EgressRule(dst="*.example.com")]),
                   maps, host="127.0.0.1", port=0)
    query = (_struct.pack(">HHHHHH", 9, 0x0100, 1, 0, 0, 0)
             + _encode_name("meta.example.com") + _struct.pack(">HH", 1, 1))

    def hostile_forward(data, resolvers, tcp=False):
        return synthesize_a(parse_query(data), "169.254.169.254", ttl=300)

    gate._forward = hostile_forward
    reply = gate.serve_packet(query)
    rcode = _struct.unpack(">H", reply[2:4])[0] & 0xF
    assert rcode == 3                      # refused, not relayed
    assert maps.dns_entries() == {}        # and never cached
    assert gate.stats.refused == 1
