"""Monitor suite: compose rendering, stack lifecycle over a fake runner,
netlogger enrichment + drain, CLI verbs.

Parity bar: internal/monitor compose service set (compose.yaml.tmpl:
11-198 -- otel-collector, prometheus, opensearch + bootstrap +
dashboards), the six log indices (MONITORING-REFERENCE.md:5), and the
ebpf netlogger drain->enrich->emit pipeline.
"""

from __future__ import annotations

import json
import subprocess
import time
from pathlib import Path

import pytest
import yaml

from clawker_tpu import consts
from clawker_tpu.config import load_config
from clawker_tpu.firewall.hashes import zone_hash
from clawker_tpu.firewall.maps import FakeMaps
from clawker_tpu.firewall.model import Action, EgressEvent, PROTO_TCP, Reason
from clawker_tpu.monitor.netlogger import NetLogger
from clawker_tpu.monitor.stack import (
    COMPOSE_PROJECT,
    LOG_INDICES,
    MonitorStack,
    render_bootstrap_script,
    render_compose,
)
from clawker_tpu.testenv import TestEnv


@pytest.fixture
def cfg():
    with TestEnv() as tenv:
        proj = tenv.base / "p"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: mon\n")
        yield load_config(proj)


# ---------------------------------------------------------------- rendering

def test_compose_service_set(cfg):
    compose = yaml.safe_load(render_compose(cfg.settings.monitoring))
    assert set(compose["services"]) == {
        "otel-collector", "opensearch", "opensearch-bootstrap",
        "opensearch-dashboards", "prometheus",
    }
    assert compose["name"] == COMPOSE_PROJECT
    # deterministic: same settings, same bytes
    assert render_compose(cfg.settings.monitoring) == render_compose(cfg.settings.monitoring)


def test_bootstrap_script_loops_over_tree():
    """Seeding is plain directory loops over the mounted tree: base
    corpus and unit overlays apply identically."""
    script = render_bootstrap_script()
    for surface in ("_component_template", "_index_template",
                    "_ingest/pipeline", "_plugins/_ism/policies",
                    "saved_objects/_import"):
        assert surface in script
    assert "osd-xsrf" in script  # dashboards import header


def test_render_writes_stack_dir(cfg):
    stack = MonitorStack(cfg)
    d = stack.render()
    for f in ("compose.yaml", "otel-config.yaml", "prometheus.yaml",
              "bootstrap.sh", "units-ledger.yaml"):
        assert (d / f).exists(), f
    # the bootstrap tree carries the full corpus + the claude-code unit
    tree = d / "opensearch-bootstrap"
    for index in LOG_INDICES[1:]:  # clawker-otlp has no template (catch-all)
        assert (tree / "index-templates" / f"{index}.json").exists() or \
            index == "claude-code"
    assert (tree / "index-templates" / "claude-code.json").exists()
    assert (tree / "component-templates" / "clawker-common.json").exists()
    assert (tree / "ingest-pipelines" / "netlogger-normalize.json").exists()
    assert (tree / "ism-policies" / "clawker-retention.json").exists()
    assert (tree / "saved-objects" / "clawker.ndjson").exists()
    assert (tree / "saved-objects" / "claude-code.ndjson").exists()
    otel = yaml.safe_load((d / "otel-config.yaml").read_text())
    # claude-code telemetry routed to its own index by service.name; the
    # condition rides inside the OTTL statement (a separate `condition`
    # key is rejected by the pinned collector)
    assert "logs/claude-code" in otel["service"]["pipelines"]
    assert "transform/metrics" in otel["processors"]
    table = otel["connectors"]["routing"]["table"]
    assert all(set(row) == {"statement", "pipelines"} for row in table)
    assert any(row["statement"].startswith("route() where ")
               and "claude-code" in row["statement"] for row in table)
    # declared lane retentions produce real ISM policies for unit indices
    ism = json.loads(
        (tree / "ism-policies" / "clawker-units-default.json").read_text())
    assert ism["policy"]["ism_template"][0]["index_patterns"] == ["claude-code*"]


def test_down_resets_units_ledger(cfg):
    from clawker_tpu.monitor.ledger import LEDGER_FILE

    runner = FakeCompose()
    stack = MonitorStack(cfg, runner=runner)
    stack.render()
    assert (stack.dir / LEDGER_FILE).exists()
    stack.down()
    # --volumes deleted every seeded object, so the ledger resets too
    # (the documented SeedCollision escape hatch)
    assert not (stack.dir / LEDGER_FILE).exists()


# ---------------------------------------------------------------- lifecycle

class FakeCompose:
    def __init__(self, rc=0, stdout=""):
        self.calls = []
        self.rc = rc
        self.stdout = stdout

    def __call__(self, *args):
        self.calls.append(args)
        return subprocess.CompletedProcess(args, self.rc, self.stdout, "")


def test_up_down_status_over_runner(cfg):
    runner = FakeCompose(stdout='{"Service": "opensearch", "State": "running"}\n')
    stack = MonitorStack(cfg, runner=runner)
    stack.up()
    assert runner.calls[0][:2] == ("up", "-d")
    assert (stack.dir / "compose.yaml").exists()  # up renders first
    rows = stack.status()
    assert rows == [{"Service": "opensearch", "State": "running"}]
    stack.down()
    assert runner.calls[-1][0] == "down"


def test_up_failure_raises(cfg):
    from clawker_tpu.monitor.stack import MonitorError

    stack = MonitorStack(cfg, runner=FakeCompose(rc=1))
    with pytest.raises(MonitorError):
        stack.up()


# ---------------------------------------------------------------- netlogger

def _event(cg=7, ip="203.0.113.9", verdict=Action.DENY, reason=Reason.NO_ROUTE,
           zone=""):
    return EgressEvent(
        ts_ns=time.monotonic_ns(), cgroup_id=cg, dst_ip=ip, dst_port=443,
        zone_hash=zone_hash(zone) if zone else 0, verdict=verdict,
        proto=PROTO_TCP, reason=reason,
    )


def test_netlogger_drains_and_enriches(tmp_path):
    maps = FakeMaps()
    maps.emit_event(_event(zone="example.com", verdict=Action.REDIRECT,
                           reason=Reason.ROUTE))
    maps.emit_event(_event(verdict=Action.DENY))
    out = tmp_path / "egress.jsonl"
    nl = NetLogger(
        maps, out_path=out,
        resolve_cgroup=lambda cg: "clawker.mon.dev" if cg == 7 else "",
        resolve_zone=lambda zh: "example.com" if zh == zone_hash("example.com") else "",
    )
    assert nl.drain_once() == 2
    recs = [json.loads(l) for l in out.read_text().splitlines()]
    assert recs[0]["verdict"] == "REDIRECT" and recs[0]["zone"] == "example.com"
    assert recs[0]["container"] == "clawker.mon.dev"
    assert recs[1]["verdict"] == "DENY" and recs[1]["reason"] == "NO_ROUTE"
    assert nl.drain_once() == 0  # ring drained


def test_netlogger_background_loop(tmp_path):
    maps = FakeMaps()
    nl = NetLogger(maps, out_path=tmp_path / "e.jsonl", poll_s=0.05)
    nl.start()
    try:
        maps.emit_event(_event())
        deadline = time.time() + 5
        while nl.emitted < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert nl.emitted == 1
    finally:
        # final sweep on stop picks up late events
        maps.emit_event(_event())
        nl.stop()
    assert nl.emitted == 2


def test_handler_resolvers(cfg):
    from clawker_tpu.engine.drivers import FakeDriver
    from clawker_tpu.firewall.enroll import FakeAttacher, FakeCgroupResolver
    from clawker_tpu.firewall.runtime import build_handler
    from clawker_tpu.monitor.netlogger import handler_resolvers

    driver = FakeDriver()
    driver.api.add_image("envoyproxy/envoy:v1.30.2")
    maps = FakeMaps()
    handler = build_handler(cfg, driver.engine(), maps=maps,
                            resolver=FakeCgroupResolver(), attacher=FakeAttacher(),
                            dns_host="127.0.0.1", dns_port=0)
    try:
        from clawker_tpu.engine.api import ContainerSpec

        driver.api.add_image("a:1")
        eng = driver.engine()
        cid = eng.create_container("clawker.mon.dev", ContainerSpec(image="a:1"))
        eng.start_container(cid)
        cgid = handler.enable({"container_id": cid})["cgroup_id"]
        rc, rz = handler_resolvers(handler)
        assert rc(cgid) == cid and rc(999999) == ""
        assert rz(zone_hash("api.anthropic.com")) == "api.anthropic.com"
        assert rz(0) == "" and rz(12345) == ""
    finally:
        handler.close()
        if handler.stack.gate is not None:
            handler.stack.gate.stop()


# --------------------------------------------------------------------- CLI

def test_cli_monitor_init_and_egress(cfg, tmp_path):
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli
    from clawker_tpu.engine.drivers import FakeDriver

    proj = Path(cfg.project_root)
    runner = CliRunner()
    res = runner.invoke(cli, ["monitor", "init"],
                        obj=Factory(cwd=proj, driver=FakeDriver()),
                        catch_exceptions=False)
    assert res.exit_code == 0
    assert "clawker-ebpf-egress" in res.stdout
    assert (cfg.data_dir / "monitor" / "compose.yaml").exists()
    # egress tail over a seeded log
    logp = cfg.logs_dir / "ebpf-egress.jsonl"
    logp.parent.mkdir(parents=True, exist_ok=True)
    logp.write_text(json.dumps({
        "@timestamp": "2026-07-29T00:00:00Z", "verdict": "DENY",
        "container": "clawker.mon.dev", "dst_ip": "1.2.3.4", "dst_port": 443,
        "zone": "", "reason": "NO_DNS_ENTRY",
    }) + "\n")
    res = runner.invoke(cli, ["monitor", "egress", "--deny-only"],
                        obj=Factory(cwd=proj, driver=FakeDriver()),
                        catch_exceptions=False)
    assert res.exit_code == 0
    assert "DENY" in res.stdout and "clawker.mon.dev" in res.stdout
