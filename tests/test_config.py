"""Config facade tests: schema coercion, settings/project stores, egress
composition, XDG isolation."""

from pathlib import Path

import pytest

from clawker_tpu import consts
from clawker_tpu.config import (
    EgressRule,
    ProjectConfig,
    Settings,
    load_config,
    settings_store,
)
from clawker_tpu.config.schema import from_dict, to_dict
from clawker_tpu.util import xdg


def test_from_dict_nested_and_unknown_keys():
    p = from_dict(
        ProjectConfig,
        {
            "project": "demo",
            "build": {"stack": "python", "packages": ["ripgrep"], "bogus": 1},
            "security": {"egress": [{"dst": "pypi.org", "proto": "https"}]},
            "unknown_top": True,
        },
    )
    assert p.project == "demo"
    assert p.build.stack == "python"
    assert p.build.packages == ["ripgrep"]
    assert p.security.egress[0].dst == "pypi.org"


def test_to_dict_drops_defaults():
    p = ProjectConfig(project="demo")
    d = to_dict(p)
    assert d == {"project": "demo"}


def test_egress_rule_key_and_default_port():
    r = EgressRule(dst="pypi.org", proto="https")
    assert r.effective_port() == 443
    assert r.key() == "pypi.org:https:443"


def test_settings_defaults(tenv):
    s = settings_store().typed()
    assert isinstance(s, Settings)
    assert s.firewall.enable is False
    assert s.runtime.driver == "local"
    assert s.control_plane.admin_port == 7443


def test_settings_file_overrides(tenv):
    tenv.write_settings("firewall:\n  enable: true\nruntime:\n  driver: tpu_vm\n  tpu:\n    pod: my-v5e\n")
    s = settings_store().typed()
    assert s.firewall.enable is True
    assert s.runtime.driver == "tpu_vm"
    assert s.runtime.tpu.pod == "my-v5e"


def test_xdg_isolation(tenv):
    assert str(xdg.config_dir()) == str(tenv.config)
    assert xdg.validate_directories() == []


def test_load_config_with_project(tenv, tmp_path):
    tenv.make_project(
        tmp_path,
        "project: demo\nsecurity:\n  egress:\n    - dst: pypi.org\n      proto: https\n",
    )
    cfg = load_config(tmp_path)
    assert cfg.project_name() == "demo"
    keys = {r.key() for r in cfg.egress_rules()}
    assert "pypi.org:https:443" in keys
    # required internal domains always present
    assert any(r.dst == "api.anthropic.com" for r in cfg.egress_rules())


def test_load_config_no_project(tenv, tmp_path):
    cfg = load_config(tmp_path)
    assert cfg.project is None
    with pytest.raises(LookupError):
        cfg.project_name()


def test_project_local_overlay_union(tenv, tmp_path):
    tenv.make_project(
        tmp_path,
        "project: demo\nbuild:\n  packages: [a]\n",
        local="build:\n  packages: [b]\n",
    )
    cfg = load_config(tmp_path)
    assert cfg.project.build.packages == ["a", "b"]
