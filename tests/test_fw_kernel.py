"""The REAL kernel programs under test: fw.c compiled with the host
compiler (native/ebpf/fw_harness.c) and driven via ctypes.

This is the verifier-shaped gate the dev tree can run: the decision logic
(fw_decide), context rewrites, reverse-NAT, v6 mapping, sock_create and
the event rate limiter all execute as written, against emulated maps --
and are differential-tested against the Python policy oracle
(clawker_tpu/firewall/policy.py), the same dual-guard the storage engine
uses.  The clang -target bpf artifact gate is scripts/check_bpf.sh (runs
where clang exists; the TPU-VM provisioner builds fw.o for real).

Parity bar: the reference exercises its programs only through e2e against
a live kernel (test/e2e/firewall_test.go); this harness reaches the same
logic without a kernel.
"""

from __future__ import annotations

import ctypes
import random
import shutil
import socket
import struct
import subprocess
from pathlib import Path

import pytest

from clawker_tpu.firewall.model import (
    FLAG_ENFORCE,
    FLAG_HOSTPROXY,
    PROTO_TCP,
    PROTO_UDP,
    Action,
    ContainerPolicy,
    DnsEntry,
    Reason,
    RouteKey,
    RouteVal,
)

EBPF_DIR = Path(__file__).resolve().parent.parent / "native" / "ebpf"
CC = shutil.which("cc") or shutil.which("gcc")
pytestmark = pytest.mark.skipif(CC is None, reason="no host C compiler")

# map ids (fw_harness.c enum -- harness ABI)
M_CONTAINERS, M_BYPASS, M_DNS, M_ROUTES, M_UDP, M_TCP, M_RL = range(7)

OK, EPERM = 1, 0
SOCK_STREAM, SOCK_DGRAM, SOCK_RAW, SOCK_PACKET = 1, 2, 3, 10
AF_INET, AF_INET6 = 2, 10


class SockAddr(ctypes.Structure):
    """bpf_sock_addr as fw.c declares it (UAPI layout subset)."""

    _fields_ = [
        ("user_family", ctypes.c_uint32),
        ("user_ip4", ctypes.c_uint32),
        ("user_ip6", ctypes.c_uint32 * 4),
        ("user_port", ctypes.c_uint32),
        ("family", ctypes.c_uint32),
        ("type", ctypes.c_uint32),
        ("protocol", ctypes.c_uint32),
        ("msg_src_ip4", ctypes.c_uint32),
        ("msg_src_ip6", ctypes.c_uint32 * 4),
    ]


class Event(ctypes.Structure):
    _fields_ = [
        ("ts_ns", ctypes.c_uint64),
        ("cgroup_id", ctypes.c_uint64),
        ("zone_hash", ctypes.c_uint64),
        ("dst_ip", ctypes.c_uint32),
        ("dst_port", ctypes.c_uint16),
        ("verdict", ctypes.c_uint8),
        ("proto", ctypes.c_uint8),
        ("reason", ctypes.c_uint8),
        ("pad", ctypes.c_uint8 * 7),
    ]


def ip_be(ip: str) -> int:
    return struct.unpack("<I", socket.inet_aton(ip))[0]


def be_ip(v: int) -> str:
    return socket.inet_ntoa(struct.pack("<I", v))


def port_be(p: int) -> int:
    return socket.htons(p)


@pytest.fixture(scope="module")
def fw():
    so = EBPF_DIR / "build" / "fw_harness.so"
    subprocess.run(["make", "-C", str(EBPF_DIR), "harness"], check=True,
                   capture_output=True)
    lib = ctypes.CDLL(str(so))
    lib.fwh_map_update.argtypes = [ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p]
    lib.fwh_map_lookup.argtypes = [ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p]
    lib.fwh_map_delete.argtypes = [ctypes.c_int, ctypes.c_void_p]
    lib.fwh_set_cgroup.argtypes = [ctypes.c_uint64]
    lib.fwh_set_cookie.argtypes = [ctypes.c_uint64]
    lib.fwh_set_time_ns.argtypes = [ctypes.c_uint64]
    lib.fwh_set_boot_ns.argtypes = [ctypes.c_uint64]
    lib.fwh_pop_event.argtypes = [ctypes.POINTER(Event)]
    for name in ("connect4", "sendmsg4", "recvmsg4", "getpeername4",
                 "connect6", "sendmsg6", "recvmsg6", "getpeername6"):
        fn = getattr(lib, f"fwh_run_{name}")
        fn.argtypes = [ctypes.POINTER(SockAddr)]
        fn.restype = ctypes.c_int
    lib.fwh_run_sock_create.argtypes = [ctypes.c_uint32] * 3
    lib.fwh_run_sock_create.restype = ctypes.c_int
    return lib


class Kern:
    """Typed convenience wrapper over the harness lib."""

    def __init__(self, lib):
        self.lib = lib
        lib.fwh_reset()

    # -- state
    def enroll(self, cg: int, pol: ContainerPolicy) -> None:
        key = struct.pack("<Q", cg)
        val = pol.pack()
        assert self.lib.fwh_map_update(M_CONTAINERS, key, val) == 0

    def set_bypass(self, cg: int, deadline_boot_ns: int) -> None:
        key = struct.pack("<Q", cg)
        val = struct.pack("<Q", deadline_boot_ns)
        assert self.lib.fwh_map_update(M_BYPASS, key, val) == 0

    def bypass_present(self, cg: int) -> bool:
        out = ctypes.create_string_buffer(8)
        return bool(self.lib.fwh_map_lookup(M_BYPASS, struct.pack("<Q", cg), out))

    def cache_dns(self, ip: str, entry: DnsEntry) -> None:
        assert self.lib.fwh_map_update(M_DNS, socket.inet_aton(ip), entry.pack()) == 0

    def add_route(self, rk: RouteKey, rv: RouteVal) -> None:
        assert self.lib.fwh_map_update(M_ROUTES, rk.pack(), rv.pack()) == 0

    def flow(self, map_id: int, cookie: int):
        out = ctypes.create_string_buffer(8)
        if not self.lib.fwh_map_lookup(map_id, struct.pack("<Q", cookie), out):
            return None
        ip, port = struct.unpack("<IH2x", out.raw)
        return be_ip(ip), socket.ntohs(port)

    # -- programs
    def connect4(self, cg: int, ip: str, port: int, *, udp=False, cookie=1):
        self.lib.fwh_set_cgroup(cg)
        self.lib.fwh_set_cookie(cookie)
        ctx = SockAddr(user_family=AF_INET, user_ip4=ip_be(ip),
                       user_port=port_be(port), family=AF_INET,
                       type=SOCK_DGRAM if udp else SOCK_STREAM,
                       protocol=PROTO_UDP if udp else PROTO_TCP)
        rc = self.lib.fwh_run_connect4(ctypes.byref(ctx))
        return rc, be_ip(ctx.user_ip4), socket.ntohs(ctx.user_port & 0xFFFF)

    def sendmsg4(self, cg: int, ip: str, port: int, *, cookie=1):
        self.lib.fwh_set_cgroup(cg)
        self.lib.fwh_set_cookie(cookie)
        ctx = SockAddr(user_family=AF_INET, user_ip4=ip_be(ip),
                       user_port=port_be(port), family=AF_INET,
                       type=SOCK_DGRAM, protocol=PROTO_UDP)
        rc = self.lib.fwh_run_sendmsg4(ctx)
        return rc, be_ip(ctx.user_ip4), socket.ntohs(ctx.user_port & 0xFFFF)

    def rewrite4(self, prog: str, cg: int, src_ip: str, src_port: int, *, cookie=1):
        self.lib.fwh_set_cgroup(cg)
        self.lib.fwh_set_cookie(cookie)
        ctx = SockAddr(user_family=AF_INET, user_ip4=ip_be(src_ip),
                       user_port=port_be(src_port), family=AF_INET)
        rc = getattr(self.lib, f"fwh_run_{prog}")(ctypes.byref(ctx))
        return rc, be_ip(ctx.user_ip4), socket.ntohs(ctx.user_port & 0xFFFF)

    def connect6(self, cg: int, ip6_words: list[int], port: int, *, udp=False, cookie=1):
        self.lib.fwh_set_cgroup(cg)
        self.lib.fwh_set_cookie(cookie)
        ctx = SockAddr(user_family=AF_INET6,
                       user_ip6=(ctypes.c_uint32 * 4)(*ip6_words),
                       user_port=port_be(port), family=AF_INET6,
                       type=SOCK_DGRAM if udp else SOCK_STREAM,
                       protocol=PROTO_UDP if udp else PROTO_TCP)
        rc = self.lib.fwh_run_connect6(ctypes.byref(ctx))
        return rc, list(ctx.user_ip6), socket.ntohs(ctx.user_port & 0xFFFF)

    def events(self) -> list[Event]:
        out = []
        ev = Event()
        while self.lib.fwh_pop_event(ctypes.byref(ev)):
            out.append(Event.from_buffer_copy(ev))
        return out


POL = ContainerPolicy(envoy_ip="172.28.0.2", dns_ip="172.28.0.1",
                      hostproxy_ip="172.28.0.1", hostproxy_port=18374,
                      flags=FLAG_ENFORCE | FLAG_HOSTPROXY)
CG = 4242


@pytest.fixture()
def k(fw):
    kern = Kern(fw)
    kern.enroll(CG, POL)
    return kern


# ------------------------------------------------------------ decide steps

def test_unenrolled_cgroup_untouched(fw):
    k = Kern(fw)
    rc, ip, port = k.connect4(999, "8.8.8.8", 443)
    assert (rc, ip, port) == (OK, "8.8.8.8", 443)
    assert k.events() == []


def test_ip_literal_denied_enforce_mode(k):
    rc, *_ = k.connect4(CG, "8.8.4.4", 443)
    assert rc == EPERM
    (ev,) = k.events()
    assert ev.verdict == int(Action.DENY)
    assert ev.reason == int(Reason.NO_DNS_ENTRY)


def test_monitor_mode_allows_and_logs(fw):
    k = Kern(fw)
    k.enroll(CG, ContainerPolicy(envoy_ip="172.28.0.2", dns_ip="172.28.0.1",
                                 hostproxy_ip="0.0.0.0", hostproxy_port=0,
                                 flags=0))
    rc, *_ = k.connect4(CG, "8.8.4.4", 443)
    assert rc == OK
    (ev,) = k.events()
    assert ev.reason == int(Reason.MONITOR)


def test_loopback_allowed_silently(k):
    rc, *_ = k.connect4(CG, "127.0.0.1", 9999)
    assert rc == OK
    assert k.events() == []


def test_intra_net_cidr_allowed_silently(fw):
    """Sibling services on the sandbox bridge need no rules (reference
    e2e: firewall_test.go:398 IntraNetworkBypass)."""
    k = Kern(fw)
    k.enroll(CG, ContainerPolicy(envoy_ip="172.28.0.2", dns_ip="172.28.0.1",
                                 hostproxy_ip="0.0.0.0", hostproxy_port=0,
                                 flags=FLAG_ENFORCE,
                                 net_ip="172.28.0.0", net_prefix=24))
    rc, ip, port = k.connect4(CG, "172.28.0.77", 8080)
    assert (rc, ip, port) == (OK, "172.28.0.77", 8080)
    assert k.events() == []
    # one bit outside the prefix: back to default deny (no dns entry)
    rc, *_ = k.connect4(CG, "172.28.1.77", 8080)
    assert rc == EPERM
    # the gateway (= the host) is NOT a sibling: an arbitrary host port
    # must stay blocked even inside the CIDR (firewall_test.go:497
    # "CIDR bypass doesn't cover host")
    rc, *_ = k.connect4(CG, "172.28.0.1", 9999)
    assert rc == EPERM


def test_intra_net_prefix_edge_cases_match_oracle(fw):
    """Prefix-mask boundaries (0 = disabled, 31/32 = near-host masks,
    host-order base address) must agree between the C kernel and the
    Python oracle -- an off-by-one in mask math either opens the whole
    internet (prefix 0 treated as /0 match-all) or breaks sibling reach."""
    from clawker_tpu.firewall import policy as oracle
    from clawker_tpu.firewall.maps import FakeMaps

    probes = ["172.28.0.76", "172.28.0.77", "172.28.0.78", "172.28.1.77",
              "8.8.4.4", "0.0.0.0", "255.255.255.255"]
    cases = [
        ("0.0.0.0", 0),        # disabled: nothing intra-net
        ("172.28.0.0", 0),     # prefix 0 with a base set: still disabled
        ("172.28.0.76", 31),   # /31: exactly .76/.77
        ("172.28.0.77", 32),   # /32: exactly the one host
        ("172.28.0.77", 24),   # host-order base: mask applies to both sides
        ("172.28.0.0", 1),     # /1: half the internet (mask sanity)
    ]
    for net_ip, net_prefix in cases:
        pol = ContainerPolicy(envoy_ip="172.29.0.2", dns_ip="172.29.0.1",
                              hostproxy_ip="0.0.0.0", hostproxy_port=0,
                              flags=FLAG_ENFORCE,
                              net_ip=net_ip, net_prefix=net_prefix)
        k = Kern(fw)
        k.enroll(CG, pol)
        fm = FakeMaps()
        fm.enroll(CG, pol)
        for ip in probes:
            rc, *_ = k.connect4(CG, ip, 8080)
            v = oracle.connect4(fm, CG, ip, 8080, sock_cookie=1)
            want = OK if v.action is not Action.DENY else EPERM
            assert rc == want, (
                f"net={net_ip}/{net_prefix} ip={ip}: kernel rc={rc} "
                f"oracle={v.action.name}/{v.reason.name}")
    # explicit floor: with prefix 0 the bypass must never fire
    k = Kern(fw)
    k.enroll(CG, ContainerPolicy(envoy_ip="172.29.0.2", dns_ip="172.29.0.1",
                                 hostproxy_ip="0.0.0.0", hostproxy_port=0,
                                 flags=FLAG_ENFORCE,
                                 net_ip="172.28.0.0", net_prefix=0))
    assert k.connect4(CG, "172.28.0.77", 8080)[0] == EPERM


def test_dns_rewritten_to_gate(k):
    rc, ip, port = k.connect4(CG, "8.8.8.8", 53, udp=True, cookie=77)
    assert rc == OK
    assert (ip, port) == (POL.dns_ip, 53)       # hardcoded resolver captured
    assert k.flow(M_UDP, 77) == ("8.8.8.8", 53)  # reverse-NAT noted
    rc, ip, port = k.connect4(CG, POL.dns_ip, 53, udp=True)
    assert (rc, ip, port) == (OK, POL.dns_ip, 53)  # gate itself: untouched


def test_envoy_and_hostproxy_allowed(k):
    assert k.connect4(CG, POL.envoy_ip, 10000)[0] == OK
    assert k.connect4(CG, POL.hostproxy_ip, 18374)[0] == OK
    # hostproxy on the wrong port is not the side channel
    assert k.connect4(CG, POL.hostproxy_ip, 2222)[0] == EPERM


def test_route_redirects_to_envoy_and_reverses(k):
    zone = 0xDEAD
    k.cache_dns("93.184.216.34", DnsEntry(zone, 2**62))
    k.add_route(RouteKey(zone, 443, PROTO_TCP),
                RouteVal(Action.REDIRECT, redirect_ip=POL.envoy_ip,
                         redirect_port=10000))
    rc, ip, port = k.connect4(CG, "93.184.216.34", 443, cookie=5)
    assert (rc, ip, port) == (OK, POL.envoy_ip, 10000)
    (ev,) = k.events()
    assert ev.verdict == int(Action.REDIRECT) and ev.zone_hash == zone
    # getpeername presents the original dst (tcp_flows consulted)
    rc, ip, port = k.rewrite4("getpeername4", CG, POL.envoy_ip, 10000, cookie=5)
    assert (ip, port) == ("93.184.216.34", 443)
    # recvmsg does NOT consult tcp_flows
    rc, ip, port = k.rewrite4("recvmsg4", CG, POL.envoy_ip, 10000, cookie=5)
    assert (ip, port) == (POL.envoy_ip, 10000)


def test_any_port_route_fallback(k):
    zone = 0xBEEF
    k.cache_dns("1.2.3.4", DnsEntry(zone, 2**62))
    k.add_route(RouteKey(zone, 0, PROTO_TCP), RouteVal(Action.ALLOW))
    assert k.connect4(CG, "1.2.3.4", 8443)[0] == OK
    # but proto must match: UDP to the same zone has no route
    assert k.connect4(CG, "1.2.3.4", 8443, udp=True)[0] == EPERM


def test_resolved_zone_unruled_port_denied(k):
    zone = 0xCAFE
    k.cache_dns("4.4.4.4", DnsEntry(zone, 2**62))
    k.add_route(RouteKey(zone, 443, PROTO_TCP), RouteVal(Action.ALLOW))
    assert k.connect4(CG, "4.4.4.4", 443)[0] == OK
    rc, *_ = k.connect4(CG, "4.4.4.4", 22)
    assert rc == EPERM
    evs = k.events()
    assert evs[-1].reason == int(Reason.NO_ROUTE)


def test_udp_reverse_nat_roundtrip(k):
    """sendmsg rewrite -> recvmsg presents the original source (the app
    sees replies from the resolver it addressed)."""
    rc, ip, port = k.sendmsg4(CG, "9.9.9.9", 53, cookie=31)
    assert (ip, port) == (POL.dns_ip, 53)
    rc, ip, port = k.rewrite4("recvmsg4", CG, POL.dns_ip, 53, cookie=31)
    assert (ip, port) == ("9.9.9.9", 53)
    # replies from unrelated sources are not rewritten
    rc, ip, port = k.rewrite4("recvmsg4", CG, "5.5.5.5", 53, cookie=31)
    assert (ip, port) == ("5.5.5.5", 53)


# ------------------------------------------------------------------ bypass

def test_bypass_allows_everything_and_deadman_deletes(fw):
    k = Kern(fw)
    k.enroll(CG, POL)
    k.lib.fwh_set_boot_ns(1_000)
    k.set_bypass(CG, 5_000)
    rc, *_ = k.connect4(CG, "8.8.4.4", 443)
    assert rc == OK
    (ev,) = k.events()
    assert ev.reason == int(Reason.BYPASS)
    # deadline passes: first touch deletes the entry IN KERNEL (no
    # userspace needed -- fail-closed even if the CP died)
    k.lib.fwh_set_boot_ns(6_000)
    rc, *_ = k.connect4(CG, "8.8.4.4", 443)
    assert rc == EPERM
    assert not k.bypass_present(CG)


# -------------------------------------------------------------------- IPv6

V4MAPPED = struct.unpack("<I", bytes([0, 0, 0xFF, 0xFF]))[0]


def words(ip4: str) -> list[int]:
    return [0, 0, V4MAPPED, ip_be(ip4)]


def test_v6_native_denied_v4mapped_routed(k):
    # native v6: denied (v4-only data plane)
    rc, *_ = k.connect6(CG, [0x20010DB8, 0, 0, 1], 443)
    assert rc == EPERM
    (ev,) = k.events()
    assert ev.reason == int(Reason.IPV6)
    # v6 loopback: allowed
    lo = [0, 0, 0, struct.unpack("<I", struct.pack(">I", 1))[0]]
    assert k.connect6(CG, lo, 9999)[0] == OK
    # v4-mapped routes through the v4 decision, rewrite stays mapped
    zone = 0xF00D
    k.cache_dns("93.184.216.34", DnsEntry(zone, 2**62))
    k.add_route(RouteKey(zone, 443, PROTO_TCP),
                RouteVal(Action.REDIRECT, redirect_ip=POL.envoy_ip,
                         redirect_port=10000))
    rc, ip6, port = k.connect6(CG, words("93.184.216.34"), 443, cookie=9)
    assert rc == OK
    assert ip6[:3] == [0, 0, V4MAPPED]          # still v4-mapped form
    assert be_ip(ip6[3]) == POL.envoy_ip and port == 10000
    # getpeername6 reverses it
    k.lib.fwh_set_cookie(9)
    ctx = SockAddr(user_family=AF_INET6,
                   user_ip6=(ctypes.c_uint32 * 4)(*words(POL.envoy_ip)),
                   user_port=port_be(10000), family=AF_INET6)
    k.lib.fwh_run_getpeername6(ctypes.byref(ctx))
    assert be_ip(ctx.user_ip6[3]) == "93.184.216.34"


def test_v6_bypass_opens_native_v6(fw):
    k = Kern(fw)
    k.enroll(CG, POL)
    k.lib.fwh_set_boot_ns(0)
    k.set_bypass(CG, 10_000)
    rc, *_ = k.connect6(CG, [0x20010DB8, 0, 0, 1], 443)
    assert rc == OK


# ------------------------------------------------------------- sock_create

def test_raw_and_packet_sockets_denied(k):
    k.lib.fwh_set_cgroup(CG)
    assert k.lib.fwh_run_sock_create(AF_INET, SOCK_RAW, 1) == EPERM  # ICMP
    assert k.lib.fwh_run_sock_create(AF_INET, SOCK_PACKET, 0) == EPERM
    assert k.lib.fwh_run_sock_create(AF_INET, SOCK_STREAM, 6) == OK
    evs = k.events()
    assert [e.reason for e in evs] == [int(Reason.RAW_SOCKET)] * 2
    # unenrolled cgroup: raw sockets are not our business
    k.lib.fwh_set_cgroup(31337)
    assert k.lib.fwh_run_sock_create(AF_INET, SOCK_RAW, 1) == OK


# --------------------------------------------------------------- ratelimit

def test_event_rate_limit_window(fw):
    k = Kern(fw)
    k.enroll(CG, POL)
    k.lib.fwh_set_time_ns(0)
    for _ in range(100):
        k.connect4(CG, "8.8.4.4", 443)      # every one emits (denied)
    assert len(k.events()) == 64            # FW_RL_BURST
    # new window refills
    k.lib.fwh_set_time_ns(200_000_000)
    k.connect4(CG, "8.8.4.4", 443)
    assert len(k.events()) == 1


# ------------------------------------------------- differential vs oracle

def test_differential_against_policy_oracle(fw):
    """The kernel C and the Python executable spec must produce the same
    verdict stream over randomized scenarios (the dual-guard)."""
    from clawker_tpu.firewall import policy as oracle
    from clawker_tpu.firewall.maps import FakeMaps

    rng = random.Random(1234)
    ips = ["8.8.8.8", "127.0.0.1", "172.28.0.1", "172.28.0.2",
           "93.184.216.34", "1.2.3.4", "4.4.4.4", "10.0.0.7"]
    ports = [53, 80, 443, 22, 8443, 18374]
    zones = {"93.184.216.34": 0xA1, "1.2.3.4": 0xB2, "4.4.4.4": 0xC3}

    for trial in range(300):
        flags = rng.choice([0, FLAG_ENFORCE, FLAG_ENFORCE | FLAG_HOSTPROXY])
        # intra-net CIDR allowance: off, the bridge /24, or a /16 that
        # also covers the 172.28.* service IPs
        net_ip, net_prefix = rng.choice([
            ("0.0.0.0", 0), ("10.0.0.0", 24), ("172.28.0.0", 16)])
        pol = ContainerPolicy(envoy_ip="172.28.0.2", dns_ip="172.28.0.1",
                              hostproxy_ip="172.28.0.1", hostproxy_port=18374,
                              flags=flags, net_ip=net_ip, net_prefix=net_prefix)
        k = Kern(fw)
        k.enroll(CG, pol)
        fm = FakeMaps()
        fm.enroll(CG, pol)

        for ip, zh in zones.items():
            if rng.random() < 0.7:
                k.cache_dns(ip, DnsEntry(zh, 2**62))
                fm.cache_dns(ip, DnsEntry(zh, 2**40))  # unix-s horizon
        routes = {}
        for zh in (0xA1, 0xB2, 0xC3):
            if rng.random() < 0.7:
                rk = RouteKey(zh, rng.choice([0, 443, 53, 22]),
                              rng.choice([PROTO_TCP, PROTO_UDP]))
                rv = rng.choice([
                    RouteVal(Action.ALLOW),
                    RouteVal(Action.DENY),
                    RouteVal(Action.REDIRECT, redirect_ip="172.28.0.2",
                             redirect_port=10000),
                ])
                routes[rk] = rv
                k.add_route(rk, rv)
        fm.sync_routes(routes)

        for _ in range(10):
            ip = rng.choice(ips)
            port = rng.choice(ports)
            udp = rng.random() < 0.4
            proto = PROTO_UDP if udp else PROTO_TCP
            v = oracle.decide(fm, CG, ip, port, proto)
            rc, out_ip, out_port = k.connect4(CG, ip, port, udp=udp)
            ctxt = f"trial={trial} ip={ip} port={port} proto={proto} flags={flags}"
            if v.action in (Action.ALLOW,):
                assert rc == OK, ctxt
                assert (out_ip, out_port) == (ip, port), ctxt
            elif v.action in (Action.REDIRECT, Action.REDIRECT_DNS):
                assert rc == OK, ctxt
                assert (out_ip, out_port) == (v.redirect_ip, v.redirect_port), ctxt
            else:
                assert rc == EPERM, ctxt
            # event streams agree on (verdict, reason)
            k_evs = [(e.verdict, e.reason) for e in k.events()]
            o_evs = [(int(e.verdict), int(e.reason)) for e in fm.drain_events()]
            assert k_evs == o_evs, ctxt
