"""nsd daemon tests: the Docker API surface over real namespaces.

Skip-gated on nsd capability (root + unshare/nsenter); where it runs,
every assertion is against real kernel behavior through the SAME client
(engine/httpapi.HTTPDockerAPI) the local/tpu_vm drivers use -- so wire
format, hijack framing and lifecycle semantics are pinned daemon-side.
The CLI-level behavior rides on top in tests/e2e/.
"""

from __future__ import annotations

import io
import os
import tarfile
import threading
import time

import pytest

from clawker_tpu.engine.drivers.nsdriver import nsd_capable

pytestmark = pytest.mark.skipif(
    not nsd_capable(), reason="nsd needs root + unshare/nsenter")


@pytest.fixture(scope="module")
def api(tmp_path_factory):
    from clawker_tpu.engine.httpapi import HTTPDockerAPI, unix_socket_factory
    from clawker_tpu.nsd.server import NsDaemon

    td = tmp_path_factory.mktemp("nsd")
    sock = td / "nsd.sock"
    daemon = NsDaemon(td / "state", sock)
    t = threading.Thread(target=daemon.serve, daemon=True)
    t.start()
    for _ in range(200):
        if sock.exists():
            break
        time.sleep(0.01)
    api = HTTPDockerAPI(unix_socket_factory(sock))
    list(api.image_pull("busybox:latest"))
    yield api
    daemon.shutdown()


def _create(api, name, cmd, **cfg):
    base = {"Image": "busybox:latest", "Cmd": cmd, "Labels": {}}
    base.update(cfg)
    return api.container_create(name, base)["Id"]


def test_ping_info_version(api):
    assert api.ping()
    assert api.info()["Name"] == "nsd"
    assert api.version()["ApiVersion"] == "1.43"


def test_lifecycle_exit_code_and_framed_logs(api):
    cid = _create(api, "lc1", ["sh", "-c", "echo out-line; echo err-line >&2; exit 3"])
    api.container_start(cid)
    assert api.container_wait(cid)["StatusCode"] == 3
    insp = api.container_inspect(cid)
    assert insp["State"]["Status"] == "exited"
    assert insp["State"]["ExitCode"] == 3
    logs = b"".join(api.container_logs(cid))
    # stdcopy framing: stream ids distinguish stdout/stderr
    assert b"\x01\x00\x00\x00" in logs and b"out-line" in logs
    assert b"\x02\x00\x00\x00" in logs and b"err-line" in logs
    api.container_remove(cid, force=True)


def test_pid_and_uts_isolation(api):
    cid = _create(api, "iso1", ["sh", "-c", 'echo "pid=$$ host=$(hostname)"'],
                  Hostname="isolated-ns")
    api.container_start(cid)
    api.container_wait(cid)
    logs = b"".join(api.container_logs(cid))
    assert b"pid=1 " in logs          # the command IS namespace init
    assert b"host=isolated-ns" in logs
    api.container_remove(cid, force=True)


def test_overlay_writes_never_touch_host(api):
    marker = f"/tmp/nsd-breakout-{os.getpid()}"
    cid = _create(api, "ovl1", ["sh", "-c", f"echo gotcha > {marker}"])
    api.container_start(cid)
    api.container_wait(cid)
    assert not os.path.exists(marker), "container write leaked to host"
    api.container_remove(cid, force=True)


def test_attach_stdin_and_archive_before_start(api):
    cid = _create(api, "att1", ["sh", "-c",
                                "read l; echo got:$l; cat /seeded/f.txt"],
                  OpenStdin=True)
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        data = b"seeded-content\n"
        ti = tarfile.TarInfo("f.txt")
        ti.size = len(data)
        tf.addfile(ti, io.BytesIO(data))
    api.put_archive(cid, "/seeded", buf.getvalue())
    stream = api.container_attach(cid, tty=False)
    api.container_start(cid)
    stream.write(b"over-stdin\n")
    got = b"".join(p for _, p in stream.frames())
    stream.close()
    assert b"got:over-stdin" in got
    assert b"seeded-content" in got
    api.container_wait(cid)
    api.container_remove(cid, force=True)


def test_archive_maps_bind_shadowed_paths(api, tmp_path):
    host_dir = tmp_path / "bound"
    host_dir.mkdir()
    cid = _create(api, "arc1", ["sh", "-c", "cat /work/in.txt > /work/out.txt"],
                  HostConfig={"Binds": [f"{host_dir}:/work"]})
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        data = b"bind-routed\n"
        ti = tarfile.TarInfo("in.txt")
        ti.size = len(data)
        tf.addfile(ti, io.BytesIO(data))
    api.put_archive(cid, "/work", buf.getvalue())
    assert (host_dir / "in.txt").read_bytes() == b"bind-routed\n"
    api.container_start(cid)
    api.container_wait(cid)
    assert (host_dir / "out.txt").read_bytes() == b"bind-routed\n"
    out = api.get_archive(cid, "/work/out.txt")
    with tarfile.open(fileobj=io.BytesIO(out)) as tf:
        assert tf.extractfile("out.txt").read() == b"bind-routed\n"
    api.container_remove(cid, force=True)


def test_exec_in_namespaces_with_exit_code(api):
    cid = _create(api, "ex1", ["sh", "-c", "sleep 15"], Hostname="exhost")
    api.container_start(cid)
    time.sleep(0.3)
    e = api.exec_create(cid, {"Cmd": ["sh", "-c", "hostname"]})
    s = api.exec_start(e["Id"], tty=False)
    out = b"".join(p for _, p in s.frames())
    assert b"exhost" in out
    e2 = api.exec_create(cid, {"Cmd": ["sh", "-c", "exit 9"]})
    s2 = api.exec_start(e2["Id"], tty=False)
    list(s2.frames())
    assert api.exec_inspect(e2["Id"])["ExitCode"] == 9
    api.container_stop(cid, timeout=1)
    assert api.container_inspect(cid)["State"]["ExitCode"] == 137
    api.container_remove(cid, force=True)


def test_volumes_and_label_filters(api):
    api.volume_create("nsdvol1", labels={"clawker.managed": "1"})
    vols = api.volume_list(filters={"label": ["clawker.managed=1"]})
    assert any(v["Name"] == "nsdvol1" for v in vols["Volumes"])
    cid = _create(api, "vol1", ["sh", "-c", "echo kept > /data/keep.txt"],
                  HostConfig={"Binds": ["nsdvol1:/data"]},
                  Labels={"clawker.project": "nsdtest"})
    api.container_start(cid)
    api.container_wait(cid)
    out = api.get_archive(cid, "/data/keep.txt")
    assert b"kept" in out
    rows = api.container_list(all=True,
                              filters={"label": ["clawker.project=nsdtest"]})
    assert any(r["Id"] == cid for r in rows)
    api.container_remove(cid, force=True)
    api.volume_remove("nsdvol1")


def test_conflict_and_not_found_map_to_http_statuses(api):
    from clawker_tpu.errors import NotFoundError

    cid = _create(api, "dup1", ["true"])
    with pytest.raises(Exception) as ei:
        _create(api, "dup1", ["true"])
    assert "already in use" in str(ei.value)
    api.container_remove(cid, force=True)
    with pytest.raises(NotFoundError):
        api.container_inspect("definitely-missing")


def test_concurrent_lifecycles_do_not_interfere(api):
    """Daemon-level race stress: N containers created/started/waited/
    removed from parallel threads; every exit code and log must be the
    right container's (the reference's -race analog at the daemon
    seam)."""
    N = 6
    errors: list[str] = []

    def one(i: int) -> None:
        try:
            # DISTINCT exit code per container: shared codes would let a
            # swapped wait result pass undetected
            cid = _create(api, f"race{i}",
                          ["sh", "-c", f"echo out-{i}; exit {10 + i}"])
            api.container_start(cid)
            code = api.container_wait(cid)["StatusCode"]
            if code != 10 + i:
                errors.append(f"race{i}: exit {code} != {10 + i}")
            logs = b"".join(api.container_logs(cid))
            if f"out-{i}".encode() not in logs:
                errors.append(f"race{i}: logs missing own marker: {logs!r}")
            for j in range(N):
                if j != i and f"out-{j}".encode() in logs:
                    errors.append(f"race{i}: got race{j}'s output: {logs!r}")
            api.container_remove(cid, force=True)
        except Exception as e:  # noqa: BLE001 - collect, don't die
            errors.append(f"race{i}: {e.__class__.__name__}: {e}")

    threads = [threading.Thread(target=one, args=(i,)) for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not any(t.is_alive() for t in threads), "daemon deadlock under load"
    assert not errors, errors
    rows = api.container_list(all=True)
    assert not any(r["Names"][0].startswith("/race") for r in rows)


def test_socket_modes_are_restrictive_at_bind(tmp_path_factory):
    """The nsd unix socket is root-equivalent: it must come up 0600 with
    a 0700 parent dir regardless of the inherited umask (ADVICE round 5
    -- a 0755 socket dir + umask-mode socket hands container control to
    every local user)."""
    from clawker_tpu.nsd.server import NsDaemon

    td = tmp_path_factory.mktemp("nsd-sock")
    sock_dir = td / "run" / "clawker-nsd"
    sock = sock_dir / "nsd.sock"
    old_umask = os.umask(0o022)        # deliberately permissive
    try:
        daemon = NsDaemon(td / "state", sock)
        t = threading.Thread(target=daemon.serve, daemon=True)
        t.start()
        try:
            # the parent chmod is serve()'s LAST pre-listen step: poll
            # for it (not bare socket existence) or the assert can race
            # the daemon thread between bind and chmod
            for _ in range(200):
                if (sock.exists()
                        and (sock_dir.stat().st_mode & 0o777) == 0o700):
                    break
                time.sleep(0.01)
            assert sock.exists(), "daemon never bound its socket"
            assert (sock.stat().st_mode & 0o777) == 0o600
            assert (sock_dir.stat().st_mode & 0o777) == 0o700
            # the bind must not have leaked the narrow umask back out
            assert os.umask(0o022) == 0o022
        finally:
            daemon.shutdown()
    finally:
        os.umask(old_umask)
