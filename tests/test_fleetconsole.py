"""Fleet console suite (ISSUE 13): daemon-backed multi-run TUI.

The acceptance shape: the console renders everything one loopd hosts
(per-loop status, breakers, pools, tenants, workerd, ANOM-Z, span
waterfalls) from the SAME console-feed schema `loopd status --format
json` serves scripts; damage-tracked painting plus row virtualization
hold the repaint budget at 256 agents across 4 hosted runs; and the
per-run dashboard reuses the dirty-row painter instead of repainting
the full table every tick.
"""

from __future__ import annotations

import json
import time

import pytest

from clawker_tpu import consts
from clawker_tpu.config import load_config
from clawker_tpu.engine.drivers import FakeDriver
from clawker_tpu.engine.fake import exit_behavior
from clawker_tpu.loopd.feed import console_feed
from clawker_tpu.telemetry.spans import SpanRecord
from clawker_tpu.testenv import TestEnv
from clawker_tpu.ui.damage import DamagePainter
from clawker_tpu.ui.fleetconsole import (
    MAX_AGENT_ROWS,
    FleetConsole,
    SpanTail,
    virtualize,
)
from clawker_tpu.ui.iostreams import IOStreams

IMAGE = "clawker-consoleproj:default"


@pytest.fixture
def env():
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text(
            "project: consoleproj\n")
        cfg = load_config(proj)
        yield tenv, proj, cfg


def driver_with(n_workers: int, behavior=None):
    drv = FakeDriver(n_workers=n_workers)
    for api in drv.apis:
        api.add_image(IMAGE)
        api.set_behavior(IMAGE, behavior or exit_behavior(b"done\n", 0))
    return drv


class _Sink:
    def __init__(self):
        self.chunks: list[str] = []

    def write(self, s: str) -> None:
        self.chunks.append(s)

    def flush(self) -> None:
        pass

    def text(self) -> str:
        return "".join(self.chunks)


# --------------------------------------------------------------- painter


def test_damage_painter_first_frame_paints_all():
    sink = _Sink()
    p = DamagePainter(sink.write, sink.flush)
    assert p.paint(["a", "b", "c"]) == 3
    assert sink.text() == "\x1b[2Ka\n\x1b[2Kb\n\x1b[2Kc\n"


def test_damage_painter_unchanged_frame_paints_nothing():
    sink = _Sink()
    p = DamagePainter(sink.write, sink.flush)
    p.paint(["a", "b", "c"])
    sink.chunks.clear()
    assert p.paint(["a", "b", "c"]) == 0
    # one cursor-up, one batched cursor-down, zero rewrites
    assert sink.text() == "\x1b[3A\x1b[3B"


def test_damage_painter_rewrites_only_dirty_rows():
    sink = _Sink()
    p = DamagePainter(sink.write, sink.flush)
    p.paint(["a", "b", "c", "d"])
    sink.chunks.clear()
    assert p.paint(["a", "B", "c", "d"]) == 1
    out = sink.text()
    assert "\x1b[2KB\n" in out and "\x1b[2Ka" not in out
    assert out.startswith("\x1b[4A\x1b[1B")     # skip a, rewrite B, skip c+d
    assert out.endswith("\x1b[2B")


def test_damage_painter_growth_and_shrink():
    sink = _Sink()
    p = DamagePainter(sink.write, sink.flush)
    p.paint(["a"])
    assert p.paint(["a", "b", "c"]) == 2        # growth appends
    sink.chunks.clear()
    assert p.paint(["a"]) == 0                  # shrink: erase stale tail
    out = sink.text()
    assert out.count("\x1b[2K\n") == 2 and out.endswith("\x1b[2A")
    # after a shrink, a repaint of the same frame is still clean
    assert p.paint(["a"]) == 0


def test_damage_painter_reset_forces_full_repaint():
    sink = _Sink()
    p = DamagePainter(sink.write, sink.flush)
    p.paint(["a", "b"])
    p.reset()
    assert p.paint(["a", "b"]) == 2


# ------------------------------------------------------------------ feed


def _status_doc() -> dict:
    return {
        "pid": 99, "project": "p", "uptime_s": 7.5,
        "runs": [{
            "run": "r1", "state": "running", "tenant": "t", "client": "c",
            "parallel": 2, "iterations": 3, "placement": "spread",
            "subscribers": 1, "events_dropped": 4,
            "agents": [
                {"agent": "a0", "worker": "w0", "status": "running",
                 "iteration": 2, "exit_codes": [0, 0]},
                {"agent": "a1", "worker": "w1", "status": "failed",
                 "iteration": 1, "exit_codes": []},
            ]}],
        "admission": {"workers": {"w0": {"inflight": 1, "capacity": 4,
                                         "pending": 0, "rejected": 0}},
                      "tenants": {"t": {"weight": 1.0, "inflight": 1,
                                        "queued": 0, "dispatched": 3}}},
        "health": [{"worker": "w0", "state": "closed",
                    "breaker_state_gauge": 0, "probe_p50_ms": 1.0}],
        "workerd": {"w0": "ok"},
        "warm_pools": {},
        "sentinel": {"enabled": True, "rows": [
            {"agent": "a1", "worker": "w1", "latest_z": 4.4,
             "flagged": True}]},
        "shipper": {"enabled": True, "ingested_docs": 10,
                    "pending_batches": 0, "dropped_docs": 0},
        "events_dropped_total": 4,
    }


def test_console_feed_normalizes_runs_and_merges_sentinel():
    feed = console_feed(_status_doc())
    assert feed["pid"] == 99 and feed["events_dropped_total"] == 4
    (run,) = feed["runs"]
    assert run["events_dropped"] == 4 and run["subscribers"] == 1
    a0, a1 = run["agents"]
    assert a0["exits"] == "0,0" and a0["anomaly_z"] is None
    # the daemon sentinel's latest z lands on the matching agent row
    assert a1["anomaly_z"] == 4.4 and a1["status"] == "failed"
    assert feed["workers"]["w0"]["capacity"] == 4
    assert feed["shipper"]["enabled"]


def test_console_feed_tolerates_sparse_docs():
    feed = console_feed({})
    assert feed["runs"] == [] and feed["health"] == []
    assert feed["shipper"] == {"enabled": False}


# -------------------------------------------------------- virtualization


def _agents(n: int, run: int, status: str = "running") -> list[dict]:
    return [{"agent": f"r{run}-a{i:03d}", "worker": f"w{i % 4}",
             "status": status, "iteration": 1, "exits": "-",
             "anomaly_z": None} for i in range(n)]


def test_virtualize_below_budget_shows_everything():
    runs = [{"run": "r0", "agents": _agents(10, 0)}]
    ((_, visible, hidden),) = virtualize(runs)
    assert len(visible) == 10 and hidden == 0


def test_virtualize_bounds_rows_and_keeps_interesting_first():
    runs = []
    for r in range(4):
        agents = _agents(64, r)
        agents[50]["status"] = "failed"
        agents[51]["anomaly_z"] = 9.9
        runs.append({"run": f"r{r}", "agents": agents})
    out = virtualize(runs, budget=MAX_AGENT_ROWS)
    total = sum(len(v) for _, v, _ in out)
    assert total <= MAX_AGENT_ROWS
    for _, visible, hidden in out:
        names = {a["agent"] for a in visible}
        assert hidden == 64 - len(visible)
        # the failed row and the hottest-anomaly row survive the cut
        assert any(a["status"] == "failed" for a in visible)
        assert any(a.get("anomaly_z") == 9.9 for a in visible)
        assert names == set(sorted(names))      # stable render order


# -------------------------------------------------------------- spantail


def _write_spans(path, n, t0=0.0):
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "a", encoding="utf-8") as fh:
        for i in range(n):
            root = SpanRecord(
                trace_id="r1", span_id=f"s{t0}-{i}", parent_id="",
                name="iteration", agent=f"a{i % 4}", worker="w0",
                t_start=t0 + i, t_end=t0 + i + 0.8,
                attrs={"iteration": i})
            child = SpanRecord(
                trace_id="r1", span_id=f"c{t0}-{i}",
                parent_id=f"s{t0}-{i}", name="wait", agent=root.agent,
                worker="w0", t_start=t0 + i + 0.2, t_end=t0 + i + 0.7)
            fh.write(json.dumps(root.to_json()) + "\n")
            fh.write(json.dumps(child.to_json()) + "\n")


def test_spantail_incremental_and_bounded(tmp_path):
    from clawker_tpu.ui.colors import ColorScheme

    path = tmp_path / "flight.jsonl"
    _write_spans(path, 3)
    tail = SpanTail(path, limit=8)
    assert tail.poll() == 6
    lines = tail.waterfall_lines(ColorScheme(enabled=False))
    assert len(lines) == 3
    assert all("|" in l and "ms" in l for l in lines)
    assert "=" in lines[0]                      # the wait phase drew
    # incremental: only NEW records parse on the next poll
    _write_spans(path, 2, t0=100.0)
    assert tail.poll() == 4
    # bounded: the window holds the newest `limit` records
    assert len(tail.records) == 8


# ---------------------------------------------------- repaint budget @256


def test_repaint_budget_256_agents_4_runs():
    """The acceptance gate's test twin: 4 hosted runs x 64 agents --
    the frame is bounded by virtualization, steady-state frames with a
    handful of changed rows repaint a small fraction of their rows, and
    a frame builds+paints inside a generous wall ceiling."""
    statuses: dict = {}

    def doc() -> dict:
        runs = []
        for r in range(4):
            agents = []
            for i in range(64):
                status, iteration = statuses.get((r, i), ("running", 1))
                agents.append({"agent": f"loop-r{r}-{i:03d}",
                               "worker": f"w{i % 4}", "status": status,
                               "iteration": iteration, "exit_codes": [0]})
            runs.append({"run": f"run{r}", "state": "running",
                         "tenant": f"t{r}", "client": "x", "parallel": 64,
                         "iterations": 4, "placement": "spread",
                         "subscribers": 1, "events_dropped": 0,
                         "agents": agents})
        return {"pid": 1, "project": "p", "uptime_s": 1.0, "runs": runs,
                "admission": {"workers": {}, "tenants": {}}, "health": [],
                "workerd": {}, "warm_pools": {},
                "sentinel": {"enabled": False},
                "shipper": {"enabled": False}, "events_dropped_total": 0}

    streams, _, out, _ = IOStreams.test()
    console = FleetConsole(streams, lambda: console_feed(doc()))
    console.render_once()                       # frame 0 paints everything
    base = dict(console.painter.stats())
    walls = []
    for f in range(12):
        for j in range(8):                      # 8 rows churn per tick,
            statuses[(j % 4, (f + j) % 64)] = (  # mostly still running --
                "running" if (f + j) % 5 else "done", f)  # steady state
        t0 = time.perf_counter()
        console.render_once()
        walls.append(time.perf_counter() - t0)
        out.truncate(0)
        out.seek(0)
    frame = console.frame_lines(console_feed(doc()))
    agent_rows = sum(1 for l in frame if "loop-r" in l and "spans" not in l)
    assert agent_rows <= MAX_AGENT_ROWS         # virtualized at 256 agents
    assert len(frame) <= 140                    # whole frame bounded
    assert any("+" in l and "more" in l for l in frame)
    stats = console.painter.stats()
    painted = stats["rows_painted"] - base["rows_painted"]
    total = stats["rows_total"] - base["rows_total"]
    # steady-state damage: most rows are clean most frames
    assert painted < total * 0.5, (painted, total)
    # generous wall ceiling -- the bench gate owns the tight budget;
    # this catches an accidental O(agents^2) or full-file re-read
    assert sorted(walls)[len(walls) // 2] < 0.25


def test_console_renders_all_sections(env):
    tenv, proj, cfg = env
    doc = _status_doc()
    streams, _, out, _ = IOStreams.test()
    console = FleetConsole(streams, lambda: console_feed(doc),
                           logs_dir=cfg.logs_dir)
    from clawker_tpu.monitor.ledger import flight_path

    _write_spans(flight_path(cfg.logs_dir, "r1"), 2)
    text = console.snapshot()
    assert "fleet console" in text and "run r1" in text
    assert "a0" in text and "a1" in text
    assert "ANOM-Z" in text and "4.4" in text   # sentinel flag column
    assert "workers" in text and "workerd=ok" in text
    assert "tenants" in text
    assert "drops=4" in text                    # per-run dropped frames
    assert "spans" in text and "ms" in text     # waterfall rendered
    assert "ship:0p/0d" in text                 # shipper state in the bar


# ------------------------------------------------------- multi-pod merge


def _pod_status_doc(pod: str, n_agents: int = 16) -> dict:
    return {
        "pid": 1, "pod": pod, "project": "p", "uptime_s": 1.0,
        "runs": [{
            "run": f"r-{pod}", "state": "running", "tenant": "shared",
            "client": "c", "parallel": n_agents, "iterations": 2,
            "placement": "spread", "subscribers": 0, "events_dropped": 1,
            "agents": [
                {"agent": f"{pod}-a{i:03d}", "worker": f"{pod}-0",
                 "status": "running", "iteration": 1, "exit_codes": [0]}
                for i in range(n_agents)]}],
        "admission": {
            "workers": {"fake-0": {"inflight": 1, "capacity": 4,
                                   "pending": 0, "rejected": 0}},
            "tenants": {"shared": {"weight": 1.0, "inflight": 1,
                                   "queued": 0, "dispatched": 2}}},
        "health": [{"worker": "fake-0", "state": "closed",
                    "breaker_state_gauge": 0, "probe_p50_ms": 1.0}],
        "workerd": {"fake-0": "ok"}, "warm_pools": {},
        "sentinel": {"enabled": False}, "shipper": {"enabled": False},
        "events_dropped_total": 1,
    }


def test_merge_feeds_concatenates_and_disambiguates():
    from clawker_tpu.loopd.feed import merge_feeds

    feeds = [console_feed(_pod_status_doc(f"pod{i}")) for i in range(8)]
    merged = merge_feeds(feeds)
    assert merged["pods"] == [f"pod{i}" for i in range(8)]
    assert len(merged["runs"]) == 8
    assert {r["pod"] for r in merged["runs"]} == set(merged["pods"])
    # worker-keyed sections pod-prefixed: two pods' fake-0 never alias
    assert "pod0/fake-0" in merged["workers"]
    assert "pod7/fake-0" in merged["workerd"]
    assert all(h["worker"].split("/")[0] in merged["pods"]
               for h in merged["health"])
    # tenant rows SUM federation-wide (the view the router's WFQ
    # balances); drop counters sum too
    assert merged["tenants"]["shared"]["dispatched"] == 16
    assert merged["events_dropped_total"] == 8
    # the single-pod degenerate case is the feed itself, untouched
    assert merge_feeds([feeds[0]]) is feeds[0]


def test_console_multi_pod_feed_pod_column_and_budget():
    """The federation console satellite: 8 pods' feeds concatenated --
    the POD column appears, virtualization still bounds the frame at
    128 agents, and the damage painter holds a clean repaint."""
    from clawker_tpu.loopd.feed import merge_feeds

    feeds = [console_feed(_pod_status_doc(f"pod{i}")) for i in range(8)]
    merged = merge_feeds(feeds)
    streams, _, _, _ = IOStreams.test()
    console = FleetConsole(streams, lambda: merged)
    frame = console.frame_lines(merged)
    text = "\n".join(frame)
    assert "POD" in text                        # the multi-pod column
    assert "pods=pod0" in text                  # head names the pods
    agent_rows = sum(1 for l in frame if "-a0" in l)
    assert agent_rows <= MAX_AGENT_ROWS         # virtualized @128 agents
    assert len(frame) <= 140                    # whole frame bounded
    console.render_once()
    base = console.painter.stats()["rows_painted"]
    console.render_once()                       # unchanged merged feed:
    stats = console.painter.stats()             # zero repainted rows
    assert stats["rows_painted"] == base
    # single-pod feed renders WITHOUT the POD column: byte-identical
    # to the pre-federation console
    single = console.frame_lines(feeds[0])
    assert "POD" not in "\n".join(single)


# ------------------------------------------------ dashboard reuses painter


def test_dashboard_repaints_only_dirty_rows():
    from clawker_tpu.ui.dashboard import LoopDashboard

    class _Sched:
        loop_id = "dash1"

        def status(self):
            return [{"agent": f"a{i}", "worker": "w0", "status": "running",
                     "iteration": 1, "exit_codes": []} for i in range(16)]

    streams, _, out, _ = IOStreams.test()
    for stream in (streams.stdin, streams.stdout, streams.stderr):
        stream.isatty = lambda: True
    dash = LoopDashboard(streams, _Sched())
    dash.render_once()
    first = dash.painter.stats()["rows_painted"]
    assert first == dash.painter.stats()["rows_total"]
    dash.render_once()
    second = dash.painter.stats()["rows_painted"] - first
    # only the rows carrying elapsed time may repaint; the 16-row agent
    # table must not (the ISSUE 13 dirty-row fix)
    assert second <= 2, second


# --------------------------------------------------------------- CLI/RPC


def _submit_and_wait(cfg, drv, parallel=2):
    from clawker_tpu.loopd.client import LoopdClient
    from clawker_tpu.loopd.server import LoopdServer

    srv = LoopdServer(cfg, drv).start()
    with LoopdClient(srv.sock_path) as client:
        client.submit_run({"parallel": parallel, "iterations": 1},
                          stream=True)
        for frame in client.events():
            if frame.get("type") == "run_done":
                break
    return srv


def test_cli_fleet_console_once_and_json(env):
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    drv = driver_with(2)
    srv = _submit_and_wait(cfg, drv)
    try:
        res = CliRunner().invoke(
            cli, ["fleet", "console", "--once"],
            obj=Factory(cwd=proj, driver=drv), catch_exceptions=False)
        assert res.exit_code == 0, res.output
        assert "fleet console" in res.output and "run " in res.output
        res2 = CliRunner().invoke(
            cli, ["fleet", "console", "--format", "json"],
            obj=Factory(cwd=proj, driver=drv), catch_exceptions=False)
        assert res2.exit_code == 0, res2.output
        feed = json.loads(res2.output[res2.output.index("{"):])
        assert feed["runs"] and feed["runs"][0]["agents"]
        assert "events_dropped" in feed["runs"][0]
    finally:
        srv.stop()


def test_cli_fleet_console_without_daemon_exits_nonzero(env):
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    res = CliRunner().invoke(
        cli, ["fleet", "console", "--once"],
        obj=Factory(cwd=proj, driver=driver_with(1)))
    assert res.exit_code == 1
    assert "loopd" in res.output + (res.stderr or "")


def test_loopd_status_json_carries_console_feed(env):
    """The satellite contract: `loopd status --format json` and the
    console share one schema -- the feed rides under `console`, with
    per-run dropped-frame counts."""
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    drv = driver_with(2)
    srv = _submit_and_wait(cfg, drv)
    try:
        res = CliRunner().invoke(
            cli, ["loopd", "status", "--format", "json"],
            obj=Factory(cwd=proj, driver=drv), catch_exceptions=False)
        assert res.exit_code == 0, res.output
        doc = json.loads(res.output[res.output.index("{"):])
        feed = doc["console"]
        assert feed["runs"] and feed == console_feed(doc)
        run = feed["runs"][0]
        assert {"run", "state", "agents", "events_dropped"} <= set(run)
        assert all({"agent", "worker", "status", "iteration", "exits",
                    "anomaly_z"} <= set(a) for a in run["agents"])
    finally:
        srv.stop()


def test_console_bounds_run_count_live_runs_first():
    """Review fix: loopd retains up to 64 done runs -- the console must
    bound run sections (live first, newest done next) or the frame
    blows the repaint budget and the painter's cursor math."""
    runs = []
    for i in range(70):
        runs.append({"run": f"done{i:02d}", "state": "done", "tenant": "t",
                     "client": "c", "parallel": 2, "iterations": 1,
                     "placement": "spread", "subscribers": 0,
                     "events_dropped": 0, "agents": _agents(2, i, "done")})
    runs.append({"run": "liveA", "state": "running", "tenant": "t",
                 "client": "c", "parallel": 2, "iterations": 1,
                 "placement": "spread", "subscribers": 1,
                 "events_dropped": 0, "agents": _agents(2, 99)})
    feed = {"pid": 1, "project": "p", "uptime_s": 0.0, "runs": runs,
            "workers": {}, "tenants": {}, "health": [], "workerd": {},
            "warm_pools": {}, "sentinel": {"enabled": False},
            "shipper": {"enabled": False}, "events_dropped_total": 0}
    streams, _, _, _ = IOStreams.test()
    console = FleetConsole(streams, lambda: feed)
    frame = console.frame_lines(feed)
    assert len(frame) <= 140, len(frame)
    text = "\n".join(frame)
    assert "run liveA" in text                  # live run always shown
    assert "run done69" in text                 # newest done kept
    assert "run done00" not in text             # oldest done collapsed
    assert "more run(s) not shown" in text
