"""Placement & admission suite (ISSUE 6 / docs/loop-placement.md).

Unit coverage for the policy engine (spread/pack/topology, breaker
exclusion, latency weighting, topology fallback) and the admission
controller (token bucket, bounded queue, weighted fair queueing,
tenant caps, worker reset), then the pod-scale integration shapes on
the fake pod:

- 64 loops / 4 workers: no worker's admission bucket (or daemon) ever
  exceeds its cap, the burst still completes to budget.
- Two tenants sharing one pod through one controller: 1:1 weights
  complete with neither tenant starved behind the other's burst.
- A worker with an OPEN breaker receives ZERO placements.
- ``--resume`` restores the pending admission queue in journal order.
"""

from __future__ import annotations

import threading
import time

import pytest

from clawker_tpu import consts
from clawker_tpu.config import load_config
from clawker_tpu.config.schema import TPUSettings
from clawker_tpu.engine.api import Engine
from clawker_tpu.engine.drivers import FakeDriver, Worker
from clawker_tpu.engine.fake import FakeDockerAPI, exit_behavior
from clawker_tpu.errors import ClawkerError
from clawker_tpu.fleet.inventory import pod_topology
from clawker_tpu.health import BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN
from clawker_tpu.loop import LoopScheduler, LoopSpec
from clawker_tpu.loop.journal import (
    REC_ADMIT_QUEUED,
    REC_CREATED,
    REC_PLACEMENT,
    RunJournal,
    journal_path,
    replay,
)
from clawker_tpu.monitor.events import PLACEMENT_DECISION, PlacementEvent
from clawker_tpu.placement import (
    ADMISSION_DISPATCHED,
    ADMISSION_QUEUED,
    ADMISSION_REJECTED,
    AdmissionController,
    PlacementContext,
    get_policy,
)
from clawker_tpu.testenv import TestEnv

IMAGE = "clawker-loopproj:default"


@pytest.fixture
def env():
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: loopproj\n")
        cfg = load_config(proj)
        yield tenv, proj, cfg


def seed(drv: FakeDriver, behavior=None) -> None:
    for api in drv.apis:
        api.add_image(IMAGE)
        api.set_behavior(IMAGE, behavior or exit_behavior(b"iter done\n", 0))


def workers(n: int) -> list[Worker]:
    # bare workers with a non-None engine sentinel (eligibility checks
    # only test presence; no engine call is made by the policies)
    return [Worker(id=f"w{i}", index=i, engine=object()) for i in range(n)]


# ---------------------------------------------------------------- topology


def test_pod_topology_explicit_shape():
    topo = pod_topology(TPUSettings(topology="2x4"), 8)
    assert topo.known and (topo.rows, topo.cols) == (2, 4)
    assert topo.coords[0] == (0, 0) and topo.coords[5] == (1, 1)
    assert topo.group_of(3) == 0 and topo.group_of(4) == 1
    # intra-row is cheap, crossing a row costs a full row width
    assert topo.distance(0, 3) == 3
    assert topo.distance(0, 4) == 4


def test_pod_topology_near_square_inference():
    topo = pod_topology(TPUSettings(), 8)
    assert topo.known and (topo.rows, topo.cols) == (2, 4)
    assert pod_topology(TPUSettings(), 16).cols == 4


def test_pod_topology_degrades_to_unknown():
    assert not pod_topology(TPUSettings(), 1).known
    assert not pod_topology(TPUSettings(topology="3x3"), 8).known  # mismatch
    assert not pod_topology(TPUSettings(topology="banana"), 8).known


# ----------------------------------------------------------------- policies


def test_spread_equal_weights_is_round_robin():
    ws = workers(3)
    plan = get_policy("spread").plan(PlacementContext(workers=ws), 7)
    assert [w.id for w in plan] == ["w0", "w1", "w2", "w0", "w1", "w2", "w0"]


def test_spread_latency_weighting_shifts_share():
    ws = workers(2)
    lat = {"w0": 0.010, "w1": 0.040}    # w1 is 4x slower than the median
    ctx = PlacementContext(workers=ws, latency_s=lambda wid: lat[wid])
    plan = get_policy("spread").plan(ctx, 12)
    share = [w.id for w in plan]
    assert share.count("w0") > share.count("w1")
    assert share.count("w1") >= 1       # weighted, never starved entirely


def test_open_and_half_open_workers_excluded():
    ws = workers(3)
    states = {"w0": BREAKER_OPEN, "w1": BREAKER_CLOSED,
              "w2": BREAKER_HALF_OPEN}
    ctx = PlacementContext(workers=ws,
                           breaker_state=lambda wid: states[wid])
    for policy in ("spread", "pack", "topology"):
        plan = get_policy(policy).plan(ctx, 6)
        assert {w.id for w in plan} == {"w1"}, policy
        assert get_policy(policy).pick(ctx).id == "w1"
    # pick never falls back to unhealthy workers
    states["w1"] = BREAKER_OPEN
    assert get_policy("spread").pick(ctx) is None


def test_plan_falls_back_when_whole_fleet_is_open():
    """A fully-dead fleet still places: the loops strand into failover
    and --orphan-grace bounds the run (the pre-placement stance)."""
    ws = workers(2)
    ctx = PlacementContext(workers=ws,
                           breaker_state=lambda wid: BREAKER_OPEN)
    assert len(get_policy("spread").plan(ctx, 4)) == 4
    with pytest.raises(ClawkerError):
        get_policy("spread").plan(PlacementContext(workers=[]), 1)


def test_topology_prefers_pod_local_groups():
    ws = workers(8)
    topo = pod_topology(TPUSettings(topology="2x4"), 8)
    ctx = PlacementContext(workers=ws, topology=topo)
    plan = get_policy("topology").plan(ctx, 4)
    groups = {topo.group_of(w.index) for w in plan}
    assert len(groups) == 1             # one ICI group covers the run
    # more slots than one group's fair share can hold: spill, capped
    plan8 = get_policy("topology").plan(ctx, 8)
    counts = {}
    for w in plan8:
        counts[w.id] = counts.get(w.id, 0) + 1
    assert max(counts.values()) <= 1    # ceil(8/8) fair-share cap holds


def test_topology_pick_prefers_ici_neighbors():
    ws = workers(8)
    topo = pod_topology(TPUSettings(topology="2x4"), 8)
    ctx = PlacementContext(workers=ws, topology=topo)
    target = get_policy("topology").pick(ctx, exclude={"w0"}, near=ws[0])
    assert target.id == "w1"            # same row, one hop
    # the whole near row unhealthy: jump rows rather than nothing
    states = {f"w{i}": (BREAKER_OPEN if i < 4 else BREAKER_CLOSED)
              for i in range(8)}
    ctx2 = PlacementContext(workers=ws, topology=topo,
                            breaker_state=lambda wid: states[wid])
    assert get_policy("topology").pick(
        ctx2, exclude={"w0"}, near=ws[0]).id == "w4"


def test_topology_unknown_falls_back_to_spread():
    ws = workers(3)
    ctx = PlacementContext(workers=ws, topology=None)
    plan = get_policy("topology").plan(ctx, 6)
    assert [w.id for w in plan] == ["w0", "w1", "w2"] * 2


def test_unknown_policy_raises():
    with pytest.raises(ClawkerError):
        get_policy("best-fit")


def test_placement_event_round_trip():
    ev = PlacementEvent("loop-1", "w2", "topology", "teamA",
                        "replaced", "from w0")
    assert PlacementEvent.parse("loop-1", ev.detail()) == ev
    bare = PlacementEvent("loop-1", "w2", "spread", "default", "placed")
    assert PlacementEvent.parse("loop-1", bare.detail()) == bare


# ---------------------------------------------------------------- admission


class _Recorder:
    """Collects dispatches; releases on demand."""

    def __init__(self):
        self.dispatched: list[str] = []
        self.releases: dict[str, list] = {}

    def runner(self, tag: str):
        def run(release):
            self.dispatched.append(tag)
            self.releases.setdefault(tag, []).append(release)
        return run

    def release(self, tag: str) -> None:
        self.releases[tag].pop(0)()


def test_token_bucket_caps_inflight_and_releases_dispatch_next():
    adm = AdmissionController(max_inflight_per_worker=2)
    rec = _Recorder()
    outcomes = [adm.submit("w0", "t", rec.runner(f"j{i}")) for i in range(5)]
    assert outcomes[:2] == [ADMISSION_DISPATCHED] * 2
    assert outcomes[2:] == [ADMISSION_QUEUED] * 3
    assert rec.dispatched == ["j0", "j1"]
    rec.release("j0")
    assert rec.dispatched == ["j0", "j1", "j2"]     # token handoff, FIFO
    st = adm.stats()["workers"]["w0"]
    assert st["inflight"] == 2 and st["inflight_hwm"] == 2
    assert st["pending"] == 2
    # double release of one token must not mint a second one
    rec.release("j1")
    rec.releases["j1"] = rec.releases["j0"]
    assert adm.stats()["workers"]["w0"]["inflight"] == 2


def test_bounded_queue_rejects_and_counts():
    adm = AdmissionController(max_inflight_per_worker=1,
                              max_pending_per_worker=2)
    rec = _Recorder()
    outcomes = [adm.submit("w0", "t", rec.runner(f"j{i}")) for i in range(4)]
    assert outcomes == [ADMISSION_DISPATCHED, ADMISSION_QUEUED,
                        ADMISSION_QUEUED, ADMISSION_REJECTED]
    st = adm.stats()
    assert st["workers"]["w0"]["rejected"] == 1
    assert st["tenants"]["t"]["rejected"] == 1


def test_wfq_interleaves_equal_tenants():
    adm = AdmissionController(max_inflight_per_worker=1)
    adm.register_tenant("a", weight=1.0)
    adm.register_tenant("b", weight=1.0)
    rec = _Recorder()
    adm.submit("w0", "a", rec.runner("hold"))       # occupy the token
    for i in range(3):
        adm.submit("w0", "a", rec.runner(f"a{i}"))
    for i in range(3):
        adm.submit("w0", "b", rec.runner(f"b{i}"))
    order = []
    for _ in range(6):
        rec.release(rec.dispatched[-1] if rec.dispatched[-1] != "hold"
                    else "hold")
        order.append(rec.dispatched[-1])
    # tenant b enqueued AFTER a's burst, yet interleaves 1:1 instead of
    # waiting behind it -- the whole point of the fair queue
    assert order == ["a0", "b0", "a1", "b1", "a2", "b2"]


def test_wfq_weight_ratio_biases_order():
    adm = AdmissionController(max_inflight_per_worker=1)
    adm.register_tenant("heavy", weight=2.0)
    adm.register_tenant("light", weight=1.0)
    rec = _Recorder()
    adm.submit("w0", "light", rec.runner("hold"))
    for i in range(4):
        adm.submit("w0", "heavy", rec.runner(f"h{i}"))
    for i in range(2):
        adm.submit("w0", "light", rec.runner(f"l{i}"))
    last = "hold"
    order = []
    for _ in range(6):
        rec.release(last)
        last = rec.dispatched[-1]
        order.append(last)
    # 2:1 weights -> heavy drains two slots per light slot
    assert order == ["h0", "h1", "l0", "h2", "h3", "l1"]


def test_tenant_max_inflight_cap_spans_workers():
    adm = AdmissionController(max_inflight_per_worker=4)
    adm.register_tenant("capped", weight=1.0, max_inflight=2)
    rec = _Recorder()
    outcomes = [adm.submit(f"w{i}", "capped", rec.runner(f"j{i}"))
                for i in range(4)]
    assert outcomes.count(ADMISSION_DISPATCHED) == 2
    assert outcomes.count(ADMISSION_QUEUED) == 2
    rec.release(rec.dispatched[0])
    assert len(rec.dispatched) == 3     # cap slot freed -> next dispatch


def test_cancelled_tickets_melt_without_consuming_tokens():
    adm = AdmissionController(max_inflight_per_worker=1)
    rec = _Recorder()
    cancelled = {"flag": False}
    settled = []
    adm.submit("w0", "t", rec.runner("hold"))
    adm.submit("w0", "t", rec.runner("stale"),
               cancelled=lambda: cancelled["flag"],
               on_cancel=lambda: settled.append("stale"))
    adm.submit("w0", "t", rec.runner("live"))
    cancelled["flag"] = True
    rec.release("hold")
    assert rec.dispatched == ["hold", "live"]       # stale melted
    assert settled == ["stale"]
    assert adm.stats()["tenants"]["t"]["cancelled"] == 1


def test_reset_worker_returns_tenant_slots_and_voids_stale_releases():
    adm = AdmissionController(max_inflight_per_worker=2)
    adm.register_tenant("t", weight=1.0, max_inflight=2)
    rec = _Recorder()
    adm.submit("w0", "t", rec.runner("dead0"))
    adm.submit("w0", "t", rec.runner("dead1"))
    # tenant capped: a submission on a healthy worker queues
    assert adm.submit("w1", "t", rec.runner("j")) == ADMISSION_QUEUED
    adm.reset_worker("w0")
    # the reset returned the tenant's slots: the queued launch dispatches
    assert rec.dispatched[-1] == "j"
    assert adm.stats()["workers"]["w0"]["inflight"] == 0
    # a stale release from the pre-reset epoch must not go negative or
    # free anything extra
    rec.release("dead0")
    st = adm.stats()
    assert st["workers"]["w0"]["inflight"] == 0
    assert st["tenants"]["t"]["inflight"] == 1      # just the live launch


# ---------------------------------------------------- scheduler integration


def test_64_loop_burst_respects_admission_caps(env):
    """(a) of the ISSUE-6 test satellite: a 64-loop burst on the
    4-worker fake pod never exceeds any worker's admission cap -- by
    the controller's own high-water mark AND by the fake daemon's
    observed call concurrency -- and still completes to budget."""
    tenv, proj, cfg = env
    drv = FakeDriver(n_workers=4)
    seed(drv, exit_behavior(b"", 0, delay=0.02))
    cap = 4
    sched = LoopScheduler(
        cfg, drv, LoopSpec(parallel=64, iterations=1,
                           max_inflight_per_worker=cap))
    sched.start()
    loops = sched.run(poll_s=0.05)
    stats = sched.admission.stats()
    sched.cleanup(remove_containers=True)
    assert all(l.status == "done" for l in loops)
    assert len(loops) == 64
    for wid, w in stats["workers"].items():
        assert w["inflight_hwm"] <= cap, (wid, w)
        assert w["inflight"] == 0
    # the burst genuinely saturated the buckets (a cap that never binds
    # would make this test vacuous)
    assert any(w["inflight_hwm"] == cap for w in stats["workers"].values())
    assert stats["tenants"]["default"]["dispatched"] >= 64
    # daemon-side: no worker ever saw more concurrent create/start work
    # than its admission cap
    for gate in drv.gates:
        assert gate.launch_hwm <= cap


def test_two_tenants_share_pod_without_starvation(env):
    """(b): two runs (1:1 weights) through ONE shared admission
    controller; the second tenant's burst lands after the first has
    queued everything, yet its launches interleave instead of waiting
    behind the whole first run."""
    tenv, proj, cfg = env
    drv = FakeDriver(n_workers=4)
    seed(drv, exit_behavior(b"", 0, delay=0.02))
    adm = AdmissionController(max_inflight_per_worker=1)
    created: list[tuple[str, str]] = []
    lock = threading.Lock()

    def on_event(agent, event, detail=""):
        if event == "created":
            with lock:
                created.append((agent.split("-")[0], agent))

    scheds = [
        LoopScheduler(
            cfg, drv,
            LoopSpec(parallel=16, iterations=1, tenant=t, agent_prefix=t),
            admission=adm, on_event=on_event)
        for t in ("teama", "teamb")
    ]
    scheds[0].start()                   # tenant A queues its whole burst
    scheds[1].start()                   # THEN tenant B arrives
    threads = [threading.Thread(target=s.run, kwargs={"poll_s": 0.05})
               for s in scheds]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    for s in scheds:
        assert all(l.status == "done" for l in s.loops), s.spec.tenant
        s.events.flush()
    stats = adm.stats()
    for s in scheds:
        s.cleanup(remove_containers=True)
    assert stats["tenants"]["teama"]["dispatched"] == 16
    assert stats["tenants"]["teamb"]["dispatched"] == 16
    # neither tenant starved: inside the first half of all creations,
    # both tenants are well represented (a starved tenant would be
    # absent until the other's burst drained)
    with lock:
        first_half = [t for t, _ in created[:len(created) // 2]]
    assert first_half.count("teama") >= 4
    assert first_half.count("teamb") >= 4


def test_open_breaker_worker_receives_zero_placements(env):
    """(c): a worker quarantined BEFORE the run starts gets no initial
    slots, no migrations, and no admission dispatches -- while the rest
    of the pod absorbs its share and completes."""
    tenv, proj, cfg = env
    drv = FakeDriver(n_workers=4)
    seed(drv)
    drv.inject_fault(1, "refuse")       # the daemon really is dead
    decisions: list[PlacementEvent] = []

    def on_event(agent, event, detail=""):
        if event == PLACEMENT_DECISION:
            decisions.append(PlacementEvent.parse(agent, detail))

    sched = LoopScheduler(cfg, drv,
                          LoopSpec(parallel=16, iterations=2),
                          on_event=on_event)
    dead = drv.workers()[1].id
    sched._ensure_health().breakers[dead].trip("pre-run quarantine")
    sched.start()
    loops = sched.run(poll_s=0.05)
    stats = sched.admission.stats()
    sched.events.flush()
    journal = RunJournal.read(journal_path(cfg.logs_dir, sched.loop_id))
    sched.cleanup(remove_containers=True)
    assert all(l.status == "done" for l in loops)
    assert all(l.worker.id != dead for l in loops)
    assert not any(d.worker == dead for d in decisions)
    assert stats["workers"].get(dead, {}).get("dispatched", 0) == 0
    assert not any(r.get("worker") == dead for r in journal
                   if r.get("kind") in (REC_PLACEMENT, REC_CREATED))
    # and the dead worker's daemon saw zero create/start attempts
    assert drv.gates[1].launch_hwm == 0


def test_resume_restores_pending_queue_order(env):
    """(d): kill a scheduler while launches still sit in the admission
    queue; --resume re-enqueues them in the journaled queue order, so
    the second generation creates them in exactly that order."""
    tenv, proj, cfg = env
    drv = FakeDriver(n_workers=1)

    class SlowCreate(FakeDockerAPI):
        def container_create(self, name, config):
            time.sleep(0.15)
            return super().container_create(name, config)

    from clawker_tpu.engine.drivers.fakedriver import _FaultGate

    api = SlowCreate()
    drv.apis[0] = api
    drv.gates[0] = _FaultGate(api)
    drv._workers[0].engine = Engine(drv.gates[0])
    seed(drv, exit_behavior(b"", 0, delay=0.05))

    spec = LoopSpec(parallel=6, iterations=1, placement="pack",
                    max_inflight_per_worker=1)
    sched = LoopScheduler(cfg, drv, spec)
    sched.start()
    runner = threading.Thread(target=sched.run, kwargs={"poll_s": 0.05},
                              daemon=True)
    runner.start()
    jpath = journal_path(cfg.logs_dir, sched.loop_id)
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        recs = RunJournal.read(jpath)
        if sum(1 for r in recs if r.get("kind") == REC_CREATED) >= 2:
            break
        time.sleep(0.02)
    sched.kill()
    runner.join(20.0)
    # the dead generation's lane thread may still be inside a slow
    # create (a real SIGKILL would have taken it down too): wait for
    # the journal to go quiet so the replay sees a settled tail
    prev = -1
    for _ in range(50):
        n = len(RunJournal.read(jpath))
        if n == prev:
            break
        prev = n
        time.sleep(0.2)
    image = replay(RunJournal.read(jpath))
    pending = list(image.queued_order)
    assert len(pending) >= 2, "kill point left no queued launches"

    # a lane thread mid-create at the kill writes no journal record (a
    # SIGKILLed process journals nothing) but its daemon-side create may
    # still have landed: reconcile must FINISH that launch from the
    # discovered container, not create it a second time, so it drops out
    # of the resumed generation's create order
    from clawker_tpu.runtime.names import container_name
    already = {a for a in pending
               if any(c.name == container_name("loopproj", a)
                      for c in api.containers.values())}

    resumed = LoopScheduler.resume(cfg, drv, image)
    resumed.reconcile()
    loops = resumed.run(poll_s=0.05)
    resumed.cleanup(remove_containers=True)
    assert all(l.status == "done" for l in loops)
    gen2 = RunJournal.read(jpath)
    resume_at = max(i for i, r in enumerate(gen2)
                    if r.get("kind") == "resume")
    created_after = [r["agent"] for r in gen2[resume_at:]
                     if r.get("kind") == REC_CREATED
                     and r.get("agent") in pending]
    assert created_after == [a for a in pending if a not in already]


def test_admission_rejection_strands_then_replaces(env):
    """Backpressure overflow: a queue-full rejection re-routes through
    the rescue pass (no breaker penalty) and the run still completes."""
    tenv, proj, cfg = env
    drv = FakeDriver(n_workers=1)
    seed(drv, exit_behavior(b"", 0, delay=0.02))
    adm = AdmissionController(max_inflight_per_worker=1,
                              max_pending_per_worker=1)
    sched = LoopScheduler(
        cfg, drv, LoopSpec(parallel=4, iterations=1, placement="pack"),
        admission=adm)
    sched.start()
    loops = sched.run(poll_s=0.1)
    stats = adm.stats()
    health_state = sched.health.state(drv.workers()[0].id)
    sched.cleanup(remove_containers=True)
    assert all(l.status == "done" for l in loops)
    assert stats["workers"]["fake-0"]["rejected"] >= 1
    assert health_state == BREAKER_CLOSED   # backpressure never penalized


def test_journal_replay_builds_queue_order():
    recs = [
        {"kind": "run", "run": "r1", "spec": {"parallel": 3}},
        {"kind": REC_ADMIT_QUEUED, "agent": "a0", "worker": "w0",
         "tenant": "t"},
        {"kind": REC_ADMIT_QUEUED, "agent": "a1", "worker": "w0",
         "tenant": "t"},
        {"kind": REC_ADMIT_QUEUED, "agent": "a2", "worker": "w0",
         "tenant": "t"},
        {"kind": REC_CREATED, "agent": "a0", "worker": "w0", "cid": "c0"},
        # a1 re-queued (re-placement): moves to the back
        {"kind": REC_ADMIT_QUEUED, "agent": "a1", "worker": "w0",
         "tenant": "t"},
    ]
    image = replay(recs)
    assert image.queued_order == ["a2", "a1"]


# ----------------------------------------------------------------- CLI


def test_cli_fleet_placement_view(env):
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    drv = FakeDriver(n_workers=4)
    res = CliRunner().invoke(
        cli, ["fleet", "placement", "--slots", "8", "--format", "json"],
        obj=Factory(cwd=proj, driver=drv), catch_exceptions=False)
    assert res.exit_code == 0, res.output
    import json as _json
    doc = _json.loads(res.output)
    assert doc["policy"] == "spread" and doc["slots"] == 8
    assert len(doc["workers"]) == 4
    assert sum(w["planned_slots"] for w in doc["workers"]) == 8
    assert doc["admission"]["max_inflight_per_worker"] == 4
    # a dead worker renders non-closed and flips the exit status -- in
    # BOTH formats, and even with a single probe round (the breaker
    # threshold clamps to the rounds requested, like fleet health)
    for extra in ([], ["--format", "json"]):
        drv2 = FakeDriver(n_workers=2)
        drv2.inject_fault(1, "refuse")
        res = CliRunner().invoke(
            cli, ["fleet", "placement", "--probes", "1", *extra],
            obj=Factory(cwd=proj, driver=drv2))
        assert res.exit_code == 1, extra


def test_cli_loop_placement_flags(env):
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    drv = FakeDriver(n_workers=2)
    seed(drv)
    res = CliRunner().invoke(
        cli, ["loop", "-p", "4", "-n", "1", "--placement", "topology",
              "--tenant", "teamx", "--max-inflight-per-worker", "2",
              "--json"],
        obj=Factory(cwd=proj, driver=drv), catch_exceptions=False)
    assert res.exit_code == 0, res.output
    import json as _json
    doc = _json.loads(res.stdout)
    assert len(doc["agents"]) == 4
    assert all(a["status"] == "done" for a in doc["agents"])


def test_topology_cap_holds_under_latency_skew():
    """A fast worker among slow row-mates gets the ORDER bias, never
    more than its fair-share cap of the slots (review regression)."""
    ws = workers(8)
    topo = pod_topology(TPUSettings(topology="2x4"), 8)
    lat = {f"w{i}": (0.005 if i == 0 else 0.050) for i in range(8)}
    ctx = PlacementContext(workers=ws, topology=topo,
                           latency_s=lambda wid: lat[wid])
    plan = get_policy("topology").plan(ctx, 8)
    counts = {}
    for w in plan:
        counts[w.id] = counts.get(w.id, 0) + 1
    assert max(counts.values()) <= 1    # ceil(8/8): weight biases order,
    assert len(plan) == 8               # the cap stays a cap


def test_sweep_melts_cancelled_tickets_on_a_full_gate():
    """A stopped run's queued tickets settle even when every token is
    held by a wedged launch that will never release (review
    regression: the melt must not hide behind the capacity check)."""
    adm = AdmissionController(max_inflight_per_worker=1,
                              max_pending_per_worker=4)
    rec = _Recorder()
    adm.submit("w0", "t", rec.runner("wedged"))     # token never released
    stop = {"flag": False}
    settled = []
    adm.submit("w0", "t", rec.runner("queued"),
               cancelled=lambda: stop["flag"],
               on_cancel=lambda: settled.append("queued"))
    stop["flag"] = True
    adm.sweep()
    assert settled == ["queued"]
    st = adm.stats()["workers"]["w0"]
    assert st["pending"] == 0 and st["inflight"] == 1


def test_release_epoch_stamped_at_dispatch_not_at_run():
    """A reset_worker landing between dispatch accounting and the
    release closure's creation must not hand the stranded launch the
    NEW epoch (review regression: the epoch is stamped inside the
    pump's lock hold, not re-read when the dispatch runs)."""
    class RacingController(AdmissionController):
        race_once = True

        def _run_dispatches(self, dispatches):
            if dispatches and self.race_once:
                self.race_once = False
                self.reset_worker(dispatches[0].worker_id)
            super()._run_dispatches(dispatches)

    adm = RacingController(max_inflight_per_worker=1)
    rec = _Recorder()
    adm.submit("w0", "t", rec.runner("stranded"))
    # post-reset: a fresh launch holds the new epoch's only token
    adm.submit("w0", "t", rec.runner("live"))
    assert adm.stats()["workers"]["w0"]["inflight"] == 1
    # the stranded pre-reset launch finally settles: its release must
    # no-op, not free the live launch's token
    rec.release("stranded")
    assert adm.stats()["workers"]["w0"]["inflight"] == 1


def test_spread_weight_ceiling_under_extreme_skew():
    """One 2ms worker among 200ms peers gets a bigger share, not the
    whole plan: the weight ceiling keeps spread from collapsing into
    pack under latency skew (review regression)."""
    ws = workers(4)
    lat = {"w0": 0.002, "w1": 0.2, "w2": 0.2, "w3": 0.2}
    ctx = PlacementContext(workers=ws, latency_s=lambda wid: lat[wid])
    plan = get_policy("spread").plan(ctx, 64)
    counts = {}
    for w in plan:
        counts[w.id] = counts.get(w.id, 0) + 1
    # weight(w0) caps at 10 vs 1.0 each: ~10/13 of the slots at most,
    # and every slow worker still receives a meaningful share
    assert counts["w0"] <= 52
    assert all(counts.get(f"w{i}", 0) >= 3 for i in (1, 2, 3))


def test_rejection_churn_bounded_by_orphan_grace(env):
    """A queue that never drains cannot spin the run forever: rejection
    strands skip the strand ceiling (flow control, no breaker penalty),
    so --orphan-grace must bound the orphan -> re-place -> reject cycle
    (review regression: every re-placement used to restart the grace
    clock, making the cycle unbounded)."""
    tenv, proj, cfg = env
    drv = FakeDriver(n_workers=1)
    seed(drv)

    class AlwaysFull(AdmissionController):
        def submit(self, worker_id, tenant, run, *, cancelled=None,
                   on_cancel=None):
            return ADMISSION_REJECTED

    sched = LoopScheduler(
        cfg, drv,
        LoopSpec(parallel=1, iterations=1, placement="pack",
                 orphan_grace_s=0.6),
        admission=AlwaysFull())
    sched.start()
    t0 = time.monotonic()
    loops = sched.run(poll_s=0.05)
    wall = time.monotonic() - t0
    sched.cleanup(remove_containers=True)
    assert all(l.status == "failed" for l in loops)
    assert wall < 10.0


def test_topology_shape_ignores_resume_stand_ins(env):
    """The pod grid derives from the REAL fleet: engine-less stand-ins
    for journaled-but-absent workers must not inflate the inference
    (review regression: 4 workers + 1 stand-in read as a 1x5 ring,
    collapsing every ICI group and handing the dead worker a live
    coordinate)."""
    tenv, proj, cfg = env
    drv = FakeDriver(n_workers=4)
    seed(drv)
    sched = LoopScheduler(cfg, drv, LoopSpec(parallel=1, iterations=1))
    sched._extra_workers.append(Worker(id="gone", index=4, engine=None))
    topo = sched._placement_ctx().topology
    assert topo.known and (topo.rows, topo.cols) == (2, 2)
    # the stand-in sits OUTSIDE the grid: a singleton group of its own
    assert topo.group_of(4) not in {topo.group_of(i) for i in range(4)}
