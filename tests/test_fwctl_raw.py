"""fwctl-raw against the REAL kernel: pins, attach, enforce, drain.

The raw-syscall native control tool (native/ebpf/fwctl_raw.c) is built
with plain cc and driven against programs the in-process lane pinned
into bpffs (FwKernel.pin_all): a cross-process, cross-language loop --
Python assembles + verifier-loads + pins, the C binary attaches by pin
path, a probe child observes kernel EPERM, and the C binary drains the
ringbuf into the exact JSON dialect PinnedMaps.drain_events parses.

Skip-gated on bpf(2) + bpffs + a compiler; where it runs, nothing is
mocked (the fwctl mock suite remains the everywhere-tier).
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from pathlib import Path

import pytest

from clawker_tpu.firewall import bpfkern

EBPF_DIR = Path(__file__).resolve().parent.parent / "native" / "ebpf"
BPFFS = Path("/sys/fs/bpf")


def _capable() -> bool:
    return (bpfkern.kernel_available() and BPFFS.is_dir()
            and os.access(BPFFS, os.W_OK))


pytestmark = pytest.mark.skipif(
    not _capable(), reason="needs bpf(2) + writable bpffs")


@pytest.fixture(scope="module")
def binary():
    res = subprocess.run(["make", "-C", str(EBPF_DIR), "fwctl-raw"],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    return str(EBPF_DIR / "build" / "fwctl-raw")


@pytest.fixture()
def pinned():
    """FwKernel pinned into a scratch bpffs dir (+cleanup)."""
    from clawker_tpu.firewall.fwprogs import FwKernel

    pin = BPFFS / f"clawker-test-{os.getpid()}"
    kern = FwKernel()
    kern.pin_all(str(pin))
    yield kern, pin
    for f in list(pin.iterdir()):
        f.unlink()
    pin.rmdir()
    kern.close()


def test_attach_enforce_events_via_native_tool(binary, pinned):
    from clawker_tpu.firewall.bpflive import LiveSandbox, probe_tcp_connect
    from clawker_tpu.firewall.fwprogs import LiveMaps
    from clawker_tpu.firewall.model import ContainerPolicy, FLAG_ENFORCE

    kern, pin = pinned
    maps = LiveMaps(kern)
    # scratch cgroup WITHOUT python-side attach: the C binary does it
    sb = LiveSandbox.__new__(LiveSandbox)
    root = bpfkern.cgroup2_root()
    sb.cg_dir = root / f"fwctlraw-{os.getpid()}"
    sb.cg_dir.mkdir(exist_ok=True)
    sb.kern = None
    sb.maps = None
    try:
        res = subprocess.run(
            [binary, "attach", "--cgroup", str(sb.cg_dir),
             "--pin-dir", str(pin)], capture_output=True, text=True)
        assert res.returncode == 0, res.stderr
        assert json.loads(res.stdout)["programs"] == 9

        cg_id = os.stat(sb.cg_dir).st_ino
        maps.enroll(cg_id, ContainerPolicy(
            envoy_ip="127.0.0.1", dns_ip="127.0.0.1", flags=FLAG_ENFORCE))

        out = sb.run_in_cgroup(probe_tcp_connect, "10.99.0.9", 443, 1.0)
        assert out["result"] == "eperm", out

        # native status sees the enrollment
        res = subprocess.run([binary, "status", "--pin-dir", str(pin)],
                             capture_output=True, text=True)
        st = json.loads(res.stdout)
        assert any(e["cgroup"] == cg_id for e in st["enrolled"]), st

        # native events drain: the dialect PinnedMaps parses
        res = subprocess.run([binary, "events", "--max", "64",
                              "--pin-dir", str(pin)],
                             capture_output=True, text=True)
        assert res.returncode == 0, res.stderr
        evs = [json.loads(l) for l in res.stdout.splitlines()]
        deny = [e for e in evs if e["cgroup"] == cg_id]
        assert deny and deny[0]["dst_ip"] == "10.99.0.9"
        assert deny[0]["dst_port"] == 443 and deny[0]["verdict"] == 1

        # native detach restores egress
        res = subprocess.run(
            [binary, "detach", "--cgroup", str(sb.cg_dir),
             "--pin-dir", str(pin)], capture_output=True, text=True)
        assert res.returncode == 0, res.stderr
        out = sb.run_in_cgroup(probe_tcp_connect, "10.99.0.9", 443, 0.4)
        assert out["result"] != "eperm", out
    finally:
        maps.close()
        try:
            sb.cg_dir.rmdir()
        except OSError:
            pass


def test_pinnedmaps_drain_events_via_native_tool(binary, pinned):
    """The PRODUCT event lane: PinnedMaps opens the pins and shells to
    the native tool for the ringbuf drain -- fully real end to end."""
    from clawker_tpu.firewall.bpflive import LiveSandbox, probe_raw_socket
    from clawker_tpu.firewall.bpfsys import PinnedMaps
    from clawker_tpu.firewall.model import ContainerPolicy, FLAG_ENFORCE, Reason

    kern, pin = pinned
    pm = PinnedMaps(pin, fwctl=binary)
    sb = LiveSandbox.__new__(LiveSandbox)
    root = bpfkern.cgroup2_root()
    sb.cg_dir = root / f"fwctlraw-pm-{os.getpid()}"
    sb.cg_dir.mkdir(exist_ok=True)
    try:
        cg_id = kern.attach_cgroup(str(sb.cg_dir))
        # enrollment THROUGH the pins: both views are the same kernel maps
        pm.enroll(cg_id, ContainerPolicy(
            envoy_ip="127.0.0.1", dns_ip="127.0.0.1", flags=FLAG_ENFORCE))
        assert sb.run_in_cgroup(probe_raw_socket)["result"] == "eperm"
        time.sleep(0.1)
        evs = pm.drain_events(128)
        assert any(e.reason is Reason.RAW_SOCKET for e in evs), evs
        pm.unenroll(cg_id)
        kern.detach_cgroup(str(sb.cg_dir))
    finally:
        pm.close()
        try:
            sb.cg_dir.rmdir()
        except OSError:
            pass