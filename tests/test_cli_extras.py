"""CLI surface suite: network, settings, auth, alias, version,
harness/stack listing, docs generation.

Parity bar: the reference's command-group inventory (SURVEY.md 2.4 --
network Docker-parity, settings, auth rotate, alias, version) and
cmd/gen-docs; worktree verbs are covered in test_cli.py.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest
from click.testing import CliRunner

from clawker_tpu import consts
from clawker_tpu.cli.factory import Factory
from clawker_tpu.cli.root import cli
from clawker_tpu.engine.drivers import FakeDriver
from clawker_tpu.testenv import TestEnv


@pytest.fixture
def env():
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: extras\n")
        yield tenv, proj


def invoke(proj, *args, driver=None, input=None):
    return CliRunner().invoke(
        cli, list(args), obj=Factory(cwd=proj, driver=driver or FakeDriver()),
        catch_exceptions=False, input=input,
    )


# ------------------------------------------------------------------ network

def test_network_verbs(env):
    tenv, proj = env
    drv = FakeDriver()
    res = invoke(proj, "network", "ensure", driver=drv)
    assert res.exit_code == 0 and consts.NETWORK_NAME in res.stdout
    res = invoke(proj, "network", "ls", driver=drv)
    assert consts.NETWORK_NAME in res.stdout
    res = invoke(proj, "network", "inspect", consts.NETWORK_NAME, driver=drv)
    assert json.loads(res.stdout)["Name"] == consts.NETWORK_NAME
    res = invoke(proj, "network", "rm", consts.NETWORK_NAME, driver=drv)
    assert res.exit_code == 0
    assert consts.NETWORK_NAME not in invoke(proj, "network", "ls", driver=drv).stdout


# ----------------------------------------------------------------- settings

def test_settings_get_set_list(env):
    tenv, proj = env
    res = invoke(proj, "settings", "get", "firewall.enable")
    assert res.stdout.strip() == "false"
    res = invoke(proj, "settings", "set", "firewall.enable", "true")
    assert res.exit_code == 0
    assert invoke(proj, "settings", "get", "firewall.enable").stdout.strip() == "true"
    assert "firewall" in invoke(proj, "settings", "list").stdout
    res = invoke(proj, "settings", "get", "no.such.key")
    assert res.exit_code != 0
    # non-leaf get answers the whole subtree as JSON
    res = invoke(proj, "settings", "get", "monitoring")
    assert res.exit_code == 0 and "opensearch_port" in json.dumps(json.loads(res.stdout))
    # value-type guard: a truthy string must never flip a boolean
    res = CliRunner().invoke(cli, ["settings", "set", "firewall.enable", "no"],
                             obj=Factory(cwd=proj, driver=FakeDriver()))
    assert res.exit_code != 0 and "boolean" in res.output
    res = CliRunner().invoke(cli, ["settings", "set", "host_proxy.port", "abc"],
                             obj=Factory(cwd=proj, driver=FakeDriver()))
    assert res.exit_code != 0


# --------------------------------------------------------------------- auth

def test_auth_status_and_rotate(env):
    tenv, proj = env
    assert "not initialized" in invoke(proj, "auth", "status").stdout
    from clawker_tpu.firewall import pki

    cfg = Factory(cwd=proj).config
    ca1 = pki.ensure_ca(cfg.pki_dir)
    assert "CA:" in invoke(proj, "auth", "status").stdout
    res = invoke(proj, "auth", "rotate", input="y\n")
    assert res.exit_code == 0
    ca2 = pki.ensure_ca(cfg.pki_dir)
    assert ca1.cert_pem != ca2.cert_pem


# ------------------------------------------------------------ alias/version

def test_version_cmd(env):
    tenv, proj = env
    from clawker_tpu import __version__

    out = invoke(proj, "version").stdout
    assert consts.PRODUCT in out and __version__ in out


def test_alias_set_expand_dispatch(env):
    tenv, proj = env
    res = invoke(proj, "alias", "set", "st", "settings list")
    assert res.exit_code == 0
    assert "st\tsettings list" in invoke(proj, "alias", "ls").stdout
    # the alias dispatches through the rewritten argv
    res = invoke(proj, "st")
    assert res.exit_code == 0
    res = invoke(proj, "alias", "rm", "st")
    assert res.exit_code == 0
    res = CliRunner().invoke(cli, ["st"], obj=Factory(cwd=proj, driver=FakeDriver()))
    assert res.exit_code != 0  # gone


def test_alias_with_flags_and_args(env):
    """argv-level expansion: flags inside expansions work (docker-style)."""
    tenv, proj = env
    invoke(proj, "alias", "set", "fg", "settings get")
    res = invoke(proj, "fg", "firewall.enable")   # alias + trailing arg
    assert res.exit_code == 0 and res.stdout.strip() == "false"
    invoke(proj, "alias", "set", "sl", "settings list")
    assert invoke(proj, "sl").exit_code == 0


def test_corrupt_aliases_file_never_crashes_dispatch(env):
    tenv, proj = env
    from clawker_tpu.util import xdg

    (xdg.config_dir() / "aliases.yaml").write_text("- just\n- a list\n")
    res = CliRunner().invoke(cli, ["definitely-not-a-command"],
                             obj=Factory(cwd=proj, driver=FakeDriver()))
    assert res.exit_code == 2 and "No such command" in res.output
    (xdg.config_dir() / "aliases.yaml").write_text("st: [settings, list]\n")
    res = CliRunner().invoke(cli, ["st"], obj=Factory(cwd=proj, driver=FakeDriver()))
    assert res.exit_code == 2  # non-string expansion ignored, clean error


# ------------------------------------------------------- harness/stack/docs

def test_harness_and_stack_ls(env):
    tenv, proj = env
    out = invoke(proj, "harness", "ls").stdout
    assert "claude" in out and "codex" in out
    out = invoke(proj, "stack", "ls").stdout
    for s in ("python", "go", "node", "rust"):
        assert s in out


def test_gen_docs(env, tmp_path):
    tenv, proj = env
    out_dir = tmp_path / "ref"
    res = invoke(proj, "gen-docs", "--out", str(out_dir))
    assert res.exit_code == 0, res.output
    pages = {p.name for p in out_dir.iterdir()}
    assert "clawker.md" in pages and "README.md" in pages
    assert "clawker_firewall.md" in pages
    assert "clawker_loop.md" in pages
    assert "clawker_worktree_add.md" in pages
    body = (out_dir / "clawker_loop.md").read_text()
    assert "--parallel" in body and "# clawker loop" in body
    # hidden commands stay out of the reference
    assert "clawker_gen-docs.md" not in pages
