"""Federation suite (ISSUE 17): the multi-pod front-tier router.

The acceptance shape: capacity leases amortize router->pod admission
RPCs (acquire/renew/expiry under partition, the >=5x evidence the
bench gates); the pod tier of two-level placement orders pods by
locality/load/health; global WFQ interleaves two tenants' batches
across two fake pods; killing a pod mid-run migrates its run onto a
survivor via journal adoption with ZERO duplicate creates
(cross_pod_exactly_once green); `clawker fed status` renders every
pod; and discover_all stays byte-identical to discover() on a
single-pod deployment.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from clawker_tpu import consts
from clawker_tpu.config import load_config
from clawker_tpu.engine.drivers import FakeDriver, Worker
from clawker_tpu.engine.fake import exit_behavior
from clawker_tpu.errors import ClawkerError
from clawker_tpu.federation import FederationRouter, LeaseManager, PodRegistry
from clawker_tpu.fleet.inventory import federation_topology
from clawker_tpu.health import BREAKER_CLOSED, BREAKER_OPEN
from clawker_tpu.loopd import LoopdError, socket_path
from clawker_tpu.loopd.client import LoopdClient, discover, discover_all
from clawker_tpu.loopd.server import LoopdServer
from clawker_tpu.placement import PlacementContext, PodPolicy
from clawker_tpu.testenv import TestEnv

IMAGE = "clawker-fedproj:default"


@pytest.fixture
def env():
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: fedproj\n")
        cfg = load_config(proj)
        yield tenv, proj, cfg


def driver_with(n_workers: int, *, prefix: str = "fake", behavior=None):
    drv = FakeDriver(n_workers=n_workers, prefix=prefix)
    for api in drv.apis:
        api.add_image(IMAGE)
        api.set_behavior(IMAGE, behavior or exit_behavior(b"done\n", 0))
    return drv


def hold_behavior(hold: threading.Event):
    def run(io) -> int:
        if not hold.is_set():
            hold.wait(20.0)
        return 0

    return run


def wait_for(pred, timeout=10.0, interval=0.01) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def total_creates(drv) -> int:
    return sum(len(api.calls_named("container_create")) for api in drv.apis)


def pod_server(tenv, cfg, name: str, drv) -> LoopdServer:
    """One fake pod: a loopd on its own socket dir (the dir name IS the
    pod name -- the federation.name default) over a shared cfg, so all
    pods see ONE journal store, as cross-pod adoption requires."""
    sock = tenv.base / name / "loopd.sock"
    return LoopdServer(cfg, drv, sock_path=sock).start()


@pytest.fixture
def server(env):
    tenv, proj, cfg = env
    drv = driver_with(2)
    srv = LoopdServer(cfg, drv).start()
    yield cfg, drv, srv
    srv.stop()


@pytest.fixture
def two_pods(env):
    tenv, proj, cfg = env
    drivers: dict[str, FakeDriver] = {}
    servers: list[LoopdServer] = []
    for name in ("podA", "podB"):
        drv = driver_with(2, prefix=name)
        drivers[name] = drv
        servers.append(pod_server(tenv, cfg, name, drv))
    cfg.settings.federation.enable = True
    cfg.settings.federation.pods = [str(s.sock_path) for s in servers]
    yield cfg, drivers, servers
    for s in servers:
        try:
            s.stop()
        except Exception:  # noqa: BLE001 - a test may have killed it
            pass


# ----------------------------------------------------------------- leases


def test_lease_acquire_clamps_to_pool_and_reports_exhaustion(server):
    """The daemon grants at most its pool (live workers x per-worker
    cap x LEASE_POOL_FACTOR); an exhausted pool answers 0 tokens with a
    retry hint instead of blocking the control connection."""
    cfg, drv, srv = server
    client = LoopdClient(srv.sock_path)
    client.hello()
    pool = srv._lease_pool()
    doc = client.lease_acquire(tokens=10**6, ttl_s=5.0)
    assert doc["tokens"] == pool and doc["lease"]
    assert doc["pod"] == srv.pod_name()
    starved = client.lease_acquire(tokens=1, ttl_s=5.0)
    assert starved["tokens"] == 0 and starved["lease"] == ""
    assert starved["retry_after_s"] > 0
    # releasing returns the credits to the pool
    client.lease_release(doc["lease"])
    again = client.lease_acquire(tokens=1, ttl_s=5.0)
    assert again["tokens"] == 1
    stats = client.status()["leases"]
    assert stats["active"] == 1 and stats["pool"] == pool
    client.close()


def test_lease_renew_refreshes_and_expired_lease_must_reacquire(server):
    cfg, drv, srv = server
    client = LoopdClient(srv.sock_path)
    client.hello()
    doc = client.lease_acquire(tokens=2, ttl_s=0.3)
    assert doc["tokens"] == 2
    renewed = client.lease_renew(doc["lease"])
    assert renewed["tokens"] == 2           # fresh credit block
    time.sleep(0.6)                          # TTL lapses; the daemon sweeps
    with pytest.raises(LoopdError, match="unknown or expired"):
        client.lease_renew(doc["lease"])
    # the control connection survived the inline error: re-acquire works
    fresh = client.lease_acquire(tokens=2, ttl_s=0.3)
    assert fresh["tokens"] == 2 and fresh["lease"] != doc["lease"]
    client.close()


def test_lease_manager_amortizes_admission_rpcs(server):
    """The perf tentpole's unit twin: 40 launches on an amortized lease
    cost ~spends/tokens wire RPCs; the per-launch baseline pays one RPC
    per launch -- the >=5x gap the federation bench gates."""
    cfg, drv, srv = server
    client = LoopdClient(srv.sock_path)
    client.hello()
    am = LeaseManager(tokens=8, ttl_s=5.0)
    for _ in range(40):
        am.spend("p", client)
    assert am.spends == 40
    assert am.rpcs <= 40 // 5, am.rpcs      # 1 acquire + 4 renews
    base = LeaseManager(tokens=8, ttl_s=5.0, amortize=False)
    for _ in range(20):
        base.spend("p", client)
    assert base.rpcs == base.spends == 20
    # per-spend wire cost: amortized <= baseline / 5 (the bench gate)
    assert (am.rpcs / am.spends) * 5 <= base.rpcs / base.spends
    am.release_all({"p": client})
    client.close()


def test_lease_partition_costs_one_failed_rpc_then_reacquires(server):
    """A swept lease (daemon restart / partition past TTL) fails ONE
    renew; the manager drops state and re-acquires -- no stall, no
    crash on the spend path."""
    cfg, drv, srv = server
    client = LoopdClient(srv.sock_path)
    client.hello()
    mgr = LeaseManager(tokens=2, ttl_s=5.0)
    mgr.spend("p", client)
    first = mgr._leases["p"].lease_id
    # the pod forgets the lease mid-TTL (restart during a partition)
    client.lease_release(first)
    mgr.spend("p", client)                  # spends the last local credit
    rpcs_before = mgr.rpcs
    mgr.spend("p", client)                  # renew fails -> re-acquire
    assert mgr._leases["p"].lease_id != first
    assert mgr.rpcs - rpcs_before == 2      # exactly: failed renew + acquire
    # full TTL expiry on BOTH sides: silent local drop, fresh acquire
    expired = LeaseManager(tokens=2, ttl_s=0.3)
    expired.spend("p", client)
    time.sleep(0.6)
    expired.spend("p", client)
    assert expired.rpcs == 2                # two acquires, zero failures
    client.close()


# --------------------------------------------------------------- pod tier


def _pod_ctx(n=4, shape="2x2", broken=(), loads=None):
    topo = federation_topology(shape, n)
    workers = [Worker(id=f"p{i}", index=i, hostname=f"p{i}",
                      engine=object()) for i in range(n)]
    states = {f"p{i}": (BREAKER_OPEN if i in broken else BREAKER_CLOSED)
              for i in range(n)}
    return PlacementContext(
        workers=workers,
        breaker_state=lambda wid: states[wid],
        latency_s=lambda wid: 0.0,
        load=dict(loads or {}),
        topology=topo if topo.known else None), workers


def test_pod_policy_prefers_dcn_adjacent_pods():
    """Two-level placement's pod tier: with a 2x2 pod grid, re-placing
    near p0 picks its row-mate p1 over the p2/p3 row -- the exact
    locality machinery of worker placement, one level up."""
    ctx, workers = _pod_ctx()
    pick = PodPolicy().pick(ctx, exclude={"p0"}, near=workers[0])
    assert pick is not None and pick.id == "p1"
    # row-mate unhealthy: the next-cheapest pod across the DCN boundary
    ctx2, workers2 = _pod_ctx(broken=(1,))
    pick2 = PodPolicy().pick(ctx2, exclude={"p0"}, near=workers2[0])
    assert pick2 is not None and pick2.id == "p2"


def test_pod_policy_plan_packs_a_pod_group():
    """A 2-slot plan lands inside ONE DCN-adjacent pod row instead of
    straddling the expensive boundary."""
    ctx, _ = _pod_ctx()
    planned = [w.id for w in PodPolicy().plan(ctx, 2)]
    assert set(planned) == {"p0", "p1"}
    # load breaks ties one level up too: an empty pod beats a loaded one
    ctx3, _ = _pod_ctx(shape="", loads={"p0": 5, "p1": 5, "p2": 0, "p3": 0})
    pick = PodPolicy().pick(ctx3)
    assert pick is not None and pick.id == "p2"


def test_registry_digests_status_and_marks_dead_pods(two_pods):
    cfg, drivers, servers = two_pods
    registry = PodRegistry(discover_all(cfg))
    try:
        assert registry.names() == ["podA", "podB"]
        registry.refresh()
        for pod in registry.pods.values():
            assert pod.alive and pod.healthy and pod.workers == 2
            assert pod.load == 0 and pod.runs == []
        servers[1].kill()
        registry.refresh()
        assert registry.get("podA").alive
        dead = registry.get("podB")
        assert not dead.alive and not dead.healthy
        assert [p.name for p in registry.alive_pods()] == ["podA"]
    finally:
        registry.close()


# --------------------------------------------------- router / global WFQ


def _bare_router() -> FederationRouter:
    """Router with WFQ state only -- the discipline needs no pods."""
    r = FederationRouter.__new__(FederationRouter)
    r._shares = {}
    r._vtime = 0.0
    return r


def test_router_wfq_interleaves_two_tenants():
    """Pure WFQ discipline: 4 alpha requests + 2 beta requests at equal
    weight dispatch interleaved (a,b,a,b,a,a) -- the burst tenant never
    buries the small one (serial would be aaaabb)."""
    reqs = ([("alpha", {"parallel": 1})] * 4
            + [("beta", {"parallel": 1})] * 2)
    assert _bare_router().dispatch_order(reqs) == [0, 4, 1, 5, 2, 3]
    # weight tips the interleave: a weight-2 tenant drains 2:1
    reqs2 = ([("heavy", {"parallel": 1, "tenant_weight": 2.0})] * 4
             + [("light", {"parallel": 1})] * 2)
    order2 = _bare_router().dispatch_order(reqs2)
    heavy_first_two = [i for i in order2[:3] if i < 4]
    assert len(heavy_first_two) == 2


def test_router_submits_across_pods_with_global_wfq(two_pods):
    cfg, drivers, servers = two_pods
    router = FederationRouter(cfg, discover_all(cfg))
    try:
        reqs = ([("alpha", {"parallel": 1, "iterations": 1,
                            "tenant": "alpha"})] * 4
                + [("beta", {"parallel": 1, "iterations": 1,
                             "tenant": "beta"})] * 2)
        results = router.submit_many(reqs)
        assert len(results) == 6
        by_pod: dict[str, int] = {}
        for pod, ack in results:
            assert ack["run"]
            by_pod[pod] = by_pod.get(pod, 0) + 1
        # load-balanced across BOTH pods (least-loaded pod tier)
        assert by_pod == {"podA": 3, "podB": 3}, by_pod
        # the hot path amortized: 6 submits cost at most one lease
        # acquire per pod, not one admission RPC per launch
        assert router.lease.rpcs <= 2, router.lease.stats()
        doc = router.status()
        assert doc["tenants"]["alpha"]["dispatched"] == 4
        assert doc["tenants"]["beta"]["dispatched"] == 2
        for srv in servers:
            assert wait_for(lambda: all(
                r.done.is_set() for r in srv.runs.values()))
    finally:
        router.close()


def test_router_shards_one_large_run_across_pods(two_pods):
    cfg, drivers, servers = two_pods
    router = FederationRouter(cfg, discover_all(cfg))
    try:
        shards = router.submit_sharded(
            {"parallel": 4, "iterations": 1, "tenant": "big"})
        assert sum(size for _, size, _ in shards) == 4
        assert {pod for pod, _, _ in shards} == {"podA", "podB"}
        for pod, size, ack in shards:
            assert len(ack["agents"]) == size
            assert router.placements()[ack["run"]] == pod
        for srv in servers:
            assert wait_for(lambda: all(
                r.done.is_set() for r in srv.runs.values()))
            assert all(r.result["ok"] for r in srv.runs.values())
    finally:
        router.close()


# -------------------------------------------------------------- migration


def test_pod_kill_migrates_runs_with_zero_duplicate_creates(env):
    """The tentpole's failure story: kill the pod hosting a live run;
    migrate_pod re-places it onto the survivor via journal adoption --
    the run keeps its id, finishes on the survivor, and the federation-
    wide exactly-once audit is green."""
    from clawker_tpu.chaos.invariants import cross_pod_exactly_once

    tenv, proj, cfg = env
    hold = threading.Event()
    drivers = {
        "podA": driver_with(2, prefix="podA",
                            behavior=hold_behavior(hold)),
        "podB": driver_with(2, prefix="podB",
                            behavior=hold_behavior(hold)),
    }
    srv_a = pod_server(tenv, cfg, "podA", drivers["podA"])
    srv_b = pod_server(tenv, cfg, "podB", drivers["podB"])
    cfg.settings.federation.enable = True
    cfg.settings.federation.pods = [str(srv_a.sock_path),
                                    str(srv_b.sock_path)]
    router = FederationRouter(cfg, discover_all(cfg))
    try:
        pod, ack = router.submit(
            {"parallel": 2, "iterations": 1, "tenant": "mig"})
        run_id = ack["run"]
        assert pod == "podA"            # both empty: index order wins
        # both loops genuinely executing on pod A before the kill
        assert wait_for(lambda: total_creates(drivers["podA"]) == 2)
        srv_a.kill()
        moved = router.migrate_pod("podA", orphan_grace_s=0.2)
        assert moved == [run_id]
        assert router.placements()[run_id] == "podB"
        hold.set()
        run = srv_b.runs[run_id]        # adopted under the SAME id
        assert run.done.wait(20.0)
        assert run.result["ok"], run.result
        # the dead pod never created again; the survivor created only
        # what the journal authorized -- exactly once, federation-wide
        assert total_creates(drivers["podA"]) == 2
        violations = cross_pod_exactly_once(drivers, cfg, run_id)
        assert violations == [], violations
        assert router.status()["placements"][run_id] == "podB"
    finally:
        router.close()
        srv_b.stop()


def test_migrate_unknown_pod_and_no_survivor(two_pods):
    cfg, drivers, servers = two_pods
    router = FederationRouter(cfg, discover_all(cfg))
    try:
        with pytest.raises(ClawkerError, match="unknown pod"):
            router.migrate_pod("podZ")
        # no healthy survivor: the drain reports zero moves, no crash
        router.registry.get("podB").alive = False
        assert router.migrate_pod("podA") == []
    finally:
        router.close()


# ------------------------------------------------------------ CLI surface


def test_fed_status_cli_table_and_json(env):
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    drv = driver_with(2)
    # no pod answering: non-zero (federation liveness probe contract)
    res = CliRunner().invoke(cli, ["fed", "status"],
                             obj=Factory(cwd=proj, driver=drv))
    assert res.exit_code == 1
    srv = LoopdServer(cfg, drv).start()
    try:
        res = CliRunner().invoke(cli, ["fed", "status"],
                                 obj=Factory(cwd=proj, driver=drv),
                                 catch_exceptions=False)
        assert res.exit_code == 0, res.output
        assert "POD" in res.output and srv.pod_name() in res.output
        res2 = CliRunner().invoke(
            cli, ["fed", "status", "--format", "json"],
            obj=Factory(cwd=proj, driver=drv), catch_exceptions=False)
        assert res2.exit_code == 0, res2.output
        doc = json.loads(res2.output[res2.output.index("{"):])
        (pod,) = doc["pods"]
        assert pod["alive"] and pod["healthy"] and pod["workers"] == 2
    finally:
        srv.stop()


def test_cli_loop_pods_rejects_in_process_modes(env, tmp_path):
    from click.testing import CliRunner

    from clawker_tpu.cli.factory import Factory
    from clawker_tpu.cli.root import cli

    tenv, proj, cfg = env
    drv = driver_with(1)
    res = CliRunner().invoke(
        cli, ["loop", "--pods", "--resume", "whatever"],
        obj=Factory(cwd=proj, driver=drv))
    assert res.exit_code != 0 and "--pods" in res.output
    plan = tmp_path / "plan.json"
    plan.write_text('{"seed": 1, "events": []}')
    res = CliRunner().invoke(
        cli, ["loop", "--pods", "--chaos-plan", str(plan)],
        obj=Factory(cwd=proj, driver=drv))
    assert res.exit_code != 0 and "--pods" in res.output


# ------------------------------------------------------- discover_all


def test_discover_all_single_pod_matches_discover(server):
    """The degrade regression: with no federation configured,
    discover_all is exactly [discover()] -- same socket, same daemon."""
    cfg, drv, srv = server
    single = discover(cfg)
    many = discover_all(cfg)
    assert single is not None and len(many) == 1
    assert many[0].path == single.path == socket_path(cfg)
    single.close()
    for c in many:
        c.close()


def test_discover_all_dedups_and_skips_dead_sockets(env):
    tenv, proj, cfg = env
    drv = driver_with(1)
    srv = LoopdServer(cfg, drv).start()
    try:
        cfg.settings.federation.pods = [
            str(socket_path(cfg)),              # duplicate of canonical
            str(tenv.base / "nowhere" / "loopd.sock"),  # never existed
        ]
        many = discover_all(cfg)
        assert len(many) == 1 and many[0].path == socket_path(cfg)
        for c in many:
            c.close()
        cfg.settings.loopd.enable = False       # master switch still wins
        assert discover_all(cfg) == []
    finally:
        srv.stop()
