"""Workspace-seed suite: the content-addressed seed fan-out (ISSUE 16).

The acceptance shape: the deterministic tar ABI digests stably across
metadata churn (and collapses undiverged worktrees to one digest), never
descends into .git / symlinked dirs / foreign mounts; the host-side TTL
cache pays the tree walk once per fan-out and serves the digest-keyed
view back for worker shipping; the workerd-resident SeedStore is a
bytes-bounded LRU whose eviction degrades launches to the per-create
fallback rather than failing; snapshot creates referencing a digest
resolve from the worker-local store with zero further WAN bytes; and a
snapshot-mode scheduler run journals REC_SEED_TAR / REC_SEED_SHIP
write-ahead with content-addressed dedup.
"""

from __future__ import annotations

import os
import time

import pytest

from clawker_tpu import consts
from clawker_tpu.config import load_config
from clawker_tpu.engine.drivers import FakeDriver
from clawker_tpu.engine.fake import exit_behavior
from clawker_tpu.loop import LoopScheduler, LoopSpec
from clawker_tpu.loop.journal import (
    REC_SEED_SHIP,
    REC_SEED_TAR,
    RunJournal,
    journal_path,
    replay,
)
from clawker_tpu.runtime.orchestrate import (
    clear_workspace_seed_cache,
    workspace_seed_by_digest,
    workspace_seed_tar,
)
from clawker_tpu.testenv import TestEnv
from clawker_tpu.workerd.executor import ExecutorSet, WorkerdExecutor
from clawker_tpu.workerd.server import SeedStore, WorkerdServer
from clawker_tpu.workspace.strategy import _tar_tree, seed_digest

IMAGE = "clawker-seedproj:default"


@pytest.fixture
def env():
    with TestEnv() as tenv:
        proj = tenv.base / "proj"
        proj.mkdir()
        (proj / consts.PROJECT_FLAT_FORM).write_text("project: seedproj\n")
        cfg = load_config(proj)
        clear_workspace_seed_cache()
        yield tenv, proj, cfg
        clear_workspace_seed_cache()


def make_tree(root, salt="a"):
    (root / "src").mkdir(parents=True, exist_ok=True)
    (root / "src" / "main.py").write_text(f"print('{salt}')\n")
    (root / "README.md").write_text("hello\n")


def wait_for(pred, timeout=10.0, interval=0.01) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ------------------------------------------------------------- tar ABI


def test_digest_stable_across_metadata_churn(tmp_path):
    """mtime / mode-within-class churn never changes the digest; a
    content change always does."""
    make_tree(tmp_path)
    d1 = seed_digest(_tar_tree(tmp_path))
    os.utime(tmp_path / "README.md", (1, 1))
    (tmp_path / "src" / "main.py").chmod(0o664)    # still non-exec: 0o644
    d2 = seed_digest(_tar_tree(tmp_path))
    assert d1 == d2
    (tmp_path / "README.md").write_text("changed\n")
    assert seed_digest(_tar_tree(tmp_path)) != d1


def test_identical_trees_collapse_to_one_digest(tmp_path):
    """N undiverged worktrees of one base share a single digest -- the
    property that turns a 32-agent fan-out into one cached seed."""
    a, b = tmp_path / "wt-a", tmp_path / "wt-b"
    a.mkdir(), b.mkdir()
    make_tree(a), make_tree(b)
    assert seed_digest(_tar_tree(a)) == seed_digest(_tar_tree(b))
    make_tree(b, salt="diverged")
    assert seed_digest(_tar_tree(a)) != seed_digest(_tar_tree(b))


def test_tar_skips_git_dir_and_symlinked_dirs(tmp_path):
    import io
    import tarfile

    make_tree(tmp_path)
    (tmp_path / ".git").mkdir()
    (tmp_path / ".git" / "HEAD").write_text("ref: refs/heads/main\n")
    (tmp_path / "loop").symlink_to(tmp_path, target_is_directory=True)
    tar = _tar_tree(tmp_path)
    names = tarfile.open(fileobj=io.BytesIO(tar)).getnames()
    assert not any(n.startswith(".git") for n in names)
    # the symlink entry itself survives; nothing UNDER it is walked
    assert "loop" in names
    assert not any(n.startswith("loop/") for n in names)


# --------------------------------------------------------- host cache


def test_workspace_seed_cache_hit_and_by_digest(tmp_path):
    make_tree(tmp_path)
    clear_workspace_seed_cache()
    try:
        d1, tar1 = workspace_seed_tar(tmp_path)
        d2, tar2 = workspace_seed_tar(tmp_path)       # cache hit
        assert (d1, tar1) == (d2, tar2)
        assert workspace_seed_by_digest(d1) == tar1
        assert workspace_seed_by_digest("0" * 64) is None
    finally:
        clear_workspace_seed_cache()


# ---------------------------------------------------------- SeedStore


def test_seed_store_lru_bounded_by_bytes():
    store = SeedStore(max_bytes=100)
    assert store.put("a", b"x" * 60)
    assert store.put("b", b"y" * 60)       # evicts "a" (LRU)
    assert store.get("a") is None
    assert store.get("b") == b"y" * 60
    assert not store.put("huge", b"z" * 101)   # over cap: stored nothing
    assert store.get("huge") is None
    # re-put of the same digest replaces, never double-counts
    assert store.put("b", b"y" * 60)
    assert store.bytes_held == 60
    store.clear()
    assert store.get("b") is None and store.bytes_held == 0


def test_seed_store_get_refreshes_lru():
    store = SeedStore(max_bytes=100)
    store.put("a", b"x" * 40)
    store.put("b", b"y" * 40)
    store.get("a")                          # "a" becomes most-recent
    store.put("c", b"z" * 40)               # evicts "b", not "a"
    assert store.get("a") is not None
    assert store.get("b") is None


# ------------------------------------------------------ workerd seeds


def test_seed_intent_then_create_resolves_from_local_store(env):
    """submit_seed stores the tar worker-side; a later create intent
    referencing the digest hits the store and fans out over the local
    socket.  Dropping the store degrades the NEXT create to the
    per-create fallback walk -- it still lands."""
    tenv, proj, cfg = env
    make_tree(proj)
    drv = FakeDriver(n_workers=1)
    drv.api.add_image(IMAGE)
    sock = tenv.base / "wd.sock"
    srv = WorkerdServer(cfg, drv.local_engine(0), worker_id="fake-0",
                        sock_path=sock).start()
    ex = WorkerdExecutor("fake-0", sock, intent_deadline_s=10.0)
    try:
        digest, tar = workspace_seed_tar(proj)
        assert ex.submit_seed(digest, tar)
        assert not ex.submit_seed(digest, tar)   # per-channel dedup
        assert ex.stats["seeds"] == 1 and ex.seeded(digest)
        assert wait_for(lambda: srv.stats["seeds_stored"] == 1)

        def fill(agent):
            return ex.submit_pool_fill(agent, {
                "agent": agent, "image": IMAGE, "loop_id": "seedrun",
                "worker": "fake-0", "workspace_mode": "snapshot",
                "seed_digest": digest}).result(timeout=10.0)

        cid = fill("wd-hit")
        assert cid and srv.stats["seed_hits"] == 1
        assert consts.WORKSPACE_DIR in drv.api.containers[cid].archives

        srv.drop_seeds()                     # chaos: seed_cache_evict
        cid2 = fill("wd-miss")
        assert cid2 and srv.stats["seed_misses"] == 1
        assert consts.WORKSPACE_DIR in drv.api.containers[cid2].archives
    finally:
        ex.close()
        srv.stop()
        drv.close()


# ------------------------------------------------- scheduler seed WAL


def test_snapshot_run_journals_seed_records_once(env):
    """A snapshot-mode workerd fan-out journals ONE REC_SEED_TAR for the
    digest and at most one REC_SEED_SHIP per (digest, worker) -- the
    write-ahead dedup that makes --resume replay free -- and the run's
    image folds them into .seeds / .seeded."""
    tenv, proj, cfg = env
    make_tree(proj)
    drv = FakeDriver(n_workers=2)
    for api in drv.apis:
        api.add_image(IMAGE)
        api.set_behavior(IMAGE, exit_behavior(b"", 0, delay=0.02))
    servers, exs = [], {}
    for i, w in enumerate(drv.workers()):
        sock = tenv.base / f"wd-{i}.sock"
        servers.append(WorkerdServer(cfg, drv.local_engine(i),
                                     worker_id=w.id, sock_path=sock).start())
        exs[w.id] = WorkerdExecutor(w.id, sock, intent_deadline_s=10.0)
    execset = ExecutorSet(exs)
    sched = LoopScheduler(
        cfg, drv, LoopSpec(parallel=4, iterations=1, image=IMAGE,
                           workspace_mode="snapshot"),
        executors=execset)
    try:
        sched.start()
        loops = sched.run(poll_s=0.05)
        assert all(l.status == "done" for l in loops)
        records = RunJournal.read(journal_path(cfg.logs_dir, sched.loop_id))
        tars = [r for r in records if r.get("kind") == REC_SEED_TAR]
        ships = [r for r in records if r.get("kind") == REC_SEED_SHIP]
        assert len(tars) == 1                      # one digest, one WAL
        digest = tars[0]["digest"]
        assert len({(s["digest"], s["worker"]) for s in ships}) == len(ships)
        image = replay(records)
        assert image.seeds.get(digest) == tars[0]["bytes"]
        assert set(image.seeded.get(digest, [])) == {s["worker"]
                                                     for s in ships}
        # every create on every daemon referenced content, not a walk:
        # the per-channel transfer count stays at one
        for ex in exs.values():
            assert ex.stats["seeds"] <= 1
    finally:
        sched.cleanup(remove_containers=True)
        execset.close_all()
        for s in servers:
            s.stop()
        drv.close()
